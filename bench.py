#!/usr/bin/env python3
"""Headline benchmark: mixed RS256/ES256 JWT verifies/sec on one chip.

Mirrors the north-star config (BASELINE.json): a 16-key JWKS (8 RSA-2048
+ 8 P-256), large batches of mixed RS256/ES256 tokens, verified through
``TPUBatchKeySet`` — JOSE prep on host (C++ runtime), signature math on
the device engine.

Honesty rules (VERDICT r2):
- every token in a batch is UNIQUE (distinct sub/jti → distinct payload
  bytes and signatures): no claims-parse amortization, full wire cost;
- the headline ``value`` is the MEDIAN steady-state rate over a
  pipelined window of back-to-back batches (≥8 measured intervals),
  not the peak rep — the peak is demoted to a side field;
- wire accounting: ``wire_effective_mbps`` is the H2D record traffic
  actually moved during the window; ``wire_probe_mbps`` is a raw
  device_put probe run right after; their ratio says how much of the
  link the pipeline extracts.

Prints exactly ONE JSON line on stdout.

Environment knobs: CAP_BENCH_BATCH (default 65536), CAP_BENCH_WINDOW
(default 8 measured batches), CAP_BENCH_UNIQUE (default = batch).

CAP_BENCH_MESH=N (VERDICT r5 #7) additionally runs the resident mix
under ``shard_map`` on an N-device mesh and records
``resident_mesh_vps`` plus the ACTUAL per-device shard sizes of every
placed record in the JSON. Without real multi-chip hardware this
forces an N-virtual-device CPU backend (absolute rates are then
meaningless — pair it with a small CAP_BENCH_BATCH; the value is the
structure: the sharded programs compile, run, and split n/N with no
stray replication); a real slice sets CAP_MESH_REAL=1 to keep its
native backend and the same command captures the scaling number.
"""

import json
import math
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
import importlib.util
_spec = importlib.util.find_spec("cap_tpu")
if _spec is None or not (_spec.origin or "").startswith(REPO + os.sep):
    # Not installed, or an installed copy would shadow THIS checkout:
    # the bench must always measure the code it sits next to.
    sys.path.insert(0, REPO)

BASELINE_TARGET = 500_000.0  # verifies/sec, BASELINE.json north_star


def _ensure_native() -> None:
    """Build the native runtime pieces if they aren't built yet."""
    from cap_tpu._build import build_native
    build_native()


def _make_fixtures(n_unique: int):
    """North-star workload (cap_tpu.testing.headline_fixtures):
    16-key JWKS + n_unique UNIQUE mixed RS256/ES256 tokens."""
    from cap_tpu import testing as T

    return T.headline_fixtures(n_unique)


def _resident_mixed_vps(ks, tokens):
    """Engine-side number (VERDICT r3 #2): verifies/sec with the packed
    records already DEVICE-RESIDENT — no host prep, packing, or H2D on
    the timed path. Methodology (slope, min-of-3, accept-sum check)
    lives in ``resident_slope_vps`` — one implementation shared with
    tools/profile_families.py.
    """
    from cap_tpu.jwt.tpu_keyset import (
        resident_dispatchers,
        resident_slope_vps,
    )

    # Dispatch-slope mode: the scaled-record mode (fns_scaled) was
    # measured to UNDER-report the engine ~20% — (1+reps)x-tiled
    # batches run genuinely slower per token (bigger HBM working set),
    # so it cancels dispatch overhead by changing the workload. The
    # plain slope matches the device-timeline trace (docs/PERF.md r5).
    n, fns = resident_dispatchers(ks, tokens)
    return resident_slope_vps(n, fns, details=True)


def _resident_slhdsa128s_vps(n_tokens: int):
    """Second PQ engine number: SLH-DSA-SHAKE-128s verifies/sec with
    the decoded hash-forest lanes (FORS values, WOTS chains, auth
    paths, precomputed ADRS words) device-resident.

    Same slope methodology (shared ``resident_slope_vps``); the
    verdict — the on-device root compare — IS the accept-sum
    integrity check. Host 128s signing costs ~4 s/signature, so the
    batch cycles a 4-signature pool (``slhdsa_unique_tokens`` in the
    record keeps that honest): unlike a cache tier, the engine does
    the FULL hash forest for every lane, so duplicates measure
    exactly what unique tokens would.
    """
    import json as _json

    from cap_tpu.jwt.jose import b64url_encode
    from cap_tpu.jwt.jwk import parse_jwks, serialize_public_key
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )
    from cap_tpu.tpu import slhdsa

    n_unique = 4
    privs, jwk_dicts = [], []
    for s in (61, 62):
        priv, pub = slhdsa.keygen("SLH-DSA-SHAKE-128s",
                                  bytes([s]) * 32)
        privs.append(priv)
        jwk_dicts.append(serialize_public_key(pub,
                                              kid=f"bench-slh{s}"))
    base = []
    for i in range(n_unique):
        header = {"alg": "SLH-DSA-SHAKE-128s",
                  "kid": f"bench-slh{61 + i % 2}"}
        h = b64url_encode(_json.dumps(
            header, separators=(",", ":")).encode())
        p = b64url_encode(_json.dumps(
            {"sub": f"slh-{i}", "jti": f"t{i}"},
            separators=(",", ":")).encode())
        si = (h + "." + p).encode()
        base.append(h + "." + p + "."
                    + b64url_encode(privs[i % 2].sign(si)))
    tokens = [base[i % n_unique] for i in range(n_tokens)]
    ks = TPUBatchKeySet(parse_jwks({"keys": jwk_dicts}))
    n, fns = resident_dispatchers(ks, tokens)
    vps, trials = resident_slope_vps(n, fns, details=True)
    return vps, trials, n_unique


def _resident_mldsa44_vps(n_tokens: int):
    """Post-quantum engine number: ML-DSA-44 verifies/sec with the
    decoded lanes (z/c/hints + key tables) device-resident.

    Same slope methodology as ``resident_mixed_vps`` (shared
    ``resident_slope_vps`` implementation, accept-sum integrity via
    on-device w1-lane comparison against the pure-int oracle — see
    resident_dispatchers). Fixtures come from the in-repo
    deterministic FIPS 204 signer: 2 AKP keys, ``n_tokens`` unique
    tokens (CAP_BENCH_MLDSA, default 256 — signing is host-side
    numpy, ~40 ms/token, and stays off the timed path).
    """
    import json as _json

    from cap_tpu.jwt.jose import b64url_encode
    from cap_tpu.jwt.jwk import parse_jwks, serialize_public_key
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )
    from cap_tpu.tpu import mldsa

    privs, jwk_dicts = [], []
    for s in (51, 52):
        priv, pub = mldsa.keygen("ML-DSA-44", bytes([s]) * 32)
        privs.append(priv)
        jwk_dicts.append(serialize_public_key(pub, kid=f"bench-pq{s}"))
    tokens = []
    for i in range(n_tokens):
        header = {"alg": "ML-DSA-44", "kid": f"bench-pq{51 + i % 2}"}
        h = b64url_encode(_json.dumps(
            header, separators=(",", ":")).encode())
        p = b64url_encode(_json.dumps(
            {"sub": f"pq-{i}", "jti": f"t{i}"},
            separators=(",", ":")).encode())
        si = (h + "." + p).encode()
        tokens.append(h + "." + p + "."
                      + b64url_encode(privs[i % 2].sign(si)))
    ks = TPUBatchKeySet(parse_jwks({"keys": jwk_dicts}))
    # Fused-vs-unfused A/B, interleaved on the same resident keyset
    # (the r14 weather rule): the FUSED arm is the single-round-trip
    # engine (device μ/SampleInBall/w1Encode/c̃) and the headline
    # resident_mldsa44_vps; the UNFUSED arm is the r11 two-phase
    # split. On a CPU-only host the honest verdict may favor either —
    # hashlib's native Keccak competes with XLA:CPU lanes — and the
    # record publishes both.
    arms = {}
    prev = os.environ.get("CAP_TPU_MLDSA_FUSED")
    try:
        for arm, flag in (("fused", "1"), ("unfused", "0")):
            os.environ["CAP_TPU_MLDSA_FUSED"] = flag
            n, fns = resident_dispatchers(ks, tokens)
            arms[arm] = resident_slope_vps(n, fns, details=True)
    finally:
        if prev is None:
            os.environ.pop("CAP_TPU_MLDSA_FUSED", None)
        else:
            os.environ["CAP_TPU_MLDSA_FUSED"] = prev
    return arms


def _rotation_fields(ks, jwks, tokens) -> dict:
    """CAP_BENCH_ROTATE=1: measure hot-rotation cost on the LIVE keyset.

    Three measurements, embedded under ``rotate`` in the BENCH json so
    tools/bench_trend.py can track rotation cost across rounds:

    - ``swap_s``: wall time of ``swap_keys`` to a same-keys/new-kids
      JWKS with a grace window (table build + atomic install);
    - the GRACE window holding: a batch signed under the retired kids
      right after the swap — rejects and CPU-fallback tokens must both
      be 0 (retired kids still resolve on the device path);
    - the ``unknown_kid`` burst WITHOUT grace: the same batch after a
      zero-grace swap — every retired-kid token falls off the device
      path onto the CPU oracle (kid is a routing hint, not an
      enforcement, so verdicts stay correct; the cost is the fallback
      burst and its wall time).
    """
    from cap_tpu import telemetry
    from cap_tpu.jwt.jwk import JWK

    rotated = [JWK(j.key, kid=(j.kid + "-r2") if j.kid else None,
                   alg=j.alg, use=j.use) for j in jwks]
    sample = tokens[:4096]
    base_epoch = ks.key_epoch
    t0 = time.perf_counter()
    ks.swap_keys(rotated, grace_s=300.0)
    swap_s = time.perf_counter() - t0
    with telemetry.recording() as rec:
        t0 = time.perf_counter()
        out = ks.verify_batch(sample)
        grace_verify_s = time.perf_counter() - t0
        grace_fallback = rec.counters().get("cpu_fallback.tokens", 0)
    grace_rejects = sum(1 for r in out if isinstance(r, Exception))
    ks.swap_keys(rotated, grace_s=0.0)
    with telemetry.recording() as rec:
        t0 = time.perf_counter()
        out = ks.verify_batch(sample)
        burst_verify_s = time.perf_counter() - t0
        burst_fallback = rec.counters().get("cpu_fallback.tokens", 0)
    burst_rejects = sum(1 for r in out if isinstance(r, Exception))
    # Restore the original tables so nothing later measures rotated
    # state (epochs only move forward).
    ks.swap_keys(jwks, epoch=base_epoch + 3, grace_s=0.0)
    return {"rotate": {
        "sample": len(sample),
        "swap_s": round(swap_s, 4),
        "grace_window_rejects": grace_rejects,
        "grace_fallback_tokens": int(grace_fallback),
        "grace_verify_s": round(grace_verify_s, 4),
        "unknown_kid_fallback_tokens": int(burst_fallback),
        "unknown_kid_rejects": burst_rejects,
        "unknown_kid_verify_s": round(burst_verify_s, 4),
    }}


def _oidc_ab_fields() -> dict:
    """CAP_BENCH_OIDC_NATIVE=0,1: the config-⑤ A/B over a REAL
    accelerated keyset (ES256 — runs crypto-free via the host signer).

    Interleaved same-window arms per rep (the r14 weather rule):
    ③-analog raw signature verify (``verify_batch_raw``), ⑤-raw with
    the Python rules (``CAP_OIDC_NATIVE=0`` → ``oidc_raw_vps``), and
    ⑤-raw with the native claims engine (``oidc_native_vps``). The
    ratio fields are the ROADMAP-#4 acceptance (⑤-raw ≤ 1.15 × ③ at
    equal link MB/s); on a chip host this measures the real ladder,
    device-stubbed hosts track the host-side story via
    tools/bench_stages.py's claims row instead.
    """
    import hashlib
    import random
    import statistics as _st

    from cap_tpu.jwt.jose import b64url_encode
    from cap_tpu.jwt.jwk import parse_jwks
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.oidc import Config, Provider, Request
    from cap_tpu.tpu.ec import HostECPublicKey, curve, host_ecdsa_sign

    arms = [a for a in os.environ.get(
        "CAP_BENCH_OIDC_NATIVE", "").split(",") if a]
    if not arms:
        return {}
    n = min(int(os.environ.get("CAP_BENCH_OIDC_BATCH", 1 << 14)),
            1 << 17)
    reps = int(os.environ.get("CAP_BENCH_OIDC_REPS", 3))
    issuer, client = "https://bench.idp.example/", "bench-client"
    # crypto-free ES256 fixtures (host signer + pure-int keys, the
    # r11 pattern) so the A/B runs on hosts without `cryptography`
    rng = random.Random(0x0517C)
    cp = curve("P-256")
    priv_d, jwk_dicts = [], []
    for i in range(4):
        d = rng.randrange(1, cp.n)
        pub = HostECPublicKey.from_private("P-256", d).public_numbers()
        priv_d.append(d)
        jwk_dicts.append({
            "kty": "EC", "crv": "P-256", "alg": "ES256",
            "kid": f"oidc-{i}",
            "x": b64url_encode(pub.x.to_bytes(32, "big")),
            "y": b64url_encode(pub.y.to_bytes(32, "big")),
        })
    ks = TPUBatchKeySet(parse_jwks({"keys": jwk_dicts}))
    cfg = Config(issuer=issuer, client_id=client,
                 supported_signing_algs=["ES256"])
    p = Provider(cfg, keyset=ks, discovery_doc={"issuer": issuer})
    req = Request(3600.0, "http://127.0.0.1:1/cb")

    def sign(claims: dict, i: int) -> str:
        h = b64url_encode(json.dumps(
            {"alg": "ES256", "kid": f"oidc-{i % 4}"},
            separators=(",", ":")).encode())
        pl = b64url_encode(json.dumps(
            claims, separators=(",", ":")).encode())
        e = int.from_bytes(
            hashlib.sha256(f"{h}.{pl}".encode()).digest(), "big")
        r, s = host_ecdsa_sign("P-256", priv_d[i % 4], e,
                               rng.randrange(1, cp.n))
        return f"{h}.{pl}." + b64url_encode(
            r.to_bytes(32, "big") + s.to_bytes(32, "big"))

    now = time.time()
    uniq = [sign({"iss": issuer, "sub": f"u{i:05d}", "aud": [client],
                  "exp": now + 86400, "iat": now,
                  "nonce": req.nonce(), "jti": f"b{i:05d}"}, i)
            for i in range(min(n, 2048))]
    toks = (uniq * (n // len(uniq) + 1))[:n]

    def rate(fn):
        out = fn()
        bad = sum(1 for r in out if isinstance(r, Exception))
        assert bad == 0, f"{bad} unexpected rejects"
        t0 = time.perf_counter()
        fn()
        return n / (time.perf_counter() - t0)

    prev = os.environ.get("CAP_OIDC_NATIVE")
    series = {"raw3": [], "0": [], "1": []}
    try:
        ks.verify_batch_raw(toks[:256])      # warm compile
        for _ in range(reps):
            series["raw3"].append(rate(
                lambda: ks.verify_batch_raw(toks)))
            for arm in arms:
                os.environ["CAP_OIDC_NATIVE"] = arm
                series[arm].append(rate(
                    lambda: p.verify_id_token_batch(toks, req,
                                                    raw=True)))
    finally:
        if prev is None:
            os.environ.pop("CAP_OIDC_NATIVE", None)
        else:
            os.environ["CAP_OIDC_NATIVE"] = prev

    med = {k: _st.median(v) for k, v in series.items() if v}
    fields = {"oidc_batch": n,
              "cfg3_raw_verify_vps": round(med["raw3"], 1)}
    if "0" in med:
        fields["oidc_raw_vps"] = round(med["0"], 1)
        fields["oidc_python_over_cfg3"] = round(
            med["raw3"] / med["0"], 3)
    if "1" in med:
        fields["oidc_native_vps"] = round(med["1"], 1)
        fields["oidc_native_over_cfg3"] = round(
            med["raw3"] / med["1"], 3)
    return {"oidc": fields}


def _probe_wire_mbps() -> float:
    """Raw sustained H2D bandwidth right now (16 MB u8, best of 2)."""
    import jax
    import numpy as np

    buf = np.random.default_rng(0).integers(
        0, 256, size=16 << 20, dtype=np.uint8)
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        arr = jax.device_put(buf)
        arr.block_until_ready()
        # block_until_ready can return early on tunneled backends —
        # only a materializing read truly fences the transfer.
        float(arr[-1])
        dt = time.perf_counter() - t0
        best = max(best, (buf.nbytes / dt) / (1 << 20))
        del arr
    return best


def _setup_mesh_backend() -> int:
    """CAP_BENCH_MESH=N: force the N-virtual-device CPU backend (must
    run before first backend use) unless CAP_MESH_REAL=1 says the
    process already owns a real N-device slice. Returns N (0 = off).
    """
    mesh_n = int(os.environ.get("CAP_BENCH_MESH", "0") or 0)
    if not mesh_n:
        return 0
    if mesh_n < 1 or mesh_n & (mesh_n - 1):
        raise SystemExit("CAP_BENCH_MESH must be a power of two")
    if os.environ.get("CAP_MESH_REAL") != "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", mesh_n)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={mesh_n}")
    return mesh_n


def _resident_mesh_fields(jwks, tokens, mesh_n: int) -> dict:
    """Slope-time the packed mix on an N-device mesh; report the rate
    and the actual per-device shard rows of every placed record."""
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )
    from cap_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(mesh_n)
    ks = TPUBatchKeySet(jwks, mesh=mesh)
    records = []
    n_tok, fns = resident_dispatchers(ks, tokens, records_out=records)
    vps, trials = resident_slope_vps(n_tok, fns, details=True)
    shards = [sorted(s.data.shape[0] for s in rec.addressable_shards)
              for rec in records]
    return {
        "resident_mesh_vps": round(vps, 1) if vps else None,
        "resident_mesh_trials_vps": [round(v, 1) for v in trials],
        "mesh_devices": mesh_n,
        "mesh_record_shard_rows": shards,
    }


def main() -> None:
    mesh_n = _setup_mesh_backend()
    _ensure_native()
    from cap_tpu import compile_cache, telemetry

    compile_cache.enable()

    batch = int(os.environ.get("CAP_BENCH_BATCH", 1 << 16))
    window = int(os.environ.get("CAP_BENCH_WINDOW", 8))
    n_unique = min(int(os.environ.get("CAP_BENCH_UNIQUE", batch)), batch)

    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    t0 = time.perf_counter()
    jwks, unique = _make_fixtures(n_unique)
    tokens = (unique * (batch // len(unique) + 1))[:batch]
    sign_s = time.perf_counter() - t0
    ks = TPUBatchKeySet(jwks)

    # Warmup: triggers XLA compilation for every bucket shape.
    out = ks.verify_batch(tokens)
    bad = sum(1 for r in out if isinstance(r, Exception))
    if bad:
        print(json.dumps({"metric": "error", "value": bad,
                          "unit": "failed_verifies", "vs_baseline": 0.0}))
        return

    # Steady-state pipelined window: window+1 back-to-back batches,
    # 2-deep in flight; the first completion (pipeline fill) is
    # dropped, leaving `window` measured completion intervals.
    rec = telemetry.enable()
    done_t = []
    t_start = time.perf_counter()
    for out in ks.verify_stream(tokens for _ in range(window + 1)):
        done_t.append(time.perf_counter())
        # The timed path must verify correctly too — a pipelining
        # regression returning errors must not produce a clean rate.
        bad = sum(1 for r in out if isinstance(r, Exception))
        if bad:
            print(json.dumps({"metric": "error", "value": bad,
                              "unit": "failed_verifies",
                              "vs_baseline": 0.0}))
            return
    # flush the occupancy plane's interval accounting (r22) into the
    # recorder before it is read — the engine dispatched in-process
    from cap_tpu.obs import occupancy as _occupancy

    _occupancy.publish(rec)
    telemetry.disable()
    all_counters = rec.counters()
    # Stage attribution (the observability layer's per-stage p50/95/99
    # from bounded histograms): prep, per-family dispatch, device sync,
    # claims — the BENCH record now explains WHERE the time went, not
    # just the headline rate.
    stage_latency = {
        name: {"count": int(s["count"]), "p50": round(s["p50"], 6),
               "p95": round(s["p95"], 6), "p99": round(s["p99"], 6)}
        for name, s in sorted(rec.summary().items())
    }
    pad_gauges = {k: round(v, 4) for k, v in sorted(rec.gauges().items())
                  if k.startswith("device.")}
    h2d_bytes = all_counters.get("h2d.bytes", 0)
    # Fleet/serve health counters ride along in the BENCH record (the
    # retry/failover/stall story of the run, zero when nothing fired):
    # fleet.* comes from any FleetClient/WorkerPool activity in-process,
    # worker.*/batcher.* from serve components.
    health_counters = {
        k: v for k, v in sorted(all_counters.items())
        if k.startswith(("fleet.", "worker.", "batcher."))
    }
    # Decision accounting + SLO evaluation (cap_tpu.obs): the record
    # carries its own verdict/reason breakdown and objective status, so
    # BENCH_r06+ is self-describing and tools/bench_trend.py can track
    # these fields without re-running anything.
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo

    decision_counts = obs_decision.decision_counters(all_counters)
    try:
        slo_results = [
            {"name": r["name"], "ok": r["ok"], "windows": r["windows"]}
            for r in obs_slo.evaluate_once(rec.snapshot())
        ]
    except Exception as e:  # noqa: BLE001 - advisory field
        slo_results = [{"error": repr(e)}]

    intervals = [b - a for a, b in zip(done_t, done_t[1:])]
    rates = [batch / dt for dt in intervals]
    value = statistics.median(rates)
    peak = max(rates)
    # Steady state starts at the first completion (pipeline fill and
    # any tunnel stall during it excluded, matching the median).
    agg = (batch * window) / (done_t[-1] - done_t[0])
    slats = sorted(intervals)
    p99 = slats[max(0, math.ceil(0.99 * len(slats)) - 1)]  # nearest rank

    bytes_per_batch = h2d_bytes / (window + 1)
    med_interval = statistics.median(intervals)
    eff_mbps = (bytes_per_batch / med_interval) / (1 << 20)
    probe_mbps = _probe_wire_mbps()

    # Self-describing weather (VERDICT r4 #6): a BENCH record must
    # explain its own p99 and headline without docs/PERF.md. A "stall"
    # is a completion interval >3× the window median — the tunnel's
    # 10-90 s dropouts, which no engine change can subdivide.
    stall = [dt for dt in intervals if dt > 3 * med_interval]
    bytes_per_token = bytes_per_batch / batch
    link_ceiling = (probe_mbps * (1 << 20) / bytes_per_token
                    if bytes_per_token else None)

    try:
        resident, resident_trials = _resident_mixed_vps(ks, tokens)
    except Exception as e:  # noqa: BLE001 - resident metric is advisory
        print(f"resident_mixed_vps failed: {e!r}", file=sys.stderr)
        resident, resident_trials = None, []

    mldsa_n = int(os.environ.get("CAP_BENCH_MLDSA", "256") or 0)
    mldsa_vps, mldsa_trials = None, []
    mldsa_unfused_vps, mldsa_unfused_trials = None, []
    if mldsa_n:
        try:
            arms = _resident_mldsa44_vps(mldsa_n)
            mldsa_vps, mldsa_trials = arms["fused"]
            mldsa_unfused_vps, mldsa_unfused_trials = arms["unfused"]
        except Exception as e:  # noqa: BLE001 - advisory metric
            print(f"resident_mldsa44_vps failed: {e!r}",
                  file=sys.stderr)

    slh_n = int(os.environ.get("CAP_BENCH_SLHDSA", "128") or 0)
    slh_vps, slh_trials, slh_unique = None, [], 0
    if slh_n:
        try:
            slh_vps, slh_trials, slh_unique = \
                _resident_slhdsa128s_vps(slh_n)
        except Exception as e:  # noqa: BLE001 - advisory metric
            print(f"resident_slhdsa128s_vps failed: {e!r}",
                  file=sys.stderr)

    mesh_fields = {}
    if mesh_n:
        try:
            mesh_fields = _resident_mesh_fields(jwks, tokens, mesh_n)
        except Exception as e:  # noqa: BLE001 - mesh metric is advisory
            print(f"resident_mesh_vps failed: {e!r}", file=sys.stderr)
            mesh_fields = {"resident_mesh_vps": None,
                           "mesh_devices": mesh_n,
                           "mesh_error": repr(e)}

    rotate_fields = {}
    if os.environ.get("CAP_BENCH_ROTATE") == "1":
        try:
            rotate_fields = _rotation_fields(ks, jwks, tokens)
        except Exception as e:  # noqa: BLE001 - advisory field
            print(f"rotation bench failed: {e!r}", file=sys.stderr)
            rotate_fields = {"rotate": {"error": repr(e)}}

    oidc_fields = {}
    if os.environ.get("CAP_BENCH_OIDC_NATIVE"):
        try:
            oidc_fields = _oidc_ab_fields()
        except Exception as e:  # noqa: BLE001 - advisory field
            print(f"oidc A/B bench failed: {e!r}", file=sys.stderr)
            oidc_fields = {"oidc": {"error": repr(e)}}

    print(f"sign={sign_s:.1f}s window={window} "
          f"rates={[round(r) for r in rates]} "
          f"interval_s p50={slats[len(slats) // 2]:.3f} p99={p99:.3f} "
          f"h2d={h2d_bytes / (1 << 20):.1f}MB "
          f"eff={eff_mbps:.1f}MB/s probe={probe_mbps:.1f}MB/s "
          f"resident={resident and round(resident)}/s",
          file=sys.stderr)

    print(json.dumps({
        "metric": "jwt_verifies_per_sec_rs256_es256_16key_jwks",
        "value": round(value, 1),                 # MEDIAN steady-state
        "unit": "verifies/sec",
        "vs_baseline": round(value / BASELINE_TARGET, 4),
        "value_peak": round(peak, 1),
        "value_window_mean": round(agg, 1),
        "p99_batch_latency_s": round(p99, 3),
        "batch": batch,
        "unique_tokens": n_unique,
        "wire_effective_mbps": round(eff_mbps, 2),
        "wire_probe_mbps": round(probe_mbps, 2),
        "wire_efficiency": round(eff_mbps / probe_mbps, 3)
        if probe_mbps else None,
        # Weather self-description: how many completion intervals were
        # tunnel stalls (>3× median) and how much of the window they
        # ate; what the link could carry at most for THIS record size.
        # value ≈ link_implied_ceiling_vps × wire_efficiency — a low
        # headline with a low ceiling is the wire, not the engine.
        "stall_intervals": len(stall),
        "stall_seconds": round(sum(stall), 3),
        # Retry/failover/serve-health counters observed during the
        # window (fleet.failovers, fleet.fallback_tokens, worker.*,
        # batcher.* — empty dict = clean run, nothing fired).
        "health_counters": health_counters,
        # Reason-keyed decision counters and SLO objective status for
        # the measured window (cap_tpu.obs): the record explains its
        # own verdicts, and bench_trend.py enforces the fields exist.
        "decisions": decision_counts,
        "slo": slo_results,
        # Per-stage attribution from the telemetry histograms: every
        # span observed during the measured window, p50/p95/p99 in
        # seconds, plus per-family padding/lane gauges — the perf
        # trajectory carries its own breakdown now.
        "telemetry": {"stage_latency": stage_latency,
                      "device_gauges": pad_gauges},
        # Pipeline-occupancy rollup for the measured window (r22):
        # busy/wall ratio of the device dispatch timeline, per-family
        # split, dispatch count — the BENCH record now says how FULL
        # the pipeline was while the headline was set.
        "occupancy": _occupancy.occupancy_from_counters(all_counters),
        "bytes_per_token": round(bytes_per_token, 1),
        "link_implied_ceiling_vps": round(link_ceiling, 1)
        if link_ceiling else None,
        # Engine speed with records device-resident (no wire): the
        # number that measures THIS repo's progress regardless of the
        # tunnel's minute-to-minute bandwidth. `value` stays the honest
        # end-to-end rate. Trials published so measurement spread is
        # visible; the estimate is min-of-3 on TIME, i.e. the MAX of
        # resident_trials_vps (slower trials ate a tunnel stall).
        "resident_mixed_vps": round(resident, 1) if resident else None,
        "resident_trials_vps": [round(v, 1) for v in resident_trials],
        # Post-quantum engine rates (resident lanes; same slope/min-
        # of-3 semantics and weather caveats as the mixed number —
        # tools/bench_trend.py tracks them). resident_mldsa44_vps is
        # the FUSED single-round-trip arm from r17 on; the unfused
        # (r11 two-phase) arm rides along as the interleaved A/B.
        "resident_mldsa44_vps": round(mldsa_vps, 1) if mldsa_vps
        else None,
        "resident_mldsa44_trials_vps": [round(v, 1)
                                        for v in mldsa_trials],
        "resident_mldsa44_unfused_vps":
            round(mldsa_unfused_vps, 1) if mldsa_unfused_vps else None,
        "resident_mldsa44_unfused_trials_vps":
            [round(v, 1) for v in mldsa_unfused_trials],
        # SLH-DSA-SHAKE-128s resident hash-forest rate (the second PQ
        # family; slhdsa_unique_tokens keeps the signing-pool reuse
        # honest — see _resident_slhdsa128s_vps).
        "resident_slhdsa128s_vps": round(slh_vps, 1) if slh_vps
        else None,
        "resident_slhdsa128s_trials_vps": [round(v, 1)
                                           for v in slh_trials],
        "slhdsa_tokens": slh_n,
        "slhdsa_unique_tokens": slh_unique,
        # CAP_BENCH_MESH=N only: the same resident mix under shard_map
        # (resident_mesh_vps, per-record sorted per-device shard rows).
        **mesh_fields,
        # CAP_BENCH_ROTATE=1 only: hot-rotation cost (swap latency,
        # grace-window integrity, unknown-kid fallback burst).
        **rotate_fields,
        # CAP_BENCH_OIDC_NATIVE=0,1 only: the config-⑤ A/B —
        # oidc_raw_vps (Python rules) vs oidc_native_vps (native
        # claims engine) vs the ③-analog raw verify, same window.
        **oidc_fields,
    }))


if __name__ == "__main__":
    main()
