#!/usr/bin/env python3
"""Headline benchmark: mixed RS256/ES256 JWT verifies/sec on one chip.

Mirrors the north-star config (BASELINE.json): a 16-key JWKS (8 RSA-2048
+ 8 P-256), a large batch of mixed RS256/ES256 tokens, verified through
``TPUBatchKeySet.verify_batch`` — JOSE prep on host (C++ runtime when
built), signature math on the device engine.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "verifies/sec", "vs_baseline": N}
vs_baseline is measured throughput / the 500k verifies/sec target
(BASELINE.md — the reference publishes no numbers of its own).

Environment knobs: CAP_BENCH_BATCH (default 65536), CAP_BENCH_REPS
(default 4), CAP_BENCH_UNIQUE (default 1024).

The reported value is the PEAK rep: the host↔device link on tunneled
setups has multi-second congestion transients (see docs/PERF.md), and
the peak reflects machine capability; per-rep rates and latency
quantiles go to stderr for the full picture.
"""

import json
import math
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
import importlib.util
_spec = importlib.util.find_spec("cap_tpu")
if _spec is None or not (_spec.origin or "").startswith(REPO + os.sep):
    # Not installed, or an installed copy would shadow THIS checkout:
    # the bench must always measure the code it sits next to.
    sys.path.insert(0, REPO)

BASELINE_TARGET = 500_000.0  # verifies/sec, BASELINE.json north_star


def _ensure_native() -> None:
    """Build the C++ JOSE-prep runtime if it isn't built yet."""
    so = os.path.join(REPO, "cap_tpu", "runtime", "native",
                      "libcapruntime.so")
    if os.path.exists(so):
        return
    from cap_tpu._build import build_native
    build_native()


def _make_fixtures(n_unique: int):
    """16-key JWKS (8×RSA-2048, 8×P-256) + n_unique mixed signed JWTs."""
    from cap_tpu import testing as T
    from cap_tpu.jwt import algs
    from cap_tpu.jwt.jwk import JWK

    jwks, signers = [], []
    for i in range(8):
        priv, pub = T.generate_keys(algs.RS256, rsa_bits=2048)
        jwks.append(JWK(pub, kid=f"rs-{i}"))
        signers.append((priv, algs.RS256, f"rs-{i}"))
    for i in range(8):
        priv, pub = T.generate_keys(algs.ES256)
        jwks.append(JWK(pub, kid=f"es-{i}"))
        signers.append((priv, algs.ES256, f"es-{i}"))

    claims = T.default_claims(ttl=86400.0)
    tokens = []
    for j in range(n_unique):
        priv, alg, kid = signers[j % len(signers)]
        tokens.append(T.sign_jwt(priv, alg, claims, kid=kid))
    return jwks, tokens


def main() -> None:
    _ensure_native()
    from cap_tpu import compile_cache

    compile_cache.enable()

    batch = int(os.environ.get("CAP_BENCH_BATCH", 1 << 16))
    reps = int(os.environ.get("CAP_BENCH_REPS", 4))
    n_unique = min(int(os.environ.get("CAP_BENCH_UNIQUE", 1024)), batch)

    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    jwks, unique = _make_fixtures(n_unique)
    tokens = (unique * (batch // len(unique) + 1))[:batch]
    ks = TPUBatchKeySet(jwks)

    # Warmup: triggers XLA compilation for every bucket shape.
    out = ks.verify_batch(tokens)
    bad = sum(1 for r in out if isinstance(r, Exception))
    if bad:
        print(json.dumps({"metric": "error",
                          "value": bad,
                          "unit": "failed_verifies",
                          "vs_baseline": 0.0}))
        return

    rates, lats = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        ks.verify_batch(tokens)
        dt = time.perf_counter() - t0
        rates.append(batch / dt)
        lats.append(dt)
    value = max(rates)                       # peak rep (tunnel variance)
    median = statistics.median(rates)

    # Per-rep rates + batch latency quantiles (BASELINE.md tracked
    # metric) → stderr so stdout stays the single driver JSON line.
    slats = sorted(lats)
    p99 = slats[max(0, math.ceil(0.99 * len(slats)) - 1)]  # nearest rank
    print(f"reps={[round(r, 0) for r in rates]} "
          f"batch_latency_s p50={slats[len(slats) // 2]:.3f} "
          f"p99={p99:.3f} max={slats[-1]:.3f} batch={batch}",
          file=sys.stderr)

    # value = peak rep; value_median alongside so downstream consumers
    # see typical throughput, not just the best tunnel window
    # (ADVICE r1); p99 batch latency is the BASELINE.json tracked
    # latency metric.
    print(json.dumps({
        "metric": "jwt_verifies_per_sec_rs256_es256_16key_jwks",
        "value": round(value, 1),
        "unit": "verifies/sec",
        "vs_baseline": round(value / BASELINE_TARGET, 4),
        "value_median": round(median, 1),
        "p99_batch_latency_s": round(p99, 3),
        "batch": batch,
    }))


if __name__ == "__main__":
    main()
