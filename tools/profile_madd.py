#!/usr/bin/env python3
"""Where does the fused-madd ES256 core spend its time?

Slope-times, on device-resident operands at ladder shapes ([I, 2N]
planes, 2N lanes):
  madd   — the Pallas fused mixed-add kernel alone, chained
  gather — the fused x‖y window-table gather alone, chained
  core   — the full _ecdsa_rns_core for reference

All chains use the slope method ((t(1+R) - t(1)) / R) so dispatch and
sync constants cancel (tunnel methodology, docs/PERF.md).
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(os.environ.get("N", 32768))
REPS = int(os.environ.get("REPS", 3))
CHAIN = int(os.environ.get("CHAIN", 32))   # windows per rep

os.environ.setdefault("CAP_TPU_RNS", "1")

from cap_tpu import testing as T
from cap_tpu.tpu import ec as tpuec
from cap_tpu.tpu import ec_rns, pallas_madd

import jax
import jax.numpy as jnp
from jax import lax


def slope(fn, sync):
    sync(fn(1))
    sync(fn(1 + REPS))
    t0 = time.perf_counter()
    sync(fn(1))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(fn(1 + REPS))
    tR = time.perf_counter() - t0
    return (tR - t1) / REPS


def main():
    print(f"backend={jax.default_backend()} N={N} lanes={2*N} "
          f"chain={CHAIN}", flush=True)
    c = ec_rns.ctx_for("P-256")
    rng = np.random.default_rng(0)
    ia, ib = c.A.count, c.B.count
    lanes = 2 * N
    print(f"I_A={ia} I_B={ib} tile={pallas_madd._TILE}")

    def plane():
        return (jax.device_put(rng.integers(
                    0, 4000, (ia, lanes)).astype(np.int32)),
                jax.device_put(rng.integers(
                    0, 4000, (ib, lanes)).astype(np.int32)))

    X, Y, Z = plane(), plane(), plane()
    iap = ec_rns.packed_cols(c)
    x2 = jax.device_put(  # packed A|B<<16 table words
        (rng.integers(0, 4000, (iap, lanes))
         | (rng.integers(0, 4000, (iap, lanes)) << 16)).astype(np.int32))
    y2 = jax.device_put(
        (rng.integers(0, 4000, (iap, lanes))
         | (rng.integers(0, 4000, (iap, lanes)) << 16)).astype(np.int32))
    inf = jax.device_put(np.zeros(lanes, bool))
    has = jax.device_put(np.ones(lanes, bool))

    # (a) fused madd kernel chained CHAIN times
    @partial(jax.jit, static_argnames=("reps",))
    def madd_chain(Xa, Xb, Ya, Yb, Za, Zb, reps: int):
        def body(i, st):
            Xs, Ys, Zs = st
            Xn, Yn, Zn, dd = pallas_madd.madd_fused(
                c, Xs, Ys, Zs, inf, has, x2, y2)
            return (Xn, Yn, Zn)

        Xs, Ys, Zs = lax.fori_loop(
            0, reps * CHAIN, body, ((Xa, Xb), (Ya, Yb), (Za, Zb)))
        return Xs[0]

    t = slope(lambda r: madd_chain(X[0], X[1], Y[0], Y[1], Z[0], Z[1],
                                   reps=r),
              lambda o: float(jnp.sum(o)))
    print(f"madd kernel x{CHAIN}:   {t*1000:7.1f} ms "
          f"({t/CHAIN*1e3:.2f} ms/window)", flush=True)

    # (b) gather chained: fused x||y table, per-lane rows
    keys = [T.generate_keys("ES256")[1] for _ in range(8)]
    table = tpuec.ECKeyTable("P-256", keys)
    rtab = table.rns()
    tab = rtab.tab
    print(f"table: {tab.shape} = {tab.nbytes/(1<<20):.1f} MB")
    idx = jax.device_put(
        rng.integers(0, tab.shape[0], lanes).astype(np.int32))

    @partial(jax.jit, static_argnames=("reps",))
    def gather_chain(i0, reps: int):
        def body(i, acc):
            g = jnp.take(tab, (i0 + i) % tab.shape[0], axis=0).T
            return acc + jnp.sum(g, axis=0)

        return lax.fori_loop(0, reps * CHAIN, body,
                             jnp.zeros(lanes, jnp.int32))

    t = slope(lambda r: gather_chain(idx, reps=r),
              lambda o: float(jnp.sum(o)))
    print(f"gather x{CHAIN}:        {t*1000:7.1f} ms "
          f"({t/CHAIN*1e3:.2f} ms/window)", flush=True)

    # (c) full core
    cp = table.curve
    consts = cp.device_consts()
    k = cp.k
    r_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    s_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    e_np = rng.integers(0, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    kid = rng.integers(0, 8, N).astype(np.int32)
    rr = jax.device_put(r_np)
    ss = jax.device_put(s_np)
    ee = jax.device_put(e_np)
    kidd = jax.device_put(kid)

    def run():
        return ec_rns._ecdsa_rns_core(
            rr, ss, ee, kidd, rtab.tab, *consts[4:9],
            crv=cp.name, nbits=cp.nbits)

    ok, deg = run()
    float(jnp.sum(ok))
    t0 = time.perf_counter()
    ok, deg = run()
    float(jnp.sum(ok))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [run() for _ in range(1 + REPS)]
    acc = outs[0][0]
    for o, _ in outs[1:]:
        acc = acc ^ o
    float(jnp.sum(acc))
    tR = time.perf_counter() - t0
    per = (tR - t1) / REPS
    print(f"full core:          {per*1000:7.1f} ms "
          f"= {N/per:,.0f}/s resident", flush=True)


if __name__ == "__main__":
    main()
