#!/usr/bin/env python3
"""Resident-compute timing of the ES* verify cores — ladder A/B.

Methodology (docs/PERF.md): operands live on device; the core is
dispatched K times back-to-back with a dependency chain (output feeds a
dummy lane of the next call's inputs is unnecessary — calls on the same
stream serialize); timing = slope between 1 rep and R reps, removing
dispatch/sync constants. Only value materialization truly syncs.

Runs BOTH window-add laws (the round-6 affine-ladder A/B) and prints
the ratio:

    N=32768 CRV=P-256 ENGINE=rns REPS=4 python tools/profile_es_core.py

ENGINE=rns (default) times _ecdsa_rns_core; ENGINE=limb times the
u8-limb _ecdsa_core. LADDERS=jacobian,affine picks the laws.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(os.environ.get("N", 32768))
REPS = int(os.environ.get("REPS", 4))
CRV = os.environ.get("CRV", "P-256")
ENGINE = os.environ.get("ENGINE", "rns")
LADDERS = os.environ.get("LADDERS", "jacobian,affine").split(",")

from cap_tpu.tpu import ec as tpuec
from cap_tpu.tpu import ec_rns

import jax
import jax.numpy as jnp

os.environ.setdefault("CAP_TPU_RNS", "1")

_ALG = {"P-256": "ES256", "P-384": "ES384", "P-521": "ES512"}


def _gen_keys(crv: str, n: int):
    """Real keys via the cryptography stack when present; otherwise
    dependency-free host keys (the table only reads public_numbers)."""
    try:
        from cap_tpu import testing as T

        return [T.generate_keys(_ALG[crv])[1] for _ in range(n)]
    except ImportError:
        import random

        rng = random.Random(0)
        cn = tpuec.curve(crv).n
        return [tpuec.HostECPublicKey.from_private(
            crv, rng.randrange(1, cn)) for _ in range(n)]


def _slope(run, sync):
    """min-of-3 slope between a 1-rep and a (1+REPS)-rep dispatch set."""
    sync(run())
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        sync(run())
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        outs = [run() for _ in range(1 + REPS)]
        acc = outs[0][0]
        for o, _ in outs[1:]:
            acc = acc ^ o
        float(jnp.sum(acc))
        tR = time.perf_counter() - t0
        per = (tR - t1) / REPS
        if per > 0 and (best is None or per < best):
            best = per
    return best


def main():
    print(f"backend={jax.default_backend()} N={N} crv={CRV} "
          f"engine={ENGINE}", flush=True)
    keys = _gen_keys(CRV, 8)
    table = tpuec.ECKeyTable(CRV, keys)
    cp = table.curve
    consts = cp.device_consts()

    rng = np.random.default_rng(0)
    k = cp.k
    # random-ish valid-range scalars as limbs
    r_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    s_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    e_np = rng.integers(0, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    idx_np = rng.integers(0, 8, N, dtype=np.int64).astype(np.int32)

    r = jax.device_put(r_np)
    s = jax.device_put(s_np)
    e = jax.device_put(e_np)
    idx = jax.device_put(idx_np)

    if ENGINE == "rns":
        rtab = table.rns()

        def mk_run(ladder):
            def run():
                return ec_rns._ecdsa_rns_core(
                    r, s, e, idx, rtab.tab, *consts[4:9],
                    crv=cp.name, nbits=cp.nbits,
                    wbits=rtab.ctx.w_bits, ladder=ladder)
            return run
    else:
        g_tabs = cp.g_tables()

        def mk_run(ladder):
            def run():
                return tpuec._ecdsa_core(
                    r, s, e, idx, table.tqx, table.tqy, *g_tabs,
                    *consts, nbits=cp.nbits, n_windows=cp.n_windows,
                    pbits=cp.pbits, ladder=ladder)
            return run

    def sync(out):
        float(jnp.sum(out[0]))

    per_ladder = {}
    for ladder in LADDERS:
        per = _slope(mk_run(ladder), sync)
        per_ladder[ladder] = per
        if per is None:
            print(f"{ladder:9s} no clean slope", flush=True)
            continue
        print(f"{ladder:9s} core={per * 1e3:8.1f} ms per {N} = "
              f"{N / per:,.0f} verifies/s resident", flush=True)
    if all(per_ladder.get(x) for x in ("jacobian", "affine")):
        ratio = per_ladder["jacobian"] / per_ladder["affine"]
        print(f"affine is {ratio:.2f}x the jacobian rate "
              f"({'faster' if ratio > 1 else 'slower'})", flush=True)


if __name__ == "__main__":
    main()
