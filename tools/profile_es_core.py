#!/usr/bin/env python3
"""Resident-compute timing of the ES256 RNS verify core.

Methodology (docs/PERF.md): operands live on device; the core is
dispatched K times back-to-back with a dependency chain (output feeds a
dummy lane of the next call's inputs is unnecessary — calls on the same
stream serialize); timing = slope between 1 rep and R reps, removing
dispatch/sync constants. Only value materialization truly syncs.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(os.environ.get("N", 32768))
REPS = int(os.environ.get("REPS", 4))

from cap_tpu import testing as T
from cap_tpu.tpu import ec as tpuec
from cap_tpu.tpu import ec_rns

import jax
import jax.numpy as jnp

os.environ.setdefault("CAP_TPU_RNS", "1")


def main():
    print(f"backend={jax.default_backend()} N={N}", flush=True)
    keys = []
    for i in range(8):
        priv, pub = T.generate_keys("ES256")
        keys.append(pub)
    table = tpuec.ECKeyTable("P-256", keys)
    cp = table.curve
    rtab = table.rns()
    consts = cp.device_consts()

    rng = np.random.default_rng(0)
    k = cp.k
    # random-ish valid-range scalars as limbs
    r_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    s_np = rng.integers(1, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    e_np = rng.integers(0, 1 << 16, (k, N), dtype=np.int64).astype(np.uint32)
    idx_np = rng.integers(0, 8, N, dtype=np.int64).astype(np.int32)

    r = jax.device_put(r_np)
    s = jax.device_put(s_np)
    e = jax.device_put(e_np)
    idx = jax.device_put(idx_np)


    def run():
        return ec_rns._ecdsa_rns_core(
            r, s, e, idx, rtab.tab, *consts[4:9],
            crv=cp.name, nbits=cp.nbits)

    # compile + settle
    ok, deg = run()
    float(jnp.sum(ok))
    t0 = time.perf_counter()
    ok, deg = run()
    float(jnp.sum(ok))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [run() for _ in range(1 + REPS)]
    acc = outs[0][0]
    for o, _ in outs[1:]:
        acc = acc ^ o
    float(jnp.sum(acc))
    tR = time.perf_counter() - t0
    per = (tR - t1) / REPS
    print(f"1rep={t1:.3f}s  {1+REPS}rep={tR:.3f}s  -> core={per*1000:.1f} ms "
          f"per {N} = {N/per:,.0f} verifies/s resident", flush=True)


if __name__ == "__main__":
    main()
