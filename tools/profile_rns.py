#!/usr/bin/env python3
"""TPU microbench: RNS (MXU) vs limb (VPU) RS256 modexp throughput."""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

import random

from cap_tpu.tpu import limbs as L
from cap_tpu.tpu import rns
from cap_tpu.tpu.rsa import RSAKeyTable, verify_pkcs1v15_arrays

N = int(os.environ.get("CAP_PROF_BATCH", 1 << 14))
rng = random.Random(9)


def modulus(bits):
    from cryptography.hazmat.primitives.asymmetric import rsa as crsa

    priv = crsa.generate_private_key(public_exponent=65537, key_size=bits)
    return priv.public_key().public_numbers().n


def bench(label, fn):
    fn()  # compile
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    dt = (time.perf_counter() - t0) / reps
    print(f"{label}: {N} tokens in {dt*1e3:.1f}ms = {N/dt:,.0f}/s")


def main():
    bits = 2048
    mods = [modulus(bits) for _ in range(8)]
    k = L.nlimbs_for_bits(bits) + 1
    idx = np.asarray([i % 8 for i in range(N)], np.int32)
    s = [rng.randrange(mods[i]) for i in idx]
    want = [pow(x, 65537, mods[i]) for x, i in zip(s, idx)]
    sl = L.ints_to_limbs(s, k)
    el = L.ints_to_limbs(want, k)

    ctx = rns.context(2048, k)
    rtab = rns.RNSKeyTable(ctx, mods)

    def rns_fn():
        ok = rns.verify_em_equals(ctx, rtab, sl, el, idx)
        assert ok.all()

    bench("RNS  RS2048 modexp+cmp", rns_fn)

    table = RSAKeyTable([(n, 65537) for n in mods])
    from cap_tpu.tpu.rsa import modexp_for_table

    def limb_fn():
        em = modexp_for_table(table, sl, idx)
        em.block_until_ready()

    bench("limb RS2048 modexp    ", limb_fn)


if __name__ == "__main__":
    main()
