#!/usr/bin/env python3
"""Generate CVB1 golden frames for the Go client's byte-parity tests.

The Go toolchain is not available in this image, so the Go package's
framing is pinned against the Python protocol implementation via these
golden vectors: the Python side (the worker's source of truth) writes
request/response frames to clients/go/captpu/testdata/, and
captpu_test.go asserts byte equality / decode equality.

Run after any protocol change:  python tools/gen_go_golden.py
"""

import io
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.errors import InvalidSignatureError
from cap_tpu.serve import protocol

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "clients", "go", "captpu", "testdata")

TOKENS = ["eyJhbGciOiJSUzI1NiJ9.e30.c2ln", "a.b.c", ""]
RESULTS = [
    {"iss": "https://example.com/", "aud": ["client-id"], "n": 3},
    InvalidSignatureError(
        "no known key successfully validated the token signature"),
    {"sub": "alice", "unicode": "ü†✓"},
]


class _Sock:
    """Duck-typed socket capturing sendall output."""

    def __init__(self):
        self.buf = io.BytesIO()

    def sendall(self, b):
        self.buf.write(b)


def main():
    os.makedirs(OUT, exist_ok=True)
    s = _Sock()
    protocol.send_request(s, TOKENS)
    with open(os.path.join(OUT, "request.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    s = _Sock()
    protocol.send_response(s, RESULTS)
    with open(os.path.join(OUT, "response.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    s = _Sock()
    protocol.send_ping(s)
    ping = s.buf.getvalue()
    s = _Sock()
    protocol.send_pong(s)
    with open(os.path.join(OUT, "ping.bin"), "wb") as f:
        f.write(ping)
    with open(os.path.join(OUT, "pong.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    meta = {
        "tokens": TOKENS,
        "results": [
            {"claims": r} if isinstance(r, dict) else
            {"error": f"{type(r).__name__}: {r}"}
            for r in RESULTS
        ],
    }
    with open(os.path.join(OUT, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, ensure_ascii=False)
    print(f"golden vectors written to {OUT}")


if __name__ == "__main__":
    main()
