#!/usr/bin/env python3
"""Generate golden vectors: CVB1 frames and adversarial JWS encodings.

The Go toolchain is not available in this image, so the Go package's
framing is pinned against the Python protocol implementation via these
golden vectors: the Python side (the worker's source of truth) writes
request/response frames to clients/go/captpu/testdata/, and
captpu_test.go asserts byte equality / decode equality. The
checksummed frame pair (types 7/8) and the STATS frames get their own
golden files the same way.

``sig_conformance.json`` pins the adversarial SIGNATURE-ENCODING
vectors (VERDICT r5 open item): high-S ECDSA, DER-instead-of-raw and
trailing-garbage ES signatures, wrong-length raw sigs, leading-zero-
stripped RSA signatures — each with the verdict the reference's
go-jose → Go stdlib path produces. Keys and nonces are FIXED
constants, so regeneration is byte-stable; signing is pure host
integer math (tpu/ec host signer + textbook RSA over pinned primes),
so this tool runs with or without the ``cryptography`` package.
tests/test_conformance.py pins all four verify surfaces to these
verdicts.

Run after any protocol change:  python tools/gen_go_golden.py
"""

import hashlib
import io
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.errors import InvalidSignatureError, ThrottledError
from cap_tpu.serve import protocol

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "clients", "go", "captpu", "testdata")

TOKENS = ["eyJhbGciOiJSUzI1NiJ9.e30.c2ln", "a.b.c", ""]
RESULTS = [
    {"iss": "https://example.com/", "aud": ["client-id"], "n": 3},
    InvalidSignatureError(
        "no known key successfully validated the token signature"),
    {"sub": "alice", "unicode": "ü†✓"},
]

# Pinned admission-pushback response vector (r20): one verified token
# next to one THROTTLED one — the additive encoding on the ordinary
# status-1 entry (class head "ThrottledError", machine-parseable
# retry_after_ms hint). Its own golden file; every frame generated
# before it stays byte-identical (the pushback wire note in
# docs/SERVE.md §Admission & fairness).
PUSH_RETRY_MS = 250
PUSH_RESULTS = [
    {"sub": "quiet"},
    ThrottledError(retry_after_ms=PUSH_RETRY_MS),
]

# Pinned trace id for the traced frame pair (types 9/10): 16 lowercase
# hex chars, exactly what telemetry.new_trace_id() emits. Fixed so
# regeneration is byte-stable.
TRACE_ID = "00112233aabbccdd"

# Pinned keyplane distribution fixture for the KEYS frame pair (types
# 11/12): a shape-only JWKS (no real key material needed on the wire
# layer) and a fixed epoch. send_keys_push canonicalizes the JSON
# (sorted keys, compact separators), so regeneration is byte-stable.
KEYS_EPOCH = 3
KEYS_JWKS = {"keys": [
    {"kty": "RSA", "kid": "rot-2024-a", "n": "AQAB", "e": "AQAB"},
    {"kty": "EC", "kid": "rot-2024-b", "crv": "P-256",
     "x": "AQAB", "y": "AQAB"},
]}

# Pinned peer-fill fixture for the verdict-cache warming pair (types
# 13/14): one import op carrying one accept entry — digest, payload
# (base64 of a fixed claims JSON), validity window, exp. All values
# fixed; send_peer_fill canonicalizes the JSON (sorted keys, compact
# separators), so regeneration is byte-stable.
PEER_FILL_DOC = {
    "op": "import",
    "epoch": 3,
    "entries": [[
        "00112233445566778899aabbccddeeff",
        "eyJzdWIiOiJnb2xkZW4ifQ==",      # b64({"sub":"golden"})
        1700000000.0,
        4102444800.0,
        4102444800.0,
    ]],
}
PEER_ACK_DOC = {"imported": 1}

# Pinned shm-attach fixture for the shared-memory transport pair
# (types 15/16): a fixed region path (never resolved at generation
# time — the wire layer only moves the string). send_shm_attach
# canonicalizes the JSON, so regeneration is byte-stable.
SHM_PATH = "/dev/shm/cap-shm-golden"


class _Sock:
    """Duck-typed socket capturing sendall output."""

    def __init__(self):
        self.buf = io.BytesIO()

    def sendall(self, b):
        self.buf.write(b)


# ---------------------------------------------------------------------------
# adversarial signature-encoding conformance vectors
# ---------------------------------------------------------------------------

# Pinned P-256 private scalar (test-only, never a real credential).
EC_D = 0x1B493A7B224D954F5D893F3A21DFD54DDBE14E1D4B83E339E2C0DCA70E7E2E01

# Pinned RSA-2048 primes (deterministic Miller-Rabin search, seed
# 0xCAB2024; test-only). e = 65537.
RSA_P = int(
    "ace2006657a2b4ad544d0954bce7d1e37fe4b537f74e7536c52c88ed72e7d62b"
    "19667309bd9fcce4c3c45a07b260403087876c148c05d84a90f41273382f18fe"
    "2fe198fc5e1384f492f9f24211adc82b229c1b6c7d9be2c160d02313df3d8212"
    "2f2ae6b3828e8fac496ef4ac4f31be57336494bcd1a8c1529185aef89bfd52cf", 16)
RSA_Q = int(
    "fdc56bde8ee8d655b614f1fa82f5ffa6f0b479f4f299649af871d5ca93b6f481"
    "a66aa8c2cef8626c86aefb50ab087d3865a849d759fe88c5cc833c7128be36a9"
    "b250724e106bad3dfda7019d173cd51d2d3d18f70575ebd8bb2ae0eb0460d356"
    "f5afbf9addee8354cd403e078aeb42382aeeada73f74170025ac5a3e10c1c5df", 16)
RSA_E = 65537

# Fixed claims: no timestamps derived at generation time (exp pinned
# far future) so regeneration is byte-stable.
CLAIMS = {"iss": "https://example.com/", "sub": "golden",
          "aud": ["client-id"], "iat": 1700000000, "nbf": 1700000000,
          "exp": 4102444800}

_SHA256_DIGESTINFO = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def _b64u(raw: bytes) -> str:
    from cap_tpu.jwt.jose import b64url_encode

    return b64url_encode(raw)


def _signing_input(alg: str, kid: str, claims=CLAIMS) -> str:
    header = {"alg": alg, "typ": "JWT", "kid": kid}
    return (_b64u(json.dumps(header, separators=(",", ":")).encode())
            + "." +
            _b64u(json.dumps(claims, separators=(",", ":")).encode()))


def _der_int(v: int) -> bytes:
    raw = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw
    return b"\x02" + bytes([len(raw)]) + raw


def _der_sig(r: int, s: int) -> bytes:
    body = _der_int(r) + _der_int(s)
    return b"\x30" + bytes([len(body)]) + body


def _ec_vectors():
    """ES256 adversarial encodings; (jwk, vectors)."""
    from cap_tpu.tpu.ec import curve, host_ecdsa_sign, scalar_mult

    cp = curve("P-256")
    qx, qy = scalar_mult(cp, EC_D, (cp.gx, cp.gy))
    jwk = {"kty": "EC", "crv": "P-256", "kid": "sig-es",
           "x": _b64u(qx.to_bytes(32, "big")),
           "y": _b64u(qy.to_bytes(32, "big"))}

    si = _signing_input("ES256", "sig-es")
    digest = hashlib.sha256(si.encode()).digest()
    e = int.from_bytes(digest, "big")
    # Deterministic test nonce (test fixtures only — NEVER a pattern
    # for production signing, where k must be unpredictable).
    k = (int.from_bytes(hashlib.sha256(b"golden-es-k").digest(),
                        "big") % (cp.n - 2)) + 1
    r, s = host_ecdsa_sign("P-256", EC_D, e, k)

    def tok(sig_bytes: bytes) -> str:
        return si + "." + _b64u(sig_bytes)

    raw = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    high_s = r.to_bytes(32, "big") + (cp.n - s).to_bytes(32, "big")
    vectors = [
        {"name": "es256-valid", "alg": "ES256", "token": tok(raw),
         "verdict": "accept", "note": "control: well-formed raw r||s"},
        {"name": "es256-high-s", "alg": "ES256", "token": tok(high_s),
         "verdict": "accept",
         "note": "s' = n - s: Go crypto/ecdsa (the reference's "
                 "verifier) does NOT enforce low-S; parity means we "
                 "accept it on every surface too"},
        {"name": "es256-der-encoded", "alg": "ES256",
         "token": tok(_der_sig(r, s)), "verdict": "reject",
         "note": "valid DER of a valid (r,s) — JOSE mandates raw "
                 "fixed-width r||s (RFC 7518 §3.4); length != 64"},
        {"name": "es256-der-trailing-garbage", "alg": "ES256",
         "token": tok(_der_sig(r, s) + b"\x00\x17"), "verdict": "reject",
         "note": "DER with trailing bytes"},
        {"name": "es256-sig-63-bytes", "alg": "ES256",
         "token": tok(raw[:-1]), "verdict": "reject",
         "note": "last byte truncated (leading-zero-strip analog)"},
        {"name": "es256-sig-65-bytes", "alg": "ES256",
         "token": tok(raw + b"\x00"), "verdict": "reject",
         "note": "one trailing garbage byte"},
        {"name": "es256-sig-empty", "alg": "ES256", "token": tok(b""),
         "verdict": "reject", "note": "empty signature segment"},
        {"name": "es256-r-zero", "alg": "ES256",
         "token": tok(b"\x00" * 32 + s.to_bytes(32, "big")),
         "verdict": "reject", "note": "r = 0 outside [1, n-1]"},
        {"name": "es256-s-zero", "alg": "ES256",
         "token": tok(r.to_bytes(32, "big") + b"\x00" * 32),
         "verdict": "reject", "note": "s = 0 outside [1, n-1]"},
        {"name": "es256-r-equals-n", "alg": "ES256",
         "token": tok(cp.n.to_bytes(32, "big") + s.to_bytes(32, "big")),
         "verdict": "reject", "note": "r = n outside [1, n-1]"},
        {"name": "es256-tampered-payload", "alg": "ES256",
         "token": _signing_input("ES256", "sig-es",
                                 dict(CLAIMS, sub="evil"))
         + "." + _b64u(raw),
         "verdict": "reject", "note": "valid sig, different payload"},
    ]
    return jwk, vectors


def _rsa_pkcs1v15_sign(msg: bytes, n: int, d: int, k: int = 256) -> bytes:
    h = hashlib.sha256(msg).digest()
    t = _SHA256_DIGESTINFO + h
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    return pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")


def _mldsa_vectors():
    """ML-DSA-44 adversarial ENCODING vectors; (jwk, vectors).

    The post-quantum analog of the ES*/RS* encoding suite: every
    vector is a structurally-valid JWS whose reject (when expected)
    comes from the FIPS 204 signature layer — wrong length, bit-
    flipped c̃, an out-of-range z coefficient, a hint-count overflow,
    nonzero hint padding. Keys come from a PINNED keygen seed and the
    signer is deterministic (rnd = 0³²), so regeneration is
    byte-stable, exactly like the classical fixtures above.
    """
    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.tpu import mldsa

    p = mldsa.PARAMS["ML-DSA-44"]
    priv, pub = mldsa.keygen("ML-DSA-44", bytes(range(32)))
    jwk = serialize_public_key(pub, kid="sig-pq")

    si = _signing_input("ML-DSA-44", "sig-pq")
    sig = priv.sign(si.encode())

    def tok(sig_bytes: bytes) -> str:
        return si + "." + _b64u(sig_bytes)

    # Out-of-range z: overwrite the first packed z slot with encoded
    # value 0 → z₀ = γ1, which fails the ‖z‖∞ < γ1 − β verify gate.
    z_lo = p.lam // 4
    z_oor = bytearray(sig)
    z_oor[z_lo: z_lo + 3] = b"\x00\x00\x00"
    # Hint-count overflow: the per-poly cumulative index byte must
    # never exceed ω; HintBitUnpack returns ⊥ (FIPS 204 Alg 21).
    h_overflow = bytearray(sig)
    h_overflow[-1] = p.omega + 1
    # Nonzero hint padding: bytes past the last used index must be 0.
    h_pad = bytearray(sig)
    h_pad[p.lam // 4 + p.l * 32 * p.z_bits + p.omega - 1] = \
        0 if h_pad[p.lam // 4 + p.l * 32 * p.z_bits + p.omega - 1] \
        else 200
    flipped = bytearray(sig)
    flipped[0] ^= 0x01

    vectors = [
        {"name": "mldsa44-valid", "alg": "ML-DSA-44", "token": tok(sig),
         "verdict": "accept",
         "note": "control: well-formed FIPS 204 signature"},
        {"name": "mldsa44-sig-truncated", "alg": "ML-DSA-44",
         "token": tok(sig[:-1]), "verdict": "reject",
         "note": "last byte truncated: length != 2420"},
        {"name": "mldsa44-sig-extended", "alg": "ML-DSA-44",
         "token": tok(sig + b"\x00"), "verdict": "reject",
         "note": "one trailing zero byte: length != 2420"},
        {"name": "mldsa44-ctilde-bitflip", "alg": "ML-DSA-44",
         "token": tok(bytes(flipped)), "verdict": "reject",
         "note": "one bit of c~ flipped: the final hash compare fails"},
        {"name": "mldsa44-z-out-of-range", "alg": "ML-DSA-44",
         "token": tok(bytes(z_oor)), "verdict": "reject",
         "note": "first z slot rewritten to encoded 0 -> z = gamma1, "
                 "outside the ||z|| < gamma1 - beta verify gate"},
        {"name": "mldsa44-hint-count-overflow", "alg": "ML-DSA-44",
         "token": tok(bytes(h_overflow)), "verdict": "reject",
         "note": "cumulative hint index > omega: HintBitUnpack "
                 "returns bottom"},
        {"name": "mldsa44-hint-padding-nonzero", "alg": "ML-DSA-44",
         "token": tok(bytes(h_pad)), "verdict": "reject",
         "note": "nonzero byte in the unused hint padding region"},
        {"name": "mldsa44-tampered-payload", "alg": "ML-DSA-44",
         "token": _signing_input("ML-DSA-44", "sig-pq",
                                 dict(CLAIMS, sub="evil"))
         + "." + _b64u(sig),
         "verdict": "reject", "note": "valid sig, different payload"},
    ]
    return jwk, vectors


def _slhdsa_vectors():
    """SLH-DSA-SHAKE-128f adversarial ENCODING vectors; (jwk, vectors).

    The hash-based analog of the ML-DSA suite. SLH-DSA's only
    structural gate is the signature LENGTH — there is no malleable
    algebraic encoding — so the adversarial surface is: truncation,
    extension/trailing garbage, a bit-flipped randomizer R (re-steers
    H_msg, so every FORS index — including what would be an
    "out-of-range" index under a fixed digest — resolves to a
    different leaf and the root compare fails), a corrupted FORS
    value, and a corrupted hypertree auth node. Keys come from a
    PINNED keygen seed and the signer is deterministic (opt_rand =
    PK.seed), so regeneration is byte-stable. 128f keeps generation
    fast; the KAT file covers 128s the same way.
    """
    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.tpu import slhdsa

    pset = "SLH-DSA-SHAKE-128f"
    p = slhdsa.PARAMS[pset]
    priv, pub = slhdsa.keygen(pset, bytes(range(32, 64)))
    jwk = serialize_public_key(pub, kid="sig-slh")

    si = _signing_input(pset, "sig-slh")
    sig = priv.sign(si.encode())

    def tok(sig_bytes: bytes) -> str:
        return si + "." + _b64u(sig_bytes)

    n = p.n
    r_flip = bytearray(sig)
    r_flip[3] ^= 0x10                    # inside R
    fors_idx = bytearray(sig)
    # First auth node of FORS tree 0: the path the (digest-pinned)
    # leaf index walks no longer commits to the right root.
    fors_idx[n + n] ^= 0x01
    ht_auth = bytearray(sig)
    ht_auth[-1] ^= 0x80

    vectors = [
        {"name": "slhdsa128f-valid", "alg": pset, "token": tok(sig),
         "verdict": "accept",
         "note": "control: well-formed FIPS 205 signature"},
        {"name": "slhdsa128f-sig-truncated", "alg": pset,
         "token": tok(sig[:-1]), "verdict": "reject",
         "note": f"last byte truncated: length != {p.sig_size}"},
        {"name": "slhdsa128f-sig-extended", "alg": pset,
         "token": tok(sig + b"\x00"), "verdict": "reject",
         "note": "one trailing zero byte: wrong length"},
        {"name": "slhdsa128f-trailing-garbage", "alg": pset,
         "token": tok(sig + b"\xde\xad"), "verdict": "reject",
         "note": "two trailing garbage bytes: wrong length"},
        {"name": "slhdsa128f-r-bitflip", "alg": pset,
         "token": tok(bytes(r_flip)), "verdict": "reject",
         "note": "one bit of the randomizer R flipped: H_msg "
                 "re-steers every FORS/hypertree index"},
        {"name": "slhdsa128f-fors-path-corrupt", "alg": pset,
         "token": tok(bytes(fors_idx)), "verdict": "reject",
         "note": "FORS auth node corrupted: the digest-selected leaf "
                 "index walks to a wrong root (the out-of-range-"
                 "index analog — indices are digest-derived, never "
                 "encoded)"},
        {"name": "slhdsa128f-ht-auth-corrupt", "alg": pset,
         "token": tok(bytes(ht_auth)), "verdict": "reject",
         "note": "last hypertree auth node corrupted"},
        {"name": "slhdsa128f-tampered-payload", "alg": pset,
         "token": _signing_input(pset, "sig-slh",
                                 dict(CLAIMS, sub="evil"))
         + "." + _b64u(sig),
         "verdict": "reject", "note": "valid sig, different payload"},
    ]
    return jwk, vectors


def _rsa_vectors():
    n = RSA_P * RSA_Q
    d = pow(RSA_E, -1, (RSA_P - 1) * (RSA_Q - 1))
    jwk = {"kty": "RSA", "kid": "sig-rs",
           "n": _b64u(n.to_bytes(256, "big")),
           "e": _b64u(b"\x01\x00\x01")}

    si = _signing_input("RS256", "sig-rs")
    sig = _rsa_pkcs1v15_sign(si.encode(), n, d)

    # Find a claims tweak whose signature integer has a LEADING ZERO
    # byte at full width — the encoding a sloppy signer would strip.
    stripped = None
    for i in range(10000):
        si2 = _signing_input("RS256", "sig-rs",
                             dict(CLAIMS, jti=f"lz-{i:04d}"))
        sig2 = _rsa_pkcs1v15_sign(si2.encode(), n, d)
        if sig2[0] == 0:
            stripped = (si2, sig2)
            break
    assert stripped is not None, "no leading-zero signature in range"
    si2, sig2 = stripped

    def tok(inp: str, sig_bytes: bytes) -> str:
        return inp + "." + _b64u(sig_bytes)

    vectors = [
        {"name": "rs256-valid", "alg": "RS256", "token": tok(si, sig),
         "verdict": "accept", "note": "control: 256-byte signature"},
        {"name": "rs256-leading-zero-full-width", "alg": "RS256",
         "token": tok(si2, sig2), "verdict": "accept",
         "note": "control: signature whose top byte IS 0x00, at full "
                 "256-byte width — must verify"},
        {"name": "rs256-leading-zero-stripped", "alg": "RS256",
         "token": tok(si2, sig2[1:]), "verdict": "reject",
         "note": "same signature with the leading zero STRIPPED "
                 "(255 bytes): Go crypto/rsa and OpenSSL both demand "
                 "len(sig) == modulus size"},
        {"name": "rs256-sig-zero-extended", "alg": "RS256",
         "token": tok(si, b"\x00" + sig), "verdict": "reject",
         "note": "257 bytes: zero-extended beyond the modulus size"},
        {"name": "rs256-tampered-payload", "alg": "RS256",
         "token": _signing_input("RS256", "sig-rs",
                                 dict(CLAIMS, sub="evil"))
         + "." + _b64u(sig),
         "verdict": "reject", "note": "valid sig, different payload"},
    ]
    return jwk, vectors


def write_sig_conformance(out_dir: str) -> str:
    ec_jwk, ec_vecs = _ec_vectors()
    rsa_jwk, rsa_vecs = _rsa_vectors()
    pq_jwk, pq_vecs = _mldsa_vectors()
    slh_jwk, slh_vecs = _slhdsa_vectors()
    doc = {
        "comment": "Adversarial signature-encoding conformance "
                   "vectors. Verdicts pin go-jose -> Go stdlib "
                   "semantics (classical families) and FIPS 204/205 "
                   "decode/verify gates (ML-DSA, SLH-DSA); every "
                   "cap_tpu verify surface must match them "
                   "bit-for-bit. Keys are fixed TEST fixtures "
                   "(never real credentials).",
        "keys": {"keys": [ec_jwk, rsa_jwk, pq_jwk, slh_jwk]},
        "vectors": ec_vecs + rsa_vecs + pq_vecs + slh_vecs,
    }
    path = os.path.join(out_dir, "sig_conformance.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def main():
    os.makedirs(OUT, exist_ok=True)
    s = _Sock()
    protocol.send_request(s, TOKENS)
    with open(os.path.join(OUT, "request.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    s = _Sock()
    protocol.send_response(s, RESULTS)
    with open(os.path.join(OUT, "response.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    s = _Sock()
    protocol.send_ping(s)
    ping = s.buf.getvalue()
    s = _Sock()
    protocol.send_pong(s)
    with open(os.path.join(OUT, "ping.bin"), "wb") as f:
        f.write(ping)
    with open(os.path.join(OUT, "pong.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Checksummed frame pair (types 7/8) + STATS frames: separate
    # golden files; the classic CVB1 files above stay byte-identical.
    s = _Sock()
    protocol.send_request(s, TOKENS, crc=True)
    with open(os.path.join(OUT, "request_crc.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_response(s, RESULTS, crc=True)
    with open(os.path.join(OUT, "response_crc.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_stats_request(s)
    with open(os.path.join(OUT, "stats_request.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_stats_response(
        s, {"pid": 7, "queued_tokens": 0, "inflight_batches": 1})
    with open(os.path.join(OUT, "stats_response.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Traced frame pair (types 9/10): the checksummed envelope plus
    # the additive trace-context field. Own golden files; every file
    # above stays byte-identical (tests/test_conformance.py pins them).
    s = _Sock()
    protocol.send_request(s, TOKENS, trace=TRACE_ID)
    with open(os.path.join(OUT, "request_trace.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_response(s, RESULTS, trace=TRACE_ID)
    with open(os.path.join(OUT, "response_trace.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Keyplane KEYS frame pair (types 11/12): additive like the traced
    # pair — everything written above stays byte-identical.
    s = _Sock()
    protocol.send_keys_push(s, KEYS_JWKS, KEYS_EPOCH)
    with open(os.path.join(OUT, "keys_push.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_keys_ack(s, epoch=KEYS_EPOCH)
    with open(os.path.join(OUT, "keys_ack.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Peer-fill frame pair (types 13/14): additive like the KEYS pair —
    # everything written above stays byte-identical.
    s = _Sock()
    protocol.send_peer_fill(s, PEER_FILL_DOC)
    with open(os.path.join(OUT, "peer_fill.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    s.sendall(protocol.encode_peer_ack(PEER_ACK_DOC))
    with open(os.path.join(OUT, "peer_ack.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Admission-pushback response vector (r20): the plain and
    # checksummed forms of a mixed verified/throttled response —
    # additive ON THE PAYLOAD of the existing status-1 entry, so
    # every file above stays byte-identical.
    s = _Sock()
    protocol.send_response(s, PUSH_RESULTS)
    with open(os.path.join(OUT, "response_push.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    protocol.send_response(s, PUSH_RESULTS, crc=True)
    with open(os.path.join(OUT, "response_push_crc.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    # Shared-memory transport pair (types 15/16): additive like every
    # pair before it — everything written above stays byte-identical.
    s = _Sock()
    protocol.send_shm_attach(s, SHM_PATH)
    with open(os.path.join(OUT, "shm_attach.bin"), "wb") as f:
        f.write(s.buf.getvalue())
    s = _Sock()
    s.sendall(protocol.encode_shm_ack())
    with open(os.path.join(OUT, "shm_ack.bin"), "wb") as f:
        f.write(s.buf.getvalue())

    meta = {
        "tokens": TOKENS,
        "trace_id": TRACE_ID,
        "push_retry_after_ms": PUSH_RETRY_MS,
        "push_results": [
            {"claims": r} if isinstance(r, dict) else
            {"error": f"{type(r).__name__}: {r}"}
            for r in PUSH_RESULTS
        ],
        "keys_epoch": KEYS_EPOCH,
        "keys_jwks": KEYS_JWKS,
        "peer_fill_doc": PEER_FILL_DOC,
        "peer_ack_doc": PEER_ACK_DOC,
        "shm_path": SHM_PATH,
        "results": [
            {"claims": r} if isinstance(r, dict) else
            {"error": f"{type(r).__name__}: {r}"}
            for r in RESULTS
        ],
    }
    with open(os.path.join(OUT, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, ensure_ascii=False)

    sig_path = write_sig_conformance(OUT)
    print(f"golden vectors written to {OUT} (+ {os.path.basename(sig_path)})")


if __name__ == "__main__":
    main()
