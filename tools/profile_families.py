#!/usr/bin/env python3
"""Resident packed-path verifies/sec for EVERY algorithm family.

The headline bench measures the RS256/ES256 mix; this walks all ten
JOSE algorithms through the same resident methodology (records already
on device, min-of-3 slope, accept-sum checked) so the per-family
engine rates are on record. Usage:

    python tools/profile_families.py [n_tokens]
    python tools/profile_families.py [n_tokens] --mesh N

``--mesh N`` runs every family's packed program under ``shard_map``
on an N-device mesh (VERDICT r4 #7). Without real multi-chip
hardware it forces the N-virtual-device CPU backend, where absolute
rates are meaningless but the SHARDED step itself compiles, executes,
and splits the batch n/N per device — so a sharding-overhead
regression (replication of the batch, a stray all-gather) shows up
as a per-device dispatch-size change long before real hardware does,
and on a real N-chip slice the same command captures the scaling
number.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ALGS = ["RS256", "RS384", "RS512", "PS256", "PS384", "PS512",
        "ES256", "ES384", "ES512", "EdDSA"]


def _parse_args(argv):
    n, mesh_n = 16384, None
    pos = []
    i = 0
    while i < len(argv):
        if argv[i] == "--mesh":
            if i + 1 >= len(argv):
                sys.exit("usage: profile_families.py [n_tokens] --mesh N")
            mesh_n = int(argv[i + 1])
            if mesh_n < 1 or mesh_n & (mesh_n - 1):
                sys.exit("--mesh N must be a power of two (packed "
                         "records pad to power-of-two batch sizes)")
            i += 2
        else:
            pos.append(argv[i])
            i += 1
    if pos:
        n = int(pos[0])
    return n, mesh_n


# --mesh needs the virtual devices BEFORE first backend use. Env vars
# are not enough on this image (the axon sitecustomize pins the TPU
# platform — tests/conftest.py); jax.config.update still wins when it
# runs before any device call. A real multi-chip slice sets
# CAP_MESH_REAL=1 to keep its native backend instead.
_N_TOKENS, _MESH_N = _parse_args(sys.argv[1:])
if _MESH_N is not None and os.environ.get("CAP_MESH_REAL") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", _MESH_N)
    os.environ.setdefault("CAP_TPU_RNS", "1")


def measure(alg: str, n: int, mesh=None):
    from cap_tpu import testing as T
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )

    priv, pub = T.generate_keys(alg)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")], mesh=mesh)
    base = [T.sign_jwt(priv, alg, T.default_claims(sub=f"s{i}"), kid="k0")
            for i in range(512)]
    toks = (base * ((n // len(base)) + 1))[:n]
    n_tok, fns = resident_dispatchers(ks, toks)
    return n_tok, resident_slope_vps(n_tok, fns)


def main():
    n = _N_TOKENS
    mesh = None
    if _MESH_N is not None:
        from cap_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(_MESH_N)
        print(f"mesh: {len(mesh.devices.flat)} devices "
              f"({mesh.devices.flat[0].platform})")
    print(f"resident packed path, {n} tokens/family, min-of-3 slope")
    for alg in ALGS:
        try:
            n_tok, vps = measure(alg, n, mesh=mesh)
            if vps is None:
                print(f"{alg:6s} no clean slope (timer noise)",
                      flush=True)
                continue
            print(f"{alg:6s} {n_tok / vps * 1e3:7.1f} ms  "
                  f"{vps / 1e3:7.0f}k verifies/s", flush=True)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{alg:6s} FAILED: {e}", flush=True)


if __name__ == "__main__":
    main()
