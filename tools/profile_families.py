#!/usr/bin/env python3
"""Resident packed-path verifies/sec for EVERY algorithm family.

The headline bench measures the RS256/ES256 mix; this walks all ten
JOSE algorithms through the same resident methodology (records already
on device, min-of-3 slope, accept-sum checked) so the per-family
engine rates are on record. Usage:

    python tools/profile_families.py [n_tokens]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ALGS = ["RS256", "RS384", "RS512", "PS256", "PS384", "PS512",
        "ES256", "ES384", "ES512", "EdDSA"]


def measure(alg: str, n: int):
    from cap_tpu import testing as T
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )

    priv, pub = T.generate_keys(alg)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")])
    base = [T.sign_jwt(priv, alg, T.default_claims(sub=f"s{i}"), kid="k0")
            for i in range(512)]
    toks = (base * ((n // len(base)) + 1))[:n]
    n_tok, fns = resident_dispatchers(ks, toks)
    return n_tok, resident_slope_vps(n_tok, fns)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    print(f"resident packed path, {n} tokens/family, min-of-3 slope")
    for alg in ALGS:
        try:
            n_tok, vps = measure(alg, n)
            if vps is None:
                print(f"{alg:6s} no clean slope (timer noise)",
                      flush=True)
                continue
            print(f"{alg:6s} {n_tok / vps * 1e3:7.1f} ms  "
                  f"{vps / 1e3:7.0f}k verifies/s", flush=True)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{alg:6s} FAILED: {e}", flush=True)


if __name__ == "__main__":
    main()
