#!/usr/bin/env python3
"""Resident packed-path verifies/sec for EVERY algorithm family.

The headline bench measures the RS256/ES256 mix; this walks all ten
JOSE algorithms through the same resident methodology (records already
on device, min-of-3 slope, accept-sum checked) so the per-family
engine rates are on record. Usage:

    python tools/profile_families.py [n_tokens]
    python tools/profile_families.py [n_tokens] --mesh N
    python tools/profile_families.py [n_tokens] --trace
    python tools/profile_families.py [n_tokens] --ladder affine

``--mesh N`` runs every family's packed program under ``shard_map``
on an N-device mesh (VERDICT r4 #7). Without real multi-chip
hardware it forces the N-virtual-device CPU backend, where absolute
rates are meaningless but the SHARDED step itself compiles, executes,
and splits the batch n/N per device — so a sharding-overhead
regression (replication of the batch, a stray all-gather) shows up
as a per-device dispatch-size change long before real hardware does,
and on a real N-chip slice the same command captures the scaling
number.

``--trace`` (VERDICT r5 #6) additionally times each family from the
DEVICE TIMELINE: the dispatchers run under ``jax.profiler.trace``,
the trace-viewer JSON is parsed, and the per-dispatch ms is the union
span of on-device execution events (everything not on a host python
thread) divided by the dispatch count. Slope samples that exceed the
trace-implied rate by >15% are flagged ``SLOPE-OUTLIER`` — the
round-5 scoreboard's unannotated 1046k/s ES256 sample is exactly the
artifact this retires: a favorable tunnel window inside the min-of-3
shifts the slope, but cannot shift the device timeline.

``--ladder {jacobian,affine}`` pins the ES* window-add law for the
affine-ladder A/B (docs/PERF.md round 6); default is the engine's own
default (CAP_TPU_EC_LADDER or jacobian).
"""
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ALGS = ["RS256", "RS384", "RS512", "PS256", "PS384", "PS512",
        "ES256", "ES384", "ES512", "EdDSA"]


def _parse_args(argv):
    n, mesh_n, trace, ladder = 16384, None, False, None
    pos = []
    i = 0
    while i < len(argv):
        if argv[i] == "--mesh":
            if i + 1 >= len(argv):
                sys.exit("usage: profile_families.py [n_tokens] --mesh N")
            mesh_n = int(argv[i + 1])
            if mesh_n < 1 or mesh_n & (mesh_n - 1):
                sys.exit("--mesh N must be a power of two (packed "
                         "records pad to power-of-two batch sizes)")
            i += 2
        elif argv[i] == "--trace":
            trace = True
            i += 1
        elif argv[i] == "--ladder":
            if i + 1 >= len(argv) or argv[i + 1] not in ("jacobian",
                                                         "affine"):
                sys.exit("usage: --ladder {jacobian|affine}")
            ladder = argv[i + 1]
            i += 2
        else:
            pos.append(argv[i])
            i += 1
    if pos:
        n = int(pos[0])
    return n, mesh_n, trace, ladder


# --mesh needs the virtual devices BEFORE first backend use. Env vars
# are not enough on this image (the axon sitecustomize pins the TPU
# platform — tests/conftest.py); jax.config.update still wins when it
# runs before any device call. A real multi-chip slice sets
# CAP_MESH_REAL=1 to keep its native backend instead.
_N_TOKENS, _MESH_N, _TRACE, _LADDER = _parse_args(sys.argv[1:])
if _MESH_N is not None and os.environ.get("CAP_MESH_REAL") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", _MESH_N)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_MESH_N}")
    os.environ.setdefault("CAP_TPU_RNS", "1")
if _LADDER is not None:
    os.environ["CAP_TPU_EC_LADDER"] = _LADDER


def trace_device_ms(fns, reps: int = 3):
    """Device-timeline ms per dispatch set, via jax.profiler.

    Runs the family's dispatchers ``reps`` times back-to-back under a
    profiler trace, parses the trace-viewer JSON, and returns the
    union span (max end − min start, ms) of all EXECUTION events that
    are not on a host python thread — XLA device/runtime op events —
    divided by ``reps``. Ground truth against slope-method artifacts:
    host dispatch stalls and tunnel weather stretch a wall-clock
    slope, but cannot add device-op span. Returns None when the trace
    carries no device events (unknown runtime).
    """
    import jax

    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(reps):
                for _, fn in fns:
                    fn().block_until_ready()
        paths = glob.glob(td + "/**/*.trace.json.gz", recursive=True)
        if not paths:
            return None
        events = []
        for path in paths:
            with gzip.open(path) as f:
                events.extend(json.load(f).get("traceEvents", []))
    host_tids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and "python" in str(e["args"].get("name", "")).lower()):
            host_tids.add((e["pid"], e["tid"]))
    spans = [(e["ts"], e["ts"] + e["dur"]) for e in events
             if e.get("ph") == "X" and e.get("dur", 0) > 0
             and (e["pid"], e["tid"]) not in host_tids
             and not str(e.get("name", "")).startswith("$")]
    if not spans:
        return None
    lo = min(s for s, _ in spans)
    hi = max(t for _, t in spans)
    return (hi - lo) / 1e3 / reps


def measure(alg: str, n: int, mesh=None, trace=False):
    from cap_tpu import testing as T
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import (
        TPUBatchKeySet,
        resident_dispatchers,
        resident_slope_vps,
    )

    priv, pub = T.generate_keys(alg)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")], mesh=mesh)
    base = [T.sign_jwt(priv, alg, T.default_claims(sub=f"s{i}"), kid="k0")
            for i in range(512)]
    toks = (base * ((n // len(base)) + 1))[:n]
    n_tok, fns = resident_dispatchers(ks, toks)
    vps = resident_slope_vps(n_tok, fns)
    t_ms = trace_device_ms(fns) if trace else None
    return n_tok, vps, t_ms


def main():
    n = _N_TOKENS
    mesh = None
    if _MESH_N is not None:
        from cap_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(_MESH_N)
        print(f"mesh: {len(mesh.devices.flat)} devices "
              f"({mesh.devices.flat[0].platform})")
    mode = f", ladder={_LADDER}" if _LADDER else ""
    print(f"resident packed path, {n} tokens/family, min-of-3 slope"
          f"{mode}")
    for alg in ALGS:
        try:
            n_tok, vps, t_ms = measure(alg, n, mesh=mesh, trace=_TRACE)
            if vps is None:
                print(f"{alg:6s} no clean slope (timer noise)",
                      flush=True)
                continue
            line = (f"{alg:6s} {n_tok / vps * 1e3:7.1f} ms  "
                    f"{vps / 1e3:7.0f}k verifies/s")
            if t_ms is not None:
                trace_vps = n_tok / t_ms * 1e3
                line += (f"  | trace {t_ms:7.1f} ms "
                         f"{trace_vps / 1e3:7.0f}k/s")
                if vps > 1.15 * trace_vps:
                    # >15% over the device timeline: the slope sample
                    # is measurement weather, not engine speed.
                    line += "  SLOPE-OUTLIER"
            elif _TRACE:
                line += "  | trace n/a"
            print(line, flush=True)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{alg:6s} FAILED: {e}", flush=True)


if __name__ == "__main__":
    main()
