#!/usr/bin/env python3
"""keyplane-smoke: boot a stub fleet, rotate keys live, verify it.

The CI guard for the keyplane (``make keyplane-smoke``):

1. spawn a 2-worker stub WorkerPool and keep a background driver
   hammering mixed (verified + rejected) batches through a
   FleetClient for the whole run;
2. push THREE key epochs through ``pool.push_keys`` while that load
   flows; FAIL if any worker misses an epoch (no convergence within
   two supervisor sweeps), if any verdict is wrong, or if any
   submission is lost;
3. scrape every worker's obs endpoint; FAIL if the ``keyplane.epoch``
   gauge is missing or stale;
4. evaluate the default SLO rules (which now include rotation
   propagation lag and push-failure rate) over the merged counters;
   FAIL on breach or evaluation error.

Runs under JAX_PLATFORMS=cpu inside the tier-1 time budget (~10 s).
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPOCHS = (1, 2, 3)


def main() -> int:
    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.obs import slo as obs_slo
    from tools import capstat

    failures = []
    telemetry.enable()
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3)
    try:
        if not pool.wait_all_ready(30):
            print("keyplane-smoke: fleet did not come up",
                  file=sys.stderr)
            return 1
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        stop = threading.Event()
        verified = [0]

        def driver():
            i = 0
            while not stop.is_set():
                toks = [f"kp-{i}.ok", f"kp-{i}.bad"]
                out = cl.verify_batch(toks)
                if len(out) != 2:
                    failures.append("lost submissions")
                    return
                if isinstance(out[0], Exception) or \
                        not isinstance(out[1], Exception):
                    failures.append(
                        f"WRONG verdict during rotation (batch {i})")
                    return
                verified[0] += 2
                i += 1

        t = threading.Thread(target=driver, daemon=True)
        t.start()

        def jwks(epoch):
            return {"keys": [{"kty": "RSA", "kid": f"rot-{epoch}",
                              "n": "AQAB", "e": "AQAB"}]}

        for epoch in EPOCHS:
            time.sleep(0.2)
            acks = pool.push_keys(jwks(epoch), epoch=epoch)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if all(e == epoch
                       for e in pool.key_epochs().values()):
                    break
                time.sleep(0.1)
            else:
                failures.append(
                    f"epoch {epoch} did not converge: "
                    f"{pool.key_epochs()} (acks {acks})")
        stop.set()
        t.join(timeout=30)
        if t.is_alive():
            failures.append("driver thread wedged")
        if verified[0] == 0:
            failures.append("driver verified nothing during rotation")
        if pool.epoch_skew() != 0:
            failures.append(f"epoch skew {pool.epoch_skew()} after "
                            "convergence")

        # Obs surface: every worker's scrape carries the final epoch.
        for wid, (host, port) in sorted(pool.obs_endpoints().items()):
            data = capstat.scrape(f"{host}:{port}")
            got = data["extra"].get("keyplane.epoch")
            if got != float(EPOCHS[-1]):
                failures.append(
                    f"worker {wid}: keyplane.epoch gauge is {got}, "
                    f"want {EPOCHS[-1]}")

        # SLO engine over this process's counters (pushes, propagate
        # latency, decisions from the router surface).
        try:
            results = obs_slo.evaluate_once(
                telemetry.active().snapshot())
            for r in results:
                if r["name"] in ("wrong_verdicts", "rotation_lag",
                                 "push_failures") and not r["ok"]:
                    failures.append(f"SLO breach in clean run: {r}")
        except Exception as e:  # noqa: BLE001 - the gate itself
            failures.append(f"SLO engine evaluation error: {e!r}")
        rec = telemetry.active()
        if "keyplane.propagate_s" not in rec.summary():
            failures.append("no keyplane.propagate_s observations")
    finally:
        pool.close()
    if failures:
        for f in failures:
            print(f"keyplane-smoke FAIL: {f}", file=sys.stderr)
        return 1
    print(f"keyplane-smoke OK: {len(EPOCHS)} live rotations converged "
          f"on 2 workers with {verified[0]} tokens verified under "
          "load, zero wrong verdicts, epoch gauges present, SLO "
          "rules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
