#!/usr/bin/env python3
"""obs-smoke: boot stub fleets, scrape them, fail on gaps.

The CI guard for the observability surface (``make obs-smoke``):

1. spawn a 2-worker stub WorkerPool (no jax in the children — starts
   in ~1 s) and drive a few traced MIXED (verified + rejected)
   batches through a FleetClient;
2. scrape every worker's /metrics (Prometheus text) and /snapshot;
3. FAIL (exit 1) if any required gauge is missing or NaN, if the
   Prometheus text lacks the required metric families, or if the
   traced request produced no flight-recorder entry;
4. FAIL if any exercised surface (serve worker, fleet router) reports
   ZERO decision counters — accept AND reject must both have counted
   for the mixed batch (cap_tpu.obs.decision);
4b. VERDICT-CACHE GATE: drive a repeated-token burst and FAIL if the
   workers report zero ``vcache.hits``, if the exactness invariant
   ``vcache.lookups == vcache.hits + vcache.misses`` does not hold on
   the merged scrape, or if the ``vcache.stale_accepts`` tripwire
   moved — on BOTH serve chains;
5. FAIL if the SLO engine cannot evaluate the default rules over the
   live fleet's merged counters, or if the wrong-verdict objective is
   breached;
6. NATIVE-CHAIN GATE: repeat the same load against a fleet booted
   with ``--serve-chain native`` (the native telemetry plane counts
   the serve surface in C) and FAIL on any missing/NaN gauge —
   including ``serve.native.ring_hwm`` — or on any decision-counter
   divergence from the python-chain run: obs must cost less, never
   count differently. Skipped with a notice when the native library
   cannot build on this host.
7. NATIVE FRONT-DOOR GATE (r21): the 2-pool topology again, served
   through the native relay gateway (``NativeFrontDoorServer``) —
   FAIL unless the exact fleet invariant ``frontdoor.lookups ==
   affinity_hits + affinity_misses`` holds with the C fast path's
   deltas folded in, the raw native slots agree (``lookups == hits``
   — the fast path only takes live primaries), relays counted, zero
   stale accepts, and capstat renders the chain= line. Skipped with
   a notice when the library lacks the front-door TU.
8. OCCUPANCY GATE (r22): a deterministic sequential burst (each
   frame is exactly one batcher flush) on BOTH serve chains — FAIL
   if the ``device.occupancy`` gauge is missing/NaN/out-of-range on
   any scrape, if the exact flush-reason equation
   ``sum(batcher.flush.*) == batcher.flushes == device.dispatches``
   drifts, if the stage waterfall (ring wait + batcher wait +
   dispatch gap + exec) does not sum to the measured end-to-end
   request time within the 1-core tolerance, if the native chain
   reports ``serve.native.occ_fallbacks`` with a fresh library, or
   if the python/native occupancy counters are not bit-equal on the
   chain-invariant set (``device.dispatches``,
   ``device.stub.intervals``).

Runs under JAX_PLATFORMS=cpu inside the tier-1 time budget (~15 s).
"""

from __future__ import annotations

import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_PROM = [
    "cap_up",
    "cap_worker_pid",
    "cap_batcher_queued_tokens",
    "cap_batcher_inflight_batches",
    "cap_worker_requests_total",
    "cap_worker_tokens_total",
    "cap_batcher_batch_size",       # summary (quantiles + _sum/_count)
]

# gauges the native chain must additionally serve on every scrape
REQUIRED_NATIVE_GAUGES = ["serve.native.ring_depth",
                          "serve.native.ring_hwm",
                          "serve.native.obs_plane"]


def run_fleet(serve_chain):
    """Boot one 2-worker stub fleet on the given serve chain, drive
    the canonical mixed load, scrape and gate it. Returns (failures,
    info) where info carries the decision counters for cross-chain
    parity and the chains that actually came up."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo
    from tools import capstat

    failures = []
    info = {"chains": set(), "serve_decisions": {},
            "router_decisions": {}, "tid": None}
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3,
                      serve_chain=serve_chain)
    try:
        if not pool.wait_all_ready(30):
            return ([f"{serve_chain}: fleet did not come up"], info)
        info["chains"] = set(pool.serve_chains().values())
        telemetry.enable()
        telemetry.active().reset()   # per-run router counters
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        with telemetry.trace() as tid:
            for i in range(4):
                out = cl.verify_batch([f"smoke-{i}.ok", f"smoke-{i}.bad"])
                assert len(out) == 2
        info["tid"] = tid
        obs = pool.obs_endpoints()
        if len(obs) != 2:
            failures.append(f"expected 2 obs endpoints, got {obs}")
        worker_data = {}
        traced = False
        for wid, (host, port) in sorted(obs.items()):
            ep = f"{host}:{port}"
            worker_data[ep] = capstat.scrape(ep)
            text = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5).read().decode()
            for name in REQUIRED_PROM:
                if f"\n{name}" not in "\n" + text:
                    failures.append(f"worker {wid}: /metrics missing {name}")
            # \b-anchored: a NaN VALUE renders as a standalone token;
            # metric NAMES may legitimately contain the substring
            # ("tenant_…")
            import re as _re

            if _re.search(r"\bnan\b", text, _re.IGNORECASE):
                failures.append(f"worker {wid}: NaN value in /metrics")
            traced = traced or any(e.get("trace") == tid
                                   for e in worker_data[ep]["flight"])
            if serve_chain == "native":
                extra = worker_data[ep].get("extra") or {}
                for g in REQUIRED_NATIVE_GAUGES:
                    v = extra.get(g)
                    if v is None:
                        failures.append(
                            f"worker {wid}: missing native gauge {g}")
                    elif v != v:
                        failures.append(
                            f"worker {wid}: native gauge {g} is NaN")
        failures.extend(capstat.check_required(worker_data))
        if not traced:
            failures.append(
                f"trace {tid} reached no worker flight recorder")
        # The renderer must work over a live scrape (capstat's own
        # smoke), and must contain the aggregate section.
        rendered = capstat.render_fleet(worker_data, cl.snapshot())
        if "fleet aggregate" not in rendered:
            failures.append("capstat.render_fleet missing aggregate")

        # Decision counters: the mixed batches above were half .ok /
        # half rejected, so BOTH verdicts must have counted on every
        # exercised surface — workers (merged scrape) and the router
        # (this process's recorder).
        worker_counters = telemetry.merge_snapshots(
            [d["snapshot"] for d in worker_data.values()]
        ).get("counters") or {}
        failures.extend(obs_decision.nonzero_check(worker_counters,
                                                   ["serve"]))
        router_counters = telemetry.active().snapshot()["counters"]
        failures.extend(obs_decision.nonzero_check(router_counters,
                                                   ["router"]))
        info["serve_decisions"] = obs_decision.decision_counters(
            {k: v for k, v in worker_counters.items()
             if k.startswith("decision.serve.")})
        info["router_decisions"] = obs_decision.decision_counters(
            router_counters)

        # Verdict-cache gate: a repeated-token burst (client tier off
        # — the workers must see every repeat) has to HIT, and the
        # exactness invariant hits+misses == lookups must hold on the
        # fresh merged scrape. stale_accepts is the serve-time clamp
        # tripwire: any movement in a clean run is a cache bug.
        for _ in range(5):
            out = cl.verify_batch(["smoke-hot.ok"] * 4)
            assert len(out) == 4
        cache_counters = telemetry.merge_snapshots(
            [capstat.scrape(f"{host}:{port}")["snapshot"]
             for _, (host, port) in sorted(obs.items())]
        ).get("counters") or {}
        hits = cache_counters.get("vcache.hits", 0)
        misses = cache_counters.get("vcache.misses", 0)
        lookups = cache_counters.get("vcache.lookups", 0)
        if hits <= 0:
            failures.append("verdict cache: zero hits after a "
                            "repeated-token burst")
        if lookups != hits + misses:
            failures.append(
                f"verdict cache: lookups {lookups} != hits {hits} + "
                f"misses {misses} (accounting drift)")
        if cache_counters.get("vcache.stale_accepts", 0):
            failures.append("verdict cache: stale_accepts tripwire "
                            "moved in a clean run")

        # SLO engine over the LIVE fleet: an evaluation error (not a
        # breach — a crash/parse failure) is a smoke failure; so is a
        # wrong-verdict breach, which can only mean corrupted verdict
        # accounting in a clean stub run.
        try:
            merged_all = telemetry.merge_snapshots(
                [d["snapshot"] for d in worker_data.values()]
                + [telemetry.active().snapshot()])
            slo_results = obs_slo.evaluate_once(merged_all)
            for r in slo_results:
                if r["name"] == "wrong_verdicts" and not r["ok"]:
                    failures.append(f"SLO breach in clean run: {r}")
        except Exception as e:  # noqa: BLE001 - the gate itself
            failures.append(f"SLO engine evaluation error: {e!r}")
    finally:
        pool.close()
    return ([f"{serve_chain}: {f}" for f in failures], info)


def _tenant_token(issuer: str, kid: str, suffix: str) -> str:
    """A stub-verifiable token whose payload carries a real issuer
    claim (suffix .ok/.bad drives the stub verdict; the payload drives
    tenant attribution)."""
    import base64
    import json

    def b64(obj):
        return base64.urlsafe_b64encode(
            json.dumps(obj).encode()).rstrip(b"=").decode()

    return (b64({"alg": "ES256", "kid": kid}) + "."
            + b64({"iss": issuer}) + "." + suffix)


def run_tenant_gate(serve_chain):
    """The two-tenant attribution gate: a QUIET tenant (all accepts)
    and a FLOODING tenant (all rejects, 10× the traffic) through one
    stub fleet. FAIL if (a) the two issuers do not produce DISTINCT
    per-tenant counters keyed by their hashes, (b) the exact
    ``tenant.lookups == tenant.attributed + tenant.overflow`` equation
    drifts, (c) the flooding tenant's default per-tenant SLO rule does
    NOT breach or the quiet tenant's does, or (d) any RAW issuer
    string appears in any scraped surface (/metrics, /snapshot,
    /decisions). Returns (failures, tenant-counter map) so main() can
    pin native-vs-python equality."""
    import hashlib
    import json as _json

    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo
    from tools import capstat

    iss_quiet = "https://tenant-quiet.example"
    iss_flood = "https://tenant-flood.example"
    h_quiet = hashlib.sha256(iss_quiet.encode()).hexdigest()[:12]
    h_flood = hashlib.sha256(iss_flood.encode()).hexdigest()[:12]
    quiet = _tenant_token(iss_quiet, "kq", "ok")
    flood = _tenant_token(iss_flood, "kf", "bad")
    failures = []
    tenant_counters = {}
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3,
                      serve_chain=serve_chain)
    try:
        if not pool.wait_all_ready(30):
            return ([f"{serve_chain}: tenant fleet did not come up"],
                    tenant_counters)
        telemetry.enable()
        telemetry.active().reset()
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        for _ in range(3):
            assert len(cl.verify_batch([quiet] * 4)) == 4
        for _ in range(12):
            assert len(cl.verify_batch([flood] * 8)) == 8
        obs = pool.obs_endpoints()
        raw_bodies = []
        snaps = []
        for wid, (host, port) in sorted(obs.items()):
            data = capstat.scrape(f"{host}:{port}")
            snaps.append(data["snapshot"])
            raw_bodies.append(urllib.request.urlopen(
                f"http://{host}:{port}/metrics",
                timeout=5).read().decode())
            raw_bodies.append(_json.dumps(data["snapshot"]))
            raw_bodies.append(urllib.request.urlopen(
                f"http://{host}:{port}/decisions",
                timeout=5).read().decode())
            # the /tenants operator endpoint: must serve the rollup
            # (hashed ids only) and join the redaction sweep
            ten_body = urllib.request.urlopen(
                f"http://{host}:{port}/tenants",
                timeout=5).read().decode()
            raw_bodies.append(ten_body)
            if _json.loads(ten_body).get("lookups", 0) <= 0:
                failures.append(
                    f"worker {wid}: /tenants served zero lookups "
                    "after two-tenant traffic")
        merged = telemetry.merge_snapshots(snaps)
        counters = merged.get("counters") or {}
        qa = counters.get(f"decision.serve.tenant.{h_quiet}.accept", 0)
        fr = counters.get(f"decision.serve.tenant.{h_flood}.reject", 0)
        if qa < 12:
            failures.append(f"quiet tenant accept counter {qa} < 12")
        if fr < 96:
            failures.append(f"flood tenant reject counter {fr} < 96")
        if counters.get(f"decision.serve.tenant.{h_quiet}.reject", 0):
            failures.append("quiet tenant shows rejects")
        look = counters.get("tenant.lookups", 0)
        attr = counters.get("tenant.attributed", 0)
        ovf = counters.get("tenant.overflow", 0)
        if not look or look != attr + ovf:
            failures.append(
                f"tenant accounting drift: lookups {look} != "
                f"attributed {attr} + overflow {ovf}")
        # per-tenant latency series must exist for both tenants
        for h in (h_quiet, h_flood):
            if f"tenant.{h}.request_s" not in (merged.get("series")
                                               or {}):
                failures.append(f"missing tenant latency series for "
                                f"{h}")
        # default per-tenant SLO: flood breaches, quiet stays green
        states = {}
        for r in obs_slo.evaluate_once(merged):
            if r["name"].startswith("tenant_reject_ratio["):
                states[r.get("tenant")] = r["ok"]
        if states.get(h_flood, True):
            failures.append("flooding tenant's reject-ratio rule did "
                            "NOT breach")
        if not states.get(h_quiet, False):
            failures.append("quiet tenant's reject-ratio rule is not "
                            "green")
        # capstat ledger renders over the live scrape
        rendered = capstat.render_tenants(merged)
        if h_flood not in rendered or "BREACH" not in rendered:
            failures.append("capstat.render_tenants missing the "
                            "flooding tenant / its breach state")
        # redaction: no raw issuer anywhere on any scraped surface
        for body in raw_bodies:
            for needle in (iss_quiet, iss_flood, "tenant-quiet",
                           "tenant-flood", "://"):
                if needle in body:
                    failures.append(
                        f"raw issuer material {needle!r} leaked into "
                        "a scraped surface")
                    break
        # decision-side tenant counters only: vcache.tenant.* hit
        # splits depend on request/chunk coalescing timing, decision
        # totals never do — those are the cross-chain equality pin
        tenant_counters = {
            k: v for k, v in sorted(counters.items())
            if (k.startswith("decision.") and ".tenant." in k)
            or k.startswith("tenant.")}
    finally:
        pool.close()
    return ([f"{serve_chain}: {f}" for f in failures], tenant_counters)


def run_admission_gate(serve_chain):
    """The flooding-tenant ADMISSION gate (r20): a two-tenant stub
    fleet with per-tenant token buckets armed (deterministic config —
    rate ≈ 0, burst 8 — so refill is negligible and the counts are
    exact). FAIL if (a) the flooder collects zero ``throttled``
    rejects or the quiet tenant collects ANY, (b) the exact equation
    ``admission.checked == admission.admitted + admission.throttled``
    drifts on the merged scrape, (c) a throttled response carries no
    parseable retry-after hint, or (d) the quiet tenant's verdicts are
    not all accepts (admission must never alter a verdict). Returns
    (failures, admission-counter map) so main() can pin
    native-vs-python equality — the config is deterministic, so the
    chains must count IDENTICALLY."""
    import hashlib

    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.serve import protocol
    from tools import capstat

    iss_quiet = "https://adm-quiet.example"
    iss_flood = "https://adm-flood.example"
    h_flood = hashlib.sha256(iss_flood.encode()).hexdigest()[:12]
    h_quiet = hashlib.sha256(iss_quiet.encode()).hexdigest()[:12]
    quiet = _tenant_token(iss_quiet, "aq", "ok")
    flood = _tenant_token(iss_flood, "af", "ok")
    failures = []
    adm_counters = {}
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3,
                      serve_chain=serve_chain,
                      env_extra={"CAP_SERVE_FAIR": "1",
                                 "CAP_SERVE_ADMIT_RATE": "0.0001",
                                 "CAP_SERVE_ADMIT_BURST": "8"})
    try:
        if not pool.wait_all_ready(30):
            return ([f"{serve_chain}: admission fleet did not come "
                     "up"], adm_counters)
        telemetry.enable()
        telemetry.active().reset()
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        quiet_out = cl.verify_batch([quiet] * 6)
        flood_out = []
        for _ in range(4):
            flood_out.extend(cl.verify_batch([flood] * 8))
        thr = [r for r in flood_out if isinstance(r, Exception)
               and str(r).startswith("ThrottledError")]
        if not thr:
            failures.append("flooding tenant collected zero "
                            "throttled rejects")
        if any(isinstance(r, Exception) for r in quiet_out):
            failures.append("quiet tenant's verdicts were altered "
                            "under admission")
        if thr and protocol.retry_after_hint(str(thr[0])) is None:
            failures.append("throttled response carries no parseable "
                            "retry-after hint")
        merged = telemetry.merge_snapshots(
            [capstat.scrape(f"{host}:{port}")["snapshot"]
             for _, (host, port) in sorted(
                 pool.obs_endpoints().items())])
        counters = merged.get("counters") or {}
        checked = counters.get("admission.checked", 0)
        admitted = counters.get("admission.admitted", 0)
        throttled = counters.get("admission.throttled", 0)
        if not checked or checked != admitted + throttled:
            failures.append(
                f"admission accounting drift: checked {checked} != "
                f"admitted {admitted} + throttled {throttled}")
        ft = counters.get(
            f"decision.serve.tenant.{h_flood}.reject.throttled", 0)
        qt = counters.get(
            f"decision.serve.tenant.{h_quiet}.reject.throttled", 0)
        if ft <= 0:
            failures.append("flood tenant's throttled counter is "
                            f"zero (got {ft})")
        if qt:
            failures.append(f"quiet tenant was throttled ({qt})")
        if len(thr) != throttled:
            failures.append(
                f"wire/counter mismatch: {len(thr)} throttled "
                f"responses vs counter {throttled}")
        adm_counters = {
            k: v for k, v in sorted(counters.items())
            if k.startswith("admission.")
            or k.endswith(".reject.throttled")}
    finally:
        pool.close()
    return ([f"{serve_chain}: {f}" for f in failures], adm_counters)


def run_occupancy_gate(serve_chain):
    """The pipeline-occupancy gate (r22): drive a DETERMINISTIC
    sequential single-token burst through a 2-worker stub fleet —
    each frame arrives alone, so every frame is exactly one batcher
    flush and one engine dispatch on BOTH chains. FAIL if (a) the
    ``device.occupancy`` gauge is missing/NaN/out-of-range on any
    worker scrape, (b) the exact flush-reason equation
    ``sum(batcher.flush.*) == batcher.flushes == device.dispatches``
    drifts, (c) the per-stage histograms do not sum to the measured
    end-to-end request mean within the (generous — 1-core CI)
    tolerance, or (d) the native chain counts ``occ_fallbacks`` with
    a freshly built library or serves no measured ring-wait samples.
    Returns (failures, chain-invariant occupancy counters) so main()
    can pin python-vs-native bit-equality — flush-reason NAMES are
    timing-dependent under load, but the dispatch/interval totals of
    this sequential drive never are."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from tools import capstat

    failures = []
    occ_counters = {}
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3,
                      serve_chain=serve_chain)
    try:
        if not pool.wait_all_ready(30):
            return ([f"{serve_chain}: occupancy fleet did not come "
                     "up"], occ_counters)
        telemetry.enable()
        telemetry.active().reset()
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        # sequential blocking calls with DISTINCT tokens: no frame
        # coalescing (next send waits for the previous response) and
        # no verdict-cache short-circuit — N calls == N dispatches
        n = 16
        for i in range(n):
            out = cl.verify_batch([f"occ-{serve_chain}-{i}.ok"])
            assert len(out) == 1
        snaps = []
        for wid, (host, port) in sorted(pool.obs_endpoints().items()):
            data = capstat.scrape(f"{host}:{port}")
            snaps.append(data["snapshot"])
            gauges = (data["snapshot"] or {}).get("gauges") or {}
            occ = gauges.get("device.occupancy")
            if occ is None:
                failures.append(f"worker {wid}: device.occupancy "
                                "gauge missing after the burst")
            elif not (occ == occ and 0.0 <= occ <= 1.0):
                failures.append(f"worker {wid}: device.occupancy "
                                f"gauge out of range ({occ})")
        merged = telemetry.merge_snapshots(snaps)
        counters = merged.get("counters") or {}
        dispatches = counters.get("device.dispatches", 0)
        flushes = counters.get("batcher.flushes", 0)
        flush_sum = sum(v for k, v in counters.items()
                        if k.startswith("batcher.flush."))
        if dispatches != n:
            failures.append(f"device.dispatches {dispatches} != {n} "
                            "sequential frames")
        if flush_sum != flushes or flushes != dispatches:
            failures.append(
                f"flush-reason accounting drift: sum(batcher.flush.*) "
                f"{flush_sum} != batcher.flushes {flushes} != "
                f"device.dispatches {dispatches}")
        busy = counters.get("device.busy_us", 0)
        wall = counters.get("device.wall_us", 0)
        if wall <= 0 or busy < 0 or busy > wall:
            failures.append(f"occupancy counters implausible: "
                            f"busy_us {busy} wall_us {wall}")
        # stage waterfall: the per-stage means must sum to the e2e
        # request mean within a generous band — a missing stage or a
        # double-counted one lands far outside it even on a loaded
        # 1-core CI box
        summ = telemetry.summarize_snapshot(merged)
        stage_sum = sum(
            summ[s]["mean"] for s in
            ("queue.ring_wait_s", "queue.batcher_wait_s",
             "queue.dispatch_gap_s", "device.exec_s") if s in summ)
        e2e_name = ("serve.native.request_s" if serve_chain == "native"
                    else "serve.request_s")
        e2e = (summ.get(e2e_name) or {}).get("mean", 0.0)
        if e2e <= 0:
            failures.append(f"no {e2e_name} samples for the "
                            "stage-sum check")
        elif not (0.2 * e2e <= stage_sum <= 2.0 * e2e):
            failures.append(
                f"stage waterfall drifted from e2e: stages sum "
                f"{stage_sum * 1e6:.1f}us vs {e2e_name} mean "
                f"{e2e * 1e6:.1f}us")
        if serve_chain == "native":
            if counters.get("serve.native.occ_fallbacks", 0):
                failures.append(
                    "occ_fallbacks moved with a fresh native library "
                    "(occupancy layout handshake failed)")
            if "queue.ring_wait_s" not in summ:
                failures.append("native chain served no measured "
                                "queue.ring_wait_s samples")
        occ_counters = {k: counters.get(k, 0)
                        for k in ("device.dispatches",
                                  "device.stub.intervals")}
    finally:
        pool.close()
    return ([f"{serve_chain}: {f}" for f in failures], occ_counters)


def run_frontdoor_gate():
    """The 2-pool front-door gate: a repeated-token burst routed by
    digest affinity must (a) show ``frontdoor.affinity_hits`` > 0 with
    the EXACT ``lookups == hits + misses`` accounting, (b) leave
    ``vcache.stale_accepts`` untouched on every worker, and (c) render
    through capstat's front-door view."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import FrontDoor, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from tools import capstat

    failures = []
    pools = [WorkerPool(1, keyset_spec="stub", ping_interval=0.3)
             for _ in range(2)]
    fd = None
    try:
        for i, p in enumerate(pools):
            if not p.wait_all_ready(30):
                return [f"frontdoor: pool {i} did not come up"]
        telemetry.enable()
        telemetry.active().reset()
        fd = FrontDoor(pools, fallback=StubKeySet())
        # spread + repeat: every distinct token lands on its ring
        # owner; repeats must land on the SAME owner (that worker's
        # vcache then hits)
        toks = [f"fd-smoke-{i}.ok" for i in range(16)]
        for _ in range(5):
            out = fd.verify_batch(toks)
            assert len(out) == len(toks)
        c = fd.counters()
        if c.get("frontdoor.affinity_hits", 0) <= 0:
            failures.append("front door: zero affinity hits after a "
                            "repeated-token burst")
        if c.get("frontdoor.lookups", 0) != \
                c.get("frontdoor.affinity_hits", 0) \
                + c.get("frontdoor.affinity_misses", 0):
            failures.append(
                f"front door: lookups {c.get('frontdoor.lookups')} != "
                f"hits {c.get('frontdoor.affinity_hits')} + misses "
                f"{c.get('frontdoor.affinity_misses')} "
                "(accounting drift)")
        worker_counters = {}
        for p in pools:
            for wid, (host, port) in sorted(p.obs_endpoints().items()):
                data = capstat.scrape(f"{host}:{port}")
                wc = (data["snapshot"] or {}).get("counters") or {}
                for k, v in wc.items():
                    worker_counters[k] = worker_counters.get(k, 0) + v
                if wc.get("vcache.stale_accepts", 0):
                    failures.append(
                        f"front door: stale_accepts moved on "
                        f"{host}:{port}")
        if worker_counters.get("vcache.hits", 0) <= 0:
            failures.append("front door: repeats produced no worker "
                            "vcache hits (affinity broken?)")
        rendered = capstat.render_frontdoor(fd.snapshot())
        if "affinity_hit" not in rendered or "pool 0" not in rendered:
            failures.append("capstat.render_frontdoor missing fields")
    finally:
        if fd is not None:
            fd.close()
        for p in pools:
            p.close()
    return failures


def run_native_frontdoor_gate():
    """The NATIVE router-chain front-door gate (r21): the same 2-pool
    topology as :func:`run_frontdoor_gate`, but served through
    ``NativeFrontDoorServer`` — C readers route and relay, Python only
    sees the slow path. A spread + repeated burst over the gateway's
    front socket must (a) keep the EXACT fleet invariant ``lookups ==
    affinity_hits + affinity_misses`` with the native deltas folded
    in, (b) relay every fast-path token (``relays`` > 0 with
    ``lookups == hits`` on the raw native slots — the fast path only
    takes live primaries), (c) leave ``vcache.stale_accepts`` at zero
    on every worker, and (d) render through capstat's front-door view
    with the chain= line."""
    import socket

    from cap_tpu import telemetry
    from cap_tpu.fleet import WorkerPool
    from cap_tpu.fleet.frontdoor import FrontDoor, NativeFrontDoorServer
    from cap_tpu.serve import protocol
    from tools import capstat

    failures = []
    pools = [WorkerPool(1, keyset_spec="stub", ping_interval=0.3)
             for _ in range(2)]
    gw = None
    try:
        for i, p in enumerate(pools):
            if not p.wait_all_ready(30):
                return [f"native frontdoor: pool {i} did not come up"]
        telemetry.enable()
        telemetry.active().reset()
        gw = NativeFrontDoorServer(FrontDoor(pools), refresh_s=0.1)
        toks = [f"fdnat-smoke-{i}.ok" for i in range(16)]
        s = socket.create_connection(gw.address, timeout=10)
        try:
            s.settimeout(10)
            reader = protocol.FrameReader(s)
            for _ in range(5):
                protocol.send_request(s, toks)
                ftype, entries = reader.recv_frame()
                if ftype != protocol.T_VERIFY_RESP or len(
                        entries) != len(toks):
                    failures.append("native frontdoor: bad verify "
                                    f"response ({ftype})")
                if any(st != 0 for st, _ in entries):
                    failures.append("native frontdoor: unexpected "
                                    "reject in a clean burst")
            # single-token repeats: single-owner frames, the splice
            # path, and every repeat must hit the SAME owner's vcache
            for _ in range(10):
                protocol.send_request(s, [toks[0]])
                ftype, entries = reader.recv_frame()
                if entries[0][0] != 0:
                    failures.append("native frontdoor: repeat burst "
                                    "rejected")
        finally:
            s.close()
        stats = gw.stats()
        c = stats.get("counters") or {}
        lookups = c.get("frontdoor.lookups", 0)
        hits = c.get("frontdoor.affinity_hits", 0)
        misses = c.get("frontdoor.affinity_misses", 0)
        if lookups <= 0:
            failures.append("native frontdoor: zero lookups after "
                            "the burst")
        if lookups != hits + misses:
            failures.append(
                f"native frontdoor: lookups {lookups} != hits {hits} "
                f"+ misses {misses} (accounting drift)")
        nat_lookups = c.get("frontdoor.native.lookups", 0)
        nat_hits = c.get("frontdoor.native.hits", 0)
        if nat_lookups != nat_hits:
            failures.append(
                f"native frontdoor: fast path lookups {nat_lookups} "
                f"!= hits {nat_hits} (the fast path only takes live "
                "primaries)")
        if c.get("frontdoor.native.relays", 0) <= 0:
            failures.append("native frontdoor: zero native relays — "
                            "everything went slow-path")
        if c.get("frontdoor.native.proto_errors", 0):
            failures.append("native frontdoor: protocol errors in a "
                            "clean run")
        for p in pools:
            for wid, (host, port) in sorted(p.obs_endpoints().items()):
                wc = (capstat.scrape(f"{host}:{port}")["snapshot"]
                      or {}).get("counters") or {}
                if wc.get("vcache.stale_accepts", 0):
                    failures.append(
                        f"native frontdoor: stale_accepts moved on "
                        f"{host}:{port}")
        rendered = capstat.render_frontdoor(stats)
        if "chain=native" not in rendered or "relays=" not in rendered:
            failures.append("capstat.render_frontdoor missing the "
                            "native chain line")
    finally:
        if gw is not None:
            gw.close(deadline_s=10.0)
        for p in pools:
            p.close()
    return failures


def main() -> int:
    failures, py_info = run_fleet("python")
    if py_info["chains"] != {"python"}:
        failures.append(f"python run came up as {py_info['chains']}")

    # two-tenant attribution gate (python chain): distinct issuers →
    # distinct hashed tenant counters, flood breaches its per-tenant
    # SLO while the quiet tenant stays green, zero raw issuers
    ten_failures, py_tenants = run_tenant_gate("python")
    failures.extend(ten_failures)

    # flooding-tenant ADMISSION gate (python chain): flooder throttled
    # with the exact checked == admitted + throttled equation, quiet
    # tenant untouched, retry-after hint parseable
    adm_failures, py_adm = run_admission_gate("python")
    failures.extend(adm_failures)

    # pipeline-occupancy gate (r22, python chain): occupancy gauge
    # live, exact flush-reason equation, stage waterfall sums to e2e
    occ_failures, py_occ = run_occupancy_gate("python")
    failures.extend(occ_failures)

    # native-chain gate: same load, native serve chain + telemetry
    # plane; decision counters must be IDENTICAL to the python run
    native_ok = False
    try:
        from cap_tpu.serve import native_serve
        native_ok = bool(getattr(native_serve.load(), "cap_tel_ok",
                                 False))
    except Exception:  # noqa: BLE001 - no compiler on this host
        native_ok = False
    if native_ok:
        nat_failures, nat_info = run_fleet("native")
        if nat_info["chains"] != {"native"}:
            nat_failures.append(
                f"native run came up as {nat_info['chains']}")
        failures.extend(nat_failures)
        if nat_info["serve_decisions"] != py_info["serve_decisions"]:
            failures.append(
                "native/python serve decision counters diverge: "
                f"native={nat_info['serve_decisions']} "
                f"python={py_info['serve_decisions']}")
        nat_ten_failures, nat_tenants = run_tenant_gate("native")
        failures.extend(nat_ten_failures)
        if nat_tenants != py_tenants:
            failures.append(
                "native/python TENANT counters diverge: "
                f"native={nat_tenants} python={py_tenants}")
        nat_adm_failures, nat_adm = run_admission_gate("native")
        failures.extend(nat_adm_failures)
        if nat_adm != py_adm:
            failures.append(
                "native/python ADMISSION counters diverge: "
                f"native={nat_adm} python={py_adm}")
        nat_occ_failures, nat_occ = run_occupancy_gate("native")
        failures.extend(nat_occ_failures)
        if nat_occ != py_occ:
            failures.append(
                "native/python OCCUPANCY counters diverge: "
                f"native={nat_occ} python={py_occ}")
    else:
        print("obs-smoke NOTE: native serve runtime unavailable — "
              "native-chain gate skipped", file=sys.stderr)

    # 2-pool front-door gate (routing-tier accounting + worker-side
    # cache integrity under affinity routing)
    failures.extend(run_frontdoor_gate())

    # …and the same topology through the NATIVE router chain (r21):
    # exact lookup accounting with the C fast path folded in, native
    # relays counted, zero stale accepts, capstat chain line
    fd_native_ok = False
    try:
        from cap_tpu.serve import native_serve
        fd_native_ok = bool(getattr(native_serve.load(), "cap_fd_ok",
                                    False))
    except Exception:  # noqa: BLE001 - no compiler on this host
        fd_native_ok = False
    if fd_native_ok:
        failures.extend(run_native_frontdoor_gate())
    else:
        print("obs-smoke NOTE: native front-door runtime unavailable "
              "— native router gate skipped", file=sys.stderr)

    if failures:
        for f in failures:
            print(f"obs-smoke FAIL: {f}", file=sys.stderr)
        return 1
    print("obs-smoke OK: python fleet scraped clean (gauges, trace "
          "reassembly, decision counters, SLO engine), two-tenant "
          "gate clean (hashed attribution, flood SLO breach, zero "
          "raw issuers), admission gate clean (flooder throttled "
          "with exact checked==admitted+throttled, quiet tenant "
          "untouched, retry-after parseable), occupancy gate clean "
          "(gauge live, sum(flush.*) == dispatches exact, stage "
          "waterfall sums to e2e)"
          + (", native fleet scraped clean with counter AND tenant "
             "AND admission AND occupancy parity to the python run"
             if native_ok else "")
          + ", 2-pool front door routed clean (affinity hits, exact "
            "lookup accounting, zero stale accepts)"
          + (", native router chain routed clean (C fast path folded "
             "into the exact invariant, relays counted, zero stale "
             "accepts)" if fd_native_ok else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
