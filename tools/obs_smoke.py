#!/usr/bin/env python3
"""obs-smoke: boot stub fleets, scrape them, fail on gaps.

The CI guard for the observability surface (``make obs-smoke``):

1. spawn a 2-worker stub WorkerPool (no jax in the children — starts
   in ~1 s) and drive a few traced MIXED (verified + rejected)
   batches through a FleetClient;
2. scrape every worker's /metrics (Prometheus text) and /snapshot;
3. FAIL (exit 1) if any required gauge is missing or NaN, if the
   Prometheus text lacks the required metric families, or if the
   traced request produced no flight-recorder entry;
4. FAIL if any exercised surface (serve worker, fleet router) reports
   ZERO decision counters — accept AND reject must both have counted
   for the mixed batch (cap_tpu.obs.decision);
4b. VERDICT-CACHE GATE: drive a repeated-token burst and FAIL if the
   workers report zero ``vcache.hits``, if the exactness invariant
   ``vcache.lookups == vcache.hits + vcache.misses`` does not hold on
   the merged scrape, or if the ``vcache.stale_accepts`` tripwire
   moved — on BOTH serve chains;
5. FAIL if the SLO engine cannot evaluate the default rules over the
   live fleet's merged counters, or if the wrong-verdict objective is
   breached;
6. NATIVE-CHAIN GATE: repeat the same load against a fleet booted
   with ``--serve-chain native`` (the native telemetry plane counts
   the serve surface in C) and FAIL on any missing/NaN gauge —
   including ``serve.native.ring_hwm`` — or on any decision-counter
   divergence from the python-chain run: obs must cost less, never
   count differently. Skipped with a notice when the native library
   cannot build on this host.

Runs under JAX_PLATFORMS=cpu inside the tier-1 time budget (~15 s).
"""

from __future__ import annotations

import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_PROM = [
    "cap_up",
    "cap_worker_pid",
    "cap_batcher_queued_tokens",
    "cap_batcher_inflight_batches",
    "cap_worker_requests_total",
    "cap_worker_tokens_total",
    "cap_batcher_batch_size",       # summary (quantiles + _sum/_count)
]

# gauges the native chain must additionally serve on every scrape
REQUIRED_NATIVE_GAUGES = ["serve.native.ring_depth",
                          "serve.native.ring_hwm",
                          "serve.native.obs_plane"]


def run_fleet(serve_chain):
    """Boot one 2-worker stub fleet on the given serve chain, drive
    the canonical mixed load, scrape and gate it. Returns (failures,
    info) where info carries the decision counters for cross-chain
    parity and the chains that actually came up."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo
    from tools import capstat

    failures = []
    info = {"chains": set(), "serve_decisions": {},
            "router_decisions": {}, "tid": None}
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.3,
                      serve_chain=serve_chain)
    try:
        if not pool.wait_all_ready(30):
            return ([f"{serve_chain}: fleet did not come up"], info)
        info["chains"] = set(pool.serve_chains().values())
        telemetry.enable()
        telemetry.active().reset()   # per-run router counters
        cl = FleetClient(pool, fallback=StubKeySet(), rr_seed=0)
        with telemetry.trace() as tid:
            for i in range(4):
                out = cl.verify_batch([f"smoke-{i}.ok", f"smoke-{i}.bad"])
                assert len(out) == 2
        info["tid"] = tid
        obs = pool.obs_endpoints()
        if len(obs) != 2:
            failures.append(f"expected 2 obs endpoints, got {obs}")
        worker_data = {}
        traced = False
        for wid, (host, port) in sorted(obs.items()):
            ep = f"{host}:{port}"
            worker_data[ep] = capstat.scrape(ep)
            text = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5).read().decode()
            for name in REQUIRED_PROM:
                if f"\n{name}" not in "\n" + text:
                    failures.append(f"worker {wid}: /metrics missing {name}")
            if "nan" in text.lower():
                failures.append(f"worker {wid}: NaN value in /metrics")
            traced = traced or any(e.get("trace") == tid
                                   for e in worker_data[ep]["flight"])
            if serve_chain == "native":
                extra = worker_data[ep].get("extra") or {}
                for g in REQUIRED_NATIVE_GAUGES:
                    v = extra.get(g)
                    if v is None:
                        failures.append(
                            f"worker {wid}: missing native gauge {g}")
                    elif v != v:
                        failures.append(
                            f"worker {wid}: native gauge {g} is NaN")
        failures.extend(capstat.check_required(worker_data))
        if not traced:
            failures.append(
                f"trace {tid} reached no worker flight recorder")
        # The renderer must work over a live scrape (capstat's own
        # smoke), and must contain the aggregate section.
        rendered = capstat.render_fleet(worker_data, cl.snapshot())
        if "fleet aggregate" not in rendered:
            failures.append("capstat.render_fleet missing aggregate")

        # Decision counters: the mixed batches above were half .ok /
        # half rejected, so BOTH verdicts must have counted on every
        # exercised surface — workers (merged scrape) and the router
        # (this process's recorder).
        worker_counters = telemetry.merge_snapshots(
            [d["snapshot"] for d in worker_data.values()]
        ).get("counters") or {}
        failures.extend(obs_decision.nonzero_check(worker_counters,
                                                   ["serve"]))
        router_counters = telemetry.active().snapshot()["counters"]
        failures.extend(obs_decision.nonzero_check(router_counters,
                                                   ["router"]))
        info["serve_decisions"] = obs_decision.decision_counters(
            {k: v for k, v in worker_counters.items()
             if k.startswith("decision.serve.")})
        info["router_decisions"] = obs_decision.decision_counters(
            router_counters)

        # Verdict-cache gate: a repeated-token burst (client tier off
        # — the workers must see every repeat) has to HIT, and the
        # exactness invariant hits+misses == lookups must hold on the
        # fresh merged scrape. stale_accepts is the serve-time clamp
        # tripwire: any movement in a clean run is a cache bug.
        for _ in range(5):
            out = cl.verify_batch(["smoke-hot.ok"] * 4)
            assert len(out) == 4
        cache_counters = telemetry.merge_snapshots(
            [capstat.scrape(f"{host}:{port}")["snapshot"]
             for _, (host, port) in sorted(obs.items())]
        ).get("counters") or {}
        hits = cache_counters.get("vcache.hits", 0)
        misses = cache_counters.get("vcache.misses", 0)
        lookups = cache_counters.get("vcache.lookups", 0)
        if hits <= 0:
            failures.append("verdict cache: zero hits after a "
                            "repeated-token burst")
        if lookups != hits + misses:
            failures.append(
                f"verdict cache: lookups {lookups} != hits {hits} + "
                f"misses {misses} (accounting drift)")
        if cache_counters.get("vcache.stale_accepts", 0):
            failures.append("verdict cache: stale_accepts tripwire "
                            "moved in a clean run")

        # SLO engine over the LIVE fleet: an evaluation error (not a
        # breach — a crash/parse failure) is a smoke failure; so is a
        # wrong-verdict breach, which can only mean corrupted verdict
        # accounting in a clean stub run.
        try:
            merged_all = telemetry.merge_snapshots(
                [d["snapshot"] for d in worker_data.values()]
                + [telemetry.active().snapshot()])
            slo_results = obs_slo.evaluate_once(merged_all)
            for r in slo_results:
                if r["name"] == "wrong_verdicts" and not r["ok"]:
                    failures.append(f"SLO breach in clean run: {r}")
        except Exception as e:  # noqa: BLE001 - the gate itself
            failures.append(f"SLO engine evaluation error: {e!r}")
    finally:
        pool.close()
    return ([f"{serve_chain}: {f}" for f in failures], info)


def run_frontdoor_gate():
    """The 2-pool front-door gate: a repeated-token burst routed by
    digest affinity must (a) show ``frontdoor.affinity_hits`` > 0 with
    the EXACT ``lookups == hits + misses`` accounting, (b) leave
    ``vcache.stale_accepts`` untouched on every worker, and (c) render
    through capstat's front-door view."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import FrontDoor, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet
    from tools import capstat

    failures = []
    pools = [WorkerPool(1, keyset_spec="stub", ping_interval=0.3)
             for _ in range(2)]
    fd = None
    try:
        for i, p in enumerate(pools):
            if not p.wait_all_ready(30):
                return [f"frontdoor: pool {i} did not come up"]
        telemetry.enable()
        telemetry.active().reset()
        fd = FrontDoor(pools, fallback=StubKeySet())
        # spread + repeat: every distinct token lands on its ring
        # owner; repeats must land on the SAME owner (that worker's
        # vcache then hits)
        toks = [f"fd-smoke-{i}.ok" for i in range(16)]
        for _ in range(5):
            out = fd.verify_batch(toks)
            assert len(out) == len(toks)
        c = fd.counters()
        if c.get("frontdoor.affinity_hits", 0) <= 0:
            failures.append("front door: zero affinity hits after a "
                            "repeated-token burst")
        if c.get("frontdoor.lookups", 0) != \
                c.get("frontdoor.affinity_hits", 0) \
                + c.get("frontdoor.affinity_misses", 0):
            failures.append(
                f"front door: lookups {c.get('frontdoor.lookups')} != "
                f"hits {c.get('frontdoor.affinity_hits')} + misses "
                f"{c.get('frontdoor.affinity_misses')} "
                "(accounting drift)")
        worker_counters = {}
        for p in pools:
            for wid, (host, port) in sorted(p.obs_endpoints().items()):
                data = capstat.scrape(f"{host}:{port}")
                wc = (data["snapshot"] or {}).get("counters") or {}
                for k, v in wc.items():
                    worker_counters[k] = worker_counters.get(k, 0) + v
                if wc.get("vcache.stale_accepts", 0):
                    failures.append(
                        f"front door: stale_accepts moved on "
                        f"{host}:{port}")
        if worker_counters.get("vcache.hits", 0) <= 0:
            failures.append("front door: repeats produced no worker "
                            "vcache hits (affinity broken?)")
        rendered = capstat.render_frontdoor(fd.snapshot())
        if "affinity_hit" not in rendered or "pool 0" not in rendered:
            failures.append("capstat.render_frontdoor missing fields")
    finally:
        if fd is not None:
            fd.close()
        for p in pools:
            p.close()
    return failures


def main() -> int:
    failures, py_info = run_fleet("python")
    if py_info["chains"] != {"python"}:
        failures.append(f"python run came up as {py_info['chains']}")

    # native-chain gate: same load, native serve chain + telemetry
    # plane; decision counters must be IDENTICAL to the python run
    native_ok = False
    try:
        from cap_tpu.serve import native_serve
        native_ok = bool(getattr(native_serve.load(), "cap_tel_ok",
                                 False))
    except Exception:  # noqa: BLE001 - no compiler on this host
        native_ok = False
    if native_ok:
        nat_failures, nat_info = run_fleet("native")
        if nat_info["chains"] != {"native"}:
            nat_failures.append(
                f"native run came up as {nat_info['chains']}")
        failures.extend(nat_failures)
        if nat_info["serve_decisions"] != py_info["serve_decisions"]:
            failures.append(
                "native/python serve decision counters diverge: "
                f"native={nat_info['serve_decisions']} "
                f"python={py_info['serve_decisions']}")
    else:
        print("obs-smoke NOTE: native serve runtime unavailable — "
              "native-chain gate skipped", file=sys.stderr)

    # 2-pool front-door gate (routing-tier accounting + worker-side
    # cache integrity under affinity routing)
    failures.extend(run_frontdoor_gate())

    if failures:
        for f in failures:
            print(f"obs-smoke FAIL: {f}", file=sys.stderr)
        return 1
    print("obs-smoke OK: python fleet scraped clean (gauges, trace "
          "reassembly, decision counters, SLO engine)"
          + (", native fleet scraped clean with counter parity to "
             "the python run" if native_ok else "")
          + ", 2-pool front door routed clean (affinity hits, exact "
            "lookup accounting, zero stale accepts)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
