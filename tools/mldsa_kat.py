#!/usr/bin/env python3
"""make mldsa-kat: the ML-DSA known-answer + parity gate.

Two checks, exit nonzero on any mismatch:

1. **KAT sweep** — every pinned vector in tests/data/mldsa_kat.json
   through all four verify surfaces (CPU oracle KeySet, TPU batch
   native + object paths, serve worker, fleet router); every verdict
   must equal the pinned one on every surface.
2. **oracle/engine parity selftest** — freshly generated random
   signatures (valid + mutated) per parameter set, device engine vs
   the pure-int host oracle, bit-exact.

Dependency-free (no ``cryptography``), stub-free (real engine), and
fast enough for the local CI gate (``make check``).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KAT_PATH = os.path.join(REPO, "tests", "data", "mldsa_kat.json")


def kat_sweep() -> int:
    from cap_tpu.fleet import FleetClient
    from cap_tpu.jwt.jwk import parse_jwks
    from cap_tpu.jwt.keyset import StaticKeySet
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.serve.client import VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    with open(KAT_PATH) as f:
        kat = json.load(f)
    jwks = parse_jwks(kat["keys"])
    tokens = [v["token"] for v in kat["vectors"]]
    wants = [v["verdict"] == "accept" for v in kat["vectors"]]

    out = {}
    out["oracle"] = StaticKeySet([j.key for j in jwks]).verify_batch(
        tokens)
    ks = TPUBatchKeySet(jwks)
    out["tpu"] = ks.verify_batch(tokens)
    out["tpu_objects"] = ks._verify_batch_objects(tokens)
    w = VerifyWorker(TPUBatchKeySet(jwks), target_batch=16,
                     max_wait_ms=5.0)
    try:
        host, port = w.address
        with VerifyClient(host, port, timeout=600.0) as c:
            out["serve"] = c.verify_batch(tokens)
        out["router"] = FleetClient([(host, port)],
                                    rr_seed=0).verify_batch(tokens)
    finally:
        w.close()

    bad = 0
    for i, (v, want) in enumerate(zip(kat["vectors"], wants)):
        for surf, res in out.items():
            got = not isinstance(res[i], Exception)
            if got != want:
                print(f"mldsa-kat FAIL: {v['name']} on {surf}: "
                      f"{'accept' if got else 'reject'} != pinned "
                      f"{v['verdict']}", file=sys.stderr)
                bad += 1
    print(f"mldsa-kat: {len(tokens)} vectors x "
          f"{len(out)} surfaces swept")
    return bad


def parity_selftest(per_set: int = 96) -> int:
    from cap_tpu.tpu import mldsa

    bad = 0
    for pset in sorted(mldsa.PARAMS):
        p = mldsa.PARAMS[pset]
        priv, pub = mldsa.keygen(pset, bytes([77]) * 32)
        table = mldsa.MLDSAKeyTable(pset, [pub])
        base = [(priv.sign(f"kat-{pset}-{i}".encode()),
                 f"kat-{pset}-{i}".encode()) for i in range(8)]
        sigs, msgs = [], []
        for i in range(per_set):
            sig, msg = base[i % len(base)]
            mode = i % 4
            if mode == 1:
                b = bytearray(sig)
                b[i % len(sig)] ^= 1 << (i % 8)
                sig = bytes(b)
            elif mode == 2:
                sig = sig[:-1]
            elif mode == 3:
                msg = msg + b"?"
            sigs.append(sig)
            msgs.append(msg)
        got = mldsa.verify_mldsa_batch(
            table, sigs, msgs, np.zeros(per_set, np.int32))
        want = [mldsa.py_verify(pub, s, m) for s, m in zip(sigs, msgs)]
        mism = [i for i in range(per_set) if bool(got[i]) != want[i]]
        if mism:
            print(f"mldsa-kat PARITY FAIL: {pset} at {mism[:8]}",
                  file=sys.stderr)
            bad += len(mism)
        else:
            print(f"mldsa-kat: {pset} engine/oracle parity on "
                  f"{per_set} randomized verifies "
                  f"({sum(want)} accept / {per_set - sum(want)} reject)")
    return bad


def main() -> int:
    bad = kat_sweep() + parity_selftest()
    if bad:
        print(f"mldsa-kat: {bad} mismatches", file=sys.stderr)
        return 1
    print("mldsa-kat OK: four-surface KAT sweep + engine/oracle "
          "parity selftest green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
