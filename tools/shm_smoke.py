#!/usr/bin/env python3
"""shm-smoke: the shared-memory transport's local CI gate.

Boots one worker per available serve chain (python always; native when
the runtime builds) with ``transport="shm"``, drives it over the ring
from the Python shm client, and FAILS on:

- the client not actually negotiating shm (silently measuring the
  socket would defeat the gate),
- missing/zero ``serve.shm.*`` accounting (attaches, frames) or a
  missing ``serve.shm.active`` gauge,
- ANY protocol error (a malformed ring record under a clean drive
  means the transport is corrupting frames),
- a wrong verdict anywhere,
- the socket-fallback contract breaking: a ``transport="socket"``
  worker must ack the attach status-1, KEEP serving the same
  connection over the socket, and count ``serve.shm_fallbacks``.

Stub engines only — no jax import, fits the tier-1 time budget.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cap_tpu import telemetry  # noqa: E402
from cap_tpu.fleet.worker_main import StubKeySet  # noqa: E402
from cap_tpu.serve.shm_client import ShmVerifyClient  # noqa: E402
from cap_tpu.serve.worker import VerifyWorker  # noqa: E402


def fail(msg: str) -> None:
    print(f"shm-smoke FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def drive_chain(chain: str) -> None:
    telemetry.enable()
    telemetry.active().reset()
    w = VerifyWorker(StubKeySet(), serve_native=chain == "native",
                     max_wait_ms=1.0, transport="shm")
    try:
        if w.serve_chain != chain:
            fail(f"requested chain {chain} but worker runs "
                 f"{w.serve_chain}")
        if w.transport != "shm":
            fail(f"[{chain}] worker transport={w.transport}, not shm "
                 "(stale library?)")
        host, port = w.address
        with ShmVerifyClient(host, port) as cl:
            if cl.transport != "shm":
                fail(f"[{chain}] client fell back to the socket: "
                     f"{cl.attach_error}")
            for i in range(20):
                toks = [f"smoke-{chain}-{i}-{j}.ok" for j in range(32)]
                toks.append(f"smoke-{chain}-{i}-reject.bad")
                out = cl.verify_batch(toks)
                for tok, res in zip(toks[:-1], out[:-1]):
                    if res != {"sub": tok}:
                        fail(f"[{chain}] wrong verdict for {tok}: "
                             f"{res!r}")
                if not isinstance(out[-1], Exception):
                    fail(f"[{chain}] reject token accepted")
            if not cl.ping():
                fail(f"[{chain}] ping over the ring failed")
            st = cl.stats()
        gauges = w._obs_gauges()
        if gauges.get("serve.shm.active") != 1.0:
            fail(f"[{chain}] serve.shm.active gauge is "
                 f"{gauges.get('serve.shm.active')}")
        counters = st.get("counters") or {}
        attaches = counters.get("serve.shm.attaches", 0)
        frames = counters.get("serve.shm.frames", 0)
        if attaches < 1:
            fail(f"[{chain}] serve.shm.attaches={attaches}")
        if frames < 20:
            fail(f"[{chain}] serve.shm.frames={frames} (expected the "
                 "drive's frames)")
        proto_errs = (counters.get("worker.protocol_errors", 0)
                      + counters.get("serve.native.protocol_errors", 0))
        if proto_errs:
            fail(f"[{chain}] {proto_errs} protocol errors under a "
                 "clean shm drive")
        stale = counters.get("serve.shm.stale_gen", 0)
        if stale:
            fail(f"[{chain}] serve.shm.stale_gen={stale} on a fresh "
                 "region")
        print(f"shm-smoke [{chain}]: attach ok, {frames} ring frames, "
              f"0 protocol errors")
    finally:
        w.close(deadline_s=10)


def drive_fallback() -> None:
    telemetry.enable()
    telemetry.active().reset()
    w = VerifyWorker(StubKeySet(), max_wait_ms=1.0,
                     transport="socket")
    try:
        host, port = w.address
        with ShmVerifyClient(host, port) as cl:
            if cl.transport != "socket":
                fail("socket-transport worker accepted an attach")
            if cl.attach_error is None:
                fail("refusal carried no error string")
            out = cl.verify_batch(["fallback.ok"])
            if out[0] != {"sub": "fallback.ok"}:
                fail("socket fallback connection does not serve")
        rec = telemetry.active()
        if not rec.counters().get("serve.shm_fallbacks"):
            fail("serve.shm_fallbacks not counted on refusal")
        print("shm-smoke [fallback]: status-1 ack, socket kept "
              "serving, serve.shm_fallbacks counted")
    finally:
        w.close(deadline_s=10)


def main() -> None:
    chains = ["python"]
    try:
        from cap_tpu.serve import native_serve

        lib = native_serve.load()
        if getattr(lib, "cap_shm_ok", False):
            chains.append("native")
        else:
            print("shm-smoke: native runtime predates the shm TU — "
                  "python chain only", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - no compiler
        print(f"shm-smoke: native runtime unavailable ({e}) — python "
              "chain only", file=sys.stderr)
    for chain in chains:
        drive_chain(chain)
    drive_fallback()
    print(f"shm-smoke OK: chains={','.join(chains)} + socket fallback")


if __name__ == "__main__":
    main()
