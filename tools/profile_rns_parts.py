#!/usr/bin/env python3
"""Component timings for the RNS REDC on the real chip."""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax
import jax.numpy as jnp

from cap_tpu.tpu import rns

N = 16384
ctx = rns.context(2048, 129)
I = ctx.A.count
print("channels per base:", I)

rngnp = np.random.default_rng(0)
x = jnp.asarray(rngnp.integers(0, 4000, size=(I, N)), jnp.int32)
sig = jnp.asarray(rngnp.integers(0, 4000, size=(I, N)), jnp.int32)


def timeit(label, fn, *args):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(10):
        r = f(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / 10
    print(f"{label:28s} {dt*1e3:8.2f} ms")


def matmuls(sig):
    return rns._split_matmul(ctx.W_AB, sig)


def modfix(x):
    m = ctx.dA["m"][:, None]
    return rns._mod_fix(x, m, ctx.dA["m_f"][:, None],
                        ctx.dA["inv_f"][:, None])


def extend(sig):
    return rns._extend(sig, ctx.dA, ctx.dB, ctx.W_AB, ctx.Amod_B, -1e-4)


def alpha_only(sig):
    return jnp.floor(jnp.sum(sig.astype(jnp.float32)
                             * ctx.dA["inv_f"][:, None], axis=0) - 1e-4)


def redc(xA, xB):
    consts = (ctx.dA, ctx.dB, ctx.W_AB, ctx.W_BA, ctx.Amod_B,
              ctx.Bmod_A, ctx.invA_B)
    sig_c = jnp.ones((I, N), jnp.int32)
    n_B = jnp.full((ctx.B.count, N), 3001, jnp.int32)
    return rns._redc(xA, xB, sig_c, n_B, consts)


timeit("4x split matmuls", matmuls, sig)
timeit("mod_fix (one)", modfix, x)
timeit("alpha sum", alpha_only, sig)
timeit("extend (A->B)", extend, sig)
xB = jnp.asarray(rngnp.integers(0, 4000, size=(ctx.B.count, N)), jnp.int32)
timeit("full redc", redc, x, xB)
