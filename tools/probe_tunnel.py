#!/usr/bin/env python3
"""Characterize the host<->device tunnel: bandwidth vs chunk size, whether
concurrent transfer streams aggregate, and H2D/compute overlap.

Methodology per docs/PERF.md: block_until_ready does not actually block on
this stack; only value materialization (np.asarray) truly syncs.  So every
measurement ends with a materializing read of a tiny reduction of the
transferred data.
"""

import os
import sys
import time
import threading

import numpy as np
import jax
import jax.numpy as jnp


def _sync(dev_arrays):
    """Materialize a scalar that depends on every array (true sync)."""
    tot = 0.0
    for d in dev_arrays:
        tot += float(jnp.sum(d[:: max(1, d.size // 4)].astype(jnp.float32)))
    return tot


@jax.jit
def _touch(x):
    return jnp.sum(x.astype(jnp.float32))


def bw_single(size_mb: float, reps: int = 3) -> float:
    """One-stream H2D bandwidth, MB/s (best of reps)."""
    n = int(size_mb * (1 << 20))
    best = 0.0
    for r in range(reps):
        x = np.random.randint(0, 255, n, dtype=np.uint8)
        t0 = time.perf_counter()
        d = jax.device_put(x)
        s = _touch(d)
        float(s)
        dt = time.perf_counter() - t0
        best = max(best, size_mb / dt)
    return best


def bw_threads(n_threads: int, size_mb_each: float, reps: int = 3) -> float:
    """Aggregate H2D bandwidth with n_threads concurrent device_put calls."""
    n = int(size_mb_each * (1 << 20))
    xs = [np.random.randint(0, 255, n, dtype=np.uint8)
          for _ in range(n_threads)]
    best = 0.0
    for r in range(reps):
        out = [None] * n_threads

        def work(i):
            out[i] = jax.device_put(xs[i])

        ts = [threading.Thread(target=work, args=(i,))
              for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for d in out:
            float(_touch(d))
        dt = time.perf_counter() - t0
        best = max(best, n_threads * size_mb_each / dt)
    return best


def overlap_test(size_mb: float = 4.0):
    """Does H2D overlap with device compute?

    Time (a) compute alone, (b) transfer alone, (c) dispatch compute then
    transfer concurrently.  If (c) ~= max(a, b), overlap works.
    """
    n = int(size_mb * (1 << 20))

    @jax.jit
    def burn(a):
        # ~enough matmuls to take O(100ms+)
        for _ in range(8):
            a = jnp.tanh(a @ a)
        return jnp.sum(a)

    a = jax.device_put(np.random.rand(2048, 2048).astype(np.float32))
    float(burn(a))  # compile

    t0 = time.perf_counter()
    float(burn(a))
    t_compute = time.perf_counter() - t0

    x = np.random.randint(0, 255, n, dtype=np.uint8)
    t0 = time.perf_counter()
    d = jax.device_put(x)
    float(_touch(d))
    t_xfer = time.perf_counter() - t0

    x2 = np.random.randint(0, 255, n, dtype=np.uint8)
    t0 = time.perf_counter()
    fut = burn(a)          # dispatched async
    d2 = jax.device_put(x2)
    float(_touch(d2))
    float(fut)
    t_both = time.perf_counter() - t0
    return t_compute, t_xfer, t_both


def main():
    print(f"devices: {jax.devices()}", flush=True)
    # warm up dispatch path
    float(_touch(jax.device_put(np.zeros(1024, np.uint8))))

    print("-- chunk size sweep (single stream, best-of-3, MB/s) --",
          flush=True)
    for mb in (0.25, 1, 4, 16, 64):
        r = bw_single(mb)
        print(f"  {mb:>6} MB: {r:8.1f} MB/s", flush=True)

    print("-- concurrent streams (4 MB each, best-of-3, aggregate MB/s) --",
          flush=True)
    for nt in (1, 2, 4, 8, 16):
        r = bw_threads(nt, 4.0)
        print(f"  {nt:>2} threads: {r:8.1f} MB/s", flush=True)

    print("-- dtype check (16MB, u8 vs i32 same byte count) --", flush=True)
    n = 16 << 20
    x8 = np.random.randint(0, 255, n, dtype=np.uint8)
    x32 = np.random.randint(0, 2**31 - 1, n // 4, dtype=np.int32)
    for name, x in (("u8", x8), ("i32", x32)):
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            d = jax.device_put(x)
            float(_touch(d))
            best = max(best, 16.0 / (time.perf_counter() - t0))
        print(f"  {name}: {best:8.1f} MB/s", flush=True)

    print("-- overlap test --", flush=True)
    tc, tx, tb = overlap_test(8.0)
    print(f"  compute={tc:.3f}s xfer={tx:.3f}s both={tb:.3f}s "
          f"(sum={tc+tx:.3f}, overlap {'YES' if tb < 0.75*(tc+tx) else 'NO'})",
          flush=True)


if __name__ == "__main__":
    main()
