#!/usr/bin/env python3
"""bench-trend: the BENCH_r*.json regression sentinel.

The perf trajectory lives in committed round records (BENCH_r01.json …,
MULTICHIP_r01.json …) that, until now, only a human reading
docs/PERF.md would compare. This tool parses the whole series and
FAILS (exit 1) when the LATEST round regresses any tracked metric by
more than ``THRESHOLD`` (10%) against the BEST of the up-to-3
preceding rounds — best-of-3 because single rounds ride tunnel
weather (BENCH_r03's headline dropped 38% on wire stalls alone and
recovered; the best-of window absorbs that without absorbing a real
regression).

Tracked metrics (all higher-is-better; latency/wire fields are
published weather, not tracked — see docs/PERF.md on stalls):

- ``value``              — the honest end-to-end headline rate
- ``value_peak``         — best pipelined interval
- ``resident_mixed_vps`` — engine speed with records device-resident
                           (weather-independent: THE regression signal)
- ``serve_fleet``        — bench_serve fleet-mode value, when present
- ``resident_mldsa44_vps`` — post-quantum engine rate (ML-DSA-44
                           resident lanes), tracked from round 11 on

A second series, ``BENCH_SERVE_r*.json`` (the serve-chain records
tools/bench_stages.py + bench_serve.py produce, committed from round
12 on), tracks the native serve chain:

- ``serve_native_vps``          — native-chain single-worker serve
                                  rate, device stubbed (higher better)
- ``stage_python_us_per_token`` — Python-side serial cost per served
                                  token with the native chain on
                                  (LOWER is better — inverted check)
- ``zipf_cached_vps``           — end-to-end fleet rate on the Zipf
                                  90%-repeat mix with the verdict
                                  cache ON (higher better; round 14+)

MULTICHIP records are checked structurally: the latest round must
still report ``ok`` (rc 0) on the same-or-larger device count.

Also verifies the latest BENCH record is SELF-DESCRIBING per this
round's contract: carries ``decisions`` (reason-keyed counters) and
``slo`` (objective evaluation) once the record is from round ≥ 6 —
earlier rounds predate the fields and are exempt.

``--selftest`` exercises the detector on synthetic series (including
an injected 15% regression over the real series) and exits nonzero if
the detector misbehaves — wired before the real check in
``make bench-trend`` so a broken sentinel cannot silently pass CI.
"""

from __future__ import annotations

import argparse
import copy
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THRESHOLD = 0.10          # >10% below best-of-window = regression
WINDOW = 3                # best of the last 3 preceding rounds
TRACKED = ("value", "value_peak", "resident_mixed_vps", "serve_fleet",
           "resident_mldsa44_vps",
           # second PQ family (r17): the resident SLH-DSA hash-forest
           # rate — higher is better, tracked like the ML-DSA number
           "resident_slhdsa128s_vps")
# serve-chain series (BENCH_SERVE_r*.json): metric → higher_is_better
SERVE_TRACKED = {"serve_native_vps": True,
                 "stage_python_us_per_token": False,
                 # full-observability native chain (native telemetry
                 # plane on): us/token, lower is better — the r13
                 # "obs on at wire speed" contract must not erode
                 "serve_native_obs_us_per_token": False,
                 # verdict-cache tier: end-to-end Zipf(0.9-repeat)
                 # fleet rate with the cache ON (higher is better) —
                 # the r14 memory-speed-repeats contract
                 "zipf_cached_vps": True,
                 # OIDC verify-AND-validate, device-stubbed, native
                 # claims-rule engine on (higher is better) — the r15
                 # wire-speed-validation contract (bench_stages.py
                 # claims row; chip-host bench.py emits the real-
                 # ladder analog under "oidc")
                 "oidc_native_vps": True,
                 # front-door tier: end-to-end multi-pool fleet rate
                 # on the Zipf 90%-repeat mix with digest-affinity
                 # routing (higher is better) — the r16 fleet-wide
                 # verdict-tier contract (bench_serve multi-pool mode)
                 "fleet_affinity_vps": True,
                 # zero-copy ingest: closed-loop serve rate over the
                 # shared-memory ring transport, device stubbed
                 # (higher is better) — the r18 recv+copy-elimination
                 # contract (bench_stages transport column /
                 # bench_serve CAP_SERVE_TRANSPORTS mode)
                 "shm_vps": True,
                 # tenant fairness: the WELL-BEHAVED tenant's
                 # verified/s under a flooding tenant with the fair
                 # plane on (DRR + admission; higher is better) — the
                 # r20 enforcement contract (bench_serve
                 # CAP_SERVE_FLOOD mode)
                 "fairness_vps": True,
                 # router tier at wire speed: the native relay
                 # gateway's closed-loop rate on the pinned Zipf
                 # multi-pool workload (higher is better) — the r21
                 # zero-copy front-door contract (bench_serve
                 # CAP_FRONTDOOR_CHAINS gateway arms)
                 "fleet_native_vps": True,
                 # pipeline occupancy: fraction of bench wall time the
                 # engine spent inside dispatch intervals on the
                 # pinned serve workload (higher is better) — the r22
                 # queueing-delay-plane contract; a drop means the
                 # pipeline grew bubbles even if throughput held
                 "device_occupancy": True}
# Rounds from this PR onward must embed decision/SLO fields.
SELF_DESCRIBING_FROM_ROUND = 6


def load_series(repo: str = REPO) -> List[Tuple[int, Dict[str, Any]]]:
    """[(round, parsed-metric-dict)] for every BENCH_rNN.json, in
    round order. Records whose bench errored (no parsed dict) carry
    an empty dict — they participate as gaps, not as zeros."""
    out = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        out.append((int(m.group(1)),
                    parsed if isinstance(parsed, dict) else {}))
    return sorted(out)


def load_multichip(repo: str = REPO) -> List[Tuple[int, Dict[str, Any]]]:
    out = []
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r*.json"))):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                out.append((int(m.group(1)), json.load(f)))
        except (OSError, ValueError):
            continue
    return sorted(out)


def load_serve_series(repo: str = REPO) -> List[Tuple[int,
                                                      Dict[str, Any]]]:
    """[(round, record)] for every BENCH_SERVE_rNN.json, in order."""
    out = []
    for path in sorted(glob.glob(os.path.join(repo,
                                              "BENCH_SERVE_r*.json"))):
        m = re.search(r"BENCH_SERVE_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                out.append((int(m.group(1)), json.load(f)))
        except (OSError, ValueError):
            continue
    return sorted(out)


def check_serve_series(series: List[Tuple[int, Dict[str, Any]]],
                       threshold: float = THRESHOLD,
                       window: int = WINDOW) -> List[str]:
    """Regressions in the serve-chain series; handles the
    lower-is-better metric by inverting the comparison."""
    if len(series) < 2:
        return []
    latest_round, latest = series[-1]
    prior = series[:-1][-window:]
    findings = []
    for metric, higher in SERVE_TRACKED.items():
        vals = [(rnd, d.get(metric)) for rnd, d in prior
                if isinstance(d.get(metric), (int, float))]
        if not vals:
            continue
        best_round, best = (max(vals, key=lambda t: t[1]) if higher
                            else min(vals, key=lambda t: t[1]))
        now = latest.get(metric)
        if not isinstance(now, (int, float)):
            findings.append(
                f"SERVE r{latest_round:02d}: tracked metric {metric!r} "
                f"disappeared (best r{best_round:02d}={best:.3f})")
            continue
        drop = (1.0 - now / best) if higher else (now / best - 1.0)
        if drop > threshold:
            findings.append(
                f"SERVE r{latest_round:02d}: {metric} = {now:.3f}, "
                f"{drop * 100:.1f}% worse than best-of-last-"
                f"{len(prior)} (r{best_round:02d}={best:.3f})")
    return findings


def metric_value(parsed: Dict[str, Any], metric: str) -> Optional[float]:
    if metric == "serve_fleet":
        v = parsed.get("serve_fleet_value")
    else:
        v = parsed.get(metric)
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    return None


def check_series(series: List[Tuple[int, Dict[str, Any]]],
                 threshold: float = THRESHOLD,
                 window: int = WINDOW) -> List[str]:
    """Regression findings for the LATEST round vs best-of-window.

    A metric absent from the latest record is only a finding when a
    previous round DID report it (a tracked number silently vanishing
    is itself a regression signal); metrics absent everywhere are
    skipped (older series predate them).
    """
    if len(series) < 2:
        return []
    latest_round, latest = series[-1]
    prior = series[:-1][-window:]
    findings = []
    for metric in TRACKED:
        best, best_round = None, None
        for rnd, parsed in prior:
            v = metric_value(parsed, metric)
            if v is not None and (best is None or v > best):
                best, best_round = v, rnd
        if best is None:
            continue
        now = metric_value(latest, metric)
        if now is None:
            findings.append(
                f"r{latest_round:02d}: tracked metric {metric!r} "
                f"disappeared (best r{best_round:02d}={best:.1f})")
            continue
        drop = 1.0 - now / best
        if drop > threshold:
            weather = ""
            if latest.get("stall_intervals"):
                weather = (f"  [weather: {latest['stall_intervals']} "
                           f"stall intervals, "
                           f"{latest.get('stall_seconds', 0)}s — "
                           "check resident_mixed_vps before blaming "
                           "the engine]")
            findings.append(
                f"r{latest_round:02d}: {metric} = {now:.1f}, "
                f"-{drop * 100:.1f}% vs best-of-last-{len(prior)} "
                f"(r{best_round:02d}={best:.1f}, threshold "
                f"{threshold * 100:.0f}%){weather}")
    return findings


def check_multichip(series: List[Tuple[int, Dict[str, Any]]]
                    ) -> List[str]:
    if not series:
        return []
    rnd, latest = series[-1]
    findings = []
    if latest.get("skipped"):
        return []
    if not latest.get("ok", False) or latest.get("rc", 1) != 0:
        findings.append(f"MULTICHIP r{rnd:02d}: not ok "
                        f"(rc={latest.get('rc')})")
    prev_devs = [d.get("n_devices", 0) for _, d in series[:-1]
                 if not d.get("skipped")]
    if prev_devs and latest.get("n_devices", 0) < max(prev_devs):
        findings.append(
            f"MULTICHIP r{rnd:02d}: device count shrank "
            f"({latest.get('n_devices')} < {max(prev_devs)})")
    return findings


def check_self_describing(series: List[Tuple[int, Dict[str, Any]]]
                          ) -> List[str]:
    """Round ≥ SELF_DESCRIBING_FROM_ROUND records must carry the
    decision/SLO embedding (bench.py writes them from this PR on)."""
    if not series:
        return []
    rnd, latest = series[-1]
    if rnd < SELF_DESCRIBING_FROM_ROUND or not latest:
        return []
    findings = []
    for field in ("decisions", "slo"):
        if field not in latest:
            findings.append(
                f"r{rnd:02d}: BENCH record is not self-describing — "
                f"missing {field!r} (bench.py must embed it)")
    return findings


# ---------------------------------------------------------------------------
# selftest: the detector must detect
# ---------------------------------------------------------------------------


def _synthetic(values: List[Optional[float]]
               ) -> List[Tuple[int, Dict[str, Any]]]:
    return [(i + 1, {} if v is None else {"value": v})
            for i, v in enumerate(values)]


def selftest(repo: str = REPO) -> List[str]:
    problems = []

    # 1. flat series: clean
    if check_series(_synthetic([100.0, 101.0, 99.0, 100.0])):
        problems.append("flat synthetic series flagged")
    # 2. 16% drop vs best-of-3: must flag
    if not check_series(_synthetic([100.0, 95.0, 98.0, 84.0])):
        problems.append("16% synthetic regression NOT flagged")
    # 3. drop >10% vs best but window slid past the peak: best-of-3
    #    looks at the last 3 only, so an old peak cannot page forever
    if check_series(_synthetic([200.0, 100.0, 100.0, 100.0, 95.0])):
        problems.append("stale-peak comparison leaked past the window")
    # 4. metric disappearing: must flag
    gone = _synthetic([100.0, 100.0])
    gone.append((3, {"value_peak": 5.0}))
    if not any("disappeared" in f for f in check_series(gone)):
        problems.append("vanished tracked metric NOT flagged")
    # 4b. serve series: higher-is-better drop and lower-is-better RISE
    #     must both flag; a clean pair must not
    sv = [(11, {"serve_native_vps": 1e6,
                "stage_python_us_per_token": 0.8,
                "serve_native_obs_us_per_token": 0.9}),
          (12, {"serve_native_vps": 1e6,
                "stage_python_us_per_token": 0.8,
                "serve_native_obs_us_per_token": 0.9})]
    if check_serve_series(sv):
        problems.append("flat serve series flagged")
    if not check_serve_series(
            [sv[0], (12, {"serve_native_vps": 0.8e6,
                          "stage_python_us_per_token": 0.8,
                          "serve_native_obs_us_per_token": 0.9})]):
        problems.append("serve vps regression NOT flagged")
    if not check_serve_series(
            [sv[0], (12, {"serve_native_vps": 1e6,
                          "stage_python_us_per_token": 1.0,
                          "serve_native_obs_us_per_token": 0.9})]):
        problems.append("us/token REGRESSION (rise) NOT flagged")
    if not check_serve_series(
            [sv[0], (12, {"serve_native_vps": 1e6,
                          "stage_python_us_per_token": 0.8,
                          "serve_native_obs_us_per_token": 1.2})]):
        problems.append("obs us/token REGRESSION (rise) NOT flagged")
    # a round that predates the obs metric must not flag when the
    # NEXT round introduces it (absent-everywhere-before is not a
    # disappearance)
    if check_serve_series(
            [(11, {"serve_native_vps": 1e6,
                   "stage_python_us_per_token": 0.8}),
             sv[1]]):
        problems.append("introducing the obs metric flagged")
    # 4c. verdict-cache Zipf headline: a drop must flag, introducing
    #     the metric must not, and it vanishing must flag
    zc = [(13, {"serve_native_vps": 1e6}),
          (14, {"serve_native_vps": 1e6, "zipf_cached_vps": 5e5})]
    if check_serve_series(zc):
        problems.append("introducing zipf_cached_vps flagged")
    if not check_serve_series(
            [zc[1], (15, {"serve_native_vps": 1e6,
                          "zipf_cached_vps": 3e5})]):
        problems.append("zipf_cached_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [zc[1], (15, {"serve_native_vps": 1e6})])):
        problems.append("vanished zipf_cached_vps NOT flagged")
    # 4d. oidc_native_vps (r15): introducing must not flag; a drop
    #     and a disappearance must
    oc = [(14, {"serve_native_vps": 1e6}),
          (15, {"serve_native_vps": 1e6, "oidc_native_vps": 3e5})]
    if check_serve_series(oc):
        problems.append("introducing oidc_native_vps flagged")
    if not check_serve_series(
            [oc[1], (16, {"serve_native_vps": 1e6,
                          "oidc_native_vps": 2e5})]):
        problems.append("oidc_native_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [oc[1], (16, {"serve_native_vps": 1e6})])):
        problems.append("vanished oidc_native_vps NOT flagged")
    # 4e. fleet_affinity_vps (r16): introducing must not flag; a drop
    #     and a disappearance must
    fa = [(15, {"serve_native_vps": 1e6}),
          (16, {"serve_native_vps": 1e6, "fleet_affinity_vps": 4e4})]
    if check_serve_series(fa):
        problems.append("introducing fleet_affinity_vps flagged")
    if not check_serve_series(
            [fa[1], (17, {"serve_native_vps": 1e6,
                          "fleet_affinity_vps": 2e4})]):
        problems.append("fleet_affinity_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [fa[1], (17, {"serve_native_vps": 1e6})])):
        problems.append("vanished fleet_affinity_vps NOT flagged")
    # 4e2. shm_vps (r18): introducing must not flag; a drop and a
    #      disappearance must
    sm = [(17, {"serve_native_vps": 1e6}),
          (18, {"serve_native_vps": 1e6, "shm_vps": 2e6})]
    if check_serve_series(sm):
        problems.append("introducing shm_vps flagged")
    if not check_serve_series(
            [sm[1], (19, {"serve_native_vps": 1e6,
                          "shm_vps": 1e6})]):
        problems.append("shm_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [sm[1], (19, {"serve_native_vps": 1e6})])):
        problems.append("vanished shm_vps NOT flagged")
    # 4e3. fairness_vps (r20): introducing must not flag; a drop and
    #      a disappearance must
    fv = [(19, {"serve_native_vps": 1e6}),
          (20, {"serve_native_vps": 1e6, "fairness_vps": 5e4})]
    if check_serve_series(fv):
        problems.append("introducing fairness_vps flagged")
    if not check_serve_series(
            [fv[1], (21, {"serve_native_vps": 1e6,
                          "fairness_vps": 3e4})]):
        problems.append("fairness_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [fv[1], (21, {"serve_native_vps": 1e6})])):
        problems.append("vanished fairness_vps NOT flagged")
    # 4e4. fleet_native_vps (r21): introducing must not flag; a drop
    #      and a disappearance must
    fn = [(20, {"serve_native_vps": 1e6}),
          (21, {"serve_native_vps": 1e6, "fleet_native_vps": 2e5})]
    if check_serve_series(fn):
        problems.append("introducing fleet_native_vps flagged")
    if not check_serve_series(
            [fn[1], (22, {"serve_native_vps": 1e6,
                          "fleet_native_vps": 1e5})]):
        problems.append("fleet_native_vps regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [fn[1], (22, {"serve_native_vps": 1e6})])):
        problems.append("vanished fleet_native_vps NOT flagged")
    # 4e5. device_occupancy (r22): introducing must not flag; a drop
    #      (pipeline grew bubbles) and a disappearance must
    oc2 = [(21, {"serve_native_vps": 1e6}),
           (22, {"serve_native_vps": 1e6, "device_occupancy": 0.4})]
    if check_serve_series(oc2):
        problems.append("introducing device_occupancy flagged")
    if not check_serve_series(
            [oc2[1], (23, {"serve_native_vps": 1e6,
                           "device_occupancy": 0.3})]):
        problems.append("device_occupancy regression NOT flagged")
    if not any("disappeared" in f for f in check_serve_series(
            [oc2[1], (23, {"serve_native_vps": 1e6})])):
        problems.append("vanished device_occupancy NOT flagged")
    # 4f. resident_slhdsa128s_vps (r17, BENCH series): introducing
    #     must not flag; a drop and a disappearance must
    def _pq(vals):
        return [(i + 16, ({} if v is None else
                          {"value": 100.0,
                           "resident_slhdsa128s_vps": v})
                 if v != "absent" else {"value": 100.0})
                for i, v in enumerate(vals)]

    if check_series(_pq(["absent", 5000.0])):
        problems.append("introducing resident_slhdsa128s_vps flagged")
    if not check_series(_pq(["absent", 5000.0, 3000.0])):
        problems.append(
            "resident_slhdsa128s_vps regression NOT flagged")
    if not any("disappeared" in f
               for f in check_series(_pq(["absent", 5000.0,
                                          "absent"]))):
        problems.append("vanished resident_slhdsa128s_vps NOT flagged")
    # 5. the REAL series with a 15% regression injected into a copy of
    #    the newest record: must flag (the acceptance-bar case)
    real = load_series(repo)
    if len(real) >= 2:
        injected = copy.deepcopy(real)
        rnd, parsed = injected[-1]
        bumped = dict(parsed)
        for metric in TRACKED:
            v = metric_value(parsed, metric)
            if v is not None:
                bumped[metric if metric != "serve_fleet"
                       else "serve_fleet_value"] = v * 0.85
        injected[-1] = (rnd, bumped)
        if not check_series(injected):
            problems.append(
                "15% regression injected into the real series NOT "
                "flagged")
        # 6. and the real series itself must evaluate (clean or not,
        #    deterministically — no exceptions)
        check_series(real)
    else:
        problems.append("real BENCH series too short to self-test")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_trend",
        description="flag >10% regressions in the BENCH_r*.json series")
    ap.add_argument("--selftest", action="store_true",
                    help="exercise the detector on synthetic series")
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--threshold", type=float, default=THRESHOLD)
    args = ap.parse_args(argv)

    if args.selftest:
        problems = selftest(args.repo)
        if problems:
            for p in problems:
                print(f"bench-trend SELFTEST FAIL: {p}",
                      file=sys.stderr)
            return 1
        print("bench-trend selftest OK: detector flags synthetic and "
              "injected regressions, passes flat series")
        return 0

    series = load_series(args.repo)
    if not series:
        print("bench-trend: no BENCH_r*.json series found",
              file=sys.stderr)
        return 1
    findings = (check_series(series, threshold=args.threshold)
                + check_multichip(load_multichip(args.repo))
                + check_self_describing(series)
                + check_serve_series(load_serve_series(args.repo),
                                     threshold=args.threshold))
    rounds = ", ".join(f"r{r:02d}" for r, _ in series)
    if findings:
        for f in findings:
            print(f"bench-trend REGRESSION: {f}", file=sys.stderr)
        return 1
    latest_round, latest = series[-1]
    vals = {m: metric_value(latest, m) for m in TRACKED}
    print(f"bench-trend OK: {rounds}; r{latest_round:02d} tracked "
          + " ".join(f"{m}={v:.0f}" for m, v in vals.items()
                     if v is not None)
          + f"; no metric >{args.threshold * 100:.0f}% below "
            f"best-of-last-{WINDOW}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
