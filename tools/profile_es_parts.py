#!/usr/bin/env python3
"""Breakdown of the ES256 RNS core: where do the milliseconds go?

Times, with device-resident operands and slope methodology:
  redc   — one rmul (REDC) chain, length matching the ladder's count
  gather — the per-window table gathers alone
  scalar — the limb-domain scalar work (range checks, inverse, u1/u2)
  full   — the whole _ecdsa_rns_core
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(os.environ.get("N", 32768))
REPS = int(os.environ.get("REPS", 3))

os.environ.setdefault("CAP_TPU_RNS", "1")

from cap_tpu import testing as T
from cap_tpu.tpu import ec as tpuec
from cap_tpu.tpu import ec_rns
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def slope(fn, sync):
    """Seconds per rep via (R reps) - (1 rep).

    Both rep-count variants are compiled AND run once before timing —
    static rep counts are separate XLA programs, and a first execution
    can include lazy work (constant hoisting) beyond compilation.
    """
    sync(fn(1))
    sync(fn(1 + REPS))
    t0 = time.perf_counter()
    sync(fn(1))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(fn(1 + REPS))
    tR = time.perf_counter() - t0
    return (tR - t1) / REPS


def main():
    print(f"backend={jax.default_backend()} N={N}", flush=True)
    c = ec_rns.ctx_for("P-256")
    rng = np.random.default_rng(0)
    ia, ib = c.A.count, c.B.count

    xA = jax.device_put(rng.integers(0, 8000, (ia, 2 * N)).astype(np.int32))
    xB = jax.device_put(rng.integers(0, 8000, (ib, 2 * N)).astype(np.int32))

    n_chain = 32 * 5          # ladder REDC layers (2-acc: 5 per window)

    @partial(jax.jit, static_argnames=("reps",))
    def redc_chain(a, b, reps: int):
        def body(i, v):
            return ec_rns.rmul(c, v, v)

        v = lax.fori_loop(0, reps * n_chain, body, (a, b))
        return v[0]

    t = slope(lambda r: redc_chain(xA, xB, reps=r),
              lambda o: float(jnp.sum(o)))
    print(f"redc chain ({n_chain} rmuls @ [·,{2*N}]): {t*1000:7.1f} ms",
          flush=True)

    # gathers: ONE fused x‖y take per window (the packed window-major
    # table, ECRNSKeyTable.tab), matching the core's shape exactly
    keys = [T.generate_keys("ES256")[1] for _ in range(8)]
    table = tpuec.ECKeyTable("P-256", keys)
    rtab = table.rns()
    idx = jax.device_put(
        rng.integers(0, rtab.tab.shape[0], 2 * N).astype(np.int32))

    @partial(jax.jit, static_argnames=("reps",))
    def gathers(idx, reps: int):
        def body(i, acc):
            # consume EVERY gathered row: a row-0 slice would let
            # XLA's slice-of-gather rewrite shrink the timed gather
            # to one index and report fiction
            g = jnp.take(rtab.tab, idx + i, axis=0)
            return acc + jnp.sum(g, axis=0)

        return lax.fori_loop(0, reps * 32, body,
                             jnp.zeros((rtab.tab.shape[1],), jnp.int32))

    t = slope(lambda r: gathers(idx, reps=r), lambda o: float(jnp.sum(o)))
    print(f"gathers (32 windows × 1 fused take @ [{2*N}]): {t*1000:7.1f} ms",
          flush=True)

    # scalar limb part: mimic steps 1-2 + final checks cost via bignum
    from cap_tpu.tpu import bignum as B

    cp = table.curve
    consts = cp.device_consts()
    n_, npp, nr2, none_, nm2 = consts[4:9]
    k = cp.k
    r = jax.device_put(
        rng.integers(1, 1 << 16, (k, N), np.int64).astype(np.uint32))

    @partial(jax.jit, static_argnames=("reps",))
    def scalar_part(r, reps: int):
        sh = r.shape
        nb = jnp.broadcast_to(n_, sh)
        nppb = jnp.broadcast_to(npp, sh)
        nr2b = jnp.broadcast_to(nr2, sh)

        def body(i, acc):
            s_m = B.mont_mul(acc, nr2b, nb, nppb)
            w_m = B.batch_mont_inverse(s_m, n_, npp, nr2, none_, nm2,
                                       nbits=cp.nbits)
            return B.mont_mul(acc, w_m, nb, nppb)

        return lax.fori_loop(0, reps, body, r)

    t = slope(lambda r_: scalar_part(r, reps=r_),
              lambda o: float(jnp.sum(o)))
    print(f"scalar (inverse tree + mont_muls @ [{k},{N}]): {t*1000:7.1f} ms",
          flush=True)


if __name__ == "__main__":
    main()
