#!/usr/bin/env python3
"""claims-parity: the native claims-rule engine differential gate.

Sweeps the generated adversarial corpus (tools/gen_claims_corpus.py,
~1k cases) through THREE rule paths and fails on any divergence:

1. **dict path** — ``Provider.verify_id_token_batch`` over parsed
   claims dicts: the pure-Python reference semantics;
2. **raw path, Python rules** — ``raw=True`` with
   ``CAP_OIDC_NATIVE=0``: registered-claims tape subset + the Python
   rule loop (the pre-r15 behavior);
3. **raw path, native rules** — ``raw=True`` with the engine on: one
   ``cap_claims_validate_batch`` call, per-token fallback corners.

Parity contract (the ISSUE acceptance): bit-identical VERDICTS
(accept/reject, and accepted bytes are the signed payload) and
identical exception CLASSES — which pins the obs reason classes too
(``obs.decision.classify`` is class-driven). The sweep is crypto-free:
signatures ride the stub seam (tokens ending in the ``sigok`` b64
marker verify; the payload IS the middle segment), so the gate runs
everywhere, jax-free, in seconds.

Also asserts COVERAGE: every native status (every rule's reject code
and the fallback) must be observed at least once — a corpus edit that
silently stops exercising a rule fails the gate.

Exit 0 green; 1 on divergence, missing native engine, or lost
coverage. ``make claims-parity`` wires this into ``make check``.
"""

from __future__ import annotations

import base64
import collections
import json
import os
import sys
import time
from typing import Any, Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gen_claims_corpus import (  # noqa: E402
    CLIENT,
    FIXED_NOW,
    ISSUER,
    NONCE,
    POLICIES,
    SEED,
    build_corpus,
    corpus_sha256,
)


def _b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


SIG_OK = _b64(b"sigok")
_HDRS = {alg: _b64(json.dumps({"alg": alg},
                              separators=(",", ":")).encode())
         for alg in ("ES256", "RS384")}


class DifferentialStubKeySet:
    """The crypto-free signature seam: ``<hdr>.<payload-b64>.<SIG_OK>``
    verifies; the payload is the decoded middle segment. Rejection and
    malformed-payload classes mirror what the real TPU raw/dict paths
    produce, so provider-level wrapping is identical in both modes."""

    def _one(self, token: str, want_raw: bool) -> Any:
        from cap_tpu.errors import (
            InvalidSignatureError,
            MalformedTokenError,
        )

        parts = token.split(".")
        if len(parts) != 3 or parts[2] != SIG_OK:
            return InvalidSignatureError(
                "no known key successfully validated the token "
                "signature")
        try:
            pad = "=" * (-len(parts[1]) % 4)
            payload = base64.urlsafe_b64decode(parts[1] + pad)
        except Exception:  # noqa: BLE001
            return MalformedTokenError("invalid base64url segment")
        try:
            claims = json.loads(payload)
        except (ValueError, UnicodeDecodeError) as e:
            return MalformedTokenError(
                f"payload is not valid JSON: {e}")
        if not isinstance(claims, dict):
            return MalformedTokenError("payload is not a JSON object")
        return payload if want_raw else claims

    def verify_batch(self, tokens):
        return [self._one(t, False) for t in tokens]

    def verify_batch_raw(self, tokens):
        return [self._one(t, True) for t in tokens]


def token_for(case: Dict[str, Any]) -> str:
    hdr = _HDRS[case["alg"]]
    return f"{hdr}.{_b64(case['payload'].encode('utf-8'))}.{SIG_OK}"


def make_rig(policy: Dict[str, Any]):
    """(provider, request) for one corpus policy, clock pinned to
    FIXED_NOW, stub signature seam injected."""
    from cap_tpu.oidc import Config, Provider, Request

    cfg = Config(issuer=ISSUER, client_id=CLIENT,
                 supported_signing_algs=["ES256"],
                 audiences=(policy["audiences"]
                            if policy["name"] != "other-aud" else None),
                 now_func=lambda: FIXED_NOW)
    provider = Provider(cfg, keyset=DifferentialStubKeySet(),
                        discovery_doc={"issuer": ISSUER})
    request = Request(
        3600.0, "http://127.0.0.1:1/cb", nonce=NONCE,
        audiences=(policy["audiences"]
                   if policy["name"] == "other-aud" else None),
        max_age=policy["max_age"])
    return provider, request


def _tag(result: Any) -> str:
    if isinstance(result, Exception):
        return type(result).__name__
    return "accept"


def run_sweep(cases: List[Dict[str, Any]] | None = None
              ) -> Tuple[List[str], Dict[str, int]]:
    """(problems, native-status counts) over the whole corpus."""
    from cap_tpu.obs import decision
    from cap_tpu.oidc import claims_native

    if cases is None:
        cases = build_corpus(SEED)
    problems: List[str] = []
    status_counts: collections.Counter = collections.Counter()

    by_policy: Dict[int, List[Dict[str, Any]]] = \
        collections.defaultdict(list)
    for case in cases:
        by_policy[case["policy"]].append(case)

    prev = os.environ.get("CAP_OIDC_NATIVE")
    try:
        for pol_idx, group in sorted(by_policy.items()):
            provider, request = make_rig(POLICIES[pol_idx])
            toks = [token_for(c) for c in group]

            dict_out = provider.verify_id_token_batch(toks, request)
            os.environ["CAP_OIDC_NATIVE"] = "0"
            py_out = provider.verify_id_token_batch(toks, request,
                                                    raw=True)
            os.environ["CAP_OIDC_NATIVE"] = "1"
            nat_out = provider.verify_id_token_batch(toks, request,
                                                     raw=True)

            # native status coverage (direct engine drive over the
            # signature-accepted subset, same inputs the wired path
            # used)
            import numpy as np

            acc = [i for i, r in enumerate(
                provider.keyset.verify_batch_raw(toks))
                if not isinstance(r, Exception)]
            if acc:
                alg_ok = np.asarray(
                    [1 if group[i]["alg"] == "ES256" else 0
                     for i in acc], np.uint8)
                st = claims_native.validate_payloads(
                    [group[i]["payload"].encode("utf-8") for i in acc],
                    alg_ok, FIXED_NOW,
                    provider._policy_blob(request))
                if st is None:
                    problems.append(
                        f"policy {pol_idx}: native engine refused the "
                        "batch")
                else:
                    for s in st:
                        status_counts[
                            claims_native.STATUS_INDEX[int(s)]] += 1

            for case, d, py, na in zip(group, dict_out, py_out,
                                       nat_out):
                td, tp, tn = _tag(d), _tag(py), _tag(na)
                if not (td == tp == tn):
                    problems.append(
                        f"{case['name']}: dict={td} raw-python={tp} "
                        f"raw-native={tn}")
                    continue
                if td == "accept":
                    if not (isinstance(py, bytes)
                            and isinstance(na, bytes) and py == na
                            and json.loads(py) == d):
                        problems.append(
                            f"{case['name']}: accepted bytes/claims "
                            "diverge")
                elif decision.classify(d) != decision.classify(na):
                    problems.append(
                        f"{case['name']}: obs reason class diverges "
                        f"({decision.classify(d)} vs "
                        f"{decision.classify(na)})")
    finally:
        if prev is None:
            os.environ.pop("CAP_OIDC_NATIVE", None)
        else:
            os.environ["CAP_OIDC_NATIVE"] = prev
    return problems, dict(status_counts)


def main() -> int:
    from cap_tpu.oidc import claims_native

    cases = build_corpus(SEED)
    print(f"claims-parity: {len(cases)} corpus cases "
          f"(seed {SEED}, sha256 {corpus_sha256(cases)[:16]}…)")
    if not claims_native.enabled():
        print("claims-parity FAIL: native claims engine unavailable "
              "(libcapruntime.so missing cap_claims_* or layout "
              "drift)", file=sys.stderr)
        return 1
    t0 = time.perf_counter()
    problems, status_counts = run_sweep(cases)
    dt = time.perf_counter() - t0

    missing = [name for name in claims_native.STATUS_INDEX
               if status_counts.get(name, 0) == 0]
    for name in missing:
        problems.append(
            f"coverage: native status {name!r} never observed — the "
            "corpus stopped exercising its rule")

    print("native status coverage: "
          + " ".join(f"{k}={v}"
                     for k, v in sorted(status_counts.items())))
    if problems:
        for p in problems[:40]:
            print(f"claims-parity DIVERGENCE: {p}", file=sys.stderr)
        if len(problems) > 40:
            print(f"... and {len(problems) - 40} more",
                  file=sys.stderr)
        return 1
    print(f"claims-parity OK: {len(cases)} cases × 3 engines, "
          f"verdicts and reason classes bit-identical ({dt:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
