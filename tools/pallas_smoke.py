#!/usr/bin/env python3
"""make pallas-smoke: the kernel-path liveness + bit-equality gate.

The r11 native-build lesson, applied to kernels: a Pallas kernel that
silently stops compiling (API drift, missing Mosaic support, a stale
jax) would leave the fused paths dead while every test that exercises
only the jnp fallback stays green. This gate COMPILES both house PQ
kernels in interpret mode on the CPU backend and bit-checks them
against their references; a missing/broken Pallas stack is a loud
skip with a counter, never a silent pass of nothing.

Checks (exit nonzero on any mismatch):
1. ``pallas_ntt``: fused forward/inverse kernels vs the int64
   ``ntt_ref``/``intt_ref`` host references AND the stagewise jnp
   graph, on random lanes + edge lanes (0, q-1).
2. ``pallas_keccak``: the f1600 kernel vs the numpy uint64 reference
   AND the jnp interleaved path; SHAKE absorb/squeeze driver vs
   stdlib hashlib on mixed-length messages.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception as e:  # noqa: BLE001 - env without pallas
        # Graceful skip WITH a visible counter line — the driver can
        # grep it; a missing stack is a known state, not a green lie.
        print(f"pallas-smoke SKIP: pallas unavailable "
              f"({type(e).__name__}: {e}); kernels_skipped=2")
        return 0

    import hashlib

    import jax.numpy as jnp

    from cap_tpu.tpu import ntt as NTT
    from cap_tpu.tpu import pallas_keccak as KK
    from cap_tpu.tpu import pallas_ntt as PN

    rng = np.random.default_rng(0xC0FFEE)
    bad = 0

    # --- NTT kernel -----------------------------------------------------
    a = rng.integers(0, NTT.Q, (5, 3, 256), dtype=np.int64)
    a[0, 0, :4] = [0, NTT.Q - 1, 1, NTT.Q - 2]
    x = jnp.asarray(a.astype(np.uint32))
    fwd = np.asarray(PN.ntt_fused(x, interpret=True))
    if not (fwd.astype(np.int64) == NTT.ntt_ref(a)).all():
        print("pallas-smoke FAIL: ntt_fused != ntt_ref",
              file=sys.stderr)
        bad += 1
    if not (fwd == np.asarray(NTT.ntt(x))).all():
        print("pallas-smoke FAIL: ntt_fused != jnp ntt",
              file=sys.stderr)
        bad += 1
    inv = np.asarray(PN.intt_fused(jnp.asarray(fwd), interpret=True))
    if not (inv.astype(np.int64) == a).all():
        print("pallas-smoke FAIL: intt_fused roundtrip",
              file=sys.stderr)
        bad += 1
    print("pallas-smoke: NTT kernel compiled + bit-equal "
          f"({a.size // 256} lanes, interpret mode)")

    # --- Keccak kernel --------------------------------------------------
    st = rng.integers(0, 2 ** 64, (9, 25), dtype=np.uint64)
    il = jnp.asarray(KK.interleave(st))
    want = KK.f1600_ref(st)
    got_k = KK.deinterleave(np.asarray(KK.f1600_pallas(
        il, interpret=True)))
    if not (got_k == want).all():
        print("pallas-smoke FAIL: f1600 kernel != numpy ref",
              file=sys.stderr)
        bad += 1
    got_j = KK.deinterleave(np.asarray(KK.f1600(il)))
    if not (got_j == want).all():
        print("pallas-smoke FAIL: jnp f1600 != numpy ref",
              file=sys.stderr)
        bad += 1
    msgs = [rng.integers(0, 256, int(rng.integers(0, 400)),
                         dtype=np.uint8).tobytes() for _ in range(7)]
    blocks, nblk = KK.pack_blocks(msgs, KK.RATE_SHAKE256)
    by = np.asarray(KK.lanes_to_bytes(KK.squeeze_lanes(
        KK.absorb(jnp.asarray(blocks), jnp.asarray(nblk)),
        KK.RATE_SHAKE256, 2))).astype(np.uint8)
    for i, msg in enumerate(msgs):
        if by[i].tobytes() != hashlib.shake_256(msg).digest(272):
            print(f"pallas-smoke FAIL: SHAKE driver msg {i}",
                  file=sys.stderr)
            bad += 1
    print("pallas-smoke: Keccak kernel compiled + bit-equal "
          "(f1600 + SHAKE driver vs hashlib)")

    if bad:
        print(f"pallas-smoke: {bad} failures", file=sys.stderr)
        return 1
    print("pallas-smoke OK: both PQ kernels live and bit-exact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
