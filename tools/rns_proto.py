#!/usr/bin/env python3
"""Numpy prototype of RNS-Montgomery modexp (validation only).

Validates the exact scheme the TPU engine uses before it's written in
JAX: two RNS bases of ~13-bit primes, Bajard fast base extension with
floor-approximated alpha (error {-1,0}) on the A->B direction and an
offset-0.5 exact alpha on the B->A direction, f32-exact 7-bit-split
matmuls, Barrett guess-then-fix channel reduction, and a shifted
comparison window at the end instead of any RNS->binary conversion.
"""

import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sieve_primes(lo, hi):
    n = hi
    mask = np.ones(n, bool)
    mask[:2] = False
    for i in range(2, int(n ** 0.5) + 1):
        if mask[i]:
            mask[i * i:: i] = False
    return [p for p in range(lo, hi) if mask[p]]


def pick_base(primes, min_bits, skip=0):
    out = []
    bits = 0.0
    i = skip
    while bits < min_bits:
        p = primes[i]
        out.append(p)
        bits += np.log2(p)
        i += 1
    return out, i


class Base:
    def __init__(self, ms):
        self.m = np.array(ms, np.int64)
        self.I = len(ms)
        self.prod = 1
        for p in ms:
            self.prod *= int(p)
        # (M/m_i)^{-1} mod m_i  and  M/m_i mod (other base channels)
        self.Mi = [self.prod // int(p) for p in ms]
        self.inv_Mi = np.array([pow(M % int(p), -1, int(p))
                                for M, p in zip(self.Mi, ms)], np.int64)
        self.inv_f = (1.0 / self.m).astype(np.float32)


def ext_matrix(src: Base, dst: Base):
    """W[j, i] = (src.M / src.m[i]) mod dst.m[j]."""
    W = np.empty((dst.I, src.I), np.int64)
    for i, Mi in enumerate(src.Mi):
        W[:, i] = np.array([Mi % int(m) for m in dst.m], np.int64)
    return W


def split7(x):
    return x >> 7, x & 127


def exact_split_matmul(W, sig):
    """Simulate the 4x bf16 matmul with f32 accumulation; assert exact."""
    Wh, Wl = split7(W)
    sh, sl = split7(sig)
    outs = []
    for a in (Wh, Wl):
        for b in (sh, sl):
            af = a.astype(np.float32)
            bf = b.astype(np.float32)
            c = af @ bf                      # f32 accumulation
            ci = a @ b                       # exact int reference
            assert np.all(c == ci.astype(np.float32)), "f32 inexact!"
            assert ci.max() < (1 << 24)
            outs.append(ci)
    hh, hl, lh, ll = outs
    return hh, hl + lh, ll                    # weights 2^14, 2^7, 2^0


def mod_fix(x, m):
    """Barrett guess-then-fix: exact x mod m for x < 2^31, m < 2^13."""
    xf = x.astype(np.float32)
    q = np.floor(xf * (1.0 / m.astype(np.float32))).astype(np.int64)
    r = x - q * m
    r = np.where(r < 0, r + m, r)
    r = np.where(r < 0, r + m, r)
    r = np.where(r >= m, r - m, r)
    r = np.where(r >= m, r - m, r)
    assert np.all((0 <= r) & (r < m)), (x.max(), m)
    return r


def extend(sig, src: Base, dst: Base, W, A_mod_dst, offset):
    """Base extension with approximated alpha. sig: [I_src, N]."""
    hh, mid, ll = exact_split_matmul(W, sig)
    # alpha estimate
    s = (sig.astype(np.float32) * src.inv_f[:, None]).sum(0)
    alpha = np.floor(s + offset).astype(np.int64)   # offset<0: A->B floor
    m = dst.m[:, None]
    rhh = mod_fix(hh, m)
    rmid = mod_fix(mid, m)
    rll = mod_fix(ll, m)
    c14 = (1 << 14) % m
    c7 = (1 << 7) % m
    comb = rhh * c14 + rmid * c7 + rll            # < 3*2^26
    comb = mod_fix(comb, m)
    # subtract alpha * (src.prod mod dst.m): keep positive
    corr = (alpha[None, :] % m) * (A_mod_dst[:, None] % m)  # < 2^26
    corr = mod_fix(corr, m)
    out = mod_fix(comb - corr + m, m)
    return out, alpha


class RNSMont:
    def __init__(self, n_int, nbits):
        primes = sieve_primes(1 << 12, 1 << 13)
        random.Random(7).shuffle(primes)
        msA, used = pick_base(primes, nbits + 8)
        msB, _ = pick_base(primes, nbits + 8, skip=used)
        self.A = Base(msA)
        self.B = Base(msB)
        self.n = n_int
        self.W_AB = ext_matrix(self.A, self.B)
        self.W_BA = ext_matrix(self.B, self.A)
        self.Amod_B = np.array([self.A.prod % int(m) for m in self.B.m],
                               np.int64)
        self.Bmod_A = np.array([self.B.prod % int(m) for m in self.A.m],
                               np.int64)
        self.n_A = np.array([n_int % int(m) for m in self.A.m], np.int64)
        self.n_B = np.array([n_int % int(m) for m in self.B.m], np.int64)
        # per-channel merged constant: (-n^{-1} mod A)_i * inv_Mi mod a_i
        npr = [(-pow(n_int, -1, int(m))) % int(m) for m in self.A.m]
        self.sig_c = (np.array(npr, np.int64) * self.A.inv_Mi) % self.A.m
        self.invA_B = np.array(
            [pow(self.A.prod % int(m), -1, int(m)) for m in self.B.m],
            np.int64)
        self.A2_n = (self.A.prod * self.A.prod) % n_int

    def to_rns(self, xs):
        xA = np.array([[x % int(m) for x in xs] for m in self.A.m],
                      np.int64)
        xB = np.array([[x % int(m) for x in xs] for m in self.B.m],
                      np.int64)
        return xA, xB

    def redc(self, xA, xB):
        """(xA,xB) -> t = x*A^{-1} mod n (+ c*n), both bases."""
        mA = self.A.m[:, None]
        mB = self.B.m[:, None]
        sig = mod_fix(xA * self.sig_c[:, None], mA)
        qB, _ = extend(sig, self.A, self.B, self.W_AB, self.Amod_B,
                       offset=-1e-4)
        # t_B = (x + q*n) * A^{-1} mod b
        t = mod_fix(xB + mod_fix(qB * self.n_B[:, None], mB), mB)
        t = mod_fix(t * self.invA_B[:, None], mB)
        # back-extend t to A (exact alpha: offset 0.5)
        sig2 = mod_fix(t * self.B.inv_Mi[:, None], mB)
        tA, _ = extend(sig2, self.B, self.A, self.W_BA, self.Bmod_A,
                       offset=0.5 - 1e-4)
        return tA, t

    def mul_redc(self, aA, aB, bA, bB):
        pA = mod_fix(aA * bA, self.A.m[:, None])
        pB = mod_fix(aB * bB, self.B.m[:, None])
        return self.redc(pA, pB)

    def modexp_65537(self, xs):
        sA, sB = self.to_rns(xs)
        a2A, a2B = self.to_rns([self.A2_n] * len(xs))
        xA, xB = self.mul_redc(sA, sB, a2A, a2B)      # enter domain
        x0A, x0B = xA, xB
        for _ in range(16):
            xA, xB = self.mul_redc(xA, xB, xA, xB)
        xA, xB = self.mul_redc(xA, xB, x0A, x0B)
        oneA, oneB = self.to_rns([1] * len(xs))
        return self.mul_redc(xA, xB, oneA, oneB)      # exit; < c*n

    def matches(self, xA, xB, expected_ints):
        """x == expected + c*n for c in 0..3, checked in base B."""
        ok = np.zeros(len(expected_ints), bool)
        for c in range(4):
            eB = np.array([[(e + c * self.n) % int(m) for e in expected_ints]
                           for m in self.B.m], np.int64)
            ok |= np.all(xB == eB, axis=0)
        return ok


def main():
    rng = random.Random(1)
    for bits in (2048, 1024):
        p = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
        q = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
        n = p * q
        eng = RNSMont(n, bits)
        xs = [rng.randrange(n) for _ in range(64)] + [0, 1, n - 1, n // 2]
        xA, xB = eng.modexp_65537(xs)
        want = [pow(x, 65537, n) for x in xs]
        ok = eng.matches(xA, xB, want)
        assert ok.all(), np.nonzero(~ok)
        # negative control
        bad = eng.matches(xA, xB, [w ^ 1 for w in want])
        assert not bad.any()
        print(f"RNS modexp {bits}-bit OK  "
              f"(I_A={eng.A.I}, I_B={eng.B.I})")


if __name__ == "__main__":
    main()
