"""Microbenchmark the native claims pipeline (phase 1 + phase 2).

The serve path's binding constraint on a one-core host is host-side
work; after raw passthrough removed serialization, what remains on the
dict path is `_capclaims.parse_batch` (docs/PERF.md "Next levers").
This times that call on bench-shaped payloads, next to json.loads.

Usage: python tools/profile_claims.py [n_tokens]
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.runtime import native_binding as nb


def make_payloads(n: int):
    payloads = []
    for i in range(n):
        claims = {
            "iss": "https://issuer.example.com/",
            "sub": f"user-{i:08d}",
            "aud": ["api://default", "app-1"],
            "exp": 1785500000 + i,
            "nbf": 1785400000,
            "iat": 1785400000 + i,
            "jti": f"jti-{i:016x}",
            "name": "Ada Lovelace",
            "email_verified": True,
            "scope": "openid profile email",
        }
        payloads.append(json.dumps(claims, separators=(",", ":")).encode())
    return payloads


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    payloads = make_payloads(n)
    scratch = bytearray()
    offs = np.empty(n, np.int64)
    lens = np.empty(n, np.int64)
    for i, p in enumerate(payloads):
        offs[i] = len(scratch)
        lens[i] = len(p)
        scratch += p
    scratch = bytes(scratch)

    ext = nb._claims_ext
    if ext is None:
        print("extension not built", file=sys.stderr)
        return

    # Warm + correctness spot-check against json.loads.
    out, n_bad = ext.parse_batch(scratch, offs, lens)
    ref = [json.loads(p) for p in payloads[:64]]
    assert n_bad == 0 and out[:64] == ref, \
        "native parse diverges from json.loads"

    for name, fn in [
        ("parse_batch (phase1+2)",
         lambda: ext.parse_batch(scratch, offs, lens)),
        ("validate_batch (phase1)",
         lambda: ext.validate_batch(scratch, offs, lens)),
        ("json.loads loop",
         lambda: [json.loads(p) for p in payloads]),
    ]:
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        print(f"{name:26s} {best * 1e3:8.1f} ms   "
              f"{n / best / 1e3:8.0f} k tok/s")


if __name__ == "__main__":
    main()
