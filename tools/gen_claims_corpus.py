#!/usr/bin/env python3
"""The claims-rule differential corpus: ~1k adversarial id_token
payloads covering the full registered-claims rule cross-product.

Like ``gen_go_golden.py``, generation is SEEDED and byte-stable: the
same seed always produces the same corpus, and the sha256 of its
canonical JSON form is pinned in ``tests/test_claims_native.py`` — a
generator edit that changes coverage must re-pin, visibly. Unlike the
golden signatures, the EXPECTED verdicts are not stored: the corpus
is differential, the pure-Python dict path is the reference, and the
raw-path Python rules and the native engine (claims_validate.cpp)
must both match it verdict-for-verdict and class-for-class.

Axes (systematic single-axis sweeps + seeded random combinations):

- iss: match / mismatch / missing / non-string scalars / null
- exp: valid / past / boundary / missing / string / bool / bigint /
  float / container
- nbf, iat: absent / past / inside-leeway / beyond-leeway / boundary /
  bool / string
- nonce: match / mismatch / missing / non-string / null / escaped
- aud: string / list / multi / empty / missing / null / non-string
  entries (the go-jose-parity reject) / nested containers / object
- azp: absent / match / mismatch / non-string / null (× aud shapes —
  the 3-rule interplay)
- parse corners: escaped keys, duplicate keys, unicode, deep nesting,
  long extra claims, whitespace, surrogate escapes
- alg header: allowed / disallowed (the header-segment-cache arm)
- policies: default, configured-audiences, multi-audience config,
  max_age-requested (the auth_time rare-flag arm)

CLI: ``python tools/gen_claims_corpus.py`` prints case count and the
corpus sha256 (what the test pins).
"""

from __future__ import annotations

import hashlib
import json
import random
from typing import Any, Dict, List, Tuple

SEED = 20260805
FIXED_NOW = 1_750_000_000.0
ISSUER = "https://idp.example/"
CLIENT = "client-1"
NONCE = "n-123456"
LEEWAY = 60.0

# Policies the corpus sweeps (index referenced per case). Fields map
# onto Config/Request construction in the sweep driver.
POLICIES: List[Dict[str, Any]] = [
    {"name": "default", "audiences": [], "max_age": None},
    {"name": "conf-aud", "audiences": [CLIENT, "svc-2"], "max_age": None},
    {"name": "other-aud", "audiences": ["svc-3"], "max_age": None},
    {"name": "max-age", "audiences": [], "max_age": 600.0},
]

# alg header arms: (tag, alg) — "ES256" is the allowed one; the sweep
# driver builds the compact header segment from the alg.
ALG_ARMS = [("ok", "ES256"), ("bad", "RS384")]


def _dump(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False)


def _base_claims(**over: Any) -> Dict[str, Any]:
    c: Dict[str, Any] = {
        "iss": ISSUER, "sub": "alice", "aud": [CLIENT],
        "exp": FIXED_NOW + 3600, "iat": FIXED_NOW - 10, "nonce": NONCE,
    }
    for k, v in over.items():
        if v is ...:
            c.pop(k, None)
        else:
            c[k] = v
    return c


def _axis_variants() -> Dict[str, List[Tuple[str, Any]]]:
    """Per-claim variant menus: (tag, value); ``...`` removes the
    claim. Values chosen to hit every rule status AND every
    conservative-fallback corner on both engines."""
    far = FIXED_NOW + 3600
    return {
        "iss": [
            ("good", ISSUER), ("evil", "https://evil.example/"),
            ("missing", ...), ("int", 123), ("null", None),
            ("empty", ""), ("float", 1.5), ("bool", True),
            ("prefix", ISSUER[:-1]), ("list", [ISSUER]),
            ("obj", {"v": ISSUER}), ("big", 10 ** 30),
        ],
        "exp": [
            ("ok", far), ("past", FIXED_NOW - 3600),
            ("now", FIXED_NOW), ("now+1", FIXED_NOW + 1),
            ("now-1", FIXED_NOW - 1), ("missing", ...),
            ("str", "1999999999"), ("bool", True), ("null", None),
            ("float", FIXED_NOW + 0.5), ("neg", -1),
            ("big", 10 ** 30), ("list", [far]), ("obj", {"t": far}),
            ("hugefloat", 1.5e308),
        ],
        "nbf": [
            ("absent", ...), ("past", FIXED_NOW - 100),
            ("in-leeway", FIXED_NOW + LEEWAY - 1),
            ("boundary", FIXED_NOW + LEEWAY),
            ("beyond", FIXED_NOW + LEEWAY + 1),
            ("far", FIXED_NOW + 9e6), ("str", "soon"), ("bool", False),
            ("null", None), ("float", FIXED_NOW + 59.5),
        ],
        "iat": [
            ("past", FIXED_NOW - 10), ("absent", ...),
            ("in-leeway", FIXED_NOW + LEEWAY - 1),
            ("boundary", FIXED_NOW + LEEWAY),
            ("beyond", FIXED_NOW + LEEWAY + 1), ("str", "now"),
            ("bool", True), ("null", None), ("big", 10 ** 25),
        ],
        "nonce": [
            ("good", NONCE), ("wrong", "n-zzz"), ("missing", ...),
            ("int", 5), ("null", None), ("empty", ""),
            ("case", NONCE.upper()), ("prefix", NONCE + "x"),
            ("list", [NONCE]), ("obj", {"n": NONCE}),
        ],
        "aud": [
            ("client-list", [CLIENT]), ("client-str", CLIENT),
            ("other-str", "svc-2"), ("other-list", ["svc-2"]),
            ("multi-ok", [CLIENT, "svc-2"]),
            ("multi-other", ["svc-2", "svc-3"]),
            ("multi-dup", [CLIENT, CLIENT]),
            ("nonstring-int", [CLIENT, 42]), ("nonstring-only", [42]),
            ("nonstring-null", [CLIENT, None]),
            ("nonstring-bool", [True]),
            ("nested", [CLIENT, ["svc-2"]]),
            ("nested-obj", [{"aud": CLIENT}]),
            ("empty", []), ("missing", ...), ("null", None),
            ("obj", {"weird": 1}), ("int", 7),
            ("conf-aud", ["svc-2", CLIENT]), ("conf-only", ["svc-3"]),
            ("long", [f"svc-{i}" for i in range(40)] + [CLIENT]),
        ],
        "azp": [
            ("absent", ...), ("client", CLIENT), ("evil", "intruder"),
            ("int", 7), ("null", None), ("bool", False), ("empty", ""),
            ("list", [CLIENT]), ("obj", {"azp": CLIENT}),
        ],
        "auth_time": [
            ("absent", ...), ("fresh", FIXED_NOW - 30),
            ("stale", FIXED_NOW - 9000), ("str", "then"),
            ("bool", True), ("null", None),
        ],
    }


def _text_corners() -> List[Tuple[str, str]]:
    """Raw-TEXT payload cases (escapes, duplicates, malformed shapes)
    that dict construction cannot express."""
    good = _dump(_base_claims())
    far = FIXED_NOW + 3600
    return [
        ("esc-key-iss", good.replace('"iss"', '"i\\u0073s"')),
        ("esc-key-exp", good.replace('"exp"', '"e\\u0078p"')),
        ("esc-key-extra",
         good[:-1] + ',"e\\u0078tra":1}'),
        ("esc-val-iss", good.replace(
            _dump(ISSUER), '"https:\\/\\/idp.example\\/"')),
        ("esc-val-nonce", good.replace(
            _dump(NONCE), '"n-\\u0031\\u0032\\u0033456"')),
        ("esc-val-aud", good.replace(
            _dump([CLIENT]), '["client-\\u0031"]')),
        ("dup-exp-live-then-dead",
         good[:-1] + f',"exp":{FIXED_NOW - 100}}}'),
        ("dup-exp-dead-then-live",
         _dump(_base_claims(exp=FIXED_NOW - 100))[:-1]
         + f',"exp":{far}}}'),
        ("dup-iss", good[:-1] + ',"iss":"https://evil.example/"}'),
        ("dup-nonce", good[:-1] + ',"nonce":"n-zzz"}'),
        ("ws-heavy", good.replace(",", " ,\n\t").replace(":", " : ")),
        ("unicode-extra", _dump(_base_claims(name="Zoë 😀",
                                             org="日本語"))),
        ("nested-extra", _dump(_base_claims(
            ctx={"a": {"b": {"c": [1, 2, {"d": None}]}}}))),
        ("deep-nesting",
         '{"iss":%s,"aud":["%s"],"exp":%d,"nonce":"%s","deep":%s}'
         % (_dump(ISSUER), CLIENT, int(FIXED_NOW + 3600), NONCE,
            "[" * 70 + "1" + "]" * 70)),
        ("surrogate-esc", good[:-1] + ',"x":"\\ud800"}'),
        ("nan-literal", good[:-1] + ',"x":NaN}'),
        ("infinity-literal", good[:-1] + ',"x":Infinity}'),
        ("bignum-extra", good[:-1] + ',"x":' + "9" * 400 + "}"),
        ("trailing-garbage", good + "x"),
        ("not-object", _dump([1, 2, 3])),
        ("not-json", "this is not json"),
        ("empty-payload", ""),
        ("empty-object", "{}"),
        ("sub-object", _dump(_base_claims(sub={"id": "alice"}))),
        ("auth-time-obj", _dump(_base_claims(auth_time={"t": 1}))),
        ("float-exp-sci", good.replace(
            _dump(FIXED_NOW + 3600), "1.7500036e9")),
    ]


def build_corpus(seed: int = SEED) -> List[Dict[str, Any]]:
    """[{name, policy, alg, payload}] — deterministic for a seed."""
    rng = random.Random(seed)
    axes = _axis_variants()
    cases: List[Dict[str, Any]] = []

    def add(name: str, payload: str, policy: int = 0,
            alg: str = "ES256") -> None:
        cases.append({"name": name, "policy": policy, "alg": alg,
                      "payload": payload})

    # 1. single-axis sweeps: every variant of every claim, other
    #    claims held good, across every policy
    for pol_idx in range(len(POLICIES)):
        for claim, variants in axes.items():
            for tag, value in variants:
                payload = _dump(_base_claims(**{claim: value}))
                add(f"p{pol_idx}-{claim}-{tag}", payload, pol_idx)

    # 2. alg arm: allowed vs disallowed header over good + a few bads
    for tag, alg in ALG_ARMS:
        add(f"alg-{tag}-good", _dump(_base_claims()), 0, alg)
        add(f"alg-{tag}-expired",
            _dump(_base_claims(exp=FIXED_NOW - 5)), 0, alg)
        add(f"alg-{tag}-wrongiss",
            _dump(_base_claims(iss="https://evil.example/")), 0, alg)

    # 3. raw-text corners across two policies
    for pol_idx in (0, 1):
        for tag, text in _text_corners():
            add(f"p{pol_idx}-text-{tag}", text, pol_idx)

    # 4. seeded random cross-product combos (aud × azp × times ×
    #    policy × alg) until ~1k total
    claim_names = list(axes.keys())
    while len(cases) < 1050:
        over = {}
        for claim in claim_names:
            # bias towards good values so combos explore rule ORDER
            # (first-failure attribution), not just all-bad payloads
            if rng.random() < 0.55:
                continue
            tag, value = rng.choice(axes[claim])
            over[claim] = value
        extra = rng.random()
        base = _base_claims(**over)
        if extra < 0.2:
            base["scope"] = "openid email profile"
            base["jti"] = f"t-{rng.randrange(1 << 30):08x}"
        elif extra < 0.3:
            base["ctx"] = {"k": [rng.randrange(100) for _ in range(5)]}
        payload = _dump(base)
        pol_idx = rng.randrange(len(POLICIES))
        alg = "ES256" if rng.random() < 0.8 else "RS384"
        add(f"combo-{len(cases):04d}", payload, pol_idx, alg)
    return cases


def corpus_sha256(cases: List[Dict[str, Any]]) -> str:
    blob = json.dumps(cases, separators=(",", ":"),
                      ensure_ascii=False, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def main() -> None:
    cases = build_corpus()
    print(f"cases: {len(cases)}")
    print(f"sha256: {corpus_sha256(cases)}")


if __name__ == "__main__":
    main()
