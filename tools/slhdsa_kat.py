#!/usr/bin/env python3
"""make slhdsa-kat: the SLH-DSA known-answer + parity gate.

Two checks, exit nonzero on any mismatch (the mldsa-kat pattern):

1. **KAT sweep** — every pinned vector in tests/data/slhdsa_kat.json
   through all four verify surfaces (CPU oracle KeySet, TPU batch
   native + object paths, serve worker, fleet router); every verdict
   must equal the pinned one on every surface.
2. **oracle/engine parity** — ≥1k randomized batched verifies per
   parameter set (valid + mutated signatures over a base-signature
   pool), device hash-forest engine vs the pure-hashlib oracle,
   bit-exact. CAP_SLHDSA_KAT_N overrides the per-set count.

Dependency-free (no ``cryptography``), stub-free (real engine).
Heavier than mldsa-kat — SLH-DSA verify is ~2-6k hashes/token — so
the parity sweep batches large and reuses a small signing pool.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KAT_PATH = os.path.join(REPO, "tests", "data", "slhdsa_kat.json")


def kat_sweep() -> int:
    from cap_tpu.fleet import FleetClient
    from cap_tpu.jwt.jwk import parse_jwks
    from cap_tpu.jwt.keyset import StaticKeySet
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.serve.client import VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    with open(KAT_PATH) as f:
        kat = json.load(f)
    jwks = parse_jwks(kat["keys"])
    tokens = [v["token"] for v in kat["vectors"]]
    wants = [v["verdict"] == "accept" for v in kat["vectors"]]

    out = {}
    out["oracle"] = StaticKeySet([j.key for j in jwks]).verify_batch(
        tokens)
    ks = TPUBatchKeySet(jwks)
    out["tpu"] = ks.verify_batch(tokens)
    out["tpu_objects"] = ks._verify_batch_objects(tokens)
    w = VerifyWorker(TPUBatchKeySet(jwks), target_batch=16,
                     max_wait_ms=5.0)
    try:
        host, port = w.address
        with VerifyClient(host, port, timeout=600.0) as c:
            out["serve"] = c.verify_batch(tokens)
        out["router"] = FleetClient([(host, port)],
                                    rr_seed=0).verify_batch(tokens)
    finally:
        w.close()

    bad = 0
    for i, (v, want) in enumerate(zip(kat["vectors"], wants)):
        for surf, res in out.items():
            got = not isinstance(res[i], Exception)
            if got != want:
                print(f"slhdsa-kat FAIL: {v['name']} on {surf}: "
                      f"{'accept' if got else 'reject'} != pinned "
                      f"{v['verdict']}", file=sys.stderr)
                bad += 1
    print(f"slhdsa-kat: {len(tokens)} vectors x "
          f"{len(out)} surfaces swept")
    return bad


def _mutate(sig: bytes, msg: bytes, i: int, p):
    mode = i % 8
    if mode in (0, 1, 2):                  # 3/8 valid
        return sig, msg
    if mode == 3:                          # R flip
        b = bytearray(sig)
        b[i % p.n] ^= 1 << (i % 8)
        return bytes(b), msg
    if mode == 4:                          # FORS region corruption
        b = bytearray(sig)
        b[p.n + (i * 131) % (p.k * (1 + p.a) * p.n)] ^= 0x20
        return bytes(b), msg
    if mode == 5:                          # wrong length
        return (sig[:-1] if i % 2 else sig + b"\x00"), msg
    if mode == 6:                          # hypertree corruption
        b = bytearray(sig)
        b[-(1 + (i * 53) % 1024)] ^= 0xFF
        return bytes(b), msg
    return sig, msg + b"!"                 # tampered message


def parity_selftest() -> int:
    from cap_tpu.tpu import slhdsa

    per_set = int(os.environ.get("CAP_SLHDSA_KAT_N", "1024"))
    batch = 256
    bad = 0
    for pset in sorted(slhdsa.PARAMS):
        p = slhdsa.PARAMS[pset]
        privs, pubs = [], []
        for s in (70, 71):
            pr, pu = slhdsa.keygen(pset, bytes([s]) * 32)
            privs.append(pr)
            pubs.append(pu)
        table = slhdsa.SLHDSAKeyTable(pset, pubs)
        base = []
        for i in range(4):
            msg = f"kat-{pset}-{i}".encode()
            base.append((privs[i % 2].sign(msg), msg, i % 2))
        n_acc = n_done = 0
        for lo in range(0, per_set, batch):
            m = min(batch, per_set - lo)
            sigs, msgs, rows = [], [], []
            for i in range(lo, lo + m):
                sig, msg, row = base[i % len(base)]
                sig, msg = _mutate(sig, msg, i, p)
                sigs.append(sig)
                msgs.append(msg)
                rows.append(row)
            got = slhdsa.verify_slhdsa_batch(
                table, sigs, msgs, np.asarray(rows, np.int32))
            want = [slhdsa.py_verify(pubs[rows[i]], sigs[i], msgs[i])
                    for i in range(m)]
            mism = [i for i in range(m) if bool(got[i]) != want[i]]
            if mism:
                print(f"slhdsa-kat PARITY FAIL: {pset} at "
                      f"{[lo + i for i in mism[:8]]}", file=sys.stderr)
                bad += len(mism)
            n_acc += sum(want)
            n_done += m
        print(f"slhdsa-kat: {pset} engine/oracle parity on {n_done} "
              f"randomized verifies ({n_acc} accept / "
              f"{n_done - n_acc} reject)")
    return bad


def main() -> int:
    bad = kat_sweep() + parity_selftest()
    if bad:
        print(f"slhdsa-kat: {bad} mismatches", file=sys.stderr)
        return 1
    print("slhdsa-kat OK: four-surface KAT sweep + engine/oracle "
          "parity green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
