#!/usr/bin/env python3
"""capstat: live fleet observability — scrape, merge, render.

Scrapes the HTTP observability surface every fleet worker serves
(``cap_tpu.serve.obs``: ``/snapshot`` mergeable telemetry + live
batcher gauges, ``/flight`` slowest traced request timelines) and
renders the fleet in one screen:

- per-endpoint AND exact fleet-aggregate p50/p95/p99 for every stage
  histogram (verify_batch.total, batcher fill/dispatch/collect,
  per-family ``dispatch.*`` …);
- batcher depth / inflight / fill-ratio and per-family lane +
  padding-waste gauges;
- worker health counters (requests, tokens, protocol errors);
- with ``--client FILE``: the router's client-side view — breaker
  states and transitions (opens/closes), hedges, failovers, respawn
  and fallback counters (write the file with
  ``json.dump(fleet_client.snapshot(), f)``);
- ``--trace ID``: reassemble ONE request's cross-process timeline by
  joining the 16-hex trace id across every scraped flight recorder
  (plus the client snapshot's spans), ordered by wall-clock start;
- decision records: per-surface accept/reject-by-reason rollups
  (``cap_tpu.obs.decision``) from the merged counters;
- ``--slo`` (rules file via ``--slo-rules``): evaluate SLO burn-rate
  rules (``cap_tpu.obs.slo`` syntax; defaults when no file) against
  the merged fleet counters — **exits 2 on any breach**, so cron
  probes and CI can page on contract burn;
- ``--occupancy``: the pipeline-occupancy view (r22) — device
  occupancy %% overall and per family, flush-reason mix, the
  queueing-stage waterfall against ``serve.request_s``, idle-gap p99,
  per-worker occupancy — ROADMAP #5's denominator;
- ``--postmortem FILE``: render a collected crash postmortem
  (``cap_tpu.obs.postmortem``) — final flight ring, stage quantiles,
  decision counters, queue depth at death.

Usage:
    python tools/capstat.py HOST:OBSPORT [HOST:OBSPORT ...]
    python tools/capstat.py --watch 2 HOST:OBSPORT ...
    python tools/capstat.py --trace 33c8b42c35f4be9b HOST:OBSPORT ...
    python tools/capstat.py --slo HOST:OBSPORT ...
    python tools/capstat.py --slo-rules slo.rules HOST:OBSPORT ...
    python tools/capstat.py --postmortem worker-0.json
    python tools/capstat.py --json HOST:OBSPORT ...

Redaction: everything rendered comes from telemetry recorders, whose
write boundary rejects token-shaped names and scrubs notes — capstat
adds no payload-derived content and never sees tokens at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu import telemetry  # noqa: E402
from cap_tpu.obs import decision as obs_decision  # noqa: E402
from cap_tpu.obs import postmortem as obs_postmortem  # noqa: E402
from cap_tpu.obs import slo as obs_slo  # noqa: E402

# Stage series shown first, in pipeline order (everything else follows
# alphabetically): the client → router → worker → batcher → device
# attribution chain.
STAGE_ORDER = [
    telemetry.SPAN_CLIENT_SUBMIT,
    "router.attempt_s",
    telemetry.SPAN_ROUTER_BACKOFF,
    telemetry.SPAN_ROUTER_FALLBACK,
    telemetry.SPAN_WORKER_DEQUEUE,
    telemetry.SPAN_BATCHER_FILL,
    "batcher.fill_wait_s",
    telemetry.SPAN_BATCHER_FLUSH,
    telemetry.SPAN_BATCHER_DISPATCH,
    telemetry.SPAN_BATCHER_COLLECT,
    "verify_batch.total",
]

# Gauges a healthy scrape must carry (make obs-smoke fails without
# them, and on NaN): the minimal live-fleet dashboard.
REQUIRED_GAUGES = ["batcher.queued_tokens", "batcher.inflight_batches",
                   "worker.pid"]


def scrape(endpoint: str, timeout: float = 5.0) -> Dict[str, Any]:
    """One worker's /snapshot + /flight → {"snapshot", "extra",
    "flight"}; endpoint is "host:port" of its obs server."""
    host, _, port = endpoint.rpartition(":")
    base = f"http://{host}:{int(port)}"
    with urllib.request.urlopen(f"{base}/snapshot",
                                timeout=timeout) as r:
        snap = json.load(r)
    with urllib.request.urlopen(f"{base}/flight", timeout=timeout) as r:
        flight = json.load(r)
    return {"snapshot": snap.get("snapshot") or {},
            "extra": snap.get("extra") or {},
            "flight": flight.get("slowest") or []}


def reassemble_trace(trace_id: str,
                     sources: Sequence[Dict[str, Any]]) -> List[dict]:
    """Join one trace id across span sources into a single timeline.

    Each source is either a scrape() result (its flight entries are
    searched), a client snapshot ({"spans": [...]}), or a bare list of
    span records. Returns spans sorted by wall-clock start."""
    spans: List[dict] = []
    for src in sources:
        if isinstance(src, list):
            cand = src
        elif "flight" in src:
            cand = [s for e in src["flight"]
                    if e.get("trace") == trace_id
                    for s in e.get("spans", [])]
        else:
            cand = src.get("spans", [])
        spans.extend(s for s in cand if s.get("trace") == trace_id)
    # Dedup (a span can appear in several flight entries of one ring).
    seen = set()
    out = []
    for s in sorted(spans, key=lambda s: (s["t0"], s["name"])):
        key = (s["name"], round(s["t0"], 6), round(s["dur"], 9))
        if key not in seen:
            seen.add(key)
            out.append(s)
    return out


def render_trace(trace_id: str, spans: Sequence[dict]) -> str:
    """ASCII timeline of one reassembled cross-process trace."""
    if not spans:
        return f"trace {trace_id}: no spans found"
    t_base = min(s["t0"] for s in spans)
    lines = [f"trace {trace_id}  ({len(spans)} spans)"]
    for s in spans:
        off_ms = (s["t0"] - t_base) * 1e3
        note = f"  [{s['note']}]" if s.get("note") else ""
        lines.append(f"  +{off_ms:9.3f}ms  {s['name']:<18} "
                     f"{s['dur'] * 1e3:9.3f}ms{note}")
    return "\n".join(lines)


# Series that are NOT durations (tokens, ratios, lane counts): render
# raw instead of milliseconds.
_UNITLESS_SUFFIXES = ("_size", "_ratio", ".lanes", ".fill_ratio",
                      "_tokens")


def _series_rows(summary: Dict[str, Dict[str, float]]) -> List[str]:
    names = [n for n in STAGE_ORDER if n in summary]
    names += sorted(n for n in summary if n not in STAGE_ORDER)
    rows = []
    for n in names:
        s = summary[n]
        if n.endswith(_UNITLESS_SUFFIXES):
            fmt = lambda v: f"{v:9.2f}"          # noqa: E731
        else:
            fmt = lambda v: f"{v * 1e3:9.3f}ms"  # noqa: E731
        rows.append(f"  {n:<28} n={int(s['count']):>8}  "
                    f"p50={fmt(s['p50'])}  "
                    f"p95={fmt(s['p95'])}  "
                    f"p99={fmt(s['p99'])}  "
                    f"max={fmt(s['max'])}")
    return rows


def render_fleet(worker_data: Dict[str, Dict[str, Any]],
                 client: Optional[Dict[str, Any]] = None) -> str:
    """One screen: per-endpoint summaries, exact merged aggregate, and
    (when a client snapshot is provided) breakers + routing health."""
    lines: List[str] = []
    snaps = []
    for ep, data in sorted(worker_data.items()):
        snap = data.get("snapshot") or {}
        snaps.append(snap)
        extra = data.get("extra") or {}
        counters = snap.get("counters") or {}
        epoch = extra.get("keyplane.epoch")
        # serve.native.active gauge: 1.0 = native C++ serve chain,
        # 0.0 = pure-Python chain (absent on pre-native workers)
        chain = extra.get("serve.native.active")
        # serve.shm.active gauge: 1.0 = shm attach negotiation live —
        # rendered tr=shm/socket (absent on pre-shm workers)
        tr = extra.get("serve.shm.active")
        ring = extra.get("serve.native.ring_depth")
        # peak queued tokens since the previous scrape (native-side
        # high-water mark — bursts the point-in-time ring= misses)
        hwm = extra.get("serve.native.ring_hwm")
        vc = _vc_cell(counters, extra.get("vcache.size"))
        lines.append(f"worker {ep}  pid={int(extra.get('worker.pid', 0))}"
                     + (f"  chain={'native' if chain else 'python'}"
                        if chain is not None else "")
                     + (f"  tr={'shm' if tr else 'socket'}"
                        if tr is not None else "")
                     + (f"  ring={int(ring)}" if ring is not None else "")
                     + (f"  ring_hwm={int(hwm)}" if hwm is not None
                        else "")
                     + (f"  epoch={int(epoch)}" if epoch is not None
                        else "")
                     + (f"  vc={vc}" if vc is not None else "")
                     + f"  queued={int(extra.get('batcher.queued_tokens', 0))}"
                     f"  inflight={int(extra.get('batcher.inflight_batches', 0))}"
                     f"  requests={counters.get('worker.requests', 0)}"
                     f"  tokens={counters.get('worker.tokens', 0)}"
                     f"  protocol_errors="
                     f"{counters.get('worker.protocol_errors', 0)}")
        lines.extend(_series_rows(telemetry.summarize_snapshot(snap)))
        slowest = data.get("flight") or []
        if slowest:
            worst = slowest[0]
            lines.append(f"  flight: {len(slowest)} traced, slowest "
                         f"{worst['total_s'] * 1e3:.3f}ms "
                         f"trace={worst['trace']}")
    merged = telemetry.merge_snapshots(snaps)
    lines.append("fleet aggregate (exact bucket merge)")
    lines.extend(_series_rows(telemetry.summarize_snapshot(merged)))
    agg_counters = merged.get("counters") or {}
    lines.extend(_decision_rows(agg_counters))
    if agg_counters.get("vcache.lookups"):
        lines.append(
            f"  vcache: hit_rate={_vc_rate(agg_counters)}  "
            f"hits={agg_counters.get('vcache.hits', 0)} "
            f"misses={agg_counters.get('vcache.misses', 0)} "
            f"evictions={agg_counters.get('vcache.evictions', 0)} "
            f"epoch_bumps={agg_counters.get('vcache.epoch_bumps', 0)} "
            f"dedup_fanout="
            f"{agg_counters.get('batcher.dedup_fanout', 0)} "
            f"stale_accepts="
            f"{agg_counters.get('vcache.stale_accepts', 0)}")
    for fam in ("rs", "ps", "es", "ed"):
        waste = agg_counters.get(f"device.{fam}.pad_waste_rows")
        toks = agg_counters.get(f"device.{fam}.tokens")
        if toks:
            lines.append(f"  device.{fam}: tokens={toks} "
                         f"pad_waste_rows={waste or 0}")
    if client is not None:
        csnap = client.get("snapshot") or {}
        c = csnap.get("counters") or {}
        g = csnap.get("gauges") or {}
        lines.append(
            "router (client side)  "
            f"hedges={c.get('fleet.hedges', 0)} "
            f"hedge_wins={c.get('fleet.hedge_wins', 0)} "
            f"failovers={c.get('fleet.failovers', 0)} "
            f"breaker_opens={c.get('fleet.breaker_opens', 0)} "
            f"breaker_closes={c.get('fleet.breaker_closes', 0)} "
            f"fallback_tokens={c.get('fleet.fallback_tokens', 0)} "
            f"respawns={c.get('fleet.respawns', 0)} "
            f"breakers_open_now={int(g.get('fleet.breakers_open', 0))}")
        if client.get("epoch_skew") is not None:
            eps = "  ".join(f"w{k}={v}" for k, v in
                            sorted((client.get("key_epochs")
                                    or {}).items()))
            state = ("CONVERGED" if client["epoch_skew"] == 0
                     else f"SKEW={client['epoch_skew']}")
            lines.append(f"  key epochs: {state}"
                         + (f"  ({eps})" if eps else ""))
        for ep, st in sorted((client.get("breakers") or {}).items()):
            state = ("OPEN" if st.get("open_for_s", 0) > 0 else
                     "closed")
            lines.append(f"  breaker {ep:<21} {state:<6} "
                         f"failures={int(st.get('failures', 0))} "
                         f"open_for_s={st.get('open_for_s', 0.0):.2f}")
        lines.extend(_series_rows(telemetry.summarize_snapshot(csnap)))
    return "\n".join(lines)


def _vc_rate(counters: Dict[str, Any]) -> str:
    """Verdict-cache hit rate over a counter map, as "NN.N%"."""
    lookups = int(counters.get("vcache.lookups", 0) or 0)
    hits = int(counters.get("vcache.hits", 0) or 0)
    return f"{100.0 * hits / lookups:.1f}%" if lookups else "0.0%"


def _vc_cell(counters: Dict[str, Any], size: Any) -> Optional[str]:
    """Per-worker ``vc=hit%/size`` cell (None when the worker has no
    cache tier — pre-cache workers or --vcache off)."""
    if not counters.get("vcache.lookups") and size is None:
        return None
    sz = int(size) if size is not None else 0
    return f"{_vc_rate(counters)}/{sz}"


def _decision_rows(counters: Dict[str, Any]) -> List[str]:
    """Per-surface verdict/reason rollup lines (empty when no decision
    counters were recorded)."""
    rows = []
    for surf, row in sorted(obs_decision.surface_totals(counters).items()):
        reasons = "  ".join(
            f"{k.split('.', 1)[1]}={v}" for k, v in sorted(row.items())
            if k.startswith("reject."))
        rows.append(f"  decisions[{surf}]: accept={row['accept']} "
                    f"reject={row['reject']}"
                    + (f"  ({reasons})" if reasons else ""))
    return rows


def render_frontdoor(snap: Dict[str, Any]) -> str:
    """The front-door router-tier view (``--frontdoor FILE``): per-
    host affinity hit%, spill / re-route counts, load, and the fleet
    epoch CONVERGED/SKEW state across every pool — rotation health for
    the WHOLE fleet in one block. Accepts either the JSON of
    ``FrontDoor.snapshot()`` or a gateway process's full STATS
    document (``NativeFrontDoorServer.stats()`` / worker STATS op —
    detected by its embedded ``frontdoor`` sub-doc); a native-relay
    gateway additionally gets the chain= line (relays, splices,
    seq-reorder hold depth, fallbacks, per-reason slow-path counts).
    (When a front door runs as a worker process, its ``frontdoor.*``
    counters also ride the ordinary scrape, so the ``--watch`` generic
    delta view covers ``frontdoor.native.*`` with no special
    casing.)"""
    if isinstance(snap.get("frontdoor"), dict):
        # gateway STATS doc: routing/pool detail lives in the
        # embedded snapshot; the top-level counters carry the
        # frontdoor.native.* relay slots — overlay them
        inner = dict(snap["frontdoor"])
        inner["chain"] = snap.get("frontdoor_chain", "python")
        inner["counters"] = {**(inner.get("counters") or {}),
                             **(snap.get("counters") or {})}
        snap = inner
    c = snap.get("counters") or {}
    lookups = int(c.get("frontdoor.lookups", 0) or 0)
    hits = int(c.get("frontdoor.affinity_hits", 0) or 0)
    rate = f"{100.0 * hits / lookups:.1f}%" if lookups else "0.0%"
    lines = [
        f"front door  routing={snap.get('routing', '?')}  "
        f"lookups={lookups}  affinity_hit={rate}  "
        f"spills={c.get('frontdoor.spills', 0)}  "
        f"reroutes={c.get('frontdoor.reroutes', 0)}  "
        f"fallback_tokens={c.get('frontdoor.fallback_tokens', 0)}  "
        f"keys_pushes={c.get('frontdoor.keys_pushes', 0)}"
    ]
    nat = {k[len("frontdoor.native."):]: int(v or 0)
           for k, v in c.items() if k.startswith("frontdoor.native.")}
    if nat or snap.get("chain"):
        chain = snap.get("chain") or ("native" if nat else "python")
        lines.append(
            f"  chain={chain}  relays={nat.get('relays', 0)}  "
            f"relay_tokens={nat.get('relay_tokens', 0)}  "
            f"splices={nat.get('splices', 0)}  "
            f"seq_held_max={nat.get('seq_held_max', 0)}  "
            f"upstream_fails={nat.get('upstream_fails', 0)}  "
            f"native_fallbacks="
            f"{c.get('frontdoor.native_fallbacks', 0)}")
        slow = {k[len('slow.'):]: v for k, v in sorted(nat.items())
                if k.startswith("slow.")}
        if slow:
            lines.append("  slow path: " + "  ".join(
                f"{k}={v}" for k, v in slow.items())
                + f"  (frames={nat.get('slow_frames', 0)} "
                  f"tokens={nat.get('slow_tokens', 0)})")
    for pid, p in sorted((snap.get("pools") or {}).items()):
        toks = int(p.get("tokens", 0) or 0)
        p_hits = int(p.get("affinity_hits", 0) or 0)
        p_rate = f"{100.0 * p_hits / toks:.1f}%" if toks else "0.0%"
        lines.append(
            f"  pool {pid}  {'live' if p.get('live') else 'DEAD':<5}"
            f" endpoints={p.get('endpoints', 0)}"
            f"  tokens={toks}  affinity_hit={p_rate}"
            f"  spills_in={p.get('spills_in', 0)}"
            f"  reroutes_in={p.get('reroutes_in', 0)}"
            f"  inflight={p.get('inflight', 0)}")
    skew = snap.get("epoch_skew")
    if skew is not None:
        state = "CONVERGED" if skew == 0 else f"SKEW={skew}"
        eps = "  ".join(f"{k}={v}" for k, v in
                        sorted((snap.get("key_epochs") or {}).items()))
        lines.append(f"  fleet epochs: {state}"
                     + (f"  target={snap['epoch']}"
                        if snap.get("epoch") is not None else "")
                     + (f"  ({eps})" if eps else ""))
    peer = {k: v for k, v in c.items() if "peer_fill" in k}
    if peer:
        lines.append("  peer fill: " + "  ".join(
            f"{k.split('.', 1)[1]}={v}" for k, v in sorted(
                peer.items())))
    tenants = snap.get("tenants") or {}
    if tenants:
        lines.append(f"  tenants routed ({len(tenants)}):")
        ordered = sorted(tenants.items(),
                         key=lambda kv: kv[1].get("lookups", 0),
                         reverse=True)
        for t, row in ordered[:12]:
            tl = int(row.get("lookups", 0) or 0)
            th = int(row.get("affinity_hits", 0) or 0)
            t_rate = f"{100.0 * th / tl:.1f}%" if tl else "0.0%"
            lines.append(f"    tenant={t:<14} lookups={tl} "
                         f"affinity_hit={t_rate}")
    return "\n".join(lines)


def render_tenants(merged: Dict[str, Any],
                   prev_counters: Optional[Dict[str, int]] = None,
                   interval_s: float = 0.0, top: int = 20,
                   client: Optional[Dict[str, Any]] = None,
                   extras: Optional[Dict[str, Any]] = None) -> str:
    """The fleet tenant ledger (``--tenants``): per-tenant verify
    rate, reject mix, serve-side p99, vcache hit%, per-tenant SLO
    state, and — with admission armed — the enforcement columns
    (DRR weight, bucket fill, throttled count/rate, shed state) plus
    a pool-size/resize-event line, over the exact merged fleet scrape
    — tenants are issuer HASHES (plus ``none``/``other``), raw
    issuers never reach a scrape. Under ``--watch`` the vps and thr/s
    columns are per-interval rates (counter deltas); one-shot renders
    lifetime totals. ``client`` (the --client snapshot) supplies the
    pool-side resize-event log when present."""
    counters = {k: int(v) for k, v in
                (merged.get("counters") or {}).items()}
    tenants = obs_decision.tenant_totals(counters, surface="serve")
    summary = telemetry.summarize_snapshot(merged)
    # per-tenant SLO state from the DEFAULT tenant templates (the
    # reject-ratio budget + per-tenant wrong-verdicts), evaluated over
    # the same merged counters the table renders
    slo_state: Dict[str, str] = {}
    try:
        rules = [r for r in obs_slo.default_rules()
                 if obs_slo.is_tenant_template(r)]
        for r in obs_slo.evaluate_once(merged, rules):
            tid = r.get("tenant")
            if tid is None:
                continue
            if not r["ok"]:
                slo_state[tid] = "BREACH"
            else:
                slo_state.setdefault(tid, "ok")
    except Exception as e:  # noqa: BLE001 - ledger must still render
        slo_state = {}
        print(f"capstat: tenant SLO evaluation failed: {e!r}",
              file=sys.stderr)
    look = counters.get("tenant.lookups", 0)
    attr = counters.get("tenant.attributed", 0)
    ovf = counters.get("tenant.overflow", 0)
    ev = counters.get("tenant.table_evictions", 0)
    state = ("EXACT" if look == attr + ovf else
             f"DRIFT({look}!={attr}+{ovf})")
    lines = [f"tenants ({len(tenants)} observed)  lookups={look} "
             f"attributed={attr} overflow={ovf} evictions={ev} "
             f"[{state}]"]
    gauges = {k: v for k, v in (merged.get("gauges") or {}).items()}
    # live worker gauges (admission rate/burst, per-tenant fill /
    # weight / shed state) arrive via the scrape's "extra" section,
    # pre-merged by main() — min for fills, max otherwise
    gauges.update(extras or {})
    # admission summary: the exact checked == admitted + throttled
    # equation, rendered EXACT/DRIFT like the tenant equation above
    adm_checked = counters.get("admission.checked", 0)
    adm_ok = counters.get("admission.admitted", 0)
    adm_thr = counters.get("admission.throttled", 0)
    adm_armed = adm_checked or gauges.get("admission.active")
    if adm_armed:
        astate = ("EXACT" if adm_checked == adm_ok + adm_thr else
                  f"DRIFT({adm_checked}!={adm_ok}+{adm_thr})")
        lines.append(
            f"  admission: checked={adm_checked} admitted={adm_ok} "
            f"throttled={adm_thr} [{astate}]  "
            f"rate={gauges.get('admission.rate', '-')}/s "
            f"burst={gauges.get('admission.burst', '-')}  "
            f"sheds={counters.get('admission.sheds', 0)} "
            f"unsheds={counters.get('admission.unsheds', 0)}")
    # pool line: size/ready gauges + resize counters (pool-side, so
    # they reach a scrape through the --client snapshot's recorder)
    pool_bits = []
    if client is not None and client.get("pool_size") is not None:
        pool_bits.append(f"size={client['pool_size']}")
    if gauges.get("fleet.pool_size") is not None:
        pool_bits.append(f"gauge_size={int(gauges['fleet.pool_size'])}")
    if gauges.get("fleet.workers_ready") is not None:
        pool_bits.append(f"ready={int(gauges['fleet.workers_ready'])}")
    for k, label in (("fleet.resize.up", "up"),
                     ("fleet.resize.down", "down"),
                     ("fleet.resize.shed", "shed"),
                     ("fleet.resize.unshed", "unshed"),
                     ("fleet.admission_pushes", "adm_pushes")):
        if counters.get(k):
            pool_bits.append(f"{label}={counters[k]}")
    if pool_bits:
        lines.append("  pool: " + "  ".join(pool_bits))
    for e in ((client or {}).get("resize_events") or [])[-4:]:
        lines.append(
            f"    resize[{e.get('kind')}] {e.get('from')}→{e.get('to')}"
            f"  reason={e.get('reason')}"
            + (f"  tenant={e.get('tenant')}" if e.get("tenant")
               else ""))
    rate_col = "vps" if prev_counters is not None and interval_s > 0 \
        else "tokens"
    thr_col = "thr/s" if rate_col == "vps" else "thrtl"
    lines.append(f"  {'tenant':<14} {rate_col:>10} {'accept':>9} "
                 f"{'reject':>9} {thr_col:>8} {'p99':>10} "
                 f"{'vc-hit':>7} {'w':>3} {'fill':>7} {'shed':>5} "
                 f"{'slo':<7} reject mix")
    ordered = sorted(tenants.items(),
                     key=lambda kv: kv[1].get("tokens", 0),
                     reverse=True)
    for t, row in ordered[:top]:
        toks = row.get("tokens", 0)
        if rate_col == "vps":
            prev = prev_counters.get(
                f"decision.serve.tenant.{t}.tokens", 0)
            d = toks if toks < prev else toks - prev
            rate = f"{d / interval_s:10.1f}"
        else:
            rate = f"{toks:10d}"
        thr_n = row.get("reject.throttled", 0)
        if rate_col == "vps":
            pthr = prev_counters.get(
                f"decision.serve.tenant.{t}.reject.throttled", 0)
            dthr = thr_n if thr_n < pthr else thr_n - pthr
            thr_cell = f"{dthr / interval_s:8.1f}"
        else:
            thr_cell = f"{thr_n:8d}"
        s = summary.get(f"tenant.{t}.request_s")
        p99 = f"{s['p99'] * 1e3:8.2f}ms" if s else "       -"
        vl = row.get("vcache.lookups", 0)
        vh = row.get("vcache.hits", 0)
        vc = f"{100.0 * vh / vl:6.1f}%" if vl else "      -"
        w = gauges.get(f"admission.tenant.{t}.weight")
        w_cell = f"{int(w):>3}" if w is not None else "  1"
        fill = gauges.get(f"admission.tenant.{t}.fill")
        fill_cell = f"{fill:7.1f}" if fill is not None else "      -"
        shed = gauges.get(f"admission.tenant.{t}.shed_scale")
        shed_cell = f"{shed:5.2f}" if shed is not None else "    -"
        mix = "  ".join(
            f"{k.split('.', 1)[1]}={v}" for k, v in sorted(
                row.items(), key=lambda kv: -kv[1]
                if isinstance(kv[1], int) else 0)
            if k.startswith("reject."))[:60]
        wrong = row.get("wrong_verdicts", 0)
        lines.append(
            f"  {t:<14} {rate} {row.get('accept', 0):>9} "
            f"{row.get('reject', 0):>9} {thr_cell} {p99} {vc} "
            f"{w_cell} {fill_cell} {shed_cell} "
            f"{slo_state.get(t, '-'):<7} "
            + (f"WRONG={wrong} " if wrong else "") + mix)
    if len(tenants) > top:
        lines.append(f"  … {len(tenants) - top} more (sorted by "
                     "tokens; raise --tenants-top)")
    return "\n".join(lines)


def render_occupancy(worker_data: Dict[str, Dict[str, Any]]) -> str:
    """The ``--occupancy`` view (r22): per-worker device occupancy %,
    flush-reason mix, the stage waterfall (where each microsecond of
    ``serve.request_s`` waits), and idle-gap p99 — the measurement
    half of ROADMAP #5's ≥90% occupancy gate, over the same mergeable
    counters every other view renders."""
    from cap_tpu.obs import occupancy as obs_occupancy

    lines = []
    merged = merged_snapshot(worker_data)
    counters = {k: int(v) for k, v in
                (merged.get("counters") or {}).items()}
    agg = obs_occupancy.occupancy_from_counters(counters)
    if agg is None:
        return ("occupancy: no device.* counters in the scrape "
                "(no engine dispatched yet, or workers predate r22)")
    fam_mix = "  ".join(
        f"{fam}={row['occupancy'] * 100:.1f}%" for fam, row in
        sorted(agg["families"].items(),
               key=lambda kv: -kv[1]["busy_us"]))
    lines.append(
        f"fleet occupancy {agg['occupancy'] * 100:6.2f}%  "
        f"(busy {agg['busy_us'] / 1e3:.1f}ms / wall "
        f"{agg['wall_us'] / 1e6:.1f}s, worker-weighted)  "
        f"dispatches={agg['dispatches']}  {fam_mix}")
    # flush-reason mix: every flush attributed to WHY it fired
    flushes = counters.get("batcher.flushes", 0)
    reasons = {k.rsplit(".", 1)[1]: v for k, v in counters.items()
               if k.startswith("batcher.flush.")}
    if reasons:
        total = sum(reasons.values())
        mix = "  ".join(f"{r}={v} ({100.0 * v / total:.0f}%)"
                        for r, v in sorted(reasons.items(),
                                           key=lambda kv: -kv[1]))
        eq = "EXACT" if total == flushes else \
            f"DRIFT({total}!={flushes})"
        lines.append(f"  flush reasons: {mix}  [{eq} vs "
                     f"batcher.flushes={flushes}]")
    # stage waterfall: mean time per request in each queueing stage;
    # their sum ≈ the end-to-end request mean (pinned by test)
    summary = telemetry.summarize_snapshot(merged)
    req = (summary.get("serve.request_s")
           or summary.get("serve.native.request_s"))
    stage_names = ["queue.ring_wait_s", "queue.batcher_wait_s",
                   "queue.dispatch_gap_s", "device.exec_s"]
    stages = [(n, summary[n]) for n in stage_names if n in summary]
    if stages:
        lines.append(f"  {'stage':<24} {'mean':>10} {'p99':>10} "
                     f"{'count':>9}  share")
        denom = req["mean"] if req else \
            sum(s["mean"] for _, s in stages)
        for name, s in stages:
            share = s["mean"] / denom if denom > 0 else 0.0
            bar = "#" * int(round(share * 20))
            lines.append(
                f"  {name:<24} {s['mean'] * 1e6:8.1f}us "
                f"{s['p99'] * 1e6:8.1f}us {int(s['count']):>9}  "
                f"{share * 100:5.1f}% {bar}")
        if req is not None:
            lines.append(
                f"  {'serve.request_s (e2e)':<24} "
                f"{req['mean'] * 1e6:8.1f}us "
                f"{req['p99'] * 1e6:8.1f}us {int(req['count']):>9}")
    gap = summary.get("device.idle_gap_s")
    if gap is not None:
        lines.append(
            f"  idle gaps: {int(gap['count'])} bubbles, "
            f"mean {gap['mean'] * 1e3:.2f}ms, p99 "
            f"{gap['p99'] * 1e3:.2f}ms — host-prep time #5's "
            "double-buffering closes")
    # per-worker occupancy (each scrape's own counters)
    if len(worker_data) > 1:
        lines.append(f"  {'worker':<22} {'occ%':>7} {'dispatches':>11} "
                     f"{'busy_ms':>9}")
        for ep, data in sorted(worker_data.items()):
            wc = {k: int(v) for k, v in
                  ((data.get("snapshot") or {})
                   .get("counters") or {}).items()}
            w = obs_occupancy.occupancy_from_counters(wc)
            if w is None:
                lines.append(f"  {ep:<22}       -")
                continue
            lines.append(f"  {ep:<22} {w['occupancy'] * 100:6.2f}% "
                         f"{w['dispatches']:>11} "
                         f"{w['busy_us'] / 1e3:9.1f}")
    return "\n".join(lines)


def counter_deltas(prev: Dict[str, Any],
                   cur: Dict[str, Any]) -> Dict[str, int]:
    """Per-interval counter increases between two merged scrapes.

    Counters are cumulative per process, so a worker respawn RESETS
    its contribution and the merged total can go backwards. Burn must
    never render negative: a counter below its previous value (or one
    that just appeared) is treated as freshly started — the delta is
    its current value, the Prometheus ``increase()`` stance. Pinned by
    test across a simulated respawn.
    """
    out: Dict[str, int] = {}
    for k, v in cur.items():
        p = prev.get(k)
        v = int(v)
        out[k] = v if (p is None or v < int(p)) else v - int(p)
    return out


def render_deltas(deltas: Dict[str, int], interval_s: float) -> str:
    """The --watch burn view: per-interval counter deltas and rates
    (quantiles stay absolute — they are already windowless)."""
    rows = [f"interval deltas ({interval_s:g}s)"]
    for k, d in sorted(deltas.items()):
        if not d:
            continue
        rate = d / interval_s if interval_s > 0 else 0.0
        rows.append(f"  {k:<44} +{d:<10} {rate:10.1f}/s")
    if len(rows) == 1:
        rows.append("  (no counter movement)")
    return "\n".join(rows)


def merged_snapshot(worker_data: Dict[str, Dict[str, Any]],
                    client: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """One merged snapshot over every scraped worker plus (optionally)
    the client-side snapshot — what the SLO engine evaluates."""
    snaps = [d.get("snapshot") for d in worker_data.values()]
    if client is not None:
        snaps.append(client.get("snapshot"))
    return telemetry.merge_snapshots(snaps)


def run_slo(worker_data: Dict[str, Dict[str, Any]],
            client: Optional[Dict[str, Any]],
            rules_file: Optional[str]) -> tuple:
    """(rendered table, breach?) for the --slo path. A rules file that
    fails to parse raises SLOError — an unevaluable SLO config is a
    failure, not a silent pass."""
    if rules_file:
        with open(rules_file) as f:
            rules = obs_slo.parse_rules(f.read())
    else:
        rules = obs_slo.default_rules()
    results = obs_slo.evaluate_once(
        merged_snapshot(worker_data, client), rules)
    return obs_slo.format_results(results), obs_slo.any_breach(results)


def check_required(worker_data: Dict[str, Dict[str, Any]]) -> List[str]:
    """Missing/NaN required gauges per endpoint (obs-smoke's check)."""
    problems = []
    for ep, data in sorted(worker_data.items()):
        extra = data.get("extra") or {}
        for name in REQUIRED_GAUGES:
            v = extra.get(name)
            if v is None:
                problems.append(f"{ep}: missing gauge {name}")
            elif v != v:                  # NaN
                problems.append(f"{ep}: gauge {name} is NaN")
    return problems


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="capstat", description="scrape + render fleet telemetry")
    ap.add_argument("endpoints", nargs="*",
                    help="worker obs endpoints (host:port); not "
                         "needed with --postmortem")
    ap.add_argument("--client", metavar="FILE",
                    help="JSON file with FleetClient.snapshot() for "
                         "breaker/routing view")
    ap.add_argument("--trace", metavar="ID",
                    help="reassemble one trace id across the fleet")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate SLO rules (the default set, or "
                         "--slo-rules FILE) against the merged fleet; "
                         "exit 2 on breach")
    ap.add_argument("--slo-rules", metavar="FILE",
                    help="rules file for --slo (cap_tpu.obs.slo "
                         "syntax); implies --slo")
    ap.add_argument("--frontdoor", metavar="FILE",
                    help="JSON file with FrontDoor.snapshot() for the "
                         "router-tier view (per-host affinity hit%%, "
                         "spill/re-route counts, fleet epoch state)")
    ap.add_argument("--tenants", action="store_true",
                    help="render the fleet tenant ledger (per-tenant "
                         "vps/reject mix/p99/vcache hit%%/SLO state "
                         "over the merged scrape; --watch turns the "
                         "tokens column into a per-interval rate)")
    ap.add_argument("--occupancy", action="store_true",
                    help="render the pipeline-occupancy view (device "
                         "occupancy %%, flush-reason mix, stage "
                         "waterfall, idle-gap p99 over the merged "
                         "scrape)")
    ap.add_argument("--tenants-top", type=int, default=20,
                    metavar="N", help="rows in the tenant ledger "
                    "(default 20, sorted by tokens)")
    ap.add_argument("--postmortem", metavar="FILE",
                    help="render a collected crash postmortem file "
                         "(no endpoints scraped)")
    ap.add_argument("--watch", type=float, metavar="SECONDS",
                    help="re-scrape and re-render every N seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the merged scrape as JSON")
    args = ap.parse_args(argv)

    if args.postmortem:
        doc = obs_postmortem.read_postmortem(args.postmortem)
        if doc is None:
            print(f"capstat: cannot read postmortem "
                  f"{args.postmortem}", file=sys.stderr)
            return 1
        print(obs_postmortem.render_postmortem(doc))
        return 0

    frontdoor = None
    if args.frontdoor:
        with open(args.frontdoor) as f:
            frontdoor = json.load(f)
        if not args.endpoints:
            print(render_frontdoor(frontdoor))
            return 0

    if not args.endpoints:
        ap.error("endpoints are required unless --postmortem or "
                 "--frontdoor is used")

    client = None
    if args.client:
        with open(args.client) as f:
            client = json.load(f)

    breached = False
    prev_counters: Optional[Dict[str, int]] = None
    prev_t = time.monotonic()
    while True:
        worker_data: Dict[str, Dict[str, Any]] = {}
        for ep in args.endpoints:
            try:
                worker_data[ep] = scrape(ep)
            except OSError as e:
                worker_data[ep] = {"snapshot": {}, "extra": {},
                                   "flight": [], "error": str(e)}
        if args.trace:
            sources: List[Any] = list(worker_data.values())
            if client is not None:
                sources.append({"spans": [
                    s for s in (client.get("spans") or [])]})
            spans = reassemble_trace(args.trace, sources)
            print(render_trace(args.trace, spans))
        elif args.json:
            merged = telemetry.merge_snapshots(
                [d.get("snapshot") for d in worker_data.values()])
            print(json.dumps({
                "workers": worker_data,
                "aggregate": {
                    "snapshot": merged,
                    "series": telemetry.summarize_snapshot(merged)},
            }, indent=1))
        else:
            if frontdoor is not None:
                print(render_frontdoor(frontdoor))
            if args.occupancy:
                print(render_occupancy(worker_data))
            elif args.tenants:
                merged = merged_snapshot(worker_data, client)
                now = time.monotonic()
                extras: Dict[str, Any] = {}
                for d in worker_data.values():
                    for k, v in (d.get("extra") or {}).items():
                        if not isinstance(v, (int, float)):
                            continue
                        if k in extras:
                            extras[k] = (min(extras[k], v)
                                         if k.endswith(".fill")
                                         else max(extras[k], v))
                        else:
                            extras[k] = v
                print(render_tenants(
                    merged, prev_counters=prev_counters,
                    interval_s=now - prev_t, top=args.tenants_top,
                    client=client, extras=extras))
                if args.watch:
                    prev_counters = {
                        k: int(v) for k, v in
                        (merged.get("counters") or {}).items()}
                    prev_t = now
            else:
                print(render_fleet(worker_data, client))
                if args.watch:
                    # burn view: cumulative counters hide movement at
                    # a glance — show what changed THIS interval
                    # (respawn resets clamp to the fresh value, never
                    # negative)
                    cur = {k: int(v) for k, v in (merged_snapshot(
                        worker_data).get("counters") or {}).items()}
                    now = time.monotonic()
                    if prev_counters is not None:
                        print(render_deltas(
                            counter_deltas(prev_counters, cur),
                            now - prev_t))
                    prev_counters, prev_t = cur, now
        if args.slo or args.slo_rules:
            table, breach = run_slo(worker_data, client,
                                    args.slo_rules)
            print(table)
            breached = breached or breach
        if not args.watch:
            break
        time.sleep(args.watch)
        print()
    return 2 if breached else 0


if __name__ == "__main__":
    sys.exit(main())
