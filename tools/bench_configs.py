#!/usr/bin/env python3
"""The BASELINE.md config ladder (configs ①-⑤), one JSON line each.

① 1k RS256, single 2048-bit key, StaticKeySet   (CPU reference path)
② RS256/384/512 mix, 2048+4096-bit, 8-key JWKS  (batched RSA + gather)
③ ES256/ES384 on P-256/P-384 JWKS               (batched ECDSA)
④ PS256 + EdDSA mix, rotating kids              (PSS + Ed25519)
⑤ end-to-end Provider.verify_id_token_batch over OIDC discovery JWKS
   (the full RP stack sharing the accelerated KeySet path)

CAP_CFG_BATCH scales the per-config batch (default 16384; config ①
fixed at 1000 per the ladder, ⑤ at min(batch, 100k)).
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cap_tpu import compile_cache

compile_cache.enable()

from cap_tpu import testing as T
from cap_tpu.jwt import StaticKeySet
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

BATCH = int(os.environ.get("CAP_CFG_BATCH", 1 << 14))
REPS = int(os.environ.get("CAP_CFG_REPS", 3))


def tile(unique, n):
    return (unique * (n // len(unique) + 1))[:n]


def sign_unique(signers, n):
    """n UNIQUE tokens (distinct sub/jti), signed across threads."""
    return T.sign_unique_jwts(signers, n)


def rate(fn, n):
    fn()
    vals = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        fn()
        vals.append(n / (time.perf_counter() - t0))
    return statistics.median(vals)


def rate_stream(ks, toks, window: int = 4):
    """Steady-state pipelined rate: median completion interval over
    ``window`` back-to-back batches (2-deep), pipeline fill dropped —
    the same methodology as bench.py's headline. Returns
    (rate, effective_h2d_mbps): the device configs here are WIRE-bound
    on the tunnel-attached dev chip, so each number carries the link
    throughput it was measured at (docs/PERF.md)."""
    from cap_tpu import telemetry

    ks.verify_batch(toks)                      # warm compile
    rec = telemetry.enable()
    done = []
    for out in ks.verify_stream(toks for _ in range(window + 1)):
        done.append(time.perf_counter())
        assert not any(isinstance(r, Exception) for r in out)
    telemetry.disable()
    h2d = rec.counters().get("h2d.bytes", 0) / (window + 1)
    intervals = [b - a for a, b in zip(done, done[1:])]
    med = statistics.median(intervals)
    return len(toks) / med, (h2d / med) / (1 << 20)


def emit(name, value, n, eff_mbps=None):
    rec = {"metric": name, "value": round(value, 1),
           "unit": "verifies/sec", "batch": n}
    if eff_mbps is not None:
        rec["wire_effective_mbps"] = round(eff_mbps, 2)
    print(json.dumps(rec), flush=True)


def config1():
    n = 1000
    priv, pub = T.generate_keys("RS256", rsa_bits=2048)
    ks = StaticKeySet([pub])
    toks = tile([T.sign_jwt(priv, "RS256", T.default_claims(ttl=86400))
                 for _ in range(32)], n)

    def run():
        for t in toks:
            ks.verify_signature(t)

    emit("cfg1_rs256_static_cpu", rate(run, n), n)


def config2():
    n = BATCH
    jwks, signers = [], []
    for i, (alg, bits) in enumerate(
            [("RS256", 2048)] * 3 + [("RS384", 2048)] * 2
            + [("RS512", 4096)] * 2 + [("RS256", 4096)]):
        priv, pub = T.generate_keys(alg, rsa_bits=bits)
        jwks.append(JWK(pub, kid=f"k{i}"))
        signers.append((priv, alg, f"k{i}"))
    toks = sign_unique(signers, n)
    ks = TPUBatchKeySet(jwks)
    # rate_stream warms compile and asserts every batch verifies
    r, eff = rate_stream(ks, toks)
    emit("cfg2_rs_mix_8key_jwks", r, n, eff)


def config3():
    n = BATCH
    jwks, signers = [], []
    for i in range(4):
        priv, pub = T.generate_keys("ES256")
        jwks.append(JWK(pub, kid=f"p256-{i}"))
        signers.append((priv, "ES256", f"p256-{i}"))
    for i in range(4):
        priv, pub = T.generate_keys("ES384")
        jwks.append(JWK(pub, kid=f"p384-{i}"))
        signers.append((priv, "ES384", f"p384-{i}"))
    toks = sign_unique(signers, n)
    ks = TPUBatchKeySet(jwks)
    # rate_stream warms compile and asserts every batch verifies
    r, eff = rate_stream(ks, toks)
    emit("cfg3_es256_es384", r, n, eff)


def config4():
    n = BATCH
    jwks, signers = [], []
    for i in range(4):
        priv, pub = T.generate_keys("PS256", rsa_bits=2048)
        jwks.append(JWK(pub, kid=f"ps-{i}"))
        signers.append((priv, "PS256", f"ps-{i}"))
    for i in range(4):
        priv, pub = T.generate_keys("EdDSA")
        jwks.append(JWK(pub, kid=f"ed-{i}"))
        signers.append((priv, "EdDSA", f"ed-{i}"))
    toks = sign_unique(signers, n)
    ks = TPUBatchKeySet(jwks)
    # rate_stream warms compile and asserts every batch verifies
    r, eff = rate_stream(ks, toks)
    emit("cfg4_ps256_eddsa", r, n, eff)


def config5():
    from cap_tpu.oidc import Config, Provider, Request
    from cap_tpu.oidc.testing import TestProvider

    n = min(BATCH, 100_000)
    idp = TestProvider().start()
    try:
        cfg = Config(issuer=idp.issuer(), client_id=idp.client_id,
                     client_secret=idp.client_secret,
                     supported_signing_algs=["ES256"],
                     allowed_redirect_urls=["http://127.0.0.1:1/cb"],
                     provider_ca=idp.ca_cert())
        # accelerated KeySet shared by the whole RP stack, built from
        # the IdP's signing key (the discovery JWKS equivalent)
        priv, pub, alg, kid = idp.signing_keys()
        ks = TPUBatchKeySet([JWK(pub, kid=kid)])
        p = Provider(cfg, keyset=ks)
        req = Request(3600.0, "http://127.0.0.1:1/cb")
        claims = T.default_claims(issuer=idp.issuer(), ttl=3600.0,
                                  aud=[idp.client_id])
        claims["nonce"] = req.nonce()
        toks = tile([T.sign_jwt(priv, alg, claims, kid=kid)
                     for _ in range(128)], n)

        def run():
            out = p.verify_id_token_batch(toks, req)
            bad = sum(1 for r in out if isinstance(r, Exception))
            assert bad == 0, bad

        emit("cfg5_oidc_verify_id_token_e2e", rate(run, n), n)

        def run_raw():
            # the serve-style mode: registered-claims validation off
            # the native tape, accepted tokens return payload bytes
            out = p.verify_id_token_batch(toks, req, raw=True)
            bad = sum(1 for r in out if isinstance(r, Exception))
            assert bad == 0, bad

        emit("cfg5_oidc_verify_id_token_e2e_raw", rate(run_raw, n), n)
    finally:
        idp.stop()


def main():
    only = os.environ.get("CAP_CFG_ONLY", "")
    wanted = {int(c) for c in only.split(",") if c} if only else None
    for i, fn in enumerate((config1, config2, config3, config4,
                            config5), start=1):
        if wanted is not None and i not in wanted:
            continue
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - report per config
            print(json.dumps({"metric": fn.__name__, "error":
                              f"{type(e).__name__}: {e}"}), flush=True)


if __name__ == "__main__":
    main()
