#!/usr/bin/env python3
"""Stage-level profile of the headline benchmark using cap_tpu.telemetry.

Runs RS256-only, ES256-only, and mixed batches and prints the per-stage
summary so optimization targets the real bottleneck.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cap_tpu import telemetry
from cap_tpu import testing as T
from cap_tpu.jwt import algs
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

BATCH = int(os.environ.get("CAP_PROF_BATCH", 1 << 14))


def make(alg_list):
    jwks, signers = [], []
    for i, alg in enumerate(alg_list):
        kw = {"rsa_bits": 2048} if alg == "RS256" else {}
        priv, pub = T.generate_keys(alg, **kw)
        jwks.append(JWK(pub, kid=f"k-{i}"))
        signers.append((priv, alg, f"k-{i}"))
    claims = T.default_claims(ttl=86400.0)
    uniq = [T.sign_jwt(p, a, claims, kid=k) for p, a, k in signers]
    toks = (uniq * (BATCH // len(uniq) + 1))[:BATCH]
    return TPUBatchKeySet(jwks), toks


def run(name, alg_list):
    ks, toks = make(alg_list)
    ks.verify_batch(toks)  # warmup/compile
    with telemetry.recording() as rec:
        t0 = time.perf_counter()
        ks.verify_batch(toks)
        dt = time.perf_counter() - t0
    print(f"== {name}: {BATCH} tokens in {dt:.3f}s = {BATCH/dt:,.0f}/s")
    for k, s in sorted(rec.summary().items()):
        print(f"   {k:24s} n={int(s['count']):3d} total={s['total']:.3f}s "
              f"mean={s['mean']*1e3:.1f}ms")
    for k, v in sorted(rec.counters().items()):
        print(f"   {k:24s} = {v}")


if __name__ == "__main__":
    run("RS256 x8keys", ["RS256"] * 8)
    run("ES256 x8keys", ["ES256"] * 8)
    run("mixed 8+8", ["RS256"] * 8 + ["ES256"] * 8)
