#!/usr/bin/env python3
"""Serving-path benchmark: throughput vs per-REQUEST p99 latency.

What a user of the framework actually experiences (VERDICT r2 #5): N
concurrent CVB1 clients stream small verify requests at a VerifyWorker
whose AdaptiveBatcher owns the latency/throughput tradeoff; this sweeps
``max_wait_ms`` operating points and reports, per point, sustained
verifies/sec and request-latency quantiles.

Env knobs: CAP_SERVE_CLIENTS (32), CAP_SERVE_REQ_TOKENS (64),
CAP_SERVE_SECONDS (12 per point), CAP_SERVE_WAITS ("1,5,20"),
CAP_SERVE_TARGET_BATCH (8192).

ZIPF TOKEN MIX (``CAP_SERVE_ZIPF=s``): request tokens are drawn from a
Zipf(s) distribution over the unique pool instead of contiguous
windows — the repeat-heavy traffic shape real ingress has (the same
bearer token arriving hundreds of times inside its lifetime), and the
measurement harness ROADMAP item #3's verdict cache needs.
``CAP_SERVE_ZIPF_POOL=N`` bounds the sampled pool (the repeat-rate
knob: smaller pool → higher repeat rate). The pool's rank→token
permutation is computed ONCE in the parent from a pinned seed
(``CAP_SERVE_ZIPF_SEED``, default 1234) and shipped to every driver
process, so repeat_rate is exact and comparable across every
``CAP_SERVE_FLEET`` / chain / vcache arm. The BENCH json reports
tokens sent vs unique vs repeats per point.

VERDICT-CACHE A/B (fleet mode, ``CAP_SERVE_VCACHES="on,off"``): every
(size, chain) arm runs once per cache state (workers spawned with
CAP_SERVE_VCACHE=1/0), each point records its worker-side
``cache`` counters (lookups/hits/misses/evictions/dedup_fanout/
stale_accepts), and the headline gains ``zipf_cached_vps`` /
``zipf_uncached_vps`` and their ratio — the §Round 14 measurement of
ROADMAP #3's ≥5×-at-90%-repeat bar.

SERVE-CHAIN COMPARISON (fleet mode, ``CAP_SERVE_CHAINS=
"python,native"``): every fleet size runs once per listed chain
(workers spawned with CAP_SERVE_NATIVE=0/1), and the headline gains
``serve_native_vps`` / ``serve_python_vps`` and their ratio — the
host-saturation A/B docs/PERF.md §Round 12 records.

MULTI-POOL FRONT-DOOR MODE (``CAP_SERVE_POOLS=N``): N fresh
``WorkerPool`` "hosts" behind :class:`cap_tpu.fleet.FrontDoor`
drivers, one run per routing arm in ``CAP_SERVE_ROUTING``
("affinity,rr" — consistent-hash digest affinity vs round-robin),
arms interleaved over ``CAP_SERVE_REPS``. ``CAP_SERVE_POOL_WORKERS``
sizes each pool, ``CAP_SERVE_VCACHE_CAP`` bounds each worker's
verdict cache (the fleet-scale regime: corpus >> one worker's cache),
``CAP_SERVE_SPILL`` sets the bounded-load constant. Headline:
``fleet_affinity_vps`` / ``fleet_rr_vps`` + ratio (§Round 16,
tracked by bench_trend).

FLEET MODE (``CAP_SERVE_FLEET="1,2"``): instead of one in-process
worker, spin a ``WorkerPool`` per listed size under the single-owner
placement model (one worker process per device group — NO chip
sharing, fixing the VERDICT r5 shared-chip extrapolation) and drive it
with ``FleetClient`` processes. Reports per-size throughput and the
scaling ratio of the largest over the smallest size. Fleet knobs:
``CAP_SERVE_FLEET_KEYSET`` (worker ``--keyset`` spec; default
``stub:batch_ms=1,token_us=300`` — simulated device occupancy that
sleeps WITHOUT the GIL so cross-process overlap is real even on a
1-core host, sized so the WORKER is the bottleneck (the regime a
fleet exists for; at ~100 µs/token and below, this host's single
core saturates on the Python serve+client chains first and the
measurement stops being about placement); use ``jwks:<path>`` for
real engines on real hardware).

Prints one JSON line on stdout: per-point results + the best-throughput
point's p99 as the headline fields.
"""

import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fixtures(n_unique: int = 16384):
    from cap_tpu import testing as T

    return T.headline_fixtures(n_unique)


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[max(0, math.ceil(q * len(sorted_vals)) - 1)]


def _zipf_cfg():
    """(s, pool) from the env, or None — shipped to client procs."""
    s = os.environ.get("CAP_SERVE_ZIPF")
    if not s:
        return None
    return (float(s), int(os.environ.get("CAP_SERVE_ZIPF_POOL", 0)))


def _zipf_pool_indices(n_tokens, zipf):
    """The SHARED Zipf pool: rank→token-index permutation, computed
    ONCE in the parent from a pinned seed (``CAP_SERVE_ZIPF_SEED``,
    default 1234) and shipped to every driver process. Every client in
    every arm (fleet size × serve chain × vcache) then hammers the
    IDENTICAL hot-token set, so ``repeat_rate`` in the json is exact
    and comparable across ``CAP_SERVE_FLEET`` arms — drivers must
    never regenerate the pool per process."""
    import numpy as np

    if zipf is None:
        return None
    _, pool = zipf
    n = min(pool or n_tokens, n_tokens)
    seed = int(os.environ.get("CAP_SERVE_ZIPF_SEED", "1234"))
    return np.random.RandomState(seed).permutation(n_tokens)[:n]


def _zipf_picker(tokens, req_tokens, seed, zipf, pool_idx=None):
    """Request generator state for the Zipf token mix: returns
    ``pick() -> (token_list, index_array)``. Rank→token mapping is the
    parent's shared pinned permutation (``pool_idx``) so every client
    hammers the SAME hot tokens — that is what makes the mix
    cacheable."""
    import numpy as np

    zs, pool = zipf
    perm = (np.asarray(pool_idx) if pool_idx is not None
            else _zipf_pool_indices(len(tokens), zipf))
    n = len(perm)
    w = np.arange(1, n + 1, dtype=np.float64) ** -zs
    cdf = np.cumsum(w)
    cdf /= cdf[-1]
    rng = np.random.RandomState(seed * 7919 + 17)

    def pick():
        idx = perm[np.searchsorted(cdf, rng.random_sample(req_tokens))]
        return [tokens[i] for i in idx], idx

    return pick


def _client_proc(host, port, tokens, req_tokens, depth, start_at,
                 seconds, seed, outq, zipf=None, pool_idx=None):
    """One client PROCESS: its own interpreter, so response decoding
    never shares the worker's (or other clients') GIL — in-process
    client threads cap the whole bench at one core of json parsing
    (measured: ~15k verifies/s regardless of depth or batch knobs)."""
    from collections import deque

    from cap_tpu.serve.client import VerifyClient

    # generous timeout: first flushes of a fresh shape bucket can hit
    # an XLA compile (~40s over the tunnel) before the cache warms
    cl = VerifyClient(host, port, timeout=180.0)
    t0s: deque = deque()
    lats = []
    done = 0
    sent = 0
    used = set()
    picker = _zipf_picker(tokens, req_tokens, seed, zipf,
                          pool_idx=pool_idx) if zipf else None
    while time.time() < start_at:
        time.sleep(0.005)
    deadline = time.time() + seconds

    def gen():
        nonlocal sent
        rng = seed * 7919 + 17
        while time.time() < deadline:
            t0s.append(time.perf_counter())
            if picker is not None:
                toks, idx = picker()
                used.update(idx.tolist())
                sent += len(toks)
                yield toks
                continue
            rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
            lo = rng % max(1, len(tokens) - req_tokens)
            sent += req_tokens
            used.update(range(lo, lo + req_tokens))
            yield tokens[lo: lo + req_tokens]

    err = None
    try:
        # depth > 1: the client keeps frames in flight, so request
        # latency includes pipeline queueing — the honest number a
        # pipelining caller experiences.
        for out in cl.verify_stream(gen(), depth=depth):
            in_window = time.time() < deadline
            lats.append(time.perf_counter() - t0s.popleft())
            bad = sum(1 for r in out if isinstance(r, Exception))
            assert bad == 0, f"unexpected failures: {bad}"
            if in_window:
                done += len(out)
    except BaseException as e:  # noqa: BLE001 - reported to the parent
        err = f"{type(e).__name__}: {e}"
    finally:
        cl.close()
        # ALWAYS report, error or not — a silent child death would
        # stall the parent's collection for its full timeout
        outq.put((done, lats, err, sent, used))


def run_point(keyset, tokens, max_wait_ms: float, n_clients: int,
              req_tokens: int, seconds: float,
              target_batch: int, depth: int = 1) -> dict:
    import multiprocessing as mp

    from cap_tpu.serve.worker import VerifyWorker

    worker = VerifyWorker(keyset, target_batch=target_batch,
                          max_wait_ms=max_wait_ms)
    host, port = worker.address
    zipf = _zipf_cfg()
    pool_idx = _zipf_pool_indices(len(tokens), zipf)
    # spawn (not fork): children must never inherit live TPU/jax state
    ctx = mp.get_context("spawn")
    outq = ctx.Queue()
    start_at = time.time() + max(4.0, n_clients * 0.15)  # spawn lag
    procs = [ctx.Process(
        target=_client_proc,
        args=(host, port, tokens, req_tokens, depth, start_at,
              seconds, i, outq, zipf, pool_idx), daemon=True)
        for i in range(n_clients)]
    for p in procs:
        p.start()
    total = 0
    lats = []
    errors = []
    sent_total = 0
    used_union: set = set()
    try:
        for _ in procs:
            d, ls, err, sent, used = outq.get(timeout=seconds + 300)
            total += d
            lats.extend(ls)
            sent_total += sent
            used_union |= used
            if err:
                errors.append(err)
        for p in procs:
            p.join(timeout=30)
    finally:
        worker.close()
    if errors:
        raise RuntimeError(f"client processes failed: {errors[:3]}")

    lats.sort()
    pt = {
        "max_wait_ms": max_wait_ms,
        "clients": n_clients,
        "req_tokens": req_tokens,
        "pipeline_depth": depth,
        "serve_chain": worker.serve_chain,
        "throughput": round(total / seconds, 1),
        "requests": len(lats),
        "p50_ms": round(_quantile(lats, 0.50) * 1e3, 1),
        "p95_ms": round(_quantile(lats, 0.95) * 1e3, 1),
        "p99_ms": round(_quantile(lats, 0.99) * 1e3, 1),
    }
    pt.update(_mix_fields(zipf, sent_total, used_union))
    return pt


def _mix_fields(zipf, sent_total: int, used_union: set) -> dict:
    """Unique-vs-repeat accounting for the BENCH json (exact: the
    union of every client's sampled indices)."""
    unique = len(used_union)
    out = {
        "tokens_sent": sent_total,
        "tokens_unique": unique,
        "tokens_repeat": max(0, sent_total - unique),
        "repeat_rate": (round(1.0 - unique / sent_total, 4)
                        if sent_total else None),
    }
    if zipf:
        out["zipf_s"], out["zipf_pool"] = zipf[0], zipf[1] or None
    return out


def _fleet_client_proc(endpoints, tokens, req_tokens, start_at, seconds,
                       seed, outq, zipf=None, pool_idx=None):
    """One closed-loop FleetClient PROCESS (own interpreter)."""
    from cap_tpu.fleet import FleetClient

    cl = FleetClient(endpoints, attempt_timeout=30.0,
                     total_deadline=120.0)
    lats = []
    done = 0
    sent = 0
    used = set()
    picker = _zipf_picker(tokens, req_tokens, seed, zipf,
                          pool_idx=pool_idx) if zipf else None
    rng = seed * 7919 + 17
    while time.time() < start_at:
        time.sleep(0.005)
    deadline = time.time() + seconds
    err = None
    try:
        while time.time() < deadline:
            if picker is not None:
                toks, idx = picker()
                used.update(idx.tolist())
            else:
                rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
                lo = rng % max(1, len(tokens) - req_tokens)
                toks = tokens[lo: lo + req_tokens]
                used.update(range(lo, lo + req_tokens))
            sent += len(toks)
            t0 = time.perf_counter()
            out = cl.verify_batch(toks)
            lats.append(time.perf_counter() - t0)
            bad = sum(1 for r in out if isinstance(r, Exception))
            assert bad == 0, f"unexpected failures: {bad}"
            done += len(out)
    except BaseException as e:  # noqa: BLE001 - reported to the parent
        err = f"{type(e).__name__}: {e}"
    finally:
        outq.put((done, lats, err, sent, used))


def _native_drive(endpoints, tokens, req_tokens, seconds, n_clients,
                  depth=32):
    """Drive every endpoint with the NATIVE closed-loop driver
    (cap_bench_drive: pipelined plain CVB1 frames, sent and parsed in
    C threads) — client cost leaves the measurement, so the number is
    the fleet's serve capacity, not the Python client chain's
    (CAP_SERVE_DRIVER=native)."""
    import ctypes
    import threading

    import numpy as np

    from cap_tpu.serve import native_serve

    lib = native_serve.load()
    encoded = [t.encode() for t in tokens]
    blob = np.frombuffer(b"".join(encoded), np.uint8)
    offs = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(e) for e in encoded], out=offs[1:])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    conns_per = max(1, n_clients // max(1, len(endpoints)))
    outs = []

    def drive(host, port):
        out_tokens = np.zeros(1, np.int64)
        out_reqs = np.zeros(1, np.int64)
        lib.cap_bench_drive(            # releases the GIL for the run
            host.encode(), port, blob.ctypes.data_as(u8p),
            offs.ctypes.data_as(i64p), len(encoded), req_tokens,
            depth, seconds, conns_per,
            out_tokens.ctypes.data_as(i64p),
            out_reqs.ctypes.data_as(i64p))
        outs.append((int(out_tokens[0]), int(out_reqs[0])))

    threads = [threading.Thread(target=drive, args=ep, daemon=True)
               for ep in endpoints]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return (sum(o[0] for o in outs), sum(o[1] for o in outs))


def run_fleet_point(n_workers: int, keyset_spec: str, tokens,
                    n_clients: int, req_tokens: int, seconds: float,
                    max_wait_ms: float, target_batch: int,
                    serve_chain=None, vcache=None) -> dict:
    """Throughput of an n-worker fleet under single-owner placement.

    serve_chain: None (inherit the environment) or "python"/"native" —
    workers spawn with CAP_SERVE_NATIVE forced accordingly, for the
    chain A/B the §Round 12 host-saturation comparison needs.
    vcache: None (inherit) or "on"/"off" — the verdict-cache A/B arm
    (CAP_SERVE_VCACHE forced in the workers) the §Round 14
    cached-vs-uncached Zipf comparison needs."""
    import multiprocessing as mp

    from cap_tpu.fleet import WorkerPool

    env_extra = {}
    if serve_chain is not None:
        env_extra["CAP_SERVE_NATIVE"] = \
            "1" if serve_chain == "native" else "0"
    if vcache is not None:
        env_extra["CAP_SERVE_VCACHE"] = "1" if vcache == "on" else "0"
    # CAP_SERVE_TELEMETRY=0: workers run with the observability layer
    # off — isolates the serve chain in the A/B (decision accounting
    # costs the same on both chains and dominates once the native
    # chain is on; PERF.md §Round 12)
    if os.environ.get("CAP_SERVE_TELEMETRY", "1") == "0":
        env_extra["CAP_FLEET_TELEMETRY"] = "0"
    pool = WorkerPool(n_workers, keyset_spec=keyset_spec,
                      target_batch=target_batch, max_wait_ms=max_wait_ms,
                      ping_interval=1.0, env_extra=env_extra)
    try:
        if not pool.wait_all_ready(120.0):
            raise RuntimeError("fleet did not come up")
        endpoints = sorted(pool.endpoints().values())
        chains = pool.serve_chains()
        zipf = _zipf_cfg()
        pool_idx = _zipf_pool_indices(len(tokens), zipf)
        driver = os.environ.get("CAP_SERVE_DRIVER", "python")
        total, lats, errors = 0, [], []
        sent_total = 0
        used_union: set = set()
        if driver == "native":
            # C closed-loop drivers: measures fleet SERVE capacity
            # (no request-latency quantiles — the driver counts, it
            # does not time individual requests)
            total, _n_req = _native_drive(endpoints, tokens,
                                          req_tokens, seconds,
                                          n_clients)
            sent_total = total
        else:
            ctx = mp.get_context("spawn")
            outq = ctx.Queue()
            start_at = time.time() + max(4.0, n_clients * 0.15)
            procs = [ctx.Process(
                target=_fleet_client_proc,
                args=(endpoints, tokens, req_tokens, start_at, seconds,
                      i, outq, zipf, pool_idx), daemon=True)
                for i in range(n_clients)]
            for p in procs:
                p.start()
            for _ in procs:
                d, ls, err, sent, used = outq.get(timeout=seconds + 300)
                total += d
                lats.extend(ls)
                sent_total += sent
                used_union |= used
                if err:
                    errors.append(err)
            for p in procs:
                p.join(timeout=30)
        if errors:
            raise RuntimeError(f"fleet clients failed: {errors[:3]}")
        merged = pool.stats_merged()
        stats = merged["workers"]
        agg = merged["aggregate"]
        served = {wid: (s or {}).get("counters", {}).get(
            "worker.tokens", 0) for wid, s in stats.items()}
    finally:
        pool.close()
    lats.sort()
    pt = {
        "n_workers": n_workers,
        "keyset_spec": keyset_spec,
        "clients": n_clients,
        "req_tokens": req_tokens,
        # what each worker ANNOUNCED on its ready line (ground truth:
        # a native request that fell back shows up as python here)
        "serve_chains": {str(w): c for w, c in sorted(chains.items())},
        # True when the workers' decision fold ran on the NATIVE
        # telemetry plane (detected from plane-only counters in the
        # merged scrape — not from the requested knob, so a silent
        # obs fallback shows up as false in the record)
        "native_obs": any(k.startswith("serve.native.hdr_cache")
                          for k in (agg.get("counters") or {})),
        # verdict-cache arm + exact worker-side cache accounting for
        # this point (merged scrape counters — hit rate of the serve
        # tier, not the drivers')
        "vcache": vcache or "env",
        "cache": {
            "lookups": (agg.get("counters") or {}).get(
                "vcache.lookups", 0),
            "hits": (agg.get("counters") or {}).get("vcache.hits", 0),
            "misses": (agg.get("counters") or {}).get(
                "vcache.misses", 0),
            "evictions": (agg.get("counters") or {}).get(
                "vcache.evictions", 0),
            "dedup_fanout": (agg.get("counters") or {}).get(
                "batcher.dedup_fanout", 0),
            "stale_accepts": (agg.get("counters") or {}).get(
                "vcache.stale_accepts", 0),
        },
        "driver": driver,
        "throughput": round(total / seconds, 1),
        "requests": len(lats),
        "p50_ms": round(_quantile(lats, 0.50) * 1e3, 1),
        "p99_ms": round(_quantile(lats, 0.99) * 1e3, 1),
        # pipeline-occupancy rollup (r22): fleet-wide busy/wall ratio
        # + per-family split from the merged scrape, plus the flush
        # trigger mix — which knob (size/timeout/handoff) actually
        # released each engine dispatch during this point
        "occupancy": agg.get("occupancy"),
        "flush_reasons": {
            k[len("batcher.flush."):]: v
            for k, v in sorted((agg.get("counters") or {}).items())
            if k.startswith("batcher.flush.")},
        "per_worker_tokens": served,
        "placement": {w: list(d) for w, d in
                      pool.placement_map().items()},
        # EXACT fleet-side stage attribution: the workers' mergeable
        # histogram snapshots, bucket-added across the fleet (not an
        # average of per-worker quantiles), plus respawn accounting.
        "telemetry": {
            "stage_latency": {
                name: {"count": int(s["count"]),
                       "p50": round(s["p50"], 6),
                       "p95": round(s["p95"], 6),
                       "p99": round(s["p99"], 6)}
                for name, s in sorted(agg["series"].items())},
            "counters": agg["counters"],
            "respawns": agg["restarts"],
        },
    }
    pt.update(_mix_fields(zipf, sent_total, used_union))
    return pt


def _frontdoor_client_proc(groups, routing, spill, tokens, req_tokens,
                           start_at, seconds, seed, outq, zipf=None,
                           pool_idx=None):
    """One closed-loop FrontDoor driver PROCESS (own interpreter):
    routes over the pool endpoint groups by digest affinity (or rr,
    the control arm) and ships its routing counters back with the
    throughput numbers."""
    from cap_tpu.fleet.frontdoor import FrontDoor

    fd = FrontDoor(groups, routing=routing, spill_factor=spill,
                   client_kw={"attempt_timeout": 30.0,
                              "total_deadline": 120.0})
    lats = []
    done = 0
    sent = 0
    used = set()
    picker = _zipf_picker(tokens, req_tokens, seed, zipf,
                          pool_idx=pool_idx) if zipf else None
    rng = seed * 7919 + 17
    while time.time() < start_at:
        time.sleep(0.005)
    deadline = time.time() + seconds
    err = None
    try:
        while time.time() < deadline:
            if picker is not None:
                toks, idx = picker()
                used.update(idx.tolist())
            else:
                rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
                lo = rng % max(1, len(tokens) - req_tokens)
                toks = tokens[lo: lo + req_tokens]
                used.update(range(lo, lo + req_tokens))
            sent += len(toks)
            t0 = time.perf_counter()
            out = fd.verify_batch(toks)
            lats.append(time.perf_counter() - t0)
            bad = sum(1 for r in out if isinstance(r, Exception))
            assert bad == 0, f"unexpected failures: {bad}"
            done += len(out)
    except BaseException as e:  # noqa: BLE001 - reported to the parent
        err = f"{type(e).__name__}: {e}"
    finally:
        outq.put((done, lats, err, sent, used, fd.counters()))
        fd.close()


def run_frontdoor_point(n_pools: int, pool_workers: int, routing: str,
                        keyset_spec: str, tokens, n_clients: int,
                        req_tokens: int, seconds: float,
                        max_wait_ms: float, target_batch: int,
                        env_extra=None) -> dict:
    """Throughput of an n_pools × pool_workers fleet behind the
    digest-affinity front door (or the rr control arm). Fresh pools
    per point: cache state must NOT leak between routing arms."""
    import multiprocessing as mp

    from cap_tpu import telemetry
    from cap_tpu.fleet import WorkerPool

    pools = [WorkerPool(pool_workers, keyset_spec=keyset_spec,
                        target_batch=target_batch,
                        max_wait_ms=max_wait_ms, ping_interval=1.0,
                        env_extra=dict(env_extra or {}))
             for _ in range(n_pools)]
    try:
        for i, p in enumerate(pools):
            if not p.wait_all_ready(120.0):
                raise RuntimeError(f"pool {i} did not come up")
        groups = [sorted(p.endpoints().values()) for p in pools]
        zipf = _zipf_cfg()
        pool_idx = _zipf_pool_indices(len(tokens), zipf)
        spill = float(os.environ.get("CAP_SERVE_SPILL", "2.0"))
        ctx = mp.get_context("spawn")
        outq = ctx.Queue()
        start_at = time.time() + max(4.0, n_clients * 0.15)
        procs = [ctx.Process(
            target=_frontdoor_client_proc,
            args=(groups, routing, spill, tokens, req_tokens, start_at,
                  seconds, i, outq, zipf, pool_idx), daemon=True)
            for i in range(n_clients)]
        for p in procs:
            p.start()
        total, lats, errors = 0, [], []
        sent_total = 0
        used_union: set = set()
        fd_counters: dict = {}
        for _ in procs:
            d, ls, err, sent, used, ctr = outq.get(
                timeout=seconds + 300)
            total += d
            lats.extend(ls)
            sent_total += sent
            used_union |= used
            for k, v in ctr.items():
                fd_counters[k] = fd_counters.get(k, 0) + v
            if err:
                errors.append(err)
        for p in procs:
            p.join(timeout=30)
        if errors:
            raise RuntimeError(f"frontdoor clients failed: "
                               f"{errors[:3]}")
        merged = telemetry.merge_snapshots(
            [(s or {}).get("snapshot")
             for pool in pools for s in pool.stats().values()])
        agg_counters = merged.get("counters") or {}
    finally:
        for p in pools:
            p.close()
    lats.sort()
    lookups = fd_counters.get("frontdoor.lookups", 0)
    hits = fd_counters.get("frontdoor.affinity_hits", 0)
    pt = {
        "n_pools": n_pools,
        "pool_workers": pool_workers,
        "routing": routing,
        "keyset_spec": keyset_spec,
        "clients": n_clients,
        "req_tokens": req_tokens,
        "throughput": round(total / seconds, 1),
        "requests": len(lats),
        "p50_ms": round(_quantile(lats, 0.50) * 1e3, 1),
        "p99_ms": round(_quantile(lats, 0.99) * 1e3, 1),
        "frontdoor": {
            "lookups": lookups,
            "affinity_hits": hits,
            "affinity_hit_rate": (round(hits / lookups, 4)
                                  if lookups else None),
            "spills": fd_counters.get("frontdoor.spills", 0),
            "reroutes": fd_counters.get("frontdoor.reroutes", 0),
            "fallback_tokens": fd_counters.get(
                "frontdoor.fallback_tokens", 0),
        },
        "cache": {
            "lookups": agg_counters.get("vcache.lookups", 0),
            "hits": agg_counters.get("vcache.hits", 0),
            "misses": agg_counters.get("vcache.misses", 0),
            "evictions": agg_counters.get("vcache.evictions", 0),
            "stale_accepts": agg_counters.get("vcache.stale_accepts",
                                              0),
            "peer_fills": agg_counters.get("vcache.peer_fills", 0),
        },
    }
    pt.update(_mix_fields(_zipf_cfg(), sent_total, used_union))
    return pt


def _materialize_drive_tokens(tokens, zipf, pool_idx, n_out=16384):
    """The gateway arms' SHARED drive corpus. ``cap_bench_drive``
    samples request windows uniformly from its blob with a
    per-connection seed, so pinning the workload across chain arms
    means pinning the BLOB: when the Zipf mix is on, the full token
    sequence is pre-sampled ONCE here in the parent (pinned seed over
    the shared rank→token permutation) and every chain arm's C driver
    replays the identical byte stream — same blob + same conn count →
    frame-for-frame identical traffic on both router chains."""
    if zipf is None:
        return list(tokens)
    import numpy as np

    zs, _pool = zipf
    perm = np.asarray(pool_idx)
    n = len(perm)
    w = np.arange(1, n + 1, dtype=np.float64) ** -zs
    cdf = np.cumsum(w)
    cdf /= cdf[-1]
    seed = int(os.environ.get("CAP_SERVE_ZIPF_SEED", "1234"))
    rng = np.random.RandomState(seed * 7919 + 29)
    idx = perm[np.searchsorted(cdf, rng.random_sample(n_out))]
    return [tokens[i] for i in idx]


def _align_drive_tokens(drive_tokens, n_pools):
    """Owner-align the gateway drive corpus (``CAP_FRONTDOOR_ALIGN``,
    default 1): group the materialized sequence by owning pool — the
    parent-side ring is bit-identical to the router's (pinned by
    test_frontdoor_native's parity tests), so contiguous request
    windows become single-owner. That is the ingress shape ANY
    affinity-aware upstream tier produces (the Python FrontDoor driver
    itself ships per-pool sub-batches), and the shape that exercises
    the native relay's zero-copy splice path; ``=0`` leaves the Zipf
    stream unaligned, so nearly every frame mixes owners and rides the
    re-frame relay path instead. Both chain arms get the SAME corpus
    either way — the A/B stays frame-identical."""
    if os.environ.get("CAP_FRONTDOOR_ALIGN", "1") == "0":
        return drive_tokens
    from cap_tpu.fleet.frontdoor import ConsistentHashRing
    from cap_tpu.serve.vcache import token_digest

    ring = ConsistentHashRing(list(range(n_pools)))
    buckets = [[] for _ in range(n_pools)]
    for t in drive_tokens:
        buckets[ring.primary(token_digest(t))].append(t)
    return [t for b in buckets for t in b]


def _gateway_stats(host, port):
    """One CVB1 STATS round-trip against a gateway process — the
    router-side counter scrape the gateway A/B records (frontdoor.*
    routing counters + frontdoor.native.* relay counters)."""
    import socket

    from cap_tpu.serve import protocol as P

    s = socket.create_connection((host, port), timeout=30)
    try:
        s.settimeout(30)
        P.send_stats_request(s)
        ftype, entries = P.FrameReader(s).recv_frame()
        if ftype != P.T_STATS_RESP or entries[0][0] != 0:
            raise RuntimeError(f"gateway stats failed: {ftype}")
        return json.loads(entries[0][1])
    finally:
        s.close()


def _spawn_gateway(keyset_spec, chain):
    """A deployed router-tier gateway PROCESS: worker_main with a
    ``frontdoor:`` keyset, pinned to the requested router chain
    (``--frontdoor-chain python|native`` — no silent fallback arm
    contamination: a chain mismatch on the ready line is an error)."""
    import subprocess

    p = subprocess.Popen(
        [sys.executable, "-m", "cap_tpu.fleet.worker_main",
         "--keyset", keyset_spec, "--frontdoor-chain", chain,
         "--obs-port", "-1"],
        stdout=subprocess.PIPE, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    line = (p.stdout.readline() or "").strip()
    kv = dict(f.split("=", 1) for f in line.split()[1:] if "=" in f)
    if (not line.startswith("CAP_FLEET_READY")
            or kv.get("frontdoor_chain") != chain):
        p.kill()
        p.wait(timeout=30)
        raise RuntimeError(
            f"gateway chain={chain} did not come up: {line!r}")
    return p, ("127.0.0.1", int(kv["port"]))


def run_gateway_point(n_pools: int, pool_workers: int, chain: str,
                      keyset_spec: str, drive_tokens, n_clients: int,
                      req_tokens: int, seconds: float,
                      max_wait_ms: float, target_batch: int,
                      env_extra=None) -> dict:
    """Wire-speed router-tier arm: the same pools-behind-front-door
    topology as :func:`run_frontdoor_point`, but the router is ONE
    deployed gateway process (worker_main ``--keyset frontdoor:``)
    and the load is the native closed-loop C driver aimed at the
    gateway's front socket — client cost leaves the measurement, so
    the number is the ROUTER TIER's serve capacity, python chain vs
    native relay chain on the frame-identical pinned workload."""
    from cap_tpu import telemetry
    from cap_tpu.fleet import WorkerPool

    pools = [WorkerPool(pool_workers, keyset_spec=keyset_spec,
                        target_batch=target_batch,
                        max_wait_ms=max_wait_ms, ping_interval=1.0,
                        env_extra=dict(env_extra or {}))
             for _ in range(n_pools)]
    gw = None
    try:
        for i, p in enumerate(pools):
            if not p.wait_all_ready(120.0):
                raise RuntimeError(f"pool {i} did not come up")
        spill = os.environ.get("CAP_SERVE_SPILL", "2.0")
        spec = ("frontdoor:" + ";".join(
            "pool=" + "+".join(
                f"{h}:{pt}" for h, pt in sorted(p.endpoints().values()))
            for p in pools) + f";spill={spill}")
        gw, gw_addr = _spawn_gateway(spec, chain)
        total, n_reqs = _native_drive([gw_addr], drive_tokens,
                                      req_tokens, seconds, n_clients)
        st = _gateway_stats(*gw_addr)
        ctr = st.get("counters") or {}
        lookups = ctr.get("frontdoor.lookups", 0)
        hits = ctr.get("frontdoor.affinity_hits", 0)
        misses = ctr.get("frontdoor.affinity_misses", 0)
        # the r21 counting contract, enforced per point: every routed
        # token is a lookup and lands in exactly one bucket — native
        # fast path included (its deltas fold into the same counters)
        if lookups != hits + misses:
            raise RuntimeError(
                f"front-door accounting broke: lookups={lookups} != "
                f"hits={hits} + misses={misses}")
        merged = telemetry.merge_snapshots(
            [(s or {}).get("snapshot")
             for pool in pools for s in pool.stats().values()])
        agg_counters = merged.get("counters") or {}
    finally:
        if gw is not None:
            gw.terminate()
            try:
                gw.wait(timeout=30)
            except Exception:  # noqa: BLE001 - last resort
                gw.kill()
        for p in pools:
            p.close()
    native = {k[len("frontdoor.native."):]: v for k, v in ctr.items()
              if k.startswith("frontdoor.native.")}
    vps = total / seconds
    return {
        "n_pools": n_pools,
        "pool_workers": pool_workers,
        "gateway_chain": chain,
        "keyset_spec": keyset_spec,
        "clients": n_clients,
        "req_tokens": req_tokens,
        "driver": "native",
        "throughput": round(vps, 1),
        "requests": n_reqs,
        "relay_us_per_token": (round(1e6 / vps, 3) if vps else None),
        "frontdoor": {
            "lookups": lookups,
            "affinity_hits": hits,
            "affinity_misses": misses,
            "affinity_hit_rate": (round(hits / lookups, 4)
                                  if lookups else None),
            "spills": ctr.get("frontdoor.spills", 0),
            "reroutes": ctr.get("frontdoor.reroutes", 0),
            "fallback_tokens": ctr.get("frontdoor.fallback_tokens", 0),
            "native_fallbacks": ctr.get("frontdoor.native_fallbacks",
                                        0),
        },
        "native": native,
        "cache": {
            "lookups": agg_counters.get("vcache.lookups", 0),
            "hits": agg_counters.get("vcache.hits", 0),
            "stale_accepts": agg_counters.get("vcache.stale_accepts",
                                              0),
        },
        "tokens_sent": total,
        "drive_corpus": len(drive_tokens),
    }


def _mk_tenant_tokens(iss: str, kid: str, n: int = 128):
    """Stub-verifiable tokens for ONE tenant: a shared header (kid) +
    payload (iss) with n distinct trailing segments, so the batcher's
    dedup can't collapse the load while tenant attribution stays
    per-issuer."""
    import base64 as _b64
    import json as _json

    def b64(obj):
        return _b64.urlsafe_b64encode(
            _json.dumps(obj).encode()).rstrip(b"=").decode()

    hdr = b64({"alg": "ES256", "kid": kid})
    pay = b64({"iss": iss})
    return [f"{hdr}.{pay}.s{i}.ok" for i in range(n)]


def _tenant_driver_proc(endpoints, tokens, req_tokens, start_at,
                        seconds, target_vps, outq):
    """One closed-loop per-tenant driver PROCESS: hammers its tenant's
    token pool, optionally rate-limited to target_vps (the flooding
    driver runs unbounded / at the configured flood rate), and splits
    its outcomes accepted / throttled / rejected so the fairness A/B
    can report the per-tenant vps + p99 view."""
    import time as _t

    from cap_tpu.fleet import FleetClient

    cl = FleetClient(endpoints, attempt_timeout=30.0,
                     total_deadline=120.0)
    lats = []
    ok = thr = rej = 0
    i = 0
    # warmup exclusion (CAP_SERVE_WARMUP_S): latencies sampled only
    # after the cold-start transient (first flushes, bucket prefill)
    # — the steady-state p99 is what the fairness bar describes; the
    # same window applies to every arm, flood and baseline alike
    warmup = float(os.environ.get("CAP_SERVE_WARMUP_S", "0"))
    while _t.time() < start_at:
        _t.sleep(0.005)
    t_start = _t.time()
    measure_from = t_start + warmup
    deadline = t_start + seconds
    sent = 0
    err = None
    try:
        while _t.time() < deadline:
            if target_vps and sent > (_t.time() - t_start) * target_vps:
                _t.sleep(0.002)
                continue
            batch = [tokens[(i + j) % len(tokens)]
                     for j in range(req_tokens)]
            i += req_tokens
            in_window = _t.time() >= measure_from
            t0 = _t.perf_counter()
            out = cl.verify_batch(batch)
            if in_window:
                lats.append(_t.perf_counter() - t0)
            sent += len(batch)
            for r in out:
                if isinstance(r, Exception):
                    if str(r).startswith("ThrottledError"):
                        thr += 1
                    else:
                        rej += 1
                else:
                    ok += 1
    except BaseException as e:  # noqa: BLE001 - reported to the parent
        err = f"{type(e).__name__}: {e}"
    finally:
        outq.put((ok, thr, rej, lats, err))


def run_fairness_point(arm: str, flood_vps: float, keyset_spec: str,
                       n_workers: int, n_victims: int,
                       req_tokens: int, seconds: float,
                       max_wait_ms: float, target_batch: int,
                       with_flood: bool = True) -> dict:
    """One fairness arm: a fleet with (fair) or without (fifo) the
    enforcement plane, a flooding tenant driver next to well-behaved
    drivers, per-tenant vps/p99 split from the drivers AND the exact
    merged worker counters. with_flood=False is the no-flood baseline
    the inflation ratios are computed against."""
    import multiprocessing as mp

    from cap_tpu.fleet import WorkerPool
    from cap_tpu.obs import decision as obs_decision

    env_extra = {"CAP_SERVE_VCACHE": "0"}   # honest scheduling A/B
    if arm == "fair":
        env_extra["CAP_SERVE_FAIR"] = "1"
        env_extra["CAP_SERVE_ADMIT_RATE"] = os.environ.get(
            "CAP_SERVE_FAIR_RATE", "2000")
        burst = os.environ.get("CAP_SERVE_FAIR_BURST")
        if burst:
            env_extra["CAP_SERVE_ADMIT_BURST"] = burst
    autoscale = {"min_workers": n_workers,
                 "max_workers": n_workers + 1,
                 "high_queue_per_worker": float(os.environ.get(
                     "CAP_SERVE_SCALE_WATERMARK", "2048")),
                 "sustain_ticks": 2, "quiet_ticks": 1000,
                 "interval_s": 1.0}
    pool = WorkerPool(n_workers, keyset_spec=keyset_spec,
                      target_batch=target_batch,
                      max_wait_ms=max_wait_ms, ping_interval=0.5,
                      env_extra=env_extra,
                      autoscale=autoscale if arm == "fair" else None)
    try:
        if not pool.wait_all_ready(120.0):
            raise RuntimeError("fairness fleet did not come up")
        endpoints = sorted(pool.endpoints().values())
        quiet_toks = _mk_tenant_tokens(
            "https://tenant-wellbehaved.example", "kw")
        flood_toks = _mk_tenant_tokens(
            "https://tenant-flooding.example", "kf")
        # victim offered load is PINNED (CAP_SERVE_VICTIM_VPS per
        # driver, 0 = closed loop) so both arms and the no-flood
        # baseline see the identical well-behaved demand — that is
        # what makes the p99 inflation ratios comparable.
        victim_vps = float(os.environ.get("CAP_SERVE_VICTIM_VPS",
                                          "0"))
        n_flooders = int(os.environ.get("CAP_SERVE_FLOOD_CLIENTS",
                                        "1"))
        # the flood's batch size may differ from the victims' (a
        # flood of big frames behind small victim requests is the
        # head-of-line shape the FIFO control arm must exhibit)
        flood_req = int(os.environ.get("CAP_SERVE_FLOOD_REQ_TOKENS",
                                       str(req_tokens)))
        ctx = mp.get_context("spawn")
        outq = ctx.Queue()
        floodq = ctx.Queue()
        start_at = time.time() + max(3.0,
                                     (n_victims + n_flooders) * 0.2)
        procs = [ctx.Process(
            target=_tenant_driver_proc,
            args=(endpoints, quiet_toks, req_tokens, start_at,
                  seconds, victim_vps, outq), daemon=True)
            for _ in range(n_victims)]
        if with_flood:
            for _ in range(n_flooders):
                procs.append(ctx.Process(
                    target=_tenant_driver_proc,
                    args=(endpoints, flood_toks, flood_req, start_at,
                          seconds, flood_vps / n_flooders, floodq),
                    daemon=True))
        for p in procs:
            p.start()
        v_ok = v_thr = v_rej = 0
        v_lats = []
        errors = []
        for _ in range(n_victims):
            ok, thr, rej, lats, err = outq.get(timeout=seconds + 300)
            v_ok += ok
            v_thr += thr
            v_rej += rej
            v_lats.extend(lats)
            if err:
                errors.append(err)
        f_ok = f_thr = f_rej = 0
        f_lats = []
        if with_flood:
            for _ in range(n_flooders):
                ok, thr, rej, lats, err = floodq.get(
                    timeout=seconds + 300)
                f_ok += ok
                f_thr += thr
                f_rej += rej
                f_lats.extend(lats)
                if err:
                    errors.append(err)
        for p in procs:
            p.join(timeout=30)
        if errors:
            raise RuntimeError(f"fairness drivers failed: {errors[:3]}")
        merged = pool.stats_merged()
        agg_counters = merged["aggregate"]["counters"]
        tenants = obs_decision.tenant_totals(agg_counters,
                                             surface="serve")
        resize_events = pool.resize_events()
    finally:
        pool.close()
    v_lats.sort()
    f_lats.sort()
    return {
        "arm": arm,
        "with_flood": with_flood,
        "n_workers": n_workers,
        "victims": n_victims,
        "flood_target_vps": flood_vps if with_flood else 0,
        "victim_vps": round(v_ok / seconds, 1),
        "victim_p50_ms": round(_quantile(v_lats, 0.50) * 1e3, 2),
        "victim_p99_ms": round(_quantile(v_lats, 0.99) * 1e3, 2),
        "victim_throttled": v_thr,
        "victim_rejected": v_rej,
        "flood_vps": round(f_ok / seconds, 1),
        "flood_throttled": f_thr,
        "flood_p99_ms": round(_quantile(f_lats, 0.99) * 1e3, 2),
        "admission": {
            "checked": agg_counters.get("admission.checked", 0),
            "admitted": agg_counters.get("admission.admitted", 0),
            "throttled": agg_counters.get("admission.throttled", 0),
            "sheds": agg_counters.get("admission.sheds", 0),
        },
        "resize_events": resize_events,
        "tenants": tenants,
    }


def fairness_main() -> None:
    """Fairness A/B mode (``CAP_SERVE_FLOOD=<tenant_vps>``): a
    flooding tenant driver next to well-behaved drivers, run through
    a FAIR fleet (DRR + admission + autoscaler) and a FIFO control
    fleet, arms interleaved over ``CAP_SERVE_REPS``, plus one
    no-flood baseline per arm. Headlines: ``fairness_vps`` (the
    well-behaved tenant's verified/s under flood on the fair arm —
    bench-trend-tracked) and ``fair_p99_ms`` next to the inflation
    ratios the acceptance bar reads (fair ≤ 2× no-flood while fifo
    inflates)."""
    from cap_tpu import telemetry

    telemetry.enable()
    flood_vps = float(os.environ["CAP_SERVE_FLOOD"])
    n_workers = int(os.environ.get("CAP_SERVE_POOL_WORKERS", 1))
    keyset_spec = os.environ.get("CAP_SERVE_FLEET_KEYSET",
                                 "stub:batch_ms=1,token_us=300")
    n_victims = int(os.environ.get("CAP_SERVE_CLIENTS", 2))
    req_tokens = int(os.environ.get("CAP_SERVE_REQ_TOKENS", 64))
    seconds = float(os.environ.get("CAP_SERVE_SECONDS", 12))
    max_wait_ms = float(os.environ.get("CAP_SERVE_WAITS",
                                       "2").split(",")[0])
    target_batch = int(os.environ.get("CAP_SERVE_TARGET_BATCH", 8192))
    reps = int(os.environ.get("CAP_SERVE_REPS", 2))

    points = []
    baselines = {}
    for arm in ("fair", "fifo"):
        pt = run_fairness_point(arm, flood_vps, keyset_spec,
                                n_workers, n_victims, req_tokens,
                                max(4.0, seconds / 2), max_wait_ms,
                                target_batch, with_flood=False)
        baselines[arm] = pt
        print(f"fairness arm={arm:<5} NO-FLOOD  "
              f"victim_vps={pt['victim_vps']:>9.0f} "
              f"p99={pt['victim_p99_ms']:7.1f}ms", file=sys.stderr)
    for rep in range(reps):
        for arm in ("fair", "fifo"):      # interleaved, same-day arms
            pt = run_fairness_point(arm, flood_vps, keyset_spec,
                                    n_workers, n_victims, req_tokens,
                                    seconds, max_wait_ms,
                                    target_batch)
            pt["rep"] = rep
            points.append(pt)
            print(f"fairness arm={arm:<5} rep={rep} "
                  f"victim_vps={pt['victim_vps']:>9.0f} "
                  f"p99={pt['victim_p99_ms']:7.1f}ms  "
                  f"flood_vps={pt['flood_vps']:>9.0f} "
                  f"flood_throttled={pt['flood_throttled']}  "
                  f"resizes={len(pt['resize_events'])}",
                  file=sys.stderr)

    def _best(arm, key="victim_vps"):
        vals = [p[key] for p in points if p["arm"] == arm]
        return max(vals) if vals else None

    def _p99(arm):
        vals = [p["victim_p99_ms"] for p in points if p["arm"] == arm]
        return min(vals) if vals else None

    fairness_vps = _best("fair")
    fair_p99 = _p99("fair")
    fifo_p99 = _p99("fifo")
    base_fair = baselines["fair"]["victim_p99_ms"] or None
    base_fifo = baselines["fifo"]["victim_p99_ms"] or None
    print(json.dumps({
        "metric": "fairness_victim_verifies_per_sec",
        "value": fairness_vps,
        "unit": "verifies/sec",
        "fairness_vps": fairness_vps,
        "fair_p99_ms": fair_p99,
        "fifo_p99_ms": fifo_p99,
        "noflood_fair_p99_ms": base_fair,
        "noflood_fifo_p99_ms": base_fifo,
        "p99_inflation_fair": (round(fair_p99 / base_fair, 3)
                               if fair_p99 and base_fair else None),
        "p99_inflation_fifo": (round(fifo_p99 / base_fifo, 3)
                               if fifo_p99 and base_fifo else None),
        "fifo_victim_vps": _best("fifo"),
        "flood_target_vps": flood_vps,
        "throttled_total": sum(p["admission"]["throttled"]
                               for p in points),
        "sheds_total": sum(p["admission"]["sheds"] for p in points),
        "resize_events_total": sum(len(p["resize_events"])
                                   for p in points),
        "baselines": baselines,
        "points": points,
    }))


def frontdoor_main() -> None:
    """Multi-pool front-door mode (``CAP_SERVE_POOLS=N``): N fresh
    WorkerPools ("hosts") behind FrontDoor drivers, one run per
    routing arm in ``CAP_SERVE_ROUTING`` (default "affinity,rr"),
    arms INTERLEAVED over ``CAP_SERVE_REPS`` repetitions so same-day
    weather hits both arms equally. Headline:
    ``fleet_affinity_vps`` / ``fleet_rr_vps`` and their ratio — the
    §Round 16 affinity-vs-round-robin A/B (the per-worker verdict
    cache is ON in both arms; only the routing policy differs).

    GATEWAY-CHAIN A/B (``CAP_FRONTDOOR_CHAINS="python,native"``, the
    r21 arms): the same pool topology behind ONE deployed worker_main
    gateway per listed router chain, driven at the front socket by
    the native closed-loop C driver on a frame-identical pinned
    workload (Zipf mix pre-materialized once in the parent — see
    :func:`_materialize_drive_tokens`). ALL arms — routing × chain —
    interleave inside every rep. Headlines: ``fleet_native_vps`` /
    ``fleet_gateway_python_vps``, their ratio, the native arm's
    speedup over the in-driver ``fleet_affinity_vps`` baseline, and
    ``frontdoor_relay_us_per_token``. Set the env to "" to skip the
    gateway arms (routing-only legacy shape)."""
    n_pools = int(os.environ["CAP_SERVE_POOLS"])
    pool_workers = int(os.environ.get("CAP_SERVE_POOL_WORKERS", 1))
    keyset_spec = os.environ.get("CAP_SERVE_FLEET_KEYSET",
                                 "stub:batch_ms=1,token_us=300")
    n_clients = int(os.environ.get("CAP_SERVE_CLIENTS", 4))
    req_tokens = int(os.environ.get("CAP_SERVE_REQ_TOKENS", 64))
    seconds = float(os.environ.get("CAP_SERVE_SECONDS", 12))
    max_wait_ms = float(os.environ.get("CAP_SERVE_WAITS",
                                       "2").split(",")[0])
    target_batch = int(os.environ.get("CAP_SERVE_TARGET_BATCH", 8192))
    routings = [r for r in os.environ.get(
        "CAP_SERVE_ROUTING", "affinity,rr").split(",") if r]
    reps = int(os.environ.get("CAP_SERVE_REPS", 2))
    # Per-worker cache capacity: the fleet-scale regime is token
    # corpus >> one worker's cache (millions of users), which is
    # exactly when routing policy decides whether the fleet caches
    # the corpus ONCE (affinity: each host holds its ring share) or
    # N× with thrash (rr: every host needs everything).
    env_extra = {}
    if os.environ.get("CAP_SERVE_VCACHE_CAP"):
        env_extra["CAP_SERVE_VCACHE_CAP"] = \
            os.environ["CAP_SERVE_VCACHE_CAP"]
    if keyset_spec.startswith("stub"):
        tokens = [f"bench.{i:06d}.ok" for i in range(16384)]
    else:
        from cap_tpu import testing as T

        _, tokens = T.headline_fixtures(16384)

    # r21 gateway-chain arms: one deployed router process per chain,
    # native C drivers at the front. The drive corpus is materialized
    # ONCE here (pinned Zipf seed) so every chain arm replays the
    # identical byte stream.
    chains = [c for c in os.environ.get(
        "CAP_FRONTDOOR_CHAINS", "python,native").split(",") if c]
    zipf = _zipf_cfg()
    drive_tokens = _align_drive_tokens(
        _materialize_drive_tokens(
            tokens, zipf, _zipf_pool_indices(len(tokens), zipf)),
        n_pools)

    points = []
    gw_points = []
    for rep in range(reps):
        for routing in routings:      # interleaved: a,rr,a,rr,…
            pt = run_frontdoor_point(
                n_pools, pool_workers, routing, keyset_spec, tokens,
                n_clients, req_tokens, seconds, max_wait_ms,
                target_batch, env_extra=env_extra)
            pt["rep"] = rep
            points.append(pt)
            fdc = pt["frontdoor"]
            print(f"frontdoor pools={n_pools} routing={routing:<8} "
                  f"rep={rep}  thr={pt['throughput']:>9.0f}/s  "
                  f"p50={pt['p50_ms']:6.1f}ms "
                  f"p99={pt['p99_ms']:7.1f}ms  "
                  f"aff_hit={fdc['affinity_hit_rate']}  "
                  f"vc_hit="
                  f"{pt['cache']['hits']}/{pt['cache']['lookups']}",
                  file=sys.stderr)
        for chain in chains:          # …then gw-py,gw-native, same rep
            pt = run_gateway_point(
                n_pools, pool_workers, chain, keyset_spec,
                drive_tokens, n_clients, req_tokens, seconds,
                max_wait_ms, target_batch, env_extra=env_extra)
            pt["rep"] = rep
            pt["aligned"] = os.environ.get("CAP_FRONTDOOR_ALIGN",
                                           "1") != "0"
            gw_points.append(pt)
            fdc = pt["frontdoor"]
            print(f"frontdoor pools={n_pools} gateway={chain:<7} "
                  f"rep={rep}  thr={pt['throughput']:>9.0f}/s  "
                  f"relay={pt['relay_us_per_token']}us/tok  "
                  f"aff_hit={fdc['affinity_hit_rate']}  "
                  f"relays={pt['native'].get('relays', 0)} "
                  f"splices={pt['native'].get('splices', 0)}",
                  file=sys.stderr)

    def _best(routing):
        vals = [p["throughput"] for p in points
                if p["routing"] == routing]
        return max(vals) if vals else None

    def _gw_best(chain):
        vals = [p["throughput"] for p in gw_points
                if p["gateway_chain"] == chain]
        return max(vals) if vals else None

    affinity_vps = _best("affinity")
    rr_vps = _best("rr")
    native_vps = _gw_best("native")
    gw_python_vps = _gw_best("python")
    stale = (sum(p["cache"]["stale_accepts"] for p in points)
             + sum(p["cache"]["stale_accepts"] for p in gw_points))
    print(json.dumps({
        "metric": "fleet_affinity_verifies_per_sec",
        "value": affinity_vps,
        "unit": "verifies/sec",
        "fleet_affinity_vps": affinity_vps,
        "fleet_rr_vps": rr_vps,
        "affinity_speedup_vs_rr": (round(affinity_vps / rr_vps, 3)
                                   if affinity_vps and rr_vps
                                   else None),
        # r21 router-tier headlines: the native relay gateway vs the
        # python gateway on the identical pinned workload, plus the
        # native arm against the in-driver routing baseline above
        "fleet_native_vps": native_vps,
        "fleet_gateway_python_vps": gw_python_vps,
        "native_speedup_vs_python_gw": (
            round(native_vps / gw_python_vps, 3)
            if native_vps and gw_python_vps else None),
        "native_speedup_vs_affinity": (
            round(native_vps / affinity_vps, 3)
            if native_vps and affinity_vps else None),
        "frontdoor_relay_us_per_token": (
            round(1e6 / native_vps, 3) if native_vps else None),
        "n_pools": n_pools,
        "pool_workers": pool_workers,
        "vcache_cap": env_extra.get("CAP_SERVE_VCACHE_CAP"),
        "stale_accepts_total": stale,
        "points": points,
        "gateway_points": gw_points,
    }))


def fleet_main() -> None:
    from cap_tpu import telemetry

    # Parent-process recorder: pool supervision counters (respawns,
    # crashes, ping latency) land here and ride into the BENCH JSON.
    telemetry.enable()
    sizes = [int(s) for s in
             os.environ["CAP_SERVE_FLEET"].split(",") if s]
    keyset_spec = os.environ.get("CAP_SERVE_FLEET_KEYSET",
                                 "stub:batch_ms=1,token_us=300")
    n_clients = int(os.environ.get("CAP_SERVE_CLIENTS", 8))
    req_tokens = int(os.environ.get("CAP_SERVE_REQ_TOKENS", 64))
    seconds = float(os.environ.get("CAP_SERVE_SECONDS", 12))
    max_wait_ms = float(os.environ.get("CAP_SERVE_WAITS", "2").split(",")[0])
    target_batch = int(os.environ.get("CAP_SERVE_TARGET_BATCH", 8192))
    if keyset_spec.startswith("stub"):
        # constant first segment: stub tokens model real traffic's
        # few-distinct-JOSE-headers shape (decision family attribution
        # caches by header segment; one unique segment per token would
        # be a pathological workload no IdP produces)
        tokens = [f"bench.{i:06d}.ok" for i in range(16384)]
    else:
        from cap_tpu import testing as T

        _, tokens = T.headline_fixtures(16384)

    # serve-chain A/B: run every size once per listed chain (empty →
    # one run inheriting the environment's CAP_SERVE_NATIVE)
    chains = [c for c in os.environ.get(
        "CAP_SERVE_CHAINS", "").split(",") if c] or [None]
    # verdict-cache A/B: CAP_SERVE_VCACHES="on,off" runs every
    # (size, chain) arm once per listed cache state — the §Round 14
    # cached-vs-uncached Zipf headline pair
    vcaches = [v for v in os.environ.get(
        "CAP_SERVE_VCACHES", "").split(",") if v] or [None]
    points = []
    for n in sizes:
        for chain in chains:
            for vc in vcaches:
                pt = run_fleet_point(n, keyset_spec, tokens, n_clients,
                                     req_tokens, seconds, max_wait_ms,
                                     target_batch, serve_chain=chain,
                                     vcache=vc)
                points.append(pt)
                hit_line = ""
                if pt["cache"]["lookups"]:
                    rate = (100.0 * pt["cache"]["hits"]
                            / pt["cache"]["lookups"])
                    hit_line = f"  vc_hit={rate:.1f}%"
                print(f"fleet n={n} chain={chain or 'env'} "
                      f"vc={vc or 'env'}  "
                      f"thr={pt['throughput']:>9.0f}/s  "
                      f"p50={pt['p50_ms']:6.1f}ms "
                      f"p99={pt['p99_ms']:7.1f}ms{hit_line}  "
                      f"per-worker={pt['per_worker_tokens']}",
                      file=sys.stderr)

    best = max(points, key=lambda p: p["throughput"])
    smallest = min(points, key=lambda p: p["n_workers"])
    scaling = (round(best["throughput"] / smallest["throughput"], 3)
               if smallest["throughput"] else None)
    rec = telemetry.active()
    supervision = {
        k: v for k, v in sorted(rec.counters().items())
        if k.startswith("fleet.")
    } if rec is not None else {}
    ping = (rec.summary().get("fleet.ping_s") if rec is not None
            else None)
    # Reason-keyed decision counters across the whole sweep (worker-
    # side, summed from every point's exact merged snapshot) + the SLO
    # objective status over those counters: the fleet BENCH record is
    # self-describing from this round on (tools/bench_trend.py).
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo

    sweep_counters: dict = {}
    for pt in points:
        for k, v in (pt.get("telemetry", {}).get("counters")
                     or {}).items():
            sweep_counters[k] = sweep_counters.get(k, 0) + int(v)
    if rec is not None:
        for k, v in rec.counters().items():
            sweep_counters[k] = sweep_counters.get(k, 0) + int(v)
    try:
        slo_results = [
            {"name": r["name"], "ok": r["ok"], "windows": r["windows"]}
            for r in obs_slo.evaluate_once({"counters": sweep_counters})
        ]
    except Exception as e:  # noqa: BLE001 - advisory field
        slo_results = [{"error": repr(e)}]
    def _chain_best(name):
        vals = [p["throughput"] for p in points
                if set((p.get("serve_chains") or {}).values()) == {name}]
        return max(vals) if vals else None

    native_vps = _chain_best("native")
    python_vps = _chain_best("python")

    # verdict-cache Zipf headline pair: best cache-on vs best
    # cache-off throughput among the Zipf-mix points (None unless the
    # Zipf mode and both arms ran)
    def _vc_best(state):
        vals = [p["throughput"] for p in points
                if p.get("vcache") == state and p.get("zipf_s")]
        return max(vals) if vals else None

    zipf_cached_vps = _vc_best("on")
    zipf_uncached_vps = _vc_best("off")
    # pipeline-occupancy headline (r22): the best point's busy/wall
    # ratio (the workload the throughput headline describes) + its
    # idle-gap p99 — where the microseconds waited while the headline
    # was being set; bench_trend tracks device_occupancy
    best_occ = best.get("occupancy") or {}
    idle_gap = (best.get("telemetry", {}).get("stage_latency")
                or {}).get("device.idle_gap_s") or {}
    print(json.dumps({
        "metric": "serve_fleet_verifies_per_sec",
        "value": best["throughput"],
        "unit": "verifies/sec",
        "p99_request_latency_ms": best["p99_ms"],
        "fleet_scaling_vs_smallest": scaling,
        # chain A/B headline (None unless both chains were run):
        # native-chain best vs python-chain best across the sweep
        "serve_native_vps": native_vps,
        "serve_python_vps": python_vps,
        "chain_speedup_native_vs_python": (
            round(native_vps / python_vps, 3)
            if native_vps and python_vps else None),
        # verdict-cache Zipf headline (None unless CAP_SERVE_ZIPF and
        # CAP_SERVE_VCACHES=on,off both ran): end-to-end vps with the
        # cache tier on vs off on the identical pinned token pool.
        "zipf_cached_vps": zipf_cached_vps,
        "zipf_uncached_vps": zipf_uncached_vps,
        "cache_speedup_on_vs_off": (
            round(zipf_cached_vps / zipf_uncached_vps, 3)
            if zipf_cached_vps and zipf_uncached_vps else None),
        "device_occupancy": (round(best_occ["occupancy"], 4)
                             if best_occ else None),
        "occupancy": best_occ or None,
        "idle_gap_p99_s": idle_gap.get("p99"),
        "flush_reasons": best.get("flush_reasons") or None,
        "placement_model": "single-owner-per-device",
        # Pool-side supervision attribution for the whole sweep:
        # respawn/crash/hung counters + health-ping latency quantiles.
        "supervision_counters": supervision,
        "ping_p99_s": round(ping["p99"], 6) if ping else None,
        "decisions": obs_decision.decision_counters(sweep_counters),
        # per-tenant rollup of the same sweep counters (issuer-hash
        # keyed: tokens / accept / reject mix / vcache hit splits) —
        # the BENCH record shows WHOSE traffic the headline served
        "tenants": obs_decision.tenant_totals(sweep_counters),
        "slo": slo_results,
        "points": points,
    }))


def transport_main() -> None:
    """CAP_SERVE_TRANSPORTS=1: the shm-vs-socket serve A/B and the
    Go-driver loadgen point.

    Emits ``shm_vps`` (closed-loop C drive over the mapped ring
    against a device-stubbed worker — the zero-copy ingest rate) next
    to the interleaved socket arm, and ``go_client_vps`` when a Go
    toolchain exists (``clients/go/captpu/loadgen`` against the same
    worker; null with a note otherwise — this image has no Go).
    """
    import ctypes
    import shutil
    import subprocess

    import numpy as np

    from cap_tpu import telemetry
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.serve import native_serve
    from cap_tpu.serve.worker import VerifyWorker

    telemetry.disable()
    seconds = float(os.environ.get("CAP_SERVE_SECONDS", 5))
    req_tokens = int(os.environ.get("CAP_SERVE_REQ_TOKENS", 64))
    depth = int(os.environ.get("CAP_SERVE_DEPTH", 48))
    n_conns = int(os.environ.get("CAP_SERVE_CLIENTS", 4))
    lib = native_serve.load()
    if not getattr(lib, "cap_shm_ok", False):
        raise RuntimeError("library lacks the shm TU "
                           "(run: make native-build)")
    chain = "native"
    try:
        worker = VerifyWorker(StubKeySet(raw=1), serve_native=True,
                              max_wait_ms=2.0, transport="shm",
                              vcache=False)
        if worker.serve_chain != "native":
            worker.close(deadline_s=5)
            raise RuntimeError("native chain unavailable")
    except Exception:  # noqa: BLE001 - python-chain fallback
        chain = "python"
        worker = VerifyWorker(StubKeySet(raw=1), serve_native=False,
                              max_wait_ms=2.0, transport="shm",
                              vcache=False)
    assert worker.transport == "shm"
    host, port = worker.address
    tokens = [f"bench.{i:06d}.ok" for i in range(8192)]
    encoded = [t.encode() for t in tokens]
    blob = np.frombuffer(b"".join(encoded), np.uint8)
    offs = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(e) for e in encoded], out=offs[1:])
    out_tokens = np.zeros(1, np.int64)
    out_reqs = np.zeros(1, np.int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    shm_dir = os.environ.get("CAP_SHM_DIR") or (
        "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp")

    def drive(arm: str, window_s: float) -> float:
        t0 = time.perf_counter()
        if arm == "shm":
            rc = lib.cap_shm_drive(
                host.encode(), port, shm_dir.encode(),
                blob.ctypes.data_as(u8p), offs.ctypes.data_as(i64p),
                len(encoded), req_tokens, depth, window_s, n_conns,
                1 << 20,
                out_tokens.ctypes.data_as(i64p),
                out_reqs.ctypes.data_as(i64p))
        else:
            rc = lib.cap_bench_drive(
                host.encode(), port, blob.ctypes.data_as(u8p),
                offs.ctypes.data_as(i64p), len(encoded), req_tokens,
                depth, window_s, n_conns,
                out_tokens.ctypes.data_as(i64p),
                out_reqs.ctypes.data_as(i64p))
        elapsed = time.perf_counter() - t0
        if rc != 0 or int(out_tokens[0]) == 0:
            raise RuntimeError(f"{arm} drive failed (rc={rc})")
        return int(out_tokens[0]) / elapsed

    go_point = None
    go_note = None
    try:
        drive("socket", 0.5)        # warmup
        drive("shm", 0.5)
        best = {"socket": 0.0, "shm": 0.0}
        for _ in range(2):          # interleaved arms, best-of-2
            for arm in ("socket", "shm"):
                vps = drive(arm, seconds / 2)
                best[arm] = max(best[arm], vps)
                print(f"transport {arm:6s} chain={chain} "
                      f"vps={vps:>10.0f}", file=sys.stderr)
        go = shutil.which("go")
        if go:
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            out = subprocess.run(
                [go, "run", "./loadgen", "-addr", f"{host}:{port}",
                 "-seconds", str(seconds / 2), "-batch",
                 str(req_tokens), "-conns", str(n_conns),
                 "-transport", "auto"],
                cwd=os.path.join(repo, "clients", "go", "captpu"),
                capture_output=True, text=True, timeout=300)
            if out.returncode == 0:
                go_point = json.loads(out.stdout.strip().splitlines()[-1])
            else:
                go_note = f"loadgen failed: {out.stderr[-500:]}"
        else:
            go_note = ("no Go toolchain on this host — run "
                       "'make go-conformance' + this mode where go "
                       "exists")
    finally:
        worker.close(deadline_s=10)
    print(json.dumps({
        "metric": "shm_verifies_per_sec",
        "value": best["shm"],
        "unit": "verifies/sec",
        "serve_chain": chain,
        "shm_vps": round(best["shm"], 1),
        "socket_vps": round(best["socket"], 1),
        "shm_vs_socket_speedup": (round(best["shm"] / best["socket"],
                                        3) if best["socket"] else None),
        "go_client_vps": (round(go_point["go_client_vps"], 1)
                          if go_point else None),
        "go_client_transport": (go_point or {}).get("transport"),
        "go_note": go_note,
    }))


def main() -> None:
    if os.environ.get("CAP_SERVE_TRANSPORTS"):
        # Transport mode: shm-vs-socket serve A/B + Go-driver loadgen.
        transport_main()
        return
    if os.environ.get("CAP_SERVE_FLOOD"):
        # Fairness mode: flooding-tenant A/B (fair DRR+admission fleet
        # vs FIFO control), per-tenant vps/p99 split + fairness_vps.
        fairness_main()
        return
    if os.environ.get("CAP_SERVE_POOLS"):
        # Multi-pool front-door mode: the affinity-vs-rr routing A/B.
        frontdoor_main()
        return
    if os.environ.get("CAP_SERVE_FLEET"):
        # Fleet mode builds no in-process engine: workers own their
        # devices exclusively (single-owner placement).
        fleet_main()
        return

    from cap_tpu import compile_cache, telemetry
    from cap_tpu._build import build_native

    build_native()
    compile_cache.enable()
    telemetry.enable()               # stage attribution in the JSON

    n_clients = int(os.environ.get("CAP_SERVE_CLIENTS", 32))
    req_tokens = int(os.environ.get("CAP_SERVE_REQ_TOKENS", 64))
    seconds = float(os.environ.get("CAP_SERVE_SECONDS", 12))
    waits = [float(w) for w in
             os.environ.get("CAP_SERVE_WAITS", "1,5,20").split(",")]
    target_batch = int(os.environ.get("CAP_SERVE_TARGET_BATCH", 8192))
    depths = [int(d) for d in
              os.environ.get("CAP_SERVE_DEPTHS", "1,2").split(",")]

    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    jwks, tokens = _fixtures()
    ks = TPUBatchKeySet(jwks)
    # Warm every (family, pad) bucket shape the batcher can flush:
    # coalesced batches pad to powers of two below target_batch.
    sz = 128
    while sz <= 16384:
        ks.verify_batch(tokens[:sz])
        sz *= 2

    points = []
    for w in waits:
        for depth in depths:
            pt = run_point(ks, tokens, w, n_clients, req_tokens,
                           seconds, target_batch, depth=depth)
            points.append(pt)
            print(f"max_wait={w:5.1f}ms depth={depth}  "
                  f"thr={pt['throughput']:>9.0f}/s  "
                  f"p50={pt['p50_ms']:6.1f}ms "
                  f"p95={pt['p95_ms']:7.1f}ms "
                  f"p99={pt['p99_ms']:7.1f}ms  reqs={pt['requests']}",
                  file=sys.stderr)

    best = max(points, key=lambda p: p["throughput"])
    rec = telemetry.active()
    # flush the occupancy plane (r22): the workers ran in-process, so
    # the interval accumulator is ours — publish before reading
    from cap_tpu.obs import occupancy as _occupancy

    _occupancy.publish(rec)
    stage_latency = {
        name: {"count": int(s["count"]), "p50": round(s["p50"], 6),
               "p95": round(s["p95"], 6), "p99": round(s["p99"], 6)}
        for name, s in sorted(rec.summary().items())
    } if rec is not None else {}
    from cap_tpu.obs import decision as obs_decision
    from cap_tpu.obs import slo as obs_slo

    counters = rec.counters() if rec is not None else {}
    try:
        slo_results = [
            {"name": r["name"], "ok": r["ok"], "windows": r["windows"]}
            for r in obs_slo.evaluate_once(
                rec.snapshot() if rec is not None else {})
        ]
    except Exception as e:  # noqa: BLE001 - advisory field
        slo_results = [{"error": repr(e)}]
    print(json.dumps({
        "metric": "serve_verifies_per_sec",
        "value": best["throughput"],
        "unit": "verifies/sec",
        "p99_request_latency_ms": best["p99_ms"],
        # Worker-side stage attribution accumulated over the sweep
        # (batcher fill/dispatch/collect, per-family dispatch.*).
        "telemetry": {"stage_latency": stage_latency},
        # pipeline-occupancy rollup over the whole sweep (r22):
        # busy/wall ratio, per-family split, dispatch count
        "occupancy": _occupancy.occupancy_from_counters(counters),
        # Decision/SLO self-description (cap_tpu.obs), serve surface.
        "decisions": obs_decision.decision_counters(counters),
        # per-tenant rollup (issuer-hash keyed), same counters
        "tenants": obs_decision.tenant_totals(counters),
        "slo": slo_results,
        "points": points,
    }))


if __name__ == "__main__":
    main()
