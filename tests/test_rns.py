"""RNS-Montgomery (MXU) modexp engine: parity vs Python ints and the
CPU oracle, including the adversarial edges (s = 0, 1, n−1; wrong EM;
multi-key gather; mixed key sizes). 1024-bit keys keep CPU compile
time bounded; the 2048-bit path is exercised on TPU by the benchmark
and by tools/rns_proto.py exhaustively."""

import random

import numpy as np
import pytest

from cap_tpu.tpu import limbs as L
from cap_tpu.tpu import rns

rng = random.Random(0xA11CE)


def _rand_modulus(bits):
    p = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
    q = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
    return p * q


@pytest.fixture(scope="module")
def engine():
    k = 65  # 1024-bit keys + spare limb
    ctx = rns.context(1024, k)
    mods = [_rand_modulus(1024), _rand_modulus(1024), _rand_modulus(990)]
    table = rns.RNSKeyTable(ctx, mods)
    return ctx, table, mods, k


def test_modexp_parity_multi_key(engine):
    ctx, table, mods, k = engine
    n_tok = 24
    idx = np.asarray([rng.randrange(len(mods)) for _ in range(n_tok)],
                     np.int32)
    s = [rng.randrange(mods[i]) for i in idx]
    want = [pow(x, 65537, mods[i]) for x, i in zip(s, idx)]
    ok = rns.verify_em_equals(ctx, table, L.ints_to_limbs(s, k),
                              L.ints_to_limbs(want, k), idx)
    assert ok.all()


def test_wrong_em_rejected(engine):
    ctx, table, mods, k = engine
    idx = np.zeros(8, np.int32)
    s = [rng.randrange(mods[0]) for _ in range(8)]
    want = [pow(x, 65537, mods[0]) for x in s]
    # flip one bit / off-by-n / swapped tokens must all fail
    bad = [w ^ 1 for w in want]
    assert not rns.verify_em_equals(
        ctx, table, L.ints_to_limbs(s, k), L.ints_to_limbs(bad, k),
        idx).any()
    rolled = want[1:] + want[:1]
    assert not rns.verify_em_equals(
        ctx, table, L.ints_to_limbs(s, k), L.ints_to_limbs(rolled, k),
        idx).any()


def test_edge_values(engine):
    ctx, table, mods, k = engine
    n = mods[0]
    s = [0, 1, n - 1, n // 2]
    idx = np.zeros(len(s), np.int32)
    want = [pow(x, 65537, n) for x in s]
    ok = rns.verify_em_equals(ctx, table, L.ints_to_limbs(s, k),
                              L.ints_to_limbs(want, k), idx)
    assert ok.all()


def test_keyset_rs256_parity_via_rns(monkeypatch):
    """Force the RNS path through the real RS256 verify stack."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    import hashlib

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding
    from cryptography.hazmat.primitives.asymmetric import rsa as crsa

    from cap_tpu.tpu.rsa import RSAKeyTable, verify_pkcs1v15_batch

    msg = b"rns end-to-end"
    privs = [crsa.generate_private_key(public_exponent=65537, key_size=1024)
             for _ in range(2)]
    table = RSAKeyTable(
        [(p.public_key().public_numbers().n, 65537) for p in privs])
    sigs = [p.sign(msg, padding.PKCS1v15(), hashes.SHA256())
            for p in privs]
    d = hashlib.sha256(msg).digest()
    idx = np.asarray([0, 1, 0, 1], np.int32)
    ok = verify_pkcs1v15_batch(table, sigs * 2, [d] * 4, "sha256", idx)
    assert ok.all()
    tampered = bytearray(sigs[0])
    tampered[7] ^= 0x40
    bad = verify_pkcs1v15_batch(table, [bytes(tampered)], [d], "sha256",
                                np.zeros(1, np.int32))
    assert not bad.any()
    # wrong key row must reject
    cross = verify_pkcs1v15_batch(table, [sigs[0]], [d], "sha256",
                                  np.ones(1, np.int32))
    assert not cross.any()
