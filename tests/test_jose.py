import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu.errors import MalformedTokenError, TokenNotSignedError
from cap_tpu.jwt.jose import b64url_decode, b64url_encode, parse_compact
from cap_tpu import testing as captest
from cap_tpu.jwt import algs


def test_b64url_roundtrip():
    for data in [b"", b"a", b"ab", b"abc", bytes(range(256))]:
        assert b64url_decode(b64url_encode(data)) == data


def test_b64url_rejects_padding_and_junk():
    with pytest.raises(MalformedTokenError):
        b64url_decode("aGk=")  # explicit padding is illegal in JWS segments
    with pytest.raises(MalformedTokenError):
        b64url_decode("a+b/")  # std alphabet not allowed
    with pytest.raises(MalformedTokenError):
        b64url_decode("aaaaa")  # length % 4 == 1 is never valid


def test_parse_compact_valid():
    priv, _ = captest.generate_keys(algs.ES256)
    token = captest.sign_jwt(priv, algs.ES256, {"sub": "x"}, kid="k1")
    parsed = parse_compact(token)
    assert parsed.alg == "ES256"
    assert parsed.kid == "k1"
    assert parsed.claims() == {"sub": "x"}
    assert parsed.signing_input.decode() == token.rsplit(".", 1)[0]


@pytest.mark.parametrize("bad", [
    "", "onlyone", "a.b", "a.b.c.d",
    "!!!.e30.sig", "e30.!!!.c2ln",
])
def test_parse_compact_malformed(bad):
    with pytest.raises(MalformedTokenError):
        parse_compact(bad)


def test_parse_compact_unsigned_rejected():
    # alg=none style token with empty signature segment
    header = b64url_encode(b'{"alg":"none"}')
    payload = b64url_encode(b'{"sub":"x"}')
    with pytest.raises(TokenNotSignedError):
        parse_compact(f"{header}.{payload}.")


def test_parse_compact_header_must_be_object_with_alg():
    payload = b64url_encode(b"{}")
    sig = b64url_encode(b"sig")
    with pytest.raises(MalformedTokenError):
        parse_compact(f"{b64url_encode(b'[1]')}.{payload}.{sig}")
    with pytest.raises(MalformedTokenError):
        parse_compact(f"{b64url_encode(b'{}')}.{payload}.{sig}")
    with pytest.raises(MalformedTokenError):
        parse_compact(f"{b64url_encode(b'not json')}.{payload}.{sig}")
