"""SLH-DSA (FIPS 205) core: codecs, sign/verify roundtrips, JWK
plumbing, and the engine-vs-oracle bit-exactness sweep.

Everything is dependency-free: the host oracle is pure hashlib, the
device engine is the batched Keccak-lane JAX graph, fixtures come
from the deterministic in-repo signer. The ≥1k-per-set parity bar
runs in ``make slhdsa-kat`` (tools/slhdsa_kat.py); here a smaller
randomized sweep keeps tier-1 inside its time budget while covering
the same mutation classes.
"""

import json

import numpy as np
import pytest

from cap_tpu.errors import InvalidJWKSError, InvalidSignatureError
from cap_tpu.jwt import algs
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import parse_jwk, serialize_public_key
from cap_tpu.jwt.verify import key_matches_alg, verify_parsed
from cap_tpu.tpu import slhdsa as S

RNG = np.random.default_rng(0x205)
FAST = "SLH-DSA-SHAKE-128f"
SMALL = "SLH-DSA-SHAKE-128s"


@pytest.fixture(scope="module")
def fast_keys():
    priv, pub = S.keygen(FAST, bytes([90]) * 32)
    priv2, pub2 = S.keygen(FAST, bytes([91]) * 32)
    return (priv, pub), (priv2, pub2)


# ---------------------------------------------------------------------------
# codecs + parameter derivations
# ---------------------------------------------------------------------------

def test_parameter_sizes():
    s = S.PARAMS[SMALL]
    f = S.PARAMS[FAST]
    assert (s.pk_size, s.sig_size) == (32, 7856)
    assert (f.pk_size, f.sig_size) == (32, 17088)
    assert s.wlen == f.wlen == 35


def test_base_2b_msb_first():
    # 0xDE 0xAD = 1101 1110 1010 1101 (MSB-first)
    assert S.base_2b(b"\xde\xad", 4, 4) == [0xD, 0xE, 0xA, 0xD]
    assert S.base_2b(b"\xde\xad", 2, 8) == [3, 1, 3, 2, 2, 2, 3, 1]
    assert S.base_2b(b"\xde\xad", 12, 1) == [0xDEA]
    assert S.base_2b(b"\x80" + b"\x00" * 2, 6, 4) == [32, 0, 0, 0]


def test_wots_digits_checksum():
    p = S.PARAMS[FAST]
    msg = bytes(16)                     # all-zero digits
    digits = S._wots_digits(msg, p)
    assert digits[:32] == [0] * 32
    csum = 32 * 15                      # 480 = 0b1_1110_0000
    assert digits[32:] == [csum >> 8, (csum >> 4) & 15, csum & 15]
    msg = b"\xff" * 16                  # all-15 digits -> csum 0
    assert S._wots_digits(msg, p)[32:] == [0, 0, 0]


def test_digest_split_widths():
    p = S.PARAMS[SMALL]
    digest = bytes(range(p.m))
    md, idx_tree, idx_leaf = S._digest_split(digest, p)
    assert len(md) == (p.k * p.a + 7) // 8 == 21
    assert idx_tree < (1 << (p.h - p.hp))
    assert idx_leaf < (1 << p.hp)


def test_adrs_layout():
    a = S.ADRS()
    a.set_layer(3)
    a.set_tree((1 << 40) + 5)
    a.set_type_and_clear(S._TREE)
    a.set_tree_height(2)
    a.set_tree_index(9)
    b = a.bytes()
    assert b[0:4] == (3).to_bytes(4, "big")
    assert b[4:16] == ((1 << 40) + 5).to_bytes(12, "big")
    assert b[16:20] == (2).to_bytes(4, "big")
    assert b[24:28] == (2).to_bytes(4, "big")
    assert b[28:32] == (9).to_bytes(4, "big")
    a.set_type_and_clear(S._WOTS_HASH)
    assert a.bytes()[20:32] == bytes(12)


# ---------------------------------------------------------------------------
# sign / verify roundtrips (host oracle)
# ---------------------------------------------------------------------------

def test_sign_verify_roundtrip_fast(fast_keys):
    (priv, pub), (_, pub2) = fast_keys
    p = pub.params
    sig = priv.sign(b"roundtrip")
    assert len(sig) == p.sig_size
    assert S.py_verify(pub, sig, b"roundtrip")
    assert not S.py_verify(pub, sig, b"roundtriq")
    assert not S.py_verify(pub, sig[:-1], b"roundtrip")
    assert not S.py_verify(pub, sig + b"\x00", b"roundtrip")
    flip = bytearray(sig)
    flip[3] ^= 0x10
    assert not S.py_verify(pub, bytes(flip), b"roundtrip")
    assert not S.py_verify(pub2, sig, b"roundtrip")
    # deterministic signer: same key, same message, same signature
    assert priv.sign(b"roundtrip") == sig


@pytest.mark.slow
def test_sign_verify_roundtrip_small():
    """128s roundtrip — ~20s of host signing, so it rides the slow
    marker; the pinned KAT file covers 128s in tier-1."""
    priv, pub = S.keygen(SMALL, bytes([92]) * 32)
    sig = priv.sign(b"small-set")
    assert len(sig) == pub.params.sig_size
    assert S.py_verify(pub, sig, b"small-set")
    assert not S.py_verify(pub, sig, b"small-sex")


def test_reject_surface_is_length_plus_root(fast_keys):
    """Every non-length mutation still verifies STRUCTURALLY (no
    parse error is possible) and rejects on the root compare."""
    (priv, pub), _ = fast_keys
    sig = priv.sign(b"m")
    for cut in (0, 1, 100, len(sig) - 1):
        assert not S.py_verify(pub, sig[:cut], b"m")
    for pos in (0, 16, 40, len(sig) // 2, len(sig) - 1):
        b = bytearray(sig)
        b[pos] ^= 0x01
        assert not S.py_verify(pub, bytes(b), b"m"), pos


# ---------------------------------------------------------------------------
# JWK / verify plumbing
# ---------------------------------------------------------------------------

def test_akp_jwk_roundtrip_and_negatives(fast_keys):
    (_, pub), _ = fast_keys
    jwk_dict = serialize_public_key(pub, kid="slh")
    assert jwk_dict["kty"] == "AKP"
    assert jwk_dict["alg"] == FAST
    jwk = parse_jwk(jwk_dict)
    assert jwk.key.pk == pub.pk
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": "SLH-DSA-SHAKE-999",
                   "pub": "AQAB"})
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": FAST})
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": FAST, "pub": "AQAB"})


def test_key_matches_alg_slhdsa(fast_keys):
    (_, pub), _ = fast_keys
    assert key_matches_alg(pub, algs.SLHDSA128F)
    assert not key_matches_alg(pub, algs.SLHDSA128S)
    assert not key_matches_alg(pub, algs.MLDSA44)
    assert not key_matches_alg(pub, algs.ES256)
    assert algs.SLHDSA128S in algs.SUPPORTED_ALGORITHMS
    assert algs.SLHDSA128F in algs.SUPPORTED_ALGORITHMS
    assert algs.SLHDSA128F not in algs.HASH_FOR_ALG
    assert algs.SLHDSA128F in algs.PQ_ALGORITHMS


def test_verify_parsed_slhdsa(fast_keys):
    from cap_tpu.jwt.jose import parse_jws

    (priv, pub), _ = fast_keys
    h = b64url_encode(json.dumps({"alg": FAST}).encode())
    pl = b64url_encode(json.dumps({"sub": "x"}).encode())
    si = (h + "." + pl).encode()
    tok = h + "." + pl + "." + b64url_encode(priv.sign(si))
    parsed = parse_jws(tok)
    verify_parsed(parsed, pub)          # must not raise
    bad = parse_jws(tok[:-6] + ("AAAAAA" if not tok.endswith("AAAAAA")
                                else "BBBBBB"))
    with pytest.raises(InvalidSignatureError):
        verify_parsed(bad, pub)


def test_decision_family_for_slhdsa():
    from cap_tpu.obs import decision

    assert decision.family_for_alg(SMALL) == "slhdsa128s"
    assert decision.family_for_alg(FAST) == "slhdsa128f"
    for fam in ("slhdsa128s", "slhdsa128f"):
        assert fam in decision.FAMILIES
    # registry order contract: the native plane indexes by position
    assert decision.FAMILIES[-2:] == ("other", "unknown")


# ---------------------------------------------------------------------------
# engine vs oracle parity (the tier-1-sized sweep)
# ---------------------------------------------------------------------------

def _mutate(sig: bytes, msg: bytes, i: int, p):
    mode = i % 8
    if mode in (0, 1, 2):
        return sig, msg
    if mode == 3:                       # R flip
        b = bytearray(sig)
        b[i % p.n] ^= 1 << (i % 8)
        return bytes(b), msg
    if mode == 4:                       # FORS region
        b = bytearray(sig)
        b[p.n + (i * 131) % (p.k * (1 + p.a) * p.n)] ^= 0x20
        return bytes(b), msg
    if mode == 5:                       # wrong length
        return (sig[:-1] if i % 2 else sig + b"\x00"), msg
    if mode == 6:                       # hypertree
        b = bytearray(sig)
        b[-(1 + (i * 53) % 512)] ^= 0xFF
        return bytes(b), msg
    return sig, msg + b"!"


def test_engine_oracle_parity_fast(fast_keys):
    (priv, pub), (priv2, pub2) = fast_keys
    p = pub.params
    pubs = [pub, pub2]
    table = S.SLHDSAKeyTable(FAST, pubs)
    base = []
    for i in range(4):
        msg = f"par-{i}".encode()
        base.append(([priv, priv2][i % 2].sign(msg), msg, i % 2))
    n = 64
    sigs, msgs, rows = [], [], []
    for i in range(n):
        sig, msg, row = base[i % 4]
        sig, msg = _mutate(sig, msg, i, p)
        sigs.append(sig)
        msgs.append(msg)
        rows.append(row)
    # batches of 16: the pad-16 graph is the shape every other SLH
    # test and the serve path compile, so this sweep adds no compiles
    got = np.concatenate([
        S.verify_slhdsa_batch(table, sigs[lo: lo + 16],
                              msgs[lo: lo + 16],
                              np.asarray(rows[lo: lo + 16], np.int32))
        for lo in range(0, n, 16)])
    want = np.array([S.py_verify(pubs[rows[i]], sigs[i], msgs[i])
                     for i in range(n)])
    mism = np.nonzero(got[:n] != want)[0]
    assert len(mism) == 0, f"verdict mismatch at {mism[:10]}"
    assert 0 < int(want.sum()) < n


def test_engine_matches_kat_small_set():
    """128s engine parity WITHOUT host signing: the pinned KAT file
    supplies the signatures (tier-1 cannot afford 128s signs)."""
    import os

    from cap_tpu.jwt.jose import b64url_decode

    kat_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "slhdsa_kat.json")
    with open(kat_path) as f:
        kat = json.load(f)
    vecs = [v for v in kat["vectors"] if v["alg"] == SMALL]
    assert vecs
    key = parse_jwk([k for k in kat["keys"]["keys"]
                     if k["alg"] == SMALL][0]).key
    table = S.SLHDSAKeyTable(SMALL, [key])
    sigs = [b64url_decode(v["signature_b64"]) for v in vecs]
    msgs = [b64url_decode(v["message_b64"]) for v in vecs]
    got = S.verify_slhdsa_batch(table, sigs, msgs,
                                np.zeros(len(vecs), np.int32))
    for i, v in enumerate(vecs):
        assert bool(got[i]) == v["testPassed"], v["name"]


# ---------------------------------------------------------------------------
# official ACVP cross-check (skip-if-offline; the ML-DSA pattern)
# ---------------------------------------------------------------------------

_ACVP_SIGVER_URL = ("https://raw.githubusercontent.com/usnistgov/"
                    "ACVP-Server/master/gen-val/json-files/"
                    "SLH-DSA-sigVer-FIPS205/internalProjection.json")


def _fetch_acvp_sigver():
    import urllib.request

    try:
        with urllib.request.urlopen(_ACVP_SIGVER_URL,
                                    timeout=15) as r:
            return json.load(r)
    except Exception as e:  # noqa: BLE001 - offline / proxy / DNS
        pytest.skip(f"NIST ACVP vectors unreachable (offline host): "
                    f"{type(e).__name__}")


@pytest.mark.slow
def test_acvp_official_sigver_crosscheck():
    """Pure-mode (external interface, empty context) official ACVP
    SLH-DSA sigVer cases through py_verify — the provenance
    cross-check for the pinned KAT file on a networked host."""
    doc = _fetch_acvp_sigver()
    checked = {}
    for group in doc.get("testGroups", []):
        pset = group.get("parameterSet")
        if pset not in S.PARAMS:
            continue
        if group.get("signatureInterface") == "internal":
            continue
        if group.get("preHash") not in (None, "pure"):
            continue
        for case in group.get("tests", []):
            ctx = case.get("context") or group.get("context") or ""
            if ctx:
                continue
            pk = bytes.fromhex(case.get("pk") or group.get("pk"))
            msg = bytes.fromhex(case["message"])
            sig = bytes.fromhex(case["signature"])
            try:
                pub = S.SLHDSAPublicKey(pset, pk)
                got = S.py_verify(pub, sig, msg)
            except ValueError:
                got = False
            want = bool(case["testPassed"])
            assert got == want, (
                f"{pset} tcId={case.get('tcId')}: py_verify={got}, "
                f"NIST testPassed={want}")
            checked[pset] = checked.get(pset, 0) + 1
    assert checked and all(v > 0 for v in checked.values()), (
        f"no pure-mode cases found: {checked} — ACVP file shape "
        "changed? update the filter")
