"""Runnable-documentation tier (reference test strategy §4 pattern 5:
Example* functions double as docs and smoke tests — here the example
apps run headless against the in-process IdP)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *flags):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script),
         "--demo", *flags],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, (script, flags, r.stdout[-1500:],
                               r.stderr[-1500:])
    return r.stdout


@pytest.mark.parametrize("flags", [(), ("--pkce",), ("--implicit",)])
def test_cli_example_flows(flags):
    out = _run("cli.py", *flags)
    assert "Login successful" in out or "token" in out.lower()


def test_spa_example_flow():
    out = _run("spa.py")
    assert '"iss"' in out or "success" in out.lower()
