"""Runnable documentation — the reference's ``Example*`` test pattern
(docs_test.go:13-79, oidc/docs_test.go:13-332, jwt/docs_test.go:14-102,
oidc/callback/docs_test.go:12-216).

Each test IS the documentation: the bodies are the exact snippets shown
in README.md and the per-package READMEs, kept working by CI. Read them
top to bottom as the user journey: verify a JWT → run an OIDC flow →
serve a callback → switch the hot path to the device engine.
"""


def test_example_readme_quickstart():
    """README.md Quickstart: sign and validate one JWT."""
    from cap_tpu import testing as captest
    from cap_tpu.jwt import Expected, StaticKeySet, Validator

    priv, pub = captest.generate_keys("ES256")
    token = captest.sign_jwt(priv, "ES256", captest.default_claims())
    claims = Validator(StaticKeySet([pub])).validate(
        token, Expected(issuer="https://example.com/",
                        signing_algorithms=["ES256"]))
    assert claims["iss"] == "https://example.com/"


def test_example_jwt_discovery_keyset():
    """cap_tpu/jwt/README.md: verify against an IdP's published JWKS
    via OIDC discovery (reference: jwt/docs_test.go:14-45)."""
    from cap_tpu import testing as captest
    from cap_tpu.jwt import (
        Expected,
        Validator,
        new_oidc_discovery_keyset,
    )
    from cap_tpu.oidc.testing import TestProvider

    with TestProvider() as idp:
        priv, pub, alg, kid = idp.signing_keys()
        token = captest.sign_jwt(
            priv, alg, captest.default_claims(issuer=idp.issuer()),
            kid=kid)

        keyset = new_oidc_discovery_keyset(
            idp.issuer(), issuer_ca_pem=idp.ca_cert())
        claims = Validator(keyset).validate(
            token, Expected(issuer=idp.issuer(),
                            signing_algorithms=[alg]))
        assert claims["iss"] == idp.issuer()


def test_example_oidc_code_flow():
    """cap_tpu/oidc/README.md: the full authorization-code flow
    (reference: oidc/docs_test.go:13-76)."""
    from cap_tpu.oidc import Config, Provider, Request
    from cap_tpu.oidc.testing import TestProvider

    redirect = "https://app.example.com/callback"
    with TestProvider() as idp:
        config = Config(
            issuer=idp.issuer(),
            client_id=idp.client_id,
            client_secret=idp.client_secret,
            supported_signing_algs=["ES256"],
            allowed_redirect_urls=[redirect],
            provider_ca=idp.ca_cert(),
        )
        provider = Provider(config)

        request = Request(120, redirect)
        url = provider.auth_url(request)      # send the user here
        assert url.startswith(idp.issuer())

        # ... the user authenticates; the IdP redirects back with
        # state + code; the app exchanges them:
        idp.set_expected_auth_nonce(request.nonce())
        token = provider.exchange(request, request.state(),
                                  idp.expected_auth_code)
        assert token.id_token().claims()["nonce"] == request.nonce()

        userinfo = provider.userinfo(token.static_token_source(),
                                     idp.replay_subject)
        assert userinfo["sub"] == idp.replay_subject


def test_example_callback_handler():
    """oidc/callback README usage: wire the auth-code WSGI handler
    (reference: oidc/callback/docs_test.go:12-116)."""
    from wsgiref.util import setup_testing_defaults

    from cap_tpu.oidc import Config, Provider, Request
    from cap_tpu.oidc.callback import SingleRequestReader, auth_code
    from cap_tpu.oidc.testing import TestProvider

    redirect = "https://app.example.com/callback"
    with TestProvider() as idp:
        provider = Provider(Config(
            issuer=idp.issuer(), client_id=idp.client_id,
            client_secret=idp.client_secret,
            supported_signing_algs=["ES256"],
            allowed_redirect_urls=[redirect],
            provider_ca=idp.ca_cert()))
        request = Request(120, redirect)
        idp.set_expected_auth_nonce(request.nonce())

        seen = {}

        def on_success(state, token, environ):
            seen["token"] = token
            return 200, [("Content-Type", "text/plain")], "welcome"

        def on_error(state, error_response, err, environ):
            return 401, [("Content-Type", "text/plain")], "denied"

        handler = auth_code(provider, SingleRequestReader(request),
                            on_success, on_error)

        environ = {"QUERY_STRING":
                   f"state={request.state()}"
                   f"&code={idp.expected_auth_code}"}
        setup_testing_defaults(environ)
        status = {}
        body = handler(environ,
                       lambda s, h, exc_info=None: status.update(s=s))
        assert status["s"].startswith("200")
        assert b"welcome" in b"".join(body)
        assert seen["token"].id_token()


def test_example_tpu_batch_keyset():
    """README hot path: the same KeySet seam, batched on the device
    engine — per-token verdicts, rejections included."""
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    priv, pub = captest.generate_keys("ES256")
    keyset = TPUBatchKeySet([JWK(pub, kid="kid-1")])
    good = captest.sign_jwt(priv, "ES256", captest.default_claims(),
                            kid="kid-1")
    results = keyset.verify_batch([good, "not-a-jwt"])
    assert results[0]["iss"] == "https://example.com/"
    assert isinstance(results[1], Exception)


def test_readme_quickstart_snippet_is_literal():
    """The README's Quickstart block, EXTRACTED from README.md and
    executed verbatim — the snippet shown to users cannot drift from
    the test that keeps it working (reference: docs_test.go:13-79
    keeps its examples in the compiled test file for the same
    reason)."""
    import pathlib
    import re

    md = (pathlib.Path(__file__).resolve().parent.parent
          / "README.md").read_text()
    m = re.search(r"## Quickstart\n\n```python\n(.*?)```", md, re.S)
    assert m, "README.md lost its Quickstart python block"
    ns: dict = {}
    exec(compile(m.group(1), "README.md#quickstart", "exec"), ns)
    assert ns["claims"]["iss"] == "https://example.com/"


def test_example_fleet_serving():
    """docs/SERVE.md: spawn a supervised 2-worker fleet and route
    through the failover client (stub engine — the same example with
    ``keyset_spec="jwks:..."`` and a StaticKeySet fallback is the
    production shape)."""
    from cap_tpu.fleet import FleetClient, WorkerPool
    from cap_tpu.fleet.worker_main import StubKeySet

    with WorkerPool(2, keyset_spec="stub") as pool:
        assert pool.wait_all_ready(30)
        client = FleetClient(pool, fallback=StubKeySet())
        res = client.verify_batch(["alice.ok", "mallory.bad"])
        assert res[0] == {"sub": "alice.ok"}
        assert isinstance(res[1], Exception)
