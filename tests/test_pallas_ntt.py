"""Fused Pallas NTT/INTT kernel parity (interpret mode on CPU).

The kernel must be bit-identical to BOTH the int64 numpy references
(``ntt_ref``/``intt_ref``) and the stagewise jnp graph it replaces —
the ``pallas_madd`` numerical contract, applied to the PQ transform.
"""

import numpy as np
import pytest

from cap_tpu.tpu import ntt as NTT
from cap_tpu.tpu import pallas_ntt as PN

RNG = np.random.default_rng(0x173)


def _lanes(shape):
    a = RNG.integers(0, NTT.Q, shape, dtype=np.int64)
    return a


def test_forward_matches_refs():
    import jax.numpy as jnp

    a = _lanes((3, 4, 256))
    a[0, 0, :4] = [0, NTT.Q - 1, 1, NTT.Q - 2]     # edge values
    x = jnp.asarray(a.astype(np.uint32))
    fused = np.asarray(PN.ntt_fused(x, interpret=True))
    assert (fused.astype(np.int64) == NTT.ntt_ref(a)).all()


def test_inverse_matches_refs_and_roundtrips():
    import jax.numpy as jnp

    a = _lanes((5, 256))
    x = jnp.asarray(a.astype(np.uint32))
    f = PN.ntt_fused(x, interpret=True)
    assert (np.asarray(PN.intt_fused(f, interpret=True))
            .astype(np.int64) == a).all()
    assert (np.asarray(PN.intt_fused(x, interpret=True))
            .astype(np.int64) == NTT.intt_ref(a)).all()


def test_matches_stagewise_jnp_graph(monkeypatch):
    """Kernel vs the jnp path it replaces, bit for bit — with the
    dispatch gate forced OFF so NTT.ntt runs the stagewise graph."""
    import jax.numpy as jnp

    monkeypatch.setenv("CAP_TPU_PALLAS_NTT", "0")
    a = _lanes((2, 7, 256))
    x = jnp.asarray(a.astype(np.uint32))
    assert (np.asarray(PN.ntt_fused(x, interpret=True))
            == np.asarray(NTT.ntt(x))).all()
    assert (np.asarray(PN.intt_fused(x, interpret=True))
            == np.asarray(NTT.intt(x))).all()


def test_row_padding_is_transparent():
    """Row counts off the tile boundary (1 row, tile+1 rows) pad and
    unpad without contaminating results."""
    import jax.numpy as jnp

    a = _lanes((1, 256))
    x = jnp.asarray(a.astype(np.uint32))
    assert (np.asarray(PN.ntt_fused(x, interpret=True))
            .astype(np.int64) == NTT.ntt_ref(a)).all()


def test_dispatch_gate(monkeypatch):
    """NTT.ntt routes to the fused kernel when enabled, and the env
    override wins over the backend default."""
    import jax

    monkeypatch.setenv("CAP_TPU_PALLAS_NTT", "1")
    assert PN.enabled()
    monkeypatch.setenv("CAP_TPU_PALLAS_NTT", "0")
    assert not PN.enabled()
    monkeypatch.delenv("CAP_TPU_PALLAS_NTT")
    assert PN.enabled() == (jax.default_backend() == "tpu")


def test_gated_dispatch_bit_equal(monkeypatch):
    """With the gate ON (forced, interpret under the hood on CPU),
    the public NTT entry points stay bit-identical to the refs."""
    import jax.numpy as jnp

    a = _lanes((2, 256))
    x = jnp.asarray(a.astype(np.uint32))
    monkeypatch.setenv("CAP_TPU_PALLAS_NTT", "0")
    want_f = np.asarray(NTT.ntt(x))
    want_i = np.asarray(NTT.intt(x))
    monkeypatch.setenv("CAP_TPU_PALLAS_NTT", "1")
    assert (np.asarray(NTT.ntt(x)) == want_f).all()
    assert (np.asarray(NTT.intt(x)) == want_i).all()
