"""Two-tenant flood chaos: the fleet-level tenant attribution pins
(ISSUE 14 acceptance): a flooding tenant breaches ITS per-tenant SLO
rule while the quiet tenant's stays green, the victim worker's
postmortem carries per-tenant counters through kill -9, the capstat
ledger renders the fleet view, and zero raw issuer strings appear on
any exposed surface — on BOTH serve chains.
"""

import base64
import hashlib
import json
import signal
import threading
import time
import urllib.request

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, WorkerPool
from cap_tpu.fleet.chaos import kill9
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.obs import decision, postmortem as obs_postmortem, slo
from tools import capstat

pytestmark = pytest.mark.chaos

HARD_TIMEOUT_S = 120

ISS_QUIET = "https://tenant-quiet.example"
ISS_FLOOD = "https://tenant-flood.example"
H_QUIET = hashlib.sha256(ISS_QUIET.encode()).hexdigest()[:12]
H_FLOOD = hashlib.sha256(ISS_FLOOD.encode()).hexdigest()[:12]


@pytest.fixture(autouse=True)
def _hard_timeout():
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"chaos test exceeded hard {HARD_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _b64(obj) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(obj).encode()).rstrip(b"=").decode()


def _token(iss: str, kid: str, suffix: str) -> str:
    return (_b64({"alg": "ES256", "kid": kid}) + "."
            + _b64({"iss": iss}) + "." + suffix)


QUIET_TOK = _token(ISS_QUIET, "kq", "ok")
FLOOD_TOK = _token(ISS_FLOOD, "kf", "bad")


@pytest.fixture(params=["python", "native"])
def fleet(request):
    native = request.param == "native"
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=40",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0,
                      env_extra={"CAP_SERVE_NATIVE":
                                 "1" if native else "0"})
    assert pool.wait_all_ready(30), "fleet did not come up"
    chains = set(pool.serve_chains().values())
    if native and chains != {"native"}:
        pool.close()
        pytest.skip(f"native chain unavailable (workers ran {chains})")
    assert native or chains == {"python"}, chains
    yield pool
    pool.close()


def _merged_worker_counters(pool):
    snaps = []
    for _wid, (host, port) in sorted(pool.obs_endpoints().items()):
        snaps.append(capstat.scrape(f"{host}:{port}")["snapshot"])
    return telemetry.merge_snapshots(snaps)


def test_two_tenant_flood_kill9_postmortem_and_slo(fleet):
    """The acceptance scenario: a flooding tenant (all rejects, 8× the
    quiet tenant's traffic) under sustained load, kill -9 landing on a
    worker mid-flood. Zero wrong verdicts; the flooding tenant's
    burn-rate rule breaches and is visible in ``capstat --tenants``
    AND the victim's postmortem; the quiet tenant's rule stays green;
    zero raw issuer strings on any exposed surface."""
    telemetry.enable()
    telemetry.active().reset()
    cl = FleetClient(fleet, fallback=StubKeySet(), attempt_timeout=2.0,
                     total_deadline=30.0)
    # first wave: both tenants reach both workers, then wait for a
    # postmortem CHECKPOINT carrying the per-tenant counters (pool
    # default interval 1 s) so the kill -9 document must include them
    for _ in range(4):
        assert len(cl.verify_batch([QUIET_TOK] * 2)) == 2
        assert len(cl.verify_batch([FLOOD_TOK] * 4)) == 4
    victim = fleet.pid(0)
    pm_path = fleet.postmortem_path(0)
    assert pm_path, "pool did not assign a postmortem path"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        doc = obs_postmortem.read_postmortem(pm_path)
        if doc and decision.tenant_totals(
                doc.get("snapshot", {}).get("counters") or {}):
            break
        time.sleep(0.1)
    # sustained flood, kill -9 landing mid-batch
    batches = ([[QUIET_TOK] * 4] * 4) + ([[FLOOD_TOK] * 8] * 16)
    results = {}

    def submit(i):
        results[i] = cl.verify_batch(batches[i])

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(len(batches))]
    for t in threads:
        t.start()
    time.sleep(0.05)
    kill9(victim)        # lands mid-flood (40 ms simulated batches)
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "submission thread wedged"
    # zero wrong verdicts / zero lost submissions, flood included
    for i, toks in enumerate(batches):
        assert len(results[i]) == len(toks)
        for tok, r in zip(toks, results[i]):
            if tok.endswith(".ok"):
                assert not isinstance(r, Exception), \
                    f"WRONG verdict for quiet tenant: {r!r}"
            else:
                assert isinstance(r, Exception), \
                    "WRONG verdict for flood tenant: accepted"
    # respawn converges
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if fleet.state(0) == "ready" and fleet.pid(0) != victim:
            break
        time.sleep(0.1)
    assert fleet.state(0) == "ready" and fleet.pid(0) != victim

    # victim's postmortem carries per-tenant counters through kill -9
    doc = fleet.postmortem(0)
    assert doc is not None, "no postmortem collected after kill -9"
    pm_counters = (doc.get("snapshot") or {}).get("counters") or {}
    pm_tenants = decision.tenant_totals(pm_counters)
    assert pm_tenants, "postmortem lost the per-tenant counters"
    # the victim served SOME of the flood before dying (router spread
    # both workers): its document attributes that traffic by tenant
    assert any(row.get("tokens") for row in pm_tenants.values())
    rendered = obs_postmortem.render_postmortem(doc)
    assert "tenants (" in rendered
    # raw postmortem JSON: no issuer material
    blob = json.dumps(doc)
    for needle in (ISS_QUIET, ISS_FLOOD, "tenant-quiet",
                   "tenant-flood", "://"):
        assert needle not in blob, f"{needle!r} leaked into postmortem"

    # fleet view: merged worker scrape → ledger + per-tenant SLO
    merged = _merged_worker_counters(fleet)
    counters = merged.get("counters") or {}
    assert counters.get(f"decision.serve.tenant.{H_FLOOD}.reject", 0) \
        > 0
    assert counters.get(f"decision.serve.tenant.{H_QUIET}.accept", 0) \
        > 0
    look = counters.get("tenant.lookups", 0)
    assert look == counters.get("tenant.attributed", 0) \
        + counters.get("tenant.overflow", 0)
    states = {}
    for r in slo.evaluate_once(merged):
        if r["name"].startswith("tenant_reject_ratio["):
            states[r.get("tenant")] = r["ok"]
    assert states.get(H_FLOOD) is False, \
        "flooding tenant's burn-rate rule did not breach"
    assert states.get(H_QUIET) is True, \
        "quiet tenant's rule is not green"
    ledger = capstat.render_tenants(merged)
    assert H_FLOOD in ledger and "BREACH" in ledger
    assert H_QUIET in ledger
    assert "tenant-quiet" not in ledger and "://" not in ledger

    # pool-side rollup + router-side tenant fold see the same tenants
    pool_tenants = fleet.tenant_totals()
    assert pool_tenants.get(H_FLOOD, {}).get("reject", 0) > 0
    router_snap = cl.snapshot()
    assert H_FLOOD in (router_snap.get("tenants") or {}), \
        "router snapshot lost its tenant fold"

    # every exposed HTTP surface (the /tenants endpoint included):
    # zero raw issuers, and /tenants serves the hashed rollup
    for _wid, (host, port) in sorted(fleet.obs_endpoints().items()):
        for path in ("/metrics", "/snapshot", "/decisions",
                     "/tenants"):
            body = urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=5) \
                .read().decode()
            for needle in (ISS_QUIET, ISS_FLOOD, "://"):
                assert needle not in body, \
                    f"{needle!r} leaked into {path}"
            if path == "/tenants":
                doc = json.loads(body)
                assert doc["lookups"] == doc["attributed"] \
                    + doc["overflow"]
    telemetry.disable()


ISS_ADM_QUIET = "https://adm-chaos-quiet.example"
ISS_ADM_FLOOD = "https://adm-chaos-flood.example"
H_ADM_QUIET = hashlib.sha256(ISS_ADM_QUIET.encode()).hexdigest()[:12]
H_ADM_FLOOD = hashlib.sha256(ISS_ADM_FLOOD.encode()).hexdigest()[:12]
ADM_QUIET_TOK = _token(ISS_ADM_QUIET, "acq", "ok")
ADM_FLOOD_TOK = _token(ISS_ADM_FLOOD, "acf", "ok")


@pytest.fixture(params=["python", "native"])
def adm_fleet(request):
    """Two-worker fleet with the r20 enforcement plane armed: DRR
    fair scheduling + per-tenant token buckets (rate sized so the
    quiet tenant never trips while the flooder must)."""
    native = request.param == "native"
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=10",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0,
                      env_extra={"CAP_SERVE_NATIVE":
                                 "1" if native else "0",
                                 "CAP_SERVE_FAIR": "1",
                                 "CAP_SERVE_ADMIT_RATE": "300",
                                 "CAP_SERVE_ADMIT_BURST": "150"})
    assert pool.wait_all_ready(30), "admission fleet did not come up"
    chains = set(pool.serve_chains().values())
    if native and chains != {"native"}:
        pool.close()
        pytest.skip(f"native chain unavailable (workers ran {chains})")
    assert native or chains == {"python"}, chains
    yield pool
    pool.close()


def test_admission_flood_kill9_quiet_slo_and_resize(adm_fleet):
    """ROADMAP #1 *Done* bar (r20 enforcement): a sustained flooding
    tenant with kill -9 landing mid-flood cannot push the well-behaved
    tenant past its SLO — the flooder is throttled (breaching only ITS
    burn-rate rules), every ADMITTED verdict is right and none is
    lost, and the pool's resize events are visible in capstat's
    ledger AND the victim's postmortem."""
    telemetry.enable()
    telemetry.active().reset()
    cl_quiet = FleetClient(adm_fleet, fallback=StubKeySet(),
                           attempt_timeout=2.0, total_deadline=30.0,
                           rr_seed=0)
    cl_flood = FleetClient(adm_fleet, fallback=StubKeySet(),
                           attempt_timeout=2.0, total_deadline=30.0,
                           rr_seed=1)
    stop = threading.Event()
    flood_out = []
    quiet_out = []
    quiet_lat = []

    def flooder():
        while not stop.is_set():
            out = cl_flood.verify_batch([ADM_FLOOD_TOK] * 32)
            flood_out.extend(out)

    def victim():
        while not stop.is_set():
            t0 = time.monotonic()
            out = cl_quiet.verify_batch([ADM_QUIET_TOK] * 4)
            quiet_lat.append(time.monotonic() - t0)
            quiet_out.append(out)
            time.sleep(0.05)     # ~80 tok/s: inside its budget

    threads = [threading.Thread(target=flooder, daemon=True)
               for _ in range(2)]
    threads.append(threading.Thread(target=victim, daemon=True))
    for t in threads:
        t.start()
    time.sleep(0.5)              # sustained flood established
    victim_pid = adm_fleet.pid(0)
    kill9(victim_pid)            # lands mid-flood
    adm_fleet.resize(3, reason="chaos-pressure")   # capstat-visible
    time.sleep(1.8)
    stop.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "driver thread wedged"

    # zero lost submissions; zero wrong verdicts among ADMITTED
    # tokens (every flood token is .ok — if it was admitted it MUST
    # verify; if not it must be the typed pushback, nothing else)
    assert quiet_out and flood_out
    for out in quiet_out:
        assert len(out) == 4
        for r in out:
            assert not isinstance(r, Exception), \
                f"quiet tenant admitted token rejected: {r!r}"
    throttled = 0
    for r in flood_out:
        if isinstance(r, Exception):
            assert str(r).startswith("ThrottledError"), \
                f"WRONG verdict for admitted flood token: {r!r}"
            throttled += 1
    assert throttled > 0, "sustained flood was never throttled"

    # the victim respawns
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if adm_fleet.state(0) == "ready" \
                and adm_fleet.pid(0) != victim_pid:
            break
        time.sleep(0.1)
    assert adm_fleet.state(0) == "ready"

    # fleet view: the flooder breaches ITS rules only, and the quiet
    # tenant's serve-side p99 stays within its SLO
    merged = _merged_worker_counters(adm_fleet)
    counters = merged.get("counters") or {}
    assert counters.get("admission.checked", 0) == \
        counters.get("admission.admitted", 0) \
        + counters.get("admission.throttled", 0)
    assert counters.get(
        f"decision.serve.tenant.{H_ADM_FLOOD}.reject.throttled", 0) > 0
    assert not counters.get(
        f"decision.serve.tenant.{H_ADM_QUIET}.reject.throttled", 0)
    states = {}
    for r in slo.evaluate_once(merged):
        if r["name"].startswith(("tenant_reject_ratio[",
                                 "tenant_throttle_ratio[")):
            states.setdefault(r.get("tenant"), True)
            states[r.get("tenant")] &= r["ok"]
    assert states.get(H_ADM_FLOOD) is False, \
        "flooding tenant breached no burn-rate rule"
    assert states.get(H_ADM_QUIET) is True, \
        "quiet tenant's rules are not green"
    quiet_p99_rule = slo.parse_rules(
        f"quiet_p99 quantile tenant.{H_ADM_QUIET}.request_s "
        "p99 max 1.0")
    res = slo.evaluate_once(merged, quiet_p99_rule)
    assert res and res[0]["ok"], \
        f"well-behaved tenant's serve p99 breached its SLO: {res}"

    # resize events: capstat ledger (client snapshot path) AND the
    # victim's postmortem carry the transition log
    router_snap = cl_quiet.snapshot()
    assert any(e["kind"] == "up"
               for e in router_snap.get("resize_events") or [])
    ledger = capstat.render_tenants(merged, client=router_snap)
    assert "resize[up]" in ledger and "chaos-pressure" in ledger
    assert H_ADM_FLOOD in ledger
    doc = adm_fleet.postmortem(0)
    assert doc is not None, "no postmortem collected after kill -9"
    pm_events = doc.get("pool_resize_events") or []
    assert any(e["kind"] == "up" for e in pm_events), \
        "victim's postmortem lost the pool resize events"
    blob = json.dumps(doc)
    for needle in (ISS_ADM_QUIET, ISS_ADM_FLOOD, "://"):
        assert needle not in blob, f"{needle!r} leaked into postmortem"
    telemetry.disable()


def test_sigterm_drain_postmortem_carries_tenant_counters(fleet):
    """Graceful path: a SIGTERM-drained worker's fresh final
    postmortem carries the per-tenant counters it folded (extends the
    r9 postmortem contract to the tenant plane)."""
    from cap_tpu.serve.client import VerifyClient

    telemetry.enable()
    telemetry.active().reset()
    # direct connection: THIS worker must fold the two tenants
    host, port = fleet.address(1)
    with VerifyClient(host, port) as direct:
        out = direct.verify_batch([QUIET_TOK] * 2 + [FLOOD_TOK] * 2)
        assert len(out) == 4
    fleet.restart(1, graceful=True)
    doc = fleet.postmortem(1)
    assert doc is not None
    assert doc.get("reason") == "sigterm-drain"
    pm_counters = (doc.get("snapshot") or {}).get("counters") or {}
    tenants = decision.tenant_totals(pm_counters)
    assert tenants.get(H_QUIET, {}).get("accept", 0) >= 2, tenants
    assert tenants.get(H_FLOOD, {}).get("reject", 0) >= 2, tenants
    rendered = obs_postmortem.render_postmortem(doc)
    assert "tenants (" in rendered and H_FLOOD in rendered
    blob = json.dumps(doc)
    assert "tenant-quiet" not in blob and "://" not in blob
    telemetry.disable()
