"""The test infra is itself tested (pattern 6, testing_provider_test.go)."""

import json
import urllib.error
import urllib.request

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu.errors import InvalidJWKSError
from cap_tpu.jwt import JSONWebKeySet, StaticKeySet
from cap_tpu.oidc.testing import TestProvider
from cap_tpu.utils import http as _http


@pytest.fixture()
def idp():
    with TestProvider() as tp:
        yield tp


def _get(idp, path):
    return _http.get(idp.issuer() + path,
                     _http.ssl_context_for_ca(idp.ca_cert()))


def test_discovery_endpoint(idp):
    status, body, _ = _get(idp, "/.well-known/openid-configuration")
    assert status == 200
    doc = json.loads(body)
    assert doc["issuer"] == idp.issuer()
    assert doc["jwks_uri"].endswith("/.well-known/jwks.json")


def test_discovery_disabled(idp):
    idp.set_disable_discovery(True)
    status, _, _ = _get(idp, "/.well-known/openid-configuration")
    assert status == 404


def test_jwks_endpoint_and_signing(idp):
    status, body, _ = _get(idp, "/.well-known/jwks.json")
    assert status == 200
    assert json.loads(body)["keys"][0]["kid"] == "kid-0"
    # a token it issues verifies against its JWKS
    tok = idp.issue_signed_jwt(nonce="n1")
    ks = JSONWebKeySet(idp.issuer() + "/.well-known/jwks.json",
                       jwks_ca_pem=idp.ca_cert())
    assert ks.verify_signature(tok)["nonce"] == "n1"


def test_jwks_fault_injection(idp):
    idp.set_disable_jwks(True)
    status, _, _ = _get(idp, "/.well-known/jwks.json")
    assert status == 404
    idp.set_disable_jwks(False)
    idp.set_invalid_jwks(True)
    ks = JSONWebKeySet(idp.issuer() + "/.well-known/jwks.json",
                       jwks_ca_pem=idp.ca_cert())
    with pytest.raises(InvalidJWKSError):
        ks.keys()


def test_key_rotation(idp):
    _, pub0, _, kid0 = idp.signing_keys()
    idp.rotate_signing_keys()
    _, pub1, _, kid1 = idp.signing_keys()
    assert kid0 != kid1
    tok = idp.issue_signed_jwt()
    with pytest.raises(Exception):
        StaticKeySet([pub0]).verify_signature(tok)
    assert StaticKeySet([pub1]).verify_signature(tok)


def test_clock_control(idp):
    idp.set_now_func(lambda: 1000000.0)
    tok = idp.issue_signed_jwt()
    claims = StaticKeySet([idp.signing_keys()[1]]).verify_signature(tok)
    assert claims["iat"] == 1000000
    assert claims["exp"] == 1000000 + int(idp.expected_expiry)


def test_custom_claims_and_audience(idp):
    idp.set_custom_claims({"groups": ["a", "b"]})
    idp.set_custom_audiences(["aud-1", "aud-2"])
    tok = idp.issue_signed_jwt()
    claims = StaticKeySet([idp.signing_keys()[1]]).verify_signature(tok)
    assert claims["groups"] == ["a", "b"]
    assert claims["aud"] == ["aud-1", "aud-2"]


def test_expected_state_override(idp):
    # inspect the 302 without following it (http.client, no redirects)
    import http.client
    from urllib.parse import urlparse

    idp.set_expected_state("forced-state")
    u = urlparse(idp.issuer())
    conn = http.client.HTTPSConnection(
        u.hostname, u.port,
        context=_http.ssl_context_for_ca(idp.ca_cert()))
    conn.request("GET", "/authorize?response_type=code&state=real&"
                        "redirect_uri=https%3A%2F%2Fapp%2Fcb")
    resp = conn.getresponse()
    assert resp.status == 302
    assert "state=forced-state" in resp.getheader("Location")
    conn.close()


def test_token_endpoint_auth(idp):
    # wrong client secret rejected
    status, body, _ = _http.post_form(
        idp.issuer() + "/token",
        {"grant_type": "authorization_code", "code": idp.expected_auth_code,
         "client_id": idp.client_id, "client_secret": "wrong"},
        _http.ssl_context_for_ca(idp.ca_cert()))
    assert status == 401
    # basic auth accepted
    import base64

    basic = base64.b64encode(
        f"{idp.client_id}:{idp.client_secret}".encode()).decode()
    status, body, _ = _http.post_form(
        idp.issuer() + "/token",
        {"grant_type": "authorization_code", "code": idp.expected_auth_code},
        _http.ssl_context_for_ca(idp.ca_cert()),
        headers={"Authorization": f"Basic {basic}"})
    assert status == 200
    assert "id_token" in json.loads(body)


def test_omit_tokens(idp):
    idp.set_omit_access_tokens(True)
    status, body, _ = _http.post_form(
        idp.issuer() + "/token",
        {"grant_type": "authorization_code", "code": idp.expected_auth_code,
         "client_id": idp.client_id, "client_secret": idp.client_secret},
        _http.ssl_context_for_ca(idp.ca_cert()))
    payload = json.loads(body)
    assert "access_token" not in payload and "id_token" in payload


def test_userinfo_endpoint(idp):
    status, body, _ = _http.get(
        idp.issuer() + "/userinfo",
        _http.ssl_context_for_ca(idp.ca_cert()),
        headers={"Authorization": "Bearer anything"})
    assert status == 200
    assert json.loads(body)["sub"] == idp.replay_subject
    # no bearer → 401
    status, _, _ = _get(idp, "/userinfo")
    assert status == 401
    # custom reply
    idp.set_user_info_reply({"sub": "custom", "plan": "pro"})
    status, body, _ = _http.get(
        idp.issuer() + "/userinfo",
        _http.ssl_context_for_ca(idp.ca_cert()),
        headers={"Authorization": "Bearer x"})
    assert json.loads(body)["plan"] == "pro"


def test_no_tls_mode():
    with TestProvider(no_tls=True) as tp:
        assert tp.issuer().startswith("http://")
        status, body, _ = _http.get(
            tp.issuer() + "/.well-known/openid-configuration")
        assert status == 200
