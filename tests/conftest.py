"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any backend is
initialized, so sharding/pjit paths are exercised without TPU hardware
(the driver separately dry-runs the multi-chip path; benches run on the
real chip).

Note: plain ``JAX_PLATFORMS=cpu`` env vars are NOT enough in this
image — the axon sitecustomize registers the TPU backend at interpreter
startup and pins the platform; ``jax.config.update`` still wins when
called before first device use.
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.find_spec("cap_tpu")
if _spec is None or not (_spec.origin or "").startswith(_REPO + os.sep):
    # Not installed, or an installed copy would shadow this checkout:
    # the suite must always test the code it sits next to.
    sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.4.34-ish) has no jax_num_cpu_devices option; the
    # only way to get virtual host devices is the XLA flag, which is
    # read at first backend init — and nothing above touched a device,
    # so setting it here still works.
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

# Persistent compilation cache: the suite is compile-dominated on CPU
# (engine programs per shape bucket); warm runs skip all of it.
from cap_tpu import compile_cache

compile_cache.enable()
