"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported
anywhere, so sharding/pjit paths are exercised without TPU hardware (the
driver separately dry-runs the multi-chip path; benches run on the real
chip).
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
