"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any backend is
initialized, so sharding/pjit paths are exercised without TPU hardware
(the driver separately dry-runs the multi-chip path; benches run on the
real chip).

Note: plain ``JAX_PLATFORMS=cpu`` env vars are NOT enough in this
image — the axon sitecustomize registers the TPU backend at interpreter
startup and pins the platform; ``jax.config.update`` still wins when
called before first device use.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# Persistent compilation cache: the suite is compile-dominated on CPU
# (engine programs per shape bucket); warm runs skip all of it.
from cap_tpu import compile_cache

compile_cache.enable()
