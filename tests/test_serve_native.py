"""Native serve chain: frame-rejection parity, end-to-end behavior,
build health.

The contract under test (ISSUE 7): the C++ reader in serve_native.cpp
must reject EXACTLY the same malformed / oversize / corrupt frames as
serve/protocol.py, with the same error classes — and the native chain
end-to-end (CAP_SERVE_NATIVE=1) must be byte-compatible with the
Python chain on every frame shape, including keys pushes, traced
requests, and pipelined streams. The build-health test force-compiles
the native sources so a compiler regression (like the r11 SHA-NI
probe that silently killed the .so) fails tier-1 instead of silently
reverting the fleet to the Python chain.
"""

import json
import os
import socket
import struct
import threading
import zlib

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.serve import protocol as P
from cap_tpu.serve.client import VerifyClient
from cap_tpu.serve.worker import VerifyWorker

try:
    from cap_tpu.serve import native_serve
    native_serve.load()
    HAVE_NATIVE = True
except Exception:  # noqa: BLE001 - no compiler / unbuildable
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native serve runtime not built "
    "(no compiler on this host?)")

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "clients", "go", "captpu", "testdata")


# ---------------------------------------------------------------------------
# malformed-frame corpus: every entry is (name, frame bytes, expected
# error class) — the SAME corpus sweeps the Python reference parser
# and the native reader, asserting identical classes.
# ---------------------------------------------------------------------------

def _hdr(ftype: int, count: int) -> bytes:
    return struct.pack("<IBI", P.MAGIC, ftype, count)


def _crc_fix(frame: bytes) -> bytes:
    """Recompute a checksummed frame's trailer over its (possibly
    patched) body, so only the intended fault is present."""
    body = frame[:-4]
    return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)


def _capture(send_fn, *args, **kw) -> bytes:
    class _Cap:
        data = b""

        def sendall(self, b):
            self.data += b

    cap = _Cap()
    send_fn(cap, *args, **kw)
    return cap.data


def malformed_corpus():
    plain_req = _capture(P.send_request, ["corpus-a.ok", "corpus-b"])
    crc_req = _capture(P.send_request, ["corpus-crc"], crc=True)
    traced_req = _capture(P.send_request, ["corpus-tr"],
                          trace="00112233aabbccdd")
    plain_resp = _capture(P.send_response, [{"s": 1}])
    crc_resp = _capture(P.send_response, [{"s": 1}], crc=True)
    corpus = [
        # -- length bombs: rejected BEFORE any allocation ------------------
        ("count-bomb", _hdr(P.T_VERIFY_REQ, 0xFFFFFFFF),
         P.FrameTooLargeError),
        ("entry-length-bomb",
         _hdr(P.T_VERIFY_REQ, 1) + struct.pack("<I", 0xFFFFFFFF),
         P.FrameTooLargeError),
        ("entry-over-bound",
         _hdr(P.T_VERIFY_REQ, 1) + struct.pack("<I", P.MAX_ENTRY_BYTES + 1),
         P.FrameTooLargeError),
        ("response-length-bomb",
         _hdr(P.T_VERIFY_RESP, 1) + struct.pack("<BI", 0, 0xFFFFFFFF),
         P.FrameTooLargeError),
        # -- structural: bad magic / type / counts -------------------------
        ("bad-magic", b"XXXX" + plain_req[4:], P.MalformedFrameError),
        ("unknown-type", _hdr(99, 0), P.MalformedFrameError),
        ("ping-nonzero-count", _hdr(P.T_PING, 2), P.MalformedFrameError),
        ("keys-push-two-entries", _crc_fix(
            _hdr(P.T_KEYS_PUSH, 2) + struct.pack("<I", 1) + b"x"
            + struct.pack("<I", 1) + b"y" + b"\0\0\0\0"),
         P.MalformedFrameError),
        ("shm-attach-two-entries", _crc_fix(
            _hdr(P.T_SHM_ATTACH, 2) + struct.pack("<I", 1) + b"x"
            + struct.pack("<I", 1) + b"y" + b"\0\0\0\0"),
         P.MalformedFrameError),
        ("shm-ack-two-entries", _crc_fix(
            _hdr(P.T_SHM_ACK, 2) + struct.pack("<BI", 0, 1) + b"x"
            + struct.pack("<BI", 0, 1) + b"y" + b"\0\0\0\0"),
         P.MalformedFrameError),
        ("shm-attach-bad-crc",
         (lambda f: f[:-5] + bytes([f[-5] ^ 0x01]) + f[-4:])(
             _capture(P.send_shm_attach, "/dev/shm/corpus")),
         P.FrameCorruptError),
        # -- status bytes --------------------------------------------------
        ("bad-status-plain",
         _hdr(P.T_VERIFY_RESP, 1) + struct.pack("<BI", 7, 1) + b"z",
         P.MalformedFrameError),
        ("bad-status-checksummed", _crc_fix(
            _hdr(P.T_VERIFY_RESP_CRC, 1) + struct.pack("<BI", 7, 1)
            + b"z" + b"\0\0\0\0"),
         P.MalformedFrameError),
        # -- CRC faults ----------------------------------------------------
        ("bad-crc-request",
         crc_req[:-5] + bytes([crc_req[-5] ^ 0x01]) + crc_req[-4:],
         P.FrameCorruptError),
        ("bad-crc-response",
         crc_resp[:15] + bytes([crc_resp[15] ^ 0x80]) + crc_resp[16:],
         P.FrameCorruptError),
        ("length-bomb-beats-crc",
         # a corrupted LENGTH prefix inside a checksummed frame is
         # rejected as too-large BEFORE the CRC runs: bound checks
         # precede allocation, CRC protects content — on both chains
         crc_resp[:12] + bytes([crc_resp[12] ^ 0x80]) + crc_resp[13:],
         P.FrameTooLargeError),
        ("bad-crc-traced",
         traced_req[:20] + bytes([traced_req[20] ^ 0x01])
         + traced_req[21:],
         P.FrameCorruptError),
        # -- trace-context faults ------------------------------------------
        ("trace-len-zero", _hdr(P.T_VERIFY_REQ_TRACE, 0) + b"\x00",
         P.MalformedFrameError),
        ("trace-len-overlong",
         _hdr(P.T_VERIFY_REQ_TRACE, 0) + bytes([P.MAX_TRACE_BYTES + 1])
         + b"a" * (P.MAX_TRACE_BYTES + 1) + b"\0\0\0\0",
         P.MalformedFrameError),
        ("trace-not-hex", _crc_fix(
            _hdr(P.T_VERIFY_REQ_TRACE, 0) + bytes([4]) + b"GGGG"
            + b"\0\0\0\0"),
         P.MalformedFrameError),
        ("trace-truncated",
         # ctx_len says 16 but the stream ends after 4 bytes: on a
         # byte buffer both parsers classify it "incomplete frame"
         _hdr(P.T_VERIFY_REQ_TRACE, 0) + bytes([16]) + b"ab12",
         ConnectionError),
        # -- token decode --------------------------------------------------
        ("token-not-utf8",
         _hdr(P.T_VERIFY_REQ, 1) + struct.pack("<I", 2) + b"\xff\xfe",
         UnicodeDecodeError),
        ("truncated-mid-entry",
         plain_req[: len(plain_req) - 3], ConnectionError),
    ]
    return corpus


def _python_class(frame: bytes):
    try:
        P.parse_frame_bytes(frame)
        return None
    except (P.ProtocolError, ConnectionError, UnicodeDecodeError) as e:
        return type(e)


def test_malformed_corpus_python_classes():
    """The corpus is self-consistent: every entry raises exactly its
    pinned class through the Python reference parser."""
    for name, frame, want in malformed_corpus():
        got = _python_class(frame)
        assert got is not None, f"{name}: parsed cleanly?!"
        assert issubclass(got, want) and (
            want is not ConnectionError or got is ConnectionError), \
            f"{name}: python raised {got}, want {want}"


@needs_native
def test_malformed_corpus_native_parity():
    """THE parity sweep: the native reader classifies every corpus
    frame with the SAME error class as the Python parser."""
    for name, frame, want in malformed_corpus():
        st = native_serve.probe_frame(frame)
        assert st != 0, f"{name}: native parser accepted it"
        got = P.NATIVE_STATUS_ERRORS[st]
        assert got is want, (
            f"{name}: native maps to {got.__name__}, "
            f"python raises {want.__name__}")


@needs_native
def test_golden_vectors_accepted_by_both_parsers():
    """Every committed golden wire vector parses cleanly through the
    Python parser AND the native reader (byte-level compatibility with
    the Go client's pinned frames)."""
    names = [f for f in sorted(os.listdir(GOLDEN_DIR))
             if f.endswith(".bin")]
    assert names, "golden vectors missing"
    for name in names:
        with open(os.path.join(GOLDEN_DIR, name), "rb") as f:
            data = f.read()
        ftype, _, _, used = P.parse_frame_bytes(data)
        assert used == len(data)
        st = native_serve.probe_frame(data)
        assert st == 0, f"{name}: native reader rejected it (st={st})"


@needs_native
def test_native_probe_fuzz_parity_on_mutations():
    """Single-byte mutations of a checksummed request: whatever the
    Python parser decides (ok / corrupt / malformed / too large), the
    native reader decides identically, byte for byte."""
    base = _capture(P.send_request, ["fuzz-a.ok", "fuzz-b"], crc=True)
    for off in range(len(base)):
        for xor in (0x01, 0x80):
            frame = base[:off] + bytes([base[off] ^ xor]) + base[off + 1:]
            want = _python_class(frame)
            st = native_serve.probe_frame(frame)
            got = None if st == 0 else P.NATIVE_STATUS_ERRORS[st]
            assert got is want or (
                got is not None and want is not None
                and issubclass(want, got)), \
                f"mutation at {off} xor {xor:#x}: native={got} " \
                f"python={want}"


# ---------------------------------------------------------------------------
# end-to-end: the native chain serves every frame shape
# ---------------------------------------------------------------------------

@pytest.fixture
def native_worker():
    if not HAVE_NATIVE:
        pytest.skip("native serve runtime not built")
    w = VerifyWorker(StubKeySet(), serve_native=True, max_wait_ms=1.0)
    assert w.serve_chain == "native"
    yield w
    w.close(deadline_s=10)


@needs_native
def test_native_roundtrip_plain_crc_traced(native_worker):
    host, port = native_worker.address
    with VerifyClient(host, port) as cl:
        out = cl.verify_batch(["n1.ok", "n2.bad", "n3.ok"])
        assert out[0] == {"sub": "n1.ok"}
        assert isinstance(out[1], Exception)
        assert out[2] == {"sub": "n3.ok"}
        assert cl.ping()
    with VerifyClient(host, port, crc=True) as cl:
        assert cl.verify_batch(["c1.ok"])[0] == {"sub": "c1.ok"}
    # traced request: response echoes the trace id, spans recorded
    with telemetry.recording() as rec:
        s = socket.create_connection((host, port))
        try:
            rd = P.FrameReader(s)
            P.send_request(s, ["tr.ok"], trace="ab12cd34ab12cd34")
            ftype, entries, trace = rd.recv_frame_ex()
            assert ftype == P.T_VERIFY_RESP_TRACE
            assert trace == "ab12cd34ab12cd34"
            assert entries[0][0] == 0
        finally:
            s.close()
        # worker-side span + flight entry landed in the recorder
        names = {sp["name"]
                 for sp in rec.trace_spans("ab12cd34ab12cd34")}
        assert telemetry.SPAN_WORKER_DEQUEUE in names
        assert telemetry.SPAN_BATCHER_FILL in names


@needs_native
def test_native_pipelined_stream_order(native_worker):
    host, port = native_worker.address
    with VerifyClient(host, port) as cl:
        batches = [[f"s{i}-{j}.ok" for j in range(8)] for i in range(40)]
        outs = list(cl.verify_stream(iter(batches), depth=6))
        assert len(outs) == len(batches)
        for want, got in zip(batches, outs):
            assert [r["sub"] for r in got] == want


@needs_native
def test_native_interleaved_control_ops_stay_in_order(native_worker):
    """Verify → ping → stats → keys → verify on ONE connection: CVB1
    responses must come back strictly in request order even though
    verifies detour through the batcher and controls through the
    drain loop."""
    host, port = native_worker.address
    s = socket.create_connection((host, port))
    try:
        rd = P.FrameReader(s)
        P.send_request(s, ["ord1.ok"])
        P.send_ping(s)
        P.send_stats_request(s)
        P.send_keys_push(s, {"keys": []}, epoch=9)
        P.send_request(s, ["ord2.ok"])
        ftype, entries = rd.recv_frame()
        assert ftype == P.T_VERIFY_RESP and entries[0][0] == 0
        assert rd.recv_frame()[0] == P.T_PONG
        ftype, entries = rd.recv_frame()
        assert ftype == P.T_STATS_RESP
        stats = json.loads(entries[0][1])
        assert stats["serve_chain"] == "native"
        ftype, entries = rd.recv_frame()
        assert ftype == P.T_KEYS_ACK and entries[0][0] == 0
        assert json.loads(entries[0][1])["epoch"] == 9
        ftype, entries = rd.recv_frame()
        assert ftype == P.T_VERIFY_RESP and entries[0][0] == 0
        assert native_worker.key_epoch == 9
    finally:
        s.close()


@needs_native
def test_native_malformed_frame_drops_connection_quietly(native_worker):
    host, port = native_worker.address
    before = native_worker._native.counters()[
        "serve.native.protocol_errors"]
    s = socket.create_connection((host, port))
    try:
        s.sendall(b"XXXX" + bytes(5))
        s.settimeout(2.0)
        assert s.recv(16) == b""        # dropped, nothing sent back
    finally:
        s.close()
    # a GOOD connection still works (the bad one didn't wedge anything)
    with VerifyClient(host, port) as cl:
        assert cl.verify_batch(["still.ok"])[0] == {"sub": "still.ok"}
    after = native_worker._native.counters()[
        "serve.native.protocol_errors"]
    assert after == before + 1


@needs_native
def test_native_concurrent_connections_no_cross_talk(native_worker):
    host, port = native_worker.address
    errs = []

    def hammer(k):
        try:
            with VerifyClient(host, port) as cl:
                for i in range(30):
                    toks = [f"c{k}-{i}-{j}.ok" for j in range(4)]
                    out = cl.verify_batch(toks)
                    assert [r["sub"] for r in out] == toks
        except Exception as e:  # noqa: BLE001
            errs.append(f"{k}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=hammer, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs


@needs_native
def test_native_ring_depth_gauge_and_obs(native_worker):
    gauges = native_worker._obs_gauges()
    assert gauges["serve.native.active"] == 1.0
    assert "serve.native.ring_depth" in gauges
    st = native_worker.stats()
    assert st["serve_chain"] == "native"
    assert "serve.native.frames" in st["counters"]


def test_python_chain_unaffected_by_default():
    w = VerifyWorker(StubKeySet(), max_wait_ms=1.0)
    try:
        assert w.serve_chain == "python"
        assert w._obs_gauges()["serve.native.active"] == 0.0
        host, port = w.address
        with VerifyClient(host, port) as cl:
            assert cl.verify_batch(["py.ok"])[0] == {"sub": "py.ok"}
    finally:
        w.close(deadline_s=10)


def test_uds_transport_falls_back_to_python_chain(tmp_path):
    """Fallback matrix: the native readers own TCP fds, so a UDS
    worker keeps the Python chain even when native is requested."""
    w = VerifyWorker(StubKeySet(), uds_path=str(tmp_path / "w.sock"),
                     serve_native=True, max_wait_ms=1.0)
    try:
        assert w.serve_chain == "python"
        with VerifyClient(uds_path=str(tmp_path / "w.sock")) as cl:
            assert cl.verify_batch(["uds.ok"])[0] == {"sub": "uds.ok"}
    finally:
        w.close(deadline_s=10)


# ---------------------------------------------------------------------------
# build health: the native chain cannot die silently again (r11's
# SHA-NI probe killed the whole .so on gcc<11 for five rounds)
# ---------------------------------------------------------------------------

def test_native_build_from_source_and_symbols_resolve(tmp_path):
    import ctypes
    import shutil

    from cap_tpu import _build

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler on this host")
    out = str(tmp_path / "libcapruntime_test.so")
    _build._build_one(
        (os.path.join("runtime", "native", "jose_native.cpp"),
         os.path.join("runtime", "native", "serve_native.cpp"),
         os.path.join("runtime", "native", "telemetry_native.cpp"),
         os.path.join("runtime", "native", "shm_ring.cpp")),
        out, False, timeout=300.0, force=True)
    assert os.path.exists(out), "native build produced no library"
    lib = ctypes.CDLL(out)
    for sym in ("cap_prepare_batch", "cap_sha_batch",
                "cap_serve_create", "cap_serve_destroy",
                "cap_serve_add_conn", "cap_serve_drain",
                "cap_serve_post_results", "cap_serve_post_raw",
                "cap_serve_probe_frame", "cap_serve_ring_depth",
                "cap_serve_counter", "cap_bench_drive",
                # the native telemetry plane (ISSUE 8)
                "cap_tel_layout", "cap_tel_create", "cap_tel_destroy",
                "cap_tel_classify_seg", "cap_tel_learn",
                "cap_tel_fold", "cap_tel_hist_observe",
                "cap_tel_counters", "cap_tel_hist_state",
                "cap_tel_drain_exemplars", "cap_tel_reset",
                "cap_serve_set_telemetry", "cap_serve_drain_aux",
                "cap_serve_post_results_tel", "cap_serve_ring_hwm",
                # the shm transport (ISSUE 13: zero-copy ingest)
                "cap_serve_set_shm", "cap_shm_create", "cap_shm_open",
                "cap_shm_close", "cap_shm_probe", "cap_shm_write",
                "cap_shm_read", "cap_shm_drive"):
        assert hasattr(lib, sym), f"symbol {sym} missing"


@needs_native
def test_batcher_handoff_callback_runs_once_per_chunk():
    from cap_tpu.serve.batcher import AdaptiveBatcher

    calls = []
    b = AdaptiveBatcher(StubKeySet(), target_batch=8, max_wait_ms=1.0)
    try:
        p = b.submit_handoff(["h1.ok", "h2.bad", "h3.ok"],
                             on_done=lambda rs: calls.append(list(rs)))
        p.event.wait(10)
        assert len(calls) == 1
        assert calls[0][0] == {"sub": "h1.ok"}
        assert isinstance(calls[0][1], Exception)
        # empty handoff: callback still fires, with []
        b.submit_handoff([], on_done=lambda rs: calls.append(rs))
        assert calls[1] == []
    finally:
        b.close(deadline_s=10)
