"""TPUBatchKeySet parity vs the CPU oracle, successes AND rejections.

This is the bit-exact-parity contract from BASELINE.md: for every token
in a mixed batch, the TPU path must produce the same verdict as the
reference-semantics CPU path (StaticKeySet / verify_parsed).
"""

import json

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.errors import InvalidSignatureError, MalformedTokenError
from cap_tpu.jwt import StaticKeySet
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet


@pytest.fixture(scope="module")
def rsa_jwks():
    """8-key JWKS: mixed 2048/3072/4096-bit RSA keys (config ② shape)."""
    pairs = []
    for i, bits in enumerate([2048, 2048, 2048, 3072, 3072, 4096, 4096, 2048]):
        priv, pub = captest.generate_keys("RS256", rsa_bits=bits)
        pairs.append((f"kid-{i}", priv, pub))
    return pairs


@pytest.fixture(scope="module")
def tpu_keyset(rsa_jwks):
    return TPUBatchKeySet([JWK(pub, kid=kid) for kid, _, pub in rsa_jwks])


def _tokens(rsa_jwks, alg, n, start=0):
    toks = []
    for j in range(n):
        kid, priv, _ = rsa_jwks[(start + j) % len(rsa_jwks)]
        toks.append(captest.sign_jwt(
            priv, alg, captest.default_claims(sub=f"user-{j}"), kid=kid))
    return toks


@pytest.mark.parametrize("alg", ["RS256", "RS384", "RS512"])
def test_rs_batch_verifies(alg, rsa_jwks, tpu_keyset):
    toks = _tokens(rsa_jwks, alg, 12)
    res = tpu_keyset.verify_batch(toks)
    for j, r in enumerate(res):
        assert isinstance(r, dict), f"token {j}: {r}"
        assert r["sub"] == f"user-{j}"


@pytest.mark.parametrize("alg", ["PS256", "PS384", "PS512"])
def test_ps_batch_verifies(alg, rsa_jwks, tpu_keyset):
    toks = _tokens(rsa_jwks, alg, 9)
    res = tpu_keyset.verify_batch(toks)
    assert all(isinstance(r, dict) for r in res)


def test_mixed_batch_parity_with_cpu(rsa_jwks, tpu_keyset):
    """Mixed good/tampered/garbage batch: verdicts must match CPU oracle."""
    good = _tokens(rsa_jwks, "RS256", 6) + _tokens(rsa_jwks, "PS256", 3)
    # tampered payload (sig of another payload)
    h, p, s = good[0].split(".")
    evil = b64url_encode(json.dumps({"sub": "evil"}).encode())
    tampered = f"{h}.{evil}.{s}"
    # truncated signature
    shortsig = good[1][: len(good[1]) - 40]
    # garbage token
    garbage = "not.a.jwt"
    # wrong kid (kid-0 key didn't sign this)
    kid0, priv7, _ = rsa_jwks[0]
    _, priv_other, _ = rsa_jwks[1]
    wrongkid = captest.sign_jwt(priv_other, "RS256",
                                captest.default_claims(), kid=kid0)
    batch = good + [tampered, shortsig, garbage, wrongkid]

    cpu = StaticKeySet([pub for _, _, pub in rsa_jwks])
    cpu_verdicts = []
    for t in batch:
        try:
            cpu.verify_signature(t)
            cpu_verdicts.append(True)
        except Exception:
            cpu_verdicts.append(False)

    tpu_res = tpu_keyset.verify_batch(batch)
    tpu_verdicts = [isinstance(r, dict) for r in tpu_res]
    # Note: wrongkid verifies on CPU StaticKeySet (trial over all keys)
    # but the kid-routed TPU path rejects it — kid routing is stricter,
    # matching the remote-JWKS (kid-matched) reference path. Compare the
    # kid-faithful subset exactly:
    assert tpu_verdicts[:-1] == cpu_verdicts[:-1]
    assert tpu_verdicts[-1] is False
    assert isinstance(tpu_res[-4], InvalidSignatureError)   # tampered
    assert isinstance(tpu_res[-2], MalformedTokenError)     # garbage


def test_no_kid_falls_back_to_trial(rsa_jwks, tpu_keyset):
    _, priv, _ = rsa_jwks[2]
    tok = captest.sign_jwt(priv, "RS256", captest.default_claims())  # no kid
    res = tpu_keyset.verify_batch([tok])
    assert isinstance(res[0], dict)


def test_unknown_kid_rejected(rsa_jwks, tpu_keyset):
    _, priv, _ = rsa_jwks[0]
    tok = captest.sign_jwt(priv, "RS256", captest.default_claims(),
                           kid="nonexistent")
    # kid not in table → trial-verifies over all keys (CPU) and succeeds,
    # same as the static reference path; a *wrong-key* kid is the reject case.
    res = tpu_keyset.verify_batch([tok])
    assert isinstance(res[0], dict)


def test_single_token_path(rsa_jwks, tpu_keyset):
    kid, priv, _ = rsa_jwks[0]
    tok = captest.sign_jwt(priv, "RS256", captest.default_claims(), kid=kid)
    assert tpu_keyset.verify_signature(tok)["sub"] == "alice"


def test_bitflip_sweep_parity(rsa_jwks, tpu_keyset):
    """Flip bits across the signature; every corruption must reject."""
    kid, priv, _ = rsa_jwks[0]
    tok = captest.sign_jwt(priv, "RS256", captest.default_claims(), kid=kid)
    h, p, s = tok.split(".")
    corrupted = []
    raw = bytearray(__import__("cap_tpu.jwt.jose", fromlist=["b64url_decode"])
                    .b64url_decode(s))
    for bit in range(0, len(raw) * 8, 191):
        mut = bytearray(raw)
        mut[bit // 8] ^= 1 << (bit % 8)
        corrupted.append(f"{h}.{p}.{b64url_encode(bytes(mut))}")
    res = tpu_keyset.verify_batch(corrupted)
    assert all(isinstance(r, InvalidSignatureError) for r in res)


def test_mixed_rsa_key_sizes_one_batch(rsa_jwks, tpu_keyset):
    """2048+4096-bit keys in one device dispatch (shared padded K)."""
    toks = _tokens(rsa_jwks, "RS512", 16)
    res = tpu_keyset.verify_batch(toks)
    assert all(isinstance(r, dict) for r in res)


def test_es_falls_back_to_cpu_until_ec_engine(tpu_keyset, rsa_jwks):
    es_priv, es_pub = captest.generate_keys("ES256")
    ks = TPUBatchKeySet(
        [JWK(pub, kid=kid) for kid, _, pub in rsa_jwks] + [JWK(es_pub, kid="es")]
    )
    tok = captest.sign_jwt(es_priv, "ES256", captest.default_claims(), kid="es")
    res = ks.verify_batch([tok])
    assert isinstance(res[0], dict)


def test_remote_keyset_rotation():
    """TPURemoteKeySet: unknown kid triggers ONE refetch + table rebuild;
    bad signatures against known kids never refetch (no amplification)."""
    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.jwt.tpu_keyset import TPURemoteKeySet

    priv1, pub1 = captest.generate_keys("ES256")
    priv2, pub2 = captest.generate_keys("ES256")
    state = {"keys": [serialize_public_key(pub1, kid="gen1")]}

    with captest.jwks_test_server(state) as (url, _srv):
        ks = TPURemoteKeySet(url, min_refresh_interval=0.0)
        claims = captest.default_claims()
        tok1 = captest.sign_jwt(priv1, "ES256", claims, kid="gen1")
        out = ks.verify_batch([tok1] * 4)
        assert all(isinstance(r, dict) for r in out)
        fetches_before = state["fetches"]

        # forged token with a KNOWN kid: must fail with NO refetch
        forged = tok1[:-8] + ("AAAAAAAA" if not tok1.endswith("AAAAAAAA")
                              else "BBBBBBBB")
        out = ks.verify_batch([forged])
        assert isinstance(out[0], Exception)
        assert state["fetches"] == fetches_before

        # rotate: new signing key, new kid → one refetch, then verifies.
        # tok1 still verifies in THIS batch (it matched the cached key
        # before the refetch — same semantics as the reference's cached
        # RemoteKeySet).
        state["keys"] = [serialize_public_key(pub2, kid="gen2")]
        tok2 = captest.sign_jwt(priv2, "ES256", claims, kid="gen2")
        out = ks.verify_batch([tok2, tok1])
        assert isinstance(out[0], dict)
        assert isinstance(out[1], dict)
        assert state["fetches"] == fetches_before + 1

        # next batch: gen1 is gone from the rebuilt table → unknown kid
        # → one more refetch, still rejected (IdP dropped the key)
        out = ks.verify_batch([tok1])
        assert isinstance(out[0], Exception)
        assert state["fetches"] == fetches_before + 2

        # attacker-style random unknown kids: the refresh cooldown and
        # the unchanged-content check bound fetches and table rebuilds
        ks2 = TPURemoteKeySet(url, min_refresh_interval=1000.0)
        ks2.verify_batch([tok2])               # builds table, 1 fetch
        fetches = state["fetches"]
        table_obj = ks2._ks
        forged2 = captest.sign_jwt(priv1, "ES256", claims, kid="evil-1")
        forged3 = captest.sign_jwt(priv1, "ES256", claims, kid="evil-2")
        out = ks2.verify_batch([forged2])
        assert isinstance(out[0], Exception)
        out = ks2.verify_batch([forged3])
        assert isinstance(out[0], Exception)
        assert state["fetches"] <= fetches + 1   # cooldown caps fetches
        assert ks2._ks is table_obj              # content unchanged →
        #                                          no table rebuild


def test_remote_keyset_raw_mode_rotation():
    """TPURemoteKeySet.verify_batch_raw: accepted tokens yield payload
    BYTES equal to the dict path's claims, and kid rotation still
    triggers exactly one refetch with per-token verdicts preserved."""
    import json as jsonlib

    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.jwt.tpu_keyset import TPURemoteKeySet

    priv1, pub1 = captest.generate_keys("ES256")
    priv2, pub2 = captest.generate_keys("ES256")
    state = {"keys": [serialize_public_key(pub1, kid="gen1")]}

    with captest.jwks_test_server(state) as (url, _srv):
        ks = TPURemoteKeySet(url, min_refresh_interval=0.0)
        claims = captest.default_claims()
        tok1 = captest.sign_jwt(priv1, "ES256", claims, kid="gen1")
        forged = tok1[:-8] + ("AAAAAAAA" if not tok1.endswith("AAAAAAAA")
                              else "BBBBBBBB")
        raws = ks.verify_batch_raw([tok1, forged])
        want = ks.verify_batch([tok1])
        assert isinstance(raws[0], bytes)
        assert jsonlib.loads(raws[0]) == want[0]
        assert isinstance(raws[1], InvalidSignatureError)

        # rotation mid-stream, raw path: one refetch, bytes come back
        state["keys"] = [serialize_public_key(pub2, kid="gen2")]
        tok2 = captest.sign_jwt(priv2, "ES256", claims, kid="gen2")
        fetches_before = state["fetches"]
        raws = ks.verify_batch_raw([tok2])
        assert isinstance(raws[0], bytes)
        assert jsonlib.loads(raws[0])["iss"] == claims["iss"]
        assert state["fetches"] == fetches_before + 1


def test_remote_keyset_refetch_failure_keeps_verdicts():
    """A failed rotation refetch (IdP down) must NOT discard the batch's
    verdicts: known-key results stay dicts, the unknown-kid token keeps
    its per-token InvalidSignatureError (ADVICE r1, medium)."""
    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.jwt.tpu_keyset import TPURemoteKeySet

    priv1, pub1 = captest.generate_keys("ES256")
    evil_priv, _ = captest.generate_keys("ES256")  # NOT in the JWKS
    state = {"keys": [serialize_public_key(pub1, kid="gen1")]}

    with captest.jwks_test_server(state) as (url, srv):
        ks = TPURemoteKeySet(url, min_refresh_interval=0.0)
        claims = captest.default_claims()
        good = captest.sign_jwt(priv1, "ES256", claims, kid="gen1")
        assert isinstance(ks.verify_batch([good])[0], dict)

        # IdP goes away; a batch with one attacker token (unknown kid)
        # plus legitimate tokens must still return per-token verdicts.
        srv.shutdown()
        srv.server_close()
        evil = captest.sign_jwt(evil_priv, "ES256", claims,
                                kid="no-such-kid")
        out = ks.verify_batch([good, evil, good])
        assert isinstance(out[0], dict)
        assert isinstance(out[1], InvalidSignatureError)
        assert isinstance(out[2], dict)


def test_resident_dispatchers_headline_mix():
    """The resident engine benchmark (bench.py resident_mixed_vps)
    dispatches the REAL packed programs on device-resident records:
    accept-bit sums must equal the token count per family bucket, and
    repeated dispatches must keep returning it (the slope-timing loop
    relies on that)."""
    from cap_tpu.jwt.tpu_keyset import resident_dispatchers

    jwks, toks = captest.headline_fixtures(256)
    ks = TPUBatchKeySet(jwks)
    n, fns = resident_dispatchers(ks, toks)
    assert n == len(toks)
    assert len(fns) == 2              # one RS256 bucket + one ES256
    per_fn = {int(fn()) for _, fn in fns}
    assert per_fn == {sum(m for m, _ in fns) // 2}
    total = sum(int(fn()) for _, fn in fns)
    assert total == n


def test_resident_dispatchers_rejects_unroutable():
    """A token that would fall back to the CPU oracle must raise — the
    resident number can never silently measure a subset."""
    from cap_tpu.errors import InvalidParameterError
    from cap_tpu.jwt.tpu_keyset import resident_dispatchers

    jwks, toks = captest.headline_fixtures(16)
    ks = TPUBatchKeySet(jwks)
    priv, _ = captest.generate_keys("ES256")
    stranger = captest.sign_jwt(priv, "ES256", captest.default_claims(),
                                kid="not-in-jwks")
    with pytest.raises(InvalidParameterError):
        resident_dispatchers(ks, toks + [stranger])


def test_wire_adaptive_chunk_sizing():
    """_chunk_tokens targets a TIME budget against the observed wire
    rate: slow link -> smaller chunks (bounded per-chunk latency), fast
    link -> the 8 MB clamp; a real batch updates the estimate."""
    jwks, toks = captest.headline_fixtures(64)
    ks = TPUBatchKeySet(jwks)
    rec_width = 292                   # RS-2048 record bytes

    default = ks._chunk_tokens(rec_width)      # no estimate: ~5 MB
    ks._wire_bps = 6 * (1 << 20)               # 6 MB/s trough
    slow = ks._chunk_tokens(rec_width)
    # 6 MB/s * 250 ms = 1.5 MB -> ~4k tokens of 292 B (pow-2)
    assert slow * rec_width <= int(1.5 * (1 << 20))
    assert slow < default
    ks._wire_bps = 100 * (1 << 20)             # fat co-located link
    fast = ks._chunk_tokens(rec_width)
    assert fast * rec_width <= (8 << 20)       # clamp
    assert fast >= default

    ks._wire_bps = None
    out = ks.verify_batch(toks)
    assert all(isinstance(r, dict) for r in out)
    from cap_tpu.runtime import prep
    if prep._load_native() is not None:
        # the object fallback never dispatches device work, so the
        # estimate only updates on the native batch path
        assert ks._wire_bps is not None and ks._wire_bps > 0


def _sign_raw_payload(priv, alg, payload: bytes, kid: str) -> str:
    """Compact JWS over an ARBITRARY payload (sign_jwt forces a claims
    dict; parity tests need e.g. a JSON array payload)."""
    import json as jsonlib

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    from cap_tpu.jwt.jose import b64url_encode

    header = jsonlib.dumps({"alg": alg, "typ": "JWT", "kid": kid},
                           separators=(",", ":")).encode()
    si = (b64url_encode(header) + "." + b64url_encode(payload)).encode()
    der = priv.sign(si, cec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    return si.decode() + "." + b64url_encode(sig)


def test_verify_batch_raw_parity():
    """Raw mode returns the SIGNED payload bytes for every token the
    dict mode accepts, the same error classes for every token it
    rejects — including a VALID signature over a non-object payload —
    and json.loads(raw) == the dict-mode claims."""
    import json as jsonlib

    from cap_tpu.errors import MalformedTokenError

    jwks, toks = captest.headline_fixtures(48)
    es_priv, es_pub = captest.generate_keys("ES256")
    ks = TPUBatchKeySet(jwks + [JWK(es_pub, kid="raw-es")])
    arr_payload = _sign_raw_payload(es_priv, "ES256", b"[1,2,3]",
                                    "raw-es")
    bad_json = _sign_raw_payload(es_priv, "ES256", b"{not json",
                                 "raw-es")
    # BOM-prefixed object: the strict phase-1 scan flags it, but
    # json.loads accepts — BOTH modes must accept (json.loads is
    # authoritative; the native mask is only a fast filter)
    bom = _sign_raw_payload(es_priv, "ES256", b'\xef\xbb\xbf{"b":1}',
                            "raw-es")
    tampered = toks[0][:-8] + ("AAAAAAAA"
                               if not toks[0].endswith("AAAAAAAA")
                               else "BBBBBBBB")
    batch = toks + [bom, arr_payload, bad_json, tampered, "garbage"]

    dicts = ks.verify_batch(batch)
    raws = ks.verify_batch_raw(batch)
    assert len(dicts) == len(raws)
    for i, (d, r) in enumerate(zip(dicts, raws)):
        if isinstance(d, Exception):
            assert isinstance(r, Exception), f"tok {i}"
            assert type(r) is type(d), f"tok {i}: {r!r} vs {d!r}"
        else:
            assert isinstance(r, bytes), f"tok {i}"
            assert jsonlib.loads(r) == d, f"tok {i}"
    # crafted tokens: valid signatures, divergent payloads
    assert dicts[-5] == {"b": 1}                        # BOM accept
    assert isinstance(raws[-5], bytes)
    assert isinstance(dicts[-4], MalformedTokenError)   # [1,2,3]
    assert isinstance(raws[-4], MalformedTokenError)
    assert isinstance(dicts[-3], MalformedTokenError)   # {not json
    assert isinstance(raws[-3], MalformedTokenError)


def test_payload_object_ok_matches_json_loads():
    """The phase-1-only validity mask agrees with json.loads on
    object/non-object/malformed/exotic payloads."""
    import json as jsonlib

    from cap_tpu.runtime import prep

    if prep._load_native() is None:
        pytest.skip("native runtime not built")
    from cap_tpu.runtime.native_binding import prepare_batch_arrays

    es_priv, es_pub = captest.generate_keys("ES256")
    payloads = [
        b'{"a":1}', b"[1,2]", b"42", b'"str"', b"{broken",
        b'{"nested":{"deep":[1,{"x":null}]}}',
        b'{"u":"\\ud83d\\ude00"}',           # surrogate pair: fallback
        b'{"big":123456789012345678901234567890123456789012}',
        "{\"k\":\"café\"}".encode(),
        b'  {"ws": 1}  ',
    ]
    toks = [_sign_raw_payload(es_priv, "ES256", p, "k") for p in payloads]
    pb = prepare_batch_arrays(toks)
    assert (pb.status == 0).all()
    got = pb.payload_object_ok(np.arange(len(toks)))
    for i, p in enumerate(payloads):
        try:
            want = isinstance(jsonlib.loads(p), dict)
        except ValueError:
            want = False
        # The mask is ONE-SIDED: True must imply json.loads accepts
        # (callers re-check the Falses with json.loads, which accepts
        # some payloads the strict scan flags, e.g. BOM prefixes).
        if got[i]:
            assert want, f"payload {i}: {p!r}"
    # and for these plain-UTF-8 payloads the mask is exact
    assert [bool(g) for g in got] == [
        True, False, False, False, False, True, True, True, True, True]
