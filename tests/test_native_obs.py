"""Native telemetry plane: the bit-exact parity contract (ISSUE 8).

The plane (runtime/native/telemetry_native.cpp) folds the serve
surface's decision accounting in C. The hard requirement under test:
counters, histogram bucket counts, and decision-ring sample positions
must be BIT-IDENTICAL to the Python fold (obs/decision.record_batch)
— pinned here by a fuzz sweep that runs an adversarial header corpus,
every error class in the taxonomy, and ≥1k random mixed batches
through both recorders, comparing counter maps and ring entries after
every batch. Plus: the graceful-degradation matrix
(CAP_SERVE_NATIVE_OBS=0, plane-less .so → Python fold) and the
cross-chain equality gate (same load on the python chain and the
native chain must produce identical decision counters).
"""

import base64
import inspect
import json
import random
import time

import pytest

from cap_tpu import errors as errors_mod
from cap_tpu import telemetry
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.obs import decision
from cap_tpu.serve.client import VerifyClient
from cap_tpu.serve.worker import VerifyWorker

try:
    from cap_tpu.serve import native_serve
    HAVE_TEL = bool(getattr(native_serve.load(), "cap_tel_ok", False))
except Exception:  # noqa: BLE001 - no compiler / unbuildable
    HAVE_TEL = False

needs_tel = pytest.mark.skipif(
    not HAVE_TEL, reason="native telemetry plane not built "
    "(no compiler on this host?)")


def make_plane():
    return native_serve.NativeTelemetryPlane()


# ---------------------------------------------------------------------------
# registry pins: the index vocabularies the native plane counts by
# ---------------------------------------------------------------------------

def test_reason_index_covers_registry_in_fixed_order():
    assert set(decision.REASON_INDEX) == set(decision.REASON_CLASSES)
    assert len(decision.REASON_INDEX) == len(decision.REASON_CLASSES)
    # order is native ABI: spot-pin the ends so a reorder cannot slip
    assert decision.REASON_INDEX[0] == decision.REASON_MALFORMED
    assert decision.REASON_INDEX[-1] == decision.REASON_INTERNAL


def test_latency_bucket_index_matches_labels():
    for lat in (None, 0.0, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                0.5, 1.0, 7.0):
        idx = decision.latency_bucket_index(lat)
        assert decision.LAT_BUCKET_INDEX[idx] == \
            decision.latency_bucket(lat)


def test_reason_index_matches_classify_for_all_error_classes():
    for _, cls in inspect.getmembers(errors_mod, inspect.isclass):
        if not issubclass(cls, errors_mod.CapError):
            continue
        err = cls("x")
        assert decision.REASON_INDEX[decision.reason_index(err)] == \
            decision.classify(err)


@needs_tel
def test_layout_handshake_enables_plane():
    assert native_serve.load().cap_tel_ok


# ---------------------------------------------------------------------------
# histogram bucket parity: lower_bound over the SAME bounds must place
# every value in the SAME bucket bisect_left picks
# ---------------------------------------------------------------------------

@needs_tel
def test_histogram_bucket_counts_bit_identical():
    plane = make_plane()
    try:
        h = telemetry.Histogram()
        rng = random.Random(13)
        vals = [rng.uniform(0.1, 10.0) ** rng.uniform(-8.0, 8.0)
                for _ in range(4000)]
        # edges: exact bounds, zero, negatives, overflow, min/max
        vals += [0.0, -3.5, 1e-9, telemetry._HIST_LO, telemetry._HIST_HI,
                 5e9, telemetry.BUCKET_BOUNDS[0],
                 telemetry.BUCKET_BOUNDS[17],
                 telemetry.BUCKET_BOUNDS[-1]]
        for v in vals:
            h.add(v)
            plane.observe(native_serve.NativeTelemetryPlane
                          .SERIES_NAMES.index("serve.native.request_s"),
                          v)
        st = plane._hist_state(0)
        assert st["buckets"] == {str(i): c for i, c
                                 in enumerate(h.counts) if c}
        assert st["count"] == h.count
        assert st["min"] == h.vmin and st["max"] == h.vmax
        # and the state merges like any recorder series
        merged = telemetry.merge_snapshots([
            {"series": {"s": st}}, {"series": {"s": st}}])
        assert merged["series"]["s"]["count"] == 2 * h.count
    finally:
        plane.destroy()


# ---------------------------------------------------------------------------
# THE parity sweep: malformed corpus + full taxonomy + random batches
# ---------------------------------------------------------------------------

def _b64(obj) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(obj).encode()).rstrip(b"=").decode()


def adversarial_segs():
    """Header segments covering every classification outcome: valid
    families, bad base64, bad JSON, non-dict JSON, missing/odd alg,
    kid variants, empty, oversize, non-ASCII."""
    return [
        _b64({"alg": "ES256", "kid": "k1"}),
        _b64({"alg": "ES384"}),
        _b64({"alg": "RS256", "kid": "longish-kid-" + "x" * 40}),
        _b64({"alg": "PS512", "kid": ""}),
        _b64({"alg": "EdDSA", "kid": "ed-key"}),
        _b64({"alg": "ML-DSA-44", "kid": "pq1"}),
        _b64({"alg": "ML-DSA-87"}),
        _b64({"alg": "HS256", "kid": "hmac"}),      # family "other"
        _b64({"alg": 5, "kid": "numeric-alg"}),     # alg not a string
        _b64({"kid": "no-alg"}),
        _b64([1, 2, 3]),                            # non-dict JSON
        _b64({"alg": "ES256", "kid": 123}),         # kid not a string
        "!!!!not-base64!!!!",
        "eyJhbGciOiJFUzI1Ni",                       # truncated b64
        base64.urlsafe_b64encode(b"\xff\xfe\x00ug").decode(),  # not JSON
        "",                                         # empty segment
        "x" * 1500,                                 # over the 1024 bound
        "ünïcode-segment",                          # non-ASCII
        "A",                                        # 1 char (bad length)
    ]


def taxonomy_rejects():
    out = []
    for _, cls in sorted(inspect.getmembers(errors_mod,
                                            inspect.isclass)):
        if issubclass(cls, errors_mod.CapError):
            out.append(cls(f"{cls.__name__} happened"))
    out += [ConnectionError("conn"), TimeoutError("slow"),
            OSError("io"), ValueError("odd")]  # unmapped → internal
    return out


def _run_both(batches):
    """Run the same batch stream through record_batch (fresh recorder)
    and through the native plane (classify → learn → fold → pump into
    a second fresh recorder); assert counters (decision AND tenant),
    per-tenant latency series, and decision rings are identical after
    EVERY batch — the r13 contract extended to the tenant plane."""
    rec_py = telemetry.Recorder()
    rec_nat = telemetry.Recorder()
    plane = make_plane()
    try:
        for bi, (results, tokens, lat, trace) in enumerate(batches):
            with telemetry.recording(rec_py):
                decision.record_batch("serve", results, tokens=tokens,
                                      latency_s=lat, trace=trace)
            plane.fold_batch(results, tokens=tokens, latency_s=lat,
                             trace=trace)
            plane.pump(rec_nat)
            py_c = {k: v for k, v in rec_py.counters().items()
                    if k.startswith(("decision.", "tenant."))}
            nat_c = {k: v for k, v in plane.counters().items()
                     if k.startswith(("decision.", "tenant."))}
            assert py_c == nat_c, f"counter divergence at batch {bi}"
            py_s = {k: v for k, v
                    in rec_py.snapshot()["series"].items()
                    if k.startswith("tenant.")}
            nat_s = {k: v for k, v
                     in plane.snapshot()["series"].items()
                     if k.startswith("tenant.")}
            assert py_s == nat_s, \
                f"tenant series divergence at batch {bi}"
            assert rec_py.decisions() == rec_nat.decisions(), \
                f"ring divergence at batch {bi}"
    finally:
        plane.destroy()


@needs_tel
def test_parity_sweep_malformed_corpus_and_taxonomy():
    segs = adversarial_segs()
    rejects = taxonomy_rejects()
    batches = []
    # one batch per adversarial segment, mixed verdicts
    for i, seg in enumerate(segs):
        tokens = [f"{seg}.p{i}.sig", f"{seg}.q{i}.sig"]
        batches.append(([{"sub": "a"}, rejects[i % len(rejects)]],
                        tokens, 0.002, None))
    # one batch carrying the ENTIRE error taxonomy at once
    tokens = [f"{segs[i % len(segs)]}.t{i}.s"
              for i in range(len(rejects))]
    batches.append((list(rejects), tokens, 0.5, "ab12cd34ab12cd34"))
    # tokens=None (family unknown) and empty batch
    batches.append(([{"ok": 1}, rejects[0]], None, None, None))
    batches.append(([], [], 0.1, None))
    # non-string tokens ride the guarded walk on both sides
    batches.append(([{"ok": 1}, b"bytes-are-rejected-shape"],
                    ["tok.ok", 1234], 0.01, None))
    _run_both(batches)


@needs_tel
def test_parity_sweep_random_mixed_batches():
    """≥1k random batches: random sizes, verdict mixes, header pools,
    latencies, traces — counters and ring positions must stay
    bit-identical throughout."""
    rng = random.Random(0xCAB)
    segs = adversarial_segs()
    segs += [_b64({"alg": "ES256", "kid": f"k{i}"}) for i in range(24)]
    rejects = taxonomy_rejects()
    lats = [None, 0.0004, 0.004, 0.04, 0.4, 4.0]
    batches = []
    for i in range(1100):
        n = rng.randrange(0, 24)
        results = []
        tokens = []
        for j in range(n):
            seg = rng.choice(segs)
            tokens.append(f"{seg}.{i}-{j}.sig")
            if rng.random() < 0.35:
                results.append(rng.choice(rejects))
            elif rng.random() < 0.5:
                results.append(b'{"raw":1}')
            else:
                results.append({"sub": f"s{j}"})
        trace = f"{rng.randrange(1 << 32):08x}" \
            if rng.random() < 0.3 else None
        use_tokens = tokens if rng.random() < 0.9 else None
        batches.append((results, use_tokens, rng.choice(lats), trace))
    _run_both(batches)


@needs_tel
def test_parity_sweep_tenant_attribution():
    """Tenant extension of the parity contract (ISSUE 14 acceptance):
    issuer-bearing tokens — a stable multi-tenant mix, a unique-issuer
    overflow flood past the table cap, adversarial payloads (missing /
    non-string / overlong issuers, undecodable segments) — produce
    bit-identical per-tenant counters AND latency histograms through
    both folds, after every batch."""
    rng = random.Random(0x7E4A47)
    rejects = taxonomy_rejects()

    def tok(i, iss, suffix="sig"):
        hdr = _b64({"alg": "ES256", "kid": f"ten-{i}"})
        return f"{hdr}.{_b64({'iss': iss})}.{suffix}"

    # stable tenants + a flood that overflows the bounded table
    stable = [tok(i, f"https://idp-{i}.example") for i in range(12)]
    flood = [tok(1000 + i, f"https://flood-{i}.unique.example")
             for i in range(decision.TENANT_CAP + 30)]
    adversarial = [
        _b64({"alg": "ES256", "kid": "t-noiss"}) + "."
        + _b64({"sub": "x"}) + ".s",                    # no iss
        _b64({"alg": "RS256", "kid": "t-numiss"}) + "."
        + _b64({"iss": 99}) + ".s",                     # non-str iss
        _b64({"alg": "ES256", "kid": "t-longiss"}) + "."
        + _b64({"iss": "x" * 1500}) + ".s",             # overlong iss
        _b64({"alg": "ES256", "kid": "t-badpay"}) + ".!!!.s",
        "no-dots-at-all",
    ]
    pool = stable + adversarial
    lats = [None, 0.0004, 0.004, 0.04, 0.4]
    batches = []
    for i in range(300):
        n = rng.randrange(1, 16)
        tokens = [rng.choice(pool) for _ in range(n)]
        if i % 3 == 0:       # flood pressure in a third of batches
            tokens += [flood[rng.randrange(len(flood))]
                       for _ in range(rng.randrange(1, 6))]
        results = [rng.choice(rejects) if rng.random() < 0.4
                   else {"s": j} for j in range(len(tokens))]
        batches.append((results, tokens, rng.choice(lats), None))
    _run_both(batches)
    # the flood hit the bounded table: "other" traffic was folded and
    # the exact equation held through BOTH folds (checked per batch)
    assert decision.TENANTS.size() == decision.TENANT_CAP


@needs_tel
def test_exemplar_ring_overflow_keeps_newest_256():
    """More than MAX_DECISION_ENTRIES exemplars between pumps: both
    sides keep the NEWEST 256 (deque(maxlen) vs native FIFO drop)."""
    rec_py = telemetry.Recorder()
    rec_nat = telemetry.Recorder()
    plane = make_plane()
    try:
        seg = _b64({"alg": "ES256", "kid": "ring"})
        # 300 batches of 17 accepts -> >256 sampled entries, no pump
        for i in range(300):
            results = [{"s": 1}] * 17
            tokens = [f"{seg}.{i}-{j}.x" for j in range(17)]
            with telemetry.recording(rec_py):
                decision.record_batch("serve", results, tokens=tokens,
                                      latency_s=0.002)
            plane.fold_batch(results, tokens=tokens, latency_s=0.002)
        drained = 0
        while True:
            n = plane.pump(rec_nat)
            drained += n
            if not n:
                break
        assert drained <= telemetry.MAX_DECISION_ENTRIES
        assert rec_py.decisions() == rec_nat.decisions()
        assert plane.counters()["serve.native.exemplar_drops"] > 0
    finally:
        plane.destroy()


# ---------------------------------------------------------------------------
# e2e: the chain wires the plane — and degrades gracefully without it
# ---------------------------------------------------------------------------

def _drive(worker, n=6):
    host, port = worker.address
    with VerifyClient(host, port) as cl:
        for i in range(n):
            out = cl.verify_batch([f"w{i}-a.ok", f"w{i}-b.ok",
                                   f"w{i}-c.bad"])
            assert len(out) == 3
    time.sleep(0.3)


@needs_tel
def test_chain_decision_counters_equal_across_chains():
    """The cross-chain gate: identical load on the python chain and
    the native chain (plane on) must produce IDENTICAL serve-surface
    decision counters — obs costs less natively, never counts
    differently."""
    telemetry.enable()
    telemetry.active().reset()
    w = VerifyWorker(StubKeySet(), max_wait_ms=1.0)  # python chain
    try:
        _drive(w)
        py_counters = {
            k: v for k, v in w.stats()["counters"].items()
            if k.startswith("decision.serve.")}
    finally:
        w.close(deadline_s=10)
        telemetry.disable()

    telemetry.enable(telemetry.Recorder())
    w = VerifyWorker(StubKeySet(), serve_native=True, max_wait_ms=1.0)
    try:
        assert w.serve_chain == "native"
        assert w._native.obs_plane is not None
        _drive(w)
        st = w.stats()
        nat_counters = {
            k: v for k, v in st["counters"].items()
            if k.startswith("decision.serve.")}
        assert nat_counters == py_counters
        # the merged snapshot carries them too (scrape/postmortem path)
        assert {k: v for k, v
                in st["snapshot"]["counters"].items()
                if k.startswith("decision.serve.")} == py_counters
        # the plane's series merged in and summarized
        assert "serve.native.request_s" in st["series"]
        # exemplars landed in the recorder's ring via the pump
        rec = telemetry.active()
        assert any(d.get("surface") == "serve"
                   for d in rec.decisions())
        # nothing double-counted: the recorder itself holds NO native
        # decision counters (they live in the plane)
        assert not any(k.startswith("decision.serve.")
                       for k in rec.counters())
    finally:
        w.close(deadline_s=10)
        telemetry.disable()


@needs_tel
def test_native_obs_env_kill_switch_falls_back_to_python_fold(
        monkeypatch):
    """CAP_SERVE_NATIVE_OBS=0: native chain still serves, the decision
    fold runs in Python, counters land in the recorder as before."""
    monkeypatch.setenv("CAP_SERVE_NATIVE_OBS", "0")
    telemetry.enable(telemetry.Recorder())
    w = VerifyWorker(StubKeySet(), serve_native=True, max_wait_ms=1.0)
    try:
        assert w.serve_chain == "native"
        assert w._native.obs_plane is None
        _drive(w, n=3)
        rec = telemetry.active()
        counters = rec.counters()
        assert counters.get("decision.serve.accept") == 6
        assert counters.get(
            "decision.serve.reject.bad_signature") == 3
        assert w._obs_gauges()["serve.native.obs_plane"] == 0.0
    finally:
        w.close(deadline_s=10)
        telemetry.disable()


@needs_tel
def test_obs_off_means_no_plane_and_no_decision_counters():
    """Telemetry disabled: the plane never attaches and the serve
    chain does zero decision accounting (the obs-off bench point)."""
    telemetry.disable()
    w = VerifyWorker(StubKeySet(), serve_native=True, max_wait_ms=1.0)
    try:
        assert w.serve_chain == "native"
        assert w._native.obs_plane is None
        _drive(w, n=2)
        st = w.stats()
        assert not any(k.startswith("decision.")
                       for k in st["counters"])
    finally:
        w.close(deadline_s=10)


@needs_tel
def test_occupancy_counters_equal_across_chains():
    """r22 randomized parity sweep: the same seeded frame plan driven
    sequentially through each chain must land BIT-EQUAL occupancy
    dispatch/interval counters — sequential blocking drives make every
    frame exactly one flush, so N frames == N dispatches == N stub
    intervals on both chains, timing-independent. (Flush-reason NAMES
    legitimately differ — "timeout" python, "handoff" native — so the
    equality set is the dispatch/interval counters; the flush EQUATION
    is asserted per chain instead.)"""
    from cap_tpu.obs import occupancy

    rng = random.Random(22)
    plan = [[f"occ{i}-{j}.ok" for j in range(rng.randint(1, 4))]
            for i in range(12)]

    def run(native):
        occupancy.reset()
        telemetry.enable(telemetry.Recorder())
        w = VerifyWorker(StubKeySet(), serve_native=native,
                         max_wait_ms=1.0)
        try:
            if native:
                assert w.serve_chain == "native"
            host, port = w.address
            with VerifyClient(host, port) as cl:
                for frame in plan:
                    assert len(cl.verify_batch(frame)) == len(frame)
            time.sleep(0.3)
            st = w.stats()
            return dict(st["counters"]), set(st["series"])
        finally:
            w.close(deadline_s=10)
            telemetry.disable()
            occupancy.reset()

    py_c, _ = run(native=False)
    nat_c, nat_series = run(native=True)

    eq = ("device.dispatches", "device.stub.intervals")
    assert {k: py_c.get(k) for k in eq} \
        == {k: nat_c.get(k) for k in eq} \
        == {"device.dispatches": len(plan),
            "device.stub.intervals": len(plan)}
    for c in (py_c, nat_c):
        flush_sum = sum(v for k, v in c.items()
                        if k.startswith("batcher.flush."))
        assert flush_sum == c.get("batcher.flushes") \
            == c.get("device.dispatches")
        assert c.get("device.wall_us", 0) > 0
        assert 0 <= c.get("device.busy_us", 0) <= c["device.wall_us"]
    # native ring-wait handshake held: measured series, zero fallbacks
    assert nat_c.get("serve.native.occ_fallbacks", 0) == 0
    assert "queue.ring_wait_s" in nat_series


@needs_tel
def test_ring_hwm_gauge_resets_on_scrape():
    telemetry.enable(telemetry.Recorder())
    w = VerifyWorker(StubKeySet(), serve_native=True, max_wait_ms=1.0)
    try:
        _drive(w, n=4)
        g = w._obs_gauges()
        assert "serve.native.ring_hwm" in g
        assert g["serve.native.ring_hwm"] >= 0.0
        # the scrape rearmed the mark at live depth (idle now → ~0)
        assert w._native.ring_hwm(reset=False) <= \
            g["serve.native.ring_hwm"]
    finally:
        w.close(deadline_s=10)
        telemetry.disable()
