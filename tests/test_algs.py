import pytest

from cap_tpu.errors import UnsupportedAlgError
from cap_tpu.jwt import algs
from cap_tpu.jwt.algs import supported_signing_algorithm


def test_registry_pinned():
    # The reference's ten asymmetric algorithms (jwt/algs.go:6-22)
    # plus the post-quantum families — ML-DSA (FIPS 204) and SLH-DSA
    # (FIPS 205), docs/PQC.md — and NOTHING else.
    assert algs.SUPPORTED_ALGORITHMS == {
        "RS256", "RS384", "RS512",
        "ES256", "ES384", "ES512",
        "PS256", "PS384", "PS512",
        "EdDSA",
        "ML-DSA-44", "ML-DSA-65", "ML-DSA-87",
        "SLH-DSA-SHAKE-128s", "SLH-DSA-SHAKE-128f",
    }
    assert algs.MLDSA_ALGORITHMS == {"ML-DSA-44", "ML-DSA-65",
                                     "ML-DSA-87"}
    assert algs.SLHDSA_ALGORITHMS == {"SLH-DSA-SHAKE-128s",
                                      "SLH-DSA-SHAKE-128f"}
    assert algs.PQ_ALGORITHMS == (algs.MLDSA_ALGORITHMS
                                  | algs.SLHDSA_ALGORITHMS)
    supported_signing_algorithm(*algs.SUPPORTED_ALGORITHMS)


@pytest.mark.parametrize("bad", ["none", "HS256", "HS384", "HS512", "rs256", "", "ES521"])
def test_unsupported_rejected(bad):
    with pytest.raises(UnsupportedAlgError):
        supported_signing_algorithm(bad)


def test_mixed_lists_rejected():
    with pytest.raises(UnsupportedAlgError):
        supported_signing_algorithm("RS256", "none")
