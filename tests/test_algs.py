import pytest

from cap_tpu.errors import UnsupportedAlgError
from cap_tpu.jwt import algs
from cap_tpu.jwt.algs import supported_signing_algorithm


def test_all_ten_supported():
    # The same ten asymmetric algorithms as the reference (jwt/algs.go:6-22).
    assert algs.SUPPORTED_ALGORITHMS == {
        "RS256", "RS384", "RS512",
        "ES256", "ES384", "ES512",
        "PS256", "PS384", "PS512",
        "EdDSA",
    }
    supported_signing_algorithm(*algs.SUPPORTED_ALGORITHMS)


@pytest.mark.parametrize("bad", ["none", "HS256", "HS384", "HS512", "rs256", "", "ES521"])
def test_unsupported_rejected(bad):
    with pytest.raises(UnsupportedAlgError):
        supported_signing_algorithm(bad)


def test_mixed_lists_rejected():
    with pytest.raises(UnsupportedAlgError):
        supported_signing_algorithm("RS256", "none")
