"""Fleet layer: placement model, pool supervision, failover routing.

Stub workers only (no jax in the children — worker_main's stub spec
never imports it), so the whole suite is subprocess-cheap and runs in
tier-1 under ``JAX_PLATFORMS=cpu``. The chaos fault-injection suite
lives in test_fleet_chaos.py.
"""

import socket
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, FleetExhaustedError, WorkerPool
from cap_tpu.fleet.worker_main import StubKeySet, make_keyset
from cap_tpu.parallel.place import (
    PlacementError,
    WorkerPlacement,
    assert_single_owner,
    single_owner_placement,
)


# ---------------------------------------------------------------------------
# placement model
# ---------------------------------------------------------------------------

def test_single_owner_placement_disjoint():
    ps = single_owner_placement(4, 8, platform="cpu")
    assert [p.device_ids for p in ps] == [(0, 1), (2, 3), (4, 5), (6, 7)]
    assert_single_owner(ps)           # no device has two owners
    env = ps[1].env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["CAP_FLEET_CPU_DEVICES"] == "2"
    assert env["CAP_FLEET_DEVICE_GROUP"] == "2,3"
    assert env["CAP_FLEET_WORKER_ID"] == "1"


def test_single_owner_placement_tpu_env():
    ps = single_owner_placement(2, 4, platform="tpu")
    env = ps[0].env()
    assert env["JAX_PLATFORMS"] == "tpu"
    assert env["TPU_VISIBLE_DEVICES"] == "0,1"


def test_placement_rejects_overcommit():
    with pytest.raises(PlacementError, match="double-book"):
        single_owner_placement(3, 4, devices_per_worker=2)
    with pytest.raises(PlacementError, match="no device"):
        single_owner_placement(5, 4)
    with pytest.raises(PlacementError, match="at least one"):
        single_owner_placement(0, 4)


def test_assert_single_owner_catches_overlap():
    ps = [WorkerPlacement(0, (0, 1)), WorkerPlacement(1, (1, 2))]
    with pytest.raises(PlacementError, match="device 1 owned by both"):
        assert_single_owner(ps)


def test_make_keyset_specs():
    ks = make_keyset("stub:batch_ms=1.5,token_us=2")
    assert isinstance(ks, StubKeySet)
    assert ks._batch_s == pytest.approx(0.0015)
    with pytest.raises(ValueError, match="unknown stub option"):
        make_keyset("stub:bogus=1")
    with pytest.raises(ValueError, match="unknown keyset spec"):
        make_keyset("nope")


# ---------------------------------------------------------------------------
# pool + router (live subprocesses)
# ---------------------------------------------------------------------------

@pytest.fixture
def pool():
    p = WorkerPool(2, keyset_spec="stub", ping_interval=0.2,
                   max_restarts=10)
    assert p.wait_all_ready(30), "fleet did not come up"
    yield p
    p.close()


def test_pool_spawns_with_disjoint_placement(pool):
    pm = pool.placement_map()
    assert len(pm) == 2
    assert set(pm[0]).isdisjoint(pm[1])
    eps = pool.endpoints()
    assert len(eps) == 2
    assert eps[0] != eps[1]           # two sockets, two processes
    assert pool.pid(0) != pool.pid(1)


def test_router_roundtrip_and_balance(pool):
    cl = FleetClient(pool, fallback=StubKeySet())
    for i in range(6):
        res = cl.verify_batch([f"t{i}.ok", "bad-token"])
        assert res[0] == {"sub": f"t{i}.ok"}
        assert isinstance(res[1], Exception)
    stats = pool.stats()
    served = {wid: (s or {}).get("counters", {}).get("worker.requests", 0)
              for wid, s in stats.items()}
    # round-robin: both workers saw traffic
    assert all(n >= 1 for n in served.values()), served


def test_pool_stats_aggregation(pool):
    cl = FleetClient(pool)
    cl.verify_batch(["a.ok"])
    stats = pool.stats()
    assert sorted(stats) == [0, 1]
    for s in stats.values():
        assert s is not None
        assert {"pid", "queued_tokens", "inflight_batches",
                "counters"} <= set(s)


def test_pool_graceful_restart_new_process(pool):
    old_pid = pool.pid(0)
    pool.restart(0, graceful=True)
    assert pool.wait_all_ready(30)
    assert pool.state(0) == "ready"
    assert pool.pid(0) != old_pid
    cl = FleetClient(pool, fallback=StubKeySet())
    assert cl.verify_batch(["r.ok"])[0] == {"sub": "r.ok"}


def test_router_skips_dead_endpoint_and_opens_breaker(pool):
    # A port with nothing listening, plus the live fleet.
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_addr = dead.getsockname()
    dead.close()                       # nothing listens here now
    eps = [dead_addr] + list(pool.endpoints().values())
    cl = FleetClient(eps, fallback=StubKeySet(), attempt_timeout=1.0,
                     breaker_threshold=1, breaker_reset_s=30.0)
    with telemetry.recording() as rec:
        for i in range(4):
            assert cl.verify_batch([f"d{i}.ok"])[0] == {"sub": f"d{i}.ok"}
    # first batch failed over; later batches skip the open breaker
    assert rec.counters().get("fleet.failovers", 0) >= 1
    states = cl.breaker_states()
    assert states[dead_addr]["open_for_s"] > 0


def test_router_exhausted_without_fallback_raises():
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_addr = dead.getsockname()
    dead.close()
    cl = FleetClient([dead_addr], attempt_timeout=0.5,
                     total_deadline=2.0, max_rounds=2)
    # No fallback: the batch RAISES — transport failure must never be
    # translated into per-token rejections (that would be a wrong
    # verdict for a valid token).
    with pytest.raises(FleetExhaustedError):
        cl.verify_batch(["x.ok"])


def test_router_empty_batch_no_network():
    cl = FleetClient([("127.0.0.1", 1)])   # nothing listening
    assert cl.verify_batch([]) == []


def test_router_concurrent_batches(pool):
    cl = FleetClient(pool, fallback=StubKeySet())
    results = {}

    def one(i):
        results[i] = cl.verify_batch([f"c{i}-{j}.ok" for j in range(4)])

    threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 8
    for i in range(8):
        assert [r["sub"] for r in results[i]] == [
            f"c{i}-{j}.ok" for j in range(4)]


def test_respawned_worker_rejoins_routing(pool):
    cl = FleetClient(pool, fallback=StubKeySet())
    cl.verify_batch(["warm.ok"])
    pool.restart(1, graceful=False)
    assert pool.wait_all_ready(30)
    # endpoints() re-polled per round: the NEW port serves traffic
    with telemetry.recording():
        for i in range(4):
            assert cl.verify_batch([f"n{i}.ok"])[0] == {"sub": f"n{i}.ok"}
    stats = pool.stats()
    assert stats[1] is not None
    assert stats[1]["counters"].get("worker.requests", 0) >= 1
