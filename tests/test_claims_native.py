"""Native claims-rule engine: differential parity, fallback matrix,
registry pins, build health, and the serve-surface wiring.

The engine (runtime/native/claims_validate.cpp, bound by
cap_tpu/oidc/claims_native.py) evaluates the pure-comparison subset
of the OIDC registered-claims rules in C off the phase-1 tape; its
verdicts — and exception classes, and therefore obs reason classes —
must be indistinguishable from the Python rules for EVERY input, with
parse corners and rare-flag arms falling back per token. Everything
here is crypto-free (the stub signature seam) and jax-free.
"""

from __future__ import annotations

import base64
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from claims_parity import (  # noqa: E402
    SIG_OK,
    DifferentialStubKeySet,
    make_rig,
    run_sweep,
    token_for,
)
from gen_claims_corpus import (  # noqa: E402
    CLIENT,
    FIXED_NOW,
    ISSUER,
    NONCE,
    POLICIES,
    SEED,
    build_corpus,
    corpus_sha256,
)

from cap_tpu import errors as cap_errors
from cap_tpu import telemetry
from cap_tpu.obs import decision
from cap_tpu.oidc import claims_native

# A generator edit that changes coverage must re-pin here, visibly
# (the gen_go_golden byte-stability stance).
CORPUS_SHA256 = \
    "7a9834f33c88e27d65fddbd3cec71d6198619b714b1ac0054809eeb9edec312b"
CORPUS_CASES = 1050


def _b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


_HDR = _b64(json.dumps({"alg": "ES256"}).encode())


def _tok(payload_text: str, hdr: str = _HDR) -> str:
    return f"{hdr}.{_b64(payload_text.encode())}.{SIG_OK}"


def _claims(**over):
    c = {"iss": ISSUER, "sub": "alice", "aud": [CLIENT],
         "exp": FIXED_NOW + 3600, "iat": FIXED_NOW - 10,
         "nonce": NONCE}
    for k, v in over.items():
        if v is ...:
            c.pop(k, None)
        else:
            c[k] = v
    return json.dumps(c, separators=(",", ":"))


@pytest.fixture()
def rig():
    return make_rig(POLICIES[0])


@pytest.fixture()
def native_on(monkeypatch):
    monkeypatch.setenv("CAP_OIDC_NATIVE", "1")
    if not claims_native.enabled():
        pytest.skip("native claims engine unavailable on this host")


# ---------------------------------------------------------------------------
# registries: fixed order, complete, mapped onto errors.py by NAME
# ---------------------------------------------------------------------------

def test_status_registry_shape():
    assert claims_native.STATUS_INDEX[0] == "ok"
    assert claims_native.STATUS_INDEX[1] == "fallback"
    # every non-terminal status maps by NAME onto the errors taxonomy
    for name in claims_native.STATUS_INDEX[2:]:
        cls_name = claims_native.STATUS_ERROR_NAMES[name]
        cls = getattr(cap_errors, cls_name)
        assert issubclass(cls, cap_errors.CapError)
    assert set(claims_native.STATUS_ERROR_NAMES) == \
        set(claims_native.STATUS_INDEX[2:])


def test_status_errors_classify_like_python():
    """Every native reject class lands in the SAME obs reason class
    the Python engine's exception would."""
    want = {
        "missing_exp": "invalid_claims",
        "expired": "expired",
        "not_before": "invalid_claims",
        "wrong_issuer": "invalid_claims",
        "unsupported_alg": "unsupported_alg",
        "wrong_nonce": "invalid_claims",
        "future_iat": "invalid_claims",
        "aud_non_string": "invalid_claims",
        "aud_mismatch": "invalid_claims",
        "multi_aud_missing_client": "invalid_claims",
        "azp_mismatch": "invalid_claims",
    }
    for idx, name in enumerate(claims_native.STATUS_INDEX):
        if name in ("ok", "fallback"):
            continue
        err = claims_native.status_error(idx, alg="ES256",
                                         client_id=CLIENT, now=0.0)
        assert decision.classify(err) == want[name], name


def test_layout_handshake_matches_registry(native_on):
    import ctypes

    from cap_tpu.runtime import native_binding

    layout = np.zeros(2, np.int32)
    native_binding._lib.cap_claims_layout(
        layout.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert layout.tolist() == [claims_native.LAYOUT_VERSION,
                               len(claims_native.STATUS_INDEX)]


def test_layout_drift_disables_engine(monkeypatch):
    """A stale .so reporting a different status-registry length must
    refuse to enable — never misclassify."""
    monkeypatch.setattr(claims_native, "LAYOUT_VERSION", 999)
    monkeypatch.setattr(claims_native, "_engine", None)
    monkeypatch.setattr(claims_native, "_engine_probed", False)
    monkeypatch.setenv("CAP_OIDC_NATIVE", "1")
    assert not claims_native.enabled()


# ---------------------------------------------------------------------------
# corpus: byte-stable generation, three-engine differential sweep
# ---------------------------------------------------------------------------

def test_corpus_is_byte_stable():
    cases = build_corpus(SEED)
    assert len(cases) == CORPUS_CASES
    assert corpus_sha256(cases) == CORPUS_SHA256, (
        "corpus generation changed — review coverage and re-pin "
        "CORPUS_SHA256")


def test_corpus_differential_sweep(native_on):
    """THE acceptance gate: ~1k adversarial cases through the dict
    path, the raw-path Python rules, and the native engine — verdicts
    and reason classes bit-identical, every status exercised."""
    problems, status_counts = run_sweep()
    assert problems == []
    for name in claims_native.STATUS_INDEX:
        assert status_counts.get(name, 0) > 0, \
            f"native status {name!r} never exercised by the corpus"


def test_thirteen_vector_suite_both_engines(rig, native_on,
                                            monkeypatch):
    """The r5 13-vector differential suite, crypto-free, swept with
    the native engine ON and OFF — verdict classes pinned equal."""
    provider, request = make_rig(POLICIES[0])
    good = _claims()
    vectors = [
        ("good", _tok(good)),
        ("expired", _tok(_claims(exp=1000))),
        ("future-nbf", _tok(_claims(nbf=2 ** 33))),
        ("wrong-nonce", _tok(_claims(nonce="nope"))),
        ("wrong-aud", _tok(_claims(aud=["other"]))),
        ("aud-string", _tok(_claims(aud=CLIENT))),
        ("multi-aud-azp", _tok(_claims(aud=[CLIENT, "x"],
                                       azp=CLIENT))),
        ("multi-aud-bad-azp", _tok(_claims(aud=[CLIENT, "x"],
                                           azp="intruder"))),
        ("aud-object-fallback", _tok(_claims(aud={"weird": 1}))),
        ("escaped-key-fallback",
         _tok(good.replace('"iss"', '"i\\u0073s"'))),
        ("wrong-issuer", _tok(_claims(iss="https://evil.example/"))),
        ("tampered", _tok(good)[:-2] + "xx"),
        ("not-a-jwt", "garbage"),
    ]
    names, toks = zip(*vectors)
    dict_out = provider.verify_id_token_batch(list(toks), request)
    monkeypatch.setenv("CAP_OIDC_NATIVE", "0")
    py_out = provider.verify_id_token_batch(list(toks), request,
                                            raw=True)
    monkeypatch.setenv("CAP_OIDC_NATIVE", "1")
    nat_out = provider.verify_id_token_batch(list(toks), request,
                                             raw=True)
    for name, d, py, na in zip(names, dict_out, py_out, nat_out):
        assert isinstance(d, Exception) == isinstance(py, Exception) \
            == isinstance(na, Exception), name
        if isinstance(d, Exception):
            assert type(d) is type(py) is type(na), \
                f"{name}: {type(d)} vs {type(py)} vs {type(na)}"
            assert decision.classify(d) == decision.classify(na), name
        else:
            assert py == na and json.loads(na) == d, name


def test_multi_aud_non_string_rejects_on_both_engines(rig,
                                                      native_on,
                                                      monkeypatch):
    """The satellite fix, pinned: ["client", 42] used to validate as
    single-audience (non-strings silently dropped); now it rejects
    with InvalidAudienceError on the dict path, the raw Python rules,
    and the native engine."""
    provider, request = make_rig(POLICIES[0])
    toks = [_tok(_claims(aud=[CLIENT, 42])),
            _tok(_claims(aud=[42])),
            _tok(_claims(aud=[CLIENT, None])),
            _tok(_claims(aud=[True]))]
    for env in ("0", "1"):
        monkeypatch.setenv("CAP_OIDC_NATIVE", env)
        for out in (provider.verify_id_token_batch(toks, request),
                    provider.verify_id_token_batch(toks, request,
                                                   raw=True)):
            for r in out:
                assert isinstance(r, cap_errors.InvalidAudienceError), \
                    (env, r)


# ---------------------------------------------------------------------------
# fallback matrix + counters (graceful degradation acceptance)
# ---------------------------------------------------------------------------

def _counters_after_raw(provider, request, toks):
    rec = telemetry.enable()
    rec.reset()
    out = provider.verify_id_token_batch(toks, request, raw=True)
    counters = {k: v for k, v in rec.counters().items()
                if k.startswith("oidc.")}
    telemetry.disable()
    return out, counters


def test_env_kill_switch_falls_back_with_counter(rig, monkeypatch):
    provider, request = rig
    monkeypatch.setenv("CAP_OIDC_NATIVE", "0")
    toks = [_tok(_claims()) for _ in range(5)]
    out, counters = _counters_after_raw(provider, request, toks)
    assert not any(isinstance(r, Exception) for r in out)
    assert counters.get("oidc.native_fallbacks", 0) == 5
    assert "oidc.native_validated" not in counters


def test_native_arm_counts_validated(rig, native_on, monkeypatch):
    provider, request = rig
    toks = [_tok(_claims()) for _ in range(4)] + \
        [_tok(_claims().replace('"iss"', '"i\\u0073s"'))]
    out, counters = _counters_after_raw(provider, request, toks)
    assert not any(isinstance(r, Exception) for r in out)
    assert counters.get("oidc.native_validated", 0) == 4
    # the escaped-key corner fell back per token, visibly
    assert counters.get("oidc.native_fallbacks", 0) == 1


def test_missing_engine_falls_back_gracefully(rig, monkeypatch):
    """Stale-.so arm: the probed engine is gone → whole batch takes
    the Python rules with the fallback counter, verdicts unchanged."""
    provider, request = rig
    monkeypatch.setenv("CAP_OIDC_NATIVE", "1")
    monkeypatch.setattr(claims_native, "_engine", None)
    monkeypatch.setattr(claims_native, "_engine_probed", True)
    toks = [_tok(_claims()), _tok(_claims(exp=FIXED_NOW - 5))]
    out, counters = _counters_after_raw(provider, request, toks)
    assert not isinstance(out[0], Exception)
    assert isinstance(out[1], cap_errors.ExpiredTokenError)
    assert counters.get("oidc.native_fallbacks", 0) == 2


def test_max_age_policy_takes_python_arm(native_on, monkeypatch):
    """The auth_time/max_age rare-flag arm: every token under a
    max_age policy falls back (counted), verdicts still identical to
    the dict path."""
    provider, request = make_rig(POLICIES[3])
    assert POLICIES[3]["max_age"] is not None
    toks = [_tok(_claims(auth_time=FIXED_NOW - 30)),
            _tok(_claims())]
    monkeypatch.setenv("CAP_OIDC_NATIVE", "1")
    out, counters = _counters_after_raw(provider, request, toks)
    dict_out = provider.verify_id_token_batch(toks, request)
    for d, r in zip(dict_out, out):
        assert isinstance(d, Exception) == isinstance(r, Exception)
        if isinstance(d, Exception):
            assert type(d) is type(r)
    assert counters.get("oidc.native_fallbacks", 0) == 2


def test_policy_blob_roundtrip(native_on):
    """pack_policy → native parse: a same-policy batch evaluates; a
    truncated blob makes the engine refuse (None → Python path)."""
    pol = claims_native.pack_policy(ISSUER, CLIENT, NONCE,
                                    ["a", "b"], 60.0, False)
    payloads = [_claims().encode()]
    ok = claims_native.validate_payloads(
        payloads, np.ones(1, np.uint8), FIXED_NOW, pol)
    assert ok is not None
    bad = claims_native.validate_payloads(
        payloads, np.ones(1, np.uint8), FIXED_NOW, pol[:-3])
    assert bad is None


# ---------------------------------------------------------------------------
# serve surface: the worker serves verify-AND-validate
# ---------------------------------------------------------------------------

def _serve_rig():
    from cap_tpu.fleet.worker_main import make_keyset

    return make_keyset(
        f"oidc-rp:issuer={ISSUER};client={CLIENT};nonce={NONCE}")


def test_worker_serves_oidc_surface(native_on):
    import time

    from cap_tpu.serve.client import VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    now = time.time()
    good = json.dumps({"iss": ISSUER, "sub": "a", "aud": [CLIENT],
                       "exp": now + 3600, "nonce": NONCE},
                      separators=(",", ":"))
    bad_iss = good.replace(ISSUER, "https://evil.example/")
    rec = telemetry.enable()
    rec.reset()
    ks = _serve_rig()
    w = VerifyWorker(ks, max_wait_ms=1.0)
    try:
        with VerifyClient(*w.address) as cl:
            out = cl.verify_batch([
                f"{_HDR}.{_b64(good.encode())}.ok",
                f"{_HDR}.{_b64(bad_iss.encode())}.ok",
                f"{_HDR}.{_b64(good.encode())}.bad",
            ])
        assert json.loads(json.dumps(out[0])) == json.loads(good)
        assert isinstance(out[1], Exception)
        assert str(out[1]).startswith("InvalidIssuerError")
        assert isinstance(out[2], Exception)
        assert str(out[2]).startswith("InvalidSignatureError")
        # the fallback/validated counters ride worker STATS — the
        # "visible in scrapes" acceptance (stats shares the recorder
        # the obs server scrapes)
        stats = w.stats()
        oidc_counters = {k: v for k, v in stats["counters"].items()
                         if k.startswith("oidc.")}
        assert oidc_counters.get("oidc.native_validated", 0) >= 2
    finally:
        w.close(deadline_s=10)
        telemetry.disable()


def test_worker_oidc_surface_python_arm(monkeypatch):
    """CAP_OIDC_NATIVE=0 end-to-end: same verdicts, fallback counter
    visible in the worker's STATS scrape."""
    import time

    from cap_tpu.serve.client import VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    monkeypatch.setenv("CAP_OIDC_NATIVE", "0")
    now = time.time()
    good = json.dumps({"iss": ISSUER, "sub": "a", "aud": [CLIENT],
                       "exp": now + 3600, "nonce": NONCE},
                      separators=(",", ":"))
    rec = telemetry.enable()
    rec.reset()
    w = VerifyWorker(_serve_rig(), max_wait_ms=1.0)
    try:
        with VerifyClient(*w.address) as cl:
            out = cl.verify_batch([f"{_HDR}.{_b64(good.encode())}.ok"])
        assert not isinstance(out[0], Exception)
        stats = w.stats()
        assert stats["counters"].get("oidc.native_fallbacks", 0) >= 1
        assert "oidc.native_validated" not in stats["counters"]
    finally:
        w.close(deadline_s=10)
        telemetry.disable()


def test_oidc_rp_spec_parsing():
    from cap_tpu.fleet.worker_main import make_keyset
    from cap_tpu.oidc.serve_keyset import OIDCRawKeySet

    ks = make_keyset(
        f"oidc-rp:issuer={ISSUER};client={CLIENT};nonce=n1;"
        "algs=ES256+RS256;aud=a+b;keyset=stub:raw=1,echo=1")
    assert isinstance(ks, OIDCRawKeySet)
    assert ks.provider.config.supported_signing_algs == \
        ["ES256", "RS256"]
    assert ks.provider.config.audiences == ["a", "b"]
    with pytest.raises(ValueError, match="unknown oidc-rp option"):
        make_keyset("oidc-rp:issuer=x;bogus=1")


def test_stub_echo_payload():
    from cap_tpu.fleet.worker_main import StubKeySet

    ks = StubKeySet(raw=1, echo=1)
    payload = b'{"sub":"me"}'
    tok = f"h.{_b64(payload)}.ok"
    out = ks.verify_batch_raw([tok, "h.!!bad-b64!!.ok", "x.bad"])
    assert out[0] == payload
    assert out[1] == b'{"sub":"stub"}'   # undecodable → fixed payload
    assert isinstance(out[2], Exception)


# ---------------------------------------------------------------------------
# build health: the r12 native-build gate extended — a silently-dead
# claims TU is impossible (all four TUs from source to a temp .so,
# cap_claims_* must resolve)
# ---------------------------------------------------------------------------

def test_native_build_all_four_tus_and_claims_symbols(tmp_path):
    import ctypes
    import shutil

    from cap_tpu import _build

    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler on this host")
    out = str(tmp_path / "libcapruntime_claims_test.so")
    _build._build_one(
        (os.path.join("runtime", "native", "jose_native.cpp"),
         os.path.join("runtime", "native", "serve_native.cpp"),
         os.path.join("runtime", "native", "telemetry_native.cpp"),
         os.path.join("runtime", "native", "claims_validate.cpp"),
         os.path.join("runtime", "native", "shm_ring.cpp")),
        out, False, timeout=300.0, force=True)
    assert os.path.exists(out), "native build produced no library"
    lib = ctypes.CDLL(out)
    for sym in ("cap_claims_layout", "cap_claims_validate_batch"):
        assert hasattr(lib, sym), f"symbol {sym} missing"
    layout = np.zeros(2, np.int32)
    lib.cap_claims_layout(
        layout.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert layout.tolist() == [claims_native.LAYOUT_VERSION,
                               len(claims_native.STATUS_INDEX)]


# ---------------------------------------------------------------------------
# doc pins
# ---------------------------------------------------------------------------

def test_docs_pin_status_table_and_metrics():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "docs", "PERF.md")) as f:
        perf = f.read()
    for name in claims_native.STATUS_INDEX:
        assert f"`{name}`" in perf, \
            f"status {name} missing from the PERF.md rule table"
    with open(os.path.join(repo, "docs", "OBSERVABILITY.md")) as f:
        obs = f.read()
    for metric in ("oidc.native_fallbacks", "oidc.native_validated"):
        assert metric in obs
    with open(os.path.join(repo, "docs", "SERVE.md")) as f:
        serve = f.read()
    assert "CAP_OIDC_NATIVE" in serve
