"""Verify service layer: protocol framing, adaptive batcher, worker/client.

The service plumbing is exercised with a stub engine (no device); one
end-to-end test runs a real TPUBatchKeySet behind the worker to pin
the claims/error parity across the wire.
"""

import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.errors import InvalidSignatureError
from cap_tpu.serve import AdaptiveBatcher, VerifyClient, VerifyWorker
from cap_tpu.serve import protocol as P
from cap_tpu.serve.client import RemoteVerifyError


class StubKeySet:
    """Deterministic engine: tokens ending in '.ok' verify."""

    def __init__(self):
        self.batches = []
        self.lock = threading.Lock()

    def verify_batch(self, tokens):
        with self.lock:
            self.batches.append(len(tokens))
        out = []
        for t in tokens:
            if t.endswith(".ok"):
                out.append({"sub": t})
            else:
                out.append(InvalidSignatureError(
                    "no known key successfully validated the token "
                    "signature"))
        return out


@pytest.fixture
def stub_worker():
    ks = StubKeySet()
    w = VerifyWorker(ks, target_batch=64, max_wait_ms=10.0)
    yield ks, w
    w.close()


def test_roundtrip_claims_and_errors(stub_worker):
    ks, w = stub_worker
    host, port = w.address
    with VerifyClient(host, port) as c:
        assert c.ping()
        res = c.verify_batch(["a.ok", "b.bad", "c.ok"])
    assert res[0] == {"sub": "a.ok"}
    assert isinstance(res[1], RemoteVerifyError)
    assert "InvalidSignatureError" in str(res[1])
    assert "b.bad" not in str(res[1])  # never echo the token
    assert res[2] == {"sub": "c.ok"}


def test_single_token_raises(stub_worker):
    _, w = stub_worker
    host, port = w.address
    with VerifyClient(host, port) as c:
        assert c.verify_signature("x.ok") == {"sub": "x.ok"}
        with pytest.raises(RemoteVerifyError):
            c.verify_signature("x.bad")


def test_empty_batch(stub_worker):
    _, w = stub_worker
    host, port = w.address
    with VerifyClient(host, port) as c:
        assert c.verify_batch([]) == []


def test_concurrent_clients_coalesce(stub_worker):
    """Tokens from many connections share device batches."""
    ks, w = stub_worker
    host, port = w.address
    results = {}

    def one(i):
        with VerifyClient(host, port) as c:
            results[i] = c.verify_batch([f"t{i}.ok"] * 8)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(8):
        assert all(r == {"sub": f"t{i}.ok"} for r in results[i])
    # 64 tokens total; coalescing must beat one-dispatch-per-request
    assert len(ks.batches) < 8


def test_batcher_flush_on_target():
    ks = StubKeySet()
    b = AdaptiveBatcher(ks, target_batch=4, max_wait_ms=10_000.0)
    try:
        done = []

        def submit():
            done.append(b.submit(["x.ok"] * 2))

        t1 = threading.Thread(target=submit)
        t2 = threading.Thread(target=submit)
        t1.start()
        t2.start()
        t1.join(timeout=5.0)
        t2.join(timeout=5.0)
        # target (4) reached by two submissions → flushed long before
        # the 10s wait window
        assert len(done) == 2 and all(len(r) == 2 for r in done)
    finally:
        b.close()


def test_batcher_flush_on_timeout():
    ks = StubKeySet()
    b = AdaptiveBatcher(ks, target_batch=1 << 20, max_wait_ms=30.0)
    try:
        t0 = time.monotonic()
        res = b.submit(["lonely.ok"])
        dt = time.monotonic() - t0
        assert res[0] == {"sub": "lonely.ok"}
        assert dt < 5.0  # flushed by the wait window, not the target
    finally:
        b.close()


def test_batcher_engine_failure_fans_out():
    class Broken:
        def verify_batch(self, tokens):
            raise RuntimeError("device fell over")

    b = AdaptiveBatcher(Broken(), target_batch=2, max_wait_ms=5.0)
    try:
        res = b.submit(["a.ok"])
        assert isinstance(res[0], RuntimeError)
    finally:
        b.close()


def test_worker_telemetry(stub_worker):
    _, w = stub_worker
    host, port = w.address
    with telemetry.recording() as rec:
        with VerifyClient(host, port) as c:
            c.verify_batch(["a.ok", "b.ok"])
        # batcher runs on its own thread; give it a beat
        time.sleep(0.1)
    counters = rec.counters()
    assert counters.get("worker.tokens") == 2
    assert counters.get("batcher.flushes", 0) >= 1


def test_end_to_end_real_keyset():
    """Real TPUBatchKeySet behind the wire: parity incl. rejections."""
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    priv, pub = captest.generate_keys("ES256")
    ks = TPUBatchKeySet([JWK(pub, kid="k0")])
    good = captest.sign_jwt(priv, "ES256", captest.default_claims(),
                            kid="k0")
    bad = good[:-8] + ("AAAAAAAA" if not good.endswith("AAAAAAAA")
                       else "BBBBBBBB")
    w = VerifyWorker(ks, target_batch=4, max_wait_ms=5.0)
    try:
        host, port = w.address
        # generous timeout: first call compiles the EC kernels on CPU
        with VerifyClient(host, port, timeout=600.0) as c:
            res = c.verify_batch([good, bad, good])
        assert res[0]["iss"] == res[2]["iss"]
        assert isinstance(res[1], RemoteVerifyError)
    finally:
        w.close()


def test_worker_raw_over_remote_keyset():
    """The serve default (raw claims) must work behind the
    rotation-aware TPURemoteKeySet: the worker routes through the SYNC
    raw adapter (no async entry on remote keysets) and the wire
    responses match the plain-keyset dict path byte-for-byte."""
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import serialize_public_key
    from cap_tpu.jwt.tpu_keyset import TPURemoteKeySet
    from cap_tpu.serve.worker import _RawClaimsSync

    priv, pub = captest.generate_keys("ES256")
    state = {"keys": [serialize_public_key(pub, kid="r0")]}

    with captest.jwks_test_server(state) as (url, _srv):
        ks = TPURemoteKeySet(url, min_refresh_interval=0.0)
        good = captest.sign_jwt(priv, "ES256", captest.default_claims(),
                                kid="r0")
        bad = good[:-8] + ("AAAAAAAA" if not good.endswith("AAAAAAAA")
                           else "BBBBBBBB")
        w = VerifyWorker(ks, target_batch=4, max_wait_ms=5.0)
        try:
            # Exact type: isinstance would also pass for the async
            # subclass, which is the wrong routing for a sync keyset.
            assert type(w._batcher._keyset) is _RawClaimsSync
            host, port = w.address
            with VerifyClient(host, port, timeout=600.0) as c:
                res = c.verify_batch([good, bad, good])
            assert res[0]["iss"] == res[2]["iss"]
            assert isinstance(res[1], RemoteVerifyError)
        finally:
            w.close()


def test_native_client_roundtrip():
    """The C ABI client shim against a live worker (built via make)."""
    pytest.importorskip("ctypes")
    try:
        from cap_tpu.serve.native_client import NativeVerifyClient
    except ImportError:
        pytest.skip("libcapclient.so not built")
    ks = StubKeySet()
    w = VerifyWorker(ks, target_batch=8, max_wait_ms=5.0)
    try:
        host, port = w.address
        with NativeVerifyClient(host, port) as c:
            assert c.ping()
            res = c.verify_batch(["n1.ok", "n2.bad"] * 3)
        assert res[0] == {"sub": "n1.ok"}
        assert isinstance(res[1], RemoteVerifyError)
        assert res[4] == {"sub": "n1.ok"}
    finally:
        w.close()


def test_worker_drops_malformed_frames_quietly(stub_worker):
    """A garbage frame (bad magic / non-UTF8 token bytes) drops the
    connection without an unhandled-exception traceback and bumps the
    worker.protocol_errors counter (ADVICE r1)."""
    import socket
    import struct

    ks, w = stub_worker
    host, port = w.address
    with telemetry.recording() as rec:
        # bad magic
        s = socket.create_connection((host, port))
        s.sendall(b"\xde\xad\xbe\xef" + b"\x01" + struct.pack("<I", 0))
        assert s.recv(1) == b""      # worker closed the connection
        s.close()

        # valid header, token bytes that are not UTF-8
        s = socket.create_connection((host, port))
        s.sendall(struct.pack("<IBI", 0x31425643, 1, 1)
                  + struct.pack("<I", 4) + b"\xff\xfe\xff\xfe")
        assert s.recv(1) == b""
        s.close()
    assert rec.counters().get("worker.protocol_errors", 0) >= 2

    # the worker still serves new connections afterwards
    with VerifyClient(host, port) as c:
        assert c.ping()
        assert c.verify_batch(["z.ok"])[0] == {"sub": "z.ok"}


class _FakeSock:
    """Byte-buffer socket for parser-level frame tests."""

    def __init__(self, data: bytes):
        self._data = data
        self._off = 0

    def recv(self, n):
        chunk = self._data[self._off:self._off + n]
        self._off += len(chunk)
        return chunk

    def recv_into(self, view, n):
        chunk = self.recv(n)
        view[:len(chunk)] = chunk
        return len(chunk)

    def sendall(self, b):
        self._data += b


def _parse_bytes(data: bytes):
    from cap_tpu.serve import protocol as P

    return P.FrameReader(_FakeSock(data)).recv_frame()


class TestFrameHardening:
    """Satellite: bound-check length prefixes, reject oversized /
    negative frames with TYPED errors instead of an allocation or
    hang, validate status bytes and ping/pong counts."""

    def test_oversized_entry_count_typed(self):
        import struct

        from cap_tpu.serve import protocol as P

        data = struct.pack("<IBI", P.MAGIC, P.T_VERIFY_REQ,
                           P.MAX_FRAME_ENTRIES + 1)
        with pytest.raises(P.FrameTooLargeError, match="entries"):
            _parse_bytes(data)

    def test_negative_length_prefix_typed_no_allocation(self):
        # 0xFFFFFFFF is "-1" to a careless i32 reader and a 4 GiB
        # allocation to a careless parser; it must be a typed reject
        # BEFORE any take/allocation of entry bytes.
        import struct

        from cap_tpu.serve import protocol as P

        data = (struct.pack("<IBI", P.MAGIC, P.T_VERIFY_REQ, 1)
                + struct.pack("<I", 0xFFFFFFFF))
        with pytest.raises(P.FrameTooLargeError, match="bytes"):
            _parse_bytes(data)

    def test_aggregate_frame_cap(self):
        import struct

        from cap_tpu.serve import protocol as P

        # Each entry is legal on its own; the SUM crosses the
        # aggregate cap and must be rejected at the crossing entry.
        n = P.MAX_FRAME_BYTES // P.MAX_ENTRY_BYTES + 1
        parts = [struct.pack("<IBI", P.MAGIC, P.T_VERIFY_REQ, n)]
        entry = b"\x00" * P.MAX_ENTRY_BYTES
        for _ in range(n):
            parts.append(struct.pack("<I", P.MAX_ENTRY_BYTES))
            parts.append(entry)
        with pytest.raises(P.FrameTooLargeError):
            _parse_bytes(b"".join(parts))

    def test_bad_magic_typed(self):
        from cap_tpu.serve import protocol as P

        with pytest.raises(P.MalformedFrameError, match="magic"):
            _parse_bytes(b"\xde\xad\xbe\xef\x01\x00\x00\x00\x00")

    def test_unknown_type_typed(self):
        import struct

        from cap_tpu.serve import protocol as P

        with pytest.raises(P.MalformedFrameError, match="unknown"):
            _parse_bytes(struct.pack("<IBI", P.MAGIC, 99, 0))

    def test_ping_with_nonzero_count_rejected(self):
        # A corrupt count on an entry-less frame would desync every
        # later frame on the connection — reject it outright.
        import struct

        from cap_tpu.serve import protocol as P

        data = struct.pack("<IBI", P.MAGIC, P.T_PING, 3)
        with pytest.raises(P.MalformedFrameError, match="nonzero"):
            _parse_bytes(data)

    def test_bad_status_byte_rejected(self):
        import struct

        from cap_tpu.serve import protocol as P

        data = (struct.pack("<IBI", P.MAGIC, P.T_VERIFY_RESP, 1)
                + struct.pack("<BI", 7, 2) + b"{}")
        with pytest.raises(P.MalformedFrameError, match="status"):
            _parse_bytes(data)

    def test_crc_roundtrip_and_every_byte_protected(self):
        from cap_tpu.serve import protocol as P

        sock = _FakeSock(b"")
        P.send_response(sock, [{"sub": "a"}, ValueError("no")], crc=True)
        frame = sock._data
        ftype, entries = _parse_bytes(frame)
        assert ftype == P.T_VERIFY_RESP_CRC
        assert entries[0] == (0, b'{"sub":"a"}')
        assert entries[1][0] == 1
        # Flip EVERY byte in turn: each corruption must raise — a
        # typed ProtocolError, or ConnectionError when the flip makes
        # a length field overrun the buffered bytes. NEVER altered
        # entries returned as data.
        for off in range(len(frame)):
            bad = bytearray(frame)
            bad[off] ^= 0x01
            with pytest.raises((P.ProtocolError, ConnectionError)):
                _parse_bytes(bytes(bad))

    def test_crc_request_roundtrip(self):
        from cap_tpu.serve import protocol as P

        sock = _FakeSock(b"")
        P.send_request(sock, ["tok-a", "tok-b"], crc=True)
        ftype, entries = _parse_bytes(sock._data)
        assert ftype == P.T_VERIFY_REQ_CRC
        assert entries == ["tok-a", "tok-b"]
        bad = bytearray(sock._data)
        bad[-6] ^= 0x40                  # inside the last token
        with pytest.raises(P.FrameCorruptError):
            _parse_bytes(bytes(bad))

    def test_plain_frames_byte_identical_to_cvb1(self):
        # The crc pair is ADDITIVE: default framing must stay exactly
        # the golden-vector CVB1 bytes (Go/native clients).
        from cap_tpu.serve import protocol as P

        s1, s2 = _FakeSock(b""), _FakeSock(b"")
        P.send_request(s1, ["x.y.z"])
        P.send_request(s2, ["x.y.z"], crc=False)
        assert s1._data == s2._data
        assert s1._data[4] == P.T_VERIFY_REQ


def test_worker_stats_op(stub_worker):
    """Satellite: telemetry over the wire. The STATS op returns the
    worker's queue depth, inflight, and telemetry snapshot in-order
    with verifies on the same connection."""
    _, w = stub_worker
    host, port = w.address
    with telemetry.recording():
        with VerifyClient(host, port) as c:
            c.verify_batch(["s1.ok", "s2.ok"])
            st = c.stats()
    assert st["queued_tokens"] == 0
    assert st["inflight_batches"] == 0
    assert st["counters"]["worker.tokens"] == 2
    assert st["counters"]["worker.requests"] == 1
    assert "batcher.batch_size" in st["series"]
    assert st["pid"] > 0


def test_crc_client_end_to_end(stub_worker):
    """A crc=True client speaks the checksummed pair with the worker
    and refuses a downgrade to plain frames."""
    _, w = stub_worker
    host, port = w.address
    with VerifyClient(host, port, crc=True) as c:
        res = c.verify_batch(["e.ok", "e.bad"])
        assert res[0] == {"sub": "e.ok"}
        assert isinstance(res[1], RemoteVerifyError)
        # pipelined stream over crc frames too
        outs = list(c.verify_stream(iter([["p1.ok"], ["p2.ok"]]),
                                    depth=2))
    assert [o[0]["sub"] for o in outs] == ["p1.ok", "p2.ok"]


def test_batcher_max_wait_bounds_latency():
    """A lone submission flushes within ~max_wait_ms even though the
    batch-size target is never reached (the p99 bound of VERDICT r1
    #7: BASELINE.json's tracked latency metric rides this knob)."""
    ks = StubKeySet()
    b = AdaptiveBatcher(ks, target_batch=1 << 20, max_wait_ms=50.0)
    try:
        lat = []
        for _ in range(5):
            t0 = time.monotonic()
            res = b.submit(["t.ok"])
            lat.append(time.monotonic() - t0)
            assert res[0] == {"sub": "t.ok"}
        lat.sort()
        # every flush was timer-driven: at least max_wait, bounded by
        # max_wait plus modest scheduling slack
        assert lat[0] >= 0.045, lat
        assert lat[-1] < 0.5, lat
    finally:
        b.close()


def test_pipelined_stream_order_and_overlap(stub_worker):
    """verify_stream keeps frames in flight on ONE connection and the
    worker answers strictly in request order — including pings and an
    empty batch interleaved mid-stream."""
    ks, w = stub_worker
    host, port = w.address
    cl = VerifyClient(host, port)
    try:
        batches = [[f"t{i}-{j}.ok" for j in range(8)] for i in range(20)]
        batches[7] = []                       # empty mid-stream
        batches[11] = ["bad-token", "x.ok"]   # mixed verdicts
        outs = list(cl.verify_stream(iter(batches), depth=6))
        assert len(outs) == len(batches)
        for i, (req, out) in enumerate(zip(batches, outs)):
            assert len(out) == len(req), f"batch {i}"
            for tok, r in zip(req, out):
                if tok.endswith(".ok"):
                    assert r == {"sub": tok}, f"batch {i}"
                else:
                    assert isinstance(r, RemoteVerifyError)
    finally:
        cl.close()


def test_pipelined_stream_deep_backlog(stub_worker):
    """A depth much larger than the worker's inflight window must
    degrade to TCP backpressure, not deadlock or reorder."""
    ks, w = stub_worker
    host, port = w.address
    cl = VerifyClient(host, port)
    try:
        n = 300
        batches = ([[f"b{i}.ok"] for i in range(n)])
        outs = list(cl.verify_stream(iter(batches), depth=64))
        assert [o[0]["sub"] for o in outs] == [f"b{i}.ok"
                                              for i in range(n)]
    finally:
        cl.close()


def test_batcher_admission_watermark():
    """submit_nowait blocks once max_queued_tokens are waiting (the
    TCP-backpressure path for pipelined connections) and resumes as the
    dispatcher drains the queue."""
    class EchoKeySet:
        def verify_batch(self, tokens):
            return [{"sub": t} for t in tokens]

    # target/max_wait chosen so the queue HOLDS: 4 queued tokens sit
    # below the flush target for ~1.5 s, keeping the watermark binding
    # while the third submission knocks.
    b = AdaptiveBatcher(EchoKeySet(), target_batch=64,
                        max_wait_ms=1500, max_batch=64,
                        max_queued_tokens=4)
    try:
        pendings = []
        t0 = time.monotonic()
        for i in range(2):                  # 4 tokens: fills watermark
            pendings.append(b.submit_nowait([f"a{i}", f"b{i}"]))
        blocked = []

        def third():
            blocked.append(b.submit_nowait(["c0", "c1"]))

        th = threading.Thread(target=third, daemon=True)
        th.start()
        time.sleep(0.4)
        # inside the flush-wait window the queue is saturated: the
        # third submission must still be waiting for admission
        assert not blocked
        # the max_wait flush drains the queue and must release it
        th.join(timeout=10)
        assert blocked, "admission never released"
        for p in pendings + blocked:
            p.event.wait(10)
            assert p.results is not None
            assert all(isinstance(r, dict) for r in p.results)
        assert time.monotonic() - t0 < 15
    finally:
        b.close()


def test_pipelined_stream_abandon_poisons_client(stub_worker):
    """Breaking out of verify_stream leaves responses on the wire; the
    client must refuse further use instead of misattributing them."""
    ks, w = stub_worker
    host, port = w.address
    cl = VerifyClient(host, port)
    batches = [[f"t{i}.ok"] for i in range(10)]
    got = []
    for out in cl.verify_stream(iter(batches), depth=4):
        got.append(out)
        break                                  # abandon mid-stream
    assert got and got[0][0] == {"sub": "t0.ok"}
    with pytest.raises(OSError):
        cl.verify_batch(["x.ok"])


# ---------------------------------------------------------------------------
# torn frames: FrameReader must reassemble frames split at EVERY byte
# boundary across recv() calls (TCP has no message boundaries — a
# frame can arrive one byte at a time, or glued to its neighbors)
# ---------------------------------------------------------------------------

class _ScriptedSocket:
    """recv() serves pre-scripted chunks (never more than asked)."""

    def __init__(self, chunks):
        self._chunks = [bytes(c) for c in chunks if len(c)]

    def recv(self, n):
        if not self._chunks:
            return b""
        c = self._chunks[0]
        if len(c) > n:
            self._chunks[0] = c[n:]
            return c[:n]
        self._chunks.pop(0)
        return c


class _CaptureSocket:
    def __init__(self):
        self.data = b""

    def sendall(self, b):
        self.data += b


def _frame_bytes(send_fn, *args, **kw):
    cap = _CaptureSocket()
    send_fn(cap, *args, **kw)
    return cap.data


def _torn_stream_frames():
    """A multi-frame byte stream exercising every frame shape the
    reader handles: plain/crc/traced requests, responses, ping/pong,
    stats, keys push/ack."""
    frames = [
        _frame_bytes(P.send_request, ["torn-a.ok", "torn-b"]),
        _frame_bytes(P.send_request, ["torn-crc"], crc=True),
        _frame_bytes(P.send_request, ["torn-tr"],
                     trace="00112233aabbccdd"),
        _frame_bytes(P.send_response, [{"sub": "x"}, ValueError("no")]),
        _frame_bytes(P.send_ping),
        _frame_bytes(P.send_pong),
        _frame_bytes(P.send_keys_push, {"keys": []}, 3),
        _frame_bytes(P.send_keys_ack, epoch=3),
    ]
    return frames, b"".join(frames)


def _read_all_frames(reader, n):
    return [reader.recv_frame_ex() for _ in range(n)]


def test_frame_reader_torn_at_every_byte_boundary():
    frames, stream = _torn_stream_frames()
    want = _read_all_frames(
        P.FrameReader(_ScriptedSocket([stream])), len(frames))
    for split in range(1, len(stream)):
        rd = P.FrameReader(_ScriptedSocket([stream[:split],
                                            stream[split:]]))
        got = _read_all_frames(rd, len(frames))
        assert got == want, f"split at byte {split} diverged"


def test_frame_reader_one_byte_at_a_time():
    frames, stream = _torn_stream_frames()
    rd = P.FrameReader(_ScriptedSocket(
        [stream[i:i + 1] for i in range(len(stream))]))
    want = _read_all_frames(
        P.FrameReader(_ScriptedSocket([stream])), len(frames))
    assert _read_all_frames(rd, len(frames)) == want


def test_parse_frame_bytes_matches_frame_reader():
    """The bytes-level reference parser (the native parity contract)
    agrees with the stream reader frame-for-frame, including consumed
    offsets that re-chain through the stream."""
    frames, stream = _torn_stream_frames()
    want = _read_all_frames(
        P.FrameReader(_ScriptedSocket([stream])), len(frames))
    pos = 0
    got = []
    for _ in frames:
        ftype, entries, trace, used = P.parse_frame_bytes(stream[pos:])
        got.append((ftype, entries, trace))
        pos += used
    assert got == want
    assert pos == len(stream)
