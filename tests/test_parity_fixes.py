"""Round-6 advisor parity fixes: unprotected-kid JWS routing and
x5c full-chain validation.

Two verdict-parity bugs from the round-5 review:

1. ``json_normalize`` compacted JSON-form JWS by dropping the
   unprotected header. A kid there is load-bearing for key selection:
   ``verify_signature`` routes by the MERGED header's kid, while the
   batch path's compact re-serialization forgot it and tried every
   type-matching key — a token whose unprotected kid names a
   different trusted key accepted on one surface and rejected on the
   other. Such tokens must ride ``normalize_batch``'s specials/object
   path instead.

2. ``jwk.py`` only DER-parsed the FIRST ``x5c`` entry; go-jose parses
   the whole chain, so a garbage intermediate entry must reject the
   key even though only the leaf's SPKI is used.

The parsing-level tests run everywhere; the four-surface and x5c
tests need the ``cryptography`` stack and skip where it is absent.
"""

import json

import pytest

from cap_tpu.errors import MalformedTokenError
from cap_tpu.jwt.jose import (
    b64url_encode,
    json_normalize,
    json_to_compact,
    normalize_batch,
    parse_jws,
)

_HDR = b64url_encode(json.dumps({"alg": "ES256"}).encode())
_PAYLOAD = b64url_encode(json.dumps({"sub": "x"}).encode())
_SIG = b64url_encode(b"\x01" * 64)


def _json_tok(unprotected=None, flattened=True) -> str:
    sig_obj = {"protected": _HDR, "signature": _SIG}
    if unprotected is not None:
        sig_obj["header"] = unprotected
    if flattened:
        return json.dumps({"payload": _PAYLOAD, **sig_obj})
    return json.dumps({"payload": _PAYLOAD, "signatures": [sig_obj]})


# ---------------------------------------------------------------------------
# Parsing layer (no crypto stack required)
# ---------------------------------------------------------------------------

class TestUnprotectedKidNormalization:
    @pytest.mark.parametrize("flattened", [True, False])
    def test_unprotected_kid_is_not_compactable(self, flattened):
        tok = _json_tok({"kid": "other-key"}, flattened=flattened)
        compact, parsed = json_normalize(tok)
        assert compact is None
        # the merged header stays authoritative on the object path
        assert parsed.kid == "other-key"
        assert parsed.alg == "ES256"

    def test_kidless_unprotected_still_compacts(self):
        tok = _json_tok({"x-meta": "v"})
        compact, parsed = json_normalize(tok)
        assert compact == f"{_HDR}.{_PAYLOAD}.{_SIG}"
        assert parse_jws(compact).alg == "ES256"

    def test_no_unprotected_still_compacts(self):
        compact, _ = json_normalize(_json_tok())
        assert compact == f"{_HDR}.{_PAYLOAD}.{_SIG}"

    def test_json_to_compact_raises_for_unprotected_kid(self):
        with pytest.raises(MalformedTokenError):
            json_to_compact(_json_tok({"kid": "k"}))

    def test_normalize_batch_routes_kid_tokens_to_specials(self):
        tok = _json_tok({"kid": "other-key"})
        plain = f"{_HDR}.{_PAYLOAD}.{_SIG}"
        out, specials = normalize_batch([plain, tok])
        assert out[0] == plain
        assert out[1] == ""               # pulled off the compact path
        assert list(specials) == [1]
        sp = specials[1]
        assert not isinstance(sp, Exception)
        assert sp.kid == "other-key"      # ParsedJWS with merged header

    def test_protected_kid_unaffected(self):
        hdr = b64url_encode(
            json.dumps({"alg": "ES256", "kid": "k1"}).encode())
        tok = json.dumps({"payload": _PAYLOAD, "protected": hdr,
                          "signature": _SIG})
        compact, parsed = json_normalize(tok)
        assert compact == f"{hdr}.{_PAYLOAD}.{_SIG}"
        assert parsed.kid == "k1"


# ---------------------------------------------------------------------------
# Four-surface verdict parity (needs the cryptography stack)
# ---------------------------------------------------------------------------

def _crypto_fixtures():
    pytest.importorskip("cryptography")
    from cap_tpu import testing as captest
    from cap_tpu.jwt import algs
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    return captest, algs, JWK, TPUBatchKeySet


def test_unprotected_kid_four_surface_parity():
    """A token signed by key A, carrying key B's kid ONLY in the
    unprotected header, must REJECT identically on all four surfaces
    (kid routing pins the wrong key); the same token carrying key A's
    kid must ACCEPT on all four. Regression for the json_normalize
    kid-drop divergence."""
    captest, algs, JWK, TPUBatchKeySet = _crypto_fixtures()
    from cap_tpu.runtime import prep

    priv_a, pub_a = captest.generate_keys(algs.ES256)
    priv_b, pub_b = captest.generate_keys(algs.ES256)
    ks = TPUBatchKeySet([JWK(pub_a, kid="kid-a"), JWK(pub_b, kid="kid-b")])

    compact = captest.sign_jwt(priv_a, algs.ES256, captest.default_claims())
    wrong_kid = captest.to_json_form(compact, unprotected={"kid": "kid-b"})
    right_kid = captest.to_json_form(compact, unprotected={"kid": "kid-a"})
    vectors = [wrong_kid, right_kid, compact]
    want_accept = [False, True, True]

    # surface 1: single-token CPU oracle (merged-header kid routing)
    oracle = []
    for tok in vectors:
        try:
            ks.verify_signature(tok)
            oracle.append(True)
        except Exception:  # noqa: BLE001 - verdict probe
            oracle.append(False)
    assert oracle == want_accept

    # surface 2: TPU batch path
    batch = ks.verify_batch(vectors)
    got = [not isinstance(r, Exception) for r in batch]
    assert got == want_accept, batch

    # surface 3: native prep (specials carry the merged ParsedJWS)
    prepped = prep.prepare_batch(vectors)
    for i, res in enumerate(prepped):
        assert not isinstance(res, Exception), f"prep vector {i}"
        assert res.kid == ["kid-b", "kid-a", None][i]

    # surface 4: serve worker over the wire
    from cap_tpu.serve.client import RemoteVerifyError, VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    w = VerifyWorker(ks, target_batch=4, max_wait_ms=5.0)
    try:
        host, port = w.address
        with VerifyClient(host, port, timeout=600.0) as c:
            res = c.verify_batch(vectors)
    finally:
        w.close()
    got = [not isinstance(r, RemoteVerifyError) for r in res]
    assert got == want_accept, res


def test_batch_and_single_agree_on_random_unprotected_kids():
    """Property-style sweep: for every (signer, unprotected-kid)
    combination the batch and single-token verdicts must agree."""
    captest, algs, JWK, TPUBatchKeySet = _crypto_fixtures()

    priv_a, pub_a = captest.generate_keys(algs.ES256)
    priv_b, pub_b = captest.generate_keys(algs.ES256)
    ks = TPUBatchKeySet([JWK(pub_a, kid="kid-a"), JWK(pub_b, kid="kid-b")])
    toks = []
    for priv in (priv_a, priv_b):
        compact = captest.sign_jwt(priv, algs.ES256,
                                   captest.default_claims())
        toks.append(compact)
        for kid in ("kid-a", "kid-b", "kid-unknown"):
            toks.append(captest.to_json_form(
                compact, unprotected={"kid": kid}))
    batch = ks.verify_batch(toks)
    for i, tok in enumerate(toks):
        try:
            ks.verify_signature(tok)
            single = True
        except Exception:  # noqa: BLE001 - verdict probe
            single = False
        assert (not isinstance(batch[i], Exception)) == single, (i, batch[i])


# ---------------------------------------------------------------------------
# x5c: every chain entry must parse (needs the cryptography stack)
# ---------------------------------------------------------------------------

class TestX5CChainValidation:
    def test_garbage_second_entry_rejected(self):
        captest, algs, _, _ = _crypto_fixtures()
        import base64

        from cap_tpu.errors import InvalidJWKSError
        from cap_tpu.jwt.jwk import parse_jwk

        priv, pub = captest.generate_keys(algs.ES256)
        jwk = captest.x5c_jwk(priv, pub)
        # valid leaf, garbage second entry: valid standard base64 that
        # is not DER — go-jose parses the whole chain, so reject
        jwk["x5c"] = [jwk["x5c"][0],
                      base64.b64encode(b"not a certificate").decode()]
        with pytest.raises(InvalidJWKSError):
            parse_jwk(jwk)

    def test_invalid_base64_second_entry_rejected(self):
        captest, algs, _, _ = _crypto_fixtures()
        from cap_tpu.errors import InvalidJWKSError
        from cap_tpu.jwt.jwk import parse_jwk

        priv, pub = captest.generate_keys(algs.ES256)
        jwk = captest.x5c_jwk(priv, pub)
        jwk["x5c"] = [jwk["x5c"][0], "!!!not-base64!!!"]
        with pytest.raises(InvalidJWKSError):
            parse_jwk(jwk)

    def test_valid_multi_entry_chain_accepted(self):
        captest, algs, _, _ = _crypto_fixtures()
        from cryptography.hazmat.primitives.asymmetric import ec as cec

        from cap_tpu.jwt.jwk import parse_jwk

        priv, pub = captest.generate_keys(algs.ES256)
        jwk = captest.x5c_jwk(priv, pub)
        # self-signed leaf repeated: every entry parses → accepted,
        # key taken from the FIRST entry
        jwk["x5c"] = [jwk["x5c"][0], jwk["x5c"][0]]
        parsed = parse_jwk(jwk)
        assert isinstance(parsed.key, cec.EllipticCurvePublicKey)
        assert (parsed.key.public_numbers()
                == pub.public_numbers())
