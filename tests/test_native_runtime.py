"""Native capruntime ↔ Python parser conformance.

The C++ batch tokenizer must agree with cap_tpu.jwt.jose.parse_compact
on every token — identical verdicts (parsed vs error class), identical
extracted fields, identical digests — across valid tokens, all malformed
classes, and adversarial headers.
"""

import hashlib
import json

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.jwt import algs
from cap_tpu.jwt.jose import b64url_encode, parse_compact
from cap_tpu.runtime import prep

native = pytest.importorskip("cap_tpu.runtime.native_binding")


def _h(d: dict) -> str:
    return b64url_encode(json.dumps(d).encode())


VALID_TOKENS = []
for alg in sorted(algs.SUPPORTED_ALGORITHMS):
    priv, _ = captest.generate_keys(alg)
    VALID_TOKENS.append(captest.sign_jwt(
        priv, alg, captest.default_claims(sub=f"u-{alg}"), kid=f"kid-{alg}"))

MALFORMED = [
    "", "a", "a.b", "a.b.c.d", "..", "a..c",
    "!!!.e30.c2ln", "e30.!!!.c2ln", "e30.e30.!!!",
    "aaaaa.e30.c2ln",                       # header len % 4 == 1
    _h({"alg": "RS256"}) + "." + _h({}) + ".",   # unsigned
    b64url_encode(b"[1]") + ".e30.c2ln",    # header not an object
    b64url_encode(b"{}") + ".e30.c2ln",     # no alg
    b64url_encode(b'{"alg":42}') + ".e30.c2ln",  # alg not a string
    b64url_encode(b'{"alg":"RS256"') + ".e30.c2ln",  # truncated JSON
    b64url_encode(b'{"alg":"RS256"} x') + ".e30.c2ln",  # trailing junk
    b64url_encode(b'not json') + ".e30.c2ln",
]

TRICKY_VALID = [
    # duplicate alg keys: last wins (Python json semantics)
    b64url_encode(b'{"alg":"RS256","alg":"ES256"}') + "." + _h({"a": 1}) + ".c2ln",
    # nested objects/arrays around alg; unicode escapes in kid
    b64url_encode(
        b'{"x":{"alg":"PS256"},"alg":"RS384","arr":[1,{"kid":"no"},null],'
        b'"kid":"k\\u00e9y","n":1.5e3,"b":true}') + "." + _h({}) + ".c2ln",
    # kid non-string -> treated as absent
    b64url_encode(b'{"alg":"EdDSA","kid":123}') + "." + _h({}) + ".c2ln",
    # unknown alg string (parses fine; alg check happens later)
    b64url_encode(b'{"alg":"HS256"}') + "." + _h({}) + ".c2ln",
]


def test_valid_tokens_match_python():
    results = native.prepare_batch(VALID_TOKENS)
    for tok, res in zip(VALID_TOKENS, results):
        ref = parse_compact(tok)
        assert not isinstance(res, Exception), res
        assert res.alg == ref.alg
        assert res.kid == ref.kid
        assert res.signature == ref.signature
        assert res.payload == ref.payload
        assert res.signing_input == ref.signing_input
        if ref.alg != "EdDSA":
            hname = algs.HASH_FOR_ALG[ref.alg]
            assert res.digest() == hashlib.new(
                hname, ref.signing_input).digest()
        assert res.claims()["sub"] == ref.claims()["sub"]


def test_malformed_match_python():
    results = native.prepare_batch(MALFORMED)
    for tok, res in zip(MALFORMED, results):
        try:
            parse_compact(tok)
            pytest.fail(f"python accepted {tok!r}")
        except Exception as ref_exc:
            assert isinstance(res, Exception), f"native accepted {tok!r}"
            assert type(res) is type(ref_exc), (
                f"{tok!r}: native {type(res).__name__} "
                f"vs python {type(ref_exc).__name__}")


def test_tricky_headers_match_python():
    results = native.prepare_batch(TRICKY_VALID)
    for tok, res in zip(TRICKY_VALID, results):
        ref = parse_compact(tok)
        assert not isinstance(res, Exception), (tok, res)
        assert res.alg == ref.alg
        assert res.kid == ref.kid


def test_kid_edge_cases_match_python():
    # empty kid, NUL-embedded kid, overlong kid, unicode-escaped kid
    cases = [
        b64url_encode(b'{"alg":"RS256","kid":""}') + "." + _h({}) + ".c2ln",
        b64url_encode(b'{"alg":"RS256","kid":"a\\u0000b"}') + "." + _h({}) + ".c2ln",
        b64url_encode(('{"alg":"RS256","kid":"' + "K" * 300 + '"}')
                      .encode()) + "." + _h({}) + ".c2ln",
        b64url_encode(b'{"alg":"RS256","kid":"k\\u00e9y"}') + "." + _h({}) + ".c2ln",
    ]
    results = native.prepare_batch(cases)
    pb = native.prepare_batch_arrays(cases)
    import numpy as np

    for i, (tok, res) in enumerate(zip(cases, results)):
        ref = parse_compact(tok)
        assert not isinstance(res, Exception)
        assert res.kid == ref.kid, (i, res.kid, ref.kid)
        assert pb.kid(i) == ref.kid, i
    # kid_rows resolves NUL-embedded kids byte-exactly and routes
    # empty-kid ("" is a present kid) separately from absent
    rows = pb.kid_rows(np.arange(4), {"a\x00b": 3, "": 9, "kéy": 1})
    assert rows[1] == 3 and rows[0] == 9 and rows[3] == 1
    assert rows[2] == -2  # overlong → slow path


def test_mixed_batch_order_preserved():
    batch = [VALID_TOKENS[0], MALFORMED[0], VALID_TOKENS[1], MALFORMED[10]]
    results = native.prepare_batch(batch)
    assert not isinstance(results[0], Exception)
    assert isinstance(results[1], Exception)
    assert not isinstance(results[2], Exception)
    assert isinstance(results[3], Exception)


def test_prep_uses_native_when_built():
    res = prep.prepare_batch(VALID_TOKENS[:2])
    assert all(not isinstance(r, Exception) for r in res)


def test_sha_batch():
    chunks = [b"", b"abc", b"x" * 1000, bytes(range(256)) * 7]
    for bits, name in [(256, "sha256"), (384, "sha384"), (512, "sha512")]:
        got = native.sha_batch(chunks, bits)
        expect = [hashlib.new(name, c).digest() for c in chunks]
        assert got == expect


def test_fuzz_parity_random_mutations():
    import random

    rng = random.Random(7)
    base = VALID_TOKENS[0]
    cases = []
    for _ in range(300):
        chars = list(base)
        for _ in range(rng.randrange(1, 4)):
            pos = rng.randrange(len(chars))
            chars[pos] = rng.choice("AZaz09._-!=")
        cases.append("".join(chars))
    results = native.prepare_batch(cases)
    for tok, res in zip(cases, results):
        try:
            ref = parse_compact(tok)
            ok_ref = True
        except Exception as e:
            ok_ref, ref_exc = False, e
        if ok_ref:
            assert not isinstance(res, Exception), tok
            assert res.alg == ref.alg and res.signature == ref.signature
        else:
            assert isinstance(res, Exception), tok
            assert type(res) is type(ref_exc), tok


# ---------------------------------------------------------------------------
# _capclaims: batch claims-JSON parsing parity vs json.loads
# ---------------------------------------------------------------------------

def _claims_ext():
    ext = native._claims_ext
    if ext is None:
        pytest.skip("_capclaims extension not built")
    return ext


def _run_claims_batch(payloads):
    import numpy as np

    ext = _claims_ext()
    blob = np.frombuffer(b"".join(payloads), np.uint8)
    lens = np.asarray([len(p) for p in payloads], np.int64)
    offs = np.zeros(len(payloads), np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    out, n_bad = ext.parse_batch(blob, offs, lens)
    assert n_bad == sum(1 for v in out if not isinstance(v, dict))
    return out


CLAIMS_EDGE = [
    b"", b"{", b"[1,2", b'{"a":}', b"nul", b'{"a":1}garbage', b"123",
    b'"just a string"', b"[]", b"{}", b'{"a": NaN}', b'{"a": Infinity}',
    b'{"a": -Infinity}', b'{"\\ud800": 1}', b'{"x": "\\ud83d\\ude00"}',
    b'{"a":1e999}', b'{"a":-0.0}', b'{"a":0.1e+5}', b'{"dup":1,"dup":2}',
    b'{"a":' + b"[" * 100 + b"]" * 100 + b"}",
    b'{"big":' + b"9" * 4500 + b"}", b"\xff\xfe", b'{"a":"\xc3\x28"}',
    b'{"a":01}', b'{"a":+1}', b'{"a":.5}', b'{"a":1.}', b'{"a":"\x01"}',
    b'  {"ws": 1}  ', b'{"t":true,"f":false,"n":null}',
    b'{"neg":-9223372036854775808,"pos":9223372036854775807}',
    b'{"over":9223372036854775808,"under":-9223372036854775809}',
    b'{"u":"\\u0041\\u00e9\\u4e2d\\uffff"}', b'{"s":"\\/\\\\\\"\\b\\f\\n\\r\\t"}',
    b'{"e":{}}', b'{"e":[[],{}]}', b'{"a":2.2250738585072014e-308}',
]


def _same_typed(a, b):
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):
        return set(a) == set(b) and all(_same_typed(a[k], b[k]) for k in a)
    if isinstance(a, list):
        return len(a) == len(b) and all(
            _same_typed(x, y) for x, y in zip(a, b))
    return a == b


def test_claims_ext_edge_parity():
    out = _run_claims_batch(CLAIMS_EDGE)
    for p, got in zip(CLAIMS_EDGE, out):
        try:
            want = json.loads(p)
            want_state = "dict" if isinstance(want, dict) else "notobj"
        except Exception:  # noqa: BLE001
            want, want_state = None, "bad"
        if isinstance(got, int):
            if got == 3:
                continue  # fallback: Python re-parses — always correct
            assert (got == 1 and want_state == "bad") or \
                (got == 2 and want_state == "notobj"), (p, got, want_state)
        else:
            assert want_state == "dict", (p, got)
            assert got == want and _same_typed(got, want), p


def test_claims_ext_fuzz_parity():
    import random

    rng = random.Random(20260730)

    def rnd_val(d=0):
        r = rng.random()
        if d > 3 or r < 0.3:
            return rng.choice([
                None, True, False, 12345, -7, 0, 3.14159, 1.5e300,
                -2.5e-10, 10 ** 25, -(10 ** 30), "plain", "unié中文",
                'esc"q\\u\n\t', "", "x" * 257])
        if r < 0.55:
            return [rnd_val(d + 1) for _ in range(rng.randint(0, 4))]
        if r < 0.65:
            return rng.randint(-(10 ** 40), 10 ** 40)
        return {f"k{rng.randint(0, 20)}": rnd_val(d + 1)
                for _ in range(rng.randint(0, 5))}

    payloads = []
    for i in range(2000):
        obj = {"iss": "https://idp.example.com", "sub": f"user-{i}",
               "aud": ["a", "b"], "exp": 1790000000 + i,
               "extra": rnd_val()}
        payloads.append(json.dumps(
            obj, ensure_ascii=rng.random() < 0.5).encode())
    out = _run_claims_batch(payloads)
    for p, got in zip(payloads, out):
        want = json.loads(p)
        if isinstance(got, int):
            assert got == 3, (p, got)  # only fallback allowed on valid input
        else:
            assert got == want and _same_typed(got, want), p


def test_claims_ext_degenerate_batches_overflow_caches():
    """Intern-table caps (256 keys / value-table entries / 64-byte value
    threshold) must only change speed, never results: an all-unique
    batch overflows every cache and still parses byte-identically."""
    payloads = []
    # > 256 distinct keys across the batch (key-cache cap), > 4096
    # distinct short values (value-table cap), values straddling the
    # 64-byte cache threshold, and > 5 keys per object (presize path).
    for i in range(1200):
        obj = {
            f"uk{i}a": f"val-{i}-alpha", f"uk{i}b": f"val-{i}-beta",
            f"uk{i}c": i, f"uk{i}d": f"v{i}" * 3, f"uk{i}e": True,
            f"uk{i}f": "x" * 63, f"uk{i}g": "y" * 64, f"uk{i}h": "z" * 65,
            "shared": "common-value",
        }
        payloads.append(json.dumps(obj, separators=(",", ":")).encode())
    out = _run_claims_batch(payloads)
    for p, got in zip(payloads, out):
        want = json.loads(p)
        assert got == want and _same_typed(got, want), p


def test_prefetch_claims_uses_ext_with_identical_results():
    """PreparedBatch.prefetch_claims: ext path == pure-json path."""
    priv, _ = captest.generate_keys(algs.ES256)
    tokens = [captest.sign_jwt(priv, algs.ES256,
                               captest.default_claims(sub=f"s-{i}"))
              for i in range(50)]
    # one weird-but-valid payload and one non-object payload via raw JWS
    h = b64url_encode(json.dumps({"alg": "ES256"}).encode())
    inf_payload = b'{"inf": Infinity}'
    tokens.append(f"{h}.{b64url_encode(b'[1,2,3]')}.c2ln")
    tokens.append(f"{h}.{b64url_encode(inf_payload)}.c2ln")

    pb1 = native.prepare_batch_arrays(tokens)
    pb1.prefetch_claims(range(pb1.n))
    saved = native._claims_ext
    try:
        native._claims_ext = None
        pb2 = native.prepare_batch_arrays(tokens)
        pb2.prefetch_claims(range(pb2.n))
    finally:
        native._claims_ext = saved
    for i in range(pb1.n):
        a, b = pb1._claims_cache[i], pb2._claims_cache[i]
        if isinstance(a, Exception):
            assert type(a) is type(b) and str(a) == str(b), i
        else:
            assert a == b and _same_typed(a, b), i
