"""Keyplane fleet propagation: KEYS pushes, convergence, chaos.

Stub workers (no jax in the children), so the suite is tier-1-cheap.
Ground truth is the stub rule — tokens ending ``.ok`` verify — which a
rotation must NEVER change: the acceptance bar is live rotation under
sustained load with zero wrong verdicts, zero lost submissions, and
every worker on the new epoch within two refresh intervals, including
a kill -9 landing mid-push.
"""

import json
import signal
import socket
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, WorkerPool
from cap_tpu.fleet.chaos import kill9
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.serve import protocol
from cap_tpu.serve.worker import VerifyWorker

HARD_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout():
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"keyplane fleet test exceeded hard {HARD_TIMEOUT_S}s "
            "timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _jwks(*kids):
    return {"keys": [{"kty": "RSA", "kid": k, "n": "AQAB", "e": "AQAB"}
                     for k in kids]}


def _wait_epochs(pool, epoch, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(e == epoch for e in pool.key_epochs().values()):
            return True
        time.sleep(0.1)
    return False


@pytest.fixture
def pool():
    p = WorkerPool(2, keyset_spec="stub", ping_interval=0.2,
                   max_restarts=10)
    assert p.wait_all_ready(30), "fleet did not come up"
    yield p
    p.close()


# ---------------------------------------------------------------------------
# propagation basics
# ---------------------------------------------------------------------------

def test_ready_line_announces_epoch(pool):
    # Stub workers boot on epoch 0 and the pool learns it from the
    # ready line before any push happens.
    assert pool.key_epochs() == {0: 0, 1: 0}
    assert pool.epoch_skew() == 0
    assert pool.keys_epoch() is None


def test_push_keys_reaches_every_worker(pool):
    acks = pool.push_keys(_jwks("k-1"))
    assert acks == {0: 1, 1: 1}
    assert pool.key_epochs() == {0: 1, 1: 1}
    assert pool.keys_epoch() == 1
    # Epochs auto-increment per push; explicit epochs are honored.
    assert set(pool.push_keys(_jwks("k-2")).values()) == {2}
    assert set(pool.push_keys(_jwks("k-3"), epoch=10).values()) == {10}
    # Workers report the epoch over STATS and the obs scrape.
    stats = pool.stats()
    assert {s["key_epoch"] for s in stats.values()} == {10}
    agg = pool.stats_merged()["aggregate"]
    assert agg["key_epochs"] == {0: 10, 1: 10}
    assert agg["epoch_skew"] == 0


def test_push_records_propagation_telemetry():
    # Own pool with a LONG supervisor interval: push_keys records the
    # distribution target before contacting workers, so a concurrent
    # supervisor sweep can legitimately re-push a not-yet-contacted
    # worker and add a third push_attempt — quiescing the sweep makes
    # the exact ==2 accounting deterministic (seen flaking under
    # full-suite CPU contention).
    p = WorkerPool(2, keyset_spec="stub", ping_interval=30.0)
    try:
        assert p.wait_all_ready(30)
        with telemetry.recording() as rec:
            p.push_keys(_jwks("t-1"))
            assert rec.counters().get("keyplane.pushes") == 1
            assert rec.counters().get("keyplane.push_attempts") == 2
            assert "keyplane.propagate_s" in rec.summary()
            assert rec.gauges().get("keyplane.epoch") == 1
    finally:
        p.close()


def test_worker_obs_scrape_carries_epoch(pool):
    pool.push_keys(_jwks("o-1"), epoch=4)
    import sys
    sys.path.insert(0, ".")
    from tools import capstat

    data = {}
    for wid, (host, port) in sorted(pool.obs_endpoints().items()):
        data[f"{host}:{port}"] = capstat.scrape(f"{host}:{port}")
    for ep, d in data.items():
        assert d["extra"].get("keyplane.epoch") == 4.0, (ep, d["extra"])
    # capstat renders the per-worker epoch.
    rendered = capstat.render_fleet(data)
    assert "epoch=4" in rendered


def test_router_surfaces_epoch_skew(pool):
    cl = FleetClient(pool, fallback=StubKeySet())
    pool.push_keys(_jwks("s-1"))
    snap = cl.snapshot()
    assert snap["epoch_skew"] == 0
    assert snap["key_epochs"] == {"0": 1, "1": 1}
    # Manufacture skew: mark one worker stale.
    with pool._lock:
        pool._handles[1].key_epoch = 0
    assert cl.key_epoch_skew() == 1
    # Endpoint-list clients have no pool → no skew view.
    cl2 = FleetClient(list(pool.endpoints().values()))
    assert cl2.key_epoch_skew() is None
    assert "epoch_skew" not in cl2.snapshot()


def test_verifies_on_connection_after_push_see_new_epoch(pool):
    # Frame order on one connection: a verify request sent AFTER a
    # KEYS push is answered by a worker already on the new epoch.
    addr = pool.endpoints()[0]
    with socket.create_connection(addr, timeout=10) as s:
        s.settimeout(10)
        protocol.send_keys_push(s, _jwks("c-1"), 6)
        protocol.send_request(s, ["after.ok"], crc=True)
        reader = protocol.FrameReader(s)
        ftype, entries = reader.recv_frame()
        assert ftype == protocol.T_KEYS_ACK
        assert json.loads(entries[0][1]) == {"epoch": 6}
        ftype, entries = reader.recv_frame()
        assert ftype == protocol.T_VERIFY_RESP_CRC
        assert entries[0][0] == 0
    assert pool.stats()[0]["key_epoch"] == 6


# ---------------------------------------------------------------------------
# non-swappable engines ack an error, never a half-applied state
# ---------------------------------------------------------------------------

class _NoSwapKeySet:
    def verify_batch(self, tokens):
        return [{"sub": t} for t in tokens]


def test_push_to_non_swappable_keyset_acks_error():
    w = VerifyWorker(_NoSwapKeySet(), target_batch=4, max_wait_ms=1.0,
                     obs_port=None)
    try:
        with socket.create_connection(w.address, timeout=10) as s:
            s.settimeout(10)
            protocol.send_keys_push(s, _jwks("x"), 1)
            ftype, entries = protocol.FrameReader(s).recv_frame()
        assert ftype == protocol.T_KEYS_ACK
        status, payload = entries[0]
        assert status == 1
        assert b"hot key rotation" in payload
        assert w.key_epoch is None
    finally:
        w.close()


# ---------------------------------------------------------------------------
# chaos: rotation under sustained load, kill -9 mid-push
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_rotation_under_load_zero_wrong_verdicts():
    """Live rotation while traffic flows: every verdict stays correct,
    nothing is lost, and the fleet converges on each pushed epoch."""
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=20",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0)
    try:
        assert pool.wait_all_ready(30)
        cl = FleetClient(pool, fallback=StubKeySet(),
                         attempt_timeout=2.0, total_deadline=30.0,
                         rr_seed=0)
        stop = threading.Event()
        failures = []
        done = []

        def driver(d):
            i = 0
            while not stop.is_set():
                toks = [f"d{d}-{i}-{j}.ok" for j in range(3)] + \
                    [f"d{d}-{i}-bad"]
                try:
                    res = cl.verify_batch(toks)
                except Exception as e:  # noqa: BLE001
                    failures.append(f"driver {d}: {e!r}")
                    return
                if len(res) != len(toks):
                    failures.append(f"driver {d}: lost submissions")
                    return
                for t, r in zip(toks, res):
                    ok = not isinstance(r, Exception)
                    if ok != t.endswith(".ok") or \
                            (ok and r != {"sub": t}):
                        failures.append(
                            f"driver {d}: WRONG verdict for {t!r}")
                        return
                done.append(len(toks))
                i += 1

        threads = [threading.Thread(target=driver, args=(d,))
                   for d in range(4)]
        for t in threads:
            t.start()
        # Three live rotations while the drivers hammer the fleet.
        for epoch in (1, 2, 3):
            time.sleep(0.3)
            pool.push_keys(_jwks(f"rot-{epoch}"), epoch=epoch)
            assert _wait_epochs(pool, epoch, timeout=15), \
                f"fleet did not converge on epoch {epoch}"
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "driver wedged"
        assert not failures, failures
        assert sum(done) > 0
    finally:
        pool.close()


@pytest.mark.chaos
def test_kill9_mid_push_converges_on_respawn():
    """SIGKILL one worker exactly while a rotation is being pushed:
    the respawned process must converge on the pushed epoch (ready-
    line re-push + supervisor sweep), with verdicts correct
    throughout."""
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=20",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0)
    try:
        assert pool.wait_all_ready(30)
        cl = FleetClient(pool, fallback=StubKeySet(),
                         attempt_timeout=2.0, total_deadline=30.0,
                         rr_seed=0)
        victim = pool.pid(0)
        pushed = threading.Event()

        def killer():
            # Land the SIGKILL in the middle of the push fan-out.
            pushed.wait(timeout=10)
            kill9(victim)

        t = threading.Thread(target=killer)
        t.start()
        pushed.set()
        acks = pool.push_keys(_jwks("mid-push"), epoch=5)
        t.join(timeout=10)
        # The killed worker may or may not have acked; the SURVIVOR
        # must have, and the pool's distribution target is epoch 5.
        assert pool.keys_epoch() == 5
        assert 5 in acks.values()
        # Convergence: the respawn path re-pushes epoch 5. Two refresh
        # (supervisor ping) intervals after the respawn is the budget;
        # respawn itself takes a few seconds on this host.
        assert _wait_epochs(pool, 5, timeout=60), \
            f"no convergence after kill -9 mid-push: {pool.key_epochs()}"
        assert pool.pid(0) != victim
        assert pool.epoch_skew() == 0
        # Traffic still produces only correct verdicts.
        res = cl.verify_batch(["post.ok", "post.bad"])
        assert res[0] == {"sub": "post.ok"}
        assert isinstance(res[1], Exception)
    finally:
        pool.close()


@pytest.mark.chaos
def test_supervisor_repushes_after_transient_push_failure():
    """A worker that misses a push (its serve socket was briefly
    unreachable) is converged by the supervisor sweep, not left
    skewed forever."""
    pool = WorkerPool(2, keyset_spec="stub", ping_interval=0.2,
                      max_restarts=10)
    try:
        assert pool.wait_all_ready(30)
        pool.push_keys(_jwks("r-1"), epoch=3)
        assert _wait_epochs(pool, 3, timeout=15)
        # Simulate a missed push: forget worker 1's ack so the pool
        # believes it is stale (epoch tracking is pool-side state).
        with pool._lock:
            pool._handles[1].key_epoch = 0
        assert pool.epoch_skew() == 3
        # The supervisor notices the stale epoch on its next sweep and
        # re-pushes the CURRENT distribution.
        assert _wait_epochs(pool, 3, timeout=15), pool.key_epochs()
    finally:
        pool.close()


@pytest.mark.chaos
def test_rotation_kill9_under_repeated_token_load_cache_tier():
    """ROADMAP #3 chaos bar: keyplane rotation with a kill -9 landing
    mid-push, under sustained REPEATED-token load (the verdict-cache
    regime). Every verdict stays ground-truth-correct through the
    rotation and the respawn (a stale cached accept would fail the
    per-token check), the live fleet's ``vcache.stale_accepts``
    tripwire never moves, and the killed worker's postmortem carries
    the cache-invalidation counter (``vcache.epoch_bumps``) from the
    push it applied before dying."""
    from cap_tpu.obs import postmortem as obs_postmortem

    pool = WorkerPool(2, keyset_spec="stub:batch_ms=5",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0)
    try:
        assert pool.wait_all_ready(30)
        cl = FleetClient(pool, fallback=StubKeySet(),
                         attempt_timeout=2.0, total_deadline=30.0,
                         rr_seed=0)
        hot = [f"hot-{i}.ok" for i in range(3)] + ["hot-x.bad"]
        stop = threading.Event()
        failures = []
        done = []

        def driver(d):
            while not stop.is_set():
                try:
                    res = cl.verify_batch(hot)
                except Exception as e:  # noqa: BLE001
                    failures.append(f"driver {d}: {e!r}")
                    return
                if len(res) != len(hot):
                    failures.append(f"driver {d}: lost submissions")
                    return
                for t, r in zip(hot, res):
                    ok = not isinstance(r, Exception)
                    if ok != t.endswith(".ok") or \
                            (ok and r != {"sub": t}):
                        failures.append(
                            f"driver {d}: WRONG verdict for {t!r}")
                        return
                done.append(len(res))

        threads = [threading.Thread(target=driver, args=(d,))
                   for d in range(3)]
        for t in threads:
            t.start()
        # Rotation 1 lands cleanly: both workers bump their caches.
        time.sleep(0.5)
        pool.push_keys(_jwks("rot-1"), epoch=1)
        assert _wait_epochs(pool, 1, timeout=15)
        # Let the killed worker checkpoint a postmortem that already
        # contains the epoch-1 invalidation + cache hits.
        victim = pool.pid(0)
        pm_path = pool.postmortem_path(0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            doc = obs_postmortem.read_postmortem(pm_path)
            if doc and (doc.get("snapshot", {}).get("counters", {})
                        .get("vcache.epoch_bumps", 0)) >= 1:
                break
            time.sleep(0.1)
        # Rotation 2 with the SIGKILL landing mid-push.
        killer = threading.Thread(target=lambda: kill9(victim))
        killer.start()
        pool.push_keys(_jwks("rot-2"), epoch=2)
        killer.join(timeout=10)
        assert _wait_epochs(pool, 2, timeout=60), pool.key_epochs()
        assert pool.pid(0) != victim
        # Sustained repeated-token load PAST any grace window (cache
        # bumps use grace 0; engines' grace is irrelevant to stubs).
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "driver wedged"
        assert not failures, failures[:3]
        assert sum(done) > 0
        # Zero stale accepts after grace expiry, fleet-wide: the
        # serve-time tripwire on the live workers never moved, and the
        # repeats DID hit the cache (the load was cache-shaped).
        agg = pool.stats_merged()["aggregate"]["counters"]
        assert agg.get("vcache.stale_accepts", 0) == 0
        assert agg.get("vcache.hits", 0) > 0
        assert agg.get("vcache.epoch_bumps", 0) >= 1
        # The killed worker's postmortem carries the invalidation
        # counter — the epoch-1 bump it applied before the SIGKILL.
        doc = pool.postmortem(0)
        assert doc is not None, "no postmortem collected"
        pm_counters = doc.get("snapshot", {}).get("counters", {})
        assert pm_counters.get("vcache.epoch_bumps", 0) >= 1, \
            sorted(k for k in pm_counters if k.startswith("vcache"))
        assert pm_counters.get("vcache.hits", 0) > 0
    finally:
        pool.close()
