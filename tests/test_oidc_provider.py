"""Provider end-to-end against the in-process HTTPS TestProvider."""

from urllib.parse import parse_qs, urlparse

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu.errors import (
    ExpiredAuthTimeError,
    ExpiredTokenError,
    InvalidAudienceError,
    InvalidAuthorizedPartyError,
    InvalidFlowError,
    InvalidIssuerError,
    InvalidNonceError,
    InvalidParameterError,
    InvalidSignatureError,
    InvalidSubjectError,
    MissingIDTokenError,
    UnauthorizedRedirectURIError,
    UnsupportedAlgError,
)
from cap_tpu.oidc import Config, Provider, Request, S256Verifier
from cap_tpu.oidc.testing import TestProvider

REDIRECT = "https://app.example.com/callback"


@pytest.fixture(scope="module")
def idp():
    with TestProvider() as tp:
        yield tp


@pytest.fixture()
def provider(idp):
    cfg = Config(
        issuer=idp.issuer(),
        client_id=idp.client_id,
        client_secret=idp.client_secret,
        supported_signing_algs=["ES256"],
        allowed_redirect_urls=[REDIRECT],
        provider_ca=idp.ca_cert(),
    )
    return Provider(cfg)


def test_discovery(provider, idp):
    assert provider.authorization_endpoint == idp.issuer() + "/authorize"
    assert provider.jwks_uri.endswith("/.well-known/jwks.json")


def test_discovery_issuer_mismatch(idp):
    cfg = Config(
        issuer=idp.issuer(), client_id="x", client_secret="y",
        supported_signing_algs=["ES256"], provider_ca=idp.ca_cert(),
    )
    doc = {"issuer": "https://evil.example.com"}
    with pytest.raises(InvalidIssuerError):
        Provider(cfg, discovery_doc=doc)


def test_auth_url_code_flow(provider):
    req = Request(60, REDIRECT, scopes=["email", "profile"])
    url = provider.auth_url(req)
    q = parse_qs(urlparse(url).query)
    assert q["response_type"] == ["code"]
    assert q["client_id"] == [provider.config.client_id]
    assert q["state"] == [req.state()]
    assert q["nonce"] == [req.nonce()]
    assert q["scope"][0].split() == ["openid", "email", "profile"]


def test_auth_url_pkce(provider):
    v = S256Verifier()
    req = Request(60, REDIRECT, pkce_verifier=v)
    q = parse_qs(urlparse(provider.auth_url(req)).query)
    assert q["code_challenge"] == [v.challenge()]
    assert q["code_challenge_method"] == ["S256"]


def test_auth_url_implicit(provider):
    req = Request(60, REDIRECT, implicit_flow=True,
                  implicit_access_token=True)
    q = parse_qs(urlparse(provider.auth_url(req)).query)
    assert q["response_type"] == ["id_token token"]
    assert q["response_mode"] == ["form_post"]


def test_auth_url_options(provider):
    req = Request(60, REDIRECT, max_age=30, prompts=["login", "consent"],
                  display="page", ui_locales=["en-US", "fr"],
                  acr_values=["phr"], claims={"id_token": {}})
    q = parse_qs(urlparse(provider.auth_url(req)).query)
    assert q["max_age"] == ["30"]
    assert q["prompt"] == ["login consent"]
    assert q["display"] == ["page"]
    assert q["ui_locales"] == ["en-US fr"]
    assert q["acr_values"] == ["phr"]
    assert "claims" in q


def test_auth_url_prompt_none_alone(provider):
    req = Request(60, REDIRECT, prompts=["none", "login"])
    with pytest.raises(InvalidParameterError):
        provider.auth_url(req)


def test_auth_url_unauthorized_redirect(provider):
    req = Request(60, "https://evil.example.com/cb")
    with pytest.raises(UnauthorizedRedirectURIError):
        provider.auth_url(req)


def test_loopback_redirect_port_agnostic(idp):
    cfg = Config(
        issuer=idp.issuer(), client_id=idp.client_id,
        client_secret=idp.client_secret,
        supported_signing_algs=["ES256"],
        allowed_redirect_urls=["http://localhost:3000/cb"],
        provider_ca=idp.ca_cert(),
    )
    p = Provider(cfg)
    p.valid_redirect("http://localhost:9999/cb")  # different port OK
    with pytest.raises(UnauthorizedRedirectURIError):
        p.valid_redirect("http://localhost:9999/other")


def test_exchange_full_flow(provider, idp):
    req = Request(60, REDIRECT)
    idp.set_expected_auth_nonce(req.nonce())
    token = provider.exchange(req, req.state(), idp.expected_auth_code)
    assert token.id_token().claims()["nonce"] == req.nonce()
    assert token.access_token().reveal() == "test-access-token"
    assert token.valid()


def test_exchange_pkce_flow(provider, idp):
    v = S256Verifier()
    req = Request(60, REDIRECT, pkce_verifier=v)
    idp.set_expected_auth_nonce(req.nonce())
    idp.set_expected_code_verifier(v.verifier())
    try:
        token = provider.exchange(req, req.state(), idp.expected_auth_code)
        assert token.id_token()
    finally:
        idp.expected_code_verifier = None


def test_exchange_guards(provider, idp):
    req = Request(60, REDIRECT)
    with pytest.raises(InvalidParameterError):
        provider.exchange(req, "other-state", "code")
    imp = Request(60, REDIRECT, implicit_flow=True)
    with pytest.raises(InvalidFlowError):
        provider.exchange(imp, imp.state(), "code")
    expired = Request(60, REDIRECT, now_func=lambda: 0.0)
    expired._now_func = None  # request was created long "ago"
    with pytest.raises(InvalidParameterError):
        provider.exchange(expired, expired.state(), "code")


def test_exchange_wrong_code(provider, idp):
    req = Request(60, REDIRECT)
    with pytest.raises(InvalidParameterError):
        provider.exchange(req, req.state(), "wrong-code")


def test_exchange_token_disabled(provider, idp):
    idp.set_disable_token(True)
    try:
        req = Request(60, REDIRECT)
        with pytest.raises(InvalidParameterError):
            provider.exchange(req, req.state(), idp.expected_auth_code)
    finally:
        idp.set_disable_token(False)


def test_exchange_omit_id_token(provider, idp):
    idp.set_omit_id_tokens(True)
    try:
        req = Request(60, REDIRECT)
        idp.set_expected_auth_nonce(req.nonce())
        with pytest.raises(MissingIDTokenError):
            provider.exchange(req, req.state(), idp.expected_auth_code)
    finally:
        idp.set_omit_id_tokens(False)


def test_verify_id_token_negative_paths(provider, idp):
    req = Request(60, REDIRECT)
    # wrong nonce
    tok = idp.issue_signed_jwt(nonce="some-other-nonce")
    with pytest.raises(InvalidNonceError):
        provider.verify_id_token(tok, req)
    # expired
    tok = idp.issue_signed_jwt(nonce=req.nonce(),
                               extra_claims={"exp": 1000000})
    with pytest.raises(ExpiredTokenError):
        provider.verify_id_token(tok, req)
    # wrong issuer
    tok = idp.issue_signed_jwt(nonce=req.nonce(),
                               extra_claims={"iss": "https://evil"})
    with pytest.raises(InvalidIssuerError):
        provider.verify_id_token(tok, req)
    # foreign single audience with no azp → caught by azp rule 3
    # (audience-intersection check is skipped when no expected audiences
    # are configured, matching provider.go:460-472 + 479-497)
    tok = idp.issue_signed_jwt(nonce=req.nonce(),
                               extra_claims={"aud": ["someone-else"]})
    with pytest.raises(InvalidAuthorizedPartyError):
        provider.verify_id_token(tok, req)
    # configured expected audiences → audience error
    req_aud = Request(60, REDIRECT, audiences=["expected-aud"])
    tok = idp.issue_signed_jwt(nonce=req_aud.nonce(),
                               extra_claims={"aud": ["someone-else"]})
    with pytest.raises(InvalidAudienceError):
        provider.verify_id_token(tok, req_aud)
    # azp present but wrong
    tok = idp.issue_signed_jwt(nonce=req.nonce(),
                               extra_claims={"azp": "other-party"})
    with pytest.raises(InvalidAuthorizedPartyError):
        provider.verify_id_token(tok, req)
    # multiple audiences incl. client, azp == client → OK
    tok = idp.issue_signed_jwt(
        nonce=req.nonce(),
        extra_claims={"aud": [idp.client_id, "second"],
                      "azp": idp.client_id})
    assert provider.verify_id_token(tok, req)["sub"]
    # corrupt signature
    idp.set_invalid_jwt_signature(True)
    try:
        tok = idp.issue_signed_jwt(nonce=req.nonce())
        with pytest.raises(InvalidSignatureError):
            provider.verify_id_token(tok, req)
    finally:
        idp.set_invalid_jwt_signature(False)


def test_verify_id_token_unsupported_alg(idp):
    cfg = Config(
        issuer=idp.issuer(), client_id=idp.client_id,
        client_secret=idp.client_secret,
        supported_signing_algs=["RS256"],  # IdP signs ES256
        provider_ca=idp.ca_cert(),
    )
    p = Provider(cfg)
    req = Request(60, REDIRECT)
    tok = idp.issue_signed_jwt(nonce=req.nonce())
    with pytest.raises(UnsupportedAlgError):
        p.verify_id_token(tok, req)


def test_verify_id_token_max_age(provider, idp):
    req = Request(60, REDIRECT, max_age=300)
    tok = idp.issue_signed_jwt(nonce=req.nonce())
    assert provider.verify_id_token(tok, req)["auth_time"]
    # auth_time far in the past → beyond max age
    req2 = Request(60, REDIRECT, max_age=10)
    tok2 = idp.issue_signed_jwt(
        nonce=req2.nonce(), extra_claims={"auth_time": 1000000})
    with pytest.raises(ExpiredAuthTimeError):
        provider.verify_id_token(tok2, req2)
    # missing auth_time claim when max_age requested
    tok3 = idp.issue_signed_jwt(nonce=req2.nonce(),
                                extra_claims={"auth_time": None})
    import json

    from cap_tpu.errors import MissingClaimError

    # rebuild without auth_time
    with pytest.raises(MissingClaimError):
        priv, _, alg, kid = idp.signing_keys()
        from cap_tpu import testing as captest

        claims = {k: v for k, v in json.loads(
            __import__("cap_tpu.jwt.jose", fromlist=["parse_compact"])
            .parse_compact(tok3).payload) .items() if k != "auth_time"}
        provider.verify_id_token(
            captest.sign_jwt(priv, alg, claims, kid=kid), req2)


def test_key_rotation_refetch(provider, idp):
    req = Request(60, REDIRECT)
    tok = idp.issue_signed_jwt(nonce=req.nonce())
    assert provider.verify_id_token(tok, req)
    idp.rotate_signing_keys()
    try:
        tok2 = idp.issue_signed_jwt(nonce=req.nonce())
        assert provider.verify_id_token(tok2, req)["sub"]
    finally:
        pass


def test_userinfo(provider, idp):
    class TS:
        def token(self):
            return "test-access-token"

    claims = provider.userinfo(TS(), idp.replay_subject)
    assert claims["sub"] == idp.replay_subject
    with pytest.raises(InvalidSubjectError):
        provider.userinfo(TS(), "mallory")


def test_userinfo_disabled(provider, idp):
    idp.set_disable_userinfo(True)
    try:
        class TS:
            def token(self):
                return "test-access-token"

        from cap_tpu.errors import UserInfoFailedError

        with pytest.raises(UserInfoFailedError):
            provider.userinfo(TS(), idp.replay_subject)
    finally:
        idp.set_disable_userinfo(False)


def test_exchange_without_at_hash(provider, idp):
    # at_hash is OPTIONAL in the code flow: an IdP issuing access tokens
    # without it must still be loginable (reference (false, nil) parity).
    idp.set_omit_at_hash(True)
    try:
        req = Request(60, REDIRECT)
        idp.set_expected_auth_nonce(req.nonce())
        token = provider.exchange(req, req.state(), idp.expected_auth_code)
        assert "at_hash" not in token.id_token().claims()
    finally:
        idp.set_omit_at_hash(False)


def test_batch_accepts_idtoken_instances(provider, idp):
    from cap_tpu.oidc import IDToken

    req = Request(60, REDIRECT)
    toks = [IDToken(idp.issue_signed_jwt(nonce=req.nonce()))]
    res = provider.verify_id_token_batch(toks, req)
    assert isinstance(res[0], dict) and res[0]["sub"]


def test_batch_id_token_verification(provider, idp):
    req = Request(60, REDIRECT)
    good = [idp.issue_signed_jwt(nonce=req.nonce()) for _ in range(4)]
    bad_nonce = idp.issue_signed_jwt(nonce="wrong")
    tampered = good[0][:-10] + "AAAAAAAAAA"
    res = provider.verify_id_token_batch(good + [bad_nonce, tampered], req)
    assert all(isinstance(r, dict) for r in res[:4])
    assert isinstance(res[4], InvalidNonceError)
    assert isinstance(res[5], InvalidSignatureError)


def test_pooled_http_reuses_connections(idp):
    """Discovery + token exchange + userinfo ride keep-alive sockets
    from the shared pool (the reference's pooled cleanhttp transports,
    oidc/provider.go:566-618): after the first request to the IdP the
    rest reuse its connection instead of re-handshaking TLS."""
    from cap_tpu import telemetry

    with telemetry.recording() as rec:
        cfg = Config(
            issuer=idp.issuer(),
            client_id=idp.client_id,
            client_secret=idp.client_secret,
            supported_signing_algs=["ES256"],
            allowed_redirect_urls=[REDIRECT],
            provider_ca=idp.ca_cert(),
        )
        p = Provider(cfg)
        req = Request(60, REDIRECT)
        idp.set_expected_auth_nonce(req.nonce())
        token = p.exchange(req, req.state(), idp.expected_auth_code)
        p.userinfo(token.static_token_source(), idp.replay_subject)
    counters = rec.counters()
    # discovery (1 fetch) + token POST + JWKS fetch + userinfo ≥ 4
    # requests; after the first each should reuse the pooled socket.
    assert counters.get("http.conn_reused", 0) >= 2, counters
