"""Callback handler e2e: wsgiref server ↔ TestProvider round trips.

Mirrors the reference's callback tests (authcode_test.go, implicit_test.go):
run the WSGI callback app in a real HTTP server, drive the IdP authorize
endpoint like a browser (including scraping the implicit flow's
auto-submitting form), and assert on HTTP responses.
"""

import re
import threading
import urllib.request
from urllib.parse import parse_qs, urlencode, urlparse
from wsgiref.simple_server import WSGIServer, make_server

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu.errors import ExpiredRequestError, NotFoundError
from cap_tpu.oidc import Config, Provider, Request
from cap_tpu.oidc.callback import (
    SingleRequestReader,
    auth_code,
    implicit,
)
from cap_tpu.oidc.testing import TestProvider
from cap_tpu.utils import http as _http


@pytest.fixture(scope="module")
def idp():
    with TestProvider() as tp:
        yield tp


def _provider(idp, redirect):
    cfg = Config(
        issuer=idp.issuer(), client_id=idp.client_id,
        client_secret=idp.client_secret,
        supported_signing_algs=["ES256"],
        allowed_redirect_urls=[redirect],
        provider_ca=idp.ca_cert(),
    )
    return Provider(cfg)


class _QuietServer(WSGIServer):
    def handle_error(self, request, client_address):
        pass


def _serve(app):
    server = make_server("127.0.0.1", 0, app, server_class=_QuietServer)
    server.RequestHandlerClass.log_message = lambda *a: None
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, f"http://127.0.0.1:{server.server_address[1]}/callback"


def _success(state, token, environ):
    return (200, [("Content-Type", "text/plain")],
            f"success:{token.id_token().claims()['sub']}")


def _error(state, resp, err, environ):
    label = resp.error if resp else type(err).__name__
    return (401, [("Content-Type", "text/plain")], f"error:{label}")


def test_authcode_callback_full_flow(idp):
    captured = {}

    def success(state, token, environ):
        captured["token"] = token
        return _success(state, token, environ)

    # placeholder redirect; real one known after server starts
    app_holder = {}

    def app(environ, start_response):
        return app_holder["app"](environ, start_response)

    server, callback_url = _serve(app)
    try:
        p = _provider(idp, callback_url)
        req = Request(60, callback_url)
        idp.set_expected_auth_nonce(req.nonce())
        app_holder["app"] = auth_code(
            p, SingleRequestReader(req), success, _error)
        # drive the IdP authorize endpoint like a browser: it 302s to our
        # callback and urllib follows the redirect straight into it
        auth = p.auth_url(req)
        status, body, _ = _http.get(
            auth, _http.ssl_context_for_ca(idp.ca_cert()))
        assert status == 200
        assert body == b"success:alice@example.com"
        assert captured["token"].valid()
    finally:
        server.shutdown()


def test_authcode_callback_error_param(idp):
    server, callback_url = _serve(
        lambda e, s: app(e, s))  # placeholder, replaced below

    def app(environ, start_response):
        return real_app(environ, start_response)

    p = _provider(idp, callback_url)
    req = Request(60, callback_url)
    real_app = auth_code(p, SingleRequestReader(req), _success, _error)
    try:
        qs = urlencode({"state": req.state(), "error": "access_denied",
                        "error_description": "nope"})
        with urllib.request.urlopen(f"{callback_url}?{qs}") as resp:
            pytest.fail("should have errored")
    except urllib.error.HTTPError as e:
        assert e.code == 401
        assert e.read() == b"error:access_denied"
    finally:
        server.shutdown()


def test_authcode_callback_unknown_state(idp):
    holder = {}
    server, callback_url = _serve(
        lambda e, s: holder["app"](e, s))
    p = _provider(idp, callback_url)
    req = Request(60, callback_url)
    holder["app"] = auth_code(p, SingleRequestReader(req), _success, _error)
    try:
        qs = urlencode({"state": "unknown-state", "code": "x"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{callback_url}?{qs}")
        assert ei.value.read() == b"error:NotFoundError"
    finally:
        server.shutdown()


def test_authcode_callback_expired_request(idp):
    holder = {}
    server, callback_url = _serve(lambda e, s: holder["app"](e, s))
    p = _provider(idp, callback_url)
    req = Request(0.000001, callback_url)
    req._expiration = 0.0  # force long-expired
    holder["app"] = auth_code(p, SingleRequestReader(req), _success, _error)
    try:
        qs = urlencode({"state": req.state(), "code": "x"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{callback_url}?{qs}")
        assert ei.value.read() == b"error:ExpiredRequestError"
    finally:
        server.shutdown()


def test_implicit_callback_full_flow(idp):
    holder = {}
    server, callback_url = _serve(lambda e, s: holder["app"](e, s))
    p = _provider(idp, callback_url)
    req = Request(60, callback_url, implicit_flow=True,
                  implicit_access_token=True)
    holder["app"] = implicit(p, SingleRequestReader(req), _success, _error)
    try:
        # impersonate the browser: GET authorize, scrape the returned
        # auto-submitting form, POST it to the callback
        auth = p.auth_url(req)
        status, body, _ = _http.get(
            auth, _http.ssl_context_for_ca(idp.ca_cert()))
        assert status == 200
        fields = dict(re.findall(
            r'name="([^"]+)" value="([^"]+)"', body.decode()))
        assert "id_token" in fields and fields["state"] == req.state()
        data = urlencode(fields).encode()
        post = urllib.request.Request(callback_url, data=data, method="POST")
        post.add_header("Content-Type", "application/x-www-form-urlencoded")
        with urllib.request.urlopen(post) as resp:
            assert resp.status == 200
            assert resp.read() == b"success:alice@example.com"
    finally:
        server.shutdown()


def test_implicit_callback_wrong_flow(idp):
    holder = {}
    server, callback_url = _serve(lambda e, s: holder["app"](e, s))
    p = _provider(idp, callback_url)
    req = Request(60, callback_url)  # NOT implicit
    holder["app"] = implicit(p, SingleRequestReader(req), _success, _error)
    try:
        data = urlencode({"state": req.state(), "id_token": "x.y.z"}).encode()
        post = urllib.request.Request(callback_url, data=data, method="POST")
        post.add_header("Content-Type", "application/x-www-form-urlencoded")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(post)
        assert ei.value.read() == b"error:InvalidFlowError"
    finally:
        server.shutdown()


def test_implicit_disabled_at_idp(idp):
    idp.set_disable_implicit(True)
    try:
        p = _provider(idp, "https://app/cb2")
        p.config.allowed_redirect_urls = []
        req = Request(60, "https://app/cb2", implicit_flow=True)
        status, _, _ = _http.get(
            p.auth_url(req), _http.ssl_context_for_ca(idp.ca_cert()))
        assert status == 403
    finally:
        idp.set_disable_implicit(False)
