"""Config / Request / Token / IDToken / PKCE / ID unit tables."""

import json

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.errors import (
    InvalidAtHashError,
    InvalidCodeHashError,
    InvalidIssuerError,
    InvalidParameterError,
    UnsupportedChallengeMethodError,
)
from cap_tpu.oidc import (
    ClientSecret,
    Config,
    IDToken,
    Request,
    S256Verifier,
    Token,
    new_id,
)
from cap_tpu.oidc.pkce import create_code_challenge


# -- Config ----------------------------------------------------------------

def _config(**kw):
    args = dict(
        issuer="https://idp.example.com",
        client_id="client-id",
        client_secret="hush",
        supported_signing_algs=["RS256"],
        allowed_redirect_urls=["https://app/callback"],
    )
    args.update(kw)
    return Config(**args)


def test_config_valid():
    c = _config()
    assert c.client_id == "client-id"
    assert isinstance(c.client_secret, ClientSecret)


@pytest.mark.parametrize("kw,exc", [
    ({"client_id": ""}, InvalidParameterError),
    ({"issuer": ""}, InvalidParameterError),
    ({"issuer": "ftp://x"}, InvalidIssuerError),
    ({"supported_signing_algs": []}, InvalidParameterError),
    ({"supported_signing_algs": ["none"]}, InvalidParameterError),
    ({"supported_signing_algs": ["HS256"]}, InvalidParameterError),
])
def test_config_invalid(kw, exc):
    with pytest.raises(exc):
        _config(**kw)


def test_config_http_issuer_allowed():
    assert _config(issuer="http://localhost:8080").issuer


def test_client_secret_redacts():
    s = ClientSecret("super-secret")
    assert "super-secret" not in str(s)
    assert "super-secret" not in repr(s)
    assert "super-secret" not in f"{s}"
    assert s.reveal() == "super-secret"
    assert s == "super-secret"


# -- IDs / PKCE ------------------------------------------------------------

def test_new_id():
    a, b = new_id(), new_id()
    assert len(a) == 20 and a != b
    assert new_id(prefix="st").startswith("st_")


def test_pkce_s256():
    v = S256Verifier()
    assert len(v.verifier()) == 43
    assert v.method() == "S256"
    import base64
    import hashlib

    expected = base64.urlsafe_b64encode(
        hashlib.sha256(v.verifier().encode()).digest()).rstrip(b"=").decode()
    assert v.challenge() == expected
    assert v.copy().verifier() == v.verifier()
    assert "REDACTED" in repr(v)


def test_pkce_rejects_bad_method():
    class Plain:
        def method(self):
            return "plain"

        def verifier(self):
            return "x" * 43

    with pytest.raises(UnsupportedChallengeMethodError):
        create_code_challenge(Plain())


# -- Request ---------------------------------------------------------------

def test_request_defaults():
    r = Request(60, "https://app/callback")
    assert r.state().startswith("st_")
    assert r.nonce().startswith("n_")
    assert r.state() != r.nonce()
    assert not r.is_expired()


def test_request_expiry():
    r = Request(0.001, "https://app/cb")
    import time

    time.sleep(0.01)
    assert not r.is_expired()  # within the 1s skew
    r2 = Request(60, "https://app/cb", now_func=lambda: 1000.0)
    assert r2.expiration() == 1060.0


def test_request_implicit_pkce_exclusive():
    with pytest.raises(InvalidParameterError):
        Request(60, "https://app/cb", implicit_flow=True,
                pkce_verifier=S256Verifier())


def test_request_state_nonce_must_differ():
    with pytest.raises(InvalidParameterError):
        Request(60, "https://app/cb", state="same", nonce="same")


def test_request_claims_json_validation():
    Request(60, "https://app/cb", claims={"id_token": {"email": None}})
    Request(60, "https://app/cb", claims='{"a": 1}')
    with pytest.raises(InvalidParameterError):
        Request(60, "https://app/cb", claims="{not json")


def test_request_defensive_copies():
    r = Request(60, "https://app/cb", scopes=["email"], audiences=["a"])
    r.scopes().append("mutate")
    r.audiences().append("mutate")
    assert r.scopes() == ["email"]
    assert r.audiences() == ["a"]


def test_request_max_age():
    r = Request(60, "https://app/cb", max_age=100, now_func=lambda: 1000.0)
    secs, auth_after = r.max_age()
    assert secs == 100 and auth_after == 900.0


# -- Token / IDToken -------------------------------------------------------

def _signed_id_token(alg="ES256", claims=None, **extra):
    priv, pub = captest.generate_keys(alg)
    c = captest.default_claims(**(claims or {}))
    c.update(extra)
    return captest.sign_jwt(priv, alg, c), pub


def test_token_requires_id_token():
    with pytest.raises(InvalidParameterError):
        Token("")


def test_token_expiry_and_validity():
    raw, _ = _signed_id_token()
    t = Token(raw, access_token="at", expiry=2000.0,
              now_func=lambda: 1000.0)
    assert t.valid() and not t.is_expired()
    # within the 10s skew of expiry
    t2 = Token(raw, access_token="at", expiry=1005.0,
               now_func=lambda: 1000.0)
    assert t2.is_expired()
    # zero expiry → never expires
    t3 = Token(raw, access_token="at", expiry=0.0)
    assert t3.valid()
    # no access token → invalid & expired
    t4 = Token(raw)
    assert not t4.valid() and t4.is_expired()


def test_token_redaction():
    raw, _ = _signed_id_token()
    t = Token(raw, access_token="secret-at", refresh_token="secret-rt")
    blob = repr(t)
    assert "secret-at" not in blob and "secret-rt" not in blob
    assert raw not in blob
    assert t.access_token().reveal() == "secret-at"


def test_id_token_claims_unverified():
    raw, _ = _signed_id_token()
    t = IDToken(raw)
    assert t.claims()["sub"] == "alice"
    assert "alice" not in str(t)


def test_at_hash_verification():
    import base64
    import hashlib

    at = "my-access-token"
    d = hashlib.sha256(at.encode()).digest()
    at_hash = base64.urlsafe_b64encode(d[:16]).rstrip(b"=").decode()
    raw, _ = _signed_id_token(at_hash=at_hash)
    t = IDToken(raw)
    assert t.verify_access_token(at) is True
    with pytest.raises(InvalidAtHashError):
        t.verify_access_token("wrong-token")


def test_c_hash_verification():
    import base64
    import hashlib

    code = "authz-code"
    d = hashlib.sha256(code.encode()).digest()
    c_hash = base64.urlsafe_b64encode(d[:16]).rstrip(b"=").decode()
    raw, _ = _signed_id_token(c_hash=c_hash)
    t = IDToken(raw)
    assert t.verify_authorization_code(code) is True
    with pytest.raises(InvalidCodeHashError):
        t.verify_authorization_code("stolen-code")


def test_eddsa_hash_claims_unverifiable():
    at = "tok"
    raw, _ = _signed_id_token(alg="EdDSA", at_hash="whatever")
    assert IDToken(raw).verify_access_token(at) is False
