"""Batched Ed25519 engine parity vs the CPU oracle.

The reference exercises EdDSA against both KeySet kinds with real
Ed25519 keys (jwt/keyset_test.go:27-266 alg table); these tests mirror
that conformance row for the device engine: successes, tampered
inputs, canonicality violations (malleable S+L, non-canonical R,
high-bit S), key routing through TPUBatchKeySet, and parity against
the ``cryptography`` oracle on mixed verdict batches.
"""

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric import ed25519

from cap_tpu import testing as captest
from cap_tpu.errors import InvalidSignatureError
from cap_tpu.jwt import StaticKeySet, algs
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
from cap_tpu.tpu.ed25519 import (
    L_ORDER,
    P,
    Ed25519KeyTable,
    decode_point,
    verify_ed25519_batch,
)


def _oracle(pub, sig: bytes, msg: bytes) -> bool:
    try:
        pub.verify(sig, msg)
        return True
    except InvalidSignature:
        return False


def test_decode_point_basepoint():
    by = 4 * pow(5, -1, P) % P
    pt = decode_point(by.to_bytes(32, "little"))
    assert pt is not None
    x, y = pt
    assert y == by and x % 2 == 0
    # y >= p is not a valid encoding
    assert decode_point(b"\xff" * 31 + b"\x7f") is None


def test_conformance_mixed_batch():
    privs = [ed25519.Ed25519PrivateKey.generate() for _ in range(4)]
    pubs = [p.public_key() for p in privs]
    table = Ed25519KeyTable(pubs)

    sigs, msgs, rows, want = [], [], [], []

    def add(sig, msg, row, ok):
        sigs.append(sig); msgs.append(msg); rows.append(row); want.append(ok)

    for i, p in enumerate(privs):
        m = b"conformance eddsa " * (i + 1)
        add(p.sign(m), m, i, True)
    good = sigs[0]
    msg0 = msgs[0]
    # tampered message
    add(good, msg0 + b"x", 0, False)
    # tampered R / tampered S
    for pos in (3, 40):
        bad = bytearray(good)
        bad[pos] ^= 1
        add(bytes(bad), msg0, 0, False)
    # wrong key
    add(good, msg0, 1, False)
    # malleable S + L (classic forgery a naive impl accepts)
    s_int = int.from_bytes(good[32:], "little")
    add(good[:32] + (s_int + L_ORDER).to_bytes(32, "little"), msg0, 0, False)
    # S with high bits set (>= 2^253)
    add(good[:32] + (s_int | (1 << 255)).to_bytes(32, "little"), msg0, 0,
        False)
    # R not on the curve / non-canonical R
    add(b"\xff" * 32 + good[32:], msg0, 0, False)
    # empty message, fresh signature
    add(privs[2].sign(b""), b"", 2, True)
    # wrong signature length
    add(good[:63], msg0, 0, False)

    ok = verify_ed25519_batch(table, sigs, msgs, np.asarray(rows, np.int32))
    assert ok.tolist() == want
    # every verdict agrees with the CPU oracle
    for sig, msg, row, got in zip(sigs, msgs, rows, ok):
        assert bool(got) == _oracle(pubs[row], sig, msg)


def test_sign_flip_rejected():
    """Flipping only R's sign bit must flip the parity check."""
    priv = ed25519.Ed25519PrivateKey.generate()
    table = Ed25519KeyTable([priv.public_key()])
    msg = b"sign bit"
    sig = priv.sign(msg)
    flipped = bytes([*sig[:31], sig[31] ^ 0x80]) + sig[32:]
    ok = verify_ed25519_batch(table, [sig, flipped], [msg, msg],
                              np.zeros(2, np.int32))
    assert ok.tolist() == [True, False]


def test_undecodable_key_rows_verify_false():
    """A key whose bytes are not a curve point always verifies False
    (Go returns false at decode; the oracle raises at verify)."""
    priv = ed25519.Ed25519PrivateKey.generate()
    bad_pub = ed25519.Ed25519PublicKey.from_public_bytes(
        b"\xff" * 31 + b"\x7f")
    table = Ed25519KeyTable([priv.public_key(), bad_pub])
    assert table.invalid.tolist() == [False, True]
    msg = b"bad key row"
    sig = priv.sign(msg)
    ok = verify_ed25519_batch(table, [sig, sig], [msg, msg],
                              np.asarray([0, 1], np.int32))
    assert ok.tolist() == [True, False]


def test_identity_precompute_key():
    """A == B makes the Shamir precompute B+(-A) the identity; the
    complete formulas must still verify correctly (no gq_inf analog)."""
    # Build a signer whose public key IS the basepoint-derived key of
    # some other secret: easiest honest construction is any key; the
    # identity-addend case (both bits set, D = identity) is exercised
    # whenever A == B. Synthesize via the table directly:
    priv = ed25519.Ed25519PrivateKey.generate()
    pub = priv.public_key()
    table = Ed25519KeyTable([pub, pub])
    msg = b"identity addend"
    sig = priv.sign(msg)
    ok = verify_ed25519_batch(table, [sig, sig], [msg, msg],
                              np.asarray([0, 1], np.int32))
    assert ok.tolist() == [True, True]


def test_tpu_keyset_eddsa_batch_paths():
    """EdDSA tokens route through the device engine on both batch paths
    and match the single-token CPU path."""
    jwks, signers = [], []
    for i in range(3):
        priv, pub = captest.generate_keys(algs.EdDSA)
        jwks.append(JWK(pub, kid=f"ed-{i}"))
        signers.append(priv)
    claims = captest.default_claims()
    tokens = [captest.sign_jwt(signers[i % 3], algs.EdDSA, claims,
                               kid=f"ed-{i % 3}") for i in range(10)]
    # one forged token: signature from a different key under kid ed-0
    forged = captest.sign_jwt(signers[1], algs.EdDSA, claims, kid="ed-0")
    tokens.append(forged)

    ks = TPUBatchKeySet(jwks)
    assert ks._ed_table is not None
    for res_list in (ks._verify_batch_objects(tokens),
                     ks.verify_batch(tokens)):
        for i, res in enumerate(res_list[:10]):
            assert isinstance(res, dict) and res["sub"] == claims["sub"]
        assert isinstance(res_list[10], InvalidSignatureError)

    static = StaticKeySet([j.key for j in jwks])
    assert static.verify_signature(tokens[0])["iss"] == claims["iss"]
    with pytest.raises(InvalidSignatureError):
        static.verify_signature(forged + "x")
