"""Front door: digest-affinity routing + fleet-wide verdict tier.

Covers the ring (stability under membership change), the routing
partition (exact hit/miss accounting, bounded-load spill, dead-pool
re-route), keyplane fan-out through the router, the peer-fill frame
pair's worker handlers on both serve chains, the peer-fill parity pin
(bit-identical verdicts and decision counters with warming on vs off,
incl. an epoch swap and an exp crossing mid-run), and the multi-pool
chaos acceptance: kill -9 an entire pool mid-rotation under sustained
hot-token load.
"""

import json
import signal
import socket
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import ConsistentHashRing, FrontDoor, WorkerPool
from cap_tpu.fleet.frontdoor import frontdoor_from_spec
from cap_tpu.fleet.worker_main import StubKeySet, make_keyset
from cap_tpu.fleet.chaos import kill9
from cap_tpu.serve import protocol as P
from cap_tpu.serve import vcache as V
from cap_tpu.serve.client import VerifyClient
from cap_tpu.serve.worker import VerifyWorker


def _digests(tokens):
    return [V.token_digest(t) for t in tokens]


# ---------------------------------------------------------------------------
# unit: the consistent-hash ring
# ---------------------------------------------------------------------------


def test_ring_deterministic_and_covers_all_pools():
    ring = ConsistentHashRing([0, 1, 2])
    toks = [f"ring-{i}" for i in range(600)]
    owners = [ring.primary(d) for d in _digests(toks)]
    assert owners == [ring.primary(d) for d in _digests(toks)]
    assert set(owners) == {0, 1, 2}
    # near-uniform split: no pool owns more than ~2/3 of the keys
    for pid in (0, 1, 2):
        assert owners.count(pid) < 400


def test_ring_membership_change_remaps_only_lost_segments():
    """THE consistent-hash property: dropping pool 2 moves ONLY the
    tokens pool 2 owned; everything else keeps its owner."""
    full = ConsistentHashRing([0, 1, 2])
    reduced = ConsistentHashRing([0, 1])
    moved = 0
    for d in _digests([f"stable-{i}" for i in range(500)]):
        before, after = full.primary(d), reduced.primary(d)
        if before == 2:
            assert after in (0, 1)
            moved += 1
        else:
            assert after == before, "unowned segment remapped"
    assert moved > 0


def test_ring_preference_distinct_pools():
    ring = ConsistentHashRing([0, 1, 2])
    for d in _digests([f"pref-{i}" for i in range(50)]):
        pref = ring.preference(d, 2)
        assert len(pref) == 2 and pref[0] != pref[1]
        assert ring.preference(d, 1) == [pref[0]]


# ---------------------------------------------------------------------------
# unit: partition accounting (bare endpoints, no dispatch)
# ---------------------------------------------------------------------------


def _bare_frontdoor(n_pools=2, **kw):
    # unreachable-but-listed endpoints: partition-level tests never
    # dispatch, and has_live_endpoint() treats a listed endpoint with
    # a closed breaker as live
    return FrontDoor([[("127.0.0.1", 1 + i)] for i in range(n_pools)],
                     **kw)


def test_partition_exact_hit_accounting_and_reuses_digests():
    fd = _bare_frontdoor()
    toks = [f"part-{i}.ok" for i in range(64)]
    groups, hits_by = fd._partition(toks, None)
    assert sorted(i for g in groups.values() for i in g) \
        == list(range(64))
    c = fd.counters()
    assert c["frontdoor.lookups"] == 64
    assert c["frontdoor.affinity_hits"] \
        + c["frontdoor.affinity_misses"] == 64
    assert c["frontdoor.spills"] == 0
    # caller-supplied digests are authoritative: a crafted digest
    # changes the route, proving no re-hash happened
    d0 = V.token_digest(toks[0])
    groups1, _ = fd._partition([toks[0]], [d0])
    fake = bytes(16)
    groups2, _ = fd._partition([toks[0]], [fake])
    assert next(iter(groups1)) == fd._ring.primary(d0)
    assert next(iter(groups2)) == fd._ring.primary(fake)


def test_partition_bounded_load_spills_to_second_choice():
    fd = _bare_frontdoor()            # default bounded-load c=1.25
    tok = "hot-spill.ok"
    d = V.token_digest(tok)
    primary, second = fd._ring.preference(d, 2)
    # primary is drowning, second idle → power-of-two spill
    fd._arms[primary].inflight = 10_000
    groups, _ = fd._partition([tok], [d])
    assert list(groups) == [second]
    c = fd.counters()
    assert c["frontdoor.spills"] == 1
    assert c["frontdoor.affinity_hits"] \
        + c["frontdoor.affinity_misses"] == c["frontdoor.lookups"]


def test_partition_reroutes_off_dead_pool():
    fd = _bare_frontdoor()
    tok = "dead-pool.ok"
    d = V.token_digest(tok)
    primary, second = fd._ring.preference(d, 2)
    # open every breaker on the primary arm → not live
    cl = fd._arms[primary].client
    for ep in cl._live_endpoints():
        for _ in range(5):
            cl._on_failure(ep)
    assert not fd._arms[primary].live()
    groups, _ = fd._partition([tok], [d])
    assert list(groups) == [second]
    assert fd.counters()["frontdoor.reroutes"] == 1


def test_rr_mode_round_robins_whole_batches():
    fd = _bare_frontdoor(routing="rr")
    seen = []
    for _ in range(4):
        groups, _ = fd._partition(["rr-a.ok", "rr-b.ok"], None)
        seen.append(next(iter(groups)))
    assert seen == [0, 1, 0, 1]
    c = fd.counters()
    assert c["frontdoor.lookups"] == 8
    assert c["frontdoor.affinity_hits"] \
        + c["frontdoor.affinity_misses"] == 8


def test_frontdoor_spec_parses():
    fd = frontdoor_from_spec(
        "pool=127.0.0.1:19001+127.0.0.1:19002;pool=127.0.0.1:19003;"
        "routing=rr;spill=3.5")
    assert len(fd._arms) == 2
    assert fd._routing == "rr" and fd._spill_factor == 3.5
    assert len(fd._arms[0].client._live_endpoints()) == 2
    with pytest.raises(ValueError):
        frontdoor_from_spec("routing=affinity")      # no pools
    with pytest.raises(ValueError):
        frontdoor_from_spec("pool=a:1;bogus=1")
    fd2 = make_keyset("frontdoor:pool=127.0.0.1:19001")
    assert isinstance(fd2, FrontDoor)


# ---------------------------------------------------------------------------
# integration: routing + re-route + fallback over live workers
# ---------------------------------------------------------------------------


def _two_workers(**kw):
    w0 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      **kw)
    w1 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      **kw)
    return w0, w1


def test_routing_end_to_end_and_affinity_repeats_hit_worker_cache():
    rec = telemetry.enable()
    rec.reset()
    w0, w1 = _two_workers(vcache=True)
    try:
        fd = FrontDoor([[w0.address], [w1.address]],
                       fallback=StubKeySet(),
                       client_kw={"attempt_timeout": 5.0,
                                  "total_deadline": 10.0})
        toks = [f"e2e-{i}.ok" for i in range(24)] + ["e2e-bad"]
        for rep in range(3):
            out = fd.verify_batch(toks)
            assert len(out) == 25
            for t, r in zip(toks, out):
                if t.endswith(".ok"):
                    assert r == {"sub": t}, (t, r)
                else:
                    assert isinstance(r, Exception)
        c = rec.counters()
        # repeats hit the worker-tier cache because affinity pinned
        # them to the same worker
        assert c.get("vcache.hits", 0) >= 25
        assert c.get("vcache.stale_accepts", 0) == 0
        assert c["frontdoor.lookups"] == 75
        # decision records on the frontdoor surface
        assert c.get("decision.frontdoor.accept", 0) == 72
        fd.close()
    finally:
        w0.close(5)
        w1.close(5)
        telemetry.disable()


def test_dead_pool_reroutes_then_terminal_fallback():
    rec = telemetry.enable()
    rec.reset()
    w0, w1 = _two_workers()
    addr1 = w1.address
    w1.close(5)                       # pool 1 is dead from the start
    try:
        fd = FrontDoor([[w0.address], [addr1]],
                       fallback=StubKeySet(),
                       client_kw={"attempt_timeout": 1.0,
                                  "total_deadline": 3.0,
                                  "max_rounds": 1,
                                  "breaker_threshold": 1})
        toks = [f"rr-{i}.ok" for i in range(32)]
        out = fd.verify_batch(toks)
        assert [r == {"sub": t} for t, r in zip(toks, out)] \
            == [True] * 32, "verdicts survived the dead pool"
        c = fd.counters()
        assert c["frontdoor.lookups"] == 32
        assert c["frontdoor.affinity_hits"] \
            + c["frontdoor.affinity_misses"] == 32
        # pool 1's share either re-routed (breaker view) or fell back
        assert c["frontdoor.reroutes"] > 0 \
            or c["frontdoor.fallback_tokens"] > 0
        # every later call routes around the dead pool at partition
        out = fd.verify_batch(toks)
        assert all(r == {"sub": t} for t, r in zip(toks, out))
        fd.close()
    finally:
        w0.close(5)
        telemetry.disable()


def test_keys_fanout_to_bare_endpoint_pools():
    w0, w1 = _two_workers()
    try:
        fd = FrontDoor([[w0.address], [w1.address]])
        acks = fd.push_keys({"keys": []})
        assert fd.key_epoch == 1
        for pool_acks in acks.values():
            assert set(pool_acks.values()) == {1}
        assert w0.key_epoch == 1 and w1.key_epoch == 1
        # swap_keys alias: the engine-facing surface a front-door
        # VerifyWorker exposes to KEYS pushes
        assert fd.swap_keys({"keys": []}) == 2
        assert w0.key_epoch == 2
        fd.close()
    finally:
        w0.close(5)
        w1.close(5)


# ---------------------------------------------------------------------------
# peer fill: worker handlers on both chains + clamp behavior
# ---------------------------------------------------------------------------


def _peer_exchange(src_addr, dst_addr, max_entries=100):
    """Pull an export from src, push it into dst; returns imported."""
    with socket.create_connection(src_addr, timeout=5) as s:
        P.send_peer_fill(s, {"op": "export", "max": max_entries})
        ftype, entries = P.FrameReader(s).recv_frame()
    assert ftype == P.T_PEER_ACK and entries[0][0] == 0
    doc = json.loads(entries[0][1])
    with socket.create_connection(dst_addr, timeout=5) as s:
        P.send_peer_fill(s, {"op": "import", "epoch": doc["epoch"],
                             "entries": doc["entries"]})
        ftype, entries = P.FrameReader(s).recv_frame()
    assert ftype == P.T_PEER_ACK and entries[0][0] == 0
    return json.loads(entries[0][1])["imported"], doc


@pytest.mark.parametrize("serve_native", [False, True])
def test_peer_fill_wire_roundtrip_warms_sibling(serve_native):
    rec = telemetry.enable()
    rec.reset()
    w0 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      serve_native=serve_native, vcache=True)
    if serve_native and w0.serve_chain != "native":
        w0.close(5)
        pytest.skip("native serve chain unavailable")
    w1 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      serve_native=serve_native, vcache=True)
    try:
        with VerifyClient(*w0.address) as c:
            c.verify_batch(["pf-a.ok", "pf-b.ok", "pf-bad"])
        imported, doc = _peer_exchange(w0.address, w1.address)
        assert imported == 2            # accepts only, never rejects
        assert all(len(row) == 5 for row in doc["entries"])
        # the warmed worker serves the verdict at memory speed: its
        # OWN engine never sees the token
        with VerifyClient(*w1.address) as c:
            out = c.verify_batch(["pf-a.ok"])
        assert out[0] == {"sub": "pf-a.ok"}
        c2 = rec.counters()
        assert c2.get("vcache.peer_fills", 0) == 2
        assert c2.get("vcache.stale_accepts", 0) == 0
    finally:
        w0.close(5)
        w1.close(5)
        telemetry.disable()


def test_peer_fill_errors_are_acked_not_fatal():
    w = VerifyWorker(StubKeySet(), target_batch=8, max_wait_ms=1.0,
                     vcache=False)          # no cache tier
    try:
        with socket.create_connection(w.address, timeout=5) as s:
            P.send_peer_fill(s, {"op": "export", "max": 10})
            ftype, entries = P.FrameReader(s).recv_frame()
        assert ftype == P.T_PEER_ACK
        assert entries[0][0] == 1           # status-1 error ack
        assert b"TypeError" in entries[0][1]
        # the connection (and worker) survive: verify still works
        with VerifyClient(*w.address) as c:
            assert c.verify_batch(["after.ok"])[0] == {"sub":
                                                       "after.ok"}
    finally:
        w.close(5)


def test_import_cannot_extend_validity():
    """The clamp acceptance: whatever the wire claims, an imported
    entry's validity is re-bounded by the IMPORTER's TTL and exp —
    warming can never extend a verdict's life."""
    vc = V.VerdictCache(max_ttl_s=0.3)
    vc.set_epoch(7)
    d = V.token_digest("clamp-t")
    far = time.time() + 3600
    # wire entry claims a huge window
    n = vc.import_entries(
        [[d.hex(), "eyJzdWIiOiJ4In0=", 0.0, far, far]], epoch=7)
    assert n == 1
    assert vc.get(d) is not V.MISS
    time.sleep(0.35)
    assert vc.get(d) is V.MISS, "import outlived the importer's TTL"
    # expired-on-arrival and wrong-epoch entries never install
    assert vc.import_entries(
        [[d.hex(), "eyJzdWIiOiJ4In0=", 0.0, time.time() - 1,
          None]], epoch=7) == 0
    assert vc.import_entries(
        [[d.hex(), "eyJzdWIiOiJ4In0=", 0.0, far, None]], epoch=8) == 0
    st = vc.stats()
    assert st["vcache.peer_fill_skips"] == 2


# ---------------------------------------------------------------------------
# parity pin: peer-fill on vs off (the acceptance sweep)
# ---------------------------------------------------------------------------


def _mixed_sequence(n_batches=18, seed=11):
    import base64
    import random

    def tok(name, ok=True, **claims):
        mid = base64.urlsafe_b64encode(
            json.dumps(claims).encode()).rstrip(b"=").decode() \
            if claims else "e30"
        return f"{name}.{mid}.{'ok' if ok else 'bad'}"

    rng = random.Random(seed)
    pool = ([tok(f"hot{i}", ok=True, exp=time.time() + 3600)
             for i in range(5)]
            + [tok(f"bad{i}", ok=False) for i in range(2)]
            + [tok("expiring", ok=True, exp=time.time() + 0.9)])
    return [[rng.choice(pool) for _ in range(rng.randrange(1, 5))]
            for _ in range(n_batches)]


def _run_peer_sweep(serve_native, peer_fill, seq, rotate_at=9):
    """Warm worker A, optionally transfer its cache into fresh worker
    B over the wire, then drive the sweep at B (epoch swap mid-run,
    expiring token crossing exp). Returns B's normalized verdicts +
    serve decision counters."""
    wa = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      serve_native=serve_native, vcache=True)
    if serve_native and wa.serve_chain != "native":
        wa.close(5)
        pytest.skip("native serve chain unavailable")
    rec = telemetry.enable()
    wb = None
    try:
        warm = sorted({t for batch in seq for t in batch})
        with VerifyClient(*wa.address) as c:
            c.verify_batch(warm)
        rec.reset()                  # B's run counts from zero
        wb = VerifyWorker(StubKeySet(), target_batch=64,
                          max_wait_ms=1.0, serve_native=serve_native,
                          vcache=True)
        if peer_fill:
            imported, _ = _peer_exchange(wa.address, wb.address)
            assert imported > 0
        out = []
        with VerifyClient(*wb.address) as c:
            for i, batch in enumerate(seq):
                if i == rotate_at:
                    wb.apply_keys({}, 2)
                out.append(c.verify_batch(batch))
        verdicts = [[str(r).split(":", 1)[0]
                     if isinstance(r, Exception) else
                     (json.loads(r) if isinstance(r, bytes) else r)
                     for r in batch] for batch in out]
        dec = {k: v for k, v in rec.counters().items()
               if k.startswith("decision.serve.")}
        stale = rec.counters().get("vcache.stale_accepts", 0)
        fills = rec.counters().get("vcache.peer_fills", 0)
        return verdicts, dec, stale, fills
    finally:
        wa.close(5)
        if wb is not None:
            wb.close(5)
        telemetry.disable()


@pytest.mark.parametrize("serve_native", [False, True])
def test_peer_fill_parity_on_vs_off(serve_native):
    """The acceptance pin: bit-identical verdicts AND serve decision
    counters with peer-fill warming on vs off, across an epoch swap
    and an exp crossing mid-run — warming changes speed, never
    verdicts."""
    seq = _mixed_sequence()
    on_v, on_d, on_stale, on_fills = _run_peer_sweep(
        serve_native, True, seq)
    off_v, off_d, off_stale, off_fills = _run_peer_sweep(
        serve_native, False, seq)
    assert on_fills > 0 and off_fills == 0
    assert on_v == off_v
    assert on_d == off_d
    assert on_stale == 0 and off_stale == 0


# ---------------------------------------------------------------------------
# chaos: kill -9 an entire pool mid-rotation under hot-token load
# ---------------------------------------------------------------------------

HARD_TIMEOUT_S = 150


@pytest.fixture(autouse=True)
def _hard_timeout():
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"frontdoor test exceeded hard {HARD_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _expected_ok(t):
    return t.endswith(".ok")


@pytest.mark.chaos
@pytest.mark.parametrize("serve_chain,router_chain", [
    ("python", "python"),
    ("native", "python"),
    # the crossed arm adds no routing coverage beyond the two above
    # (the relay is serve-chain-agnostic) — kept out of the tier-1
    # time budget, still run with the slow suite
    pytest.param("python", "native", marks=pytest.mark.slow),
    ("native", "native"),
])
def test_pool_kill9_mid_rotation_under_hot_load(serve_chain,
                                                router_chain):
    """Kill -9 an ENTIRE pool mid-rotation while hot-token load flows:
    zero wrong verdicts, zero lost submissions, zero stale accepts
    fleet-wide, epoch convergence after respawn, and a peer-filled
    replacement worker shows ``vcache.peer_fills`` > 0 in its
    postmortem. ``router_chain=native`` drives the SAME load through
    the zero-copy relay gate (NativeFrontDoorServer) over a socket —
    relay failures mid-kill must re-dispatch through the Python slow
    path with the identical availability contract."""
    native = serve_chain == "native"
    pools = [WorkerPool(2, keyset_spec="stub:batch_ms=25",
                        ping_interval=0.2, max_restarts=20,
                        max_wait_ms=1.0,
                        env_extra={"CAP_SERVE_NATIVE":
                                   "1" if native else "0"})
             for _ in range(2)]
    fd = None
    gw = None
    try:
        for p in pools:
            assert p.wait_all_ready(30), "fleet did not come up"
        chains = {c for p in pools
                  for c in p.serve_chains().values()}
        if native and chains != {"native"}:
            pytest.skip(f"native chain unavailable ({chains})")
        fd = FrontDoor(pools, fallback=StubKeySet(),
                       client_kw={"attempt_timeout": 2.0,
                                  "total_deadline": 20.0,
                                  "breaker_reset_s": 0.5})
        if router_chain == "native":
            try:
                from cap_tpu.fleet.frontdoor import \
                    NativeFrontDoorServer

                gw = NativeFrontDoorServer(fd, refresh_s=0.1)
            except (ImportError, ValueError) as e:
                pytest.skip(f"native router chain unavailable ({e})")
        hot = [f"hot-{i}.ok" for i in range(10)] + ["hot-bad"]
        stop = threading.Event()
        failures = []
        served = [0]
        local = threading.local()

        def submit(tokens):
            if gw is None:
                return fd.verify_batch(tokens)
            # one relay-gate connection per driver thread; verdicts
            # come back over the wire exactly as a fleet client sees
            # them, whatever path (splice or slow) produced each
            s = getattr(local, "sock", None)
            if s is None:
                s = socket.create_connection(gw.address,
                                             timeout=10.0)
                s.settimeout(25.0)
                local.sock = s
                local.reader = P.FrameReader(s)
            P.send_request(s, tokens)
            _ft, entries = local.reader.recv_frame()
            return [json.loads(payload) if st == 0
                    else RuntimeError(payload.decode())
                    for st, payload in entries]

        def drive():
            while not stop.is_set():
                try:
                    out = submit(hot)
                except Exception as e:  # noqa: BLE001 - recorded
                    failures.append(f"raised: {e!r}")
                    return
                if len(out) != len(hot):
                    failures.append("lost submissions")
                    return
                for t, r in zip(hot, out):
                    if _expected_ok(t) != (not isinstance(r,
                                                          Exception)):
                        failures.append(f"WRONG verdict {t!r}: {r!r}")
                        return
                    if _expected_ok(t) and r != {"sub": t}:
                        failures.append(f"WRONG claims {t!r}: {r!r}")
                        return
                served[0] += len(out)

        drivers = [threading.Thread(target=drive, daemon=True)
                   for _ in range(3)]
        for d in drivers:
            d.start()
        time.sleep(1.0)               # warm caches under load

        # rotation + the kill land together: the push is mid-flight
        # when the whole victim pool dies
        victim = pools[1]
        victim_pids = [victim.pid(w) for w in (0, 1)]
        push = threading.Thread(
            target=lambda: fd.push_keys({"keys": []}), daemon=True)
        push.start()
        time.sleep(0.01)
        for pid in victim_pids:
            if pid:
                kill9(pid)
        push.join(timeout=60)

        # sustained load through death, respawn, and re-warm
        time.sleep(6.0)
        stop.set()
        for d in drivers:
            d.join(timeout=30)
            assert not d.is_alive(), "driver wedged"
        assert not failures, failures
        assert served[0] > 0

        # epoch convergence after respawn, fleet-wide, via the router
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if fd.epoch_skew() == 0 and None not in \
                    fd.key_epochs().values():
                break
            time.sleep(0.2)
        assert fd.epoch_skew() == 0, fd.key_epochs()
        assert set(fd.key_epochs().values()) == {1}

        # zero stale accepts fleet-wide + a peer-filled replacement
        deadline = time.monotonic() + 30
        filled_wid = None
        while time.monotonic() < deadline and filled_wid is None:
            stats = victim.stats()
            for wid, st in stats.items():
                ctr = (st or {}).get("counters") or {}
                if ctr.get("vcache.stale_accepts", 0):
                    failures.append(f"stale accept on victim w{wid}")
                if ctr.get("vcache.peer_fills", 0) > 0:
                    filled_wid = wid
            if filled_wid is None:
                time.sleep(0.5)
        assert not failures, failures
        for p in pools:
            agg = p.stats_merged()["aggregate"]["counters"]
            assert agg.get("vcache.stale_accepts", 0) == 0
        assert filled_wid is not None, \
            "no respawned worker was peer-filled"

        # the acceptance artifact: the peer fill shows up in the
        # worker's POSTMORTEM (graceful restart writes a fresh doc)
        victim.restart(filled_wid, graceful=True)
        doc = victim.postmortem(filled_wid)
        assert doc is not None
        pm_counters = (doc.get("stats") or {}).get("counters") or {}
        assert pm_counters.get("vcache.peer_fills", 0) > 0, \
            pm_counters
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)
        elif fd is not None:
            fd.close()
        for p in pools:
            p.close()
