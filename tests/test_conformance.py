"""Rejection-parity conformance: crit headers, JSON-serialization JWS,
x5c JWKs (VERDICT r4 gaps 1-3), and adversarial SIGNATURE ENCODINGS
(VERDICT r5 open item, pinned golden vectors).

The bar: identical verdicts to the reference's go-jose path across ALL
four verify surfaces — CPU oracle (StaticKeySet), TPU batch
(TPUBatchKeySet), native prep (prepare_batch), and the serve worker.
Reference semantics: jwt/jwt.go:212-227 (ParseSigned + one-signature
rule), jwt/keyset.go:109-122 (go-jose JSONWebKey x5c),
jwt/keyset.go:155-167 (crit rejection via .Claims).

The classic suites need the ``cryptography`` stack for fixtures and
skip cleanly where it is absent; the golden-vector signature-encoding
suite is dependency-free down to the device engines (pinned tokens +
host-integer keys) and runs everywhere.
"""

from __future__ import annotations

import hashlib
import json
import os

import pytest

from cap_tpu.errors import (
    InvalidJWKSError,
    InvalidSignatureError,
    MalformedTokenError,
)
from cap_tpu.jwt import algs
from cap_tpu.jwt.jose import (
    json_to_compact,
    parse_compact,
    parse_json,
    parse_jws,
    peek_alg,
)
from cap_tpu.runtime import prep

try:
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import parse_jwk, parse_jwks, serialize_public_key
    from cap_tpu.jwt.keyset import StaticKeySet
    _HAVE_CRYPTO = True
except ModuleNotFoundError:
    captest = None
    parse_jwk = parse_jwks = serialize_public_key = StaticKeySet = None
    _HAVE_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAVE_CRYPTO, reason="cryptography package not installed")


@pytest.fixture(scope="module")
def es_pair():
    return captest.generate_keys(algs.ES256)


@pytest.fixture(scope="module")
def good_token(es_pair):
    priv, _ = es_pair
    return captest.sign_jwt(priv, algs.ES256, captest.default_claims(),
                            kid="c0")


def _tpu_keyset(pubs_jwks):
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    return TPUBatchKeySet(parse_jwks({"keys": pubs_jwks}))


# ---------------------------------------------------------------------------
# crit header
# ---------------------------------------------------------------------------

@needs_crypto
class TestCritRejection:
    def _crit_token(self, es_pair):
        priv, _ = es_pair
        # A VALID signature whose protected header carries crit: the
        # reject must come from the header rule, not the signature.
        return captest.sign_jwt(
            priv, algs.ES256, captest.default_claims(), kid="c0",
            extra_headers={"crit": ["exp"], "exp": 1})

    def test_python_parse_rejects(self, es_pair):
        tok = self._crit_token(es_pair)
        with pytest.raises(MalformedTokenError, match="crit"):
            parse_compact(tok)
        with pytest.raises(MalformedTokenError, match="crit"):
            peek_alg(tok)

    def test_crit_value_is_irrelevant(self, es_pair):
        priv, _ = es_pair
        # go-jose rejects on PRESENCE, whatever the value.
        for crit_val in ([], ["b64"], "exp", 7, None):
            tok = captest.sign_jwt(priv, algs.ES256,
                                   captest.default_claims(),
                                   extra_headers={"crit": crit_val})
            with pytest.raises(MalformedTokenError, match="crit"):
                parse_jws(tok)

    def test_native_prep_rejects(self, es_pair, good_token):
        tok = self._crit_token(es_pair)
        out = prep.prepare_batch([good_token, tok])
        assert not isinstance(out[0], Exception)
        assert isinstance(out[1], MalformedTokenError)
        assert "crit" in str(out[1])

    def test_cpu_and_tpu_batch_agree(self, es_pair, good_token):
        _, pub = es_pair
        tok = self._crit_token(es_pair)
        oracle = StaticKeySet([pub]).verify_batch([good_token, tok])
        device = _tpu_keyset(
            [serialize_public_key(pub, kid="c0")]).verify_batch(
                [good_token, tok])
        for o, d in zip(oracle, device):
            assert isinstance(o, Exception) == isinstance(d, Exception)
        assert isinstance(oracle[1], MalformedTokenError)
        assert isinstance(device[1], MalformedTokenError)
        assert "crit" in str(device[1])

    def test_json_form_crit_rejected_in_either_location(self, es_pair):
        tok = self._crit_token(es_pair)
        with pytest.raises(MalformedTokenError, match="crit"):
            parse_json(captest.to_json_form(tok))
        # crit in the UNPROTECTED header is equally fatal
        clean = captest.sign_jwt(es_pair[0], algs.ES256,
                                 captest.default_claims())
        with pytest.raises(MalformedTokenError, match="crit"):
            parse_json(captest.to_json_form(
                clean, unprotected={"crit": ["exp"]}))


# ---------------------------------------------------------------------------
# JSON serialization
# ---------------------------------------------------------------------------

@needs_crypto
class TestJSONSerialization:
    def test_flattened_and_general_parse_equal_compact(self, good_token):
        ref = parse_compact(good_token)
        for flattened in (True, False):
            got = parse_jws(captest.to_json_form(good_token,
                                                 flattened=flattened))
            assert got.header == ref.header
            assert got.payload == ref.payload
            assert got.signature == ref.signature
            assert got.signing_input == ref.signing_input

    def test_two_signatures_rejected(self, good_token):
        h, p, s = good_token.split(".")
        doc = {"payload": p,
               "signatures": [{"protected": h, "signature": s},
                              {"protected": h, "signature": s}]}
        with pytest.raises(MalformedTokenError, match="exactly one"):
            parse_jws(json.dumps(doc))

    def test_mixed_members_rejected(self, good_token):
        h, p, s = good_token.split(".")
        doc = {"payload": p, "protected": h, "signature": s,
               "signatures": [{"protected": h, "signature": s}]}
        with pytest.raises(MalformedTokenError, match="mixes"):
            parse_jws(json.dumps(doc))

    def test_duplicate_header_param_rejected(self, good_token):
        with pytest.raises(MalformedTokenError, match="duplicate"):
            parse_json(captest.to_json_form(
                good_token, unprotected={"kid": "c0"}))

    def test_unprotected_kid_merges(self, es_pair):
        priv, _ = es_pair
        tok = captest.sign_jwt(priv, algs.ES256, captest.default_claims())
        parsed = parse_json(captest.to_json_form(
            tok, unprotected={"kid": "side"}))
        assert parsed.kid == "side"

    def test_json_to_compact_round_trip(self, good_token):
        for flattened in (True, False):
            jf = captest.to_json_form(good_token, flattened=flattened)
            assert json_to_compact(jf) == good_token

    def test_cpu_oracle_accepts_json_form(self, es_pair, good_token):
        _, pub = es_pair
        ks = StaticKeySet([pub])
        want = ks.verify_signature(good_token)
        assert ks.verify_signature(captest.to_json_form(good_token)) == want

    def test_tpu_batch_accepts_json_form_mixed(self, es_pair, good_token):
        _, pub = es_pair
        ks = _tpu_keyset([serialize_public_key(pub, kid="c0")])
        jf_flat = captest.to_json_form(good_token)
        jf_gen = captest.to_json_form(good_token, flattened=False)
        tampered = good_token[:-6] + (
            "AAAAAA" if not good_token.endswith("AAAAAA") else "BBBBBB")
        jf_tampered = captest.to_json_form(tampered)
        h, p, s = good_token.split(".")
        two_sigs = json.dumps({
            "payload": p,
            "signatures": [{"protected": h, "signature": s}] * 2})
        res = ks.verify_batch(
            [good_token, jf_flat, jf_gen, jf_tampered, two_sigs])
        assert res[0] == res[1] == res[2]
        assert isinstance(res[3], InvalidSignatureError)
        assert isinstance(res[4], MalformedTokenError)
        assert "exactly one" in str(res[4])

    def test_unprotected_kid_still_verifies_in_batch(self, es_pair):
        # Normalization drops the unprotected kid; key selection widens
        # to trial verification — verdict must not change.
        priv, pub = es_pair
        other_priv, other_pub = captest.generate_keys(algs.ES256)
        ks = _tpu_keyset([serialize_public_key(other_pub, kid="a"),
                          serialize_public_key(pub, kid="b")])
        tok = captest.sign_jwt(priv, algs.ES256, captest.default_claims())
        jf = captest.to_json_form(tok, unprotected={"kid": "b"})
        res = ks.verify_batch([jf])
        assert not isinstance(res[0], Exception)
        assert res[0]["iss"] == "https://example.com/"

    def test_validator_and_provider_peek(self, good_token):
        assert peek_alg(captest.to_json_form(good_token)) == algs.ES256

    def test_alg_only_in_unprotected_header_batch_parity(self, es_pair):
        # go-jose verifies against the MERGED headers, so alg may live
        # only in the unprotected header. Such a token has no compact
        # form; the batch path must fall back to object-path
        # verification instead of flipping the verdict.
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec as _ec
        from cryptography.hazmat.primitives.asymmetric.utils import (
            decode_dss_signature,
        )

        from cap_tpu.jwt.jose import b64url_encode

        priv, pub = es_pair
        claims = captest.default_claims()
        h = b64url_encode(json.dumps({"kid": "c0"}).encode())
        p = b64url_encode(json.dumps(claims).encode())
        der = priv.sign((h + "." + p).encode(), _ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        sig = b64url_encode(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
        doc = json.dumps({"payload": p, "protected": h, "signature": sig,
                          "header": {"alg": algs.ES256}})

        ks = _tpu_keyset([serialize_public_key(pub, kid="c0")])
        single = ks.verify_signature(doc)
        assert single["iss"] == "https://example.com/"
        batch = ks.verify_batch([doc])
        assert batch[0] == single
        raw = ks.verify_batch_raw([doc])
        assert raw[0] == json.dumps(claims).encode()
        # prep returns a ready ParsedJWS for it, not an error
        prepped = prep.prepare_batch([doc])
        assert not isinstance(prepped[0], Exception)
        assert prepped[0].alg == algs.ES256


# ---------------------------------------------------------------------------
# x5c JWKs
# ---------------------------------------------------------------------------

@needs_crypto
class TestX5CKeys:
    @pytest.mark.parametrize("alg", [algs.RS256, algs.ES256, algs.EdDSA])
    def test_cert_only_jwk_parses_and_verifies(self, alg):
        priv, pub = captest.generate_keys(alg)
        jwk_dict = captest.x5c_jwk(priv, pub, kid="x1")
        # the chain really is the only key material
        fields = ("n", "e") if alg == algs.RS256 else ("x", "y")
        assert not any(f in jwk_dict for f in fields)
        jwk = parse_jwk(jwk_dict)
        tok = captest.sign_jwt(priv, alg, captest.default_claims(), kid="x1")
        claims = StaticKeySet([jwk.key]).verify_signature(tok)
        assert claims["iss"] == "https://example.com/"

    def test_cert_only_jwk_through_tpu_batch(self):
        priv, pub = captest.generate_keys(algs.ES256)
        ks = _tpu_keyset([captest.x5c_jwk(priv, pub, kid="x1")])
        tok = captest.sign_jwt(priv, algs.ES256, captest.default_claims(),
                               kid="x1")
        tampered = tok[:-6] + ("AAAAAA" if not tok.endswith("AAAAAA")
                               else "BBBBBB")
        res = ks.verify_batch([tok, tampered])
        assert not isinstance(res[0], Exception)
        assert isinstance(res[1], InvalidSignatureError)

    def test_params_and_matching_x5c(self):
        priv, pub = captest.generate_keys(algs.ES256)
        jwk = parse_jwk(captest.x5c_jwk(priv, pub, kid="x1",
                                        include_params=True))
        assert jwk.kid == "x1"

    def test_params_mismatching_x5c_rejected(self):
        priv, pub = captest.generate_keys(algs.ES256)
        _, other_pub = captest.generate_keys(algs.ES256)
        bad = captest.x5c_jwk(priv, pub, include_params=True)
        # swap in a different key's parameters
        bad.update({k: v for k, v in serialize_public_key(other_pub).items()
                    if k in ("x", "y")})
        with pytest.raises(InvalidJWKSError, match="match"):
            parse_jwk(bad)

    def test_kty_cert_type_mismatch_rejected(self):
        priv, pub = captest.generate_keys(algs.ES256)
        bad = captest.x5c_jwk(priv, pub)
        bad["kty"] = "RSA"
        with pytest.raises(InvalidJWKSError):
            parse_jwk(bad)

    def test_malformed_params_with_x5c_rejected(self):
        # malformed n/e (or x/y) must reject even when a valid chain is
        # present — go-jose fails to unmarshal such a key.
        priv, pub = captest.generate_keys(algs.ES256)
        bad = captest.x5c_jwk(priv, pub)
        bad.update({"x": 123, "y": 456})
        with pytest.raises(InvalidJWKSError):
            parse_jwk(bad)
        rpriv, rpub = captest.generate_keys(algs.RS256)
        bad = captest.x5c_jwk(rpriv, rpub)
        bad["n"] = 17
        with pytest.raises(InvalidJWKSError):
            parse_jwk(bad)

    def test_bad_x5c_rejected(self):
        priv, pub = captest.generate_keys(algs.ES256)
        for bad_chain in ([], ["!!!"], "not-a-list", [42]):
            bad = captest.x5c_jwk(priv, pub)
            bad["x5c"] = bad_chain
            with pytest.raises(InvalidJWKSError):
                parse_jwk(bad)

    def test_x5c_jwks_over_http(self):
        priv, pub = captest.generate_keys(algs.ES256)
        from cap_tpu.jwt.keyset import JSONWebKeySet

        state = {"keys": [captest.x5c_jwk(priv, pub, kid="x1")]}
        with captest.jwks_test_server(state) as (url, _srv):
            ks = JSONWebKeySet(url)
            tok = captest.sign_jwt(priv, algs.ES256,
                                   captest.default_claims(), kid="x1")
            assert ks.verify_signature(tok)["iss"] == "https://example.com/"


# ---------------------------------------------------------------------------
# Four-surface differential
# ---------------------------------------------------------------------------

@needs_crypto
def test_four_surface_verdict_parity(es_pair, good_token):
    """One mixed vector batch; accept/reject must agree on every
    surface (CPU oracle / TPU batch / native prep / serve worker)."""
    priv, pub = es_pair
    crit_tok = captest.sign_jwt(priv, algs.ES256, captest.default_claims(),
                                kid="c0", extra_headers={"crit": ["x"]})
    tampered = good_token[:-6] + (
        "AAAAAA" if not good_token.endswith("AAAAAA") else "BBBBBB")
    vectors = [
        good_token,
        crit_tok,
        captest.to_json_form(good_token),
        captest.to_json_form(good_token, flattened=False),
        captest.to_json_form(tampered),
        tampered,
        "definitely-not-a-jws",
    ]
    want_accept = [True, False, True, True, False, False, False]

    oracle = StaticKeySet([pub]).verify_batch(vectors)
    tpu = _tpu_keyset(
        [serialize_public_key(pub, kid="c0")]).verify_batch(vectors)
    prepped = prep.prepare_batch(vectors)

    for i, want in enumerate(want_accept):
        assert (not isinstance(oracle[i], Exception)) == want, \
            f"oracle vector {i}"
        assert (not isinstance(tpu[i], Exception)) == want, \
            f"tpu vector {i}"
        if want:
            assert oracle[i] == tpu[i], f"claims mismatch vector {i}"
            assert not isinstance(prepped[i], Exception)
        if isinstance(oracle[i], Exception):
            # error CLASS parity between oracle and device paths
            assert type(oracle[i]) is type(tpu[i]), f"class vector {i}"

    # serve worker: same batch over the wire
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.serve.client import RemoteVerifyError, VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    ks = TPUBatchKeySet(parse_jwks(
        {"keys": [serialize_public_key(pub, kid="c0")]}))
    w = VerifyWorker(ks, target_batch=8, max_wait_ms=5.0)
    try:
        host, port = w.address
        with VerifyClient(host, port, timeout=600.0) as c:
            res = c.verify_batch(vectors)
    finally:
        w.close()
    for i, want in enumerate(want_accept):
        if want:
            assert not isinstance(res[i], RemoteVerifyError), f"serve {i}"
            assert res[i]["iss"] == "https://example.com/"
        else:
            assert isinstance(res[i], RemoteVerifyError), f"serve {i}"


# ---------------------------------------------------------------------------
# CVB1 wire golden vectors (byte-identical across protocol changes)
# ---------------------------------------------------------------------------

_TESTDATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "clients", "go", "captpu", "testdata")


def _golden(name: str) -> bytes:
    with open(os.path.join(_TESTDATA, name), "rb") as f:
        return f.read()


class _CaptureSock:
    def __init__(self):
        self.chunks = []

    def sendall(self, b):
        self.chunks.append(bytes(b))

    def value(self) -> bytes:
        return b"".join(self.chunks)


class TestWireGolden:
    """The trace-context field (frame types 9/10) is ADDITIVE: every
    plain frame type 1-8 must serialize byte-identically to the
    committed golden vectors, forever. Regenerates each frame with
    the exact inputs tools/gen_go_golden.py used and compares bytes
    — a wire change that touches the old types fails here before it
    can break a deployed Go/native client."""

    TOKENS = ["eyJhbGciOiJSUzI1NiJ9.e30.c2ln", "a.b.c", ""]
    TRACE_ID = "00112233aabbccdd"

    def _results(self):
        return [
            {"iss": "https://example.com/", "aud": ["client-id"],
             "n": 3},
            InvalidSignatureError("no known key successfully "
                                  "validated the token signature"),
            {"sub": "alice", "unicode": "ü†✓"},
        ]

    def _regen(self):
        from cap_tpu.serve import protocol

        out = {}
        for name, send in (
            ("request.bin",
             lambda s: protocol.send_request(s, self.TOKENS)),
            ("response.bin",
             lambda s: protocol.send_response(s, self._results())),
            ("ping.bin", protocol.send_ping),
            ("pong.bin", protocol.send_pong),
            ("stats_request.bin", protocol.send_stats_request),
            ("stats_response.bin",
             lambda s: protocol.send_stats_response(
                 s, {"pid": 7, "queued_tokens": 0,
                     "inflight_batches": 1})),
            ("request_crc.bin",
             lambda s: protocol.send_request(s, self.TOKENS, crc=True)),
            ("response_crc.bin",
             lambda s: protocol.send_response(s, self._results(),
                                              crc=True)),
        ):
            sock = _CaptureSock()
            send(sock)
            out[name] = sock.value()
        return out

    def test_plain_frames_1_to_8_byte_identical(self):
        for name, blob in self._regen().items():
            assert blob == _golden(name), \
                f"{name} drifted from the committed golden bytes"

    def test_trace_frames_match_golden(self):
        from cap_tpu.serve import protocol

        s = _CaptureSock()
        protocol.send_request(s, self.TOKENS, trace=self.TRACE_ID)
        assert s.value() == _golden("request_trace.bin")
        s = _CaptureSock()
        protocol.send_response(s, self._results(), trace=self.TRACE_ID)
        assert s.value() == _golden("response_trace.bin")

    def test_trace_frames_parse_back(self):
        import io

        from cap_tpu.serve import protocol

        for name, want_type in (
                ("request_trace.bin", protocol.T_VERIFY_REQ_TRACE),
                ("response_trace.bin", protocol.T_VERIFY_RESP_TRACE)):
            buf = io.BytesIO(_golden(name))
            ftype, entries, trace = protocol._parse_frame(buf.read)
            assert ftype == want_type
            assert trace == self.TRACE_ID
            assert len(entries) == 3
            assert buf.read() == b""       # trailer fully consumed
        # request entries round-trip to the original tokens
        buf = io.BytesIO(_golden("request_trace.bin"))
        _, entries, _ = protocol._parse_frame(buf.read)
        assert entries == self.TOKENS

    def test_trace_frame_structure_is_additive(self):
        """Type 9 == type 7 with the ctx field spliced in after the
        header (and a recomputed trailer): byte-level proof the
        change is additive."""
        plain = _golden("request_crc.bin")
        traced = _golden("request_trace.bin")
        hdr = 9                                # <IBI
        ctx = bytes([len(self.TRACE_ID)]) + self.TRACE_ID.encode()
        # same body; type byte and trailer differ
        assert traced[hdr + len(ctx):-4] == plain[hdr:-4]
        assert traced[hdr:hdr + len(ctx)] == ctx
        assert traced[4] == 9 and plain[4] == 7

    def test_corrupt_trace_frame_detected(self):
        import io

        from cap_tpu.serve import protocol

        blob = bytearray(_golden("response_trace.bin"))
        blob[14] ^= 0x01                       # a status-ish byte
        with pytest.raises(protocol.ProtocolError):
            protocol._parse_frame(io.BytesIO(bytes(blob)).read)

    def test_meta_pins_trace_id(self):
        with open(os.path.join(_TESTDATA, "meta.json")) as f:
            meta = json.load(f)
        assert meta["trace_id"] == self.TRACE_ID
        assert meta["tokens"] == self.TOKENS


# ---------------------------------------------------------------------------
# Adversarial signature encodings (pinned golden vectors)
# ---------------------------------------------------------------------------

_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "clients", "go", "captpu", "testdata", "sig_conformance.json")


@pytest.fixture(scope="module")
def sig_golden():
    with open(_GOLDEN_PATH) as f:
        return json.load(f)


def _split_vector(vec):
    """(signing_input, sig_bytes, digest, want_accept) for one vector."""
    from cap_tpu.jwt.jose import b64url_decode

    h, p, s = vec["token"].split(".")
    signing_input = (h + "." + p).encode()
    return (signing_input, b64url_decode(s),
            hashlib.sha256(signing_input).digest(),
            vec["verdict"] == "accept")


class TestSigEncodingGolden:
    """The golden vectors' verdicts pin go-jose → Go stdlib semantics;
    this class is dependency-free down to the device engines (pinned
    tokens, host-integer keys — no ``cryptography`` needed), so the
    encoding rules are enforced in EVERY environment. The four-surface
    differential below re-pins them through the full jwt/serve stack
    where the crypto fixtures exist."""

    def test_vector_inventory(self, sig_golden):
        names = [v["name"] for v in sig_golden["vectors"]]
        assert len(names) == len(set(names))
        # The VERDICT r5 checklist is present.
        for required in ("es256-high-s", "es256-der-encoded",
                         "es256-der-trailing-garbage",
                         "es256-sig-63-bytes", "es256-sig-65-bytes",
                         "rs256-leading-zero-stripped"):
            assert required in names, required
        # Each family carries its accept control.
        verdicts = {v["name"]: v["verdict"] for v in sig_golden["vectors"]}
        assert verdicts["es256-valid"] == "accept"
        assert verdicts["rs256-valid"] == "accept"
        assert verdicts["rs256-leading-zero-full-width"] == "accept"

    def test_all_tokens_parse_as_jws(self, sig_golden):
        # Structurally the vectors are well-formed compact JWS: the
        # reject must come from the SIGNATURE layer, never the parser
        # — with ONE exception: an empty signature segment is "token
        # must be signed" at parse time (go-jose ParseSigned parity),
        # which is equally a reject.
        from cap_tpu.errors import TokenNotSignedError

        out = prep.prepare_batch([v["token"] for v in
                                  sig_golden["vectors"]])
        for v, r in zip(sig_golden["vectors"], out):
            if v["name"] == "es256-sig-empty":
                assert isinstance(r, TokenNotSignedError)
            else:
                assert not isinstance(r, Exception), \
                    f"{v['name']} failed parse: {r!r}"

    def test_ec_engine_matches_pinned_verdicts(self, sig_golden):
        import numpy as np

        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import ec as tpuec

        jwk = next(k for k in sig_golden["keys"]["keys"]
                   if k["kty"] == "EC")
        key = tpuec.HostECPublicKey(
            "P-256",
            int.from_bytes(b64url_decode(jwk["x"]), "big"),
            int.from_bytes(b64url_decode(jwk["y"]), "big"))
        table = tpuec.ECKeyTable("P-256", [key])
        vecs = [v for v in sig_golden["vectors"] if v["alg"] == "ES256"]
        parts = [_split_vector(v) for v in vecs]
        got = tpuec.verify_ecdsa_batch(
            table, [sig for _, sig, _, _ in parts],
            [dig for _, _, dig, _ in parts],
            np.zeros(len(parts), np.int64))
        for v, (_, sig, dig, want), ok in zip(vecs, parts, got):
            assert bool(ok) == want, \
                f"device engine verdict for {v['name']}: {bool(ok)}"
            if len(sig) == 64:
                # host-integer oracle agrees on every full-width sig
                assert tpuec._py_verify_one(table, 0, sig, dig) == want, \
                    f"host oracle verdict for {v['name']}"
            else:
                # wrong-width sigs are rejected by the length gate on
                # every surface (RFC 7518 §3.4 fixed width)
                assert not want

    def test_rsa_engine_matches_pinned_verdicts(self, sig_golden):
        import numpy as np

        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import rsa as tpursa

        jwk = next(k for k in sig_golden["keys"]["keys"]
                   if k["kty"] == "RSA")
        n = int.from_bytes(b64url_decode(jwk["n"]), "big")
        e = int.from_bytes(b64url_decode(jwk["e"]), "big")
        table = tpursa.RSAKeyTable([(n, e)])
        vecs = [v for v in sig_golden["vectors"] if v["alg"] == "RS256"]
        parts = [_split_vector(v) for v in vecs]
        got = tpursa.verify_pkcs1v15_batch(
            table, [sig for _, sig, _, _ in parts],
            [dig for _, _, dig, _ in parts], "sha256",
            np.zeros(len(parts), np.int64))
        for v, (_, _, _, want), ok in zip(vecs, parts, got):
            assert bool(ok) == want, \
                f"device engine verdict for {v['name']}: {bool(ok)}"


class TestMLDSAEncodingGolden:
    """Adversarial ML-DSA encoding vectors (pinned in
    sig_conformance.json): truncated/extended signatures, a
    bit-flipped c̃, an out-of-range z coefficient, hint-count
    overflow, nonzero hint padding. Dependency-free like the ES*/RS*
    engine suite — AND swept across all four verify surfaces right
    here, because the AKP/ML-DSA stack never needs ``cryptography``:
    verdicts and decision reason classes (``bad_signature`` vs
    ``malformed``) must agree everywhere."""

    @pytest.fixture(scope="class")
    def pq_vectors(self, sig_golden):
        vecs = [v for v in sig_golden["vectors"]
                if v["alg"].startswith("ML-DSA")]
        assert vecs, "ML-DSA vectors missing from sig_conformance.json"
        return vecs

    @pytest.fixture(scope="class")
    def pq_jwks(self, sig_golden):
        from cap_tpu.jwt.jwk import parse_jwk

        return [parse_jwk(k) for k in sig_golden["keys"]["keys"]
                if k.get("kty") == "AKP"]

    def test_vector_inventory(self, pq_vectors):
        names = {v["name"] for v in pq_vectors}
        for required in ("mldsa44-valid", "mldsa44-sig-truncated",
                         "mldsa44-ctilde-bitflip",
                         "mldsa44-z-out-of-range",
                         "mldsa44-hint-count-overflow",
                         "mldsa44-hint-padding-nonzero",
                         "mldsa44-sig-extended"):
            assert required in names, required
        verdicts = {v["name"]: v["verdict"] for v in pq_vectors}
        assert verdicts["mldsa44-valid"] == "accept"

    def test_oracle_matches_pinned_verdicts(self, pq_vectors, pq_jwks):
        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import mldsa

        key = pq_jwks[0].key
        for v in pq_vectors:
            h, p, s = v["token"].split(".")
            got = mldsa.py_verify(key, b64url_decode(s),
                                  (h + "." + p).encode())
            assert got == (v["verdict"] == "accept"), v["name"]

    def test_engine_matches_pinned_verdicts(self, pq_vectors, pq_jwks):
        import numpy as np

        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import mldsa

        key = pq_jwks[0].key
        table = mldsa.MLDSAKeyTable(key.parameter_set, [key])
        sigs, msgs = [], []
        for v in pq_vectors:
            h, p, s = v["token"].split(".")
            sigs.append(b64url_decode(s))
            msgs.append((h + "." + p).encode())
        got = mldsa.verify_mldsa_batch(
            table, sigs, msgs, np.zeros(len(sigs), np.int32))
        for v, ok in zip(pq_vectors, got):
            assert bool(ok) == (v["verdict"] == "accept"), v["name"]

    def test_reject_reason_class_parity_four_surfaces(self, pq_vectors,
                                                      pq_jwks):
        from cap_tpu.fleet import FleetClient
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
        from cap_tpu.obs import decision as obs_decision
        from cap_tpu.serve.client import VerifyClient
        from cap_tpu.serve.worker import VerifyWorker

        # keyset.py's StaticKeySet is importable without cryptography
        # (lazy exports) — the module-top alias is crypto-gated, so
        # import it directly for this crypto-free sweep.
        from cap_tpu.jwt.keyset import StaticKeySet as _SKS

        tokens = [v["token"] for v in pq_vectors]
        out = {}
        out["oracle"] = _SKS([j.key for j in pq_jwks]).verify_batch(
            tokens)
        ks = TPUBatchKeySet(pq_jwks)
        out["tpu"] = ks.verify_batch(tokens)
        out["tpu_objects"] = ks._verify_batch_objects(tokens)
        w = VerifyWorker(TPUBatchKeySet(pq_jwks), target_batch=8,
                         max_wait_ms=5.0)
        try:
            host, port = w.address
            with VerifyClient(host, port, timeout=600.0) as c:
                out["serve"] = c.verify_batch(tokens)
            out["router"] = FleetClient([(host, port)],
                                        rr_seed=0).verify_batch(tokens)
        finally:
            w.close()

        for i, v in enumerate(pq_vectors):
            per_surface = {}
            for surf, results in out.items():
                r = results[i]
                if isinstance(r, Exception):
                    per_surface[surf] = ("reject",
                                         obs_decision.classify(r))
                else:
                    per_surface[surf] = ("accept", None)
            assert len(set(per_surface.values())) == 1, \
                f"{v['name']}: {per_surface}"
            assert (per_surface["tpu"][0] == "accept") == \
                (v["verdict"] == "accept"), v["name"]


class TestSLHDSAEncodingGolden:
    """Adversarial SLH-DSA encoding vectors (pinned in
    sig_conformance.json): truncated/extended signatures + trailing
    garbage (the scheme's only structural gate is length), a
    bit-flipped randomizer R, a corrupted FORS auth path (the
    out-of-range-index analog — FORS indices are digest-derived,
    never encoded), and a corrupted hypertree auth node.
    Dependency-free and swept across all four verify surfaces with
    reason-class parity, like the ML-DSA suite above."""

    @pytest.fixture(scope="class")
    def slh_vectors(self, sig_golden):
        vecs = [v for v in sig_golden["vectors"]
                if v["alg"].startswith("SLH-DSA")]
        assert vecs, "SLH-DSA vectors missing from sig_conformance.json"
        return vecs

    @pytest.fixture(scope="class")
    def slh_jwks(self, sig_golden):
        from cap_tpu.jwt.jwk import parse_jwk

        return [parse_jwk(k) for k in sig_golden["keys"]["keys"]
                if k.get("alg", "").startswith("SLH-DSA")]

    def test_vector_inventory(self, slh_vectors):
        names = {v["name"] for v in slh_vectors}
        for required in ("slhdsa128f-valid", "slhdsa128f-sig-truncated",
                         "slhdsa128f-sig-extended",
                         "slhdsa128f-trailing-garbage",
                         "slhdsa128f-r-bitflip",
                         "slhdsa128f-fors-path-corrupt",
                         "slhdsa128f-ht-auth-corrupt"):
            assert required in names, required
        verdicts = {v["name"]: v["verdict"] for v in slh_vectors}
        assert verdicts["slhdsa128f-valid"] == "accept"

    def test_classical_and_mldsa_entries_untouched(self, sig_golden):
        """The append was additive: every pre-r17 vector family is
        still present under its pinned name (byte-stability of the
        existing entries is covered by the generator's determinism;
        this guards against an accidental re-keying)."""
        names = {v["name"] for v in sig_golden["vectors"]}
        for required in ("es256-valid", "es256-high-s", "rs256-valid",
                         "rs256-leading-zero-stripped",
                         "mldsa44-valid", "mldsa44-ctilde-bitflip"):
            assert required in names, required

    def test_oracle_matches_pinned_verdicts(self, slh_vectors,
                                            slh_jwks):
        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import slhdsa

        key = slh_jwks[0].key
        for v in slh_vectors:
            h, p, s = v["token"].split(".")
            got = slhdsa.py_verify(key, b64url_decode(s),
                                   (h + "." + p).encode())
            assert got == (v["verdict"] == "accept"), v["name"]

    def test_engine_matches_pinned_verdicts(self, slh_vectors,
                                            slh_jwks):
        import numpy as np

        from cap_tpu.jwt.jose import b64url_decode
        from cap_tpu.tpu import slhdsa

        key = slh_jwks[0].key
        table = slhdsa.SLHDSAKeyTable(key.parameter_set, [key])
        sigs, msgs = [], []
        for v in slh_vectors:
            h, p, s = v["token"].split(".")
            sigs.append(b64url_decode(s))
            msgs.append((h + "." + p).encode())
        got = slhdsa.verify_slhdsa_batch(
            table, sigs, msgs, np.zeros(len(sigs), np.int32))
        for v, ok in zip(slh_vectors, got):
            assert bool(ok) == (v["verdict"] == "accept"), v["name"]

    def test_reject_reason_class_parity_four_surfaces(self,
                                                      slh_vectors,
                                                      slh_jwks):
        from cap_tpu.fleet import FleetClient
        from cap_tpu.jwt.keyset import StaticKeySet as _SKS
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
        from cap_tpu.obs import decision as obs_decision
        from cap_tpu.serve.client import VerifyClient
        from cap_tpu.serve.worker import VerifyWorker

        tokens = [v["token"] for v in slh_vectors]
        out = {}
        out["oracle"] = _SKS([j.key for j in slh_jwks]).verify_batch(
            tokens)
        ks = TPUBatchKeySet(slh_jwks)
        out["tpu"] = ks.verify_batch(tokens)
        out["tpu_objects"] = ks._verify_batch_objects(tokens)
        w = VerifyWorker(TPUBatchKeySet(slh_jwks), target_batch=8,
                         max_wait_ms=5.0)
        try:
            host, port = w.address
            with VerifyClient(host, port, timeout=600.0) as c:
                out["serve"] = c.verify_batch(tokens)
            out["router"] = FleetClient([(host, port)],
                                        rr_seed=0).verify_batch(tokens)
        finally:
            w.close()

        for i, v in enumerate(slh_vectors):
            per_surface = {}
            for surf, results in out.items():
                r = results[i]
                if isinstance(r, Exception):
                    per_surface[surf] = ("reject",
                                         obs_decision.classify(r))
                else:
                    per_surface[surf] = ("accept", None)
            assert len(set(per_surface.values())) == 1, \
                f"{v['name']}: {per_surface}"
            assert (per_surface["tpu"][0] == "accept") == \
                (v["verdict"] == "accept"), v["name"]


@needs_crypto
def test_sig_encoding_four_surface_parity(sig_golden):
    """Golden vectors through the full stack: CPU oracle, TPU batch,
    native prep, serve worker — every verdict pinned."""
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.serve.client import RemoteVerifyError, VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    jwks = parse_jwks(sig_golden["keys"])
    tokens = [v["token"] for v in sig_golden["vectors"]]
    wants = [v["verdict"] == "accept" for v in sig_golden["vectors"]]

    oracle = StaticKeySet([j.key for j in jwks]).verify_batch(tokens)
    tpu = TPUBatchKeySet(jwks).verify_batch(tokens)
    for v, o, t, want in zip(sig_golden["vectors"], oracle, tpu, wants):
        assert (not isinstance(o, Exception)) == want, \
            f"oracle {v['name']}"
        assert (not isinstance(t, Exception)) == want, f"tpu {v['name']}"
        if want:
            assert o == t, f"claims mismatch {v['name']}"

    w = VerifyWorker(TPUBatchKeySet(jwks), target_batch=16,
                     max_wait_ms=5.0)
    try:
        host, port = w.address
        with VerifyClient(host, port, timeout=600.0) as c:
            res = c.verify_batch(tokens)
    finally:
        w.close()
    for v, r, want in zip(sig_golden["vectors"], res, wants):
        assert (not isinstance(r, RemoteVerifyError)) == want, \
            f"serve {v['name']}"


# ---------------------------------------------------------------------------
# decision-record reason parity: the conformance vectors through the
# decision counters on all four surfaces (cap_tpu.obs.decision)
# ---------------------------------------------------------------------------

# Vector names loaded at collection time (static pinned file, no
# crypto needed to READ it) so the sweep is genuinely parameterized.
with open(_GOLDEN_PATH) as _f:
    _SIG_VECTOR_NAMES = [v["name"] for v in json.load(_f)["vectors"]]


@pytest.fixture(scope="module")
def decision_parity(sig_golden):
    """Run the sig-conformance vectors through every surface, each
    under its own recorder; returns per-surface results + counters."""
    if not _HAVE_CRYPTO:
        pytest.skip("cryptography package not installed")
    from cap_tpu import telemetry
    from cap_tpu.fleet import FleetClient
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    from cap_tpu.serve.client import VerifyClient
    from cap_tpu.serve.worker import VerifyWorker

    jwks = parse_jwks(sig_golden["keys"])
    tokens = [v["token"] for v in sig_golden["vectors"]]
    out = {}
    counters = {}

    with telemetry.recording() as rec:
        out["oracle"] = StaticKeySet(
            [j.key for j in jwks]).verify_batch(tokens)
        counters["oracle"] = rec.counters()
    with telemetry.recording() as rec:
        out["tpu"] = TPUBatchKeySet(jwks).verify_batch(tokens)
        counters["tpu"] = rec.counters()

    w = VerifyWorker(TPUBatchKeySet(jwks), target_batch=16,
                     max_wait_ms=5.0)
    try:
        host, port = w.address
        with telemetry.recording() as rec:
            with VerifyClient(host, port, timeout=600.0) as c:
                out["serve"] = c.verify_batch(tokens)
            serve_counters = rec.counters()
        # The worker records the SERVE surface in-process; the client
        # side of this in-process test shares the recorder, so the
        # serve counters were captured above.
        counters["serve"] = serve_counters
        with telemetry.recording() as rec:
            cl = FleetClient([(host, port)], rr_seed=0)
            out["router"] = cl.verify_batch(tokens)
            counters["router"] = rec.counters()
    finally:
        w.close()
    return {"out": out, "counters": counters}


@needs_crypto
@pytest.mark.parametrize("vec_name", _SIG_VECTOR_NAMES)
def test_decision_reason_parity_four_surfaces(decision_parity,
                                              sig_golden, vec_name):
    """Satellite pin: each conformance vector increments the SAME
    decision verdict + rejection-reason class on the CPU oracle, the
    TPU batch engine, the serve worker, and the fleet router."""
    from cap_tpu.obs import decision as obs_decision

    i = next(idx for idx, v in enumerate(sig_golden["vectors"])
             if v["name"] == vec_name)
    want_accept = sig_golden["vectors"][i]["verdict"] == "accept"
    verdicts = {}
    for surface, results in decision_parity["out"].items():
        r = results[i]
        if isinstance(r, Exception):
            verdicts[surface] = ("reject", obs_decision.classify(r))
        else:
            verdicts[surface] = ("accept", None)
    assert len(set(verdicts.values())) == 1, \
        f"{vec_name}: surfaces disagree: {verdicts}"
    assert (verdicts["oracle"][0] == "accept") == want_accept


@needs_crypto
def test_decision_counters_swept_on_all_surfaces(decision_parity,
                                                 sig_golden):
    """The sweep actually flowed through the decision COUNTERS on
    every surface (accept + reject both nonzero), and every surface's
    reject-reason rollup is identical."""
    from cap_tpu.obs import decision as obs_decision

    n_accept = sum(1 for v in sig_golden["vectors"]
                   if v["verdict"] == "accept")
    n_reject = len(sig_golden["vectors"]) - n_accept
    rollups = {}
    for surface, counters in decision_parity["counters"].items():
        rollup = obs_decision.surface_totals(counters).get(surface)
        assert rollup is not None, f"no decision counters on {surface}"
        assert rollup["accept"] == n_accept, (surface, rollup)
        assert rollup["reject"] == n_reject, (surface, rollup)
        rollups[surface] = tuple(sorted(
            (k, v) for k, v in rollup.items()
            if k.startswith("reject.")))
    assert len(set(rollups.values())) == 1, rollups


# ---------------------------------------------------------------------------
# keyplane KEYS frames (types 11/12): additive golden vectors
# ---------------------------------------------------------------------------

class TestKeysWireGolden:
    """The KEYS frame pair is ADDITIVE exactly like the traced pair:
    its own golden files, while types 1-10 stay pinned byte-identical
    by TestWireGolden above. Fixture values mirror
    tools/gen_go_golden.py exactly."""

    KEYS_EPOCH = 3
    KEYS_JWKS = {"keys": [
        {"kty": "RSA", "kid": "rot-2024-a", "n": "AQAB", "e": "AQAB"},
        {"kty": "EC", "kid": "rot-2024-b", "crv": "P-256",
         "x": "AQAB", "y": "AQAB"},
    ]}

    def test_keys_frames_match_golden(self):
        from cap_tpu.serve import protocol

        s = _CaptureSock()
        protocol.send_keys_push(s, self.KEYS_JWKS, self.KEYS_EPOCH)
        assert s.value() == _golden("keys_push.bin"), \
            "keys_push.bin drifted from the committed golden bytes"
        s = _CaptureSock()
        protocol.send_keys_ack(s, epoch=self.KEYS_EPOCH)
        assert s.value() == _golden("keys_ack.bin"), \
            "keys_ack.bin drifted from the committed golden bytes"

    def test_keys_frames_parse_back(self):
        import io

        from cap_tpu.serve import protocol

        buf = io.BytesIO(_golden("keys_push.bin"))
        ftype, entries, trace = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_KEYS_PUSH and trace is None
        assert buf.read() == b""           # trailer fully consumed
        doc = json.loads(entries[0])
        assert doc["epoch"] == self.KEYS_EPOCH
        assert doc["jwks"]["keys"][0]["kid"] == "rot-2024-a"

        buf = io.BytesIO(_golden("keys_ack.bin"))
        ftype, entries, _ = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_KEYS_ACK
        assert entries[0][0] == 0
        assert json.loads(entries[0][1]) == {"epoch": self.KEYS_EPOCH}

    def test_corrupt_keys_frame_detected(self):
        import io

        from cap_tpu.serve import protocol

        blob = bytearray(_golden("keys_push.bin"))
        blob[20] ^= 0x01
        with pytest.raises(protocol.ProtocolError):
            protocol._parse_frame(io.BytesIO(bytes(blob)).read)

    def test_meta_pins_keys_fixture(self):
        with open(os.path.join(_TESTDATA, "meta.json")) as f:
            meta = json.load(f)
        assert meta["keys_epoch"] == self.KEYS_EPOCH
        assert meta["keys_jwks"] == self.KEYS_JWKS


# ---------------------------------------------------------------------------
# verdict-cache peer-fill frames (types 13/14): additive golden vectors
# ---------------------------------------------------------------------------

class TestPeerFillWireGolden:
    """The peer-fill frame pair is ADDITIVE exactly like the KEYS
    pair: its own golden files (``peer_fill.bin`` / ``peer_ack.bin``),
    while frames 1-12 stay pinned byte-identical by TestWireGolden and
    TestKeysWireGolden above. Fixture values mirror
    tools/gen_go_golden.py exactly."""

    PEER_FILL_DOC = {
        "op": "import",
        "epoch": 3,
        "entries": [[
            "00112233445566778899aabbccddeeff",
            "eyJzdWIiOiJnb2xkZW4ifQ==",
            1700000000.0,
            4102444800.0,
            4102444800.0,
        ]],
    }
    PEER_ACK_DOC = {"imported": 1}

    def test_peer_frames_match_golden(self):
        from cap_tpu.serve import protocol

        s = _CaptureSock()
        protocol.send_peer_fill(s, self.PEER_FILL_DOC)
        assert s.value() == _golden("peer_fill.bin"), \
            "peer_fill.bin drifted from the committed golden bytes"
        assert protocol.encode_peer_ack(self.PEER_ACK_DOC) \
            == _golden("peer_ack.bin"), \
            "peer_ack.bin drifted from the committed golden bytes"

    def test_peer_frames_parse_back(self):
        import io

        from cap_tpu.serve import protocol

        buf = io.BytesIO(_golden("peer_fill.bin"))
        ftype, entries, trace = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_PEER_FILL and trace is None
        assert buf.read() == b""           # trailer fully consumed
        doc = json.loads(entries[0])
        assert doc["op"] == "import" and doc["epoch"] == 3
        assert doc["entries"][0][0] == \
            "00112233445566778899aabbccddeeff"

        buf = io.BytesIO(_golden("peer_ack.bin"))
        ftype, entries, _ = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_PEER_ACK
        assert entries[0][0] == 0
        assert json.loads(entries[0][1]) == self.PEER_ACK_DOC

    def test_corrupt_peer_frame_detected(self):
        import io

        from cap_tpu.serve import protocol

        blob = bytearray(_golden("peer_fill.bin"))
        blob[20] ^= 0x01
        with pytest.raises(protocol.ProtocolError):
            protocol._parse_frame(io.BytesIO(bytes(blob)).read)

    def test_frames_1_to_12_still_byte_identical(self):
        """The additive contract, explicitly: regenerating every
        pre-peer-fill golden file yields the committed bytes — the new
        pair changed NOTHING upstream of it."""
        from cap_tpu.serve import protocol

        for name in ("request.bin", "response.bin", "ping.bin",
                     "pong.bin", "stats_request.bin",
                     "stats_response.bin", "request_crc.bin",
                     "response_crc.bin", "request_trace.bin",
                     "response_trace.bin", "keys_push.bin",
                     "keys_ack.bin"):
            assert _golden(name), f"{name} missing"
        s = _CaptureSock()
        protocol.send_keys_push(s, TestKeysWireGolden.KEYS_JWKS,
                                TestKeysWireGolden.KEYS_EPOCH)
        assert s.value() == _golden("keys_push.bin")

    def test_meta_pins_peer_fixture(self):
        with open(os.path.join(_TESTDATA, "meta.json")) as f:
            meta = json.load(f)
        assert meta["peer_fill_doc"] == self.PEER_FILL_DOC
        assert meta["peer_ack_doc"] == self.PEER_ACK_DOC


class TestShmWireGolden:
    """The shm-transport negotiation pair (types 15/16) is ADDITIVE
    exactly like the peer-fill pair: its own golden files
    (``shm_attach.bin`` / ``shm_ack.bin``), while frames 1-14 stay
    pinned byte-identical by the classes above. Fixture values mirror
    tools/gen_go_golden.py exactly."""

    SHM_PATH = "/dev/shm/cap-shm-golden"

    def test_shm_frames_match_golden(self):
        from cap_tpu.serve import protocol

        s = _CaptureSock()
        protocol.send_shm_attach(s, self.SHM_PATH)
        assert s.value() == _golden("shm_attach.bin"), \
            "shm_attach.bin drifted from the committed golden bytes"
        assert protocol.encode_shm_ack() == _golden("shm_ack.bin"), \
            "shm_ack.bin drifted from the committed golden bytes"

    def test_shm_frames_parse_back(self):
        import io

        from cap_tpu.serve import protocol

        buf = io.BytesIO(_golden("shm_attach.bin"))
        ftype, entries, trace = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_SHM_ATTACH and trace is None
        assert buf.read() == b""           # trailer fully consumed
        doc = json.loads(entries[0])
        assert doc == {"op": "attach", "path": self.SHM_PATH,
                       "version": 1}

        buf = io.BytesIO(_golden("shm_ack.bin"))
        ftype, entries, _ = protocol._parse_frame(buf.read)
        assert ftype == protocol.T_SHM_ACK
        assert entries[0][0] == 0
        assert json.loads(entries[0][1]) == {"transport": "shm"}

    def test_native_ack_byte_identical_to_python(self):
        """The native chain builds its OWN ack (serve_native.cpp
        shm_ack_frame); a Python-side client must not be able to tell
        which chain acked — pinned via the shared golden."""
        from cap_tpu.serve import protocol

        assert protocol.encode_shm_ack() == _golden("shm_ack.bin")

    def test_corrupt_shm_frame_detected(self):
        import io

        from cap_tpu.serve import protocol

        blob = bytearray(_golden("shm_attach.bin"))
        blob[15] ^= 0x01
        with pytest.raises(protocol.ProtocolError):
            protocol._parse_frame(io.BytesIO(bytes(blob)).read)

    def test_two_entry_attach_rejected(self):
        import struct
        import zlib

        from cap_tpu.serve import protocol

        body = (struct.pack("<IBI", protocol.MAGIC,
                            protocol.T_SHM_ATTACH, 2)
                + struct.pack("<I", 1) + b"x"
                + struct.pack("<I", 1) + b"y")
        frame = body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
        with pytest.raises(protocol.MalformedFrameError):
            protocol.parse_frame_bytes(frame)

    def test_frames_1_to_14_still_byte_identical(self):
        """The additive contract, explicitly: regenerating the
        peer-fill push yields the committed bytes — the shm pair
        changed NOTHING upstream of it (the classes above cover
        frames 1-12 the same way)."""
        from cap_tpu.serve import protocol

        for name in ("peer_fill.bin", "peer_ack.bin"):
            assert _golden(name), f"{name} missing"
        s = _CaptureSock()
        protocol.send_peer_fill(
            s, TestPeerFillWireGolden.PEER_FILL_DOC)
        assert s.value() == _golden("peer_fill.bin")

    def test_meta_pins_shm_fixture(self):
        with open(os.path.join(_TESTDATA, "meta.json")) as f:
            meta = json.load(f)
        assert meta["shm_path"] == self.SHM_PATH


# ---------------------------------------------------------------------------
# rotation parity: the sig-conformance vectors across an epoch swap
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rotation_parity(sig_golden):
    """The conformance vectors through a CPU ``JSONWebKeySet`` (the
    reference's remote-JWKS behavior) and a ``TPUBatchKeySet`` BEFORE
    and AFTER a keyplane epoch swap (same keys re-kidded, grace window
    on — the realistic rotation where freshly-signed old-kid tokens
    are still in flight)."""
    if not _HAVE_CRYPTO:
        pytest.skip("cryptography package not installed")
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.keyset import JSONWebKeySet
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    body = json.dumps(sig_golden["keys"]).encode()

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/jwks"
    tokens = [v["token"] for v in sig_golden["vectors"]]
    try:
        cpu_ks = JSONWebKeySet(url)
        cpu = []
        for t in tokens:
            try:
                cpu.append(cpu_ks.verify_signature(t))
            except Exception as e:  # noqa: BLE001 - verdict channel
                cpu.append(e)
        jwks = parse_jwks(sig_golden["keys"])
        ks = TPUBatchKeySet(jwks)
        pre = ks.verify_batch(tokens)
        rotated = [JWK(j.key, kid=j.kid + "-r2", alg=j.alg, use=j.use)
                   for j in jwks]
        epoch = ks.swap_keys(rotated, grace_s=300.0)
        post = ks.verify_batch(tokens)
    finally:
        server.shutdown()
    return {"cpu": cpu, "pre": pre, "post": post, "epoch": epoch,
            "keyset": ks}


@needs_crypto
@pytest.mark.parametrize("vec_name", _SIG_VECTOR_NAMES)
def test_rotation_parity_per_vector(rotation_parity, sig_golden,
                                    vec_name):
    """Satellite pin: verdict AND decision-reason class match between
    the CPU JSONWebKeySet and the keyplane-rotated TPUBatchKeySet on
    both sides of the epoch swap."""
    from cap_tpu.obs import decision as obs_decision

    i = next(idx for idx, v in enumerate(sig_golden["vectors"])
             if v["name"] == vec_name)
    want_accept = sig_golden["vectors"][i]["verdict"] == "accept"

    def verdict(r):
        if isinstance(r, Exception):
            return ("reject", obs_decision.classify(r))
        return ("accept", None)

    cpu = verdict(rotation_parity["cpu"][i])
    pre = verdict(rotation_parity["pre"][i])
    post = verdict(rotation_parity["post"][i])
    assert cpu == pre, \
        f"{vec_name}: CPU {cpu} != device pre-swap {pre}"
    assert pre == post, \
        f"{vec_name}: verdict flapped across the epoch swap: " \
        f"{pre} -> {post}"
    assert (cpu[0] == "accept") == want_accept


@needs_crypto
def test_rotation_parity_epoch_advanced(rotation_parity):
    """The sweep really crossed an epoch boundary, and the retired
    kids resolved through the grace window (no verdict depended on a
    stale-kid reject)."""
    assert rotation_parity["epoch"] == 1
    ks = rotation_parity["keyset"]
    assert ks.key_epoch == 1
    assert "sig-es" in ks._tables.kids        # grace retains old kids
    assert "sig-es-r2" in ks._tables.kids
