"""Observability surface: obs HTTP server, capstat, redaction, traces.

Tier-1, dependency-free: stub engines only (no crypto fixtures, no
jax). Covers the exposition endpoints (/metrics Prometheus text,
/snapshot mergeable JSON, /flight recorder), the capstat scraper /
renderer / trace reassembler, and — the enforceable redaction
satellite — a full traced verify (valid + malformed tokens) after
which NO recorded metric name, gauge, span record, flight entry, or
rendered output may contain token material (reference rule:
/root/reference/oidc/config.go:20-31)."""

import json
import time
import urllib.request

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.serve import obs as obs_mod
from cap_tpu.serve.worker import VerifyWorker
from tools import capstat


@pytest.fixture(autouse=True)
def _telemetry_off():
    telemetry.disable()
    yield
    telemetry.disable()


def _get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=5) as r:
        return r.read().decode()


# ---------------------------------------------------------------------------
# prometheus rendering
# ---------------------------------------------------------------------------

def test_render_prometheus_counters_gauges_summaries():
    rec = telemetry.Recorder()
    rec.count("worker.requests", 3)
    rec.gauge("fleet.breakers_open", 1)
    for i in range(10):
        rec.observe("batcher.batch_size", float(64 + i))
    text = obs_mod.render_prometheus(rec.snapshot(),
                                     {"batcher.queued_tokens": 5})
    assert "cap_up 1" in text
    assert "cap_worker_requests_total 3" in text
    assert "cap_fleet_breakers_open 1" in text
    assert "cap_batcher_queued_tokens 5" in text
    assert 'cap_batcher_batch_size{quantile="0.5"}' in text
    assert "cap_batcher_batch_size_count 10" in text
    assert "cap_batcher_batch_size_sum" in text
    # names sanitized for prometheus ('.' is illegal)
    for line in text.splitlines():
        if not line.startswith("#"):
            assert "." not in line.split("{")[0].split(" ")[0]


def test_render_prometheus_empty_snapshot():
    assert "cap_up 1" in obs_mod.render_prometheus({})


# ---------------------------------------------------------------------------
# obs server endpoints
# ---------------------------------------------------------------------------

def test_obs_server_endpoints():
    srv = obs_mod.ObsServer(extra=lambda: {"batcher.queued_tokens": 0})
    try:
        with telemetry.recording() as rec:
            rec.count("worker.requests")
            rec.trace_span("ab12cd34ab12cd34", "batcher.fill", 1.0, 0.5)
            rec.flight("ab12cd34ab12cd34", 0.5)
            assert json.loads(_get(srv.address, "/healthz"))["ok"]
            met = _get(srv.address, "/metrics")
            assert "cap_worker_requests_total 1" in met
            snap = json.loads(_get(srv.address, "/snapshot"))
            assert snap["snapshot"]["counters"]["worker.requests"] == 1
            assert snap["extra"]["batcher.queued_tokens"] == 0
            fl = json.loads(_get(srv.address, "/flight"))
            assert fl["slowest"][0]["trace"] == "ab12cd34ab12cd34"
            assert fl["slowest"][0]["spans"][0]["name"] == "batcher.fill"
        # telemetry off → still serves, just empty
        met = _get(srv.address, "/metrics")
        assert "cap_up 1" in met
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.address, "/nope")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# traced verify through a real worker (in-process), full redaction
# ---------------------------------------------------------------------------

# JWS-shaped tokens: realistic base64url segments so any leak of
# name/label/span/flight content is detectable by substring.
VALID_TOK = ("eyJhbGciOiJSUzI1NiIsImtpZCI6InNlY3JldC1raWQifQ."
             "eyJzdWIiOiJhbGljZUBleGFtcGxlLmNvbSJ9.c2lnbmF0dXJl.ok")
BAD_TOK = ("eyJhbGciOiJub25lIn0."
           "eyJzdWIiOiJtYWxsb3J5In0.Z2FyYmFnZQ")
TRUNCATED_TOK = "eyJhbGciOiJSUzI1NiJ9"


def _leak_fragments():
    frags = {"eyJ"}
    for t in (VALID_TOK, BAD_TOK, TRUNCATED_TOK):
        frags.add(t)
        for seg in t.split("."):
            if len(seg) >= 8:
                frags.add(seg)
    return frags


def test_traced_verify_records_no_token_material():
    """Satellite: drive a FULL traced verify (valid + malformed) and
    assert the redaction rule over EVERYTHING the observability layer
    recorded or can render."""
    worker = VerifyWorker(StubKeySet(), target_batch=4, max_wait_ms=1.0,
                          obs_port=0)
    try:
        with telemetry.recording() as rec:
            cl = FleetClient([worker.address], fallback=StubKeySet(),
                             rr_seed=0)
            with telemetry.trace() as tid:
                out = cl.verify_batch([VALID_TOK, BAD_TOK, TRUNCATED_TOK])
            assert out[0] == {"sub": VALID_TOK}
            assert isinstance(out[1], Exception)
            assert isinstance(out[2], Exception)
            # every surface the layer can expose:
            dumps = [
                json.dumps(rec.counters()),
                json.dumps(rec.gauges()),
                json.dumps(rec.summary()),
                json.dumps(rec.trace_spans()),
                json.dumps(rec.flight_entries()),
                json.dumps(rec.snapshot()),
                json.dumps(cl.snapshot()),
                _get(worker.obs_address, "/metrics"),
                _get(worker.obs_address, "/snapshot"),
                _get(worker.obs_address, "/flight"),
                json.dumps(worker.stats()),
            ]
            for frag in _leak_fragments():
                for i, d in enumerate(dumps):
                    assert frag not in d, \
                        f"token fragment leaked into surface {i}"
            # and the trace DID flow: both sides of the timeline exist
            names = {s["name"] for s in rec.trace_spans(tid)}
            assert telemetry.SPAN_CLIENT_SUBMIT in names
            assert telemetry.SPAN_ROUTER_ATTEMPT in names
            assert telemetry.SPAN_WORKER_DEQUEUE in names
            assert telemetry.SPAN_BATCHER_FILL in names
            assert (telemetry.SPAN_BATCHER_FLUSH in names
                    or telemetry.SPAN_BATCHER_DISPATCH in names)
            flights = [e for e in rec.flight_entries()
                       if e["trace"] == tid]
            assert flights, "traced request missing from flight ring"
    finally:
        worker.close()


# ---------------------------------------------------------------------------
# capstat: scrape, render, reassemble
# ---------------------------------------------------------------------------

def test_capstat_scrape_render_and_reassemble():
    worker = VerifyWorker(StubKeySet(), target_batch=4, max_wait_ms=1.0,
                          obs_port=0)
    try:
        with telemetry.recording() as rec:
            cl = FleetClient([worker.address], fallback=StubKeySet(),
                             rr_seed=0)
            with telemetry.trace() as tid:
                cl.verify_batch(["a.ok", "b.ok"])
            host, port = worker.obs_address
            ep = f"{host}:{port}"
            data = capstat.scrape(ep)
            assert data["snapshot"]["counters"]["worker.requests"] >= 1
            assert capstat.check_required({ep: data}) == []
            rendered = capstat.render_fleet({ep: data}, cl.snapshot())
            assert f"worker {ep}" in rendered
            assert "fleet aggregate" in rendered
            assert "router (client side)" in rendered
            assert "eyJ" not in rendered
            # cross-process reassembly: worker flight + client spans
            spans = capstat.reassemble_trace(
                tid, [data, {"spans": rec.trace_spans()}])
            names = [s["name"] for s in spans]
            assert telemetry.SPAN_CLIENT_SUBMIT in names
            assert telemetry.SPAN_BATCHER_FILL in names
            # ordered by wall-clock start
            assert [s["t0"] for s in spans] == sorted(
                s["t0"] for s in spans)
            timeline = capstat.render_trace(tid, spans)
            assert tid in timeline and "client.submit" in timeline
    finally:
        worker.close()


def test_capstat_check_required_flags_gaps():
    problems = capstat.check_required(
        {"w0": {"extra": {"batcher.queued_tokens": float("nan")}}})
    assert any("NaN" in p for p in problems)
    assert any("missing" in p for p in problems)


def test_observability_doc_pins_span_table():
    """docs/OBSERVABILITY.md's registered span-name table and the
    telemetry.SPAN_* constants are the same set — names cannot drift
    in either direction without failing here."""
    import os

    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "OBSERVABILITY.md")
    with open(doc_path) as f:
        doc = f.read()
    for name in telemetry.SPAN_NAMES:
        assert f"`{name}`" in doc, f"span {name} missing from doc table"
    for const in ("SPAN_CLIENT_SUBMIT", "SPAN_ROUTER_HEDGE",
                  "SPAN_BATCHER_FILL", "SPAN_WORKER_DEQUEUE"):
        assert const in doc
    # the engine prefix is documented too
    assert telemetry.SPAN_ENGINE_PREFIX.rstrip(".") in doc


def test_merge_preserves_fleet_totals():
    a, b = telemetry.Recorder(), telemetry.Recorder()
    a.count("worker.tokens", 10)
    b.count("worker.tokens", 32)
    for r, n in ((a, 100), (b, 50)):
        for i in range(n):
            r.observe("batcher.batch_size", float(i))
    merged = telemetry.merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["counters"]["worker.tokens"] == 42
    s = telemetry.summarize_snapshot(merged)["batcher.batch_size"]
    assert s["count"] == 150
    assert s["max"] == 99.0


# ---------------------------------------------------------------------------
# capstat --watch burn view: per-interval counter deltas
# ---------------------------------------------------------------------------

def test_capstat_counter_deltas_and_respawn_reset():
    """Delta math across scrapes: normal growth subtracts, a worker
    respawn (counter goes BACKWARDS) clamps to the fresh value —
    never a negative rate — and a newly appearing counter counts
    from zero."""
    from tools import capstat

    prev = {"worker.tokens": 1000, "worker.requests": 50,
            "decision.serve.accept": 400}
    cur = {"worker.tokens": 1600,          # +600
           "worker.requests": 20,          # respawn reset → 20
           "decision.serve.accept": 400,   # unchanged → 0
           "decision.serve.reject.expired": 7}  # new → 7
    deltas = capstat.counter_deltas(prev, cur)
    assert deltas == {"worker.tokens": 600, "worker.requests": 20,
                      "decision.serve.accept": 0,
                      "decision.serve.reject.expired": 7}
    assert all(v >= 0 for v in deltas.values())
    rendered = capstat.render_deltas(deltas, 2.0)
    assert "worker.tokens" in rendered and "+600" in rendered
    assert "300.0/s" in rendered
    # zero-delta counters are hidden from the burn view
    assert "decision.serve.accept" not in rendered
    # an all-quiet interval still renders something readable
    assert "(no counter movement)" in capstat.render_deltas(
        {"worker.tokens": 0}, 2.0)


def test_capstat_renders_ring_hwm():
    from tools import capstat

    data = {"127.0.0.1:1": {
        "snapshot": {}, "flight": [],
        "extra": {"worker.pid": 7, "batcher.queued_tokens": 0,
                  "batcher.inflight_batches": 0,
                  "serve.native.active": 1.0,
                  "serve.native.ring_depth": 3.0,
                  "serve.native.ring_hwm": 96.0},
    }}
    rendered = capstat.render_fleet(data)
    assert "chain=native" in rendered
    assert "ring_hwm=96" in rendered
