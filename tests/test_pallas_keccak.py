"""Keccak reference + device-path pinning (the ntt_ref contract,
extended to the hash plane).

Three layers, each pinned against the one below:

1. the numpy uint64 reference vs stdlib ``hashlib.shake_128/256``
   (FIPS 202) — pinned one-shot vectors plus randomized arbitrary
   absorb/squeeze lengths;
2. the jnp uint32 bit-interleaved permutation vs the reference;
3. the Pallas kernel (interpret mode on CPU) vs both.

Everything here is deterministic and CPU-only; ``make pallas-smoke``
re-runs the kernel-liveness subset as a CI gate.
"""

import hashlib

import numpy as np
import pytest

from cap_tpu.tpu import pallas_keccak as KK

RNG = np.random.default_rng(0x202)

# FIPS 202 SHAKE one-shot vectors (empty + short messages; digests are
# the stdlib's, which IS the FIPS 202 reference implementation here —
# pinned as literals so a hashlib regression would also be caught).
PINNED = [
    ("shake_128", b"", 16, "7f9c2ba4e88f827d616045507605853e"),
    ("shake_256", b"", 16, "46b9dd2b0ba88d13233b3feb743eeb24"),
    ("shake_128", b"abc", 16, "5881092dd818bf5cf8a3ddb793fbcba7"),
    ("shake_256", b"abc", 16, "483366601360a8771c6863080cc4114d"),
]


@pytest.mark.parametrize("algo,msg,outlen,hexdigest", PINNED)
def test_ref_pinned_vectors(algo, msg, outlen, hexdigest):
    fn = KK.shake128_ref if algo == "shake_128" else KK.shake256_ref
    assert fn(msg, outlen).hex() == hexdigest
    h = getattr(hashlib, algo)(msg).digest(outlen)
    assert fn(msg, outlen) == h


def test_ref_matches_hashlib_arbitrary_lengths():
    """Randomized absorb/squeeze sweep: lengths straddling every rate
    boundary (0, rate-1, rate, rate+1, multi-block) both ways."""
    lens = [0, 1, 135, 136, 137, 167, 168, 169, 200, 271, 272, 273]
    lens += [int(RNG.integers(0, 600)) for _ in range(20)]
    outs = [1, 16, 32, 135, 136, 137, 200,
            int(RNG.integers(1, 500))]
    for ln in lens:
        data = RNG.integers(0, 256, ln, dtype=np.uint8).tobytes()
        for out in outs:
            assert KK.shake128_ref(data, out) == \
                hashlib.shake_128(data).digest(out), (ln, out)
            assert KK.shake256_ref(data, out) == \
                hashlib.shake_256(data).digest(out), (ln, out)


def test_interleave_roundtrip():
    x = RNG.integers(0, 2 ** 64, (11, 25), dtype=np.uint64)
    il = KK.interleave(x)
    assert il.dtype == np.uint32 and il.shape == (11, 25, 2)
    assert (KK.deinterleave(il) == x).all()


def test_jnp_f1600_matches_ref():
    import jax.numpy as jnp

    st = RNG.integers(0, 2 ** 64, (6, 25), dtype=np.uint64)
    got = KK.deinterleave(np.asarray(KK.f1600(
        jnp.asarray(KK.interleave(st)))))
    assert (got == KK.f1600_ref(st)).all()


def test_pallas_kernel_matches_ref_interpret():
    """The fused-kernel contract: bit-equal to the numpy reference in
    interpret mode on the CPU backend (the only mode this host can
    run; compiled-mode parity rides the chip-blocked list)."""
    import jax.numpy as jnp

    st = RNG.integers(0, 2 ** 64, (7, 25), dtype=np.uint64)
    got = KK.deinterleave(np.asarray(KK.f1600_pallas(
        jnp.asarray(KK.interleave(st)), interpret=True)))
    assert (got == KK.f1600_ref(st)).all()


def test_absorb_squeeze_driver_matches_hashlib():
    """The masked variable-length batch absorb + multi-block squeeze
    — the exact driver the fused ML-DSA μ path runs."""
    import jax.numpy as jnp

    msgs = [RNG.integers(0, 256, int(RNG.integers(0, 320)),
                         dtype=np.uint8).tobytes() for _ in range(9)]
    msgs.append(b"")                     # empty-message edge
    blocks, nblk = KK.pack_blocks(msgs, KK.RATE_SHAKE256)
    state = KK.absorb(jnp.asarray(blocks), jnp.asarray(nblk))
    by = np.asarray(KK.lanes_to_bytes(KK.squeeze_lanes(
        state, KK.RATE_SHAKE256, 3))).astype(np.uint8)
    for i, msg in enumerate(msgs):
        assert by[i].tobytes() == hashlib.shake_256(msg).digest(
            3 * 136), i


def test_bits_to_lanes():
    import jax.numpy as jnp

    bits = RNG.integers(0, 2, (5, 192), dtype=np.uint32)
    lanes = np.asarray(KK.bits_to_lanes(jnp.asarray(bits)))
    back = KK.deinterleave(lanes)
    want = np.zeros((5, 3), np.uint64)
    for r in range(5):
        for b in range(192):
            if bits[r, b]:
                want[r, b // 64] |= np.uint64(1) << np.uint64(b % 64)
    assert (back == want).all()


def test_lanes_to_bytes_roundtrip():
    import jax.numpy as jnp

    raw = RNG.integers(0, 256, (4, 40), dtype=np.uint8)
    il = KK.interleave(np.ascontiguousarray(raw).view("<u8"))
    by = np.asarray(KK.lanes_to_bytes(jnp.asarray(il)))
    assert (by.astype(np.uint8) == raw).all()


def test_enabled_gate_env(monkeypatch):
    monkeypatch.setenv("CAP_TPU_PALLAS_KECCAK", "1")
    assert KK.enabled()
    monkeypatch.setenv("CAP_TPU_PALLAS_KECCAK", "0")
    assert not KK.enabled()
    monkeypatch.delenv("CAP_TPU_PALLAS_KECCAK")
    import jax

    assert KK.enabled() == (jax.default_backend() == "tpu")
