"""Tenant (issuer) attribution plane: vocabulary, bounded table,
Python-fold counters/histograms, SLO templates, redaction (ISSUE 14).

Tier-1 and dependency-free. The native-plane side of the same
contract (bit-exact parity) lives in tests/test_native_obs.py; the
fleet/chaos side (two-tenant flood, kill -9 postmortems) in
tests/test_tenant_fleet.py.
"""

import base64
import hashlib
import json
import os
import sys

import pytest

from cap_tpu import telemetry
from cap_tpu.errors import ExpiredTokenError, InvalidSignatureError
from cap_tpu.obs import decision, postmortem, slo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import capstat  # noqa: E402


def b64(obj) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(obj).encode()).rstrip(b"=").decode()


def tenant_token(iss, alg="ES256", kid="k", suffix="sig") -> str:
    return (b64({"alg": alg, "kid": kid}) + "."
            + b64({"iss": iss}) + "." + suffix)


@pytest.fixture(autouse=True)
def _fresh_attribution():
    """Tenant attribution is process-global (table + header cache);
    isolate every test from what earlier tests admitted."""
    telemetry.disable()
    decision._HDR_CACHE.clear()
    decision.TENANTS.reset()
    yield
    telemetry.disable()
    decision._HDR_CACHE.clear()
    decision.TENANTS.reset()


# ---------------------------------------------------------------------------
# derivation: sha256(iss)[:12], bounded, adversarial-proof
# ---------------------------------------------------------------------------

def test_issuer_hash_and_token_tenant():
    iss = "https://idp.example.com"
    h = decision.issuer_hash(iss)
    assert h == hashlib.sha256(iss.encode()).hexdigest()[:12]
    assert len(h) == decision.TENANT_HASH_LEN
    assert decision.token_tenant(tenant_token(iss)) == h
    # the raw issuer never appears in the id
    assert "idp" not in h and "://" not in h


@pytest.mark.parametrize("payload_seg", [
    "",                                     # empty
    "not-base64!!!",                        # undecodable
    b64([1, 2, 3]),                         # non-dict claims
    b64({"sub": "x"}),                      # no iss at all
    b64({"iss": 123}),                      # non-string iss
    b64({"iss": True}),                     # bool iss
    b64({"iss": ""}),                       # empty iss
    b64({"iss": "x" * 2000}),               # overlong iss
    "x" * 5000,                             # segment over parse bound
    base64.urlsafe_b64encode(b"\xff\xfe{").decode(),  # non-UTF-8
])
def test_token_tenant_none_for_adversarial_payloads(payload_seg):
    tok = b64({"alg": "ES256"}) + "." + payload_seg + ".sig"
    assert decision.token_tenant(tok) == decision.TENANT_NONE


def test_token_tenant_none_for_non_tokens():
    assert decision.token_tenant(None) == decision.TENANT_NONE
    assert decision.token_tenant(1234) == decision.TENANT_NONE
    assert decision.token_tenant("nodots") == decision.TENANT_NONE


def test_adversarial_issuer_values_hash_cleanly():
    """eyJ-prefixed / URL / whitespace issuer VALUES must still come
    out as plain 12-hex ids that pass the name redaction check."""
    for iss in ("eyJhbGciOiJFUzI1NiJ9", "https://a b c.example\n",
                "x" * 1024, "日本語の発行者"):
        h = decision.issuer_hash(iss)
        assert h != decision.TENANT_NONE
        telemetry.check_name(f"decision.serve.tenant.{h}.accept")


# ---------------------------------------------------------------------------
# bounded tenant table
# ---------------------------------------------------------------------------

def test_tenant_table_caps_and_overflows():
    t = decision.TenantTable(cap=4)
    labels = [t.admit(f"{i:012x}") for i in range(7)]
    # first 4 get their own slots + hash labels
    assert [lab for _, lab in labels[:4]] == \
        [f"{i:012x}" for i in range(4)]
    assert sorted(s for s, _ in labels[:4]) == [0, 1, 2, 3]
    # everything past the cap routes to the overflow bucket
    for s, lab in labels[4:]:
        assert s == decision.TENANT_OTHER_IDX
        assert lab == decision.TENANT_OTHER
    # re-admitting an existing tenant is stable
    assert t.admit("000000000000") == (0, "000000000000")
    assert t.size() == 4


def test_tenant_table_reset_counts_evictions():
    t = decision.TenantTable(cap=8)
    for i in range(5):
        t.admit(f"{i:012x}")
    with telemetry.recording() as rec:
        assert t.reset() == 5
        assert rec.counters()["tenant.table_evictions"] == 5
    assert t.size() == 0 and t.evictions == 5


def test_unique_issuer_flood_bounded_with_exact_accounting():
    """The satellite pin: a hostile unique-issuer flood cannot blow up
    label cardinality — the table caps, overflow routes to
    ``tenant.other``, and ``lookups == attributed + overflow`` holds
    EXACTLY (with zero evictions: admitted tenants never churn)."""
    cap = decision.TENANT_CAP
    n_flood = cap + 40
    with telemetry.recording() as rec:
        for i in range(n_flood):
            tok = tenant_token(f"https://flood-{i}.example",
                               kid=f"fk{i}")
            decision.record_batch("serve", [InvalidSignatureError()],
                                  tokens=[tok], latency_s=0.001)
        c = rec.counters()
    assert decision.TENANTS.size() == cap
    assert c["tenant.lookups"] == n_flood
    assert c["tenant.attributed"] == cap
    assert c["tenant.overflow"] == n_flood - cap
    assert c["tenant.lookups"] == \
        c["tenant.attributed"] + c["tenant.overflow"]
    assert c.get("tenant.table_evictions", 0) == 0
    other = f"decision.serve.tenant.{decision.TENANT_OTHER}"
    assert c[f"{other}.tokens"] == n_flood - cap
    assert c[f"{other}.reject.bad_signature"] == n_flood - cap
    # label cardinality is bounded: at most cap + none + other tenant
    # label values across every emitted counter
    labels = {k.split(".")[3] for k in c
              if k.startswith("decision.serve.tenant.")}
    assert len(labels) <= decision.N_TENANT
    for name in c:
        telemetry.check_name(name)


# ---------------------------------------------------------------------------
# the Python fold: per-tenant counters + latency histograms
# ---------------------------------------------------------------------------

def test_record_batch_per_tenant_counters_and_hist():
    ta = tenant_token("https://a.example", kid="ka")
    tb = tenant_token("https://b.example", alg="RS256", kid="kb")
    ha = decision.token_tenant(ta)
    hb = decision.token_tenant(tb)
    assert ha != hb
    with telemetry.recording() as rec:
        decision.record_batch(
            "serve",
            [{"s": 1}, InvalidSignatureError(), {"s": 2},
             ExpiredTokenError()],
            tokens=[ta, tb, ta, tb], latency_s=0.002)
        c = rec.counters()
        assert c[f"decision.serve.tenant.{ha}.tokens"] == 2
        assert c[f"decision.serve.tenant.{ha}.accept"] == 2
        assert f"decision.serve.tenant.{ha}.reject" not in c
        assert c[f"decision.serve.tenant.{hb}.tokens"] == 2
        assert c[f"decision.serve.tenant.{hb}.reject"] == 2
        assert c[f"decision.serve.tenant.{hb}"
                 ".reject.bad_signature"] == 1
        assert c[f"decision.serve.tenant.{hb}.reject.expired"] == 1
        # per-tenant latency: one observation per token at the chunk
        # latency (serve surface only)
        snap = rec.snapshot()
        sa = snap["series"][f"tenant.{ha}.request_s"]
        assert sa["count"] == 2 and sa["sum"] == 0.002 * 2
        assert snap["series"][f"tenant.{hb}.request_s"]["count"] == 2


def test_record_batch_tenant_none_paths():
    with telemetry.recording() as rec:
        # families-only fold (no payloads): everything is "none"
        decision.record_batch("tpu", [{"s": 1}, ExpiredTokenError()],
                              families=["es", "es"])
        # token-less fold
        decision.record_batch("oracle", [{"s": 1}])
        c = rec.counters()
        assert c["decision.tpu.tenant.none.tokens"] == 2
        assert c["decision.tpu.tenant.none.reject.expired"] == 1
        assert c["decision.oracle.tenant.none.accept"] == 1
        # non-serve surfaces never grow latency series
        assert not any(k.startswith("tenant.")
                       for k in rec.snapshot()["series"])
        assert c["tenant.lookups"] == 3
        assert c["tenant.attributed"] == 3


def test_record_batch_tenant_counters_all_surfaces():
    tok = tenant_token("https://s.example", kid="ks")
    h = decision.token_tenant(tok)
    with telemetry.recording() as rec:
        for surface in decision.SURFACES:
            decision.record_batch(surface, [{"s": 1}], tokens=[tok])
        c = rec.counters()
    for surface in decision.SURFACES:
        assert c[f"decision.{surface}.tenant.{h}.accept"] == 1


def test_record_wrong_verdict_counts_global_and_tenant():
    tok = tenant_token("https://w.example", kid="kw")
    h = decision.token_tenant(tok)
    with telemetry.recording() as rec:
        decision.record_wrong_verdict(tok)
        decision.record_wrong_verdict()          # tokenless: global only
        c = rec.counters()
    assert c["decision.wrong_verdicts"] == 2
    assert c[f"decision.tenant.{h}.wrong_verdicts"] == 1


def test_surface_totals_skips_tenant_keys():
    counters = {
        "decision.serve.accept": 5,
        "decision.serve.tenant.aaaaaaaaaaaa.accept": 5,
        "decision.serve.tenant.aaaaaaaaaaaa.tokens": 5,
        "decision.tenant.aaaaaaaaaaaa.wrong_verdicts": 1,
    }
    rollup = decision.surface_totals(counters)
    assert rollup == {"serve": {"accept": 5, "reject": 0}}


def test_tenant_totals_rollup():
    counters = {
        "decision.serve.tenant.aaaaaaaaaaaa.tokens": 10,
        "decision.serve.tenant.aaaaaaaaaaaa.accept": 7,
        "decision.serve.tenant.aaaaaaaaaaaa.reject": 3,
        "decision.serve.tenant.aaaaaaaaaaaa.reject.expired": 3,
        "decision.router.tenant.aaaaaaaaaaaa.tokens": 10,
        "decision.tenant.aaaaaaaaaaaa.wrong_verdicts": 2,
        "vcache.tenant.aaaaaaaaaaaa.lookups": 8,
        "vcache.tenant.aaaaaaaaaaaa.hits": 6,
        "decision.serve.accept": 7,
    }
    t = decision.tenant_totals(counters, surface="serve")
    row = t["aaaaaaaaaaaa"]
    assert row["tokens"] == 10 and row["accept"] == 7
    assert row["reject"] == 3 and row["reject.expired"] == 3
    assert row["wrong_verdicts"] == 2
    assert row["vcache.lookups"] == 8 and row["vcache.hits"] == 6
    # surface=None sums serve + router token rows
    assert decision.tenant_totals(counters)["aaaaaaaaaaaa"]["tokens"] \
        == 20


def test_count_tenant_cache_accounting():
    labels = ["aaaaaaaaaaaa"] * 4 + ["bbbbbbbbbbbb"] * 2
    with telemetry.recording() as rec:
        decision.count_tenant_cache(labels, miss_idx=[0, 4, 5])
        c = rec.counters()
    assert c["vcache.tenant.aaaaaaaaaaaa.lookups"] == 4
    assert c["vcache.tenant.aaaaaaaaaaaa.hits"] == 3
    assert c["vcache.tenant.bbbbbbbbbbbb.lookups"] == 2
    assert "vcache.tenant.bbbbbbbbbbbb.hits" not in c


# ---------------------------------------------------------------------------
# redaction: raw issuers can never reach a recorder / a postmortem
# ---------------------------------------------------------------------------

def test_check_name_rejects_raw_issuer_urls():
    with pytest.raises(ValueError):
        telemetry.check_name("decision.serve.tenant."
                             "https://idp.example.com.accept")
    with pytest.raises(ValueError):
        telemetry.check_name("tenant.http://x.y.request_s")
    assert telemetry.scrub_note("https://idp.example.com/auth") == \
        "[redacted]"
    # plain endpoint notes survive (no scheme)
    assert telemetry.scrub_note("127.0.0.1:8443") == "127.0.0.1:8443"


def test_recorder_surfaces_carry_no_issuer_after_adversarial_sweep():
    """Sweep every recorder surface (counters, series names, decision
    ring, postmortem JSON) after folding adversarial issuers — eyJ
    prefixes, URLs, overlong, non-UTF-8-ish — and assert zero raw
    issuer material anywhere."""
    issuers = ["https://evil.example/realm",
               "eyJhbGciOiJFUzI1NiJ9.sneaky",
               "http://" + "a" * 500 + ".example",
               "日本語の発行者"]
    with telemetry.recording() as rec:
        for i, iss in enumerate(issuers):
            tok = tenant_token(iss, kid=f"adv{i}")
            decision.record_batch(
                "serve", [InvalidSignatureError(), {"s": 1}],
                tokens=[tok, tok], latency_s=0.001)
        doc = postmortem.build_postmortem("test", lambda: {})
        blob = json.dumps({
            "counters": rec.counters(),
            "series": sorted(rec.snapshot()["series"]),
            "decisions": rec.decisions(),
            "postmortem": doc,
        })
    for needle in ("evil.example", "://", "sneaky", "発行者",
                   "a" * 40):
        assert needle not in blob, f"issuer material {needle!r} leaked"


# ---------------------------------------------------------------------------
# merge_snapshots over tenant sections
# ---------------------------------------------------------------------------

def _tenant_snap(h, tokens, accept, lat, k):
    rec = telemetry.Recorder()
    rec.count_many({
        f"decision.serve.tenant.{h}.tokens": tokens,
        f"decision.serve.tenant.{h}.accept": accept,
        "tenant.lookups": tokens,
        "tenant.attributed": tokens,
    })
    rec.observe_many(f"tenant.{h}.request_s", lat, k)
    return rec.snapshot()


def test_merge_snapshots_disjoint_tenant_sections():
    a = _tenant_snap("aaaaaaaaaaaa", 10, 9, 0.001, 10)
    b = _tenant_snap("bbbbbbbbbbbb", 4, 4, 0.1, 4)
    m = telemetry.merge_snapshots([a, b])
    c = m["counters"]
    assert c["decision.serve.tenant.aaaaaaaaaaaa.tokens"] == 10
    assert c["decision.serve.tenant.bbbbbbbbbbbb.tokens"] == 4
    assert c["tenant.lookups"] == 14
    assert m["series"]["tenant.aaaaaaaaaaaa.request_s"]["count"] == 10
    assert m["series"]["tenant.bbbbbbbbbbbb.request_s"]["count"] == 4


def test_merge_snapshots_overlapping_tenant_sections_add_exactly():
    a = _tenant_snap("cccccccccccc", 10, 9, 0.001, 10)
    b = _tenant_snap("cccccccccccc", 6, 2, 0.004, 6)
    m = telemetry.merge_snapshots([a, b])
    c = m["counters"]
    assert c["decision.serve.tenant.cccccccccccc.tokens"] == 16
    assert c["decision.serve.tenant.cccccccccccc.accept"] == 11
    s = m["series"]["tenant.cccccccccccc.request_s"]
    assert s["count"] == 16
    assert s["sum"] == pytest.approx(0.001 * 10 + 0.004 * 6)
    assert s["min"] == 0.001 and s["max"] == 0.004
    # the merged histogram quantile is computable (capstat p99 column)
    summary = telemetry.summarize_snapshot(m)
    assert summary["tenant.cccccccccccc.request_s"]["count"] == 16


def test_observe_many_matches_k_single_adds_in_buckets():
    h1 = telemetry.Histogram()
    h2 = telemetry.Histogram()
    for _ in range(37):
        h1.add(0.0042)
    h2.add_many(0.0042, 37)
    assert h1.counts == h2.counts
    assert h1.count == h2.count
    assert h1.vmin == h2.vmin and h1.vmax == h2.vmax


# ---------------------------------------------------------------------------
# SLO: per-tenant rule expansion
# ---------------------------------------------------------------------------

def test_default_rules_include_tenant_templates():
    rules = slo.default_rules()
    names = {r.name for r in rules}
    assert "tenant_wrong_verdicts" in names
    assert "tenant_reject_ratio" in names
    # r20: the admission plane's shed signal rides a third template
    assert "tenant_throttle_ratio" in names
    assert sum(1 for r in rules if slo.is_tenant_template(r)) == 3


def test_tenant_rule_expansion_per_observed_tenant():
    rules = slo.parse_rules(
        "tr ratio decision.serve.tenant.*.reject / "
        "decision.serve.tenant.*.tokens max 0.5 burn 1.5")
    snap = {"counters": {
        "decision.serve.tenant.aaaaaaaaaaaa.tokens": 100,
        "decision.serve.tenant.aaaaaaaaaaaa.reject": 95,
        "decision.serve.tenant.bbbbbbbbbbbb.tokens": 100,
        "decision.serve.tenant.bbbbbbbbbbbb.reject": 2,
        "decision.serve.tenant.other.tokens": 10,
        "decision.serve.tenant.other.reject": 10,
    }}
    res = slo.evaluate_once(snap, rules)
    by = {r["name"]: r for r in res}
    assert len(res) == 3                   # one per observed tenant
    assert not by["tr[aaaaaaaaaaaa]"]["ok"]
    assert by["tr[bbbbbbbbbbbb]"]["ok"]
    assert not by["tr[other]"]["ok"]       # overflow bucket counts too
    assert by["tr[aaaaaaaaaaaa]"]["tenant"] == "aaaaaaaaaaaa"


def test_tenant_template_vacuous_without_tenants():
    rules = slo.parse_rules(
        "tw counter decision.tenant.*.wrong_verdicts max 0")
    res = slo.evaluate_once({"counters": {"worker.tokens": 5}}, rules)
    assert len(res) == 1 and res[0]["ok"]
    assert "no tenants" in res[0]["detail"]


def test_tenant_quantile_template_expands_over_series():
    rules = slo.parse_rules(
        "tq quantile tenant.*.request_s p99 max 0.0001")
    rec = telemetry.Recorder()
    rec.observe_many("tenant.dddddddddddd.request_s", 0.05, 20)
    res = slo.evaluate_once(rec.snapshot(), rules)
    assert len(res) == 1
    assert res[0]["name"] == "tq[dddddddddddd]" and not res[0]["ok"]


def test_tenant_burn_windows_unchanged():
    """Multi-window burn semantics apply per expanded tenant rule: a
    sustained per-tenant burn breaches, an absorbed spike does not."""
    rules = slo.parse_rules(
        "tr ratio decision.serve.tenant.*.reject / "
        "decision.serve.tenant.*.tokens max 0.01")
    tid = "eeeeeeeeeeee"
    tok = f"decision.serve.tenant.{tid}.tokens"
    rej = f"decision.serve.tenant.{tid}.reject"
    eng = slo.SLOEngine(rules, windows=(60, 300))
    eng.observe({"counters": {tok: 0, rej: 0}}, now=0.0)
    eng.observe({"counters": {tok: 5000, rej: 100}}, now=240.0)
    res = eng.evaluate({"counters": {tok: 10000, rej: 300}}, now=299.0)
    assert [r["ok"] for r in res] == [False]

    spike = slo.SLOEngine(rules, windows=(60, 300))
    spike.observe({"counters": {tok: 0, rej: 0}}, now=0.0)
    spike.observe({"counters": {tok: 990_000, rej: 0}}, now=250.0)
    res = spike.evaluate({"counters": {tok: 1_000_000, rej: 300}},
                         now=300.0)
    assert [r["ok"] for r in res] == [True]


# ---------------------------------------------------------------------------
# capstat ledger
# ---------------------------------------------------------------------------

def test_capstat_render_tenants_ledger():
    ta = tenant_token("https://ledger-a.example", kid="la")
    tb = tenant_token("https://ledger-b.example", kid="lb")
    ha, hb = decision.token_tenant(ta), decision.token_tenant(tb)
    with telemetry.recording() as rec:
        decision.record_batch("serve", [{"s": 1}] * 8, tokens=[ta] * 8,
                              latency_s=0.002)
        decision.record_batch("serve", [InvalidSignatureError()] * 12,
                              tokens=[tb] * 12, latency_s=0.004)
        decision.count_tenant_cache(
            decision.tenant_labels([ta] * 4), [0])
        merged = rec.snapshot()
    out = capstat.render_tenants(merged)
    assert ha in out and hb in out
    assert "[EXACT]" in out                 # lookups == attr + overflow
    assert "BREACH" in out                  # flood tenant's SLO state
    assert "ledger-a" not in out and "://" not in out
    # flood first (sorted by tokens), with its reject mix
    assert out.index(hb) < out.index(ha)
    assert "bad_signature=12" in out
    # --watch shape: per-interval vps column from counter deltas
    watched = capstat.render_tenants(
        merged, prev_counters={
            f"decision.serve.tenant.{hb}.tokens": 4}, interval_s=2.0)
    assert "vps" in watched


def test_capstat_tenants_cli_over_live_scrape():
    from cap_tpu.fleet import FleetClient
    from cap_tpu.fleet.worker_main import StubKeySet
    from cap_tpu.serve.worker import VerifyWorker

    quiet = tenant_token("https://cli-quiet.example", kid="cq",
                         suffix="ok")
    hq = decision.token_tenant(quiet)
    worker = VerifyWorker(StubKeySet(), target_batch=8,
                          max_wait_ms=1.0, obs_port=0)
    try:
        with telemetry.recording():
            cl = FleetClient([worker.address], fallback=StubKeySet(),
                             rr_seed=0)
            for _ in range(3):
                assert len(cl.verify_batch([quiet] * 2)) == 2
            host, port = worker.obs_address
            rc = capstat.main(["--tenants", f"{host}:{port}"])
    finally:
        worker.close()
    assert rc == 0
    # exercised via capsys-free check: main printed the ledger with
    # the hashed tenant id (stdout captured by pytest)
    assert hq  # id derived; rendering asserted in the unit test above


# ---------------------------------------------------------------------------
# doc pin: the metric catalog + derivation rule live in the docs
# ---------------------------------------------------------------------------

def test_observability_doc_pins_tenant_attribution():
    with open(os.path.join(REPO, "docs", "OBSERVABILITY.md")) as f:
        doc = f.read()
    for needle in (
            "## Tenant attribution",
            "sha256(iss)",
            "`tenant.lookups`", "`tenant.attributed`",
            "`tenant.overflow`", "`tenant.table_evictions`",
            "`decision.<surface>.tenant.<t>.tokens`",
            "`tenant.<t>.request_s`",
            "tenant.*", "capstat --tenants",
            f"{decision.TENANT_CAP}",
            "`vcache.tenant.<t>.lookups`",
            "`frontdoor.tenant.<t>.lookups`",
            "`decision.tenant.<t>.wrong_verdicts`",
    ):
        assert needle in doc, \
            f"docs/OBSERVABILITY.md missing {needle!r}"
