"""Batched ECDSA engine parity vs the CPU oracle, all three curves.

The reference exercises ES256/384/512 against both KeySet kinds with
per-curve key sizes (jwt/keyset_test.go:27-266); these tests mirror that
conformance table for the device engine: successes, tampered inputs,
range violations, degenerate keys (Q == ±G), and routing through
TPUBatchKeySet.
"""

import hashlib
import json

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec as cec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
)

from cap_tpu import testing as captest
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
from cap_tpu.tpu.ec import ECKeyTable, curve, verify_ecdsa_batch

_CFG = {
    "P-256": (cec.SECP256R1, hashes.SHA256, 32),
    "P-384": (cec.SECP384R1, hashes.SHA384, 48),
    "P-521": (cec.SECP521R1, hashes.SHA512, 66),
}


def _raw_sign(priv, msg: bytes, hash_cls, cb: int) -> bytes:
    r, s = decode_dss_signature(priv.sign(msg, cec.ECDSA(hash_cls())))
    return r.to_bytes(cb, "big") + s.to_bytes(cb, "big")


@pytest.mark.parametrize("crv", list(_CFG))
def test_curve_conformance(crv):
    curve_cls, hash_cls, cb = _CFG[crv]
    cp = curve(crv)
    privs = [cec.generate_private_key(curve_cls()) for _ in range(3)]
    table = ECKeyTable(crv, [p.public_key() for p in privs])
    msg = b"conformance " + crv.encode()
    digest = hashlib.new(hash_cls.name, msg).digest()

    sigs, rows, want = [], [], []
    for i, p in enumerate(privs):
        sigs.append(_raw_sign(p, msg, hash_cls, cb))
        rows.append(i)
        want.append(True)
    good = sigs[0]
    # tampered s
    bad = bytearray(good)
    bad[-1] ^= 1
    sigs.append(bytes(bad)); rows.append(0); want.append(False)
    # tampered r
    bad = bytearray(good)
    bad[0] ^= 0x80
    sigs.append(bytes(bad)); rows.append(0); want.append(False)
    # wrong key
    sigs.append(sigs[1]); rows.append(2); want.append(False)
    # r = 0
    sigs.append(b"\x00" * cb + good[cb:]); rows.append(0); want.append(False)
    # s = 0
    sigs.append(good[:cb] + b"\x00" * cb); rows.append(0); want.append(False)
    # r = n (out of range)
    sigs.append(cp.n.to_bytes(cb, "big") + good[cb:])
    rows.append(0); want.append(False)
    # s = n - <real s> is a DIFFERENT valid signature (low-s not
    # enforced, matching Go crypto/ecdsa which accepts both halves)
    s_int = int.from_bytes(good[cb:], "big")
    sigs.append(good[:cb] + (cp.n - s_int).to_bytes(cb, "big"))
    rows.append(0); want.append(True)
    # wrong length
    sigs.append(good[:-1]); rows.append(0); want.append(False)
    sigs.append(good + b"\x00"); rows.append(0); want.append(False)

    ok = verify_ecdsa_batch(table, sigs, [digest] * len(sigs),
                            np.asarray(rows, np.int32))
    assert list(ok) == want


@pytest.mark.parametrize("d", [1, -1], ids=["Q=G", "Q=-G"])
def test_degenerate_keys(d):
    """Q == G exercises the host G+Q doubling branch; Q == -G the
    gq_inf (G+Q = infinity) ladder mask."""
    crv = "P-256"
    curve_cls, hash_cls, cb = _CFG[crv]
    cp = curve(crv)
    scalar = 1 if d == 1 else cp.n - 1
    priv = cec.derive_private_key(scalar, curve_cls())
    table = ECKeyTable(crv, [priv.public_key()])
    msg = b"degenerate key test"
    digest = hashlib.new(hash_cls.name, msg).digest()
    good = _raw_sign(priv, msg, hash_cls, cb)
    bad = bytearray(good)
    bad[-1] ^= 1
    ok = verify_ecdsa_batch(table, [good, bytes(bad)], [digest, digest],
                            np.zeros(2, np.int32))
    assert list(ok) == [True, False]


def test_cross_curve_hash_lengths():
    """ES512 uses SHA-512 (512 bits) on a 521-bit order: e < n un-truncated."""
    curve_cls, hash_cls, cb = _CFG["P-521"]
    priv = cec.generate_private_key(curve_cls())
    table = ECKeyTable("P-521", [priv.public_key()])
    msg = b"x" * 1000
    digest = hashlib.sha512(msg).digest()
    sig = _raw_sign(priv, msg, hash_cls, cb)
    ok = verify_ecdsa_batch(table, [sig], [digest], np.zeros(1, np.int32))
    assert list(ok) == [True]


@pytest.fixture(scope="module")
def es_jwks():
    out = []
    for i, alg in enumerate(["ES256", "ES256", "ES384", "ES512"]):
        priv, pub = captest.generate_keys(alg)
        out.append((f"ec-{i}", alg, priv, pub))
    return out


def test_tpu_keyset_es_batch(es_jwks):
    ks = TPUBatchKeySet([JWK(pub, kid=kid) for kid, _, _, pub in es_jwks])
    toks = []
    for j in range(12):
        kid, alg, priv, _ = es_jwks[j % len(es_jwks)]
        toks.append(captest.sign_jwt(
            priv, alg, captest.default_claims(sub=f"u{j}"), kid=kid))
    res = ks.verify_batch(toks)
    for j, r in enumerate(res):
        assert isinstance(r, dict), f"token {j}: {r}"
        assert r["sub"] == f"u{j}"


def test_tpu_keyset_mixed_rs_es_parity(es_jwks):
    """The north-star shape: mixed RS256+ES256 batch, parity vs oracle."""
    rs_priv, rs_pub = captest.generate_keys("RS256")
    jwks = [JWK(rs_pub, kid="rs")] + \
        [JWK(pub, kid=kid) for kid, _, _, pub in es_jwks]
    ks = TPUBatchKeySet(jwks)

    claims = captest.default_claims()
    kid0, alg0, es_priv, _ = es_jwks[0]
    batch = [
        captest.sign_jwt(rs_priv, "RS256", claims, kid="rs"),
        captest.sign_jwt(es_priv, alg0, claims, kid=kid0),
        # ES sig under the RS kid: kid routing pins the wrong key →
        # reject (matches the reference's kid-matched JWKS semantics,
        # jwt/keyset.go:126-127, unlike StaticKeySet trial-verify)
        captest.sign_jwt(es_priv, alg0, claims, kid="rs"),
        # tampered ES payload
        None,
        "gar.ba.ge",
    ]
    h, p, s = batch[1].split(".")
    from cap_tpu.jwt.jose import b64url_encode
    batch[3] = f"{h}.{b64url_encode(json.dumps({'sub': 'evil'}).encode())}.{s}"

    res = ks.verify_batch(batch)
    for tok, r in zip(batch, res):
        # oracle: the keyset's own single-token CPU path
        try:
            ks.verify_signature(tok)
            cpu_ok = True
        except Exception:
            cpu_ok = False
        assert (not isinstance(r, Exception)) == cpu_ok, (tok[:40], r)
    assert not isinstance(res[0], Exception)
    assert not isinstance(res[1], Exception)
    assert all(isinstance(r, Exception) for r in res[2:])


@pytest.mark.parametrize("alg", ["ES256", "ES384", "ES512"])
def test_es_object_path_without_native_prep(alg, monkeypatch):
    """The non-native (object) batch path must handle every ES hash
    length (regression: pad digests were hardcoded to 32 bytes)."""
    from cap_tpu.runtime import prep

    monkeypatch.setattr(prep, "_load_native", lambda: None)
    priv, pub = captest.generate_keys(alg)
    ks = TPUBatchKeySet([JWK(pub, kid="k")])
    tok = captest.sign_jwt(priv, alg, captest.default_claims(), kid="k")
    bad = tok[:-4] + ("AAAA" if not tok.endswith("AAAA") else "BBBB")
    res = ks.verify_batch([tok, bad, tok])
    assert isinstance(res[0], dict) and isinstance(res[2], dict)
    assert isinstance(res[1], Exception)


def test_es_no_kid_single_key_routes_to_device():
    priv, pub = captest.generate_keys("ES256")
    ks = TPUBatchKeySet([JWK(pub)])
    tok = captest.sign_jwt(priv, "ES256", captest.default_claims())
    res = ks.verify_batch([tok] * 3)
    assert all(isinstance(r, dict) for r in res)


@pytest.mark.heavy
def test_rns_w12_parity(monkeypatch):
    """12-bit window RNS path against the CPU oracle.

    The default is w=8 everywhere (w=12 measured slower on the chip —
    see ec_rns.default_w_bits), but the machinery stays width-generic
    for re-measurement on other parts; this pins the cross-limb digit
    extraction, the Jacobian+batched-inverse table build, and the probe
    degeneracy flags at w=12 — successes AND rejections.
    """
    from cap_tpu.tpu import ec_rns

    monkeypatch.setenv("CAP_TPU_RNS", "1")
    curve_cls, hash_cls, cb = _CFG["P-256"]
    privs = [cec.generate_private_key(curve_cls()) for _ in range(3)]
    pubs = [p.public_key() for p in privs]
    table = ECKeyTable("P-256", pubs)
    table._rns = ec_rns.ECRNSKeyTable("P-256", pubs, w_bits=12)
    assert table.rns().ctx.w_bits == 12

    msg = b"w12 parity"
    digest = hashlib.new(hash_cls.name, msg).digest()
    sigs, rows, want = [], [], []
    for i, p in enumerate(privs):
        sigs.append(_raw_sign(p, msg, hash_cls, cb))
        rows.append(i)
        want.append(True)
    good = bytearray(sigs[0])
    for flip in (0, cb - 1, cb, 2 * cb - 1):    # r/s head+tail tampering
        bad = bytearray(good)
        bad[flip] ^= 1
        sigs.append(bytes(bad)); rows.append(0); want.append(False)
    sigs.append(b"\x00" * (2 * cb)); rows.append(0); want.append(False)
    n_int = curve("P-256").n
    sigs.append(n_int.to_bytes(cb, "big") + good[cb:])   # r = n
    rows.append(0); want.append(False)
    # wrong-key dispatch must reject
    sigs.append(bytes(good)); rows.append(1); want.append(False)

    ok = verify_ecdsa_batch(table, sigs, [digest] * len(sigs),
                            np.asarray(rows, np.int32))
    assert list(ok) == want


@pytest.mark.heavy
def test_window_multiples_matches_affine_chain():
    """Jacobian fast path == the naive affine chain, several widths."""
    cp = curve("P-256")
    priv = cec.generate_private_key(cec.SECP256R1())
    nums = priv.public_key().public_numbers()
    point = (nums.x, nums.y)
    for w_bits, n_windows in ((4, 3), (8, 2), (12, 2)):
        X, Y = cp.window_multiples(point, w_bits, n_windows)
        per = (1 << w_bits) - 1
        base = point
        for i in range(n_windows):
            acc = None
            for d in range(1, per + 1):
                acc = cp.affine_add(acc, base)
                r = i * per + d - 1
                assert (X[r], Y[r]) == acc, (w_bits, i, d)
            for _ in range(w_bits):
                base = cp.affine_add(base, base)
