"""Decision records, SLO engine, postmortems, bench-trend sentinel.

Tier-1 and dependency-free (stub engines, no crypto, no jax): the
decision/reason layer (cap_tpu.obs.decision) including the
wire-roundtrip parity that makes four-surface reason accounting
structural, the SLO burn-rate engine and ``capstat --slo`` exit
codes, the postmortem writer/reader/renderer, and the BENCH series
regression sentinel."""

import inspect
import json
import os
import socket
import time
import urllib.request

import pytest

from cap_tpu import errors as errors_mod
from cap_tpu import telemetry
from cap_tpu.errors import CapError, InvalidSignatureError
from cap_tpu.fleet import FleetClient
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.obs import decision, postmortem, slo
from cap_tpu.serve import obs as obs_mod
from cap_tpu.serve.client import RemoteVerifyError
from cap_tpu.serve.worker import VerifyWorker
from tools import bench_trend, capstat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off():
    telemetry.disable()
    yield
    telemetry.disable()


def _error_classes():
    """Every concrete CapError subclass defined in cap_tpu/errors.py."""
    return [cls for _, cls in inspect.getmembers(errors_mod,
                                                 inspect.isclass)
            if issubclass(cls, CapError)]


# ---------------------------------------------------------------------------
# reason taxonomy: coverage + doc pin
# ---------------------------------------------------------------------------

def test_reason_table_covers_whole_error_taxonomy():
    """Pin: every sentinel error class maps to a registered reason —
    a new error class added without a reason mapping fails here (same
    pattern as the SPAN_NAMES doc pin)."""
    for cls in _error_classes():
        assert cls.__name__ in decision.REASON_FOR_ERROR, \
            f"{cls.__name__} missing from REASON_FOR_ERROR"
    for name, reason in decision.REASON_FOR_ERROR.items():
        assert reason in decision.REASON_CLASSES, (name, reason)


def test_observability_doc_pins_reason_table():
    with open(os.path.join(REPO, "docs", "OBSERVABILITY.md")) as f:
        doc = f.read()
    for reason in sorted(decision.REASON_CLASSES):
        assert f"`{reason}`" in doc, \
            f"reason class {reason} missing from docs/OBSERVABILITY.md"


@pytest.mark.parametrize("cls", _error_classes(),
                         ids=lambda c: c.__name__)
def test_wire_roundtrip_reason_parity(cls):
    """Satellite pin (the dependency-free core of four-surface
    parity): an error INSTANCE and its CVB1 wire form — the
    ``"<Class>: <message>"`` payload the worker sends, seen by the
    router as RemoteVerifyError — classify to the SAME reason."""
    err = cls()
    direct = decision.classify(err)
    wire_payload = f"{type(err).__name__}: {err}"
    assert decision.classify(RemoteVerifyError(wire_payload)) == direct
    assert direct in decision.REASON_CLASSES


def test_classify_specifics():
    assert decision.classify(InvalidSignatureError()) == "bad_signature"
    assert decision.classify(
        errors_mod.UnknownKeyIDError()) == "unknown_kid"
    assert decision.classify(errors_mod.ExpiredTokenError()) == "expired"
    assert decision.classify(
        errors_mod.MalformedTokenError()) == "malformed"
    assert decision.classify(ConnectionResetError()) == "transport"
    assert decision.classify(socket.timeout()) == "transport"
    assert decision.classify(ValueError("x")) == "internal"
    # unknown remote class name degrades to internal, never raises
    assert decision.classify(
        RemoteVerifyError("SomethingNewError: ?")) == "internal"


def test_family_and_kid_extraction():
    rs = "eyJhbGciOiJSUzI1NiIsImtpZCI6ImswIn0.e30.c2ln"
    fam, kid = decision.token_family_kid(rs)
    assert fam == "rs"
    assert kid == decision.hash_kid("k0")
    assert len(kid) == 12 and kid != "k0"
    assert decision.token_family_kid("garbage")[0] == "unknown"
    assert decision.token_family_kid("a.ok") == ("unknown", None)
    assert decision.family_for_alg("ES512") == "es"
    assert decision.family_for_alg("EdDSA") == "ed"
    assert decision.family_for_alg("HS256") == "other"


def test_latency_buckets():
    assert decision.latency_bucket(None) == "na"
    assert decision.latency_bucket(0.0005) == "lt1ms"
    assert decision.latency_bucket(0.5) == "lt1s"
    assert decision.latency_bucket(3.0) == "ge1s"


# ---------------------------------------------------------------------------
# recording: counters, ring, redaction
# ---------------------------------------------------------------------------

def test_record_batch_counters_and_ring():
    with telemetry.recording() as rec:
        with telemetry.trace() as tid:
            decision.record_batch(
                "serve",
                [{"sub": "a"}, InvalidSignatureError(), b"raw-ok"],
                tokens=["eyJhbGciOiJSUzI1NiJ9.e30.c2ln", "x.bad",
                        "eyJhbGciOiJFUzI1NiJ9.e30.c2ln"],
                latency_s=0.002)
        c = rec.counters()
        assert c["decision.serve.accept"] == 2
        assert c["decision.serve.reject.bad_signature"] == 1
        assert c["decision.serve.family.rs"] == 1
        assert c["decision.serve.family.es"] == 1
        ring = rec.decisions()
        assert ring, "first occurrences must be ring-sampled"
        for entry in ring:
            assert entry["surface"] == "serve"
            assert entry["lat"] == "lt10ms"
            assert entry["trace"] == tid
        reject = next(e for e in ring if e["verdict"] == "reject")
        assert reject["reason"] == "bad_signature"


def test_record_batch_noop_when_telemetry_off():
    decision.record_batch("serve", [InvalidSignatureError()],
                          tokens=["a.b"])   # must not raise, no recorder


def test_decision_ring_is_bounded():
    with telemetry.recording() as rec:
        for i in range(10_000):
            decision.record_batch("serve", [{"s": 1}])
        assert len(rec.decisions()) <= telemetry.MAX_DECISION_ENTRIES


def test_checked_entry_rejects_token_material():
    with pytest.raises(ValueError):
        decision._checked_entry({"family": "eyJhbGciOiJSUzI1NiJ9"})
    with pytest.raises(ValueError):
        decision._checked_entry({"reason": "a" * 100})


def test_counter_names_pass_redaction_check():
    """Every counter key the layer can emit survives check_name."""
    for surface in decision.SURFACES:
        for reason in decision.REASON_CLASSES:
            telemetry.check_name(f"decision.{surface}.reject.{reason}")
        for fam in decision.FAMILIES:
            telemetry.check_name(f"decision.{surface}.family.{fam}")
        telemetry.check_name(f"decision.{surface}.accept")


# ---------------------------------------------------------------------------
# end-to-end stub parity: serve vs router over the wire
# ---------------------------------------------------------------------------

def test_serve_router_decision_parity_end_to_end():
    """A mixed batch through worker + FleetClient: the serve and
    router surfaces count identical accept/reject-by-reason totals —
    the rejection crossed the wire as RemoteVerifyError and still
    incremented the same reason class."""
    worker = VerifyWorker(StubKeySet(), target_batch=8, max_wait_ms=1.0)
    try:
        with telemetry.recording() as rec:
            cl = FleetClient([worker.address], fallback=StubKeySet(),
                             rr_seed=0)
            out = cl.verify_batch(["a.ok", "b.bad", "c.ok", "d.bad",
                                   "e.bad"])
            assert len(out) == 5
            rollup = decision.surface_totals(rec.counters())
        assert rollup["serve"]["accept"] == 2
        assert rollup["serve"]["reject.bad_signature"] == 3
        assert rollup["router"]["accept"] == 2
        assert rollup["router"]["reject.bad_signature"] == 3
    finally:
        worker.close()


def test_oracle_surface_records_decisions():
    """The KeySet base class (CPU-oracle surface) records decisions
    for any subclass that only implements verify_signature."""
    from cap_tpu.jwt.keyset import KeySet

    class _Stub(KeySet):
        def verify_signature(self, token):
            if token.endswith(".ok"):
                return {"sub": token}
            raise InvalidSignatureError("nope")

    with telemetry.recording() as rec:
        out = _Stub().verify_batch(["a.ok", "b.bad"])
        assert len(out) == 2
        rollup = decision.surface_totals(rec.counters())
    assert rollup["oracle"]["accept"] == 1
    assert rollup["oracle"]["reject.bad_signature"] == 1


def test_obs_server_decisions_endpoint():
    srv = obs_mod.ObsServer()
    try:
        with telemetry.recording():
            decision.record_batch("serve", [InvalidSignatureError()],
                                  tokens=["x.y"])
            host, port = srv.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/decisions", timeout=5) as r:
                body = json.load(r)
        assert body["decisions"][0]["reason"] == "bad_signature"
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

def test_slo_parse_rules_and_errors():
    rules = slo.parse_rules("""
    # comment
    wv   counter decision.wrong_verdicts max 0
    fb   ratio fleet.fallback_tokens / worker.tokens max 0.05 burn 2
    p99  quantile batcher.flush p99 max 0.5
    """)
    assert [r.kind for r in rules] == ["counter", "ratio", "quantile"]
    assert rules[1].burn_threshold == 2.0
    with pytest.raises(slo.SLOError):
        slo.parse_rules("broken gibberish line")
    with pytest.raises(slo.SLOError):
        slo.parse_rules("x ratio a / b maximum 0.1")


def test_slo_counter_and_quantile_rules():
    rec = telemetry.Recorder()
    rec.count("decision.wrong_verdicts", 0)
    for _ in range(50):
        rec.observe("batcher.flush", 0.01)
    rules = slo.parse_rules(
        "wv counter decision.wrong_verdicts max 0\n"
        "p99 quantile batcher.flush p99 max 0.5")
    res = slo.evaluate_once(rec.snapshot(), rules)
    assert all(r["ok"] for r in res)
    rec.count("decision.wrong_verdicts", 1)
    for _ in range(5):
        rec.observe("batcher.flush", 30.0)
    res = slo.evaluate_once(rec.snapshot(), rules)
    assert not res[0]["ok"] and not res[1]["ok"]
    assert slo.any_breach(res)
    assert "BREACH" in slo.format_results(res)


def test_slo_multiwindow_burn_semantics():
    """Sustained burn breaches; a short spike the long window already
    absorbed does not (the multi-window discipline)."""
    rules = slo.parse_rules(
        "fb ratio fleet.fallback_tokens / worker.tokens max 0.01")
    sustained = slo.SLOEngine(rules, windows=(60, 300))
    t = 0.0
    sustained.observe({"counters": {"worker.tokens": 0}}, now=t)
    sustained.observe(
        {"counters": {"fleet.fallback_tokens": 100,
                      "worker.tokens": 5000}}, now=t + 240)
    res = sustained.evaluate(
        {"counters": {"fleet.fallback_tokens": 300,
                      "worker.tokens": 10000}}, now=t + 299)
    assert not res[0]["ok"], res

    spike = slo.SLOEngine(rules, windows=(60, 300))
    spike.observe({"counters": {"worker.tokens": 0}}, now=t)
    spike.observe({"counters": {"fleet.fallback_tokens": 0,
                                "worker.tokens": 990_000}}, now=t + 250)
    res = spike.evaluate(
        {"counters": {"fleet.fallback_tokens": 300,
                      "worker.tokens": 1_000_000}}, now=t + 300)
    assert res[0]["ok"], res


def test_slo_default_rules_parse():
    rules = slo.default_rules()
    names = [r.name for r in rules]
    assert "wrong_verdicts" in names
    assert "oracle_fallback" in names


# ---------------------------------------------------------------------------
# capstat --slo against a live stub worker (acceptance bar)
# ---------------------------------------------------------------------------

def test_capstat_slo_exit_codes_live_fleet(tmp_path, capsys):
    """capstat --slo over a live stub worker: clean rules exit 0,
    an injected breach exits nonzero — the pageable CI/cron shape."""
    worker = VerifyWorker(StubKeySet(), target_batch=8, max_wait_ms=1.0,
                          obs_port=0)
    try:
        with telemetry.recording():
            cl = FleetClient([worker.address], fallback=StubKeySet(),
                             rr_seed=0)
            for i in range(3):
                cl.verify_batch([f"s{i}.ok", f"s{i}.bad"])
            host, port = worker.obs_address
            ep = f"{host}:{port}"
            rc_default = capstat.main(["--slo", ep])
            # Injected breach: this fleet HAS rejections, so a zero
            # rejection budget must burn.
            rules = tmp_path / "slo.rules"
            rules.write_text(
                "no_rejects counter "
                "decision.serve.reject.bad_signature max 0\n")
            rc_breach = capstat.main(["--slo-rules", str(rules), ep])
    finally:
        worker.close()
    out = capsys.readouterr().out
    assert rc_default == 0, out
    assert rc_breach == 2, out
    assert "BREACH" in out
    assert "decisions[serve]" in out      # verdict rollup rendered


def test_capstat_slo_unparseable_rules_fail_loudly(tmp_path):
    worker = VerifyWorker(StubKeySet(), obs_port=0)
    try:
        host, port = worker.obs_address
        bad = tmp_path / "bad.rules"
        bad.write_text("not a rule at all\n")
        with pytest.raises(slo.SLOError):
            capstat.main(["--slo-rules", str(bad), f"{host}:{port}"])
    finally:
        worker.close()


# ---------------------------------------------------------------------------
# postmortems: writer, scrub, renderer, capstat --postmortem
# ---------------------------------------------------------------------------

def test_postmortem_write_read_render(tmp_path, capsys):
    path = str(tmp_path / "pm.json")
    with telemetry.recording() as rec:
        rec.count("worker.tokens", 7)
        rec.trace_span("ab12cd34ab12cd34", "batcher.fill", 1.0, 0.25)
        rec.flight("ab12cd34ab12cd34", 0.25)
        decision.record_batch("serve", [InvalidSignatureError()],
                              tokens=["t.bad"])
        w = postmortem.PostmortemWriter(
            path, interval_s=0.05,
            stats_fn=lambda: {"queued_tokens": 2,
                              "inflight_batches": 1})
        time.sleep(0.15)
        w.close("sigterm-drain")
    doc = postmortem.read_postmortem(path)
    assert doc["reason"] == "sigterm-drain"
    assert doc["snapshot"]["counters"]["worker.tokens"] == 7
    assert doc["flight"][0]["trace"] == "ab12cd34ab12cd34"
    assert doc["decisions"][0]["reason"] == "bad_signature"
    assert doc["stats"]["queued_tokens"] == 2
    rendered = postmortem.render_postmortem(doc)
    assert "sigterm-drain" in rendered
    assert "decisions[serve]" in rendered
    # capstat --postmortem renders the same file
    assert capstat.main(["--postmortem", path]) == 0
    out = capsys.readouterr().out
    assert "postmortem pid=" in out and "ab12cd34ab12cd34" in out
    # missing file: error exit, not traceback
    assert capstat.main(["--postmortem", str(tmp_path / "nope")]) == 1


def test_postmortem_scrub_redacts_token_shapes():
    doc = postmortem._scrub({
        "note": "eyJhbGciOiJSUzI1NiJ9.e30.c2ln",
        "long": "x" * 1000,
        "nested": [{"ok": "fine", "bad": "xx eyJzdWIiOiJhIn0 yy"}],
        "n": 3,
    })
    assert doc["note"] == "[redacted]"
    assert doc["long"] == "[redacted]"
    assert doc["nested"][0]["bad"] == "[redacted]"
    assert doc["nested"][0]["ok"] == "fine" and doc["n"] == 3


def test_postmortem_survives_failing_stats_fn(tmp_path):
    path = str(tmp_path / "pm.json")

    def boom():
        raise RuntimeError("stats source is the thing that crashed")

    postmortem.write_postmortem(
        path, postmortem.build_postmortem("crash", boom))
    doc = postmortem.read_postmortem(path)
    assert "stats_error" in doc and doc["reason"] == "crash"


# ---------------------------------------------------------------------------
# stalled scraper: the obs server's short-timeout handler threads
# ---------------------------------------------------------------------------

def test_obs_server_stalled_scraper_does_not_block(tmp_path):
    """A scraper that connects and never sends a request must neither
    block other scrapes nor hold its handler thread past the timeout."""
    srv = obs_mod.ObsServer(handler_timeout_s=0.5)
    try:
        host, port = srv.address
        stalled = socket.create_connection((host, port), timeout=5)
        stalled.send(b"GET /metrics")        # partial request, no CRLF
        # Healthy scrapes keep answering promptly while it hangs.
        for _ in range(3):
            t0 = time.monotonic()
            with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5) as r:
                assert json.load(r)["ok"]
            assert time.monotonic() - t0 < 2.0
        # The server times the stalled connection out and closes it.
        stalled.settimeout(5.0)
        deadline = time.monotonic() + 5.0
        closed = False
        while time.monotonic() < deadline:
            try:
                if stalled.recv(4096) == b"":
                    closed = True
                    break
            except (ConnectionError, socket.timeout, OSError):
                closed = True
                break
        assert closed, "stalled scraper connection never closed"
        stalled.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# bench-trend sentinel
# ---------------------------------------------------------------------------

def test_bench_trend_selftest_and_real_series():
    assert bench_trend.selftest(REPO) == []
    series = bench_trend.load_series(REPO)
    assert len(series) >= 5
    assert bench_trend.check_series(series) == [], \
        "committed BENCH series must pass clean"
    assert bench_trend.check_multichip(
        bench_trend.load_multichip(REPO)) == []


def test_bench_trend_flags_injected_regression():
    series = bench_trend._synthetic([100.0, 100.0, 100.0, 85.0])
    findings = bench_trend.check_series(series)
    assert findings and "-15.0%" in findings[0]


def test_bench_trend_weather_annotation():
    series = bench_trend._synthetic([100.0, 100.0])
    series.append((3, {"value": 50.0, "stall_intervals": 4,
                       "stall_seconds": 60.0}))
    findings = bench_trend.check_series(series)
    assert findings and "weather" in findings[0]


def test_bench_trend_requires_self_describing_records():
    series = [(5, {"value": 100.0}), (6, {"value": 100.0})]
    findings = bench_trend.check_self_describing(series)
    assert any("decisions" in f for f in findings)
    series = [(6, {"value": 100.0, "decisions": {}, "slo": []})]
    assert bench_trend.check_self_describing(series) == []
