"""Mesh scaling structure: the sharded step must actually shard.

VERDICT r4 #7: when real multi-chip hardware appears the scaling
number should be one command (tools/profile_families.py --mesh N);
what must be pinned NOW, on the virtual CPU mesh, is the STRUCTURE —
each device receives exactly its n/N slice of the batch and the
verdict comes back sharded the same way. A regression that silently
replicates the batch (every chip doing all tokens) or inserts a
stray all-gather would pass the existing accept/reject mesh tests
while destroying scaling; these assertions catch it.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


import jax

from cap_tpu import testing as captest
from cap_tpu.jwt import algs
from cap_tpu.jwt.jwk import JWK
from cap_tpu.parallel.mesh import DP_AXIS, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")


def _shard_sizes(arr):
    """Batch-axis length of every addressable shard of a device array."""
    return sorted(s.data.shape[-1] if s.data.ndim else 0
                  for s in arr.addressable_shards)


@pytest.mark.parametrize("alg,n_dev", [(algs.ES256, 4), (algs.RS256, 8),
                                       (algs.EdDSA, 4), (algs.PS256, 4)])
def test_packed_verdicts_shard_batch_axis(alg, n_dev):
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet, resident_dispatchers

    if len(jax.devices()) < n_dev:
        pytest.skip(f"needs {n_dev} devices")
    mesh = make_mesh(n_dev)
    priv, pub = captest.generate_keys(alg)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")], mesh=mesh)
    toks = [captest.sign_jwt(priv, alg, captest.default_claims(sub=f"s{i}"),
                             kid="k0") for i in range(64)] * 4
    n_tok, fns = resident_dispatchers(ks, toks)
    assert n_tok == 256

    # The dispatcher's summed accept count must see every token once.
    for _, fn in fns:
        assert int(fn()) == n_tok

    # The dispatcher's resident record itself must be placed SHARDED
    # (dev_put with a mesh) — a replication regression here would
    # still pass the accept-count check above.
    rec0 = fns[0][1].__defaults__[0]
    rec_sizes = sorted(s.data.shape[0] for s in rec0.addressable_shards)
    assert len(rec_sizes) == n_dev
    assert rec_sizes == [rec0.shape[0] // n_dev] * n_dev, \
        f"dispatcher record not evenly sharded: {rec_sizes}"

    # Structure: the packed verdict array is sharded n/N per device on
    # the batch axis — no replication, no gather back to one device.
    from cap_tpu.tpu import ec as tpuec
    from cap_tpu.tpu import ed25519 as tpued
    from cap_tpu.tpu import rsa as tpursa
    from cap_tpu.runtime.native_binding import prepare_batch_arrays
    from cap_tpu.jwt.tpu_keyset import (
        _pack_es_record, _pack_rsa_record)

    pb = prepare_batch_arrays(toks)
    idx = np.arange(n_tok)
    rows = np.zeros(n_tok, np.int32)
    if alg == algs.ES256:
        table = ks._ec_tables["P-256"]
        rec = _pack_es_record(pb, table, idx, rows, 32, 256)
        ok, _deg = tpuec.verify_es_packed_pending(table, rec, 32, mesh=mesh)
    elif alg == algs.EdDSA:
        table = ks._ed_table
        sigs = [pb.signature(int(j)) for j in idx]
        msgs = [pb.signing_input(int(j)) for j in idx]
        rec = tpued.ed_packed_records(table, sigs, msgs, rows)
        ok = tpued.verify_ed_packed_pending(table, rec, mesh=mesh)
    else:
        table = ks._rsa_tables[0]
        kind = "rs" if alg == algs.RS256 else "ps"
        rec = _pack_rsa_record(pb, table, kind, "sha256", idx, rows, 256)
        verify = (tpursa.verify_rs_packed_pending if kind == "rs"
                  else tpursa.verify_ps_packed_pending)
        ok = verify(table, rec, "sha256", mesh=mesh)

    sizes = _shard_sizes(ok)
    assert len(sizes) == n_dev
    assert sizes == [256 // n_dev] * n_dev, \
        f"verdicts not evenly sharded: {sizes}"
    spec = ok.sharding.spec
    assert DP_AXIS in str(spec), f"verdict not sharded on {DP_AXIS}: {spec}"
    assert bool(np.asarray(ok)[:n_tok].all())


def test_mesh_throughput_scales_with_devices():
    """Dispatch-size sanity: per-device work is n/N — the scaling
    contract a real slice realizes as near-linear throughput."""
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet, resident_dispatchers

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    priv, pub = captest.generate_keys(algs.ES256)
    toks = [captest.sign_jwt(priv, algs.ES256,
                             captest.default_claims(sub=f"s{i}"),
                             kid="k0") for i in range(128)] * 2
    shard_per_dev = {}
    for n_dev in (2, 8):
        mesh = make_mesh(n_dev)
        ks = TPUBatchKeySet([JWK(pub, kid="k0")], mesh=mesh)
        from cap_tpu.runtime.native_binding import prepare_batch_arrays
        from cap_tpu.jwt.tpu_keyset import _pack_es_record
        from cap_tpu.tpu import ec as tpuec

        pb = prepare_batch_arrays(toks)
        rec = _pack_es_record(pb, ks._ec_tables["P-256"],
                              np.arange(256), np.zeros(256, np.int32),
                              32, 256)
        ok, _ = tpuec.verify_es_packed_pending(
            ks._ec_tables["P-256"], rec, 32, mesh=mesh)
        shard_per_dev[n_dev] = _shard_sizes(ok)[0]
        assert bool(np.asarray(ok).all())
    # 4x the devices -> each device holds a 4x smaller slice.
    assert shard_per_dev[2] == 4 * shard_per_dev[8]
