"""ML-DSA (FIPS 204) core: NTT parity, encodings, sign/verify
roundtrips, JWK plumbing, and the engine-vs-oracle bit-exactness
sweep (≥1k batched verifies per parameter set — the acceptance bar).

Everything here is dependency-free (no ``cryptography``): the host
oracle is pure numpy int64, the device engine is the uint32 Montgomery
JAX graph, and fixtures come from the deterministic in-repo signer.
"""

import hashlib
import json

import numpy as np
import pytest

from cap_tpu.errors import InvalidJWKSError, InvalidSignatureError
from cap_tpu.jwt import algs
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import _CURVE_B, parse_jwk, serialize_public_key
from cap_tpu.jwt.verify import key_matches_alg, verify_parsed
from cap_tpu.tpu import mldsa as M
from cap_tpu.tpu import ntt as NTT

RNG = np.random.default_rng(0xCAB)


# ---------------------------------------------------------------------------
# NTT layer
# ---------------------------------------------------------------------------

def test_ntt_ref_roundtrip():
    a = RNG.integers(0, NTT.Q, (5, 256), dtype=np.int64)
    assert (NTT.intt_ref(NTT.ntt_ref(a)) == a).all()


def test_ntt_ref_negacyclic_product():
    """Pointwise NTT-domain products ARE negacyclic convolution in
    Z_q[x]/(x^256+1) — the algebra the whole verify equation rides."""
    a = RNG.integers(0, NTT.Q, 256, dtype=np.int64)
    b = RNG.integers(0, NTT.Q, 256, dtype=np.int64)
    c = np.zeros(256, object)
    for i in range(256):
        ai = int(a[i])
        for k in range(256):
            j = (k - i) % 256
            term = ai * int(b[j])
            c[k] = (c[k] + (term if i <= k else -term)) % NTT.Q
    via_ntt = NTT.intt_ref((NTT.ntt_ref(a) * NTT.ntt_ref(b)) % NTT.Q)
    assert (c.astype(np.int64) == via_ntt).all()


def test_device_ntt_matches_ref():
    import jax.numpy as jnp

    a = RNG.integers(0, NTT.Q, (4, 256), dtype=np.int64)
    dev = np.asarray(NTT.ntt(jnp.asarray(a.astype(np.uint32))))
    assert (dev.astype(np.int64) == NTT.ntt_ref(a)).all()
    back = np.asarray(NTT.intt(NTT.ntt(jnp.asarray(a.astype(np.uint32)))))
    assert (back.astype(np.int64) == a).all()


def test_mont_mul_is_plain_product_with_mont_operand():
    import jax.numpy as jnp

    x = RNG.integers(0, NTT.Q, 512, dtype=np.int64)
    y = RNG.integers(0, NTT.Q, 512, dtype=np.int64)
    ym = (y << NTT.MONT_BITS) % NTT.Q
    got = np.asarray(NTT.mont_mul(jnp.asarray(x.astype(np.uint32)),
                                  jnp.asarray(ym.astype(np.uint32))))
    assert (got.astype(np.int64) == (x * y) % NTT.Q).all()


@pytest.mark.parametrize("gamma2", [95232, 261888])
def test_use_hint_device_matches_ref(gamma2):
    import jax.numpy as jnp

    r = RNG.integers(0, NTT.Q, (3, 256), dtype=np.int64)
    # Force the q-1 wrap special case into the sweep.
    r[0, 0] = NTT.Q - 1
    r[0, 1] = 0
    h = RNG.integers(0, 2, (3, 256), dtype=np.int64)
    ref = NTT.use_hint_ref(h, r, gamma2)
    dev = np.asarray(NTT.use_hint(jnp.asarray(h.astype(np.uint32)),
                                  jnp.asarray(r.astype(np.uint32)),
                                  gamma2))
    assert (ref == dev.astype(np.int64)).all()
    m = (NTT.Q - 1) // (2 * gamma2)
    assert ref.max() < m


# ---------------------------------------------------------------------------
# encodings
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bits", [4, 6, 10, 13, 18, 20])
def test_bitpack_roundtrip(bits):
    arr = RNG.integers(0, 1 << bits, (3, 256), dtype=np.int64)
    packed = M.bitpack(arr, bits)
    assert packed.shape[-1] == 256 * bits // 8
    assert (M.bitunpack(packed, bits, 256) == arr).all()


@pytest.mark.parametrize("pset", sorted(M.PARAMS))
def test_hint_roundtrip_and_malformed_rejects(pset):
    p = M.PARAMS[pset]
    h = np.zeros((p.k, 256), np.uint8)
    # a plausible sparse hint pattern
    for i in range(p.k):
        h[i, RNG.choice(256, size=5, replace=False)] = 1
    enc = M.hint_bit_pack(h, p)
    assert len(enc) == p.omega + p.k
    dec = M.hint_bit_unpack(enc, p)
    assert (dec == h).all()
    # count overflow: per-poly cumulative index above omega
    bad = bytearray(enc)
    bad[p.omega + p.k - 1] = p.omega + 1
    assert M.hint_bit_unpack(bytes(bad), p) is None
    # non-increasing cumulative index
    if p.k >= 2:
        bad = bytearray(enc)
        bad[p.omega] = p.omega       # first poly claims everything
        assert M.hint_bit_unpack(bytes(bad), p) is None
    # nonzero padding after the last used index
    bad = bytearray(enc)
    bad[p.omega - 1] = 200 if bad[p.omega - 1] == 0 else 0
    assert M.hint_bit_unpack(bytes(bad), p) is None


# ---------------------------------------------------------------------------
# sign / verify roundtrips (host oracle)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pset", sorted(M.PARAMS))
def test_sign_verify_roundtrip(pset):
    p = M.PARAMS[pset]
    priv, pub = M.keygen(pset, bytes([1]) * 32)
    assert len(pub.pk) == p.pk_size
    sig = priv.sign(b"roundtrip")
    assert len(sig) == p.sig_size
    assert M.py_verify(pub, sig, b"roundtrip")
    assert not M.py_verify(pub, sig, b"roundtriq")
    assert not M.py_verify(pub, sig[:-1], b"roundtrip")
    assert not M.py_verify(pub, sig + b"\x00", b"roundtrip")
    flip = bytearray(sig)
    flip[3] ^= 0x10
    assert not M.py_verify(pub, bytes(flip), b"roundtrip")
    # deterministic: same seed -> same key, same sig
    priv2, pub2 = M.keygen(pset, bytes([1]) * 32)
    assert pub2.pk == pub.pk
    assert priv2.sign(b"roundtrip") == sig
    # different key rejects
    _, pub3 = M.keygen(pset, bytes([2]) * 32)
    assert not M.py_verify(pub3, sig, b"roundtrip")


def test_out_of_range_z_rejected():
    pset = "ML-DSA-44"
    p = M.PARAMS[pset]
    priv, pub = M.keygen(pset, bytes([3]) * 32)
    sig = bytearray(priv.sign(b"msg"))
    # encoded slot 0 -> z0 = gamma1 (>= gamma1 - beta): norm gate
    sig[p.lam // 4: p.lam // 4 + 3] = b"\x00\x00\x00"
    assert M.py_verify(pub, bytes(sig), b"msg") is False


# ---------------------------------------------------------------------------
# JWK / verify plumbing
# ---------------------------------------------------------------------------

def test_akp_jwk_roundtrip_and_negatives():
    _, pub = M.keygen("ML-DSA-44", bytes([4]) * 32)
    jwk_dict = serialize_public_key(pub, kid="pq")
    assert jwk_dict["kty"] == "AKP"
    assert jwk_dict["alg"] == "ML-DSA-44"
    jwk = parse_jwk(jwk_dict)
    assert jwk.key.pk == pub.pk
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": "ML-DSA-99", "pub": "AQAB"})
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": "ML-DSA-44"})
    with pytest.raises(InvalidJWKSError):
        parse_jwk({"kty": "AKP", "alg": "ML-DSA-44", "pub": "AQAB"})


def test_key_matches_alg_mldsa():
    _, pub44 = M.keygen("ML-DSA-44", bytes([5]) * 32)
    assert key_matches_alg(pub44, algs.MLDSA44)
    assert not key_matches_alg(pub44, algs.MLDSA65)
    assert not key_matches_alg(pub44, algs.ES256)
    assert algs.MLDSA44 in algs.SUPPORTED_ALGORITHMS
    assert algs.MLDSA44 not in algs.HASH_FOR_ALG


def test_verify_parsed_mldsa():
    from cap_tpu.jwt.jose import parse_jws

    priv, pub = M.keygen("ML-DSA-44", bytes([6]) * 32)
    h = b64url_encode(json.dumps({"alg": "ML-DSA-44"}).encode())
    pl = b64url_encode(json.dumps({"sub": "x"}).encode())
    si = (h + "." + pl).encode()
    tok = h + "." + pl + "." + b64url_encode(priv.sign(si))
    parsed = parse_jws(tok)
    verify_parsed(parsed, pub)          # must not raise
    bad = parse_jws(tok[:-6] + ("AAAAAA" if not tok.endswith("AAAAAA")
                                else "BBBBBB"))
    with pytest.raises(InvalidSignatureError):
        verify_parsed(bad, pub)


def test_curve_b_constants_match_base_points():
    """Pin the dependency-free on-curve check's b constants: every
    curve's standard base point must satisfy y² = x³ - 3x + b."""
    from cap_tpu.tpu.ec import _CURVE_INTS

    for crv, b in _CURVE_B.items():
        c = _CURVE_INTS[crv]
        p, gx, gy = c["p"], c["gx"], c["gy"]
        assert (gy * gy - (gx * gx * gx - 3 * gx + b)) % p == 0, crv


def test_decision_family_for_mldsa():
    from cap_tpu.obs import decision

    assert decision.family_for_alg("ML-DSA-44") == "mldsa44"
    assert decision.family_for_alg("ML-DSA-65") == "mldsa65"
    assert decision.family_for_alg("ML-DSA-87") == "mldsa87"
    for fam in ("mldsa44", "mldsa65", "mldsa87"):
        assert fam in decision.FAMILIES


# ---------------------------------------------------------------------------
# engine vs oracle: bit-exact parity on ≥1k batched verifies per set
# ---------------------------------------------------------------------------

def _mutate(sig: bytes, msg: bytes, i: int, p):
    """Deterministic per-index mutation mix: valid, tampered sig
    bytes, truncations, hint corruption, tampered message."""
    mode = i % 8
    if mode in (0, 1, 2):                 # 3/8 valid
        return sig, msg
    if mode == 3:                          # c~ flip
        b = bytearray(sig)
        b[i % (p.lam // 4)] ^= 1 << (i % 8)
        return bytes(b), msg
    if mode == 4:                          # z flip
        b = bytearray(sig)
        b[p.lam // 4 + (i * 7) % (p.l * 32 * p.z_bits)] ^= 0x20
        return bytes(b), msg
    if mode == 5:                          # wrong length
        return (sig[:-1] if i % 2 else sig + b"\x00"), msg
    if mode == 6:                          # hint section corruption
        b = bytearray(sig)
        b[-(1 + i % p.k)] ^= 0xFF
        return bytes(b), msg
    return sig, msg + b"!"                 # tampered message


@pytest.mark.parametrize("pset", sorted(M.PARAMS))
def test_engine_oracle_parity_1k(pset):
    """≥1k batched verifies per parameter set, mixed valid/adversarial,
    TWO keys in the table: the device engine's verdicts must equal the
    pure-int host oracle's bit-for-bit (the ROADMAP acceptance bar)."""
    p = M.PARAMS[pset]
    privs, pubs = [], []
    for s in (20, 21):
        pr, pu = M.keygen(pset, bytes([s]) * 32)
        privs.append(pr)
        pubs.append(pu)
    table = M.MLDSAKeyTable(pset, pubs)

    base = []
    for i in range(16):
        msg = f"parity-{pset}-{i}".encode()
        base.append((privs[i % 2].sign(msg), msg, i % 2))

    n = 1024
    sigs, msgs, rows = [], [], []
    for i in range(n):
        sig, msg, row = base[i % len(base)]
        sig, msg = _mutate(sig, msg, i // len(base) + i, p)
        sigs.append(sig)
        msgs.append(msg)
        rows.append(row)

    got = M.verify_mldsa_batch(table, sigs, msgs,
                               np.asarray(rows, np.int32))
    want = np.array([M.py_verify(pubs[rows[i]], sigs[i], msgs[i])
                     for i in range(n)])
    mism = np.nonzero(got[:n] != want)[0]
    assert len(mism) == 0, f"verdict mismatch at {mism[:10]}"
    assert 0 < int(want.sum()) < n      # the sweep mixed both verdicts
