"""Native relay front door: parity pin + relay e2e + counters.

The routing-decision parity pin is the load-bearing test: the C++
fast path (``cap_frontdoor_probe_route``) must make bit-identical
owner decisions to the Python :class:`ConsistentHashRing` twin —
across ring sizes, membership change, and breaker trips — exactly the
twin stance the DRR scheduler pins with ``cap_drr_*``. The relay
e2e section then drives every CVB1 frame family through a live
:class:`NativeFrontDoorServer` over real in-process workers and gates
the exact-counting contract (``frontdoor.lookups ==
affinity_hits + affinity_misses``) through the split fast/slow path.
"""

import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import ConsistentHashRing, FrontDoor
from cap_tpu.fleet.frontdoor import (NativeFrontDoorServer,
                                     native_frontdoor_enabled)
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.serve import protocol as P
from cap_tpu.serve import vcache as V
from cap_tpu.serve.worker import VerifyWorker

try:
    from cap_tpu.serve import native_serve
    _HAVE = bool(getattr(native_serve.load(), "cap_fd_ok", False))
except Exception:  # noqa: BLE001 - any load failure → skip module
    _HAVE = False

pytestmark = pytest.mark.skipif(
    not _HAVE, reason="native front-door chain unavailable")

HARD_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout():
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"frontdoor-native test exceeded {HARD_TIMEOUT_S}s")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _bare_frontdoor(n_pools=2, **kw):
    return FrontDoor([[("127.0.0.1", 1 + i)] for i in range(n_pools)],
                     **kw)


def _gateway(fd, **kw):
    kw.setdefault("refresh_s", 0.05)
    return NativeFrontDoorServer(fd, **kw)


# ---------------------------------------------------------------------------
# the parity pin: native owner decision == Python ring twin, bit-exact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_pools,vnodes", [(1, 64), (2, 64), (3, 16),
                                            (5, 64)])
def test_probe_route_parity_pin(n_pools, vnodes):
    """Randomized digests through the native ring lookup vs the
    Python twin: owner decisions must be bit-identical, including
    the breaker-trip (-1 = slow path) and membership-change cases."""
    fd = _bare_frontdoor(n_pools, vnodes=vnodes)
    # refresh_s huge: the test owns the live flags below
    gw = _gateway(fd, refresh_s=999.0)
    try:
        rng = random.Random(0xF00D + n_pools)
        digests = [rng.randbytes(16) for _ in range(300)]
        ring = fd._ring
        want = [ring.primary(d) for d in digests]
        assert gw.probe_route(digests) == want

        # breaker trip: dead owner → -1 (the frame would slow-path),
        # every other decision UNCHANGED
        dead = n_pools - 1
        gw._lib.cap_frontdoor_set_live(gw._h, dead, 0)
        want_dead = [-1 if w == dead else w for w in want]
        assert gw.probe_route(digests) == want_dead
        gw._lib.cap_frontdoor_set_live(gw._h, dead, 1)
        assert gw.probe_route(digests) == want
    finally:
        gw.close(deadline_s=5.0)


def test_probe_route_membership_change_parity():
    """Re-staging a grown ring re-pins parity: the native decision
    tracks the NEW ring exactly, and only segments the new pool owns
    moved (the consistent-hash property, through the native path)."""
    fd2 = _bare_frontdoor(2)
    gw2 = _gateway(fd2, refresh_s=999.0)
    try:
        rng = random.Random(29)
        digests = [rng.randbytes(16) for _ in range(300)]
        before = gw2.probe_route(digests)
        assert before == [fd2._ring.primary(d) for d in digests]
    finally:
        gw2.close(deadline_s=5.0)
    fd3 = _bare_frontdoor(3)
    gw3 = _gateway(fd3, refresh_s=999.0)
    try:
        after = gw3.probe_route(digests)
        assert after == [fd3._ring.primary(d) for d in digests]
        moved = [(b, a) for b, a in zip(before, after) if b != a]
        assert moved and all(a == 2 for _b, a in moved), \
            "membership change must move only the new pool's segments"
    finally:
        gw3.close(deadline_s=5.0)


def test_probe_route_point_math_matches_bisect():
    """The ring-point math itself: big-endian u64 of digest[:8] +
    upper_bound == Python int.from_bytes + bisect_right, pinned on
    crafted edge digests (all-zero, all-ff, exact point values)."""
    fd = _bare_frontdoor(3)
    gw = _gateway(fd, refresh_s=999.0)
    try:
        ring = fd._ring
        edges = [bytes(16), b"\xff" * 16]
        for pt in ring._points[:8]:
            edges.append(pt.to_bytes(8, "big") + bytes(8))
            edges.append((pt - 1).to_bytes(8, "big") + bytes(8))
        assert gw.probe_route(edges) == [ring.primary(d)
                                         for d in edges]
    finally:
        gw.close(deadline_s=5.0)


# ---------------------------------------------------------------------------
# relay e2e over live workers: every frame family, exact counters
# ---------------------------------------------------------------------------


def _two_workers(**kw):
    w0 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      **kw)
    w1 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0,
                      **kw)
    return w0, w1


def _connect(gw):
    s = socket.create_connection(gw.address, timeout=10.0)
    s.settimeout(30.0)
    return s, P.FrameReader(s)


def test_relay_e2e_all_frame_families_and_exact_counters():
    rec = telemetry.enable()
    rec.reset()
    w0, w1 = _two_workers()
    gw = None
    try:
        fd = FrontDoor([[w0.address], [w1.address]],
                       fallback=StubKeySet(),
                       client_kw={"attempt_timeout": 5.0,
                                  "total_deadline": 10.0})
        gw = _gateway(fd)
        s, r = _connect(gw)
        toks = [f"relay-{i}.ok" for i in range(24)] + ["relay-bad"]
        for crc, trace in ((False, None), (True, None),
                          (False, "ab12cd34")):
            P.send_request(s, toks, crc=crc, trace=trace)
            ftype, entries, tr = r.recv_frame_ex()
            want = (P.T_VERIFY_RESP_TRACE if trace
                    else P.T_VERIFY_RESP_CRC if crc
                    else P.T_VERIFY_RESP)
            assert ftype == want and tr == trace
            assert [e[0] for e in entries] == [0] * 24 + [1]
            for t, (st, payload) in zip(toks[:24], entries[:24]):
                assert json.loads(payload) == {"sub": t}
        P.send_ping(s)
        ftype, _ = r.recv_frame()
        assert ftype == P.T_PONG
        s.close()
        time.sleep(0.3)           # let the counter fold tick

        st = gw.stats()
        c = st["counters"]
        assert st["frontdoor_chain"] == "native"
        # THE exact-counting contract through the split path
        assert c["frontdoor.lookups"] == \
            c["frontdoor.affinity_hits"] \
            + c["frontdoor.affinity_misses"]
        assert c["frontdoor.lookups"] >= 75
        assert c["frontdoor.native.relays"] > 0
        assert c["frontdoor.native.proto_errors"] == 0
        assert c["frontdoor.native.upstream_fails"] == 0
        assert c["frontdoor.native.dropped_posts"] == 0
        # native fast path only ever counts lookups == hits
        assert c["frontdoor.native.lookups"] \
            == c["frontdoor.native.hits"]
        assert c.get("vcache.stale_accepts", 0) == 0
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)   # closes fd too
        w0.close(5)
        w1.close(5)
        telemetry.disable()


def test_relay_splices_single_owner_frames_and_holds_seq_order():
    """Pipelined single-token frames: single-owner plain requests
    splice through verbatim (zero re-encode), and responses come back
    in strict submission order even though two pools answer at
    different speeds."""
    w0, w1 = _two_workers()
    gw = None
    try:
        fd = FrontDoor([[w0.address], [w1.address]],
                       fallback=StubKeySet())
        gw = _gateway(fd)
        s, r = _connect(gw)
        n = 40
        toks = [f"seq-{i}.a.ok" for i in range(n)]
        for t in toks:
            P.send_request(s, [t])
        for t in toks:
            ftype, entries = r.recv_frame()
            assert ftype == P.T_VERIFY_RESP
            assert entries[0][0] == 0
            assert json.loads(entries[0][1]) == {"sub": t}, \
                "responses out of submission order"
        s.close()
        nc = gw.native_counters()
        assert nc["frontdoor.native.splices"] >= n // 2
        # every token either relayed natively or (overload gate) went
        # through the Python slow path — none double-counted or lost
        assert nc["frontdoor.native.relay_tokens"] \
            + nc["frontdoor.native.slow_tokens"] == n
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)
        w0.close(5)
        w1.close(5)


def test_control_frames_slow_path_stats_keys_peer_shm():
    """Control frames drain to Python and each gets EXACTLY one
    response: STATS serves the gateway doc, KEYS fans out to every
    pool (both workers converge on the pushed epoch), peer-fill and
    shm-attach are refused with proper error acks."""
    w0, w1 = _two_workers()
    gw = None
    try:
        fd = FrontDoor([[w0.address], [w1.address]])
        gw = _gateway(fd)
        s, r = _connect(gw)
        P.send_stats_request(s)
        ftype, entries = r.recv_frame()
        assert ftype == P.T_STATS_RESP
        doc = json.loads(entries[0][1])
        assert doc["frontdoor_chain"] == "native"
        assert doc["frontdoor"]["routing"] == "affinity"

        P.send_keys_push(s, {"keys": []}, epoch=5)
        ftype, entries = r.recv_frame()
        assert ftype == P.T_KEYS_ACK and entries[0][0] == 0
        assert json.loads(entries[0][1])["epoch"] == 5
        assert w0.key_epoch == 5 and w1.key_epoch == 5
        assert gw.key_epoch == 5

        P.send_peer_fill(s, {"op": "export", "max_entries": 10})
        ftype, entries = r.recv_frame()
        assert ftype == P.T_PEER_ACK and entries[0][0] == 1

        P.send_shm_attach(s, "/bogus/ring")
        ftype, entries = r.recv_frame()
        assert ftype == P.T_SHM_ACK and entries[0][0] == 1

        # still serving verifies on the same conn afterwards
        P.send_request(s, ["after-control.ok"])
        ftype, entries = r.recv_frame()
        assert ftype == P.T_VERIFY_RESP and entries[0][0] == 0
        s.close()
        assert gw.native_counters()[
            "frontdoor.native.dropped_posts"] == 0
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)
        w0.close(5)
        w1.close(5)


def test_dead_pool_upstream_fail_then_breaker_pushdown():
    """One pool's endpoint is dead from the start: the first relay
    that routes there fails upstream (connect refused) and the WHOLE
    frame re-dispatches through the Python slow path — which trips the
    breaker, the refresh thread pushes live=0 down, and later frames
    classify as dead-pool BEFORE any relay is attempted. Zero wrong
    verdicts, zero lost submissions throughout."""
    w0, w1 = _two_workers()
    gw = None
    try:
        # a port nothing listens on (bound then released)
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        fd = FrontDoor([[w0.address], [w1.address],
                        [("127.0.0.1", dead_port)]],
                       fallback=StubKeySet(),
                       client_kw={"attempt_timeout": 1.0,
                                  "total_deadline": 5.0,
                                  "max_rounds": 1,
                                  "breaker_threshold": 1,
                                  "breaker_reset_s": 60.0})
        gw = _gateway(fd)
        s, r = _connect(gw)
        toks = [f"death-{i}.ok" for i in range(48)]
        for rep in range(6):
            P.send_request(s, toks)
            ftype, entries = r.recv_frame()
            assert ftype == P.T_VERIFY_RESP
            assert len(entries) == 48, "lost submissions"
            assert [e[0] for e in entries] == [0] * 48, \
                f"wrong verdict with dead pool (rep {rep})"
            time.sleep(0.12)          # let breaker → set_live settle
        s.close()
        c = gw.stats()["counters"]
        assert c["frontdoor.lookups"] == \
            c["frontdoor.affinity_hits"] \
            + c["frontdoor.affinity_misses"]
        # rep 1 hit the upstream-fail election; once the breaker
        # pushed live=0 down, frames classified dead-pool at the edge
        assert c["frontdoor.native.upstream_fails"] > 0
        assert c.get("frontdoor.native.slow.upstream_fail", 0) > 0
        assert c.get("frontdoor.native.slow.dead_pool", 0) > 0
        assert c["frontdoor.reroutes"] > 0 \
            or c["frontdoor.fallback_tokens"] > 0
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)
        w0.close(5)
        w1.close(5)


def test_malformed_frame_severs_connection_not_gateway():
    w0, w1 = _two_workers()
    gw = None
    try:
        fd = FrontDoor([[w0.address], [w1.address]])
        gw = _gateway(fd)
        s, r = _connect(gw)
        s.sendall(b"\x00" * 64)           # bad magic
        assert s.recv(1) == b"", "reader must sever on bad magic"
        s.close()
        # the gateway itself survives and keeps serving
        s2, r2 = _connect(gw)
        P.send_request(s2, ["survivor.ok"])
        ftype, entries = r2.recv_frame()
        assert ftype == P.T_VERIFY_RESP and entries[0][0] == 0
        s2.close()
        assert gw.native_counters()[
            "frontdoor.native.proto_errors"] >= 1
    finally:
        if gw is not None:
            gw.close(deadline_s=5.0)
        w0.close(5)
        w1.close(5)


# ---------------------------------------------------------------------------
# kill switch + worker_main wiring
# ---------------------------------------------------------------------------


def test_kill_switch_env(monkeypatch):
    monkeypatch.delenv("CAP_FRONTDOOR_NATIVE", raising=False)
    assert native_frontdoor_enabled()
    for off in ("0", "false", "no", "off"):
        monkeypatch.setenv("CAP_FRONTDOOR_NATIVE", off)
        assert not native_frontdoor_enabled()
    monkeypatch.setenv("CAP_FRONTDOOR_NATIVE", "1")
    assert native_frontdoor_enabled()


def test_native_gate_requires_affinity_routing():
    fd = _bare_frontdoor(2, routing="rr")
    with pytest.raises(ValueError):
        NativeFrontDoorServer(fd)
    fd.close()


def _boot_gateway_proc(pool_port, env_extra=None, chain="auto"):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env_extra or {})}
    p = subprocess.Popen(
        [sys.executable, "-m", "cap_tpu.fleet.worker_main",
         "--keyset", f"frontdoor:pool=127.0.0.1:{pool_port}",
         "--obs-port", "-1", "--frontdoor-chain", chain],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    line = p.stdout.readline().strip()
    kv = dict(f.split("=", 1) for f in line.split()[1:])
    return p, kv


def test_worker_main_gateway_chain_selection():
    """The deployable gateway: ``--frontdoor-chain auto`` runs native,
    the CAP_FRONTDOOR_NATIVE=0 kill switch forces the python gate, and
    both report honestly on the ready line."""
    w0 = VerifyWorker(StubKeySet(), target_batch=64, max_wait_ms=1.0)
    procs = []
    try:
        port = w0.address[1]
        p1, kv1 = _boot_gateway_proc(port)
        procs.append(p1)
        assert kv1.get("frontdoor_chain") == "native", kv1
        p2, kv2 = _boot_gateway_proc(
            port, env_extra={"CAP_FRONTDOOR_NATIVE": "0"})
        procs.append(p2)
        assert kv2.get("frontdoor_chain") == "python", kv2
        # both gates serve identical verdicts
        for kv in (kv1, kv2):
            s = socket.create_connection(
                ("127.0.0.1", int(kv["port"])), timeout=10.0)
            s.settimeout(30.0)
            P.send_request(s, ["gate.ok", "gate.bad"])
            ftype, entries = P.FrameReader(s).recv_frame()
            assert [e[0] for e in entries] == [0, 1], kv
            s.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        w0.close(5)
