"""Fused single-round-trip ML-DSA verify: parity + the zero-host-SHAKE
pin.

The r17 contract: with the fused path ON (the default), a packed
ML-DSA batch is ONE device dispatch — μ, SampleInBall, the NTT
network, w1Encode, and the c̃ compare all run on-device, and the host
performs ZERO per-token SHAKE calls. The pin is a span/counter test:
``mldsa.host_shake_calls`` (bumped by every hashlib absorb-squeeze in
``mldsa.py``) must not move during a warm packed batch, while the
``dispatch.mldsa.*`` span and the device token counters do.
"""

import json

import numpy as np
import pytest

from cap_tpu import telemetry
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import parse_jwks, serialize_public_key
from cap_tpu.tpu import mldsa as M

PSET = "ML-DSA-44"


@pytest.fixture(scope="module")
def fixtures():
    privs, pubs, jwks = [], [], []
    for s in (81, 82):
        pr, pu = M.keygen(PSET, bytes([s]) * 32)
        privs.append(pr)
        pubs.append(pu)
        jwks.append(serialize_public_key(pu, kid=f"fz{s}"))

    def tok(i, evil=False):
        h = b64url_encode(json.dumps(
            {"alg": PSET, "kid": f"fz{81 + i % 2}"},
            separators=(",", ":")).encode())
        p = b64url_encode(json.dumps(
            {"sub": f"u{i}", "pad": "x" * (i * 13 % 400)},
            separators=(",", ":")).encode())
        si = (h + "." + p).encode()
        sig = privs[i % 2].sign(si)
        if evil:
            b = bytearray(sig)
            b[i % len(b)] ^= 0x10
            sig = bytes(b)
        return h + "." + p + "." + b64url_encode(sig)

    tokens = [tok(i) for i in range(12)] + \
        [tok(i, evil=True) for i in range(4)]
    return privs, pubs, jwks, tokens


def test_fused_engine_matches_oracle(fixtures):
    """Mixed valid/adversarial fused verdicts == py_verify bit-for-bit
    (the oracle contract the unfused path already carries)."""
    privs, pubs, _, _ = fixtures
    p = M.PARAMS[PSET]
    table = M.MLDSAKeyTable(PSET, pubs)
    base = [(privs[i % 2].sign(f"fu-{i}".encode()),
             f"fu-{i}".encode(), i % 2) for i in range(8)]
    n = 120                       # pad 128 = the keyset bucket shape,
    sigs, msgs, rows = [], [], []  # so the jit compile is shared
    for i in range(n):
        sig, msg, row = base[i % len(base)]
        mode = i % 6
        if mode == 1:
            b = bytearray(sig)
            b[i % len(sig)] ^= 1 << (i % 8)
            sig = bytes(b)
        elif mode == 2:
            sig = sig[:-1]
        elif mode == 3:
            msg = msg + b"?"
        elif mode == 4:
            b = bytearray(sig)
            b[i % (p.lam // 4)] ^= 0x01       # inside c~
            sig = bytes(b)
        sigs.append(sig)
        msgs.append(msg)
        rows.append(row)
    got = M.verify_mldsa_fused_pending(
        table, sigs, msgs, np.asarray(rows, np.int32), pad=128)()
    want = np.array([M.py_verify(pubs[rows[i]], sigs[i], msgs[i])
                     for i in range(n)])
    mism = np.nonzero(got[:n] != want)[0]
    assert len(mism) == 0, f"fused/oracle mismatch at {mism[:10]}"
    assert 0 < int(want.sum()) < n


def test_fused_matches_unfused_path(fixtures, monkeypatch):
    """The A/B arms agree verdict-for-verdict through the keyset."""
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    _, _, jwks, tokens = fixtures
    ks = TPUBatchKeySet(parse_jwks({"keys": jwks}))
    monkeypatch.setenv("CAP_TPU_MLDSA_FUSED", "1")
    fused = ks.verify_batch(tokens)
    monkeypatch.setenv("CAP_TPU_MLDSA_FUSED", "0")
    unfused = ks.verify_batch(tokens)
    for i, (a, b) in enumerate(zip(fused, unfused)):
        assert isinstance(a, Exception) == isinstance(b, Exception), i
        if not isinstance(a, Exception):
            assert a == b, i


def test_packed_path_zero_host_shake(fixtures, monkeypatch):
    """THE r17 pin: a warm packed batch performs zero host SHAKE calls
    with the fused path on, while the dispatch span and device
    counters prove the ML-DSA bucket actually ran on-device. The
    unfused arm — same batch, same keys — hashes per token, which
    also proves the counter is live, not vacuously zero."""
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    _, _, jwks, tokens = fixtures
    monkeypatch.setenv("CAP_TPU_MLDSA_FUSED", "1")
    ks = TPUBatchKeySet(parse_jwks({"keys": jwks}))
    ks.verify_batch(tokens)              # warm: tr/Â precompute, jit
    with telemetry.recording() as rec:
        out = ks.verify_batch(tokens)
        counters = rec.counters()
        series = rec.snapshot()["series"]
    assert any(not isinstance(r, Exception) for r in out)
    assert counters.get(M.HOST_SHAKE_COUNTER, 0) == 0, (
        "fused packed path performed host SHAKE calls")
    assert counters.get("device.mldsa.tokens", 0) == len(tokens)
    assert f"dispatch.mldsa.{PSET}" in series

    monkeypatch.setenv("CAP_TPU_MLDSA_FUSED", "0")
    with telemetry.recording() as rec:
        ks.verify_batch(tokens)
        unfused_calls = rec.counters().get(M.HOST_SHAKE_COUNTER, 0)
    # unfused: >= 2 host SHAKEs per decodable token (μ + SampleInBall
    # at prep, + the finalize compare) — the counter is demonstrably
    # live on the same traffic.
    assert unfused_calls >= len(tokens), unfused_calls


def test_fused_single_key_and_invalid_rows(fixtures):
    """Decode-invalid tokens never touch the device and finish False;
    an all-invalid chunk short-circuits to zeros."""
    _, pubs, _, _ = fixtures
    table = M.MLDSAKeyTable(PSET, [pubs[0]])
    sigs = [b"\x00" * 7, b"\x01" * 9]
    msgs = [b"a", b"b"]
    got = M.verify_mldsa_fused_pending(
        table, sigs, msgs, np.zeros(2, np.int32), pad=4)()
    assert got.shape == (4,) and not got.any()


def test_exhausted_flag_falls_back_to_oracle(fixtures, monkeypatch):
    """The SampleInBall budget-exhausted escape hatch: a token the
    device flags re-verifies on the pure-int oracle and the counter
    moves. Exhaustion cannot be provoked with real hashes (the budget
    overflows with probability ~2^-1000), so the jitted core is
    stubbed to RAISE the flag — the host-side fallback logic is what
    this test pins."""
    privs, pubs, _, _ = fixtures
    msg = b"exhaust-me"
    sig = privs[0].sign(msg)

    def fake_core(*args, **kwargs):
        # verdict False + exhausted True for slot 0; slot 1 invalid
        return (np.array([False, False]), np.array([True, False]))

    monkeypatch.setattr(M, "_fused_jit", lambda: fake_core)
    table = M.MLDSAKeyTable(PSET, pubs)
    with telemetry.recording() as rec:
        got = M.verify_mldsa_fused_pending(
            table, [sig, sig[:-1]], [msg, msg],
            np.zeros(2, np.int32), pad=2)()
        count = rec.counters().get("mldsa.fused.exhausted", 0)
    assert bool(got[0]) is True          # oracle fallback accepted
    assert bool(got[1]) is False         # invalid stays rejected
    assert count == 1
