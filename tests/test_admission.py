"""Tenant-fair scheduling + admission control (ISSUE 15 / r20).

Pins, layer by layer:

- the DRR scheduler twins: ``cap_tpu/serve/drr.py`` vs the native
  ``cap_drr_*`` probe ABI — IDENTICAL dispatch order over randomized
  multi-tenant interleaves (the cross-chain scheduling contract);
- token-bucket admission arithmetic (burst cap, lazy refill, shed
  scales) and the exact ``checked == admitted + throttled`` equation;
- the ``throttled`` reason class end to end: taxonomy coverage, wire
  round trip, retry-after hint parse;
- the batcher's ``fair=True`` mode dispatching quiet tenants ahead of
  a flooding backlog;
- both serve chains throttling a flooder (and ONLY the flooder) with
  wire pushback, counters exact, verdicts never altered;
- the router's terminal reason-class routing: NO terminal reject —
  ``throttled`` included — may trigger the CPU-oracle fallback, while
  transport failure still does; pushback honor (window, counters);
- ``WorkerPool.resize`` / shed / autoscaler state machine.
"""

import base64
import json
import os
import random
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.errors import ThrottledError
from cap_tpu.obs import decision
from cap_tpu.serve import admission as adm
from cap_tpu.serve import drr
from cap_tpu.serve import protocol
from cap_tpu.serve.batcher import AdaptiveBatcher
from cap_tpu.serve.client import RemoteVerifyError, VerifyClient
from cap_tpu.serve.worker import VerifyWorker
from cap_tpu.fleet.worker_main import StubKeySet


def _b64(obj) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(obj).encode()).rstrip(b"=").decode()


def _token(iss: str, kid: str, sfx: str = "ok") -> str:
    return (_b64({"alg": "ES256", "kid": kid}) + "."
            + _b64({"iss": iss}) + "." + sfx)


def _native_lib():
    try:
        from cap_tpu.serve import native_serve

        lib = native_serve.load()
        return lib if getattr(lib, "cap_sched_ok", False) else None
    except Exception:  # noqa: BLE001 - no compiler on this host
        return None


@pytest.fixture(autouse=True)
def _fresh_recorder():
    telemetry.enable()
    telemetry.active().reset()
    yield
    telemetry.disable()


# ---------------------------------------------------------------------------
# reason class: taxonomy, wire round trip, hint parse
# ---------------------------------------------------------------------------

def test_throttled_reason_registered_and_ordered():
    assert "throttled" in decision.REASON_CLASSES
    # insert-before-internal discipline (internal stays the native
    # fold's out-of-range bucket)
    assert decision.REASON_INDEX[-1] == decision.REASON_INTERNAL
    assert decision.REASON_INDEX[-2] == decision.REASON_THROTTLED
    err = ThrottledError(retry_after_ms=40)
    assert decision.classify(err) == "throttled"
    assert decision.REASON_INDEX[decision.reason_index(err)] \
        == "throttled"
    # wire round trip: the worker's "<Class>: <msg>" payload seen by
    # the router classifies identically
    wire = RemoteVerifyError(f"{type(err).__name__}: {err}")
    assert decision.classify(wire) == "throttled"


def test_retry_after_hint_parse():
    e = ThrottledError(retry_after_ms=250)
    payload = f"ThrottledError: {e}"
    assert protocol.is_throttled_payload(payload)
    assert protocol.retry_after_hint(payload) == 0.25
    assert protocol.retry_after_hint("ThrottledError: no hint") is None
    assert protocol.retry_after_hint(
        "InvalidSignatureError: nope") is None
    assert not protocol.is_throttled_payload("InvalidSignatureError: x")


# ---------------------------------------------------------------------------
# DRR scheduler: python twin semantics + native parity
# ---------------------------------------------------------------------------

def test_drr_weights_share_tokens_proportionally():
    s = drr.DRRScheduler(quantum=10)
    s.set_weight(0, 3)
    s.set_weight(1, 1)
    for i in range(40):
        s.push(0, ("a", i), 10)
        s.push(1, ("b", i), 10)
    order = []
    while True:
        it = s.pop()
        if it is None:
            break
        order.append(it[0])
    # first 24 pops: ~3:1 split (weight 3 earns 30 tokens per visit =
    # 3 requests; weight 1 earns 1)
    head = order[:24]
    assert head.count("a") == 18 and head.count("b") == 6


def test_drr_best_effort_slot_for_unknown_and_none():
    s = drr.DRRScheduler()
    s.push(-5, "x", 1)        # out of range → best-effort
    s.push(999, "y", 1)
    assert s.n == 2
    assert s.pop() == "x" and s.pop() == "y"
    assert drr.sched_slot_for_tokens([]) == drr.SCHED_BE
    assert drr.sched_slot_for_tokens(["no-tenant"]) == drr.SCHED_BE
    t = _token("https://drr-slot.example", "drs")
    slot = drr.sched_slot_for_tokens([t])
    assert 0 <= slot < decision.TENANT_CAP


def test_drr_big_request_accumulates_deficit():
    """A request costing more than one quantum earns credit across
    visits instead of wedging; nothing is ever stranded."""
    s = drr.DRRScheduler(quantum=4)
    s.push(0, "big", 10)      # needs 3 visits of quantum 4
    s.push(1, "small", 1)
    out = []
    while True:
        it = s.pop()
        if it is None:
            break
        out.append(it)
    assert sorted(out) == ["big", "small"]
    assert s.n == 0


@pytest.mark.parametrize("seed", [7, 1234])
def test_drr_dispatch_order_parity_native_vs_python(seed):
    """THE cross-chain pin: a randomized two-tenant (+ best-effort)
    interleave of pushes and pops through the native scheduler probe
    and the python twin must dispatch in IDENTICAL order."""
    lib = _native_lib()
    if lib is None:
        pytest.skip("native scheduler unavailable on this host")
    rng = random.Random(seed)
    quantum = rng.choice([8, 64, 512])
    d = lib.cap_drr_create(quantum)
    try:
        py = drr.DRRScheduler(quantum=quantum)
        wa, wb = rng.randint(1, 5), rng.randint(1, 5)
        lib.cap_drr_set_weight(d, 2, wa)
        py.set_weight(2, wa)
        lib.cap_drr_set_weight(d, 9, wb)
        py.set_weight(9, wb)
        nid = 0
        native_order, py_order = [], []
        for _ in range(400):
            if rng.random() < 0.55 or nid == 0:
                slot = rng.choice([2, 9, drr.SCHED_BE])
                cost = rng.randint(1, 200)
                lib.cap_drr_push(d, slot, cost)
                py.push(slot, nid, cost)
                nid += 1
            else:
                got = lib.cap_drr_pop(d)
                p = py.pop()
                assert (got >= 0) == (p is not None)
                if got >= 0:
                    native_order.append(got)
                    py_order.append(p)
        while True:
            got = lib.cap_drr_pop(d)
            p = py.pop()
            assert (got >= 0) == (p is not None)
            if got < 0:
                break
            native_order.append(got)
            py_order.append(p)
        assert native_order == py_order
        assert len(native_order) == nid
    finally:
        lib.cap_drr_destroy(d)


# ---------------------------------------------------------------------------
# token buckets
# ---------------------------------------------------------------------------

def test_bucket_burst_cap_and_exact_accounting():
    clock = [100.0]
    c = adm.AdmissionController(rate=1.0, burst=4,
                                clock=lambda: clock[0])
    mask, retry = c.check(["t1"] * 6)
    assert mask == [False] * 4 + [True] * 2
    assert retry >= 1
    # another tenant is untouched
    mask2, _ = c.check(["t2"] * 3)
    assert mask2 is None
    # refill: 2 seconds at rate 1 → 2 more tokens for t1
    clock[0] += 2.0
    mask3, _ = c.check(["t1"] * 3)
    assert mask3 == [False, False, True]
    ctr = telemetry.active().counters()
    assert ctr["admission.checked"] == 12
    assert ctr["admission.checked"] == ctr["admission.admitted"] \
        + ctr["admission.throttled"]


def test_bucket_shed_scale_tightens_and_restores():
    clock = [0.0]
    c = adm.AdmissionController(rate=10.0, burst=2,
                                clock=lambda: clock[0])
    c.check(["x"])            # bucket exists (level 1 of 2 left)
    c.set_scale("x", 0.0)     # full shed: no refill at all
    clock[0] += 100.0
    mask, _ = c.check(["x"] * 3)
    assert mask == [False, True, True]   # only the leftover token
    assert c.shed == {"x": 0.0}
    c.set_scale("x", 1.0)
    assert c.shed == {}
    clock[0] += 1.0           # 10 tok/s restored
    mask, _ = c.check(["x"] * 2)
    assert mask is None


# ---------------------------------------------------------------------------
# batcher fair mode
# ---------------------------------------------------------------------------

class _SlowRecordingKeySet:
    def __init__(self, delay_s=0.05):
        self.batches = []
        self.delay_s = delay_s
        self.gate = threading.Event()

    def verify_batch(self, tokens):
        self.gate.wait(5.0)
        self.batches.append(list(tokens))
        time.sleep(self.delay_s)
        return [{"sub": "x"} for _ in tokens]


def test_batcher_fair_mode_interleaves_tenants():
    """With a flooding tenant's backlog queued ahead of one quiet
    submission, fair mode dispatches the quiet tenant LONG before the
    flood drains; FIFO would put it last. (Everything queues inside
    one flush window — max_wait 300 ms — so the flush sequence IS the
    DRR pop order.)"""
    ks = _SlowRecordingKeySet(delay_s=0.0)
    ks.gate.set()
    flood_tok = _token("https://bf-flood.example", "bff")
    quiet_tok = _token("https://bf-quiet.example", "bfq")
    b = AdaptiveBatcher(ks, target_batch=10 ** 9, max_wait_ms=300.0,
                        max_batch=64, max_queued_tokens=10 ** 6,
                        fair=True, drr_quantum=64)
    try:
        assert b.fair
        pends = [b.submit_nowait([flood_tok] * 64) for _ in range(8)]
        quiet = b.submit_nowait([quiet_tok] * 8)
        quiet.event.wait(10.0)
        assert quiet.results is not None
        for p in pends:
            p.event.wait(10.0)
        flat_order = [t for batch in ks.batches for t in batch]
        quiet_at = flat_order.index(quiet_tok)
        # DRR gave the quiet tenant a slot within the first couple of
        # quanta instead of behind 512 flood tokens
        assert quiet_at < 256, f"quiet dispatched at {quiet_at}"
    finally:
        b.close(deadline_s=10)


def test_batcher_fifo_unchanged_by_default():
    ks = _SlowRecordingKeySet(delay_s=0.0)
    ks.gate.set()
    b = AdaptiveBatcher(ks, target_batch=4, max_wait_ms=1.0)
    try:
        assert not b.fair
        out = b.submit(["a.b.ok", "c.d.ok"])
        assert len(out) == 2
    finally:
        b.close(deadline_s=10)


# ---------------------------------------------------------------------------
# serve chains end to end
# ---------------------------------------------------------------------------

def _drive_admission(worker):
    host, port = worker.address
    cl = VerifyClient(host, port)
    try:
        flood = _token("https://e2e-flood.example", "e2f")
        quiet = _token("https://e2e-quiet.example", "e2q")
        out_flood = cl.verify_batch([flood] * 12)
        out_quiet = cl.verify_batch([quiet] * 3)
        out_flood2 = cl.verify_batch([flood] * 4)
        return out_flood + out_flood2, out_quiet
    finally:
        cl.close()


def _check_admission_outcomes(worker, out_flood, out_quiet):
    thr = [r for r in out_flood if isinstance(r, Exception)]
    assert len(thr) == 8, [str(r)[:40] for r in out_flood]  # 16 - burst 8
    for r in thr:
        assert str(r).startswith("ThrottledError"), str(r)
        assert protocol.retry_after_hint(str(r)) is not None
    # admitted flood tokens verified normally (admission never
    # alters a verdict)
    assert sum(not isinstance(r, Exception) for r in out_flood) == 8
    assert all(not isinstance(r, Exception) for r in out_quiet)
    time.sleep(0.15)
    c = worker.stats()["counters"]
    assert c.get("admission.checked") == 19
    assert c.get("admission.checked") == \
        c.get("admission.admitted", 0) + c.get("admission.throttled", 0)
    assert c.get("decision.serve.reject.throttled") == 8
    h_flood = decision.issuer_hash("https://e2e-flood.example")
    h_quiet = decision.issuer_hash("https://e2e-quiet.example")
    assert c.get(
        f"decision.serve.tenant.{h_flood}.reject.throttled") == 8
    assert not c.get(
        f"decision.serve.tenant.{h_quiet}.reject.throttled")


def test_python_chain_admission_end_to_end():
    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=False,
                     fair=True, admit_rate=1e-4, admit_burst=8)
    try:
        assert w.serve_chain == "python"
        out_flood, out_quiet = _drive_admission(w)
        _check_admission_outcomes(w, out_flood, out_quiet)
    finally:
        w.close(deadline_s=10)


def test_native_chain_admission_end_to_end():
    if _native_lib() is None:
        pytest.skip("native scheduler unavailable on this host")
    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=True,
                     fair=True, admit_rate=1e-4, admit_burst=8)
    try:
        if w.serve_chain != "native":
            pytest.skip("native chain unavailable")
        assert w._native.fair_native and w._native.adm_native
        out_flood, out_quiet = _drive_admission(w)
        _check_admission_outcomes(w, out_flood, out_quiet)
    finally:
        w.close(deadline_s=10)


def test_admission_off_means_byte_identical_behavior():
    """With admission off (the default) no throttled entry can exist
    — frames stay exactly the pre-r20 bytes (the golden vectors pin
    the encodings; this pins the serve path)."""
    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=False)
    try:
        out_flood, out_quiet = _drive_admission(w)
        assert all(not isinstance(r, Exception) for r in out_flood)
        c = w.stats()["counters"]
        assert "admission.checked" not in c
    finally:
        w.close(deadline_s=10)


def test_admission_op_shed_via_peer_fill():
    """The pool's shed lever: op=admission on the control pair scales
    one tenant's bucket; scale 0 starves it outright."""
    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=False,
                     admit_rate=1000.0, admit_burst=200.0)
    try:
        quiet = _token("https://shed-victim.example", "shv")
        h = decision.issuer_hash("https://shed-victim.example")
        host, port = w.address
        import socket as _socket

        with _socket.create_connection((host, port), timeout=5) as s:
            protocol.send_peer_fill(
                s, {"op": "admission", "scale": {h: 0.0}})
            ftype, entries = protocol.FrameReader(s).recv_frame()
        assert ftype == protocol.T_PEER_ACK and entries[0][0] == 0
        ack = json.loads(entries[0][1])
        assert ack["applied"] == 1 and ack["shed"] == {h: 0.0}
        assert w.shed_state() == {h: 0.0}
        cl = VerifyClient(host, port)
        try:
            out = cl.verify_batch([quiet] * 300)
            thr = sum(1 for r in out if isinstance(r, Exception)
                      and str(r).startswith("ThrottledError"))
            # burst 200 drains, then the scaled-to-zero rate refills
            # nothing: the tail throttles
            assert thr >= 90
        finally:
            cl.close()
        # restore
        assert w.apply_admission({"scale": {h: 1.0}})["shed"] == {}
    finally:
        w.close(deadline_s=10)


def test_admission_op_requires_armed_plane():
    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=False)
    try:
        with pytest.raises(TypeError):
            w.apply_admission({"scale": {"ab": 0.5}})
    finally:
        w.close(deadline_s=10)


# ---------------------------------------------------------------------------
# router: terminal reason-class routing + pushback honor
# ---------------------------------------------------------------------------

class _RecordingFallback:
    def __init__(self):
        self.calls = 0

    def verify_batch(self, tokens):
        self.calls += 1
        return [{"sub": "oracle"} for _ in tokens]


class _RejectingKeySet:
    """Engine that rejects every token with one fixed exception."""

    def __init__(self, err):
        self.err = err

    def verify_batch(self, tokens):
        return [self.err for _ in tokens]


def _terminal_error_for(reason):
    from cap_tpu import errors as E

    by_reason = {
        "malformed": E.MalformedTokenError(),
        "not_signed": E.TokenNotSignedError(),
        "bad_signature": E.InvalidSignatureError(),
        "unknown_kid": E.UnknownKeyIDError(),
        "unsupported_alg": E.UnsupportedAlgError(),
        "expired": E.ExpiredTokenError(),
        "invalid_claims": E.InvalidAudienceError(),
        "jwks_error": E.InvalidJWKSError(),
        "oidc_flow": E.InvalidFlowError(),
        "transport": E.CapError("worker-side transport-class reject"),
        "throttled": ThrottledError(retry_after_ms=30),
        "internal": E.NotFoundError(),
    }
    return by_reason[reason]


@pytest.mark.parametrize("reason", list(decision.REASON_INDEX))
def test_router_terminal_reason_routing(reason):
    """EVERY terminal reason — throttled included — is a VERDICT, not
    a transport failure: the router returns it and must never invoke
    the CPU-oracle fallback for it (re-verifying a throttled token on
    the oracle would defeat admission; re-verifying any reject would
    just re-reject)."""
    from cap_tpu.fleet import FleetClient

    err = _terminal_error_for(reason)
    if reason == "transport":
        # a worker-side reject whose MESSAGE classifies transport-ish
        # still crosses as a per-token verdict
        err = ThrottledError() if False else err
    w = VerifyWorker(_RejectingKeySet(err), obs_port=None,
                     serve_native=False, raw_claims=False,
                     vcache=False)
    fb = _RecordingFallback()
    try:
        cl = FleetClient([w.address], fallback=fb, rr_seed=0,
                         attempt_timeout=5.0)
        out = cl.verify_batch(["x.y.z"] * 2)
        assert fb.calls == 0, \
            f"terminal reason {reason} hit the CPU-oracle fallback"
        for r in out:
            assert isinstance(r, Exception)
            want = decision.classify(err)
            assert decision.classify(r) == want
    finally:
        w.close(deadline_s=10)


def test_router_transport_failure_still_falls_back():
    from cap_tpu.fleet import FleetClient

    fb = _RecordingFallback()
    # no listener on this endpoint → genuine transport failure
    cl = FleetClient([("127.0.0.1", 9)], fallback=fb, rr_seed=0,
                     attempt_timeout=0.3, total_deadline=2.0,
                     max_rounds=1, backoff_base=0.01)
    out = cl.verify_batch(["x.y.ok"])
    assert fb.calls == 1
    assert out[0]["sub"] == "oracle"


def test_router_pushback_window_and_counters():
    from cap_tpu.fleet import FleetClient

    w = VerifyWorker(StubKeySet(), obs_port=None, serve_native=False,
                     admit_rate=1e-4, admit_burst=2)
    try:
        flood = _token("https://pb-flood.example", "pbf")
        cl = FleetClient([w.address], fallback=_RecordingFallback(),
                         rr_seed=0)
        out = cl.verify_batch([flood] * 6)
        thr = [r for r in out if isinstance(r, Exception)]
        assert len(thr) == 4
        st = cl.pushback_state()
        assert st["active_s"] > 0
        assert st["retry_after_s"] is not None
        c = telemetry.active().counters()
        assert c.get("fleet.throttled_tokens") == 4
        # next routed batch waits (bounded) inside the window
        cl.verify_batch([flood] * 1)
        c = telemetry.active().counters()
        assert c.get("fleet.pushback_waits", 0) >= 1
    finally:
        w.close(deadline_s=10)


def test_router_all_throttled_earns_no_breaker_credit():
    from cap_tpu.fleet import FleetClient

    results = [RemoteVerifyError(
        "ThrottledError: tenant over admission budget "
        "(retry_after_ms=10)")]
    assert FleetClient._all_throttled(results)
    assert not FleetClient._all_throttled(
        results + [{"sub": "ok"}])
    assert not FleetClient._all_throttled([])


# ---------------------------------------------------------------------------
# pool resize + autoscaler state machine
# ---------------------------------------------------------------------------

def test_pool_resize_and_shed_events():
    from cap_tpu.fleet import WorkerPool

    pool = WorkerPool(1, keyset_spec="stub", ping_interval=0.3,
                      env_extra={"CAP_SERVE_ADMIT_RATE": "1000"})
    try:
        assert pool.wait_all_ready(30)
        assert pool.size() == 1
        pool.resize(2, reason="test")
        assert pool.wait_all_ready(30)
        assert pool.size() == 2 and len(pool.endpoints()) == 2
        pool.resize(1, reason="test")
        assert pool.size() == 1
        # regrow reuses the retired slot
        pool.resize(2, reason="test")
        assert pool.wait_all_ready(30)
        assert sorted(pool.endpoints()) == [0, 1]
        acks = pool.shed_tenant("deadbeef0123", 0.25)
        assert all(acks.values())
        kinds = [e["kind"] for e in pool.resize_events()]
        assert kinds == ["up", "down", "up", "shed"]
        ev = pool.resize_events()[-1]
        assert ev["tenant"] == "deadbeef0123"
        c = telemetry.active().counters()
        assert c.get("fleet.resize.up") == 2
        assert c.get("fleet.resize.down") == 1
        assert c.get("fleet.resize.shed") == 1
        assert c.get("fleet.admission_pushes") == 1
        with pytest.raises(Exception):
            pool.resize(0)
    finally:
        pool.close()


def test_autoscaler_state_machine():
    from cap_tpu.fleet import PoolAutoscaler

    class _FakePool:
        def __init__(self):
            self.n = 1
            self.sheds = []

        def size(self):
            return self.n

        def resize(self, n, reason=""):
            self.n = n

        def shed_tenant(self, t, s, reason=""):
            self.sheds.append((t, s))

        def stats_merged(self):
            raise AssertionError("tick() was given merged explicitly")

    pool = _FakePool()
    sc = PoolAutoscaler(pool, min_workers=1, max_workers=2,
                        high_queue_per_worker=100, sustain_ticks=2,
                        quiet_ticks=2, interval_s=0.0)
    hot = {"aggregate": {"queued_tokens": 1000, "counters": {},
                         "snapshot": {}}, "workers": {}}
    calm = {"aggregate": {"queued_tokens": 0, "counters": {},
                          "snapshot": {}}, "workers": {}}
    t = [0.0]

    def tick(m):
        t[0] += 1.0
        return sc.tick(now=t[0], merged=m)

    assert tick(hot) is None           # 1 hot look: not sustained
    assert tick(hot) == "up"           # sustained → scale up
    assert pool.n == 2
    # at max size + a breaching tenant → shed the flooder
    h_flood = decision.issuer_hash("https://as-flood.example")
    burn = {"aggregate": {
        "queued_tokens": 1000,
        "counters": {
            f"decision.serve.tenant.{h_flood}.tokens": 100,
            f"decision.serve.tenant.{h_flood}.reject": 90,
        },
        "snapshot": {"counters": {
            f"decision.serve.tenant.{h_flood}.tokens": 100,
            f"decision.serve.tenant.{h_flood}.reject": 90,
        }}}, "workers": {}}
    assert tick(burn) is None
    assert tick(burn) == "shed"
    assert pool.sheds == [(h_flood, sc.shed_scale)]
    # calm: unshed first, then scale down
    assert tick(calm) is None
    assert tick(calm) == "unshed"
    assert pool.sheds[-1] == (h_flood, 1.0)
    assert tick(calm) is None
    assert tick(calm) == "down"
    assert pool.n == 1


def test_autoscaler_never_sheds_quiet_tenants():
    from cap_tpu.fleet import PoolAutoscaler

    class _FakePool:
        def size(self):
            return 1

        def shed_tenant(self, *a, **k):
            raise AssertionError("quiet tenant shed")

        def resize(self, *a, **k):
            raise AssertionError("no resize expected")

    sc = PoolAutoscaler(_FakePool(), min_workers=1, max_workers=1,
                        high_queue_per_worker=1, sustain_ticks=1,
                        quiet_ticks=10 ** 9, interval_s=0.0)
    h_quiet = decision.issuer_hash("https://as-quiet.example")
    merged = {"aggregate": {
        "queued_tokens": 1000,
        "counters": {
            f"decision.serve.tenant.{h_quiet}.tokens": 100,
            f"decision.serve.tenant.{h_quiet}.accept": 100,
        },
        "snapshot": {"counters": {
            f"decision.serve.tenant.{h_quiet}.tokens": 100,
            f"decision.serve.tenant.{h_quiet}.accept": 100,
        }}}, "workers": {}}
    # pressure without any BURNING tenant: at max size, nothing sheds
    assert sc.tick(now=1.0, merged=merged) is None


# ---------------------------------------------------------------------------
# capstat ledger: admission columns render
# ---------------------------------------------------------------------------

def test_capstat_ledger_admission_columns():
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools import capstat

    h = decision.issuer_hash("https://ledger-adm.example")
    merged = {
        "counters": {
            "tenant.lookups": 10, "tenant.attributed": 10,
            "admission.checked": 10, "admission.admitted": 6,
            "admission.throttled": 4,
            f"decision.serve.tenant.{h}.tokens": 10,
            f"decision.serve.tenant.{h}.accept": 6,
            f"decision.serve.tenant.{h}.reject": 4,
            f"decision.serve.tenant.{h}.reject.throttled": 4,
            "fleet.resize.up": 1,
        },
        "gauges": {"fleet.pool_size": 2.0},
        "series": {},
    }
    extras = {"admission.active": 1.0, "admission.rate": 100.0,
              "admission.burst": 200.0,
              f"admission.tenant.{h}.fill": 3.5,
              f"admission.tenant.{h}.shed_scale": 0.25,
              f"admission.tenant.{h}.weight": 2.0}
    client = {"pool_size": 2, "resize_events": [
        {"kind": "up", "from": 1, "to": 2, "reason": "queue-pressure"}]}
    out = capstat.render_tenants(merged, client=client, extras=extras)
    assert "admission: checked=10 admitted=6 throttled=4 [EXACT]" \
        in out
    assert "pool:" in out and "up=1" in out
    assert "resize[up] 1→2" in out
    assert h in out
    assert "0.25" in out       # shed scale column
    # the throttled column renders the per-tenant count
    assert "       4" in out
