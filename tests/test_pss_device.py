"""Device-side EMSA-PSS-VERIFY + batched SHA-256 conformance.

The PS* packed path replaces the host MGF1/H' tail with on-device
hashing (cap_tpu/tpu/sha256.py + rsa._pss_verify_device): these tests
pin bit-exactness against hashlib and against the host PSS oracle
(pss_check_em), then the full PS256 keyset path against the CPU
verify oracle — rejections included.
"""

import hashlib

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


import jax
import jax.numpy as jnp

from cap_tpu import testing as captest
from cap_tpu.jwt import algs
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.keyset import StaticKeySet
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
from cap_tpu.tpu import rsa as R
from cap_tpu.tpu import sha256 as S


def test_sha256_fixed_matches_hashlib():
    rng = np.random.default_rng(5)
    for length in (4, 36, 55):
        msgs = rng.integers(0, 256, (32, length), dtype=np.uint8)
        got = np.asarray(jax.jit(S.sha256_fixed)(jnp.asarray(msgs)))
        for i in range(len(msgs)):
            assert got[i].tobytes() == \
                hashlib.sha256(msgs[i].tobytes()).digest()


def test_sha256_var_matches_hashlib():
    rng = np.random.default_rng(6)
    max_len = 262
    lens = np.concatenate([
        rng.integers(0, max_len + 1, 24),
        [0, 1, 55, 56, 63, 64, 119, 120, 127, 128, max_len],
    ]).astype(np.int64)
    msgs = np.zeros((len(lens), max_len), np.uint8)
    for i, ln in enumerate(lens):
        msgs[i, :ln] = rng.integers(0, 256, ln, dtype=np.uint8)
    got = np.asarray(jax.jit(
        lambda m, ln: S.sha256_var(m, ln, max_len))(
            jnp.asarray(msgs), jnp.asarray(lens)))
    for i, ln in enumerate(lens):
        assert got[i].tobytes() == \
            hashlib.sha256(msgs[i, :ln].tobytes()).digest(), int(ln)


def _mk_valid_em(rng, width, h_len, mhash, mod_bits, salt_len):
    em_bits = mod_bits - 1
    em_len = (em_bits + 7) // 8
    db_len = em_len - h_len - 1
    if salt_len > db_len - 1 or salt_len < 0:
        return None
    salt = bytes(rng.integers(0, 256, salt_len, dtype=np.uint8)) \
        if salt_len else b""
    h = hashlib.sha256(b"\x00" * 8 + mhash + salt).digest()
    db = b"\x00" * (db_len - salt_len - 1) + b"\x01" + salt
    mask = R._mgf1(h, db_len, "sha256")
    masked = bytes(a ^ b for a, b in zip(db, mask))
    unused = 8 * em_len - em_bits
    if unused:
        masked = bytes([masked[0] & (0xFF >> unused)]) + masked[1:]
    return (b"\x00" * (width - em_len)) + masked + h + b"\xbc"


def test_pss_device_matches_host_oracle():
    """Structural fuzz: every verdict equals pss_check_em's."""
    rng = np.random.default_rng(9)
    k, h_len = 17, 32
    width = 2 * k
    mhash = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    ems, mbs = [], []

    def add(em, mb):
        ems.append(np.frombuffer(em[:width].ljust(width, b"\x00"),
                                 np.uint8))
        mbs.append(mb)

    for mb in (width * 8 - 7, width * 8 - 4, width * 8, 270):
        em_len = (mb - 1 + 7) // 8
        db_len = em_len - h_len - 1
        for sl in {0, 1, min(32, db_len - 1), db_len - 1}:
            em = _mk_valid_em(rng, width, h_len, mhash, mb, sl)
            if em is None:
                continue
            add(em, mb)
            for mut in (lambda b: b.__setitem__(-1, 0xBB),       # trailer
                        lambda b: b.__setitem__(-2, b[-2] ^ 1),  # H bit
                        lambda b: b.__setitem__(
                            width - em_len, b[width - em_len] ^ 0x80)):
                t = bytearray(em)
                mut(t)
                add(bytes(t), mb)
        add(b"\x00" * width, mb)                 # no separator
    for _ in range(40):
        add(bytes(rng.integers(0, 256, width, dtype=np.uint8)), 270)

    em_mat = np.stack(ems)
    mb_arr = np.asarray(mbs, np.int32)
    mh_mat = np.tile(np.frombuffer(mhash, np.uint8), (len(ems), 1))
    fn = jax.jit(lambda e, m, b: R._pss_verify_device(
        e, m, b, width=width, hash_name="sha256"))
    got = np.asarray(fn(jnp.asarray(em_mat), jnp.asarray(mh_mat),
                        jnp.asarray(mb_arr)))
    for i in range(len(ems)):
        want = R.pss_check_em(em_mat[i].tobytes(), mhash,
                              int(mb_arr[i]) - 1, "sha256")
        assert bool(got[i]) == want, (i, int(mb_arr[i]))


def test_ps256_keyset_parity():
    """PS256 through the packed device path vs the CPU oracle."""
    jwks, privs, pubs = [], [], []
    for i in range(2):
        priv, pub = captest.generate_keys(algs.PS256, rsa_bits=1024)
        jwks.append(JWK(pub, kid=f"p{i}"))
        privs.append(priv)
        pubs.append(pub)
    toks = [captest.sign_jwt(privs[j % 2], algs.PS256,
                             captest.default_claims(sub=f"u{j}"),
                             kid=f"p{j % 2}")
            for j in range(40)]
    toks.append(toks[0][:-8] + "AAAAAAAA")        # tampered signature
    toks.append(toks[1].replace(".", ".x", 1))    # malformed
    ks = TPUBatchKeySet(jwks)
    oracle = StaticKeySet(pubs)
    out = ks.verify_batch(toks)
    for i, tk in enumerate(toks):
        try:
            oracle.verify_signature(tk)
            want = True
        except Exception:  # noqa: BLE001
            want = False
        assert (not isinstance(out[i], Exception)) == want, (i, out[i])


@pytest.mark.heavy
def test_ps256_keyset_parity_rns(monkeypatch):
    """Same contract on the RNS/MXU engine, mixed 2048/2040 moduli
    (same limb class, different emLen — the per-token offset math)."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    jwks, privs, pubs = [], [], []
    for i, bits in enumerate([2048, 2040]):
        priv, pub = captest.generate_keys(algs.PS256, rsa_bits=bits)
        jwks.append(JWK(pub, kid=f"p{i}"))
        privs.append(priv)
        pubs.append(pub)
    toks = [captest.sign_jwt(privs[j % 2], algs.PS256,
                             captest.default_claims(sub=f"u{j}"),
                             kid=f"p{j % 2}")
            for j in range(24)]
    toks.append(toks[0][:-8] + "AAAAAAAA")
    ks = TPUBatchKeySet(jwks)
    oracle = StaticKeySet(pubs)
    out = ks.verify_batch(toks)
    for i, tk in enumerate(toks):
        try:
            oracle.verify_signature(tk)
            want = True
        except Exception:  # noqa: BLE001
            want = False
        assert (not isinstance(out[i], Exception)) == want, (i, out[i])


def test_sha512_family_matches_hashlib():
    from cap_tpu.tpu import sha512 as S5

    rng = np.random.default_rng(7)
    for name, fixed, var in (("sha512", S5.sha512_fixed, S5.sha512_var),
                             ("sha384", S5.sha384_fixed, S5.sha384_var)):
        for length in (4, 68, 111):
            msgs = rng.integers(0, 256, (16, length), dtype=np.uint8)
            got = np.asarray(jax.jit(fixed)(jnp.asarray(msgs)))
            for i in range(len(msgs)):
                assert got[i].tobytes() == \
                    hashlib.new(name, msgs[i].tobytes()).digest(), \
                    (name, length, i)
        max_len = 300
        lens = np.concatenate([
            rng.integers(0, max_len + 1, 12),
            [0, 111, 112, 127, 128, 239, 240, max_len],
        ]).astype(np.int64)
        msgs = np.zeros((len(lens), max_len), np.uint8)
        for i, ln in enumerate(lens):
            msgs[i, :ln] = rng.integers(0, 256, ln, dtype=np.uint8)
        got = np.asarray(jax.jit(
            lambda m, ln: var(m, ln, max_len))(
                jnp.asarray(msgs), jnp.asarray(lens)))
        for i, ln in enumerate(lens):
            assert got[i].tobytes() == \
                hashlib.new(name, msgs[i, :ln].tobytes()).digest(), \
                (name, int(ln))


def test_ps384_keyset_parity():
    """PS384 through the packed device path (SHA-384 u32-pair engine)."""
    priv, pub = captest.generate_keys(algs.PS384, rsa_bits=1024)
    toks = [captest.sign_jwt(priv, algs.PS384,
                             captest.default_claims(sub=f"u{j}"),
                             kid="p0")
            for j in range(16)]
    toks.append(toks[0][:-8] + "AAAAAAAA")
    ks = TPUBatchKeySet([JWK(pub, kid="p0")])
    oracle = StaticKeySet([pub])
    out = ks.verify_batch(toks)
    for i, tk in enumerate(toks):
        try:
            oracle.verify_signature(tk)
            want = True
        except Exception:  # noqa: BLE001
            want = False
        assert (not isinstance(out[i], Exception)) == want, (i, out[i])


@pytest.mark.heavy
def test_ps512_keyset_parity():
    """PS512 (needs emLen ≥ 2·64 + 2 → ≥1536-bit keys)."""
    priv, pub = captest.generate_keys(algs.PS512, rsa_bits=1536)
    toks = [captest.sign_jwt(priv, algs.PS512,
                             captest.default_claims(sub=f"u{j}"),
                             kid="p0")
            for j in range(8)]
    toks.append(toks[0][:-8] + "AAAAAAAA")
    ks = TPUBatchKeySet([JWK(pub, kid="p0")])
    oracle = StaticKeySet([pub])
    out = ks.verify_batch(toks)
    for i, tk in enumerate(toks):
        try:
            oracle.verify_signature(tk)
            want = True
        except Exception:  # noqa: BLE001
            want = False
        assert (not isinstance(out[i], Exception)) == want, (i, out[i])
