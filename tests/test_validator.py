"""Validator claims-engine conformance (reference: jwt/jwt_test.go tables)."""

import time

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.errors import (
    ExpiredTokenError,
    InvalidAudienceError,
    InvalidIssuedAtError,
    InvalidIssuerError,
    InvalidNotBeforeError,
    InvalidParameterError,
    InvalidSignatureError,
    MissingClaimError,
    NilParameterError,
    UnsupportedAlgError,
)
from cap_tpu.jwt import Expected, StaticKeySet, Validator
from cap_tpu.jwt.validator import validate_audience


@pytest.fixture(scope="module")
def rs_keys():
    return captest.generate_keys("RS256")


@pytest.fixture(scope="module")
def es_keys():
    return captest.generate_keys("ES256")


def _validator(pub):
    return Validator(StaticKeySet([pub]))


NOW = 1_700_000_000.0


def _expected(**kw):
    kw.setdefault("now", lambda: NOW)
    return Expected(**kw)


def _claims(**kw):
    base = {"iss": "https://issuer/", "sub": "alice", "aud": ["aud1"],
            "iat": int(NOW) - 10, "nbf": int(NOW) - 10, "exp": int(NOW) + 300}
    base.update(kw)
    return {k: v for k, v in base.items() if v is not None}


def test_requires_keyset():
    with pytest.raises(NilParameterError):
        Validator(None)


def test_valid_roundtrip(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims())
    claims = _validator(pub).validate(token, _expected(
        issuer="https://issuer/", subject="alice", audiences=["aud1"],
        signing_algorithms=["RS256"],
    ))
    assert claims["sub"] == "alice"


def test_default_alg_is_rs256(rs_keys, es_keys):
    rs_priv, rs_pub = rs_keys
    es_priv, es_pub = es_keys
    token = captest.sign_jwt(rs_priv, "RS256", _claims())
    # No signing_algorithms given → RS256 expected by default.
    assert _validator(rs_pub).validate(token, _expected())
    es_token = captest.sign_jwt(es_priv, "ES256", _claims())
    with pytest.raises(UnsupportedAlgError):
        _validator(es_pub).validate(es_token, _expected())


def test_unexpected_alg_rejected(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims())
    with pytest.raises(UnsupportedAlgError):
        _validator(pub).validate(token, _expected(signing_algorithms=["ES256"]))
    with pytest.raises(UnsupportedAlgError):
        _validator(pub).validate(token, _expected(signing_algorithms=["none"]))


def test_bad_signature_rejected(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims())
    with pytest.raises(InvalidSignatureError):
        _validator(pub).validate(token[:-6] + "AAAAAA", _expected())


def test_wrong_issuer_subject_jti(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims(jti="id-1"))
    v = _validator(pub)
    assert v.validate(token, _expected(issuer="https://issuer/", id="id-1"))
    with pytest.raises(InvalidIssuerError):
        v.validate(token, _expected(issuer="https://other/"))
    with pytest.raises(InvalidParameterError):
        v.validate(token, _expected(subject="bob"))
    with pytest.raises(InvalidParameterError):
        v.validate(token, _expected(id="id-2"))


def test_audience_matching(rs_keys):
    priv, pub = rs_keys
    v = _validator(pub)
    token = captest.sign_jwt(priv, "RS256", _claims(aud=["a", "b"]))
    assert v.validate(token, _expected(audiences=["b", "z"]))
    with pytest.raises(InvalidAudienceError):
        v.validate(token, _expected(audiences=["z"]))
    # string aud claim form
    token2 = captest.sign_jwt(priv, "RS256", _claims(aud="solo"))
    assert v.validate(token2, _expected(audiences=["solo"]))


def test_validate_audience_empty_expected_skips():
    validate_audience([], ["anything"])
    validate_audience([], [])


def test_expired_token(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims(exp=int(NOW) - 3600))
    with pytest.raises(ExpiredTokenError):
        _validator(pub).validate(token, _expected())


def test_exp_within_clock_skew_ok(rs_keys):
    priv, pub = rs_keys
    # expired 30s ago but default 60s clock skew applies
    token = captest.sign_jwt(priv, "RS256", _claims(exp=int(NOW) - 30))
    assert _validator(pub).validate(token, _expected())
    with pytest.raises(ExpiredTokenError):
        _validator(pub).validate(token, _expected(clock_skew_leeway=-1))


def test_not_yet_valid(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(priv, "RS256", _claims(nbf=int(NOW) + 3600))
    with pytest.raises(InvalidNotBeforeError):
        _validator(pub).validate(token, _expected())


def test_issued_in_future(rs_keys):
    priv, pub = rs_keys
    # nbf must be valid on its own: with nbf absent it would default to the
    # (future) iat and the nbf check would fire first, masking the iat check.
    token = captest.sign_jwt(
        priv, "RS256", _claims(iat=int(NOW) + 3600, nbf=int(NOW) - 10)
    )
    with pytest.raises(InvalidIssuedAtError):
        _validator(pub).validate(token, _expected())


def test_no_time_claims_rejected(rs_keys):
    priv, pub = rs_keys
    token = captest.sign_jwt(
        priv, "RS256", _claims(iat=None, nbf=None, exp=None)
    )
    with pytest.raises(MissingClaimError):
        _validator(pub).validate(token, _expected())


def test_missing_exp_defaults_from_iat_plus_leeway(rs_keys):
    priv, pub = rs_keys
    # iat 100s ago, no exp → exp defaults to iat + 150s leeway → still valid
    token = captest.sign_jwt(
        priv, "RS256", _claims(iat=int(NOW) - 100, nbf=None, exp=None)
    )
    assert _validator(pub).validate(token, _expected())
    # with leeway suppressed (negative) → exp=iat → expired (beyond 60s skew)
    with pytest.raises(ExpiredTokenError):
        _validator(pub).validate(token, _expected(expiration_leeway=-1))


def test_missing_nbf_defaults_from_exp_minus_leeway(rs_keys):
    priv, pub = rs_keys
    # Only exp set, 400s out: nbf defaults to exp-150 → token not yet valid.
    token = captest.sign_jwt(
        priv, "RS256", _claims(iat=None, nbf=None, exp=int(NOW) + 400)
    )
    with pytest.raises(InvalidNotBeforeError):
        _validator(pub).validate(token, _expected())
    # Larger leeway covers it.
    assert _validator(pub).validate(token, _expected(not_before_leeway=500))


def test_real_time_default_now(rs_keys):
    priv, pub = rs_keys
    t = time.time()
    token = captest.sign_jwt(
        priv, "RS256",
        {"iss": "i", "iat": int(t), "nbf": int(t), "exp": int(t) + 60},
    )
    assert _validator(pub).validate(token, Expected())


def test_validate_batch_mixed(rs_keys):
    priv, pub = rs_keys
    good = captest.sign_jwt(priv, "RS256", _claims())
    expired = captest.sign_jwt(priv, "RS256", _claims(exp=int(NOW) - 3600))
    tampered = good[:-6] + "AAAAAA"
    v = _validator(pub)
    results = v.validate_batch([good, expired, tampered], _expected())
    assert results[0]["sub"] == "alice"
    assert isinstance(results[1], ExpiredTokenError)
    assert isinstance(results[2], InvalidSignatureError)
