"""Keyplane unit layer: sources, refresher, cooldowns, KEYS frames.

Everything here is crypto-free (sources and the refresher operate on
raw JWKS documents; JSONWebKeySet's cooldown is exercised through
stubbed jwk/verify modules so the DoS guard is enforced in every
environment). The crypto-backed swap tests for ``TPUBatchKeySet`` are
gated like the rest of the classic suites.
"""

import io
import json
import sys
import threading
import time
import types

import pytest

from cap_tpu import keyplane, telemetry
from cap_tpu.errors import (
    InvalidIssuerError,
    InvalidJWKSError,
    UnknownKeyIDError,
)
from cap_tpu.keyplane import (
    OIDCDiscoverySource,
    RemoteJWKSSource,
    Refresher,
    StaticFileSource,
    canonical_digest,
    source_for_spec,
)
from cap_tpu.serve import protocol
from cap_tpu.utils import http as caphttp

try:
    import cryptography  # noqa: F401

    _HAVE_CRYPTO = True
except ImportError:
    _HAVE_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAVE_CRYPTO, reason="cryptography package not installed")


def _jwks(*kids):
    return {"keys": [{"kty": "RSA", "kid": k, "n": "AQAB", "e": "AQAB"}
                     for k in kids]}


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

def test_source_for_spec_kinds(tmp_path):
    p = tmp_path / "jwks.json"
    p.write_text(json.dumps(_jwks("a")))
    assert isinstance(source_for_spec(f"jwks:{p}"), StaticFileSource)
    assert isinstance(source_for_spec("jwks-url:http://x/jwks"),
                      RemoteJWKSSource)
    assert isinstance(source_for_spec("oidc:https://idp.example"),
                      OIDCDiscoverySource)
    with pytest.raises(ValueError, match="unknown key source"):
        source_for_spec("nope:x")


def test_file_source_fetch_and_change_detection(tmp_path):
    p = tmp_path / "jwks.json"
    p.write_text(json.dumps(_jwks("a")))
    src = StaticFileSource(str(p))
    doc1, dig1 = src.fetch()
    assert {k["kid"] for k in doc1["keys"]} == {"a"}
    # Whitespace-only rewrite: same canonical digest (not a rotation).
    p.write_text(json.dumps(_jwks("a"), indent=3))
    _, dig2 = src.fetch()
    assert dig2 == dig1
    p.write_text(json.dumps(_jwks("b")))
    _, dig3 = src.fetch()
    assert dig3 != dig1


def test_file_source_rejects_garbage(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("not json")
    with pytest.raises(InvalidJWKSError):
        StaticFileSource(str(p)).fetch()
    p.write_text(json.dumps({"nokeys": True}))
    with pytest.raises(InvalidJWKSError, match="no 'keys'"):
        StaticFileSource(str(p)).fetch()


class _CountingJWKSHandler:
    """Tiny HTTP handler serving a JWKS with an ETag; counts hits and
    answers If-None-Match with 304."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler

        state = self

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                if not self.path.endswith("/jwks"):
                    self.send_response(404)
                    self.end_headers()
                    return
                state.hits += 1
                body = json.dumps(state.doc).encode()
                etag = f'"{canonical_digest(state.doc)[:16]}"'
                if state.etags and \
                        self.headers.get("If-None-Match") == etag:
                    state.hits_304 += 1
                    self.send_response(304)
                    self.send_header("ETag", etag)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                if state.etags:
                    self.send_header("ETag", etag)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.handler = H
        self.doc = _jwks("a")
        self.etags = True
        self.hits = 0
        self.hits_304 = 0


@pytest.fixture
def jwks_http():
    from http.server import ThreadingHTTPServer

    state = _CountingJWKSHandler()
    server = ThreadingHTTPServer(("127.0.0.1", 0), state.handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{server.server_address[1]}/jwks"
    yield state, url
    server.shutdown()


def test_http_get_conditional_etag_reuses_body(jwks_http):
    state, url = jwks_http
    s1, b1, h1 = caphttp.get(url, conditional=True)
    assert s1 == 200 and json.loads(b1)["keys"]
    s2, b2, h2 = caphttp.get(url, conditional=True)
    assert (s2, b2) == (200, b1)
    assert h2.get("x-cap-conditional") == "revalidated"
    assert state.hits_304 == 1          # second hit was a 304
    # Plain (non-conditional) get never sends the validator.
    s3, b3, h3 = caphttp.get(url)
    assert s3 == 200 and "x-cap-conditional" not in h3


def test_remote_source_free_refresh_on_unchanged(jwks_http):
    state, url = jwks_http
    src = RemoteJWKSSource(url)
    doc1, dig1 = src.fetch()
    _, dig2 = src.fetch()               # 304 → same digest, no body
    assert dig2 == dig1
    assert state.hits_304 >= 1
    state.doc = _jwks("a", "b")         # rotate at the IdP
    doc3, dig3 = src.fetch()
    assert dig3 != dig1
    assert {k["kid"] for k in doc3["keys"]} == {"a", "b"}


def test_remote_source_error_statuses(jwks_http):
    _, url = jwks_http
    src = RemoteJWKSSource(url + "-missing")
    with pytest.raises(InvalidJWKSError, match="status 404"):
        src.fetch()


@pytest.fixture
def oidc_http():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = types.SimpleNamespace(issuer=None, doc=_jwks("a"),
                                  wrong_issuer=False)

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.endswith("openid-configuration"):
                body = json.dumps({
                    "issuer": state.issuer + ("-evil" if
                                              state.wrong_issuer else ""),
                    "jwks_uri": state.issuer + "/jwks",
                }).encode()
            else:
                body = json.dumps(state.doc).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), H)
    state.issuer = f"http://127.0.0.1:{server.server_address[1]}"
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield state
    server.shutdown()


def test_oidc_source_discovers_jwks_uri(oidc_http):
    src = OIDCDiscoverySource(oidc_http.issuer)
    doc, _ = src.fetch()
    assert {k["kid"] for k in doc["keys"]} == {"a"}


def test_oidc_source_issuer_mismatch_rejected(oidc_http):
    oidc_http.wrong_issuer = True
    with pytest.raises(InvalidIssuerError):
        OIDCDiscoverySource(oidc_http.issuer).fetch()


# ---------------------------------------------------------------------------
# refresher: epochs, singleflight, cooldown, negative cache
# ---------------------------------------------------------------------------

class _FakeSource(keyplane.KeySource):
    def __init__(self, doc, delay_s=0.0):
        self.doc = doc
        self.delay_s = delay_s
        self.fetches = 0
        self.fail = False
        self.description = "fake"

    def fetch(self):
        self.fetches += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise InvalidJWKSError("fake: down")
        return self.doc, canonical_digest(self.doc)


def test_refresher_epoch_bumps_only_on_change():
    src = _FakeSource(_jwks("a"))
    applied = []
    r = Refresher(src, apply=applied.append, miss_cooldown_s=0.0)
    snap1 = r.refresh()
    assert snap1.epoch == 1 and snap1.kids == {"a"}
    snap2 = r.refresh()                 # unchanged → same epoch
    assert snap2.epoch == 1
    src.doc = _jwks("a", "b")
    snap3 = r.refresh()
    assert snap3.epoch == 2 and snap3.kids == {"a", "b"}
    assert [s.epoch for s in applied] == [1, 2]


def test_refresher_failed_fetch_keeps_previous_snapshot():
    src = _FakeSource(_jwks("a"))
    r = Refresher(src)
    r.refresh()
    src.fail = True
    with pytest.raises(InvalidJWKSError):
        r.refresh()
    assert r.epoch == 1 and r.snapshot.kids == {"a"}


def test_refresher_singleflight_coalesces_concurrent_callers():
    src = _FakeSource(_jwks("a"), delay_s=0.2)
    r = Refresher(src)
    snaps = []

    def go():
        snaps.append(r.refresh())

    threads = [threading.Thread(target=go) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert src.fetches == 1             # one leader, seven followers
    assert all(s is not None and s.epoch == 1 for s in snaps)


def test_on_miss_cooldown_and_negative_kid_ttl():
    src = _FakeSource(_jwks("a"))
    r = Refresher(src, miss_cooldown_s=0.15, negative_ttl_s=0.3)
    r.refresh()
    assert src.fetches == 1
    # Miss on an unknown kid → one refresh; kid still absent → negative.
    assert r.on_miss("ghost") is not None
    assert src.fetches == 2
    # Negative cache answers instantly, even after the cooldown lapses.
    time.sleep(0.2)
    assert r.on_miss("ghost") is None
    assert src.fetches == 2
    # A DIFFERENT kid inside the cooldown window is suppressed too.
    assert r.on_miss("other") is None or src.fetches == 3
    # After the negative TTL, the kid is probe-able again.
    time.sleep(0.35)
    fetches_before = src.fetches
    assert r.on_miss("ghost") is not None
    assert src.fetches == fetches_before + 1
    # A rotation that ADDS the kid clears its negative entry (wait out
    # the TTL stamped by the refetch above, plus the miss cooldown).
    src.doc = _jwks("a", "ghost")
    time.sleep(0.35)
    snap = r.on_miss("ghost")
    assert snap is not None and "ghost" in snap.kids


def test_refresher_background_polling():
    src = _FakeSource(_jwks("a"))
    r = Refresher(src, interval_s=0.1, jitter=0.0)
    r.refresh()
    r.start()
    try:
        deadline = time.monotonic() + 5
        while src.fetches < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert src.fetches >= 3, "periodic refresh did not run"
        assert r.epoch == 1             # unchanged doc → stable epoch
    finally:
        r.close()


# ---------------------------------------------------------------------------
# JSONWebKeySet refresh cooldown (the one-line DoS guard, satellite 1)
# ---------------------------------------------------------------------------

def _compact_token(kid):
    from cap_tpu.jwt.jose import b64url_encode

    h = b64url_encode(json.dumps(
        {"alg": "RS256", "kid": kid}).encode())
    p = b64url_encode(json.dumps({"sub": "x"}).encode())
    return f"{h}.{p}.{b64url_encode(b'sig')}"


@pytest.fixture
def stubbed_jwt(monkeypatch):
    """Stub the crypto-backed jwk/verify modules so the keyset's
    cooldown logic runs identically with or without ``cryptography``
    (the cooldown is transport behavior, not signature math)."""
    jwk_mod = types.ModuleType("cap_tpu.jwt.jwk")

    class _J:
        def __init__(self, kid):
            self.kid, self.use, self.key = kid, "sig", object()

    jwk_mod.parse_jwks = lambda doc: [
        _J(k.get("kid")) for k in doc.get("keys", [])]
    verify_mod = types.ModuleType("cap_tpu.jwt.verify")
    verify_mod.key_matches_alg = lambda key, alg: True
    verify_mod.verify_parsed = lambda parsed, key: None  # accept
    monkeypatch.setitem(sys.modules, "cap_tpu.jwt.jwk", jwk_mod)
    monkeypatch.setitem(sys.modules, "cap_tpu.jwt.verify", verify_mod)


def test_jwks_unknown_kid_refetch_respects_cooldown(jwks_http,
                                                    stubbed_jwt):
    from cap_tpu.jwt.keyset import JSONWebKeySet

    state, url = jwks_http
    state.etags = False                 # count full fetches only
    ks = JSONWebKeySet(url, refresh_cooldown_s=30.0)
    assert ks.verify_signature(_compact_token("a"))["sub"] == "x"
    hits_warm = state.hits              # cache fill
    # First unknown kid: ONE refetch, then a provably-unknown verdict.
    with pytest.raises(UnknownKeyIDError):
        ks.verify_signature(_compact_token("ghost"))
    assert state.hits == hits_warm + 1
    # Hammering unknown kids inside the cooldown: ZERO further fetches.
    with telemetry.recording() as rec:
        for i in range(5):
            with pytest.raises(UnknownKeyIDError, match="cooldown"):
                ks.verify_signature(_compact_token(f"ghost-{i}"))
    assert state.hits == hits_warm + 1, "cooldown did not hold"
    assert rec.counters().get("jwks.refresh_suppressed", 0) == 5
    # Known kids are untouched by the cooldown.
    assert ks.verify_signature(_compact_token("a"))["sub"] == "x"


def test_jwks_cooldown_expiry_allows_refetch(jwks_http, stubbed_jwt):
    from cap_tpu.jwt.keyset import JSONWebKeySet

    state, url = jwks_http
    state.etags = False
    ks = JSONWebKeySet(url, refresh_cooldown_s=0.1)
    with pytest.raises(UnknownKeyIDError):
        ks.verify_signature(_compact_token("ghost"))
    hits = state.hits
    time.sleep(0.15)
    # Rotation landed at the IdP; the next miss may now refetch.
    state.doc = _jwks("a", "ghost")
    assert ks.verify_signature(_compact_token("ghost"))["sub"] == "x"
    assert state.hits == hits + 1


# ---------------------------------------------------------------------------
# KEYS wire frames (types 11/12)
# ---------------------------------------------------------------------------

class _Capture:
    def __init__(self):
        self.buf = io.BytesIO()

    def sendall(self, b):
        self.buf.write(b)


def test_keys_frames_roundtrip():
    s = _Capture()
    protocol.send_keys_push(s, _jwks("a", "b"), 7)
    ftype, entries, trace = protocol._parse_frame(
        io.BytesIO(s.buf.getvalue()).read)
    assert ftype == protocol.T_KEYS_PUSH and trace is None
    doc = json.loads(entries[0])
    assert doc["epoch"] == 7
    assert {k["kid"] for k in doc["jwks"]["keys"]} == {"a", "b"}

    s = _Capture()
    protocol.send_keys_ack(s, epoch=7)
    ftype, entries, _ = protocol._parse_frame(
        io.BytesIO(s.buf.getvalue()).read)
    assert ftype == protocol.T_KEYS_ACK
    assert entries[0][0] == 0
    assert json.loads(entries[0][1]) == {"epoch": 7}

    s = _Capture()
    protocol.send_keys_ack(s, error="TypeError: no swap")
    _, entries, _ = protocol._parse_frame(
        io.BytesIO(s.buf.getvalue()).read)
    assert entries[0] == (1, b"TypeError: no swap")


def test_keys_frame_corruption_detected():
    s = _Capture()
    protocol.send_keys_push(s, _jwks("a"), 1)
    blob = bytearray(s.buf.getvalue())
    blob[len(blob) // 2] ^= 0x01
    with pytest.raises(protocol.FrameCorruptError):
        protocol._parse_frame(io.BytesIO(bytes(blob)).read)


def test_keys_frame_requires_exactly_one_entry():
    import struct

    hdr = struct.pack("<IBI", protocol.MAGIC, protocol.T_KEYS_PUSH, 2)
    with pytest.raises(protocol.MalformedFrameError, match="exactly one"):
        protocol._parse_frame(io.BytesIO(hdr).read)


def test_keys_payload_is_canonical():
    a = protocol.keys_payload({"keys": [{"kid": "a", "kty": "RSA"}]}, 1)
    b = protocol.keys_payload({"keys": [{"kty": "RSA", "kid": "a"}]}, 1)
    assert a == b                       # key order never changes bytes


# ---------------------------------------------------------------------------
# TPUBatchKeySet.swap_keys (crypto-gated: real tables, real verdicts)
# ---------------------------------------------------------------------------

@needs_crypto
class TestSwapKeys:
    @pytest.fixture(scope="class")
    def fixtures(self):
        from cap_tpu import testing as captest
        from cap_tpu.jwt.jwk import JWK

        es_priv, es_pub = captest.generate_keys("ES256")
        es2_priv, es2_pub = captest.generate_keys("ES256")
        return {
            "old": [JWK(es_pub, kid="old-1")],
            "new": [JWK(es2_pub, kid="new-1")],
            "tok_old": captest.sign_jwt(es_priv, "ES256",
                                        captest.default_claims(),
                                        kid="old-1"),
            "tok_new": captest.sign_jwt(es2_priv, "ES256",
                                        captest.default_claims(),
                                        kid="new-1"),
        }

    def test_swap_bumps_epoch_and_serves_new_keys(self, fixtures):
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        assert ks.key_epoch == 0
        assert not isinstance(
            ks.verify_batch([fixtures["tok_old"]])[0], Exception)
        got = ks.swap_keys(fixtures["new"], grace_s=30.0)
        assert got == 1 and ks.key_epoch == 1
        assert not isinstance(
            ks.verify_batch([fixtures["tok_new"]])[0], Exception)

    def test_grace_window_resolves_retired_kids(self, fixtures):
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        ks.swap_keys(fixtures["new"], grace_s=30.0)
        # Tokens signed under the just-retired kid still verify.
        res = ks.verify_batch([fixtures["tok_old"], fixtures["tok_new"]])
        assert not isinstance(res[0], Exception), \
            "retired kid flapped to reject inside the grace window"
        assert not isinstance(res[1], Exception)

    def test_grace_expiry_retires_old_kids(self, fixtures):
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        ks.swap_keys(fixtures["new"], grace_s=0.2)
        deadline = time.monotonic() + 10
        while "old-1" in ks._tables.kids and time.monotonic() < deadline:
            time.sleep(0.05)
        assert "old-1" not in ks._tables.kids, "grace never retired"
        assert isinstance(
            ks.verify_batch([fixtures["tok_old"]])[0], Exception)
        assert not isinstance(
            ks.verify_batch([fixtures["tok_new"]])[0], Exception)

    def test_zero_grace_drops_old_kids_immediately(self, fixtures):
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        ks.swap_keys(fixtures["new"], grace_s=0.0)
        assert isinstance(
            ks.verify_batch([fixtures["tok_old"]])[0], Exception)

    def test_swap_accepts_jwks_document(self, fixtures):
        from cap_tpu.jwt.jwk import serialize_public_key
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        doc = {"keys": [serialize_public_key(fixtures["new"][0].key,
                                             kid="new-1")]}
        ks.swap_keys(doc, epoch=9)
        assert ks.key_epoch == 9
        assert not isinstance(
            ks.verify_batch([fixtures["tok_new"]])[0], Exception)

    def test_swap_records_telemetry(self, fixtures):
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet(fixtures["old"])
        with telemetry.recording() as rec:
            ks.swap_keys(fixtures["new"])
            assert rec.counters().get("keyplane.swaps") == 1
            assert rec.gauges().get("keyplane.epoch") == 1
            assert telemetry.SPAN_KEYPLANE_SWAP in rec.summary()
