"""The profile_families --trace device-timeline extraction.

The slope methodology can be inflated by tunnel weather (the round-5
1046k/s ES256 outlier); --trace re-derives per-dispatch ms from the
profiler's trace-viewer JSON. This pins the parser end-to-end on a
real jax.profiler capture: device/runtime execution events are found,
host python-thread events are excluded, and the returned span divides
by the dispatch count.
"""

import importlib.util
import os
import sys

import jax
import jax.numpy as jnp


def _load_tool():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "profile_families.py")
    spec = importlib.util.spec_from_file_location("_profile_families", path)
    mod = importlib.util.module_from_spec(spec)
    saved = sys.argv
    sys.argv = [path]          # tool parses argv at import
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.argv = saved
    return mod


def test_trace_device_ms_measures_real_work():
    tool = _load_tool()

    @jax.jit
    def work(x):
        for _ in range(4):
            x = x @ x
        return jnp.sum(x)

    x = jnp.ones((256, 256))
    work(x).block_until_ready()            # compile outside the trace
    fns = [(1, lambda: work(x))]
    ms = tool.trace_device_ms(fns, reps=2)
    # Unknown runtimes legitimately return None; this box's must not.
    assert ms is not None and ms > 0

    @jax.jit
    def tiny(x):
        return jnp.sum(x)

    tiny(x).block_until_ready()
    ms_tiny = tool.trace_device_ms([(1, lambda: tiny(x))], reps=2)
    assert ms_tiny is not None
    # 4 chained 256x256 matmuls must show more device span than one sum
    assert ms > ms_tiny
