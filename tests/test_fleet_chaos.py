"""Chaos suite: the fleet availability contract under injected faults.

For EVERY fault mode — worker kill -9 mid-batch, socket stall, black
hole, corrupt response frame, delayed accepts — every submitted token
must still receive its bit-exact-correct verdict (via failover or the
terminal CPU-oracle fallback): **zero wrong verdicts, zero lost
submissions**. Ground truth is the stub engine's deterministic rule
(``*.ok`` verifies), shared between the workers and the fallback
oracle, so a verdict is comparable wherever it was produced.

Tier-1 discipline: stub workers (no jax import in children), every
blocking primitive carries a timeout, and a SIGALRM watchdog gives
each test a HARD deadline — a hung worker can never wedge the suite.
"""

import signal
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, WorkerPool
from cap_tpu.fleet.chaos import ChaosProxy, kill9
from cap_tpu.fleet.worker_main import StubKeySet

pytestmark = pytest.mark.chaos

HARD_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout():
    """Per-test SIGALRM watchdog: a wedged socket/worker fails the
    test instead of hanging the tier-1 run."""
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"chaos test exceeded hard {HARD_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _expected(tokens):
    """Ground truth: what every token's verdict MUST be."""
    return [t.endswith(".ok") for t in tokens]


def _assert_verdicts(tokens, results):
    """Zero lost: one verdict per token. Zero wrong: accept/reject
    matches ground truth exactly; accepted claims carry the token."""
    assert len(results) == len(tokens), "lost submissions"
    for t, r, want_ok in zip(tokens, results, _expected(tokens)):
        if want_ok:
            assert r == {"sub": t}, f"WRONG verdict for {t!r}: {r!r}"
        else:
            assert isinstance(r, Exception), \
                f"WRONG verdict for {t!r}: accepted"


@pytest.fixture(params=["python", "native"])
def fleet(request):
    """2 stub workers with ~80 ms of simulated device time per batch
    (sleep releases the GIL), so a kill -9 lands MID-BATCH reliably.

    Parameterized over BOTH serve chains (CAP_SERVE_NATIVE=0 / =1):
    every fault mode must produce zero wrong verdicts and zero lost
    submissions whether the workers run the Python reader/responder
    chain or the native C++ frame-I/O chain. When the native library
    can't build on this host, workers fall back to python — assert
    what actually came up so a silent fallback can't fake coverage.
    """
    native = request.param == "native"
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=80",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0,
                      env_extra={"CAP_SERVE_NATIVE":
                                 "1" if native else "0"})
    assert pool.wait_all_ready(30), "fleet did not come up"
    chains = set(pool.serve_chains().values())
    if native and chains != {"native"}:
        pool.close()
        pytest.skip(f"native chain unavailable (workers ran {chains})")
    assert native or chains == {"python"}, chains
    yield pool
    pool.close()


def _proxied_client(pool, proxies, **kw):
    kw.setdefault("attempt_timeout", 2.0)
    kw.setdefault("total_deadline", 30.0)
    kw.setdefault("hedge_after", 0.5)
    kw.setdefault("breaker_reset_s", 0.5)
    kw.setdefault("rr_seed", 0)      # deterministic: first pick is p0
    return FleetClient(lambda: [p.address for p in proxies],
                       fallback=StubKeySet(), **kw)


# ---------------------------------------------------------------------------
# fault: worker kill -9 mid-batch
# ---------------------------------------------------------------------------

def test_kill9_mid_batch_failover_and_respawn(fleet):
    cl = FleetClient(fleet, fallback=StubKeySet(), attempt_timeout=2.0,
                     total_deadline=30.0)
    batches = [[f"k{i}-{j}.ok" for j in range(4)] + [f"k{i}-bad"]
               for i in range(8)]
    results = {}

    def submit(i):
        results[i] = cl.verify_batch(batches[i])

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(len(batches))]
    victim = fleet.pid(0)
    for t in threads:
        t.start()
    # Batches are in flight (80 ms simulated device time each): this
    # SIGKILL lands mid-batch on worker 0.
    time.sleep(0.05)
    kill9(victim)
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "submission thread wedged"
    for i, toks in enumerate(batches):
        _assert_verdicts(toks, results[i])
    # The pool detects the crash and respawns onto the same devices.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if (fleet.state(0) == "ready" and fleet.pid(0) != victim):
            break
        time.sleep(0.1)
    assert fleet.state(0) == "ready" and fleet.pid(0) != victim
    assert fleet.restarts(0) >= 1
    # And the respawned worker serves.
    _assert_verdicts(["post.ok"], cl.verify_batch(["post.ok"]))


def test_kill9_sole_worker_falls_back_to_oracle():
    pool = WorkerPool(1, keyset_spec="stub:batch_ms=200",
                      ping_interval=0.2, max_restarts=20)
    try:
        assert pool.wait_all_ready(30)
        with telemetry.recording() as rec:
            cl = FleetClient(pool, fallback=StubKeySet(),
                             attempt_timeout=1.0, total_deadline=8.0,
                             max_rounds=2, breaker_reset_s=0.2)
            done = {}

            def submit():
                done["res"] = cl.verify_batch(["solo.ok", "solo.bad"])

            t = threading.Thread(target=submit)
            t.start()
            time.sleep(0.05)          # batch is on the "device"
            kill9(pool.pid(0))
            t.join(timeout=30)
            assert not t.is_alive()
        _assert_verdicts(["solo.ok", "solo.bad"], done["res"])
        # With no peer to fail over to, the oracle produced the
        # verdicts (or the respawned worker did — both are correct;
        # the contract is verdicts, not the path).
        c = rec.counters()
        assert (c.get("fleet.fallback_tokens", 0) >= 2
                or c.get("fleet.failovers", 0) >= 1)
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# fault: socket stall (bytes stop moving, connection stays open)
# ---------------------------------------------------------------------------

def test_stall_hedges_to_healthy_peer(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        warm = _proxied_client(fleet, [p0, p1])
        _assert_verdicts(["warm.ok"], warm.verify_batch(["warm.ok"]))
        p0.stall()
        # Fresh client: round-robin starts at p0, so the batch
        # DETERMINISTICALLY hits the stalled path first.
        cl = _proxied_client(fleet, [p0, p1])
        with telemetry.recording() as rec:
            tokens = [f"s{i}.ok" for i in range(4)] + ["s-bad"]
            t0 = time.monotonic()
            res = cl.verify_batch(tokens)
            dt = time.monotonic() - t0
        _assert_verdicts(tokens, res)
        c = rec.counters()
        # Either the hedge answered while the primary hung, or the
        # primary timed out and failed over — both bounded, both right.
        assert (c.get("fleet.hedges", 0) >= 1
                or c.get("fleet.failovers", 0) >= 1)
        assert dt < 10.0, f"stall cost {dt:.1f}s"


def test_stall_everything_terminal_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0,
                             total_deadline=10.0, max_rounds=2)
        p0.stall()
        p1.stall()
        with telemetry.recording() as rec:
            tokens = ["t1.ok", "t2.bad", "t3.ok"]
            res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)
        assert rec.counters().get("fleet.fallback_tokens", 0) == 3


# ---------------------------------------------------------------------------
# fault: black hole (bytes read and dropped)
# ---------------------------------------------------------------------------

def test_blackhole_one_worker_fails_over(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0)
        p0.blackhole()
        for i in range(3):
            tokens = [f"b{i}.ok", f"b{i}-bad"]
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        # Clearing the fault lets worker 0 rejoin (breaker half-open
        # probe re-admits it after breaker_reset_s).
        p0.clear()
        time.sleep(0.6)
        with telemetry.recording():
            for i in range(4):
                _assert_verdicts([f"c{i}.ok"],
                                 cl.verify_batch([f"c{i}.ok"]))


def test_blackhole_all_terminal_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0,
                             total_deadline=10.0, max_rounds=2)
        p0.blackhole()
        p1.blackhole()
        with telemetry.recording() as rec:
            tokens = [f"bh{i}.ok" for i in range(5)]
            res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)
        assert rec.counters().get("fleet.fallback_batches", 0) == 1


# ---------------------------------------------------------------------------
# fault: corrupt response frame
# ---------------------------------------------------------------------------

def test_corrupt_response_frame_is_never_a_wrong_verdict(fleet):
    """The deadliest corruption is a flipped STATUS byte (offset 9):
    without the checksummed frames it would silently turn a verified
    token into a rejection. With them it MUST surface as a transport
    error and the verdict must come from a clean path. Sweep several
    offsets through header, status, and payload bytes."""
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=2.0,
                             hedge_after=None)
        _assert_verdicts(["warm.ok"], cl.verify_batch(["warm.ok"]))
        offsets = [0, 4, 9, 10, 14, 20]   # magic, type-ish, status,
        with telemetry.recording() as rec:  # len, payload, payload
            for n, off in enumerate(offsets):
                p0.corrupt(direction="s2c", offset=off, xor=0x01,
                           times=1)
                p1.corrupt(direction="s2c", offset=off, xor=0x01,
                           times=1)
                tokens = [f"x{n}.ok", f"x{n}-bad", f"y{n}.ok"]
                _assert_verdicts(tokens, cl.verify_batch(tokens))
        # Every corruption was DETECTED (never absorbed): each batch
        # needed at least one extra attempt or the oracle.
        c = rec.counters()
        detected = (c.get("fleet.failovers", 0)
                    + c.get("fleet.fallback_batches", 0))
        assert detected >= len(offsets), c


def test_corrupt_request_frame_detected_worker_side(fleet):
    """c2s corruption: the worker's CRC check rejects the request
    (drops the connection) instead of verifying an altered token."""
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], hedge_after=None)
        # Offset 30 lands inside the first token's bytes.
        p0.corrupt(direction="c2s", offset=30, xor=0xFF, times=1)
        p1.corrupt(direction="c2s", offset=30, xor=0xFF, times=1)
        tokens = ["req-corrupt-a.ok", "req-corrupt-b.bad"]
        with telemetry.recording() as rec:
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        assert (rec.counters().get("fleet.failovers", 0)
                + rec.counters().get("fleet.fallback_batches", 0)) >= 1


# ---------------------------------------------------------------------------
# fault: delayed accepts
# ---------------------------------------------------------------------------

def test_delayed_accepts_within_deadline(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=3.0)
        p0.delay_accept(0.4)
        p1.delay_accept(0.4)
        tokens = [f"da{i}.ok" for i in range(3)] + ["da-bad"]
        res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)


# ---------------------------------------------------------------------------
# cross-process tracing under faults (the observability acceptance bar)
# ---------------------------------------------------------------------------

# JWS-shaped stub tokens: the redaction sweep below must be able to
# detect any leak of real-looking token material into telemetry.
def _jws_tokens(prefix, n_ok=3):
    toks = [f"eyJhbGciOiJSUzI1NiJ9.eyJzdWIiOiI{prefix}{i}In0.c2ln.ok"
            for i in range(n_ok)]
    toks.append(f"eyJhbGciOiJub25lIn0.eyJzdWIiOiI{prefix}In0.bad")
    return toks


def _no_payload_material(dumps, tokens):
    frags = {"eyJ"}
    for t in tokens:
        frags.update(seg for seg in t.split(".") if len(seg) >= 8)
    for frag in frags:
        for i, d in enumerate(dumps):
            assert frag not in d, \
                f"payload material leaked into surface {i}"


def _scrape_flights(pool):
    """Every worker's /flight via its obs HTTP server."""
    import json as _json
    import urllib.request

    out = {}
    for wid, (host, port) in sorted(pool.obs_endpoints().items()):
        with urllib.request.urlopen(
                f"http://{host}:{port}/flight", timeout=5) as r:
            out[wid] = _json.load(r)["slowest"]
    return out


def test_traced_hedged_retry_reassembles_cross_process(fleet):
    """A hedged-retry request under a stalled primary: the trace id
    crosses the wire (CVB1 type 9/10), the surviving worker's flight
    recorder holds the worker-side spans, and capstat reassembles the
    full client → router → worker → batcher timeline. The breaker
    transition shows up in capstat's fleet rendering. Zero payload
    material anywhere."""
    import json as _json

    from tools import capstat

    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        warm = _proxied_client(fleet, [p0, p1])
        _assert_verdicts(["w.ok"], warm.verify_batch(["w.ok"]))
        p0.stall()
        # Long reset window: once the breaker opens it stays visibly
        # open for the snapshot/rendering assertions below.
        cl = _proxied_client(fleet, [p0, p1], breaker_threshold=2,
                             breaker_reset_s=30.0)
        tokens = _jws_tokens("hedge")
        with telemetry.recording() as rec:
            with telemetry.trace() as tid:
                res = cl.verify_batch(tokens)
            _assert_verdicts(tokens, res)
            # Drive the stalled endpoint's breaker OPEN. The stalled
            # primary's failure lands when its attempt socket times
            # out (~attempt_timeout after each hedged batch), so keep
            # offering batches until the transition is observed.
            deadline = time.monotonic() + 30
            while (rec.counters().get("fleet.breaker_opens", 0) < 1
                   and time.monotonic() < deadline):
                _assert_verdicts(["more.ok"],
                                 cl.verify_batch(["more.ok"]))
                time.sleep(0.2)
            client_view = cl.snapshot()
        c = rec.counters()
        assert (c.get("fleet.hedges", 0) >= 1
                or c.get("fleet.failovers", 0) >= 1)

        # client-side spans of the traced request
        names = {s["name"] for s in rec.trace_spans(tid)}
        assert telemetry.SPAN_CLIENT_SUBMIT in names
        assert telemetry.SPAN_ROUTER_ATTEMPT in names
        if c.get("fleet.hedges", 0):
            assert telemetry.SPAN_ROUTER_HEDGE in names

        # worker-side spans: reassemble across every flight recorder
        flights = _scrape_flights(fleet)
        sources = [{"flight": fl} for fl in flights.values()]
        sources.append({"spans": rec.trace_spans()})
        spans = capstat.reassemble_trace(tid, sources)
        got = {s["name"] for s in spans}
        for stage in (telemetry.SPAN_CLIENT_SUBMIT,
                      telemetry.SPAN_ROUTER_ATTEMPT,
                      telemetry.SPAN_WORKER_DEQUEUE,
                      telemetry.SPAN_BATCHER_FILL,
                      telemetry.SPAN_BATCHER_FLUSH):
            assert stage in got, f"stage {stage} missing from {got}"
        timeline = capstat.render_trace(tid, spans)
        assert tid in timeline

        # capstat shows the breaker transition
        assert c.get("fleet.breaker_opens", 0) >= 1
        p0_ep = f"{p0.address[0]}:{p0.address[1]}"
        assert client_view["breakers"][p0_ep]["open_for_s"] > 0
        rendered = capstat.render_fleet({}, client_view)
        assert "OPEN" in rendered and "breaker_opens=" in rendered

        # redaction: nothing recorded carries payload material
        _no_payload_material(
            [timeline, rendered, _json.dumps(client_view),
             _json.dumps(rec.trace_spans()),
             _json.dumps(flights),
             _json.dumps(rec.counters()), _json.dumps(rec.summary())],
            tokens)


def test_traced_terminal_fallback_full_timeline(fleet):
    """Every worker stalled: the traced request's timeline must show
    attempts on the (dead) fleet and the terminal CPU-oracle fallback
    span — attribution for the 'at worst slow' contract."""
    import json as _json

    from tools import capstat

    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0,
                             total_deadline=10.0, max_rounds=2,
                             hedge_after=None)
        p0.stall()
        p1.stall()
        tokens = _jws_tokens("fb")
        with telemetry.recording() as rec:
            with telemetry.trace() as tid:
                res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)
        assert rec.counters().get("fleet.fallback_tokens", 0) == len(tokens)
        spans = capstat.reassemble_trace(tid, [rec.trace_spans()])
        names = [s["name"] for s in spans]
        assert telemetry.SPAN_CLIENT_SUBMIT in names
        assert names.count(telemetry.SPAN_ROUTER_ATTEMPT) >= 2  # both eps
        assert telemetry.SPAN_ROUTER_FALLBACK in names
        # the whole-request span covers the fallback span in time
        sub = next(s for s in spans
                   if s["name"] == telemetry.SPAN_CLIENT_SUBMIT)
        fb = next(s for s in spans
                  if s["name"] == telemetry.SPAN_ROUTER_FALLBACK)
        assert sub["t0"] <= fb["t0"]
        assert sub["dur"] >= fb["dur"]
        _no_payload_material(
            [capstat.render_trace(tid, spans),
             _json.dumps(rec.trace_spans()),
             _json.dumps(rec.summary())], tokens)


def test_delayed_accepts_beyond_deadline_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=0.5,
                             total_deadline=6.0, max_rounds=2,
                             hedge_after=None)
        p0.delay_accept(5.0)
        p1.delay_accept(5.0)
        tokens = ["slow.ok", "slow.bad"]
        with telemetry.recording() as rec:
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        assert rec.counters().get("fleet.fallback_tokens", 0) == 2


# ---------------------------------------------------------------------------
# crash postmortems: kill -9 leaves a readable file; SIGTERM drains fresh
# ---------------------------------------------------------------------------

def test_kill9_leaves_readable_postmortem(fleet, tmp_path):
    """A kill -9'd worker leaves a postmortem at most one checkpoint
    interval stale; the pool collects it on confirmed death and
    ``capstat --postmortem`` renders it with the final flight ring."""
    import json as _json

    from cap_tpu.obs import postmortem as obs_postmortem
    from tools import capstat

    cl = FleetClient(fleet, fallback=StubKeySet(), rr_seed=0,
                     attempt_timeout=2.0, total_deadline=30.0)
    # Give worker 0 a traced history so its checkpoint carries a
    # non-empty flight ring and decision counters.
    with telemetry.recording():
        for i in range(6):
            with telemetry.trace():
                _assert_verdicts([f"pm{i}.ok", f"pm{i}.bad"],
                                 cl.verify_batch([f"pm{i}.ok",
                                                  f"pm{i}.bad"]))
    # Postmortems checkpoint every postmortem_interval (pool default
    # 1.0 s): wait until a checkpoint after the traffic above exists.
    victim = fleet.pid(0)
    pm_path = fleet.postmortem_path(0)
    assert pm_path, "pool did not assign a postmortem path"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        doc = obs_postmortem.read_postmortem(pm_path)
        if doc and (doc.get("snapshot", {}).get("counters", {})
                    .get("worker.requests", 0)) >= 1:
            break
        time.sleep(0.1)
    kill9(victim)
    # The pool confirms the death, collects the file, and respawns.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if fleet.state(0) == "ready" and fleet.pid(0) != victim:
            break
        time.sleep(0.1)
    doc = fleet.postmortem(0)
    assert doc is not None, "no postmortem collected after kill -9"
    assert doc["pid"] == victim
    counters = doc.get("snapshot", {}).get("counters", {})
    assert counters.get("worker.requests", 0) >= 1
    assert counters.get("decision.serve.accept", 0) >= 1
    assert counters.get(
        "decision.serve.reject.bad_signature", 0) >= 1
    if set(fleet.serve_chains().values()) == {"native"}:
        # native chain: the decision counters above came from the
        # NATIVE telemetry plane (merged into the checkpoint by
        # worker.stats), and the chain's own counters ride along —
        # the postmortem carries the native side of the worker
        assert counters.get("serve.native.frames", 0) >= 1
        assert counters.get("serve.native.tokens", 0) >= 1
    assert doc.get("flight"), "final flight ring missing"
    # capstat renders the collected doc (write it like an operator
    # saving the pool's copy).
    f = tmp_path / "victim.json"
    f.write_text(_json.dumps(doc))
    assert capstat.main(["--postmortem", str(f)]) == 0
    rendered = obs_postmortem.render_postmortem(doc)
    assert "flight ring" in rendered
    assert "decisions[serve]" in rendered


def test_sigterm_drain_writes_fresh_postmortem(fleet):
    """Graceful restart: the worker's SIGTERM handler writes a FINAL
    checkpoint (reason sigterm-drain) after the drain completes."""
    victim = fleet.pid(1)
    # give the victim served traffic so the final checkpoint has
    # something to account for (direct connection: routing must not
    # send it to worker 0)
    from cap_tpu.serve.client import VerifyClient

    host, port = fleet.address(1)
    with VerifyClient(host, port) as direct:
        _assert_verdicts(["drain-a.ok", "drain-b.bad"],
                         direct.verify_batch(["drain-a.ok",
                                              "drain-b.bad"]))
    fleet.restart(1, graceful=True)
    doc = fleet.postmortem(1)
    assert doc is not None
    assert doc["pid"] == victim
    assert doc["reason"] == "sigterm-drain"
    # fresh: written within the drain window, not a stale checkpoint
    assert time.time() - doc["t_write"] < 30
    counters = doc.get("snapshot", {}).get("counters", {})
    assert counters.get("decision.serve.accept", 0) >= 1
    if set(fleet.serve_chains().values()) == {"native"}:
        # the final checkpoint runs AFTER the native teardown: the
        # merged native-plane + chain counters must have survived
        assert counters.get("serve.native.frames", 0) >= 1
        assert counters.get("serve.native.tokens", 0) >= 2


# ---------------------------------------------------------------------------
# stalled scraper: the obs server must not block the worker loop
# ---------------------------------------------------------------------------

def test_stalled_scraper_does_not_block_worker(fleet):
    """A scraper that connects to a worker's obs server and goes
    silent: verifies keep flowing, healthy scrapes keep answering,
    and the stalled connection is eventually torn down by the
    short-timeout handler."""
    import socket as _socket
    import urllib.request as _url

    obs = fleet.obs_endpoints()
    host, port = obs[0]
    stalled = _socket.create_connection((host, port), timeout=5)
    stalled.send(b"GET /snapshot")          # no CRLF: never a request
    try:
        cl = FleetClient(fleet, fallback=StubKeySet(), rr_seed=0)
        tokens = [f"ss{i}.ok" for i in range(3)] + ["ss-bad"]
        t0 = time.monotonic()
        _assert_verdicts(tokens, cl.verify_batch(tokens))
        assert time.monotonic() - t0 < 10.0
        with _url.urlopen(f"http://{host}:{port}/healthz",
                          timeout=5) as r:
            assert r.status == 200
        # The worker's obs handler (5 s timeout) closes the stalled
        # connection instead of leaking its thread forever.
        stalled.settimeout(10.0)
        deadline = time.monotonic() + 10.0
        closed = False
        while time.monotonic() < deadline:
            try:
                if stalled.recv(4096) == b"":
                    closed = True
                    break
            except (ConnectionError, _socket.timeout, OSError):
                closed = True
                break
        assert closed, "stalled scraper held its connection forever"
    finally:
        stalled.close()


# ---------------------------------------------------------------------------
# redaction sweep: decision records + postmortem files carry no payload
# ---------------------------------------------------------------------------

def test_decision_and_postmortem_redaction_sweep(fleet):
    """JWS-shaped tokens through the fleet: the decision counters,
    the sampled decision rings (every worker's /decisions + the
    router's), and the raw postmortem FILES on disk contain zero
    token/payload material — the PR-3 scrub machinery enforced at the
    new write boundaries."""
    import json as _json
    import urllib.request as _url

    tokens = _jws_tokens("redact")
    with telemetry.recording() as rec:
        cl = FleetClient(fleet, fallback=StubKeySet(), rr_seed=0)
        with telemetry.trace():
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        router_ring = rec.decisions()
        assert router_ring, "router decision ring empty"
        router_counters = rec.counters()
    assert router_counters.get("decision.router.accept", 0) >= 1

    dumps = [_json.dumps(router_ring), _json.dumps(router_counters)]
    for wid, (host, port) in sorted(fleet.obs_endpoints().items()):
        with _url.urlopen(f"http://{host}:{port}/decisions",
                          timeout=5) as r:
            dumps.append(r.read().decode())
    # Force a final checkpoint through the graceful path, then sweep
    # the raw postmortem files exactly as they sit on disk.
    paths = [fleet.postmortem_path(w) for w in (0, 1)]
    fleet.restart(0, graceful=True)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if fleet.state(0) == "ready":
            break
        time.sleep(0.1)
    for p in paths:
        try:
            with open(p) as f:
                dumps.append(f.read())
        except OSError:
            pass
    assert len(dumps) >= 5
    _no_payload_material(dumps, tokens)
