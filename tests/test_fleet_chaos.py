"""Chaos suite: the fleet availability contract under injected faults.

For EVERY fault mode — worker kill -9 mid-batch, socket stall, black
hole, corrupt response frame, delayed accepts — every submitted token
must still receive its bit-exact-correct verdict (via failover or the
terminal CPU-oracle fallback): **zero wrong verdicts, zero lost
submissions**. Ground truth is the stub engine's deterministic rule
(``*.ok`` verifies), shared between the workers and the fallback
oracle, so a verdict is comparable wherever it was produced.

Tier-1 discipline: stub workers (no jax import in children), every
blocking primitive carries a timeout, and a SIGALRM watchdog gives
each test a HARD deadline — a hung worker can never wedge the suite.
"""

import signal
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, WorkerPool
from cap_tpu.fleet.chaos import ChaosProxy, kill9
from cap_tpu.fleet.worker_main import StubKeySet

pytestmark = pytest.mark.chaos

HARD_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout():
    """Per-test SIGALRM watchdog: a wedged socket/worker fails the
    test instead of hanging the tier-1 run."""
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"chaos test exceeded hard {HARD_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _expected(tokens):
    """Ground truth: what every token's verdict MUST be."""
    return [t.endswith(".ok") for t in tokens]


def _assert_verdicts(tokens, results):
    """Zero lost: one verdict per token. Zero wrong: accept/reject
    matches ground truth exactly; accepted claims carry the token."""
    assert len(results) == len(tokens), "lost submissions"
    for t, r, want_ok in zip(tokens, results, _expected(tokens)):
        if want_ok:
            assert r == {"sub": t}, f"WRONG verdict for {t!r}: {r!r}"
        else:
            assert isinstance(r, Exception), \
                f"WRONG verdict for {t!r}: accepted"


@pytest.fixture
def fleet():
    """2 stub workers with ~80 ms of simulated device time per batch
    (sleep releases the GIL), so a kill -9 lands MID-BATCH reliably."""
    pool = WorkerPool(2, keyset_spec="stub:batch_ms=80",
                      ping_interval=0.2, max_restarts=20,
                      max_wait_ms=1.0)
    assert pool.wait_all_ready(30), "fleet did not come up"
    yield pool
    pool.close()


def _proxied_client(pool, proxies, **kw):
    kw.setdefault("attempt_timeout", 2.0)
    kw.setdefault("total_deadline", 30.0)
    kw.setdefault("hedge_after", 0.5)
    kw.setdefault("breaker_reset_s", 0.5)
    kw.setdefault("rr_seed", 0)      # deterministic: first pick is p0
    return FleetClient(lambda: [p.address for p in proxies],
                       fallback=StubKeySet(), **kw)


# ---------------------------------------------------------------------------
# fault: worker kill -9 mid-batch
# ---------------------------------------------------------------------------

def test_kill9_mid_batch_failover_and_respawn(fleet):
    cl = FleetClient(fleet, fallback=StubKeySet(), attempt_timeout=2.0,
                     total_deadline=30.0)
    batches = [[f"k{i}-{j}.ok" for j in range(4)] + [f"k{i}-bad"]
               for i in range(8)]
    results = {}

    def submit(i):
        results[i] = cl.verify_batch(batches[i])

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(len(batches))]
    victim = fleet.pid(0)
    for t in threads:
        t.start()
    # Batches are in flight (80 ms simulated device time each): this
    # SIGKILL lands mid-batch on worker 0.
    time.sleep(0.05)
    kill9(victim)
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "submission thread wedged"
    for i, toks in enumerate(batches):
        _assert_verdicts(toks, results[i])
    # The pool detects the crash and respawns onto the same devices.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if (fleet.state(0) == "ready" and fleet.pid(0) != victim):
            break
        time.sleep(0.1)
    assert fleet.state(0) == "ready" and fleet.pid(0) != victim
    assert fleet.restarts(0) >= 1
    # And the respawned worker serves.
    _assert_verdicts(["post.ok"], cl.verify_batch(["post.ok"]))


def test_kill9_sole_worker_falls_back_to_oracle():
    pool = WorkerPool(1, keyset_spec="stub:batch_ms=200",
                      ping_interval=0.2, max_restarts=20)
    try:
        assert pool.wait_all_ready(30)
        with telemetry.recording() as rec:
            cl = FleetClient(pool, fallback=StubKeySet(),
                             attempt_timeout=1.0, total_deadline=8.0,
                             max_rounds=2, breaker_reset_s=0.2)
            done = {}

            def submit():
                done["res"] = cl.verify_batch(["solo.ok", "solo.bad"])

            t = threading.Thread(target=submit)
            t.start()
            time.sleep(0.05)          # batch is on the "device"
            kill9(pool.pid(0))
            t.join(timeout=30)
            assert not t.is_alive()
        _assert_verdicts(["solo.ok", "solo.bad"], done["res"])
        # With no peer to fail over to, the oracle produced the
        # verdicts (or the respawned worker did — both are correct;
        # the contract is verdicts, not the path).
        c = rec.counters()
        assert (c.get("fleet.fallback_tokens", 0) >= 2
                or c.get("fleet.failovers", 0) >= 1)
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# fault: socket stall (bytes stop moving, connection stays open)
# ---------------------------------------------------------------------------

def test_stall_hedges_to_healthy_peer(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        warm = _proxied_client(fleet, [p0, p1])
        _assert_verdicts(["warm.ok"], warm.verify_batch(["warm.ok"]))
        p0.stall()
        # Fresh client: round-robin starts at p0, so the batch
        # DETERMINISTICALLY hits the stalled path first.
        cl = _proxied_client(fleet, [p0, p1])
        with telemetry.recording() as rec:
            tokens = [f"s{i}.ok" for i in range(4)] + ["s-bad"]
            t0 = time.monotonic()
            res = cl.verify_batch(tokens)
            dt = time.monotonic() - t0
        _assert_verdicts(tokens, res)
        c = rec.counters()
        # Either the hedge answered while the primary hung, or the
        # primary timed out and failed over — both bounded, both right.
        assert (c.get("fleet.hedges", 0) >= 1
                or c.get("fleet.failovers", 0) >= 1)
        assert dt < 10.0, f"stall cost {dt:.1f}s"


def test_stall_everything_terminal_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0,
                             total_deadline=10.0, max_rounds=2)
        p0.stall()
        p1.stall()
        with telemetry.recording() as rec:
            tokens = ["t1.ok", "t2.bad", "t3.ok"]
            res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)
        assert rec.counters().get("fleet.fallback_tokens", 0) == 3


# ---------------------------------------------------------------------------
# fault: black hole (bytes read and dropped)
# ---------------------------------------------------------------------------

def test_blackhole_one_worker_fails_over(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0)
        p0.blackhole()
        for i in range(3):
            tokens = [f"b{i}.ok", f"b{i}-bad"]
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        # Clearing the fault lets worker 0 rejoin (breaker half-open
        # probe re-admits it after breaker_reset_s).
        p0.clear()
        time.sleep(0.6)
        with telemetry.recording():
            for i in range(4):
                _assert_verdicts([f"c{i}.ok"],
                                 cl.verify_batch([f"c{i}.ok"]))


def test_blackhole_all_terminal_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=1.0,
                             total_deadline=10.0, max_rounds=2)
        p0.blackhole()
        p1.blackhole()
        with telemetry.recording() as rec:
            tokens = [f"bh{i}.ok" for i in range(5)]
            res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)
        assert rec.counters().get("fleet.fallback_batches", 0) == 1


# ---------------------------------------------------------------------------
# fault: corrupt response frame
# ---------------------------------------------------------------------------

def test_corrupt_response_frame_is_never_a_wrong_verdict(fleet):
    """The deadliest corruption is a flipped STATUS byte (offset 9):
    without the checksummed frames it would silently turn a verified
    token into a rejection. With them it MUST surface as a transport
    error and the verdict must come from a clean path. Sweep several
    offsets through header, status, and payload bytes."""
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=2.0,
                             hedge_after=None)
        _assert_verdicts(["warm.ok"], cl.verify_batch(["warm.ok"]))
        offsets = [0, 4, 9, 10, 14, 20]   # magic, type-ish, status,
        with telemetry.recording() as rec:  # len, payload, payload
            for n, off in enumerate(offsets):
                p0.corrupt(direction="s2c", offset=off, xor=0x01,
                           times=1)
                p1.corrupt(direction="s2c", offset=off, xor=0x01,
                           times=1)
                tokens = [f"x{n}.ok", f"x{n}-bad", f"y{n}.ok"]
                _assert_verdicts(tokens, cl.verify_batch(tokens))
        # Every corruption was DETECTED (never absorbed): each batch
        # needed at least one extra attempt or the oracle.
        c = rec.counters()
        detected = (c.get("fleet.failovers", 0)
                    + c.get("fleet.fallback_batches", 0))
        assert detected >= len(offsets), c


def test_corrupt_request_frame_detected_worker_side(fleet):
    """c2s corruption: the worker's CRC check rejects the request
    (drops the connection) instead of verifying an altered token."""
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], hedge_after=None)
        # Offset 30 lands inside the first token's bytes.
        p0.corrupt(direction="c2s", offset=30, xor=0xFF, times=1)
        p1.corrupt(direction="c2s", offset=30, xor=0xFF, times=1)
        tokens = ["req-corrupt-a.ok", "req-corrupt-b.bad"]
        with telemetry.recording() as rec:
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        assert (rec.counters().get("fleet.failovers", 0)
                + rec.counters().get("fleet.fallback_batches", 0)) >= 1


# ---------------------------------------------------------------------------
# fault: delayed accepts
# ---------------------------------------------------------------------------

def test_delayed_accepts_within_deadline(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=3.0)
        p0.delay_accept(0.4)
        p1.delay_accept(0.4)
        tokens = [f"da{i}.ok" for i in range(3)] + ["da-bad"]
        res = cl.verify_batch(tokens)
        _assert_verdicts(tokens, res)


def test_delayed_accepts_beyond_deadline_oracle(fleet):
    with ChaosProxy(lambda: fleet.address(0)) as p0, \
            ChaosProxy(lambda: fleet.address(1)) as p1:
        cl = _proxied_client(fleet, [p0, p1], attempt_timeout=0.5,
                             total_deadline=6.0, max_rounds=2,
                             hedge_after=None)
        p0.delay_accept(5.0)
        p1.delay_accept(5.0)
        tokens = ["slow.ok", "slow.bad"]
        with telemetry.recording() as rec:
            _assert_verdicts(tokens, cl.verify_batch(tokens))
        assert rec.counters().get("fleet.fallback_tokens", 0) == 2
