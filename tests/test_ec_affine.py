"""Affine-ladder ES* parity vs the Jacobian ladder and the host oracle.

The round-6 tentpole (VERDICT r5 #1): the affine window-add law
(2M+1S plus one batched product-tree inversion per window step) must
be bit-exact with the mixed-Jacobian law and the CPU oracle on every
curve and engine — INCLUDING the lanes the complete-ish Jacobian
formula used to absorb, which the affine law must handle explicitly:

- doubling at the chain merge (u1·G == u2·Q — constructible by anyone
  holding the private key);
- inverse points at the merge (u1·G == −u2·Q → infinity);
- an all-infinity G chain (e = 0 → u1 = 0), both rejecting and with a
  crafted ACCEPTING signature riding only the Q chain;
- r/s boundary values (0, 1, n−1, n) and e ≥ n;
- the in-ladder degenerate flags routing through the CPU oracle.

Keys and signatures are built with the dependency-free host
arithmetic (ec.HostECPublicKey / host_ecdsa_sign / _py_verify_one),
so this suite runs with or without the ``cryptography`` stack.
"""

import functools
import random

import numpy as np
import pytest

from cap_tpu.tpu import ec as tpuec
from cap_tpu.tpu.ec import (
    HostECPublicKey,
    curve,
    host_ecdsa_sign,
    scalar_mult,
    verify_ecdsa_batch,
)

_HLEN = {"P-256": 32, "P-384": 48, "P-521": 64}
CURVES = ["P-256", "P-384", "P-521"]


@functools.lru_cache(maxsize=None)
def _fixture(crv: str):
    """(table, names, sigs, digests, want) for one curve — every
    vector's expected verdict from the pure-integer oracle."""
    cp = curve(crv)
    rng = random.Random(0xC0FFEE + cp.nbits)
    cb = cp.coord_bytes
    hlen = _HLEN[crv]
    d = rng.randrange(1, cp.n)
    key = HostECPublicKey.from_private(crv, d)
    Q = (key.public_numbers().x, key.public_numbers().y)
    table = tpuec.ECKeyTable(crv, [key])

    def sig(r, s):
        return r.to_bytes(cb, "big") + s.to_bytes(cb, "big")

    def dig(e):
        return e.to_bytes(hlen, "big")

    digest = bytes(rng.randrange(256) for _ in range(hlen))
    e = int.from_bytes(digest, "big")
    r, s = host_ecdsa_sign(crv, d, e, rng.randrange(1, cp.n))

    vectors = [
        ("valid", sig(r, s), digest),
        # n−s is the OTHER valid half (low-s not enforced, like Go)
        ("valid-high-s", sig(r, cp.n - s), digest),
        ("tampered-s", sig(r, s + 1 if s + 1 < cp.n else s - 1), digest),
        ("tampered-r", sig(r + 1 if r + 1 < cp.n else r - 1, s), digest),
        ("r-zero", sig(0, s), digest),
        ("s-zero", sig(r, 0), digest),
        ("r-eq-n", sig(cp.n, s), digest),
        ("s-eq-n", sig(r, cp.n), digest),
        ("r-s-one", sig(1, 1), digest),
        ("r-s-n-minus-1", sig(cp.n - 1, cp.n - 1), digest),
    ]

    # Degenerate merges (need the private key to construct): with
    # s = 1, u2 = r and u1 = e, so e = d·r mod n makes the two chain
    # accumulators EQUAL points (doubling at the merge) and
    # e = −d·r mod n makes them inverse (merge → infinity). Both must
    # flag degenerate and re-verify on the oracle. The digest is only
    # 8·hlen bits (< nbits on P-521), so resample r until the needed
    # residue fits the digest width.
    lim = 1 << (8 * hlen)
    r0 = rng.randrange(1, cp.n)
    while d * r0 % cp.n >= lim:
        r0 = rng.randrange(1, cp.n)
    vectors.append(("deg-double-merge", sig(r0, 1), dig(d * r0 % cp.n)))
    r1 = rng.randrange(1, cp.n)
    while (cp.n - d * r1 % cp.n) % cp.n >= lim:
        r1 = rng.randrange(1, cp.n)
    vectors.append(("deg-inverse-merge", sig(r1, 1),
                    dig((cp.n - d * r1 % cp.n) % cp.n)))

    # e = 0: the whole G chain stays at infinity. Reject arm (random
    # r/s) and a crafted ACCEPT arm: R = u2·Q, r = R.x mod n,
    # s = r·u2⁻¹ (then u2 = r/s again, u1 = 0).
    vectors.append(("inf-g-reject", sig(r0, s), dig(0)))
    while True:
        u2 = rng.randrange(1, cp.n)
        ra = scalar_mult(cp, u2, Q)[0] % cp.n
        if ra:
            break
    vectors.append(("inf-g-accept", sig(ra, ra * pow(u2, -1, cp.n) % cp.n),
                    dig(0)))

    # All-ones digest: e ≥ n on P-256/P-384 (u1 reduction parity
    # between the engines and the oracle); on P-521 the 512-bit digest
    # cannot exceed n — it is simply another valid signature there.
    big = b"\xff" * hlen
    eb = int.from_bytes(big, "big")
    rb, sb = host_ecdsa_sign(crv, d, eb, rng.randrange(1, cp.n))
    vectors.append(("valid-e-ge-n", sig(rb, sb), big))

    names = [v[0] for v in vectors]
    sigs = [v[1] for v in vectors]
    digs = [v[2] for v in vectors]
    want = [tpuec._py_verify_one(table, 0, sg, dg)
            for sg, dg in zip(sigs, digs)]
    # the fixture itself must exercise both verdicts
    assert want.count(True) >= 3 and want.count(False) >= 5
    return table, names, sigs, digs, want


def _assert_parity(crv: str, ladder: str):
    table, names, sigs, digs, want = _fixture(crv)
    ok = verify_ecdsa_batch(table, sigs, digs,
                            np.zeros(len(sigs), np.int32), ladder=ladder)
    got = [bool(v) for v in ok]
    assert got == want, [
        (n, g, w) for n, g, w in zip(names, got, want) if g != w]


@pytest.mark.parametrize("crv", [
    "P-256",
    "P-384",
    # P-521 limb parity alone costs ~2 CPU-minutes on the 1-core tier-1
    # box; the ladder code paths it exercises are identical to P-384's,
    # only the limb count differs — run it with the slow suite
    pytest.param("P-521", marks=pytest.mark.slow),
])
def test_affine_limb_parity(crv, monkeypatch):
    monkeypatch.setenv("CAP_TPU_RNS", "0")
    _assert_parity(crv, "affine")


def test_affine_rns_parity_es256(monkeypatch):
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    _assert_parity("P-256", "affine")


@pytest.mark.heavy
@pytest.mark.parametrize("crv", [
    "P-384",
    pytest.param("P-521", marks=pytest.mark.slow),
])
def test_affine_rns_parity_heavy(crv, monkeypatch):
    """RNS engine on the larger curves — compile-heavy on CPU, same
    marker policy as the other RNS-on-CPU engine tests."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    _assert_parity(crv, "affine")


@pytest.mark.parametrize("engine", ["0", "1"], ids=["limb", "rns"])
def test_affine_vs_jacobian_identical_es256(engine, monkeypatch):
    """The two laws must agree verdict-for-verdict on the same batch
    (not just each match the oracle) — ladder selection cannot change
    observable behavior."""
    monkeypatch.setenv("CAP_TPU_RNS", engine)
    table, names, sigs, digs, want = _fixture("P-256")
    rows = np.zeros(len(sigs), np.int32)
    a = verify_ecdsa_batch(table, sigs, digs, rows, ladder="affine")
    j = verify_ecdsa_batch(table, sigs, digs, rows, ladder="jacobian")
    assert [bool(v) for v in a] == [bool(v) for v in j] == want


def test_degenerate_lanes_hit_oracle(monkeypatch):
    """The crafted merge degeneracies must actually raise the deg flag
    and route through the CPU-oracle re-verify (the parity contract),
    not silently produce a device verdict."""
    monkeypatch.setenv("CAP_TPU_RNS", "0")
    calls = []
    real = tpuec._cpu_verify_one

    def spy(table, row, sig_raw, digest):
        calls.append(row)
        return real(table, row, sig_raw, digest)

    monkeypatch.setattr(tpuec, "_cpu_verify_one", spy)
    _assert_parity("P-256", "affine")
    assert calls, "no lane was degenerate-flagged"


def test_ladder_mode_knob(monkeypatch):
    monkeypatch.delenv("CAP_TPU_EC_LADDER", raising=False)
    assert tpuec.ladder_mode() == "jacobian"
    monkeypatch.setenv("CAP_TPU_EC_LADDER", "affine")
    assert tpuec.ladder_mode() == "affine"
    assert tpuec.resolve_ladder(None) == "affine"
    monkeypatch.setenv("CAP_TPU_EC_LADDER", "bogus")
    assert tpuec.ladder_mode() == "jacobian"
    with pytest.raises(ValueError):
        tpuec.resolve_ladder("bogus")


def test_keyset_ladder_dispatch():
    """TPUBatchKeySet(ec_ladder=...) must route the packed ES path
    through the selected law with identical verdicts (needs the
    cryptography stack for JWT fixtures; skips where absent)."""
    pytest.importorskip("cryptography")
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    priv, pub = captest.generate_keys("ES256")
    toks = [captest.sign_jwt(priv, "ES256", captest.default_claims(
        sub=f"u{i}"), kid="k") for i in range(4)]
    bad = toks[0][:-4] + ("AAAA" if not toks[0].endswith("AAAA")
                          else "BBBB")
    batch = toks + [bad]
    with pytest.raises(Exception):
        TPUBatchKeySet([JWK(pub, kid="k")], ec_ladder="bogus")
    out = {}
    for ladder in ("jacobian", "affine"):
        ks = TPUBatchKeySet([JWK(pub, kid="k")], ec_ladder=ladder)
        out[ladder] = [not isinstance(r, Exception)
                       for r in ks.verify_batch(batch)]
    assert out["jacobian"] == out["affine"] == [True] * 4 + [False]


def test_py_oracle_agrees_with_signer():
    """Self-check of the pure-integer oracle against the host signer
    on fresh randomness (they share curve code but not verify logic)."""
    rng = random.Random(99)
    cp = curve("P-256")
    d = rng.randrange(1, cp.n)
    key = HostECPublicKey.from_private("P-256", d)
    table = tpuec.ECKeyTable("P-256", [key])
    digest = bytes(rng.randrange(256) for _ in range(32))
    e = int.from_bytes(digest, "big")
    r, s = host_ecdsa_sign("P-256", d, e, rng.randrange(1, cp.n))
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    assert tpuec._py_verify_one(table, 0, sig, digest)
    bad = bytearray(sig)
    bad[-1] ^= 1
    assert not tpuec._py_verify_one(table, 0, bytes(bad), digest)
