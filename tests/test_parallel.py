"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import hashlib

import numpy as np
import pytest

import jax

from cap_tpu.parallel import make_mesh, sharded_verify_step
from cap_tpu.parallel.mesh import shard_batch_arrays
from cap_tpu.tpu import limbs as L
from cap_tpu.tpu.rsa import RSAKeyTable, expected_pkcs1v15_em


@pytest.fixture(scope="module")
def rsa_fixture():
    # clean per-test skip (not an ERROR) on crypto-less hosts
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    msg = b"parallel test message"
    privs = [rsa.generate_private_key(public_exponent=65537, key_size=1024)
             for _ in range(2)]
    sigs = [p.sign(msg, padding.PKCS1v15(), hashes.SHA256()) for p in privs]
    table = RSAKeyTable(
        [(p.public_key().public_numbers().n,
          p.public_key().public_numbers().e) for p in privs])
    return table, sigs, hashlib.sha256(msg).digest()


def test_eight_devices_available():
    assert len(jax.devices()) >= 8


def test_sharded_verify_step_parity(rsa_fixture):
    import jax.numpy as jnp

    table, sigs, digest = rsa_fixture
    mesh = make_mesh(8)
    step = sharded_verify_step(mesh)

    n_tok = 32
    key_idx = (np.arange(n_tok) % 2).astype(np.int32)
    sig_rows = np.stack([np.frombuffer(sigs[i], np.uint8) for i in key_idx])
    lens = np.asarray([len(sigs[i]) for i in key_idx], np.int64)
    s_host = L.bytes_matrix_to_limbs(sig_rows, lens, table.k)
    sizes = np.asarray(table.sizes_bytes)[key_idx]
    expected_host = expected_pkcs1v15_em(
        [digest] * n_tok, "sha256", sizes, table.k)

    # Corrupt two tokens' signatures (flip a low limb bit).
    s_host = s_host.copy()
    s_host[0, 3] ^= 1
    s_host[0, 17] ^= 1

    key_idx_d, s_d, expected_d = shard_batch_arrays(
        mesh, key_idx, s_host, expected_host)
    ok, total = step(jnp.asarray(table.n_tab), jnp.asarray(table.np_tab),
                     jnp.asarray(table.r2_tab), key_idx_d, s_d, expected_d)
    ok = np.asarray(ok)
    want = np.ones(n_tok, bool)
    want[3] = want[17] = False
    assert (ok == want).all()
    assert int(total) == n_tok - 2


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = np.asarray(jax.jit(fn)(*args))
    assert out.all()
    g.dryrun_multichip(8)


def test_make_mesh_too_many_devices():
    with pytest.raises(ValueError):
        make_mesh(1_000_000)


def test_sharded_rns_verify_step():
    """The RNS/MXU RS verify under shard_map over the 8-device mesh."""
    import random

    import jax.numpy as jnp

    from cap_tpu.parallel.mesh import (
        make_mesh,
        shard_batch_arrays,
        sharded_rns_verify_step,
    )
    from cap_tpu.tpu import limbs as L
    from cap_tpu.tpu import rns

    rng = random.Random(0xD15C)

    def modulus(bits):
        p = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
        q = rng.getrandbits(bits // 2) | (1 << (bits // 2 - 1)) | 1
        return p * q

    k = 33  # 512-bit keys keep CPU compile time small
    mods = [modulus(512), modulus(512)]
    # random odd semiprimes can share a factor with a base prime;
    # regenerate until supported (real RSA keys never hit this)
    for _ in range(10):
        try:
            ctx = rns.context(512, k)
            table = rns.RNSKeyTable(ctx, mods)
            break
        except rns.RNSUnsupportedKey:
            mods = [modulus(512), modulus(512)]
    mesh = make_mesh(8)
    step = sharded_rns_verify_step(mesh, ctx)

    n_tok = 64
    idx = np.asarray([i % 2 for i in range(n_tok)], np.int32)
    s = [rng.randrange(mods[i]) for i in idx]
    want = [pow(x, 65537, mods[i]) for x, i in zip(s, idx)]
    s_l = L.ints_to_limbs(s, k)
    e_l = L.ints_to_limbs(want, k)
    jidx = jnp.asarray(idx)
    args = shard_batch_arrays(
        mesh, s_l, e_l,
        np.asarray(table.sig_c[jidx].T), np.asarray(table.n_B[jidx].T),
        np.asarray(table.a2_A[jidx].T), np.asarray(table.a2_B[jidx].T))
    ok, total = step(*args)
    assert np.asarray(ok).all()
    assert int(total) == n_tok


def _meshed_mixed_parity():
    from cap_tpu import testing as captest
    from cap_tpu.errors import InvalidSignatureError
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    jwks, signers = [], []
    for i, (alg, kw) in enumerate([
            ("RS256", {"rsa_bits": 1024}), ("RS256", {"rsa_bits": 1024}),
            ("ES256", {}), ("ES256", {}), ("EdDSA", {}),
            ("PS256", {"rsa_bits": 1024})]):
        priv, pub = captest.generate_keys(alg, **kw)
        jwks.append(JWK(pub, kid=f"m{i}"))
        signers.append((priv, alg, f"m{i}"))
    claims = captest.default_claims()
    toks = []
    for j in range(18):
        priv, alg, kid = signers[j % len(signers)]
        toks.append(captest.sign_jwt(priv, alg, claims, kid=kid))
    tam = toks[0][:-8] + ("AAAAAAAA" if not toks[0].endswith("AAAAAAAA")
                          else "BBBBBBBB")
    # toks[5] is PS256 (signer 5): tampering it exercises the meshed
    # device EMSA-PSS REJECTION path, not just its accept path
    tam_ps = toks[5][:-8] + ("AAAAAAAA"
                             if not toks[5].endswith("AAAAAAAA")
                             else "BBBBBBBB")
    batch = toks + [tam, tam_ps, "garbage"]

    mesh = make_mesh(8)
    meshed = TPUBatchKeySet(jwks, mesh=mesh)
    plain = TPUBatchKeySet(jwks)
    got = meshed.verify_batch(batch)
    want = plain.verify_batch(batch)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert isinstance(g, Exception) == isinstance(w, Exception)
        if not isinstance(g, Exception):
            assert g == w
    assert isinstance(got[-3], InvalidSignatureError)
    assert isinstance(got[-2], InvalidSignatureError)   # tampered PS256
    assert isinstance(got[-1], Exception)


def test_meshed_keyset_mixed_families():
    """TPUBatchKeySet(mesh=...): the PRODUCT batch path sharded over
    the 8-device mesh for all packed families (RS*, ES*, EdDSA, PS*
    with the device EMSA-PSS check) —
    verdict parity with the un-meshed keyset, rejections included
    (VERDICT r1 #3: multi-chip as a capability, not a demo). Runs the
    limb engines (CPU default); the RNS variant is the `heavy` tier
    below."""
    _meshed_mixed_parity()


@pytest.mark.heavy
def test_meshed_keyset_mixed_families_rns(monkeypatch):
    """Same parity with the RNS/MXU engines forced (accelerator path).
    Compile-heavy on CPU — excluded from the default tier; run with
    `pytest -m heavy` or `make test-all`."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    _meshed_mixed_parity()


def test_meshed_raw_mode_parity():
    """verify_batch_raw over a mesh: payload bytes match the unmeshed
    dict path's claims for accepts, error classes for rejects."""
    import json as jsonlib

    from cap_tpu import testing as captest
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

    jwks, toks = captest.headline_fixtures(64)
    tam = toks[0][:-8] + ("AAAAAAAA" if not toks[0].endswith("AAAAAAAA")
                          else "BBBBBBBB")
    batch = toks + [tam]
    meshed = TPUBatchKeySet(jwks, mesh=make_mesh(8))
    plain = TPUBatchKeySet(jwks)
    raws = meshed.verify_batch_raw(batch)
    dicts = plain.verify_batch(batch)
    for i, (r, d) in enumerate(zip(raws, dicts)):
        if isinstance(d, Exception):
            assert type(r) is type(d), f"tok {i}"
        else:
            assert jsonlib.loads(r) == d, f"tok {i}"
