"""Shared-memory CVB1 transport: ring invariants, both-chain e2e,
fallback matrix, and the kill -9 chaos contract.

The contract under test (ISSUE 13): a client killed at ANY point —
mid-write, mid-read — can never wedge or corrupt the worker; torn
records are structurally invisible (payload first, head published
last); everything a hostile producer CAN make visible (overrun
cursors, impossible lengths, foreign generations) maps onto the
socket parser's malformed classes and detaches the transport, while
surviving socket clients lose nothing.
"""

import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.serve import protocol as P
from cap_tpu.serve import shm_ring as R
from cap_tpu.serve.client import VerifyClient
from cap_tpu.serve.shm_client import ShmVerifyClient
from cap_tpu.serve.worker import VerifyWorker

try:
    from cap_tpu.serve import native_serve
    HAVE_NATIVE = bool(getattr(native_serve.load(), "cap_shm_ok",
                               False))
except Exception:  # noqa: BLE001 - no compiler
    HAVE_NATIVE = False

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHAINS = ["python"] + (["native"] if HAVE_NATIVE else [])


# ---------------------------------------------------------------------------
# ring invariants (pure Python, no worker)
# ---------------------------------------------------------------------------


def test_region_create_open_roundtrip(tmp_path):
    path = str(tmp_path / "region")
    r = R.ShmRegion.create(path, req_size=8192, resp_size=4096)
    try:
        r2 = R.ShmRegion.open(path)
        assert r2.gen == r.gen
        assert r2.ring_size == {"req": 8192, "resp": 4096}
        assert r2.ring_off == {"req": R.HDR_SIZE,
                               "resp": R.HDR_SIZE + 8192}
        r2.close()
    finally:
        r.close(unlink=True)
    assert not os.path.exists(path)


def test_region_open_rejects_garbage(tmp_path):
    bad = tmp_path / "bad"
    bad.write_bytes(b"\x00" * 16384)
    with pytest.raises(R.ShmFormatError):
        R.ShmRegion.open(str(bad))
    short = tmp_path / "short"
    short.write_bytes(b"\x00" * 64)
    with pytest.raises(R.ShmFormatError):
        R.ShmRegion.open(str(short))
    # valid magic, inconsistent ring geometry
    path = str(tmp_path / "geom")
    r = R.ShmRegion.create(path, req_size=4096, resp_size=4096)
    r.close()
    with open(path, "r+b") as f:
        f.seek(R.OFF_REQ_SIZE)
        f.write(struct.pack("<Q", 12345))        # not a power of two
    with pytest.raises(R.ShmFormatError):
        R.ShmRegion.open(path)
    os.unlink(path)


def test_ring_roundtrip_with_wraparound(tmp_path):
    r = R.ShmRegion.create(str(tmp_path / "ring"), req_size=4096,
                           resp_size=4096)
    try:
        prod = R.RingProducer(r, "req")
        cons = R.RingConsumer(r, "req")
        for i in range(300):                    # >> ring capacity
            msg = bytes([i & 0xFF]) * (1 + (i * 37) % 900)
            prod.write(msg)
            assert cons.read(timeout=1.0) == msg, i
        assert cons.read(timeout=0.01) is None
    finally:
        r.close(unlink=True)


def test_torn_write_invisible(tmp_path):
    """A producer killed mid-write never published: bytes past the
    head are garbage by definition and the consumer must see NOTHING
    — the kill -9 mid-write contract at the record level."""
    r = R.ShmRegion.create(str(tmp_path / "torn"), req_size=4096,
                           resp_size=4096)
    try:
        # simulate the partial write: record header + half a payload,
        # head NOT advanced
        mm = r._mm
        struct.pack_into("<II", mm, R.HDR_SIZE, 100, r.gen)
        mm[R.HDR_SIZE + 8: R.HDR_SIZE + 58] = b"T" * 50
        cons = R.RingConsumer(r, "req")
        assert cons.read(timeout=0.05) is None
        # a later, complete write is served normally
        R.RingProducer(r, "req").write(b"after-torn")
        assert cons.read(timeout=1.0) == b"after-torn"
    finally:
        r.close(unlink=True)


def test_stale_generation_detected(tmp_path):
    r = R.ShmRegion.create(str(tmp_path / "stale"), req_size=4096,
                           resp_size=4096, gen=7)
    try:
        mm = r._mm
        struct.pack_into("<II", mm, R.HDR_SIZE, 5, 999)  # foreign gen
        mm[R.HDR_SIZE + 8: R.HDR_SIZE + 13] = b"stale"
        struct.pack_into("<Q", mm, 64, 16)               # publish
        with pytest.raises(R.StaleGenerationError):
            R.RingConsumer(r, "req").read(timeout=0.5)
    finally:
        r.close(unlink=True)


def test_overrun_cursor_detected(tmp_path):
    r = R.ShmRegion.create(str(tmp_path / "over"), req_size=4096,
                           resp_size=4096)
    try:
        struct.pack_into("<Q", r._mm, 64, 4096 + 64)  # head >> tail+size
        with pytest.raises(P.MalformedFrameError):
            R.RingConsumer(r, "req").read(timeout=0.5)
    finally:
        r.close(unlink=True)


def test_oversized_frame_rejected_client_side(tmp_path):
    r = R.ShmRegion.create(str(tmp_path / "big"), req_size=4096,
                           resp_size=4096)
    try:
        with pytest.raises(P.FrameTooLargeError):
            R.RingProducer(r, "req").write(b"x" * 3000)  # > size/2
    finally:
        r.close(unlink=True)


@pytest.mark.skipif(not HAVE_NATIVE, reason="native shm TU not built")
def test_python_c_ring_interop(tmp_path):
    """The Python ring and the C ring speak the same bytes: records
    written by either side are read intact by the other."""
    import ctypes

    import numpy as np

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib = native_serve.load()
    path = str(tmp_path / "interop")
    r = R.ShmRegion.create(path, req_size=8192, resp_size=8192)
    try:
        cr = lib.cap_shm_open(path.encode())
        assert cr
        buf = np.zeros(8192, np.uint8)
        try:
            prod = R.RingProducer(r, "req")
            for i in range(200):                # forces wraparound
                msg = (b"py->c-%03d-" % i) + b"z" * (i % 500)
                prod.write(msg)
                n = int(lib.cap_shm_read(
                    ctypes.c_void_p(cr), 0,
                    buf.ctypes.data_as(u8p), 8192, 1.0))
                assert n == len(msg) and buf[:n].tobytes() == msg, i
            cons = R.RingConsumer(r, "resp")
            for i in range(200):
                msg = (b"c->py-%03d-" % i) + b"q" * (i % 500)
                arr = np.frombuffer(msg, np.uint8)
                assert int(lib.cap_shm_write(
                    ctypes.c_void_p(cr), 1,
                    arr.ctypes.data_as(u8p), len(msg), 1.0)) == 0
                assert cons.read(timeout=1.0) == msg, i
        finally:
            lib.cap_shm_close(ctypes.c_void_p(cr), 0)
    finally:
        r.close(unlink=True)


# ---------------------------------------------------------------------------
# end-to-end, both serve chains
# ---------------------------------------------------------------------------


@pytest.fixture(params=CHAINS)
def shm_worker(request):
    telemetry.enable()
    w = VerifyWorker(StubKeySet(), serve_native=request.param == "native",
                     max_wait_ms=1.0, transport="shm")
    assert w.serve_chain == request.param
    assert w.transport == "shm"
    yield w
    w.close(deadline_s=10)


def test_shm_verify_ping_stats(shm_worker):
    host, port = shm_worker.address
    with ShmVerifyClient(host, port) as cl:
        assert cl.transport == "shm", cl.attach_error
        out = cl.verify_batch(["s1.ok", "s2.bad", "s3.ok"])
        assert out[0] == {"sub": "s1.ok"}
        assert isinstance(out[1], Exception)
        assert out[2] == {"sub": "s3.ok"}
        assert cl.ping()
        st = cl.stats()
        assert st["transport"] == "shm"
        assert st["counters"].get("serve.shm.attaches", 0) >= 1
        assert st["counters"].get("serve.shm.frames", 0) >= 3


def test_shm_crc_and_traced_frames(shm_worker):
    host, port = shm_worker.address
    with ShmVerifyClient(host, port, crc=True) as cl:
        assert cl.transport == "shm"
        assert cl.verify_batch(["crc.ok"])[0] == {"sub": "crc.ok"}
    with ShmVerifyClient(host, port) as cl:
        out = cl.verify_batch(["tr.ok"], trace="ab12cd34ab12cd34")
        assert out[0] == {"sub": "tr.ok"}


def test_shm_keys_push_in_order(shm_worker):
    host, port = shm_worker.address
    with ShmVerifyClient(host, port) as cl:
        assert cl.verify_batch(["k1.ok"])[0] == {"sub": "k1.ok"}
        assert cl.push_keys({"keys": []}, epoch=5) == 5
        assert cl.verify_batch(["k2.ok"])[0] == {"sub": "k2.ok"}
    assert shm_worker.key_epoch == 5


def test_shm_sustained_pipelined_load(shm_worker):
    host, port = shm_worker.address
    with ShmVerifyClient(host, port) as cl:
        for i in range(60):
            toks = [f"load-{i}-{j}.ok" for j in range(32)]
            out = cl.verify_batch(toks)
            assert [r["sub"] for r in out] == toks
    st = _socket_stats(shm_worker)
    assert _proto_errors(st) == 0


def _socket_stats(worker) -> dict:
    host, port = worker.address
    with VerifyClient(host, port) as cl:
        return cl.stats()


def _proto_errors(st: dict) -> int:
    c = st.get("counters") or {}
    return (c.get("worker.protocol_errors", 0)
            + c.get("serve.native.protocol_errors", 0))


def test_gauges_and_capstat_cell(shm_worker):
    gauges = shm_worker._obs_gauges()
    assert gauges["serve.shm.active"] == 1.0
    from tools import capstat

    text = capstat.render_fleet(
        {"w0": {"snapshot": {"v": 1, "counters": {}, "gauges": {},
                             "series": {}},
                "extra": gauges}})
    assert "tr=shm" in text


# ---------------------------------------------------------------------------
# fallback matrix
# ---------------------------------------------------------------------------


@pytest.fixture(params=CHAINS)
def socket_worker(request):
    telemetry.enable()
    w = VerifyWorker(StubKeySet(), serve_native=request.param == "native",
                     max_wait_ms=1.0, transport="socket")
    assert w.serve_chain == request.param
    yield w
    w.close(deadline_s=10)


def test_attach_refused_keeps_socket_serving(socket_worker):
    """The graceful-fallback contract: a transport=socket worker acks
    status 1 and the SAME connection keeps serving; the refusal is
    counted serve.shm_fallbacks on whichever chain refused."""
    host, port = socket_worker.address
    with ShmVerifyClient(host, port) as cl:
        assert cl.transport == "socket"
        assert cl.attach_error and "TypeError" in cl.attach_error
        assert cl.verify_batch(["fb.ok"])[0] == {"sub": "fb.ok"}
        assert cl.ping()
    st = _socket_stats(socket_worker)
    assert (st["counters"].get("serve.shm_fallbacks", 0) >= 1
            or telemetry.active().counters().get(
                "serve.shm_fallbacks", 0) >= 1)
    assert st["transport"] == "socket"


def test_stale_worker_drop_redials_socket_only():
    """A worker whose parser predates frame type 15 DROPS the
    connection on the unknown type; the client must absorb that and
    redial socket-only — negotiation can never cost a working
    client."""
    import socket as _socket
    import threading

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    host, port = srv.getsockname()
    accepted = []

    def stale_worker():
        # first conn: read a little, then slam it shut (the stale
        # parser's unknown-type drop); second conn: answer one plain
        # verify frame like an old worker would
        c1, _ = srv.accept()
        accepted.append(1)
        c1.recv(4096)
        c1.close()
        c2, _ = srv.accept()
        accepted.append(2)
        rd = P.FrameReader(c2)
        ftype, entries = rd.recv_frame()
        assert ftype == P.T_VERIFY_REQ
        P.send_response(c2, [{"sub": t} for t in entries])
        c2.close()

    t = threading.Thread(target=stale_worker, daemon=True)
    t.start()
    try:
        with ShmVerifyClient(host, port, timeout=10) as cl:
            assert cl.transport == "socket"
            assert cl.attach_error is not None
            out = cl.verify_batch(["stale.ok"])
            assert out[0] == {"sub": "stale.ok"}
    finally:
        srv.close()
    assert accepted == [1, 2]


# ---------------------------------------------------------------------------
# chaos: kill -9 an shm client mid-write / mid-read, both chains
# ---------------------------------------------------------------------------

_CHAOS_CLIENT = r"""
import sys, time
from cap_tpu.serve import protocol
from cap_tpu.serve.shm_client import ShmVerifyClient

mode, host, port = sys.argv[1], sys.argv[2], int(sys.argv[3])
# read mode: a TINY response ring, so the worker's producer actually
# fills it and must give up (not wedge) when we die without reading
cl = ShmVerifyClient(host, port,
                     ring_bytes=4096 if mode == "read" else 1 << 20)
assert cl.transport == "shm", cl.attach_error
print("ATTACHED", cl._region.path, flush=True)
if mode == "write":
    i = 0
    while True:                      # hammer writes until killed
        i += 1
        cl.verify_batch([f"chaos-{i}-{j}.ok" for j in range(64)])
elif mode == "read":
    # submit work, then never consume the response ring — the worker
    # writes responses until the ring fills, then we get killed
    for i in range(4):
        cl._send(protocol.send_request,
                 [f"mid-read-{i}-{j}.ok" for j in range(64)])
    print("UNREAD", flush=True)
    time.sleep(60)
"""


@pytest.mark.chaos
@pytest.mark.parametrize("chain", CHAINS)
@pytest.mark.parametrize("mode", ["write", "read"])
def test_kill9_shm_client_worker_survives(chain, mode, tmp_path):
    """kill -9 the shm client mid-write and mid-read under sustained
    load: the worker survives, the ring file is reclaimed, and a
    surviving SOCKET client observes zero wrong verdicts and zero
    lost submissions throughout."""
    telemetry.enable()
    w = VerifyWorker(StubKeySet(), serve_native=chain == "native",
                     max_wait_ms=1.0, transport="shm")
    try:
        host, port = w.address
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHAOS_CLIENT, mode, host,
             str(port)],
            cwd=REPO, stdout=subprocess.PIPE, text=True, bufsize=1,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            line = proc.stdout.readline()
            assert line.startswith("ATTACHED"), line
            ring_path = line.split()[1]
            if mode == "read":
                assert proc.stdout.readline().startswith("UNREAD")
            # surviving socket client drives load the whole time
            with VerifyClient(host, port) as survivor:
                for i in range(5):
                    toks = [f"sv-{mode}-{i}-{j}.ok" for j in range(16)]
                    out = survivor.verify_batch(toks)
                    assert [r["sub"] for r in out] == toks
                time.sleep(0.2)      # let the chaos client really run
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait(timeout=10)
                # zero wrong verdicts / zero lost submissions AFTER
                # the kill, on the same worker
                for i in range(10):
                    toks = [f"sv2-{mode}-{i}-{j}.ok"
                            for j in range(16)]
                    out = survivor.verify_batch(toks)
                    assert [r["sub"] for r in out] == toks
                st = survivor.stats()
            assert st["counters"].get("serve.shm.attaches", 0) >= 1
            # the worker reclaims the region file (detach janitor);
            # give the EOF probe a beat
            deadline = time.monotonic() + 10
            while os.path.exists(ring_path) \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
            assert not os.path.exists(ring_path), \
                "ring file not reclaimed after kill -9"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    finally:
        w.close(deadline_s=10)


@pytest.mark.chaos
@pytest.mark.parametrize("chain", CHAINS)
def test_stale_generation_frames_counted_and_survived(chain):
    """A record stamped by a foreign generation poisons only ITS
    connection: counted (serve.shm.stale_gen), transport detached,
    worker keeps serving everyone else."""
    telemetry.enable()
    telemetry.active().reset()
    w = VerifyWorker(StubKeySet(), serve_native=chain == "native",
                     max_wait_ms=1.0, transport="shm")
    try:
        host, port = w.address
        cl = ShmVerifyClient(host, port)
        try:
            assert cl.transport == "shm"
            assert cl.verify_batch(["pre.ok"])[0] == {"sub": "pre.ok"}
            # inject a foreign-generation record directly
            region = cl._region
            mm = region._mm
            head = region.cursor("req", "head")
            size = region.ring_size["req"]
            off = region.ring_off["req"] + (head & (size - 1))
            struct.pack_into("<II", mm, off, 5, region.gen + 1)
            mm[off + 8: off + 13] = b"stale"
            region.set_cursor("req", "head", head + 16)
            # the worker detaches this connection
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                st = _socket_stats(w)
                stale = (st["counters"].get("serve.shm.stale_gen", 0)
                         or telemetry.active().counters().get(
                             "serve.shm.stale_gen", 0))
                if stale:
                    break
                time.sleep(0.1)
            assert stale >= 1, "stale-generation record not counted"
        finally:
            cl.close()
        # everyone else unaffected
        with VerifyClient(host, port) as ok_client:
            assert ok_client.verify_batch(["post.ok"])[0] == \
                {"sub": "post.ok"}
        with ShmVerifyClient(host, port) as cl2:
            assert cl2.transport == "shm"
            assert cl2.verify_batch(["post2.ok"])[0] == \
                {"sub": "post2.ok"}
    finally:
        w.close(deadline_s=10)


@pytest.mark.chaos
def test_fleet_kill9_shm_client_postmortem_shows_shm():
    """Fleet form of the chaos contract: a pool-supervised worker
    serving shm keeps its pool healthy through a client kill -9, and
    its graceful-restart postmortem carries the serve.shm.* counters."""
    from cap_tpu.fleet.pool import WorkerPool

    chain = "native" if HAVE_NATIVE else "python"
    pool = WorkerPool(1, keyset_spec="stub", transport="shm",
                      serve_chain=chain)
    try:
        assert pool.wait_all_ready(60)
        assert pool.transports() == {0: "shm"}
        host, port = pool.endpoints()[0]
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHAOS_CLIENT, "write", host,
             str(port)],
            cwd=REPO, stdout=subprocess.PIPE, text=True, bufsize=1,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            assert proc.stdout.readline().startswith("ATTACHED")
            time.sleep(0.3)
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
        with VerifyClient(host, port) as cl:
            out = cl.verify_batch(["fleet-alive.ok"])
            assert out[0] == {"sub": "fleet-alive.ok"}
        pool.restart(0, graceful=True)
        pm = pool.postmortem(0)
        assert pm is not None
        counters = (pm.get("stats") or {}).get("counters") or {}
        assert counters.get("serve.shm.attaches", 0) >= 1, counters
    finally:
        pool.close()
