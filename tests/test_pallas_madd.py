"""Fused Pallas mixed-add vs the XLA RNS path (interpret mode on CPU).

The kernel must be BIT-identical to ec_rns._madd_rns + the ladder's
lift/select bookkeeping: same fixed-point ops, same bounds. This runs
the full ECDSA verify through both paths on the same tokens —
successes, tampered signatures, and range-check rejections.
"""

import hashlib

import numpy as np
import pytest

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec as cec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
)

from cap_tpu.tpu.ec import ECKeyTable, curve, verify_ecdsa_batch


@pytest.mark.heavy
def test_fused_madd_matches_xla_path(monkeypatch):
    monkeypatch.setenv("CAP_TPU_RNS", "1")

    privs = [cec.generate_private_key(cec.SECP256R1()) for _ in range(2)]
    msg = b"pallas madd parity"
    digest = hashlib.sha256(msg).digest()
    sigs, rows = [], []
    for i, p in enumerate(privs):
        r, s = decode_dss_signature(p.sign(msg, cec.ECDSA(hashes.SHA256())))
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
        rows.append(i)
    bad = bytearray(sigs[0])
    bad[-1] ^= 1
    sigs.append(bytes(bad)); rows.append(0)
    bad = bytearray(sigs[0])
    bad[0] ^= 0x80
    sigs.append(bytes(bad)); rows.append(0)
    sigs.append(b"\x00" * 64); rows.append(0)
    n_int = curve("P-256").n
    sigs.append(sigs[0][:32] + (n_int - 1).to_bytes(32, "big"))
    rows.append(0)
    digests = [digest] * len(sigs)
    rows = np.asarray(rows, np.int32)

    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "0")
    table = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_xla = verify_ecdsa_batch(table, sigs, digests, rows)

    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "1")
    # fresh table: the jitted core caches per (crv, nbits, wbits) and
    # the fused flag is read at trace time
    from cap_tpu.tpu import ec_rns
    ec_rns._ecdsa_rns_core.clear_cache()
    table2 = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_fused = verify_ecdsa_batch(table2, sigs, digests, rows)
    ec_rns._ecdsa_rns_core.clear_cache()

    assert list(ok_xla) == list(ok_fused)
    assert list(ok_xla) == [True, True, False, False, False, False]
