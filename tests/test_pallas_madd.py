"""Fused Pallas mixed-add vs the XLA RNS path (interpret mode on CPU).

The kernel must be BIT-identical to ec_rns._madd_rns + the ladder's
lift/select bookkeeping: same fixed-point ops, same bounds. This runs
the full ECDSA verify through both paths on the same tokens —
successes, tampered signatures, and range-check rejections.
"""

import hashlib

import numpy as np
import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec as cec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
)

from cap_tpu.tpu.ec import ECKeyTable, curve, verify_ecdsa_batch


@pytest.mark.heavy
def test_fused_madd_matches_xla_path(monkeypatch):
    monkeypatch.setenv("CAP_TPU_RNS", "1")

    privs = [cec.generate_private_key(cec.SECP256R1()) for _ in range(2)]
    msg = b"pallas madd parity"
    digest = hashlib.sha256(msg).digest()
    sigs, rows = [], []
    for i, p in enumerate(privs):
        r, s = decode_dss_signature(p.sign(msg, cec.ECDSA(hashes.SHA256())))
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
        rows.append(i)
    bad = bytearray(sigs[0])
    bad[-1] ^= 1
    sigs.append(bytes(bad)); rows.append(0)
    bad = bytearray(sigs[0])
    bad[0] ^= 0x80
    sigs.append(bytes(bad)); rows.append(0)
    sigs.append(b"\x00" * 64); rows.append(0)
    n_int = curve("P-256").n
    sigs.append(sigs[0][:32] + (n_int - 1).to_bytes(32, "big"))
    rows.append(0)
    digests = [digest] * len(sigs)
    rows = np.asarray(rows, np.int32)

    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "0")
    table = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_xla = verify_ecdsa_batch(table, sigs, digests, rows)

    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "1")
    # fresh table: the jitted core caches per (crv, nbits, wbits) and
    # the fused flag is read at trace time
    from cap_tpu.tpu import ec_rns
    ec_rns._ecdsa_rns_core.clear_cache()
    table2 = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_fused = verify_ecdsa_batch(table2, sigs, digests, rows)
    ec_rns._ecdsa_rns_core.clear_cache()

    assert list(ok_xla) == list(ok_fused)
    assert list(ok_xla) == [True, True, False, False, False, False]


@pytest.mark.heavy
def test_fused_ladder_matches_xla_path(monkeypatch):
    """Whole-ladder fusion (pallas_madd.ladder_fused, interpret mode):
    same verdicts as the XLA path on accepts, a tampered signature, and
    an all-zero (range-rejected) signature. The per-window and fused
    ladders share _madd_math, so this pins the grid/masking plumbing —
    pre-gathered window rows, the entry-infinity scan, VMEM-resident
    state init at window 0 — not re-derived arithmetic."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")

    privs = [cec.generate_private_key(cec.SECP256R1()) for _ in range(2)]
    digest = hashlib.sha256(b"ladder parity").digest()
    sigs, rows = [], []
    for i, p in enumerate(privs):
        r, s = decode_dss_signature(
            p.sign(b"ladder parity", cec.ECDSA(hashes.SHA256())))
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
        rows.append(i)
    bad = bytearray(sigs[0])
    bad[-1] ^= 1
    sigs.append(bytes(bad)); rows.append(0)
    sigs.append(b"\x00" * 64); rows.append(0)
    digests = [digest] * len(sigs)
    rows = np.asarray(rows, np.int32)

    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "0")
    table = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_xla = verify_ecdsa_batch(table, sigs, digests, rows)

    from cap_tpu.tpu import ec_rns
    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "1")
    monkeypatch.setenv("CAP_TPU_PALLAS_LADDER", "1")
    ec_rns._ecdsa_rns_core.clear_cache()
    table2 = ECKeyTable("P-256", [p.public_key() for p in privs])
    ok_ladder = verify_ecdsa_batch(table2, sigs, digests, rows)
    ec_rns._ecdsa_rns_core.clear_cache()

    assert list(ok_xla) == list(ok_ladder)
    assert list(ok_xla) == [True, True, False, False]


@pytest.mark.heavy
def test_fused_redc_matches_xla_path(monkeypatch):
    """Fused REDC kernel (pallas_redc, interpret mode): same verdicts
    as the XLA path for ECDSA and Ed25519 — it now defaults ON for TPU
    backends, so its arithmetic needs its own parity pin, not just
    incidental bench coverage."""
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    monkeypatch.setenv("CAP_TPU_PALLAS_MADD", "0")

    privs = [cec.generate_private_key(cec.SECP256R1()) for _ in range(2)]
    digest = hashlib.sha256(b"redc parity").digest()
    sigs, rows = [], []
    for i, p in enumerate(privs):
        r, s = decode_dss_signature(
            p.sign(b"redc parity", cec.ECDSA(hashes.SHA256())))
        sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
        rows.append(i)
    bad = bytearray(sigs[0])
    bad[-1] ^= 1
    sigs.append(bytes(bad)); rows.append(0)
    digests = [digest] * len(sigs)
    rows = np.asarray(rows, np.int32)

    from cryptography.hazmat.primitives.asymmetric import ed25519 as ced
    from cap_tpu.tpu import ec_rns, ed25519_rns
    from cap_tpu.tpu.ed25519 import Ed25519KeyTable, verify_ed25519_batch

    ed_priv = ced.Ed25519PrivateKey.generate()
    ed_table_keys = [ed_priv.public_key()]
    ed_msgs = [b"redc parity ed", b"redc parity ed 2"]
    ed_sigs = [ed_priv.sign(m) for m in ed_msgs]
    ed_bad = bytearray(ed_sigs[0])
    ed_bad[-1] ^= 1
    ed_msgs.append(ed_msgs[0])
    ed_sigs.append(bytes(ed_bad))
    ed_rows = np.zeros(3, np.int32)

    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("CAP_TPU_PALLAS", flag)
        ec_rns._ecdsa_rns_core.clear_cache()
        ed25519_rns._ed25519_rns_core.clear_cache()
        table = ECKeyTable("P-256", [p.public_key() for p in privs])
        ok_ec = list(verify_ecdsa_batch(table, sigs, digests, rows))
        ed_table = Ed25519KeyTable(ed_table_keys)
        ok_ed = list(verify_ed25519_batch(ed_table, ed_sigs, ed_msgs,
                                          ed_rows))
        results[flag] = (ok_ec, ok_ed)
        ec_rns._ecdsa_rns_core.clear_cache()
        ed25519_rns._ed25519_rns_core.clear_cache()

    assert results["0"] == results["1"]
    assert results["0"][0] == [True, True, False]
    assert results["0"][1] == [True, True, False]


@pytest.mark.heavy
def test_fused_edwards_add_matches_xla_path(monkeypatch):
    """Fused Edwards mixed-add (pallas_edw, interpret mode): same
    Ed25519 verdicts as the XLA ladder — default ON for TPU, so its
    arithmetic gets its own parity pin."""
    from cryptography.hazmat.primitives.asymmetric import ed25519 as ced
    from cap_tpu.tpu import ed25519_rns
    from cap_tpu.tpu.ed25519 import Ed25519KeyTable, verify_ed25519_batch

    monkeypatch.setenv("CAP_TPU_PALLAS", "0")
    priv = ced.Ed25519PrivateKey.generate()
    priv2 = ced.Ed25519PrivateKey.generate()
    msgs = [b"edw parity %d" % i for i in range(4)]
    sigs = [priv.sign(m) for m in msgs[:2]] + \
        [priv2.sign(m) for m in msgs[2:]]
    bad = bytearray(sigs[0])
    bad[-1] ^= 1
    msgs.append(msgs[0])
    sigs.append(bytes(bad))
    rows = np.asarray([0, 0, 1, 1, 0], np.int32)

    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("CAP_TPU_PALLAS_EDW", flag)
        ed25519_rns._ed25519_rns_core.clear_cache()
        table = Ed25519KeyTable([priv.public_key(), priv2.public_key()])
        results[flag] = [bool(v) for v in verify_ed25519_batch(
            table, sigs, msgs, rows)]
        ed25519_rns._ed25519_rns_core.clear_cache()

    assert results["0"] == results["1"]
    assert results["0"] == [True, True, True, True, False]


@pytest.mark.heavy
def test_compiled_mosaic_parity_on_chip():
    """The COMPILED Mosaic kernel vs the XLA path on the real chip.

    The interpret-mode test above pins the kernel's arithmetic; a
    Mosaic miscompile would only surface as a mysterious bench error
    (VERDICT r3 #5). This runs the same accept/tamper/range-reject
    vectors through both paths on the attached TPU in a subprocess
    (the suite's conftest pins this process to the CPU mesh), and
    diffs the verdict vectors bitwise. Auto-skips without a TPU.
    """
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = r"""
import json, sys, hashlib
sys.path.insert(0, %r)
import jax
if jax.default_backend() in ("cpu",):
    print(json.dumps({"skip": "no TPU backend"})); sys.exit(0)
import numpy as np
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec as cec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature)
from cap_tpu.tpu.ec import ECKeyTable, curve, verify_ecdsa_batch
from cap_tpu.tpu import ec_rns
import os

privs = [cec.generate_private_key(cec.SECP256R1()) for _ in range(2)]
msg = b"mosaic parity"
digest = hashlib.sha256(msg).digest()
sigs, rows = [], []
for i, p in enumerate(privs):
    r, s = decode_dss_signature(p.sign(msg, cec.ECDSA(hashes.SHA256())))
    sigs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big"))
    rows.append(i)
bad = bytearray(sigs[0]); bad[-1] ^= 1
sigs.append(bytes(bad)); rows.append(0)
bad = bytearray(sigs[0]); bad[0] ^= 0x80
sigs.append(bytes(bad)); rows.append(0)
sigs.append(b"\x00" * 64); rows.append(0)
n_int = curve("P-256").n
sigs.append(sigs[0][:32] + (n_int - 1).to_bytes(32, "big")); rows.append(0)
digests = [digest] * len(sigs)
rows = np.asarray(rows, np.int32)

os.environ["CAP_TPU_RNS"] = "1"
# the baseline must be the true XLA path: a fused-REDC env flag would
# route BOTH runs through Mosaic and make the diff vacuous
os.environ["CAP_TPU_PALLAS"] = "0"
os.environ["CAP_TPU_PALLAS_MADD"] = "0"
table = ECKeyTable("P-256", [p.public_key() for p in privs])
ok_xla = [bool(v) for v in verify_ecdsa_batch(table, sigs, digests, rows)]

os.environ["CAP_TPU_PALLAS_MADD"] = "1"
ec_rns._ecdsa_rns_core.clear_cache()
table2 = ECKeyTable("P-256", [p.public_key() for p in privs])
ok_mosaic = [bool(v)
             for v in verify_ecdsa_batch(table2, sigs, digests, rows)]

os.environ["CAP_TPU_PALLAS_LADDER"] = "1"  # fused whole-ladder kernel
ec_rns._ecdsa_rns_core.clear_cache()
table3 = ECKeyTable("P-256", [p.public_key() for p in privs])
ok_ladder = [bool(v)
             for v in verify_ecdsa_batch(table3, sigs, digests, rows)]

os.environ["CAP_TPU_PALLAS_LADDER"] = "0"
os.environ["CAP_TPU_PALLAS"] = "1"         # fused REDC (TPU default)
ec_rns._ecdsa_rns_core.clear_cache()
table4 = ECKeyTable("P-256", [p.public_key() for p in privs])
ok_redc = [bool(v)
           for v in verify_ecdsa_batch(table4, sigs, digests, rows)]

# Ed25519: compiled fused Edwards add (TPU default) vs XLA ladder.
# Drop the fused-REDC default first — the EDW=0 baseline must be the
# true XLA path or a shared-REDC miscompile hits both runs equally.
os.environ["CAP_TPU_PALLAS"] = "0"
from cryptography.hazmat.primitives.asymmetric import ed25519 as ced
from cap_tpu.tpu import ed25519_rns
from cap_tpu.tpu.ed25519 import Ed25519KeyTable, verify_ed25519_batch
ed_priv = ced.Ed25519PrivateKey.generate()
ed_msgs = [b"mosaic parity ed 1", b"mosaic parity ed 2"]
ed_sigs = [ed_priv.sign(m) for m in ed_msgs]
edb = bytearray(ed_sigs[0]); edb[-1] ^= 1
ed_msgs.append(ed_msgs[0]); ed_sigs.append(bytes(edb))
ed_rows = np.zeros(3, np.int32)
ed_res = {}
for flag in ("0", "1"):
    os.environ["CAP_TPU_PALLAS_EDW"] = flag
    ed25519_rns._ed25519_rns_core.clear_cache()
    tbl = Ed25519KeyTable([ed_priv.public_key()])
    ed_res[flag] = [bool(v) for v in verify_ed25519_batch(
        tbl, ed_sigs, ed_msgs, ed_rows)]
    ed25519_rns._ed25519_rns_core.clear_cache()
print(json.dumps({"xla": ok_xla, "mosaic": ok_mosaic,
                  "ladder": ok_ladder, "redc": ok_redc,
                  "ed_xla": ed_res["0"], "ed_fused": ed_res["1"]}))
""" % (repo,)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "CAP_TPU_"))}
    res = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=900)
    assert res.returncode == 0, res.stderr[-2000:]
    out = json.loads(res.stdout.strip().splitlines()[-1])
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["xla"] == out["mosaic"], out
    assert out["xla"] == out["ladder"], out
    assert out["xla"] == out["redc"], out
    assert out["xla"] == [True, True, False, False, False, False], out
    assert out["ed_xla"] == out["ed_fused"] == [True, True, False], out
