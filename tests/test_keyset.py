"""KeySet conformance: all 10 algs × static/JWKS kinds, tamper cases.

Mirrors the reference's parity tables (jwt/keyset_test.go:27-514): every
supported algorithm with per-alg key sizes, verified through both
StaticKeySet and a JWKS endpoint, plus tampered-segment rejection.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.errors import (
    InvalidJWKSError,
    InvalidSignatureError,
    NilParameterError,
)
from cap_tpu.jwt import (
    JSONWebKeySet,
    StaticKeySet,
    algs,
    new_oidc_discovery_keyset,
    parse_public_key_pem,
)
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import serialize_public_key

ALL_ALGS = sorted(algs.SUPPORTED_ALGORITHMS)

# (alg, key kwargs) ladder matching the reference's per-alg key sizes.
KEY_LADDER = [
    ("RS256", {"rsa_bits": 2048}),
    ("RS384", {"rsa_bits": 3072}),
    ("RS512", {"rsa_bits": 4096}),
    ("PS256", {"rsa_bits": 2048}),
    ("PS384", {"rsa_bits": 3072}),
    ("PS512", {"rsa_bits": 4096}),
    ("ES256", {}),
    ("ES384", {}),
    ("ES512", {}),
    ("EdDSA", {}),
]


@pytest.fixture(scope="module")
def keypairs():
    return {
        alg: captest.generate_keys(alg, **kw) for alg, kw in KEY_LADDER
    }


class _JWKSHandler(BaseHTTPRequestHandler):
    jwks_body = b"{}"
    status = 200
    hits = 0

    def do_GET(self):
        type(self).hits += 1
        self.send_response(self.status)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        self.wfile.write(self.jwks_body)

    def log_message(self, *a):
        pass


@pytest.fixture()
def jwks_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _JWKSHandler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    _JWKSHandler.status = 200
    _JWKSHandler.hits = 0
    yield server, f"http://127.0.0.1:{server.server_address[1]}/jwks"
    server.shutdown()


def _set_jwks(keys_with_kids):
    _JWKSHandler.jwks_body = json.dumps(
        {"keys": [serialize_public_key(k, kid=kid) for kid, k in keys_with_kids]}
    ).encode()


@pytest.mark.parametrize("alg", [a for a, _ in KEY_LADDER])
def test_static_keyset_all_algs(alg, keypairs):
    priv, pub = keypairs[alg]
    token = captest.sign_jwt(priv, alg, captest.default_claims())
    ks = StaticKeySet([pub])
    claims = ks.verify_signature(token)
    assert claims["sub"] == "alice"


@pytest.mark.parametrize("alg", [a for a, _ in KEY_LADDER])
def test_static_keyset_wrong_key_rejected(alg, keypairs):
    priv, _ = keypairs[alg]
    _, other_pub = captest.generate_keys(alg)
    token = captest.sign_jwt(priv, alg, captest.default_claims())
    with pytest.raises(InvalidSignatureError):
        StaticKeySet([other_pub]).verify_signature(token)


@pytest.mark.parametrize("alg", [a for a, _ in KEY_LADDER])
def test_static_keyset_tampered_rejected(alg, keypairs):
    priv, pub = keypairs[alg]
    token = captest.sign_jwt(priv, alg, captest.default_claims())
    header, payload, sig = token.split(".")
    evil_payload = b64url_encode(
        json.dumps({"sub": "mallory", "exp": 9999999999}).encode()
    )
    ks = StaticKeySet([pub])
    with pytest.raises(InvalidSignatureError):
        ks.verify_signature(f"{header}.{evil_payload}.{sig}")


def test_static_keyset_trial_verification_order(keypairs):
    # Multiple keys: any one of them verifying is a success (no kid routing).
    rs_priv, rs_pub = keypairs["RS256"]
    _, es_pub = keypairs["ES256"]
    token = captest.sign_jwt(rs_priv, "RS256", captest.default_claims())
    assert StaticKeySet([es_pub, rs_pub]).verify_signature(token)["iss"]


def test_static_keyset_requires_keys():
    with pytest.raises(NilParameterError):
        StaticKeySet([])


@pytest.mark.parametrize("alg", [a for a, _ in KEY_LADDER])
def test_jwks_keyset_all_algs(alg, keypairs, jwks_server):
    _, url = jwks_server
    priv, pub = keypairs[alg]
    _set_jwks([("kid-1", pub)])
    token = captest.sign_jwt(priv, alg, captest.default_claims(), kid="kid-1")
    claims = JSONWebKeySet(url).verify_signature(token)
    assert claims["sub"] == "alice"


def test_jwks_kid_rotation_refetches(keypairs, jwks_server):
    _, url = jwks_server
    priv, pub = keypairs["ES256"]
    _set_jwks([("old-kid", pub)])
    ks = JSONWebKeySet(url)
    ks.keys()  # warm the cache with old-kid
    # Rotate: token signed under a new kid the cache doesn't know.
    _set_jwks([("new-kid", pub)])
    token = captest.sign_jwt(priv, "ES256", captest.default_claims(), kid="new-kid")
    assert ks.verify_signature(token)["sub"] == "alice"
    assert _JWKSHandler.hits >= 2


def test_jwks_no_refetch_on_forged_token(keypairs, jwks_server):
    # A forged token whose kid matches a cached key must NOT trigger a
    # network refetch (IdP-hammering amplification).
    _, url = jwks_server
    priv, pub = keypairs["ES256"]
    _set_jwks([("kid-1", pub)])
    ks = JSONWebKeySet(url)
    ks.keys()
    hits_before = _JWKSHandler.hits
    good = captest.sign_jwt(priv, "ES256", captest.default_claims(), kid="kid-1")
    forged = good[:-12] + "AAAAAAAAAAAA"
    for _ in range(3):
        with pytest.raises(InvalidSignatureError):
            ks.verify_signature(forged)
    assert _JWKSHandler.hits == hits_before


def test_jwks_404_rejected(jwks_server):
    _, url = jwks_server
    _JWKSHandler.status = 404
    with pytest.raises(InvalidJWKSError):
        JSONWebKeySet(url).keys()


def test_jwks_garbage_rejected(jwks_server):
    _, url = jwks_server
    _JWKSHandler.jwks_body = b"not json at all"
    with pytest.raises(InvalidJWKSError):
        JSONWebKeySet(url).keys()


def test_jwks_wrong_kid_rejected(keypairs, jwks_server):
    _, url = jwks_server
    priv, pub = keypairs["ES256"]
    _, other_pub = captest.generate_keys("ES256")
    _set_jwks([("a", other_pub)])
    token = captest.sign_jwt(priv, "ES256", captest.default_claims(), kid="a")
    with pytest.raises(InvalidSignatureError):
        JSONWebKeySet(url).verify_signature(token)


def test_pem_roundtrip(keypairs):
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )

    for alg in ("RS256", "ES256", "EdDSA"):
        priv, pub = keypairs[alg]
        pem = pub.public_bytes(
            Encoding.PEM, PublicFormat.SubjectPublicKeyInfo
        ).decode()
        key = parse_public_key_pem(pem)
        token = captest.sign_jwt(priv, alg, captest.default_claims())
        assert StaticKeySet([key]).verify_signature(token)["sub"] == "alice"


def test_verify_batch_default_loop(keypairs):
    priv, pub = keypairs["RS256"]
    good = captest.sign_jwt(priv, "RS256", captest.default_claims())
    bad = good[:-8] + "AAAAAAAA"
    results = StaticKeySet([pub]).verify_batch([good, bad, good])
    assert results[0]["sub"] == "alice"
    assert isinstance(results[1], InvalidSignatureError)
    assert results[2]["sub"] == "alice"
