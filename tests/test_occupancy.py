"""Pipeline occupancy & queueing-delay plane (r22, ISSUE 18).

The accounting contracts under test, from docs/OBSERVABILITY.md
§Occupancy plane:

- global busy time is the UNION of recorded intervals (overlapping
  2-deep-pipeline batches never double-count a microsecond), while
  per-family busy is the RAW duration (lane share double-counts
  deliberately);
- idle gaps are observed only BETWEEN dispatch-level intervals, never
  before the first and never for per-family enqueue slices;
- publish() flushes counter DELTAS (mergeable, reset-clamped by
  consumers) and sets scrape-window gauge ratios;
- the batch lifecycle decomposes: ``sum(batcher.flush.*) ==
  batcher.flushes == device.dispatches``, flush reasons classify
  deterministically, and the per-stage waterfall sums to the e2e
  request time within tolerance;
- the connection plane (conns_live gauge, FrameReader.hwm) and the
  ``occupancy_floor`` SLO rule ride the same counter space.
"""

import socket
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet.worker_main import StubKeySet
from cap_tpu.obs import occupancy
from cap_tpu.obs import slo
from cap_tpu.obs.occupancy import OccAccumulator, occupancy_from_counters
from cap_tpu.serve import protocol
from cap_tpu.serve.batcher import AdaptiveBatcher
from cap_tpu.serve.client import VerifyClient
from cap_tpu.serve.worker import VerifyWorker


@pytest.fixture
def rec():
    """Fresh active recorder per test; module accumulator reset so a
    prior test's unpublished deltas can never leak in."""
    occupancy.reset()
    r = telemetry.Recorder()
    telemetry.enable(r)
    yield r
    telemetry.disable()
    occupancy.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# OccAccumulator: the interval-accounting model
# ---------------------------------------------------------------------------

def test_totals_empty_until_first_interval():
    acc = OccAccumulator(FakeClock())
    assert acc.totals() == {}


def test_union_clips_overlap_but_families_count_raw(rec):
    clk = FakeClock()
    acc = OccAccumulator(clk)
    # [0, 0.10] then overlapping [0.05, 0.20]: union = 0.20s, not 0.25
    acc.record("a", 0.00, 0.10, dispatch=True)
    acc.record("b", 0.05, 0.20, dispatch=True)
    # fully-contained interval adds NOTHING to the union
    acc.record("c", 0.06, 0.08)
    clk.t = 0.20
    t = acc.totals()
    assert t["device.busy_us"] == 200_000
    assert t["device.wall_us"] == 200_000
    assert t["device.dispatches"] == 2          # c was not a dispatch
    # per-family raw durations double-count the overlap deliberately
    assert t["device.a.busy_us"] == 100_000
    assert t["device.b.busy_us"] == 150_000
    assert t["device.c.busy_us"] == 20_000
    assert t["device.a.intervals"] == 1
    assert t["device.b.intervals"] == 1


def test_idle_gap_only_between_dispatch_intervals(rec):
    acc = OccAccumulator(FakeClock())
    # first dispatch interval: no preceding work → no gap
    acc.record("a", 0.0, 0.1, dispatch=True)
    # back-to-back: no gap
    acc.record("a", 0.1, 0.2, dispatch=True)
    # non-dispatch slice after a bubble: NOT an idle gap (host packing)
    acc.record("a", 0.5, 0.6)
    # dispatch after a bubble: exactly one gap observation
    acc.record("a", 0.9, 1.0, dispatch=True)
    s = rec.summary().get("device.idle_gap_s")
    assert s is not None and s["count"] == 1
    # the observed gap: 0.9 - 0.6 = 0.3s (against the union high-water)
    assert 0.2 <= s["total"] <= 0.4


def test_publish_flushes_deltas_and_window_gauges(rec):
    clk = FakeClock()
    acc = OccAccumulator(clk)
    acc.publish(rec)                      # nothing recorded → no keys
    assert "device.wall_us" not in rec.counters()

    # binary-exact eighths keep the integer-µs math exact
    acc.record("stub", 0.0, 0.125, dispatch=True)
    clk.t = 0.25
    acc.publish(rec)
    c = rec.counters()
    assert c["device.busy_us"] == 125_000
    assert c["device.wall_us"] == 250_000
    assert c["device.dispatches"] == 1
    assert c["device.stub.busy_us"] == 125_000
    assert c["device.stub.intervals"] == 1
    g = rec.gauges()
    assert g["device.occupancy"] == pytest.approx(0.5)
    assert g["device.stub.occupancy"] == pytest.approx(0.5)

    # next window fully busy: counters accumulate, gauges show the
    # WINDOW ratio (1.0), not the lifetime ratio (0.6)
    acc.record("stub", 0.25, 0.375, dispatch=True)
    clk.t = 0.375
    acc.publish(rec)
    c = rec.counters()
    assert c["device.busy_us"] == 250_000
    assert c["device.wall_us"] == 375_000
    assert rec.gauges()["device.occupancy"] == pytest.approx(1.0)


def test_interval_noop_and_clock_untouched_while_telemetry_off():
    telemetry.disable()

    def bomb():
        raise AssertionError("clock read while telemetry off")

    acc = OccAccumulator(bomb)
    with acc.interval("stub"):
        pass
    assert acc.begin() is None
    acc.end("stub", None)
    assert acc.totals() == {}


# ---------------------------------------------------------------------------
# counter-space rollup: capstat / pool / SLO view
# ---------------------------------------------------------------------------

def test_occupancy_from_counters_lifetime_and_window():
    cur = {"device.busy_us": 250_000, "device.wall_us": 1_000_000,
           "device.dispatches": 10,
           "device.stub.busy_us": 250_000, "device.stub.intervals": 10}
    out = occupancy_from_counters(cur)
    assert out["occupancy"] == pytest.approx(0.25)
    assert out["dispatches"] == 10
    assert out["families"]["stub"]["occupancy"] == pytest.approx(0.25)
    assert out["families"]["stub"]["intervals"] == 10

    prev = {"device.busy_us": 50_000, "device.wall_us": 800_000,
            "device.dispatches": 4,
            "device.stub.busy_us": 50_000, "device.stub.intervals": 4}
    win = occupancy_from_counters(cur, prev)
    assert win["busy_us"] == 200_000 and win["wall_us"] == 200_000
    assert win["occupancy"] == pytest.approx(1.0)
    assert win["families"]["stub"]["intervals"] == 6


def test_occupancy_from_counters_reset_clamp_and_absent():
    # restarted worker: cur BELOW prev clamps to zero, never negative
    cur = {"device.busy_us": 10, "device.wall_us": 100,
           "device.dispatches": 1}
    prev = {"device.busy_us": 900, "device.wall_us": 1000,
            "device.dispatches": 50}
    out = occupancy_from_counters(cur, prev)
    assert out["busy_us"] == 0 and out["wall_us"] == 0
    assert out["occupancy"] == 0.0 and out["dispatches"] == 0
    # plane never recorded → None, not a zero rollup
    assert occupancy_from_counters({"batcher.flushes": 3}) is None


def test_occupancy_family_parse_ignores_deeper_keys():
    cur = {"device.wall_us": 100, "device.busy_us": 50,
           "device.stub.busy_us": 50, "device.stub.intervals": 1,
           "device.a.b.busy_us": 99}        # not a family key
    out = occupancy_from_counters(cur)
    assert set(out["families"]) == {"stub"}


def test_merge_snapshots_adds_occupancy_sections():
    def snap(busy, wall, n):
        return {"v": 1, "gauges": {}, "series": {},
                "counters": {"device.busy_us": busy,
                             "device.wall_us": wall,
                             "device.dispatches": n,
                             "device.stub.busy_us": busy,
                             "device.stub.intervals": n}}

    merged = telemetry.merge_snapshots(
        [snap(100_000, 1_000_000, 5), snap(300_000, 1_000_000, 7)])
    c = merged["counters"]
    assert c["device.busy_us"] == 400_000
    assert c["device.wall_us"] == 2_000_000
    assert c["device.dispatches"] == 12
    # fleet view = sum-busy / sum-wall: the worker-weighted mean
    out = occupancy_from_counters(c)
    assert out["occupancy"] == pytest.approx(0.2)
    assert out["families"]["stub"]["intervals"] == 12


# ---------------------------------------------------------------------------
# batch lifecycle: flush reasons, gauge staleness, the flush equation
# ---------------------------------------------------------------------------

def test_flush_reason_timeout_and_size(rec):
    b = AdaptiveBatcher(StubKeySet(), target_batch=4, max_wait_ms=1.0,
                        fair=False, dedup=False)
    try:
        # lone submission under target: flushes on the wait window
        assert len(b.submit(["t0.ok"])) == 1
        # a full batch flushes on size
        assert len(b.submit(["s0.ok", "s1.ok", "s2.ok", "s3.ok"])) == 4
        time.sleep(0.05)
        c = rec.counters()
        assert c.get("batcher.flush.timeout") == 1
        assert c.get("batcher.flush.size") == 1
        st = b.stats()
        assert st["flush_reasons"] == {"timeout": 1, "size": 1}
        assert st["last_flush"]["reason"] == "size"
        assert st["last_flush"]["batch_size"] == 4
        assert st["last_flush"]["batcher_wait_s"] >= 0.0
    finally:
        b.close(deadline_s=10)


def test_flush_reason_close_and_handoff(rec):
    # close: a pending under-target submission flushed by shutdown
    b = AdaptiveBatcher(StubKeySet(), target_batch=64,
                        max_wait_ms=10_000.0, fair=False, dedup=False)
    p = b.submit_nowait(["c0.ok"])
    b.close(deadline_s=10)
    assert p.event.wait(timeout=5) and len(p.results) == 1
    assert rec.counters().get("batcher.flush.close") == 1

    # handoff: one drained ring chunk alone meeting the size target
    done = []
    b2 = AdaptiveBatcher(StubKeySet(), target_batch=2,
                         max_wait_ms=10_000.0, fair=False, dedup=False)
    try:
        p2 = b2.submit_handoff(["h0.ok", "h1.ok"],
                               on_done=lambda r: done.append(r))
        assert p2.event.wait(timeout=5)
        assert len(done) == 1 and len(done[0]) == 2
        assert rec.counters().get("batcher.flush.handoff") == 1
    finally:
        b2.close(deadline_s=10)


def test_flush_equation_sum_reasons_equals_flushes(rec):
    b = AdaptiveBatcher(StubKeySet(), target_batch=3, max_wait_ms=1.0,
                        fair=False, dedup=False)
    try:
        for i in range(5):
            b.submit([f"e{i}.ok"])
        b.submit(["f0.ok", "f1.ok", "f2.ok"])
        time.sleep(0.05)
        c = rec.counters()
        flush_sum = sum(v for k, v in c.items()
                        if k.startswith("batcher.flush."))
        assert flush_sum == c.get("batcher.flushes") >= 6
    finally:
        b.close(deadline_s=10)


def test_batcher_gauges_decay_to_zero_when_queue_empties(rec):
    """The r22 staleness fix: an emptied queue must not freeze the
    last flush's depth/fill gauges on the scrape surface forever."""
    b = AdaptiveBatcher(StubKeySet(), target_batch=4, max_wait_ms=1.0,
                        fair=False, dedup=False)
    try:
        b.submit(["g0.ok", "g1.ok", "g2.ok", "g3.ok"])
        # flush-time gauges showed a full batch ...
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            g = rec.gauges()
            if g.get("batcher.queued_tokens") == 0.0 \
                    and g.get("batcher.fill_ratio") == 0.0:
                break
            time.sleep(0.01)
        # ... and the idle dispatcher decayed them to exactly zero
        g = rec.gauges()
        assert g.get("batcher.queued_tokens") == 0.0
        assert g.get("batcher.fill_ratio") == 0.0
    finally:
        b.close(deadline_s=10)


def test_stats_additive_before_first_flush():
    b = AdaptiveBatcher(StubKeySet(), target_batch=4096,
                        max_wait_ms=1000.0, fair=False, dedup=False)
    try:
        st = b.stats()
        assert "flush_reasons" not in st and "last_flush" not in st
        assert st["queued_tokens"] == 0
    finally:
        b.close(deadline_s=10)


# ---------------------------------------------------------------------------
# e2e: the stage waterfall sums to the request time; conn plane
# ---------------------------------------------------------------------------

STAGES = ("queue.ring_wait_s", "queue.batcher_wait_s",
          "queue.dispatch_gap_s", "device.exec_s")


def test_stage_waterfall_sums_to_request_time(rec):
    """Sequential single-frame drives against a 2ms-batch stub: the
    per-stage means must decompose the e2e mean — generous band, this
    runs on 1-core CI, but a MISSING stage (sum ≪ e2e) or a
    double-counted one (sum ≫ e2e) fails."""
    w = VerifyWorker(StubKeySet(batch_ms=2.0), max_wait_ms=1.0)
    try:
        host, port = w.address
        with VerifyClient(host, port) as cl:
            for i in range(8):
                assert len(cl.verify_batch([f"wf{i}.ok"])) == 1
        time.sleep(0.2)
        st = w.stats()
        summ = telemetry.summarize_snapshot(st["snapshot"])
        assert "serve.request_s" in summ
        e2e = summ["serve.request_s"]["total"] \
            / summ["serve.request_s"]["count"]
        stage_sum = sum(
            summ[s]["total"] / summ[s]["count"]
            for s in STAGES if s in summ and summ[s]["count"])
        assert 0.2 * e2e <= stage_sum <= 2.0 * e2e
        # exec time dominated by the 2ms stub batch → occupancy is
        # measurably nonzero on the counter rollup
        occ = occupancy_from_counters(st["counters"])
        assert occ is not None and occ["dispatches"] == 8
        assert occ["occupancy"] > 0.0
    finally:
        w.close(deadline_s=10)
        telemetry.disable()


def test_conn_plane_gauges_and_buffer_hwm(rec):
    w = VerifyWorker(StubKeySet(), max_wait_ms=1.0)
    try:
        host, port = w.address
        with VerifyClient(host, port) as cl:
            assert len(cl.verify_batch(["conn0.ok"])) == 1
            g = w._obs_gauges()
            assert g.get("serve.conns_live") == 1.0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if w._obs_gauges().get("serve.conns_live") == 0.0:
                break
            time.sleep(0.01)
        assert w._obs_gauges().get("serve.conns_live") == 0.0
        c = rec.counters()
        assert c.get("worker.connections") == 1
        # the connection attributed to exactly one tenant label
        assert sum(v for k, v in c.items()
                   if k.startswith("serve.tenant.")
                   and k.endswith(".conns")) == 1
        # read-buffer high-water mark observed at conn teardown
        s = rec.summary().get("serve.conn_buffered_hwm_b")
        assert s is not None and s["count"] == 1 and s["total"] > 0
    finally:
        w.close(deadline_s=10)


def test_frame_reader_tracks_buffered_hwm():
    a, b = socket.socketpair()
    try:
        protocol.send_request(b, ["hwm-token.ok"])
        r = protocol.FrameReader(a)
        assert r.hwm == 0
        ftype, entries = r.recv_frame()
        assert entries == ["hwm-token.ok"]
        # the reader buffered at least the frame's payload at once
        assert r.hwm > len("hwm-token.ok")
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# SLO: the occupancy_floor rule kind
# ---------------------------------------------------------------------------

def _occ_snapshot(busy, wall, dispatches):
    return {"counters": {"device.busy_us": busy,
                         "device.wall_us": wall,
                         "device.dispatches": dispatches},
            "gauges": {}, "series": {}}


def test_occupancy_floor_parses_and_evaluates():
    rules = slo.parse_rules("occ occupancy_floor min 0.5")
    assert len(rules) == 1 and rules[0].kind == "occupancy_floor"
    assert rules[0].max_value == 0.5

    # under load below the floor: breach
    res = slo.evaluate_once(_occ_snapshot(100_000, 1_000_000, 5), rules)
    assert len(res) == 1 and not res[0]["ok"]
    assert res[0]["windows"]["lifetime"] == pytest.approx(0.1)
    # at/above the floor: ok
    res = slo.evaluate_once(_occ_snapshot(600_000, 1_000_000, 5), rules)
    assert res[0]["ok"]


def test_occupancy_floor_idle_window_never_burns():
    rules = slo.parse_rules("occ occupancy_floor min 0.9")
    # zero dispatches → idle, not a breach (an idle fleet is cheap,
    # not broken); same for a fleet with no occupancy section at all
    res = slo.evaluate_once(_occ_snapshot(0, 1_000_000, 0), rules)
    assert res[0]["ok"] and res[0]["windows"]["lifetime"] == "idle"
    res = slo.evaluate_once({"counters": {}, "gauges": {},
                             "series": {}}, rules)
    assert res[0]["ok"]


def test_occupancy_floor_parse_rejects_bad_syntax():
    with pytest.raises(slo.SLOError):
        slo.parse_rules("occ occupancy_floor max 0.5")


def test_observability_doc_pins_occupancy_tables():
    """docs/OBSERVABILITY.md §Occupancy plane and the plane's actual
    metric names are the same set — neither side can drift without
    failing here (the span-table pin's discipline, applied to r22)."""
    import os

    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "OBSERVABILITY.md")
    with open(doc_path) as f:
        doc = f.read()
    assert "## Occupancy plane" in doc
    for name in (
            # counters the accumulator publishes
            "device.busy_us", "device.wall_us", "device.dispatches",
            "device.<fam>.busy_us", "device.<fam>.intervals",
            # flush attribution + the handshake fallback
            "batcher.flush.<reason>", "serve.native.occ_fallbacks",
            # gauges
            "device.occupancy", "device.<fam>.occupancy",
            "serve.conns_live", "serve.tenant.<t>.conns",
            # the stage waterfall
            "queue.ring_wait_s", "queue.batcher_wait_s",
            "queue.dispatch_gap_s", "device.exec_s",
            "device.idle_gap_s", "serve.conn_buffered_hwm_b",
            "serve.request_s"):
        assert f"`{name}`" in doc, f"{name} missing from doc tables"
    for reason in ("size", "timeout", "handoff", "close", "drain"):
        assert reason in doc
    assert "occupancy_floor min" in doc


def test_default_rules_keep_occupancy_floor_dormant():
    # the discrete-dispatch baseline sits far below any sane floor
    # (docs/PERF.md §Round 22) — the default rule set must not page
    # every stub fleet; the rule ships commented until ROADMAP #5
    assert not any(r.kind == "occupancy_floor"
                   for r in slo.default_rules())
