"""Telemetry subsystem: counters, spans, quantiles, hot-path wiring.

The reference has no instrumentation (SURVEY.md §5); these tests cover
the freshly-built one: recorder semantics, scoped enablement, zero
overhead when off, and that verify_batch emits stage timings/counters
without ever recording token or key material.
"""

import threading

import pytest

from cap_tpu import telemetry

try:
    from cap_tpu import testing as captest
    from cap_tpu.jwt.jwk import JWK
    from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet
    _HAVE_CRYPTO = True
except ModuleNotFoundError:          # crypto fixtures absent: the
    captest = JWK = TPUBatchKeySet = None    # recorder tests still run
    _HAVE_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAVE_CRYPTO, reason="cryptography package not installed")


@pytest.fixture(autouse=True)
def _telemetry_off():
    telemetry.disable()
    yield
    telemetry.disable()


def test_counters_and_series():
    rec = telemetry.Recorder()
    rec.count("a")
    rec.count("a", 4)
    rec.observe("lat", 0.5)
    rec.observe("lat", 1.5)
    assert rec.counters() == {"a": 5}
    assert rec.series("lat") == [0.5, 1.5]


def test_span_records_duration():
    rec = telemetry.Recorder()
    with rec.span("s"):
        pass
    vals = rec.series("s")
    assert len(vals) == 1 and vals[0] >= 0.0


def test_summary_quantiles():
    rec = telemetry.Recorder()
    for i in range(100):
        rec.observe("x", float(i))
    s = rec.summary()["x"]
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(50.0, abs=1)
    assert s["p99"] == pytest.approx(98.0, abs=1)
    assert s["max"] == 99.0
    assert s["mean"] == pytest.approx(49.5)


def test_module_noop_when_disabled():
    assert telemetry.active() is None
    telemetry.count("never")  # must not raise
    with telemetry.span("never"):
        pass
    assert telemetry.active() is None


def test_recording_scope_restores_previous():
    outer = telemetry.enable()
    with telemetry.recording() as rec:
        assert telemetry.active() is rec
        telemetry.count("inner")
    assert telemetry.active() is outer
    assert "inner" not in outer.counters()
    assert rec.counters()["inner"] == 1


def test_thread_safety():
    rec = telemetry.Recorder()

    def work():
        for _ in range(1000):
            rec.count("n")
            rec.observe("v", 1.0)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.counters()["n"] == 4000
    assert rec.summary()["v"]["count"] == 4000


# ---------------------------------------------------------------------------
# bounded metrics (the unbounded-_series footgun, fixed)
# ---------------------------------------------------------------------------

def test_memory_stays_bounded_after_1m_observations():
    """The PR-2 Recorder kept EVERY observation in a list — a
    long-running worker grew without bound. Now a series is a fixed
    bucket array plus a capped reservoir: after 1M observations the
    retained state is O(buckets), not O(observations)."""
    rec = telemetry.Recorder()
    for i in range(1_000_000):
        rec.observe("hot", (i % 977) * 1e-5)
    h = rec._series["hot"]
    assert h.count == 1_000_000
    assert h.raw is None                       # reservoir released
    assert len(h.counts) == telemetry._N_BUCKETS
    # total retained floats/ints for the series: buckets + moments,
    # nowhere near the observation count.
    assert len(h.counts) < 1000
    # raw-sample surface reports empty rather than lying
    assert rec.series("hot") == []
    # quantiles still work, from the buckets (log-scale: ≤ ~9% error,
    # uniform data over [0, 9.76e-3] → p50 ≈ 4.9e-3)
    s = rec.summary()["hot"]
    assert s["count"] == 1_000_000
    assert 0.0035 < s["p50"] < 0.0065
    assert s["max"] == pytest.approx(976e-5)


def test_small_series_quantiles_stay_exact():
    rec = telemetry.Recorder()
    for i in range(100):
        rec.observe("x", float(i))
    # under the reservoir cap: exact, same as the PR-2 semantics
    assert rec.summary()["x"]["p50"] == pytest.approx(50.0, abs=1)
    assert rec.series("x") == [float(i) for i in range(100)]


def test_gauges():
    rec = telemetry.Recorder()
    rec.gauge("depth", 7)
    rec.gauge("depth", 3)
    assert rec.gauges() == {"depth": 3.0}


def test_snapshot_merge_is_exact():
    """Fleet aggregation contract: merging two workers' snapshots
    gives the same quantiles as one recorder that saw every sample
    (bucket counts ADD; nothing is averaged)."""
    a, b, ref = (telemetry.Recorder() for _ in range(3))
    for i in range(5000):
        v = 1e-4 * (1.3 ** (i % 30))
        (a if i % 2 else b).observe("lat", v)
        ref.observe("lat", v)
        (a if i % 2 else b).count("n")
        a.gauge("queued", 5)
    merged = telemetry.merge_snapshots(
        [a.snapshot(), b.snapshot(), None, {}])
    summ = telemetry.summarize_snapshot(merged)["lat"]
    # force the reference onto its buckets too (same resolution)
    ref_h = ref._series["lat"]
    ref_h.raw = None
    for q, want in (("p50", ref_h.quantile(0.5)),
                    ("p95", ref_h.quantile(0.95)),
                    ("p99", ref_h.quantile(0.99))):
        assert summ[q] == pytest.approx(want), q
    assert summ["count"] == 5000
    assert merged["counters"]["n"] == 5000
    assert merged["gauges"]["queued"] == 5.0


def test_metric_names_reject_token_material():
    """Redaction at the WRITE boundary: a metric name that looks like
    payload (JWS 'eyJ' prefix, whitespace, over-long) is refused."""
    rec = telemetry.Recorder()
    for bad in ("eyJhbGciOiJSUzI1NiJ9.e30.c2ln",
                "lat " + "x" * 10,
                "x" * 200):
        with pytest.raises(ValueError, match="redaction"):
            rec.count(bad)
        with pytest.raises(ValueError, match="redaction"):
            rec.observe(bad, 1.0)
        with pytest.raises(ValueError, match="redaction"):
            rec.gauge(bad, 1.0)
    # notes are scrubbed, not raised (free-ish text)
    assert telemetry.scrub_note("eyJabc") == "[redacted]"
    assert telemetry.scrub_note("127.0.0.1:80") == "127.0.0.1:80"


# ---------------------------------------------------------------------------
# tracing + flight recorder
# ---------------------------------------------------------------------------

def test_trace_context_and_span_records():
    with telemetry.recording() as rec:
        assert telemetry.current_trace() is None
        with telemetry.trace() as tid:
            assert telemetry.valid_trace_id(tid) and len(tid) == 16
            assert telemetry.current_trace() == tid
            with telemetry.span("client.submit"):
                pass
        assert telemetry.current_trace() is None
    spans = rec.trace_spans(tid)
    assert [s["name"] for s in spans] == ["client.submit"]
    assert spans[0]["dur"] >= 0.0
    # the histogram observation happened too
    assert rec.summary()["client.submit"]["count"] == 1


def test_trace_scope_fans_out_to_batch_members():
    rec = telemetry.Recorder()
    with telemetry.recording(rec):
        with telemetry.trace_scope(["aa00", "bb11"]):
            with telemetry.span("batcher.dispatch"):
                pass
    assert len(rec.trace_spans("aa00")) == 1
    assert len(rec.trace_spans("bb11")) == 1


def test_flight_recorder_keeps_slowest_and_stays_bounded():
    rec = telemetry.Recorder()
    for i in range(1000):
        tid = f"{i:016x}"
        rec.trace_span(tid, "batcher.fill", float(i), 0.001)
        rec.flight(tid, total_s=(i % 97) * 1e-3)
    entries = rec.flight_entries()
    assert len(entries) == telemetry.MAX_FLIGHT_ENTRIES
    slowest = rec.flight_slowest(5)
    assert len(slowest) == 5
    assert all(e["total_s"] == 96e-3 for e in slowest[:1])
    assert slowest[0]["total_s"] >= slowest[-1]["total_s"]


def test_span_names_registered():
    # the registered-constants table: every SPAN_* constant is in
    # SPAN_NAMES, so docs and wire consumers can enumerate them
    consts = {v for k, v in vars(telemetry).items()
              if k.startswith("SPAN_") and isinstance(v, str)
              and not k.endswith("_PREFIX")}
    assert consts == set(telemetry.SPAN_NAMES)


@needs_crypto
def test_verify_batch_emits_stage_metrics():
    priv, pub = captest.generate_keys("RS256", rsa_bits=2048)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")])
    tokens = [captest.sign_jwt(priv, "RS256", captest.default_claims(),
                               kid="k0")] * 4

    with telemetry.recording() as rec:
        out = ks.verify_batch(tokens)
    assert all(isinstance(r, dict) for r in out)

    counters = rec.counters()
    assert counters["verify_batch.calls"] == 1
    assert counters["verify_batch.tokens"] == 4
    summ = rec.summary()
    assert "verify_batch.total" in summ
    # a prep span from one of the two paths must be present
    assert any(k.startswith("prep") for k in summ)
    # no metric name may carry payload material
    for name in list(counters) + list(summ):
        assert "eyJ" not in name and len(name) < 80


# ---------------------------------------------------------------------------
# merge_snapshots edge cases (the native-plane scrape/merge contract)
# ---------------------------------------------------------------------------

def test_merge_empty_and_counterless_snapshots():
    """Empty snapshots, None entries, and snapshots with only some
    sections must merge without inventing keys."""
    rec = telemetry.Recorder()
    rec.count("a", 3)
    merged = telemetry.merge_snapshots(
        [None, {}, {"v": 1}, {"counters": {}}, rec.snapshot(),
         {"v": 1, "counters": {"a": 2}, "gauges": {}, "series": {}}])
    assert merged["counters"] == {"a": 5}
    assert merged["gauges"] == {}
    assert merged["series"] == {}
    # and a merge of nothing at all is a valid empty snapshot
    empty = telemetry.merge_snapshots([])
    assert empty["counters"] == {} and empty["series"] == {}


def test_merge_disjoint_bucket_sets():
    """Two snapshots whose histograms occupy DISJOINT buckets: the
    merged series must contain both, with counts, sum, min and max
    identical to one recorder that saw every sample."""
    a, b, ref = (telemetry.Recorder() for _ in range(3))
    for v in (1e-6, 2e-6, 4e-6):
        a.observe("s", v)
        ref.observe("s", v)
    for v in (10.0, 20.0, 40.0):
        b.observe("s", v)
        ref.observe("s", v)
    merged = telemetry.merge_snapshots([a.snapshot(), b.snapshot()])
    ref_state = ref._series["s"].state()
    got = merged["series"]["s"]
    assert got["buckets"] == ref_state["buckets"]
    assert got["count"] == 6
    assert got["min"] == 1e-6 and got["max"] == 40.0
    assert got["sum"] == pytest.approx(ref_state["sum"])


def test_merge_max_bucket_index_observation():
    """An observation beyond the last bound lands in the OVERFLOW
    bucket (index len(BUCKET_BOUNDS)); the merge must carry it and
    from_state must not drop it."""
    rec = telemetry.Recorder()
    rec.observe("s", telemetry._HIST_HI * 10)     # overflow bucket
    rec.observe("s", telemetry.BUCKET_BOUNDS[-1])  # last real bound
    snap = rec.snapshot()
    overflow_idx = str(len(telemetry.BUCKET_BOUNDS))
    assert overflow_idx in snap["series"]["s"]["buckets"]
    merged = telemetry.merge_snapshots([snap, snap])
    assert merged["series"]["s"]["buckets"][overflow_idx] == 2
    h = telemetry.Histogram.from_state(merged["series"]["s"])
    assert h.count == 4
    assert h.quantile(0.99) <= h.vmax


def test_merge_native_plane_snapshot_schema_parity():
    """A native-plane snapshot (serve/native_serve.py shape) merges
    with a recorder snapshot under the SAME schema: counters add,
    series bucket-merge — the scrape path's contract. Runs without
    the native library: the shape is what is pinned here."""
    rec = telemetry.Recorder()
    rec.count("decision.serve.accept", 5)
    rec.observe("serve.native.request_s", 0.001)
    nat = {
        "v": 1,
        "counters": {"decision.serve.accept": 7,
                     "decision.serve.family.es": 12},
        "gauges": {},
        "series": {"serve.native.request_s": {
            "count": 2, "sum": 0.004, "min": 0.001, "max": 0.003,
            "buckets": {"55": 1, "61": 1}}},
    }
    merged = telemetry.merge_snapshots([rec.snapshot(), nat])
    assert merged["counters"]["decision.serve.accept"] == 12
    assert merged["counters"]["decision.serve.family.es"] == 12
    s = merged["series"]["serve.native.request_s"]
    assert s["count"] == 3
    assert s["max"] == 0.003
    # summarize accepts the merged form (what capstat renders)
    assert "serve.native.request_s" in telemetry.summarize_snapshot(
        merged)
