"""Telemetry subsystem: counters, spans, quantiles, hot-path wiring.

The reference has no instrumentation (SURVEY.md §5); these tests cover
the freshly-built one: recorder semantics, scoped enablement, zero
overhead when off, and that verify_batch emits stage timings/counters
without ever recording token or key material.
"""

import threading

import pytest

from cap_tpu import telemetry
from cap_tpu import testing as captest
from cap_tpu.jwt.jwk import JWK
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet


@pytest.fixture(autouse=True)
def _telemetry_off():
    telemetry.disable()
    yield
    telemetry.disable()


def test_counters_and_series():
    rec = telemetry.Recorder()
    rec.count("a")
    rec.count("a", 4)
    rec.observe("lat", 0.5)
    rec.observe("lat", 1.5)
    assert rec.counters() == {"a": 5}
    assert rec.series("lat") == [0.5, 1.5]


def test_span_records_duration():
    rec = telemetry.Recorder()
    with rec.span("s"):
        pass
    vals = rec.series("s")
    assert len(vals) == 1 and vals[0] >= 0.0


def test_summary_quantiles():
    rec = telemetry.Recorder()
    for i in range(100):
        rec.observe("x", float(i))
    s = rec.summary()["x"]
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(50.0, abs=1)
    assert s["p99"] == pytest.approx(98.0, abs=1)
    assert s["max"] == 99.0
    assert s["mean"] == pytest.approx(49.5)


def test_module_noop_when_disabled():
    assert telemetry.active() is None
    telemetry.count("never")  # must not raise
    with telemetry.span("never"):
        pass
    assert telemetry.active() is None


def test_recording_scope_restores_previous():
    outer = telemetry.enable()
    with telemetry.recording() as rec:
        assert telemetry.active() is rec
        telemetry.count("inner")
    assert telemetry.active() is outer
    assert "inner" not in outer.counters()
    assert rec.counters()["inner"] == 1


def test_thread_safety():
    rec = telemetry.Recorder()

    def work():
        for _ in range(1000):
            rec.count("n")
            rec.observe("v", 1.0)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.counters()["n"] == 4000
    assert rec.summary()["v"]["count"] == 4000


def test_verify_batch_emits_stage_metrics():
    priv, pub = captest.generate_keys("RS256", rsa_bits=2048)
    ks = TPUBatchKeySet([JWK(pub, kid="k0")])
    tokens = [captest.sign_jwt(priv, "RS256", captest.default_claims(),
                               kid="k0")] * 4

    with telemetry.recording() as rec:
        out = ks.verify_batch(tokens)
    assert all(isinstance(r, dict) for r in out)

    counters = rec.counters()
    assert counters["verify_batch.calls"] == 1
    assert counters["verify_batch.tokens"] == 4
    summ = rec.summary()
    assert "verify_batch.total" in summ
    # a prep span from one of the two paths must be present
    assert any(k.startswith("prep") for k in summ)
    # no metric name may carry payload material
    for name in list(counters) + list(summ):
        assert "eyJ" not in name and len(name) < 80
