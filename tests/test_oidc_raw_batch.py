"""verify_id_token_batch(raw=True) differential parity.

The raw mode validates registered claims off the native tape subset
and returns payload BYTES for accepted tokens; its VERDICTS (and error
classes) must be identical to the dict path for every vector —
including the subset extractor's conservative fallbacks (escaped keys,
container-valued registered claims). Reference semantics:
/root/reference/oidc/provider.go:418-511.
"""

from __future__ import annotations

import json

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))


from cap_tpu import testing as captest
from cap_tpu.errors import InvalidParameterError
from cap_tpu.jwt.jwk import JWK
from cap_tpu.oidc import Config, Provider, Request
from cap_tpu.oidc.testing import TestProvider


@pytest.fixture(scope="module")
def rig():
    idp = TestProvider().start()
    try:
        cfg = Config(issuer=idp.issuer(), client_id=idp.client_id,
                     client_secret=idp.client_secret,
                     supported_signing_algs=["ES256"],
                     allowed_redirect_urls=["http://127.0.0.1:1/cb"],
                     provider_ca=idp.ca_cert())
        priv, pub, alg, kid = idp.signing_keys()
        from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet

        ks = TPUBatchKeySet([JWK(pub, kid=kid)])
        p = Provider(cfg, keyset=ks)
        req = Request(3600.0, "http://127.0.0.1:1/cb")
        yield idp, p, req, priv, alg, kid
    finally:
        idp.stop()


def _vectors(idp, req, priv, alg, kid):
    def claims(**over):
        c = captest.default_claims(issuer=idp.issuer(), ttl=3600.0,
                                   aud=[idp.client_id])
        c["nonce"] = req.nonce()
        c.update(over)
        return c

    sign = lambda c: captest.sign_jwt(priv, alg, c, kid=kid)  # noqa: E731
    good = sign(claims())
    return [
        ("good", good),
        ("expired", sign(claims(exp=1000))),
        ("future-nbf", sign(claims(nbf=2 ** 33))),
        ("wrong-nonce", sign(claims(nonce="nope"))),
        ("wrong-aud", sign(claims(aud=["other"]))),
        ("aud-string", sign(claims(aud=idp.client_id))),
        ("multi-aud-azp", sign(claims(aud=[idp.client_id, "x"],
                                      azp=idp.client_id))),
        ("multi-aud-bad-azp", sign(claims(aud=[idp.client_id, "x"],
                                          azp="intruder"))),
        ("multi-aud-non-string", sign(claims(aud=[idp.client_id, 42]))),
        ("aud-object-fallback", sign(claims(aud={"weird": 1}))),
        ("escaped-key-fallback",
         sign(json.loads(json.dumps(claims()).replace(
             '"iss"', '"i\\u0073s"')))),
        ("wrong-issuer", sign(claims(iss="https://evil.example/"))),
        ("tampered", good[:-6] + ("AAAAAA" if not good.endswith("AAAAAA")
                                  else "BBBBBB")),
        ("not-a-jwt", "garbage"),
    ]


@pytest.mark.parametrize("native", ["0", "1"])
def test_raw_mode_verdict_parity(rig, monkeypatch, native):
    """Both rule engines (CAP_OIDC_NATIVE=0 Python, =1 the native
    claims engine with its conservative per-token fallbacks) must
    match the dict path vector-for-vector."""
    idp, p, req, priv, alg, kid = rig
    monkeypatch.setenv("CAP_OIDC_NATIVE", native)
    names, toks = zip(*_vectors(idp, req, priv, alg, kid))
    dict_out = p.verify_id_token_batch(list(toks), req)
    raw_out = p.verify_id_token_batch(list(toks), req, raw=True)
    assert len(dict_out) == len(raw_out) == len(toks)
    for name, d, r in zip(names, dict_out, raw_out):
        assert isinstance(d, Exception) == isinstance(r, Exception), \
            f"{name}: dict={d!r} raw={r!r}"
        if isinstance(d, Exception):
            assert type(d) is type(r), f"{name}: {type(d)} vs {type(r)}"
        else:
            # raw mode returns the signed payload bytes — the exact
            # JSON the dict path parsed
            assert json.loads(r) == d, name


def test_raw_accepted_bytes_are_payload(rig):
    idp, p, req, priv, alg, kid = rig
    c = captest.default_claims(issuer=idp.issuer(), ttl=3600.0,
                               aud=[idp.client_id])
    c["nonce"] = req.nonce()
    tok = captest.sign_jwt(priv, alg, c, kid=kid)
    out = p.verify_id_token_batch([tok], req, raw=True)
    assert isinstance(out[0], bytes)
    assert out[0] == json.dumps(c, separators=(",", ":")).encode()


def test_raw_mode_requires_raw_keyset(rig):
    idp, p, req, priv, alg, kid = rig
    from cap_tpu.jwt.keyset import StaticKeySet

    _, pub, _, _ = idp.signing_keys()
    p2 = Provider(p.config, keyset=StaticKeySet([pub]))
    with pytest.raises(InvalidParameterError, match="raw"):
        p2.verify_id_token_batch(["x.y.z"], req, raw=True)
